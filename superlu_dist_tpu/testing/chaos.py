"""Failure-domain chaos harness — deterministic whole-domain injection.

PR 1's :class:`FaultyTreeComm` perturbs the comm TRANSPORT (chunk
drop/dup/reorder with retries); this module generalizes the idea to the
failure domains a serving fleet actually loses: a process killed
mid-factorization, a numeric value going NaN at a chosen supernode, a
checkpoint artifact corrupted on disk, a rank dying mid-protocol.  Every
injection is a deterministic function of the spec — no randomness races
— so a chaos test either reproduces exactly or the code under test
changed.

Enable in a victim process via the registered env knob::

    SLU_TPU_CHAOS='kill_group=5'            # SIGKILL self after group 5
    SLU_TPU_CHAOS='kill_group=5,signal=term'  # SIGTERM instead (exercises
                                              # the checkpoint/flightrec
                                              # SIGTERM chain)
    SLU_TPU_CHAOS='nan_supernode=3'         # poison supernode 3's values
    SLU_TPU_CHAOS='kill_rank=1@group=3'     # only rank 1 dies, after its
                                              # dispatch group 3 (the
                                              # rank-failure domain)
    SLU_TPU_CHAOS='kill_rank=1,kill_op=4'   # rank 1 dies right before its
                                              # 4th public collective
    SLU_TPU_CHAOS='stall_rank=1,secs=2'     # rank 1 sleeps 2 s before a
                                              # collective: slow, NOT dead
                                              # — the detector must not
                                              # declare it failed
    SLU_TPU_CHAOS='poison_rhs=17'           # NaN the 17th column ever
                                              # submitted to a SolveServer
                                              # (poisoned-request domain)
    SLU_TPU_CHAOS='slow_client=2,secs=1'    # the 2nd ticket's client
                                              # stalls 1 s before collecting
                                              # (never-collecting client)
    SLU_TPU_CHAOS='corrupt_panel=0'         # flip a byte in front group
                                              # 0's resident panel stack —
                                              # the scrubber must catch it
    SLU_TPU_CHAOS='kill_replica=1@batch=3'  # fleet replica 1 dies before
                                              # serving its 4th accepted
                                              # batch (a REAL SIGKILL in a
                                              # process replica, a simulated
                                              # crash in a thread replica) —
                                              # the zero-loss failover domain
    SLU_TPU_CHAOS='quarantine_replica=1'    # replica 1 quarantines before
                                              # its next batch — the router
                                              # must re-route, never error
    SLU_TPU_CHAOS='slow_replica=0,secs=1'   # replica 0 stalls 1 s before a
                                              # batch: slow, NOT dead — the
                                              # fleet health monitor must
                                              # yield ZERO false failovers
    SLU_TPU_CHAOS='kill_refactor@step=2'    # SIGKILL self MID-REFACTOR on
                                              # the 3rd refactor (shadow
                                              # numeric started, nothing
                                              # adopted) — the previous
                                              # handle must keep serving
    SLU_TPU_CHAOS='poison_values=3'         # NaN the new-values entry
                                              # assembling into supernode 3
                                              # mid-refactor — the canary /
                                              # sentinels must roll back,
                                              # adopting nothing

The factor path consults :func:`get_chaos` once per factorization
(numeric/factor.py) and the streamed executor calls
:meth:`ChaosMonkey.on_group` after each completed dispatch group — a
no-op None when the knob is unset, so the production hot path pays one
``is None`` test.

Helpers for tests that inject from OUTSIDE the victim:

* :func:`corrupt_file` — deterministic bit-flip / truncation of a
  checkpoint artifact (drives the persist integrity paths);
* :class:`DyingTreeComm` — a rank that exits mid-protocol after N
  public collectives (simulated rank death);
* :class:`HangWatchdog` — bounds a lost-peer hang: dump the flight
  recorder and ``os._exit`` after a timeout unless disarmed (the
  cooperative way a serving process converts an infinite collective
  hang into a bounded, diagnosable abort).
"""

from __future__ import annotations

import dataclasses
import os
import signal
import threading

import numpy as np

from superlu_dist_tpu.parallel.treecomm import TreeComm
from superlu_dist_tpu.utils.deadline import Deadline

#: exit code of a rank killed by its own DyingTreeComm (distinct from
#: any Python/pytest code so harnesses can assert the death was the
#: injected one)
RANK_DEATH_EXIT = 17
#: exit code of a HangWatchdog abort
HANG_EXIT = 3


@dataclasses.dataclass
class ChaosPlan:
    """Parsed injection spec (all fields optional; -1 / "" = off)."""

    kill_group: int = -1      # kill self after completing this group
    signal: str = "kill"      # "kill" (SIGKILL, the kill -9 domain) or
                              # "term" (SIGTERM — handlers run first)
    nan_supernode: int = -1   # poison this supernode's A-entries
    # ---- rank-failure domain (ISSUE 8) --------------------------------
    kill_rank: int = -1       # scope kill_group/kill_op to this rank
                              # (-1 = any rank, the single-process case)
    kill_op: int = -1         # die right before this public collective
                              # (1-based count on the victim's TreeComm)
    stall_rank: int = -1      # this rank sleeps `secs` before a
    secs: float = 0.0         # collective — slow-NOT-dead injection
    stall_op: int = 1         # ...before this public collective
    epoch: int = 0            # comm/serve injections fire only in this
                              # TreeComm epoch (so a shrunken/respawned
                              # recovery epoch is not re-injected)
    # ---- serving-tier domain (ISSUE 10) -------------------------------
    poison_rhs: int = -1      # NaN the Cth SUBMITTED column (global
                              # column counter across all submits)
    slow_client: int = -1     # the Tth submitted ticket's client never
                              # collects (result() stalls `secs` first)
    corrupt_panel: int = -1   # flip one byte of front group F's
                              # resident L stack before the next scrub
    # ---- fleet domain (ISSUE 14) --------------------------------------
    kill_replica: int = -1    # this fleet replica dies (SIGKILL in a
                              # process replica, simulated crash in a
                              # thread replica)...
    batch: int = -1           # ...before serving its Kth accepted
                              # batch (0-based per-replica count)
    quarantine_replica: int = -1  # this replica quarantines before its
                              # next batch (unroutable, NOT dead)
    slow_replica: int = -1    # this replica stalls `secs` once before
                              # a batch — slow, NOT dead: the health
                              # monitor must not fail it over
    # ---- refactor domain (ISSUE 16) -----------------------------------
    kill_refactor: int = -1   # kill self MID-REFACTOR (shadow numeric
                              # running, nothing adopted yet) on the
                              # Kth refactor of this process (0-based;
                              # spec shorthand kill_refactor@step=K) —
                              # the interrupted-refactor domain: the
                              # previous handle must keep serving
    poison_values: int = -1   # NaN the new-values entry assembling into
                              # supernode S mid-refactor (same targeting
                              # as nan_supernode, scoped to refactor) —
                              # the sentinels/canary must reject and
                              # roll back, adopting nothing

    @property
    def armed(self) -> bool:
        return (self.kill_group >= 0 or self.nan_supernode >= 0
                or self.comm_armed or self.serve_armed
                or self.fleet_armed or self.refactor_armed)

    @property
    def comm_armed(self) -> bool:
        return self.kill_op >= 0 or self.stall_rank >= 0

    @property
    def serve_armed(self) -> bool:
        return (self.poison_rhs >= 0 or self.slow_client >= 0
                or self.corrupt_panel >= 0)

    @property
    def fleet_armed(self) -> bool:
        return (self.kill_replica >= 0 or self.quarantine_replica >= 0
                or self.slow_replica >= 0)

    @property
    def refactor_armed(self) -> bool:
        return self.kill_refactor >= 0 or self.poison_values >= 0


def parse_chaos_spec(spec: str) -> ChaosPlan:
    """'kill_group=5,signal=term' -> ChaosPlan.  Unknown keys raise —
    a typo'd knob silently injecting nothing would defeat the test
    (the parse_fault_spec discipline).  'kill_rank=R@group=G' is the
    rank-failure shorthand: rank R SIGKILLs itself after its dispatch
    group G."""
    plan = ChaosPlan()
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        key, _, val = part.partition("=")
        key = key.strip()
        if key == "kill_rank":
            rank, at, group = val.partition("@group=")
            plan.kill_rank = int(rank)
            if at:
                plan.kill_group = int(group)
        elif key == "kill_replica":
            rid, at, batch = val.partition("@batch=")
            plan.kill_replica = int(rid)
            plan.batch = int(batch) if at else 0
        elif key == "kill_refactor@step" or key == "kill_refactor":
            # 'kill_refactor@step=K' (the documented shorthand) or the
            # plain 'kill_refactor=K' both mean: die mid-refactor on
            # the Kth refactor of this process
            plan.kill_refactor = int(val)
        elif key in ("kill_group", "nan_supernode", "kill_op",
                     "stall_rank", "stall_op", "epoch", "poison_rhs",
                     "slow_client", "corrupt_panel", "batch",
                     "quarantine_replica", "slow_replica",
                     "poison_values"):
            setattr(plan, key, int(val))
        elif key == "secs":
            plan.secs = float(val)
        elif key == "signal":
            val = val.strip().lower()
            if val not in ("kill", "term"):
                raise ValueError(
                    f"chaos signal must be 'kill' or 'term', got {val!r}")
            plan.signal = val
        else:
            raise ValueError(f"unknown chaos-injection knob {key!r}")
    return plan


# the victim's distributed identity, bound by TreeComm construction (and
# re-bound with the ORIGINAL rank id by recovery epochs, so a survivor
# renumbered into a dead rank's slot never inherits its injection)
_BOUND = {"rank": -1, "epoch": 0}


def bind_rank(rank: int, epoch: int = 0) -> None:
    """Record this process's rank identity for rank-scoped injections
    (called by TreeComm.__init__ / recovery epoch builds)."""
    _BOUND["rank"] = int(rank)
    _BOUND["epoch"] = int(epoch)


class ChaosMonkey:
    """One factorization's injector (built from a ChaosPlan)."""

    def __init__(self, plan: ChaosPlan):
        self.plan = plan
        self.groups_seen = 0
        self._stalled = False
        self._panel_corrupted = False
        self._values_poisoned = False

    def _kill_self(self) -> None:
        sig = (signal.SIGTERM if self.plan.signal == "term"
               else signal.SIGKILL)
        os.kill(os.getpid(), sig)
        if sig == signal.SIGTERM:
            # handlers (checkpoint flush, flightrec dump) ran and
            # chained to the default disposition; if something
            # swallowed it, die anyway — the injection must kill
            os.kill(os.getpid(), signal.SIGKILL)

    # ---- process-kill domain -------------------------------------------
    def on_group(self, gi: int) -> None:
        """Called by the streamed executor after group ``gi`` completes.
        The kill lands AFTER the group's panels are emitted (and after
        any interval checkpoint for it), modeling a preemption between
        dispatch groups — the boundary the resume path restarts from.
        ``kill_rank=R@group=G`` scopes the kill to the rank bound by
        :func:`bind_rank` (any rank when unscoped — the single-process
        back-compat case), in epoch ``epoch`` only."""
        self.groups_seen += 1
        if gi != self.plan.kill_group:
            return
        if self.plan.kill_rank >= 0 and (
                _BOUND["rank"] != self.plan.kill_rank
                or _BOUND["epoch"] != self.plan.epoch):
            return
        self._kill_self()

    # ---- rank-failure domain (comm layer) -------------------------------
    def on_collective(self, seq: int, rank: int, epoch: int) -> None:
        """Called by TreeComm at every outermost public collective
        (``seq`` is 1-based).  ``kill_op`` dies right BEFORE entering
        the op — the silent-rank domain the failure detector must
        convert into RankFailureError on the peers; ``stall_rank`` just
        sleeps, and the detector must NOT declare it failed."""
        p = self.plan
        if epoch != p.epoch:
            return
        if p.kill_op >= 0 and seq >= p.kill_op and \
                p.kill_rank in (-1, rank):
            self._kill_self()
        if p.stall_rank == rank and not self._stalled and \
                seq >= p.stall_op and p.secs > 0:
            self._stalled = True
            import time
            time.sleep(p.secs)

    # ---- serving-tier domain (SolveServer hooks) ------------------------
    def _serve_epoch_ok(self) -> bool:
        # serve injections are epoch-scoped like the comm ones: a server
        # rebuilt inside a recovery epoch is never re-injected
        return _BOUND["epoch"] == self.plan.epoch

    def poison_submit(self, b2: np.ndarray, col0: int) -> np.ndarray:
        """``poison_rhs=C``: if the Cth globally-submitted column falls
        in this request's ``[col0, col0+k)`` range, return a COPY with
        that column NaN'd — the poisoned-request domain the isolation
        path must confine to one ticket.  No-op (same array) otherwise."""
        c = self.plan.poison_rhs
        if c < 0 or not self._serve_epoch_ok():
            return b2
        if not (col0 <= c < col0 + b2.shape[1]):
            return b2
        out = np.array(b2, copy=True)
        out[:, c - col0] = np.nan
        return out

    def is_slow_client(self, ticket_index: int) -> bool:
        """``slow_client=T``: the Tth submitted ticket's client never
        collects promptly — its ``result()`` stalls ``secs`` first (the
        served answer must survive uncollected; the server must never
        block on it)."""
        return (self.plan.slow_client == ticket_index
                and self._serve_epoch_ok())

    def corrupt_resident_panel(self, fronts) -> int:
        """``corrupt_panel=F``: flip one byte in front group F's
        resident L panel stack (in-place in the fronts list — the
        handle now SERVES from the corrupted stack), modeling the
        silent HBM/DRAM bit rot the integrity scrubber exists to catch.
        Fires once; returns the corrupted group index or -1."""
        f = self.plan.corrupt_panel
        if f < 0 or self._panel_corrupted or not self._serve_epoch_ok():
            return -1
        if not (0 <= f < len(fronts)):
            raise ValueError(
                f"chaos corrupt_panel={f}: handle has only "
                f"{len(fronts)} front groups")
        lp, up = fronts[f]
        was_np = isinstance(lp, np.ndarray)
        buf = np.array(np.asarray(lp), copy=True)
        raw = buf.view(np.uint8).reshape(-1)
        raw[len(raw) // 2] ^= 0xFF          # deterministic single flip
        if not was_np:
            import jax.numpy as jnp
            buf = jnp.asarray(buf)
        fronts[f] = (buf, up)
        self._panel_corrupted = True
        return f

    # ---- fleet domain (FleetRouter replica hooks) ------------------------
    def replica_kill_due(self, rid: int, batch_index: int) -> bool:
        """``kill_replica=R@batch=K``: True when replica ``rid`` must
        die before serving its ``batch_index``-th accepted batch
        (0-based per-replica count).  The caller decides how to die: a
        process replica SIGKILLs itself (the real kill -9 domain), a
        thread replica simulates the crash (stops serving with its
        accepted tickets undelivered) — either way the router must
        re-route every undelivered ticket with zero client-visible
        loss.  Epoch-scoped like every serve injection."""
        p = self.plan
        return (p.kill_replica == rid and p.batch >= 0
                and batch_index >= p.batch and self._serve_epoch_ok())

    def replica_quarantined(self, rid: int) -> bool:
        """``quarantine_replica=R``: replica ``rid`` flips to
        quarantined before its next batch — unroutable but ALIVE, the
        degraded-not-dead domain the router must route around (and
        re-route the replica's queued tickets) without erroring any
        client."""
        return (self.plan.quarantine_replica == rid
                and self._serve_epoch_ok())

    def replica_stall_s(self, rid: int) -> float:
        """``slow_replica=R,secs=S``: replica ``rid`` stalls S seconds
        ONCE before a batch.  Slow is NOT dead: the health monitor's
        liveness verdict (pid/thread, never latency) must produce zero
        false-positive failovers.  Returns the stall (0.0 after the
        first fire / for other replicas)."""
        p = self.plan
        if (p.slow_replica != rid or self._stalled
                or p.secs <= 0 or not self._serve_epoch_ok()):
            return 0.0
        self._stalled = True
        return p.secs

    # ---- refactor domain (drivers/gssvx.refactor hooks) ------------------
    def refactor_kill_due(self, step_index: int) -> bool:
        """``kill_refactor@step=K``: True when the ``step_index``-th
        refactor of this process (0-based count, maintained by the
        caller) must die MID-REFACTOR — after the shadow numeric
        factorization has started, before anything is adopted.  The
        caller SIGKILLs via :meth:`kill_now`; crash consistency demands
        the previous handle (and any bundle on disk) stay untouched.
        Epoch-scoped like every serve injection."""
        p = self.plan
        return (p.kill_refactor >= 0 and step_index >= p.kill_refactor
                and self._serve_epoch_ok())

    def kill_now(self) -> None:
        """The injected death itself (SIGKILL, or SIGTERM under
        ``signal=term`` — exercising the checkpoint/flightrec SIGTERM
        chain before dying)."""
        self._kill_self()

    def poison_refactor_values(self, plan,
                               bvals: np.ndarray) -> np.ndarray:
        """``poison_values=S``: NaN the NEW values' entry that assembles
        into supernode S — the poisoned-refactor domain: the breakdown
        sentinels (or the BERR canary) must reject the shadow factors
        and the refactor must roll back adopting nothing.  Same
        deterministic targeting as :meth:`poke_nan`; fires once per
        monkey; returns a poisoned COPY (no-op otherwise)."""
        s = self.plan.poison_values
        if s < 0 or self._values_poisoned or not self._serve_epoch_ok():
            return bvals
        self._values_poisoned = True
        # clamp to the plan's supernode count so one spec drives tests
        # of every problem size (deterministic either way)
        s = min(s, len(plan.sn_group) - 1)
        sub = dataclasses.replace(self.plan, nan_supernode=s,
                                  poison_values=-1)
        return ChaosMonkey(sub).poke_nan(plan, bvals)

    # ---- numeric-poison domain -----------------------------------------
    def poke_nan(self, plan, pattern_values: np.ndarray) -> np.ndarray:
        """Poison supernode ``nan_supernode``: NaN one A-entry that
        assembles into its front, so the non-finite sentinel must trip
        AT that supernode (localization is part of what chaos tests
        pin).  Returns a poisoned COPY; no-op when unarmed."""
        s = self.plan.nan_supernode
        if s < 0:
            return pattern_values
        g = int(plan.sn_group[s])
        slot = int(plan.sn_slot[s])
        grp = plan.groups[g]
        hit = np.nonzero(np.asarray(grp.a_slot) == slot)[0]
        if not len(hit):
            raise ValueError(
                f"chaos nan_supernode={s}: supernode assembles no "
                "A-entries (fully fill-in front) — pick another target")
        out = np.array(pattern_values, copy=True)
        out[np.asarray(grp.a_src)[hit[0]]] = np.nan
        return out


def get_chaos() -> ChaosMonkey | None:
    """The env-armed injector, or None (the production fast path).
    Re-read per call: chaos specs are per-run test state, not a latched
    process constant."""
    from superlu_dist_tpu.utils.options import env_str
    spec = env_str("SLU_TPU_CHAOS").strip()
    if not spec:
        return None
    plan = parse_chaos_spec(spec)
    return ChaosMonkey(plan) if plan.armed else None


def get_comm_chaos() -> ChaosMonkey | None:
    """Comm-layer injector for TreeComm (kill_op / stall_rank specs).
    None unless a COMM injection is armed, so the per-collective hook
    stays one ``is None`` test on the production path."""
    monkey = get_chaos()
    if monkey is None or not monkey.plan.comm_armed:
        return None
    return monkey


def get_serve_chaos() -> ChaosMonkey | None:
    """Serving-tier injector for SolveServer (poison_rhs / slow_client /
    corrupt_panel specs).  Consulted ONCE at server construction — a
    server's lifetime is the run — and None unless a serve injection is
    armed, so submit/scrub hooks stay one ``is None`` test."""
    monkey = get_chaos()
    if monkey is None or not monkey.plan.serve_armed:
        return None
    return monkey


def get_refactor_chaos() -> ChaosMonkey | None:
    """Refactor-domain injector for ``drivers/gssvx.refactor``
    (kill_refactor / poison_values specs).  Consulted ONCE per refactor
    — each refactor call gets its own monkey so the fire-once poison
    latch is per-refactor state — and None unless a refactor injection
    is armed, so the production refactor path pays one ``is None``
    test."""
    monkey = get_chaos()
    if monkey is None or not monkey.plan.refactor_armed:
        return None
    return monkey


def get_fleet_chaos() -> ChaosMonkey | None:
    """Fleet-domain injector for FleetRouter replicas (kill_replica /
    quarantine_replica / slow_replica specs).  Consulted once per
    replica at construction — each replica gets its OWN monkey so the
    fire-once stall/kill flags are per-replica state — and None unless
    a fleet injection is armed, so the replica serve loop stays one
    ``is None`` test."""
    monkey = get_chaos()
    if monkey is None or not monkey.plan.fleet_armed:
        return None
    return monkey


# ---------------------------------------------------------------------------
# outside-the-victim helpers
# ---------------------------------------------------------------------------

def corrupt_file(path: str, mode: str = "flip", offset: int | None = None,
                 keep: int | None = None) -> None:
    """Deterministically damage an on-disk artifact.

    mode="flip": XOR one byte (at ``offset``, default the middle of the
    file) — drives the sha256-mismatch path.  mode="truncate": cut the
    file to ``keep`` bytes (default half) — drives the truncated-array
    path.  Checkpoint loads must answer with structured
    CheckpointCorruptError, never garbage factors."""
    size = os.path.getsize(path)
    if mode == "flip":
        off = size // 2 if offset is None else offset
        with open(path, "r+b") as f:
            f.seek(off)
            b = f.read(1)
            f.seek(off)
            f.write(bytes([b[0] ^ 0xFF]))
    elif mode == "truncate":
        with open(path, "r+b") as f:
            f.truncate(size // 2 if keep is None else keep)
    else:
        raise ValueError(f"unknown corruption mode {mode!r}")


class DyingTreeComm(TreeComm):
    """A rank that dies mid-protocol: after ``die_after`` completed
    public collectives the NEXT one ``os._exit``\\ s with
    :data:`RANK_DEATH_EXIT` instead of participating — the simulated
    rank-death failure domain.  With ``SLU_TPU_COMM_TIMEOUT_S`` armed
    the peers' failure detector converts the abandoned collective into
    :class:`RankFailureError` on every survivor; with bounded waits OFF
    the peers hang, which is what :class:`HangWatchdog` exists to
    bound."""

    def __init__(self, *args, die_after: int = 0, **kwargs):
        super().__init__(*args, **kwargs)
        self._die_after = int(die_after)
        self._public_ops = 0

    def _maybe_die(self):
        if self._public_ops >= self._die_after:
            os._exit(RANK_DEATH_EXIT)
        self._public_ops += 1

    def bcast_any(self, arr, root=0):
        self._maybe_die()
        return super().bcast_any(arr, root=root)

    def reduce_sum_any(self, arr, root=0):
        self._maybe_die()
        return super().reduce_sum_any(arr, root=root)

    def allreduce_sum_any(self, arr, root=0):
        self._maybe_die()
        return super().allreduce_sum_any(arr, root=root)


class CountdownDeadline(Deadline):
    """Deterministic deadline injection: 'expires' at the Nth poll
    instead of on the wall clock, so tests can cancel a factorization
    at an exact dispatch-group boundary (the group loop polls once per
    group).  Everything else — checkpoint-first flush, the collective
    flag allreduce, the structured raise — runs the production path."""

    def __init__(self, fire_after_polls: int, comm=None,
                 poll_every: int = 1):
        super().__init__(seconds=0.0, comm=comm, poll_every=poll_every)
        self.fire_after_polls = int(fire_after_polls)

    def expired_local(self) -> bool:
        return self.polls > self.fire_after_polls


class HangWatchdog:
    """Bounded-hang guard of LAST RESORT for chaos tests and serving
    loops: unless :meth:`disarm` runs within ``seconds``, dump the
    flight recorder (when enabled) and ``os._exit(exit_code)``.  A
    daemon timer — deliberately NOT a signal, so it fires even while the
    main thread is blocked inside a native collective.

    Since ISSUE 8 the FIRST line of defense against a dead peer is the
    failure detector (``SLU_TPU_COMM_TIMEOUT_S`` bounded-wait legs +
    pid liveness): a dead rank raises a structured, recoverable
    :class:`~superlu_dist_tpu.utils.errors.RankFailureError` on every
    survivor, and the watchdog never fires.  Keep the watchdog armed
    only for the domains the detector cannot see — mesh/XLA in-program
    collectives, or a transport wedged with every pid still alive —
    and expect ``os._exit(3)`` to mean exactly that."""

    def __init__(self, seconds: float, exit_code: int = HANG_EXIT,
                 reason: str = "hang-watchdog"):
        self.seconds = float(seconds)
        self.exit_code = int(exit_code)
        self.reason = reason
        self._timer = None

    def _fire(self):
        try:
            from superlu_dist_tpu.persist.checkpoint import flush_active
            flush_active(self.reason)
            from superlu_dist_tpu.obs.flightrec import get_flightrec
            fr = get_flightrec()
            if fr.enabled:
                fr.dump(self.reason)
        except Exception:
            pass
        os._exit(self.exit_code)

    def arm(self) -> "HangWatchdog":
        self._timer = threading.Timer(self.seconds, self._fire)
        self._timer.daemon = True
        self._timer.start()
        return self

    def disarm(self) -> None:
        if self._timer is not None:
            self._timer.cancel()
            self._timer = None

    def __enter__(self):
        return self.arm()

    def __exit__(self, *exc):
        self.disarm()
        return False
