"""SLU116 true-positive fixture (accumulation dtype): matmul-family
calls without ``preferred_element_type`` leave the accumulation width
to whatever the backend picks — on TPU that can be bf16 partials for
16-bit inputs, silently costing the Schur updates their f32 sums."""
import jax.numpy as jnp
from jax import lax


def schur(l21, u12):
    return jnp.matmul(l21, u12)            # flagged: no pin


def gather_sum(oh, child):
    return lax.dot_general(oh, child,      # flagged: no pin
                           (((1,), (0,)), ((), ())))


def fold(vals, seg):
    import jax
    return jax.ops.segment_sum(vals, seg)  # flagged: no pin
