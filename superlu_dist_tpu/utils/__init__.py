from superlu_dist_tpu.utils.options import (
    Options, Fact, ColPerm, RowPerm, IterRefine, Trans, YesNo,
    set_default_options,
)
from superlu_dist_tpu.utils.stats import Stats
from superlu_dist_tpu.utils.errors import SuperLUError, SingularMatrixError
