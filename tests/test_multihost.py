"""Multi-process (multi-host-shaped) mesh smoke test.

The reference's defining capability is factoring across MPI processes
(pdgstrf over a Pr×Pc process grid, SRC/pdgstrf.c:243).  The TPU-native
analog: jax.distributed joins every process's devices into one global
mesh (parallel/grid.gridinit_multihost — the superlu_gridinit-over-
world-communicator analog), and the jitted factorization runs SPMD over
it, XLA inserting the inter-process collectives the reference issues by
hand.  This exercises the real multi-process runtime (2 OS processes,
Gloo transport, 1 CPU device each), not a virtual single-process mesh.
"""

import os
import socket
import subprocess
import sys


_WORKER = r"""
import os, sys
pid = int(sys.argv[1]); nproc = int(sys.argv[2]); port = sys.argv[3]
os.environ["JAX_PLATFORMS"] = "cpu"
import jax
jax.config.update("jax_platforms", "cpu")
jax.distributed.initialize(coordinator_address=f"localhost:{port}",
                           num_processes=nproc, process_id=pid)
import numpy as np, jax.numpy as jnp
from superlu_dist_tpu.parallel.grid import gridinit_multihost
from superlu_dist_tpu.models.gallery import poisson2d
from superlu_dist_tpu.sparse.formats import symmetrize_pattern
from superlu_dist_tpu.utils.options import Options
from superlu_dist_tpu.ordering.dispatch import get_perm_c
from superlu_dist_tpu.symbolic.symbfact import symbolic_factorize
from superlu_dist_tpu.numeric.plan import build_plan
from superlu_dist_tpu.numeric.factor import make_factor_fn

grid = gridinit_multihost(1, nproc)
assert len(jax.devices()) == nproc, jax.devices()
assert grid.mesh.devices.size == nproc

a = poisson2d(12)
sym = symmetrize_pattern(a)
col_order = get_perm_c(Options(), a, sym)
sf = symbolic_factorize(sym, col_order, relax=16, max_supernode=64)
plan = build_plan(sf, min_bucket=8, growth=1.5)
avals = jnp.asarray(sym.data[sf.value_perm], dtype="float32")
thresh = jnp.asarray(np.sqrt(np.finfo(np.float32).eps) * a.norm_max(),
                     "float32")
fn = make_factor_fn(plan, "float32", mesh=grid.mesh)
fronts, tiny = fn(avals, thresh)
jax.block_until_ready(fronts)
assert int(tiny) == 0
for lp, up in fronts:
    for s in lp.addressable_shards:
        assert np.isfinite(np.asarray(s.data)).all()
print(f"proc {pid} ok", flush=True)
"""


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def test_multihost_factorization_two_processes(tmp_path):
    # self-bounded via communicate(timeout=540) — pytest-timeout is not
    # available in this environment
    port = _free_port()
    script = tmp_path / "mh_worker.py"
    script.write_text(_WORKER)
    env = dict(os.environ, PYTHONPATH=os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    # the suite's conftest forces an 8-device virtual host platform; this
    # test wants the REAL multi-process topology (1 device per process)
    env.pop("XLA_FLAGS", None)
    procs = [subprocess.Popen(
        [sys.executable, str(script), str(i), "2", str(port)],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
        for i in range(2)]
    outs = []
    for p in procs:
        out, _ = p.communicate(timeout=540)
        outs.append(out.decode())
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"proc {i} failed:\n{out}"
        assert f"proc {i} ok" in out


import pytest  # noqa: E402

# slow tier: multi-process / native-build / at-scale — fast CI runs -m "not slow"
pytestmark = pytest.mark.slow
