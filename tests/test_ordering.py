import numpy as np
import pytest

from superlu_dist_tpu.models.gallery import poisson2d, random_sparse
from superlu_dist_tpu.ordering.etree import etree_symmetric, postorder, tree_levels
from superlu_dist_tpu.ordering.minimum_degree import minimum_degree
from superlu_dist_tpu.ordering.dissection import geometric_nd, bfs_nd
from superlu_dist_tpu.sparse.formats import symmetrize_pattern


def dense_etree(pat):
    """Brute-force etree via dense symbolic elimination: parent[j] = first
    below-diagonal nonzero of column j of the filled pattern."""
    n = pat.shape[0]
    f = pat.copy()
    parent = np.full(n, -1, dtype=np.int64)
    for j in range(n):
        below = np.flatnonzero(f[j + 1:, j]) + j + 1
        if len(below):
            p = below[0]
            parent[j] = p
            f[below, p] = True      # fill: column j merges into column p
            f[p, below] = True
    return parent


def sym_pattern(a):
    n = a.n_rows
    pat = np.zeros((n, n), dtype=bool)
    rows = np.repeat(np.arange(n), np.diff(a.indptr))
    pat[rows, a.indices] = True
    pat |= pat.T
    np.fill_diagonal(pat, True)
    return pat


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_etree_matches_dense(seed):
    a = random_sparse(30, density=0.08, seed=seed)
    s = symmetrize_pattern(a)
    parent = etree_symmetric(s.n_rows, s.indptr, s.indices)
    want = dense_etree(sym_pattern(a))
    assert np.array_equal(parent, want)


def test_postorder_valid():
    a = poisson2d(6)
    s = symmetrize_pattern(a)
    parent = etree_symmetric(s.n_rows, s.indptr, s.indices)
    post = postorder(parent)
    assert sorted(post) == list(range(len(parent)))
    seen = np.zeros(len(parent), dtype=bool)
    for j in post:
        for pj in [parent[j]]:
            pass
        # children must appear before parents
        assert not seen[j]
        seen[j] = True
        if parent[j] >= 0:
            assert not seen[parent[j]]
    lvl = tree_levels(parent)
    for j, p in enumerate(parent):
        if p >= 0:
            assert lvl[p] > lvl[j]


def fill_count(pat, order):
    """nnz(L) after eliminating in the given order (dense symbolic)."""
    n = pat.shape[0]
    f = pat[np.ix_(order, order)].copy()
    np.fill_diagonal(f, True)
    count = 0
    for j in range(n):
        below = np.flatnonzero(f[j + 1:, j]) + j + 1
        count += len(below) + 1
        if len(below):
            f[np.ix_(below, below)] = True
    return count


@pytest.mark.parametrize("maker", ["poisson", "random"])
def test_orderings_reduce_fill_and_are_perms(maker):
    if maker == "poisson":
        a = poisson2d(8)
    else:
        a = random_sparse(48, density=0.06, seed=3, pattern_symmetric=True)
    s = symmetrize_pattern(a)
    n = s.n_rows
    pat = sym_pattern(a)
    natural_fill = fill_count(pat, np.arange(n))
    md = minimum_degree(n, s.indptr, s.indices)
    assert sorted(md) == list(range(n))
    assert fill_count(pat, md) <= natural_fill
    nd = bfs_nd(n, s.indptr, s.indices, leaf_size=8)
    assert sorted(nd) == list(range(n))
    if maker == "poisson":
        geo = geometric_nd(a.grid_shape)
        assert sorted(geo) == list(range(n))
        assert fill_count(pat, geo) <= natural_fill


def test_geometric_nd_3d():
    from superlu_dist_tpu.models.gallery import poisson3d
    a = poisson3d(4)
    order = geometric_nd(a.grid_shape)
    assert sorted(order) == list(range(64))
