#!/usr/bin/env python
"""Time-stepping gallery driver — the middle rung of the Fact ladder
as a workload: factor ONCE, then ``slu.refactor(lu, values)`` every
step of a drifting-values sequence (the implicit time-integrator
pattern: the Jacobian's sparsity is fixed by the mesh, only its values
move with the state).  Symbolic analysis, the FactorPlan, and every
compiled program are reused by construction — the driver ASSERTS
``symbolic_seconds == 0`` and ``compile_fresh_seconds == 0.0`` on every
step after the first, and emits one bench-style JSON row recording the
per-step numeric cost next to the one-time analysis+compile cost.

    python examples/pddrive_refactor.py [matrix.rua] [--backend cpu]
"""

import json
import sys
import os
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
from examples._common import (pin_cpu_if_requested, load_matrix, make_rhs,
                              report)

N_STEPS = 6


def run_sequence(slu, name, a, n_steps=N_STEPS):
    """Factor once, refactor per step over drifting values; returns the
    per-step timing record proving the reuse invariants."""
    from superlu_dist_tpu.obs.compilestats import COMPILE_STATS
    from superlu_dist_tpu.utils.stats import Stats

    xtrue, b = make_rhs(a)
    t0 = time.perf_counter()
    stats0 = Stats()
    x, lu, stats0, info = slu.gssvx(slu.Options(), a, b, stats=stats0)
    factor_s = time.perf_counter() - t0
    assert info == 0
    resid = report(f"{name} step 0 (DOFACT)", a, b, x, xtrue, stats0)
    assert resid < 1e-8

    rng = np.random.default_rng(7)
    steps = []
    for step in range(1, n_steps):
        # drift the values, keep the pattern (a time step of an
        # implicit integrator: same mesh, new state)
        vals = a.data * (1.0 + 0.05 * rng.standard_normal(a.nnz))
        a_k = type(a)(a.n_rows, a.n_cols, a.indptr, a.indices, vals)
        xtrue_k, b_k = make_rhs(a_k, seed=step)
        marker = COMPILE_STATS.marker()
        st = Stats()
        t1 = time.perf_counter()
        slu.refactor(lu, a_k, stats=st)
        refactor_s = time.perf_counter() - t1
        x_k, lu, st2, info = slu.gssvx(
            slu.Options(fact=slu.Fact.FACTORED), a_k, b_k, lu=lu)
        assert info == 0
        symbolic_s = float(st.utime.get("SYMBFACT", 0.0))
        fresh_s = float(COMPILE_STATS.block(since=marker)["fresh_seconds"])
        # the tentpole invariants, asserted — not a timing proxy
        assert symbolic_s == 0.0, "refactor re-ran symbolic analysis"
        assert fresh_s == 0.0, "refactor triggered a fresh compile"
        resid = report(f"{name} step {step} (refactor)", a_k, b_k, x_k,
                       xtrue_k, st2)
        assert resid < 1e-8
        steps.append({"step": step, "refactor_seconds": round(refactor_s, 4),
                      "symbolic_seconds": symbolic_s,
                      "compile_fresh_seconds": fresh_s})
    return {"matrix": name, "n": a.n_rows, "nnz": a.nnz,
            "factor_seconds": round(factor_s, 4), "steps": steps}


def main():
    pin_cpu_if_requested()
    import superlu_dist_tpu as slu
    from superlu_dist_tpu.models.gallery import hilbert

    a, src = load_matrix()
    print(f"matrix: {src}  n={a.n_rows} nnz={a.nnz}")
    rows = [run_sequence(slu, src, a)]
    # a second, dense-pattern sequence: drifting Hilbert-like values
    h = hilbert(24)
    rows.append(run_sequence(slu, "hilbert(24)", h, n_steps=4))
    # one bench-style JSON row (bench.py contract: a single machine-
    # readable line a sweep harness can collect)
    print("BENCH_ROW " + json.dumps(
        {"workload": "timestep-refactor", "rows": rows}, sort_keys=True))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
