"""slulint CLI — `python -m superlu_dist_tpu.analysis [paths...]`.

Exit codes: 0 = clean (or every finding baselined/suppressed),
1 = new findings, 2 = usage error.  Pure host-side text processing: no
jax import, safe anywhere, fast enough for a pre-commit hook (the CI
budget in scripts/run_slulint.sh is 10 s for the whole tree).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from superlu_dist_tpu.analysis import baseline as bl
from superlu_dist_tpu.analysis.core import (analyze_source, default_rules,
                                            iter_py_files)

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _build_parser():
    p = argparse.ArgumentParser(
        prog="python -m superlu_dist_tpu.analysis",
        description="slulint: project-native static analysis "
                    "(collective-safety SLU101, trace-purity SLU102, "
                    "index-width SLU103, env-knob registry SLU104, "
                    "jit-cache-key hygiene SLU105)")
    p.add_argument("paths", nargs="*",
                   default=["superlu_dist_tpu", "scripts", "bench.py"],
                   help="files/directories to scan (default: the package, "
                        "scripts/, bench.py)")
    p.add_argument("--rules", default=None,
                   help="comma-separated rule ids to run (default: all)")
    p.add_argument("--baseline", default=None,
                   help="baseline JSON path (default: .slulint-baseline."
                        "json next to the repo root when present)")
    p.add_argument("--no-baseline", action="store_true",
                   help="ignore any baseline file")
    p.add_argument("--write-baseline", action="store_true",
                   help="write the current findings to the baseline and "
                        "exit 0")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="machine-readable output")
    p.add_argument("--list-rules", action="store_true",
                   help="print the rule catalog and exit")
    return p


def main(argv=None) -> int:
    args = _build_parser().parse_args(argv)
    rules = default_rules()
    if args.list_rules:
        for r in rules:
            print(f"{r.rule_id}  {r.title}")
        return 0
    if args.rules:
        wanted = {x.strip() for x in args.rules.split(",") if x.strip()}
        unknown = wanted - {r.rule_id for r in rules}
        if unknown:
            print(f"unknown rule id(s): {', '.join(sorted(unknown))}",
                  file=sys.stderr)
            return 2
        rules = [r for r in rules if r.rule_id in wanted]

    missing = [p for p in args.paths if not os.path.exists(p)]
    if missing:
        print(f"no such path(s): {', '.join(missing)}", file=sys.stderr)
        return 2

    findings, sources = [], {}
    for path in iter_py_files(args.paths):
        with open(path, encoding="utf-8") as fh:
            sources[path] = fh.read()
        findings.extend(analyze_source(sources[path], path, rules))

    baseline_path = args.baseline or os.path.join(
        _REPO_ROOT, bl.DEFAULT_BASELINE_NAME)
    if args.write_baseline:
        bl.write(baseline_path,
                 [bl.entry(f, sources[f.path], root=_REPO_ROOT)
                  for f in findings])
        print(f"wrote {len(findings)} finding(s) to {baseline_path}")
        return 0

    baselined = []
    if not args.no_baseline and os.path.exists(baseline_path):
        entries = bl.load(baseline_path)
        findings, baselined = bl.filter_new(findings, sources, entries,
                                            root=_REPO_ROOT)

    if args.as_json:
        print(json.dumps({
            "findings": [vars(f) for f in findings],
            "baselined": len(baselined)}, indent=1))
    else:
        for f in findings:
            print(f.render())
        tail = f" ({len(baselined)} baselined)" if baselined else ""
        print(f"slulint: {len(findings)} finding(s){tail} in "
              f"{len(sources)} file(s)")
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
