from superlu_dist_tpu.refine.ir import (
    iterative_refinement, componentwise_berr)
from superlu_dist_tpu.refine.condest import (
    onenormest, condition_estimate, ferr_estimate)
