"""slulint static-analysis suite tests (docs/ANALYSIS.md).

Per rule SLU101-SLU105: one true-positive fixture snippet and one
known-clean negative; plus suppression-comment handling, baseline
round-trip, the CLI exit-code contract, the knob-registry strict mode,
and the int64 accumulator regressions the rules motivated.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from superlu_dist_tpu.analysis import analyze_source, default_rules
from superlu_dist_tpu.analysis import baseline as bl
from superlu_dist_tpu.analysis.core import PARSE_ERROR_RULE

pytestmark = pytest.mark.analysis

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run_rules(source, path="fixture.py"):
    return analyze_source(source, path, default_rules())


def rule_ids(source, path="fixture.py"):
    return sorted({f.rule for f in run_rules(source, path)})


# --------------------------------------------------------------------------
# SLU101 collective-consistency
# --------------------------------------------------------------------------

SLU101_BRANCH = """
def solve(tc, x, root):
    if tc.rank == root:
        x = tc.bcast_any(x, root=root)
    return x
"""

SLU101_EARLY_EXIT = """
def gather(tc, buf, root):
    if tc.rank != root:
        return None
    return tc.reduce_sum_any(buf, root=root)
"""

SLU101_EXCEPT = """
def shutdown(tc, payload):
    try:
        risky(payload)
    except ValueError:
        tc.bcast_obj(None)
"""

SLU101_ASSERT = """
def ship(tc, lab, sizes):
    assert lab[0] == tc.rank, "ownership"
    return tc.allreduce_sum_any(sizes)
"""

SLU101_CLEAN = """
def refine(tc, r_c, dx, root):
    r = tc.allreduce_sum_any(r_c, root=root)
    if tc.rank == root:
        dx = solve(r)
    dx = tc.bcast_any(dx, root=root)
    if tc.rank != root:
        return None
    return dx
"""


def test_slu101_flags_collective_in_rank_branch():
    fs = run_rules(SLU101_BRANCH)
    assert [f.rule for f in fs] == ["SLU101"]
    assert "rank-dependent control flow" in fs[0].message


def test_slu101_flags_collective_after_rank_early_exit():
    fs = run_rules(SLU101_EARLY_EXIT)
    assert [f.rule for f in fs] == ["SLU101"]
    assert "early exit" in fs[0].message


def test_slu101_flags_collective_in_except_handler():
    fs = run_rules(SLU101_EXCEPT)
    assert [f.rule for f in fs] == ["SLU101"]
    assert "except" in fs[0].message


def test_slu101_flags_collective_after_rank_assert():
    # the exact shape fixed in parallel/panalysis.py:_part_symbolic
    fs = run_rules(SLU101_ASSERT)
    assert [f.rule for f in fs] == ["SLU101"]


def test_slu101_clean_collective_discipline_passes():
    # local work under a rank branch + collectives reached by all ranks
    # + rank-dependent return with NO collective after it: all fine
    assert rule_ids(SLU101_CLEAN) == []


# --------------------------------------------------------------------------
# SLU102 trace-purity
# --------------------------------------------------------------------------

SLU102_POSITIVE = """
import os
import jax
import numpy as np

@jax.jit
def kernel(x):
    scale = float(os.environ.get("SLU_TPU_TRACE", "1"))
    return np.asarray(x) * scale
"""

SLU102_WRAPPED = """
import jax

def make(w):
    def step(x):
        return x * int(w.sum())
    return jax.jit(step, donate_argnums=(0,))
"""

SLU102_CLEAN = """
import jax
import jax.numpy as jnp

@jax.jit
def kernel(x):
    return jnp.asarray(x) * 2.0

def host_helper(x):
    return float(x.sum())
"""


def test_slu102_flags_coercions_and_env_in_jitted():
    fs = run_rules(SLU102_POSITIVE)
    assert {f.rule for f in fs} == {"SLU102"}
    msgs = " ".join(f.message for f in fs)
    assert "environ" in msgs and "float()" in msgs and "asarray" in msgs


def test_slu102_flags_jit_wrapped_local_def():
    fs = run_rules(SLU102_WRAPPED)
    assert {f.rule for f in fs} == {"SLU102"}


def test_slu102_clean_jnp_and_host_code_pass():
    assert rule_ids(SLU102_CLEAN) == []


def test_slu102_scoped_to_hot_subpackages_in_tree():
    # inside the package tree the rule only covers numeric/ solve/ ops/
    path_hot = os.path.join("superlu_dist_tpu", "numeric", "x.py")
    path_cold = os.path.join("superlu_dist_tpu", "io", "x.py")
    assert "SLU102" in rule_ids(SLU102_POSITIVE, path_hot)
    assert "SLU102" not in rule_ids(SLU102_POSITIVE, path_cold)


# --------------------------------------------------------------------------
# SLU103 index-width
# --------------------------------------------------------------------------

SLU103_CUMSUM = """
import numpy as np

def build(counts):
    indptr = np.cumsum(counts, dtype=np.int32)
    return indptr
"""

SLU103_ALIAS = """
import numpy as np
from superlu_dist_tpu.sparse.formats import INT

def build(counts, n):
    indptr = np.zeros(n + 1, dtype=INT)
    indptr = np.cumsum(indptr, dtype=INT)
    return indptr
"""

SLU103_PRODUCT = """
import numpy as np

def flops(n_rows, n_cols):
    return n_rows.astype(np.int32) * n_cols
"""

SLU103_CLEAN = """
import numpy as np
from superlu_dist_tpu.sparse.formats import INT

def build(counts, cols, n):
    indptr = np.cumsum(counts, dtype=np.int64)
    indices = cols.astype(INT)    # indices are bounded by n: INT is fine
    nnz = int(indptr[-1])
    return indptr, indices, nnz
"""


def test_slu103_flags_int32_cumsum():
    fs = run_rules(SLU103_CUMSUM)
    assert [f.rule for f in fs] == ["SLU103"]
    assert "cumsum" in fs[0].message


def test_slu103_flags_env_selected_INT_accumulators():
    # the exact shape fixed in sparse/formats.py (dtype=INT indptr)
    fs = run_rules(SLU103_ALIAS)
    assert {f.rule for f in fs} == {"SLU103"}
    assert len(fs) == 2          # the zeros() ctor and the cumsum


def test_slu103_flags_explicit_int32_product():
    fs = run_rules(SLU103_PRODUCT)
    assert [f.rule for f in fs] == ["SLU103"]
    assert "wraps at 2^31" in fs[0].message


def test_slu103_clean_int64_accumulators_pass():
    assert rule_ids(SLU103_CLEAN) == []


# --------------------------------------------------------------------------
# SLU104 env-knob registry
# --------------------------------------------------------------------------

SLU104_POSITIVE = """
import os

def config():
    return os.environ.get("SLU_TPU_TPYO_KNOB", "1")
"""

SLU104_CLEAN = """
import os

def config(tmp):
    a = os.environ.get("SLU_TPU_TRACE", "")     # registered knob
    b = os.getenv("NSUP")                       # registered knob
    os.environ["SLU_TPU_NOT_A_KNOB_WRITE"] = "x"   # writes are exempt
    return a, b
"""


def test_slu104_flags_unregistered_env_read():
    fs = run_rules(SLU104_POSITIVE)
    assert [f.rule for f in fs] == ["SLU104"]
    assert "SLU_TPU_TPYO_KNOB" in fs[0].message


def test_slu104_registered_reads_and_writes_pass():
    assert rule_ids(SLU104_CLEAN) == []


# --------------------------------------------------------------------------
# SLU105 jit-cache-key hygiene
# --------------------------------------------------------------------------

SLU105_ENV = """
import functools
import os
import jax

@functools.lru_cache(maxsize=None)
def make_kernel(m, w):
    passes = os.environ.get("SLU_TPU_PRECISION", "highest")
    def kern(x):
        return x * len(passes)
    return jax.jit(kern)
"""

SLU105_CLOSURE = """
import functools
import jax

def build(plan, pad_width):
    @functools.lru_cache(maxsize=None)
    def make_kernel(m):
        def kern(x):
            return x[:m + pad_width]
        return jax.jit(kern)
    return make_kernel
"""

SLU105_CLEAN = """
import functools
import jax

from superlu_dist_tpu.utils.options import env_str

def make_kernel(m, w):
    # env resolved OUTSIDE the cached factory and passed as a key arg,
    # the ops/dense.make_front_kernel discipline
    return _make_kernel(m, w, env_str("SLU_TPU_PRECISION"))

@functools.lru_cache(maxsize=None)
def _make_kernel(m, w, precision):
    def kern(x):
        return x[:m] * w if precision else x
    return jax.jit(kern)
"""


def test_slu105_flags_env_read_in_cached_factory():
    fs = run_rules(SLU105_ENV)
    assert [f.rule for f in fs] == ["SLU105"]
    assert "cache key" in fs[0].message


def test_slu105_flags_enclosing_closure_variable():
    fs = run_rules(SLU105_CLOSURE)
    assert [f.rule for f in fs] == ["SLU105"]
    assert "pad_width" in fs[0].message


def test_slu105_parameterized_factory_passes():
    assert rule_ids(SLU105_CLEAN) == []


# --------------------------------------------------------------------------
# suppressions, baseline, parse errors, CLI
# --------------------------------------------------------------------------

def test_inline_suppression_silences_one_line():
    src = SLU101_BRANCH.replace(
        "x = tc.bcast_any(x, root=root)",
        "x = tc.bcast_any(x, root=root)  # slulint: disable=SLU101")
    assert rule_ids(src) == []


def test_inline_suppression_is_rule_specific():
    src = SLU101_BRANCH.replace(
        "x = tc.bcast_any(x, root=root)",
        "x = tc.bcast_any(x, root=root)  # slulint: disable=SLU102")
    assert rule_ids(src) == ["SLU101"]


def test_file_level_suppression():
    src = "# slulint: disable-file=SLU104\n" + SLU104_POSITIVE
    assert rule_ids(src) == []


def test_parse_error_is_a_gating_finding():
    fs = run_rules("def broken(:\n")
    assert [f.rule for f in fs] == [PARSE_ERROR_RULE]


def test_baseline_round_trip(tmp_path):
    src = SLU103_CUMSUM
    path = str(tmp_path / "mod.py")
    (tmp_path / "mod.py").write_text(src)
    findings = analyze_source(src, path, default_rules())
    assert findings
    bp = str(tmp_path / "baseline.json")
    bl.write(bp, [bl.entry(f, src) for f in findings])
    entries = bl.load(bp)
    new, old = bl.filter_new(findings, {path: src}, entries)
    assert new == [] and len(old) == len(findings)
    # the baseline absorbs each finding once: a second identical
    # violation still fails the gate
    doubled = findings + findings
    new2, old2 = bl.filter_new(doubled, {path: src}, entries)
    assert len(new2) == len(findings)
    # editing the flagged line invalidates its entry
    changed = src.replace("np.int32", "np.intc")
    new3, _ = bl.filter_new(analyze_source(changed, path, default_rules()),
                            {path: changed}, entries)
    assert len(new3) == len(findings)


def _run_cli(args, cwd=REPO):
    return subprocess.run(
        [sys.executable, "-m", "superlu_dist_tpu.analysis", *args],
        capture_output=True, text=True, cwd=cwd, timeout=120)


def test_cli_exit_codes_and_json(tmp_path):
    dirty = tmp_path / "dirty.py"
    dirty.write_text(SLU103_CUMSUM)
    clean = tmp_path / "clean.py"
    clean.write_text(SLU103_CLEAN)

    r = _run_cli([str(clean), "--no-baseline"])
    assert r.returncode == 0, r.stdout + r.stderr
    r = _run_cli([str(dirty), "--no-baseline", "--json"])
    assert r.returncode == 1, r.stdout + r.stderr
    doc = json.loads(r.stdout)
    assert doc["findings"][0]["rule"] == "SLU103"
    # --write-baseline then rescan: baselined findings no longer gate
    bp = str(tmp_path / "b.json")
    r = _run_cli([str(dirty), "--baseline", bp, "--write-baseline"])
    assert r.returncode == 0, r.stdout + r.stderr
    r = _run_cli([str(dirty), "--baseline", bp])
    assert r.returncode == 0, r.stdout + r.stderr
    assert "baselined" in r.stdout
    r = _run_cli(["--rules", "SLU999", str(clean)])
    assert r.returncode == 2


def test_cli_repo_tree_is_clean():
    """The acceptance gate: the shipped tree — package, scripts, bench
    AND examples (the default scan scope) — scans clean under the full
    interprocedural tier (committed baseline is empty; any finding is
    inline-suppressed with a justification)."""
    r = _run_cli([])        # default paths: package, scripts, bench, examples
    assert r.returncode == 0, r.stdout + r.stderr
    # examples/ really is in the default scope: ~90 files, not the ~74
    # of the package-only era
    n_files = int(r.stdout.rsplit(" in ", 1)[1].split()[0])
    assert n_files >= 90, r.stdout
    base = json.load(open(os.path.join(REPO, ".slulint-baseline.json")))
    assert base["findings"] == []


# --------------------------------------------------------------------------
# knob registry (SLU104's single source of truth)
# --------------------------------------------------------------------------

def test_unregistered_knob_read_raises():
    from superlu_dist_tpu.utils.options import UnknownKnobError, env_int
    with pytest.raises(UnknownKnobError):
        env_int("SLU_TPU_DOES_NOT_EXIST", 3)


def test_registry_parse_and_defaults(monkeypatch):
    from superlu_dist_tpu.utils import options as o
    assert o.env_int("NSUP") == int(os.environ.get("NSUP", 256))
    monkeypatch.setenv("SLU_TPU_OFFLOAD_LAG", "12")
    assert o.env_int("SLU_TPU_OFFLOAD_LAG") == 12
    monkeypatch.setenv("SLU_TPU_OFFLOAD_LAG", "notanint")
    assert o.env_int("SLU_TPU_OFFLOAD_LAG") == 8   # historical fallback
    monkeypatch.setenv("SLU_TPU_RECOVERY", "off")
    assert o.env_flag("SLU_TPU_RECOVERY") is False
    monkeypatch.setenv("SLU_TPU_RECOVERY", "1")
    assert o.env_flag("SLU_TPU_RECOVERY") is True
    monkeypatch.delenv("SLU_TPU_RECOVERY", raising=False)
    assert o.env_flag("SLU_TPU_RECOVERY") is True  # default


def test_strict_env_flags_typod_knob():
    """SLU_TPU_STRICT_ENV=1 + a typo'd knob name raises with a
    did-you-mean, at the first registry read (subprocess: the check is
    once-per-process)."""
    code = ("import superlu_dist_tpu.utils.options as o\n"
            "o.env_int('NSUP')\n")
    env = dict(os.environ, SLU_TPU_STRICT_ENV="1",
               SLU_TPU_PRECISON="high")   # sic: missing I
    r = subprocess.run([sys.executable, "-c", code], env=env,
                       capture_output=True, text=True, cwd=REPO, timeout=60)
    assert r.returncode != 0
    assert "SLU_TPU_PRECISON" in r.stderr
    assert "SLU_TPU_PRECISION" in r.stderr   # the did-you-mean
    # without strict mode the same typo is tolerated (historical behavior)
    env.pop("SLU_TPU_STRICT_ENV")
    r = subprocess.run([sys.executable, "-c", code], env=env,
                       capture_output=True, text=True, cwd=REPO, timeout=60)
    assert r.returncode == 0, r.stderr


def test_knob_table_covers_registry_and_docs_in_sync():
    from superlu_dist_tpu.utils.options import KNOB_REGISTRY, knob_table_md
    table = knob_table_md()
    for name in KNOB_REGISTRY:
        assert f"`{name}`" in table
    doc = open(os.path.join(REPO, "docs", "ANALYSIS.md")).read()
    for name in KNOB_REGISTRY:
        assert f"`{name}`" in doc, f"docs/ANALYSIS.md missing knob {name}"


# --------------------------------------------------------------------------
# int64 accumulator regressions (the SLU103 true-positive fixes)
# --------------------------------------------------------------------------

def test_counts_to_indptr_past_int32():
    """counts that sum past 2^31 produce exact int64 offsets; the old
    dtype=INT cumsum wrapped negative in the default int32-index build."""
    from superlu_dist_tpu.sparse.formats import counts_to_indptr
    counts = np.full(5, 2 ** 29, dtype=np.int32)   # total 2.5*2^30 > 2^31
    indptr = counts_to_indptr(counts)
    assert indptr.dtype == np.int64
    assert int(indptr[-1]) == 5 * 2 ** 29
    wrapped = np.cumsum(counts, dtype=np.int32)    # the old behavior
    assert int(wrapped[-1]) != 5 * 2 ** 29         # proves the hazard


def test_coo_to_csr_indptr_is_int64_despite_int32_indices():
    from superlu_dist_tpu.sparse.formats import INT, coo_to_csr
    a = coo_to_csr(3, 3, [0, 1, 2], [1, 2, 0], [1.0, 2.0, 3.0])
    assert a.indptr.dtype == np.int64
    assert a.indices.dtype == INT


def test_supernode_nnz_past_int32():
    """A structure whose width*rows product overflows int32: one 50k-wide
    supernode with 50k below-diagonal rows has w*u = 2.5e9 > 2^31."""
    from superlu_dist_tpu.symbolic.symbfact import supernode_nnz
    w = np.array([50_000], dtype=np.int32)
    u = np.array([50_000], dtype=np.int32)
    tri, rect = supernode_nnz(w, u)
    assert rect == 2_500_000_000
    assert tri == 50_000 * 50_001 // 2
    with np.errstate(over="ignore"):
        assert int((w * u)[0]) != 2_500_000_000   # int32 product wraps


# --------------------------------------------------------------------------
# v2: interprocedural dataflow tier (callgraph.py + dataflow.py)
# --------------------------------------------------------------------------

FIXDIR = os.path.join(REPO, "tests", "fixtures", "slulint")


def _lexical_rules():
    """The PR-3 tier: same rules with the interprocedural pass off."""
    from superlu_dist_tpu.analysis.rules_collective import CollectiveRule
    from superlu_dist_tpu.analysis.rules_index import IndexWidthRule
    from superlu_dist_tpu.analysis.rules_trace import JitCacheKeyRule
    return [CollectiveRule(interprocedural=False),
            IndexWidthRule(interprocedural=False),
            JitCacheKeyRule(interprocedural=False)]


def test_slu101_interprocedural_fixture_lexical_v1_misses():
    """Acceptance: the committed wrapper-indirected-collective fixture is
    flagged by v2 and provably missed by the PR-3 lexical tier."""
    from superlu_dist_tpu.analysis import analyze_paths
    path = os.path.join(FIXDIR, "wrapped_collective.py")
    v2 = analyze_paths([path])
    assert [f.rule for f in v2] == ["SLU101", "SLU101"]
    msgs = " ".join(f.message for f in v2)
    assert "reaches collective" in msgs and "bcast_any" in msgs
    assert "rank-dependent control flow" in msgs
    assert "early exit" in msgs          # via the rank-tainted temporary
    v1 = analyze_paths([path], _lexical_rules())
    assert v1 == []


def test_slu103_interprocedural_fixture_lexical_v1_misses():
    from superlu_dist_tpu.analysis import analyze_paths
    path = os.path.join(FIXDIR, "int32_return.py")
    v2 = analyze_paths([path])
    assert [f.rule for f in v2] == ["SLU103", "SLU103"]
    msgs = " ".join(f.message for f in v2)
    assert "return of" in msgs            # i32 through _alloc's return
    assert "int32-typed value" in msgs
    # build_promoted's .astype(np.int64) cleared the taint
    assert all("cumsum" not in f.message for f in v2)
    v1 = analyze_paths([path], _lexical_rules())
    assert v1 == []


def test_slu107_raw_dim_fixture_pair():
    """Acceptance (ISSUE 11 satellite): the committed raw-dimension
    jit-factory fixture is flagged by SLU107 — the exact pattern that
    produced the BENCH_r02 119-kernel blowup — while the ladder-rounded
    twin stays clean."""
    from superlu_dist_tpu.analysis import analyze_paths
    raw = analyze_paths([os.path.join(FIXDIR, "raw_dim_key.py")])
    assert sorted(f.rule for f in raw) == ["SLU107", "SLU107"]
    msgs = " ".join(f.message for f in raw)
    assert "raw (unbucketed) dimension" in msgs
    assert "len(...)" in msgs and ".shape" in msgs
    assert "bucket" in raw[0].hint
    clean = analyze_paths([os.path.join(FIXDIR, "bucketed_dim_key.py")])
    assert clean == []


SLU107_INLINE = """
import functools, jax, jax.numpy as jnp

@functools.lru_cache(maxsize=None)
def make(n):
    return jax.jit(lambda x: x[:n])

def a(x):
    return make(x.size)(x)          # raw .size -> flagged

def b(x):
    return make(_bucket_len(x.size))(x)   # rung-rounded -> clean
"""


def test_slu107_flags_size_and_respects_bucketizers():
    from superlu_dist_tpu.analysis import analyze_source
    fs = analyze_source(SLU107_INLINE, "mod.py", default_rules())
    slu107 = [f for f in fs if f.rule == "SLU107"]
    assert len(slu107) == 1
    assert ".size" in slu107[0].message


SLU101_RANK_TEMP = """
def solve(tc, x, root):
    r = tc.rank
    if r == root:
        x = tc.bcast_any(x, root=root)
    return x
"""

SLU101_RANK_PREDICATE = """
def is_root(tc):
    return tc.rank == 0

def ship(tc, x):
    if is_root(tc):
        x = tc.bcast_any(x)
    return x
"""


def test_slu101_rank_taint_through_temporary():
    fs = run_rules(SLU101_RANK_TEMP)
    assert [f.rule for f in fs] == ["SLU101"]
    assert analyze_source(SLU101_RANK_TEMP, "fixture.py",
                          _lexical_rules()) == []


def test_slu101_rank_taint_through_predicate_function():
    fs = run_rules(SLU101_RANK_PREDICATE)
    assert [f.rule for f in fs] == ["SLU101"]
    assert analyze_source(SLU101_RANK_PREDICATE, "fixture.py",
                          _lexical_rules()) == []


SLU105_ENV_HELPER = """
import functools
import os
import jax

def _resolve():
    return os.environ.get("SLU_TPU_PRECISION", "highest")

@functools.lru_cache(maxsize=None)
def make_kernel(m):
    passes = _resolve()
    def kern(x):
        return x * len(passes)
    return jax.jit(kern)
"""

SLU105_LATCHED = """
import functools
import os
import jax

@functools.lru_cache(maxsize=None)
def _precision():
    return os.environ.get("SLU_TPU_PRECISION", "highest")

@functools.lru_cache(maxsize=None)
def make_kernel(m):
    p = _precision()
    def kern(x):
        return x * len(p)
    return jax.jit(kern)
"""


def test_slu105_env_through_helper_call():
    fs = run_rules(SLU105_ENV_HELPER)
    assert [f.rule for f in fs] == ["SLU105"]
    assert "reaches an env read" in fs[0].message
    assert analyze_source(SLU105_ENV_HELPER, "fixture.py",
                          _lexical_rules()) == []


def test_slu105_latched_constant_exempt():
    """A zero-arg lru_cached env reader is a read-once process constant
    (ops/dense._precision): baking it in without a key is sound."""
    assert rule_ids(SLU105_LATCHED) == []


def test_callgraph_resolves_methods_and_returns():
    from superlu_dist_tpu.analysis.callgraph import (build_project,
                                                     module_name_for_path)
    assert module_name_for_path(
        os.path.join("superlu_dist_tpu", "numeric", "stream.py")) \
        == "superlu_dist_tpu.numeric.stream"
    src = """
class Comm:
    def leg(self):
        return 1
    def composite(self):
        return self.leg()

def make():
    return Comm()

def use(c: Comm):
    c.composite()

def use_factory():
    c = make()
    c.leg()
"""
    proj = build_project({"m.py": src})
    fns = proj.functions
    assert "m.Comm.composite" in fns
    assert fns["m.Comm.composite"].calls == ["m.Comm.leg"]
    assert fns["m.use"].calls == ["m.Comm.composite"]     # annotation
    assert "m.Comm.leg" in fns["m.use_factory"].calls     # return class


def test_update_baseline_prunes_stale_entries(tmp_path):
    mod = tmp_path / "mod.py"
    mod.write_text(SLU103_CUMSUM)
    bp = str(tmp_path / "b.json")
    r = _run_cli([str(mod), "--baseline", bp, "--write-baseline"])
    assert r.returncode == 0, r.stdout + r.stderr
    assert json.load(open(bp))["findings"]
    # fix the finding; --update-baseline prunes it and reports the drift
    mod.write_text(SLU103_CLEAN)
    r = _run_cli([str(mod), "--baseline", bp, "--update-baseline"])
    assert r.returncode == 0, r.stdout + r.stderr
    assert "stale pruned" in r.stdout
    assert json.load(open(bp))["findings"] == []
    # NEW findings are never added by --update-baseline (that is
    # --write-baseline's deliberate act)
    mod.write_text(SLU103_CUMSUM)
    r = _run_cli([str(mod), "--baseline", bp, "--update-baseline"])
    assert r.returncode == 0
    assert "NEW finding" in r.stdout
    assert json.load(open(bp))["findings"] == []


def test_no_dataflow_flag_restores_v1():
    """--no-dataflow measures what the interprocedural tier adds."""
    path = os.path.join("tests", "fixtures", "slulint",
                        "wrapped_collective.py")
    r = _run_cli([path, "--no-baseline"])
    assert r.returncode == 1, r.stdout
    r = _run_cli([path, "--no-baseline", "--no-dataflow"])
    assert r.returncode == 0, r.stdout
