"""Machine-parameter and timer sanity probes.

Capability analog of the reference's INSTALL tests (INSTALL/dmachtst.c:
machine epsilon / underflow / overflow probes; INSTALL/timertst.c: timer
resolution), driven by install.csh.  Here they guard the assumptions the
GESP threshold arithmetic makes: thresh = sqrt(eps)·‖A‖ must be
representable and monotone in both working precisions, and the phase
timers must actually resolve the phases they time.
"""

import time

import numpy as np


def _probe_eps(dtype):
    """Smallest e with 1 + e != 1 — must match np.finfo."""
    one = dtype(1.0)
    e = dtype(1.0)
    while one + e / dtype(2.0) != one:
        e = e / dtype(2.0)
    return e


def test_machine_epsilon_f64():
    assert _probe_eps(np.float64) == np.finfo(np.float64).eps


def test_machine_epsilon_f32():
    assert _probe_eps(np.float32) == np.finfo(np.float32).eps


def test_underflow_overflow_bounds():
    for dt in (np.float32, np.float64):
        fi = np.finfo(dt)
        assert fi.tiny > 0 and np.isfinite(fi.tiny)
        assert np.isfinite(fi.max)
        with np.errstate(over="ignore"):
            assert np.isinf(dt(fi.max) * dt(2.0))
        # GESP threshold must stay representable across the anorm range
        for anorm in (fi.tiny, 1.0, fi.max ** 0.5):
            t = np.sqrt(fi.eps) * dt(anorm)
            assert np.isfinite(t) and t >= 0


def test_timer_resolution():
    """perf_counter must resolve well under one solver phase (~ms)."""
    res = time.get_clock_info("perf_counter").resolution
    assert res < 1e-4
    t0 = time.perf_counter()
    while time.perf_counter() == t0:
        pass
    assert time.perf_counter() - t0 < 1e-3


def test_stats_timer_accumulates():
    from superlu_dist_tpu.utils.stats import Stats
    s = Stats()
    with s.timer("FACT"):
        time.sleep(0.01)
    assert s.utime["FACT"] >= 0.009
