"""Double-float ("df64") arithmetic: ~2^-48 precision from f32 pairs.

TPUs have no fp64 MXU (SURVEY.md §7 hard part 1).  This module provides the
emulated-double building blocks the full-precision path is built from: a
value is an (hi, lo) pair of float32 arrays with value = hi + lo and
|lo| <= ulp(hi)/2, giving ~48 significant bits — enough for the reference's
residual targets (≤1e-10) without iterative refinement, at ~20-30 f32 flops
per MAC.

Algorithms are the classical error-free transformations (Dekker/Knuth):
two_sum, Dekker splitting (2^12+1 factor for f32), two_prod without FMA.
The matmul accumulates in df64 via a fori_loop of rank-1 exact outer
products — VPU-bound by design (the MXU's f32 accumulation would round at
2^-24 and destroy the low words).  Use it where accuracy is worth 20-30x
flops: diagonal-block factors of nearly-singular fronts, high-precision
residuals on device.  The default pipeline (f32 factor + f64 host IR)
remains the fast path.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

_SPLIT = jnp.float32(4097.0)      # 2^12 + 1 (Dekker split factor for f32)

# Error-free transformations depend on every intermediate being rounded
# exactly once to f32 and on each HLO value being computed exactly once.
# CAVEAT (XLA:CPU, jax 0.9): the CPU pipeline strips optimization_barrier
# (33 in the StableHLO, 0 after compile) and its instruction fusion
# recomputes broadcast-fed subexpressions with LLVM contraction freedoms,
# perturbing the compensation terms toward plain-f32 accuracy.  Running
# with XLA_FLAGS=--xla_disable_hlo_passes=fusion,cpu-instruction-fusion
# restores df64-class accuracy under jit on CPU (tests verify this in a
# subprocess); eager mode is always exact.  The barriers below are kept
# for backends that honor them.
_bar = jax.lax.optimization_barrier

# Compat shim: older jaxlibs (< 0.5) ship optimization_barrier without a
# vmap batching rule, and the df64 factorization vmaps the per-front
# kernel over the batch axis (numeric/df64_factor.py).  The barrier is
# shape-preserving and elementwise-transparent, so batching is identity
# on the batch dims.
try:
    from jax.interpreters import batching as _batching
    from jax._src.lax import lax as _lax_internal
    _bar_p = _lax_internal.optimization_barrier_p
    if _bar_p not in _batching.primitive_batchers:
        def _bar_batching(args, dims, **params):
            return _bar_p.bind(*args, **params), dims
        _batching.primitive_batchers[_bar_p] = _bar_batching
except Exception:                                # pragma: no cover
    pass                                         # newer jax: rule exists


def two_sum(a, b):
    """Exact sum: returns (s, err) with s + err == a + b exactly."""
    s = _bar(a + b)
    bb = _bar(s - a)
    err = _bar(_bar(a - _bar(s - bb)) + _bar(b - bb))
    return s, err


def quick_two_sum(a, b):
    """Exact sum assuming |a| >= |b|."""
    s = _bar(a + b)
    return s, _bar(b - _bar(s - a))


def _split(a):
    t = _bar(_SPLIT * a)
    hi = _bar(t - _bar(t - a))
    return hi, _bar(a - hi)


def two_prod(a, b):
    """Exact product: (p, err) with p + err == a·b exactly (Dekker)."""
    p = _bar(a * b)
    ahi, alo = _split(a)
    bhi, blo = _split(b)
    err = _bar(_bar(_bar(_bar(ahi * bhi) - p) + _bar(ahi * blo))
               + _bar(alo * bhi))
    err = _bar(err + _bar(alo * blo))
    return p, err


def _bcast(x, y):
    """Materialize (and barrier-pin) operands at the common output shape.

    XLA sinks broadcasts below elementwise chains; on mixed-shape df64
    operands (e.g. a rank-1-update's (m,1) x (1,n)) that rewrite reorders
    the EFT arithmetic and destroys the low-word compensation (observed:
    jit result degrades to plain f32).  Broadcasting first, pinned by a
    barrier, keeps every transform at one shape.
    """
    xh, xl = x
    yh, yl = y
    shape = jnp.broadcast_shapes(xh.shape, yh.shape)
    if xh.shape == shape and yh.shape == shape:
        return xh, xl, yh, yl
    return (_bar(jnp.broadcast_to(xh, shape)),
            _bar(jnp.broadcast_to(xl, shape)),
            _bar(jnp.broadcast_to(yh, shape)),
            _bar(jnp.broadcast_to(yl, shape)))


def df64_add(x, y):
    """(hi, lo) + (hi, lo) -> normalized (hi, lo)."""
    xh, xl, yh, yl = _bcast(x, y)
    s, e = two_sum(xh, yh)
    e = e + xl + yl
    return quick_two_sum(s, e)


def df64_mul(x, y):
    xh, xl, yh, yl = _bcast(x, y)
    p, e = two_prod(xh, yh)
    e = e + xh * yl + xl * yh
    return quick_two_sum(p, e)


def df64_neg(x):
    return -x[0], -x[1]


def df64_sub(x, y):
    return df64_add(x, df64_neg(y))


def df64_div(x, y):
    """df64 division (long division with one correction): ~2^-47."""
    xh, xl, yh, yl = _bcast(x, y)
    q1 = _bar(xh / yh)
    r = df64_sub((xh, xl), df64_mul((q1, jnp.zeros_like(q1)), (yh, yl)))
    q2 = _bar(r[0] / yh)
    r2 = df64_sub(r, df64_mul((q2, jnp.zeros_like(q2)), (yh, yl)))
    q3 = _bar(r2[0] / yh)
    s, e = two_sum(q1, q2)
    return quick_two_sum(s, e + q3)


def df64_from_f64(a):
    """Split a float64 array into a df64 pair of f32 device arrays.

    The split is computed host-side in numpy so it is exact regardless of
    jax_enable_x64 (with x64 off, a device-side `a - hi` would silently
    canonicalize to f32 and zero the low word).
    """
    import numpy as np
    a64 = np.asarray(a, dtype=np.float64)
    hi = np.asarray(a64, dtype=np.float32)
    lo = np.asarray(a64 - hi.astype(np.float64), dtype=np.float32)
    return jnp.asarray(hi), jnp.asarray(lo)


def df64_to_f64(x):
    """Recombine to a host numpy float64 array (exact under any x64
    setting — device f64 may not exist on TPU)."""
    import numpy as np
    hi, lo = x
    return np.asarray(hi, dtype=np.float64) + np.asarray(lo, np.float64)


# ---- complex double-float ("zdf64"): re/im each an (hi, lo) pair ---------
# The z-twin discipline of the reference (pzgstrf.c:243 et al.) without
# twin files: a complex value is the 4-tuple (re_hi, re_lo, im_hi, im_lo)
# and the arithmetic is composed from the real error-free transforms.

def zdf64_add(x, y):
    r = df64_add((x[0], x[1]), (y[0], y[1]))
    i = df64_add((x[2], x[3]), (y[2], y[3]))
    return (*r, *i)


def zdf64_sub(x, y):
    r = df64_sub((x[0], x[1]), (y[0], y[1]))
    i = df64_sub((x[2], x[3]), (y[2], y[3]))
    return (*r, *i)


def zdf64_neg(x):
    return (-x[0], -x[1], -x[2], -x[3])


def zdf64_mul(x, y):
    """(a+bi)(c+di) = (ac - bd) + (ad + bc)i, every product/sum in df64."""
    a, b = (x[0], x[1]), (x[2], x[3])
    c, d = (y[0], y[1]), (y[2], y[3])
    re = df64_sub(df64_mul(a, c), df64_mul(b, d))
    im = df64_add(df64_mul(a, d), df64_mul(b, c))
    return (*re, *im)


def zdf64_div(x, y):
    """Scaled complex division — Smith's algorithm in df64 components.

    The naive x·conj(y)/|y|² squares the denominator magnitude and
    overflows/underflows the f32 hi words at ~1.9e19 / ~1e-19, silently
    halving the usable exponent range; Smith's form keeps every
    intermediate within a constant factor of the operands (the
    reference's scaled slud_z_div discipline, SRC/dcomplex_dist.c).
    Branchless: operands are component-swapped so the larger-magnitude
    denominator part leads, and the imaginary part's sign is fixed up.
    """
    swap = jnp.abs(y[2]) > jnp.abs(y[0])

    def sel(p, q):
        return tuple(jnp.where(swap, pi, qi) for pi, qi in zip(p, q))

    c = sel((y[2], y[3]), (y[0], y[1]))     # larger |.| denominator part
    d = sel((y[0], y[1]), (y[2], y[3]))
    a = sel((x[2], x[3]), (x[0], x[1]))
    b = sel((x[0], x[1]), (x[2], x[3]))
    t = df64_div(d, c)                      # |t| <= 1 by construction
    den = df64_add(c, df64_mul(d, t))
    re = df64_div(df64_add(a, df64_mul(b, t)), den)
    im = df64_div(df64_sub(b, df64_mul(a, t)), den)
    im = tuple(jnp.where(swap, -i, i) for i in im)
    return (*re, *im)


def zdf64_from_c128(a):
    """Split a complex128 array into the (re_hi, re_lo, im_hi, im_lo)
    f32 quadruple (exact host-side splits, see df64_from_f64)."""
    import numpy as np
    a = np.asarray(a, dtype=np.complex128)
    rh, rl = df64_from_f64(a.real)
    ih, il = df64_from_f64(a.imag)
    return rh, rl, ih, il


def zdf64_to_c128(x):
    """Recombine to host complex128 (exact)."""
    import numpy as np
    return (df64_to_f64((x[0], x[1]))
            + 1j * df64_to_f64((x[2], x[3]))).astype(np.complex128)


def df64_matmul(ah, al, bh, bl):
    """df64 GEMM: (m,k) x (k,n) pairs -> (m,n) pair, ~2^-48 accurate.

    A fori_loop of exact rank-1 outer products accumulated in df64.
    Deliberately NOT an MXU matmul: f32 accumulation inside the MXU rounds
    every partial sum to 2^-24, which is exactly what this path exists to
    avoid; the elementwise error-free transforms vectorize on the VPU.
    """
    m, k = ah.shape
    n = bh.shape[1]

    def step(i, acc):
        ch, cl = acc
        a_i = (ah[:, i][:, None], al[:, i][:, None])
        b_i = (bh[i, :][None, :], bl[i, :][None, :])
        return df64_add((ch, cl), df64_mul(a_i, b_i))

    zero = jnp.zeros((m, n), dtype=jnp.float32)
    ch, cl = jax.lax.fori_loop(0, k, step, (zero, zero))
    return ch, cl
