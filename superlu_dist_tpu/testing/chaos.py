"""Failure-domain chaos harness — deterministic whole-domain injection.

PR 1's :class:`FaultyTreeComm` perturbs the comm TRANSPORT (chunk
drop/dup/reorder with retries); this module generalizes the idea to the
failure domains a serving fleet actually loses: a process killed
mid-factorization, a numeric value going NaN at a chosen supernode, a
checkpoint artifact corrupted on disk, a rank dying mid-protocol.  Every
injection is a deterministic function of the spec — no randomness races
— so a chaos test either reproduces exactly or the code under test
changed.

Enable in a victim process via the registered env knob::

    SLU_TPU_CHAOS='kill_group=5'            # SIGKILL self after group 5
    SLU_TPU_CHAOS='kill_group=5,signal=term'  # SIGTERM instead (exercises
                                              # the checkpoint/flightrec
                                              # SIGTERM chain)
    SLU_TPU_CHAOS='nan_supernode=3'         # poison supernode 3's values

The factor path consults :func:`get_chaos` once per factorization
(numeric/factor.py) and the streamed executor calls
:meth:`ChaosMonkey.on_group` after each completed dispatch group — a
no-op None when the knob is unset, so the production hot path pays one
``is None`` test.

Helpers for tests that inject from OUTSIDE the victim:

* :func:`corrupt_file` — deterministic bit-flip / truncation of a
  checkpoint artifact (drives the persist integrity paths);
* :class:`DyingTreeComm` — a rank that exits mid-protocol after N
  public collectives (simulated rank death);
* :class:`HangWatchdog` — bounds a lost-peer hang: dump the flight
  recorder and ``os._exit`` after a timeout unless disarmed (the
  cooperative way a serving process converts an infinite collective
  hang into a bounded, diagnosable abort).
"""

from __future__ import annotations

import dataclasses
import os
import signal
import threading

import numpy as np

from superlu_dist_tpu.parallel.treecomm import TreeComm
from superlu_dist_tpu.utils.deadline import Deadline

#: exit code of a rank killed by its own DyingTreeComm (distinct from
#: any Python/pytest code so harnesses can assert the death was the
#: injected one)
RANK_DEATH_EXIT = 17
#: exit code of a HangWatchdog abort
HANG_EXIT = 3


@dataclasses.dataclass
class ChaosPlan:
    """Parsed injection spec (all fields optional; -1 / "" = off)."""

    kill_group: int = -1      # kill self after completing this group
    signal: str = "kill"      # "kill" (SIGKILL, the kill -9 domain) or
                              # "term" (SIGTERM — handlers run first)
    nan_supernode: int = -1   # poison this supernode's A-entries

    @property
    def armed(self) -> bool:
        return self.kill_group >= 0 or self.nan_supernode >= 0


def parse_chaos_spec(spec: str) -> ChaosPlan:
    """'kill_group=5,signal=term' -> ChaosPlan.  Unknown keys raise —
    a typo'd knob silently injecting nothing would defeat the test
    (the parse_fault_spec discipline)."""
    plan = ChaosPlan()
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        key, _, val = part.partition("=")
        key = key.strip()
        if key in ("kill_group", "nan_supernode"):
            setattr(plan, key, int(val))
        elif key == "signal":
            val = val.strip().lower()
            if val not in ("kill", "term"):
                raise ValueError(
                    f"chaos signal must be 'kill' or 'term', got {val!r}")
            plan.signal = val
        else:
            raise ValueError(f"unknown chaos-injection knob {key!r}")
    return plan


class ChaosMonkey:
    """One factorization's injector (built from a ChaosPlan)."""

    def __init__(self, plan: ChaosPlan):
        self.plan = plan
        self.groups_seen = 0

    # ---- process-kill domain -------------------------------------------
    def on_group(self, gi: int) -> None:
        """Called by the streamed executor after group ``gi`` completes.
        The kill lands AFTER the group's panels are emitted (and after
        any interval checkpoint for it), modeling a preemption between
        dispatch groups — the boundary the resume path restarts from."""
        self.groups_seen += 1
        if gi == self.plan.kill_group:
            sig = (signal.SIGTERM if self.plan.signal == "term"
                   else signal.SIGKILL)
            os.kill(os.getpid(), sig)
            if sig == signal.SIGTERM:
                # handlers (checkpoint flush, flightrec dump) ran and
                # chained to the default disposition; if something
                # swallowed it, die anyway — the injection must kill
                os.kill(os.getpid(), signal.SIGKILL)

    # ---- numeric-poison domain -----------------------------------------
    def poke_nan(self, plan, pattern_values: np.ndarray) -> np.ndarray:
        """Poison supernode ``nan_supernode``: NaN one A-entry that
        assembles into its front, so the non-finite sentinel must trip
        AT that supernode (localization is part of what chaos tests
        pin).  Returns a poisoned COPY; no-op when unarmed."""
        s = self.plan.nan_supernode
        if s < 0:
            return pattern_values
        g = int(plan.sn_group[s])
        slot = int(plan.sn_slot[s])
        grp = plan.groups[g]
        hit = np.nonzero(np.asarray(grp.a_slot) == slot)[0]
        if not len(hit):
            raise ValueError(
                f"chaos nan_supernode={s}: supernode assembles no "
                "A-entries (fully fill-in front) — pick another target")
        out = np.array(pattern_values, copy=True)
        out[np.asarray(grp.a_src)[hit[0]]] = np.nan
        return out


def get_chaos() -> ChaosMonkey | None:
    """The env-armed injector, or None (the production fast path).
    Re-read per call: chaos specs are per-run test state, not a latched
    process constant."""
    from superlu_dist_tpu.utils.options import env_str
    spec = env_str("SLU_TPU_CHAOS").strip()
    if not spec:
        return None
    plan = parse_chaos_spec(spec)
    return ChaosMonkey(plan) if plan.armed else None


# ---------------------------------------------------------------------------
# outside-the-victim helpers
# ---------------------------------------------------------------------------

def corrupt_file(path: str, mode: str = "flip", offset: int | None = None,
                 keep: int | None = None) -> None:
    """Deterministically damage an on-disk artifact.

    mode="flip": XOR one byte (at ``offset``, default the middle of the
    file) — drives the sha256-mismatch path.  mode="truncate": cut the
    file to ``keep`` bytes (default half) — drives the truncated-array
    path.  Checkpoint loads must answer with structured
    CheckpointCorruptError, never garbage factors."""
    size = os.path.getsize(path)
    if mode == "flip":
        off = size // 2 if offset is None else offset
        with open(path, "r+b") as f:
            f.seek(off)
            b = f.read(1)
            f.seek(off)
            f.write(bytes([b[0] ^ 0xFF]))
    elif mode == "truncate":
        with open(path, "r+b") as f:
            f.truncate(size // 2 if keep is None else keep)
    else:
        raise ValueError(f"unknown corruption mode {mode!r}")


class DyingTreeComm(TreeComm):
    """A rank that dies mid-protocol: after ``die_after`` completed
    public collectives the NEXT one ``os._exit``\\ s with
    :data:`RANK_DEATH_EXIT` instead of participating — the simulated
    rank-death failure domain.  Peers blocked on the abandoned
    collective hang (the documented LockstepVerifier limitation: a rank
    that stops calling collectives leaves nothing to cross-check), which
    is exactly what :class:`HangWatchdog` exists to bound."""

    def __init__(self, *args, die_after: int = 0, **kwargs):
        super().__init__(*args, **kwargs)
        self._die_after = int(die_after)
        self._public_ops = 0

    def _maybe_die(self):
        if self._public_ops >= self._die_after:
            os._exit(RANK_DEATH_EXIT)
        self._public_ops += 1

    def bcast_any(self, arr, root=0):
        self._maybe_die()
        return super().bcast_any(arr, root=root)

    def reduce_sum_any(self, arr, root=0):
        self._maybe_die()
        return super().reduce_sum_any(arr, root=root)

    def allreduce_sum_any(self, arr, root=0):
        self._maybe_die()
        return super().allreduce_sum_any(arr, root=root)


class CountdownDeadline(Deadline):
    """Deterministic deadline injection: 'expires' at the Nth poll
    instead of on the wall clock, so tests can cancel a factorization
    at an exact dispatch-group boundary (the group loop polls once per
    group).  Everything else — checkpoint-first flush, the collective
    flag allreduce, the structured raise — runs the production path."""

    def __init__(self, fire_after_polls: int, comm=None,
                 poll_every: int = 1):
        super().__init__(seconds=0.0, comm=comm, poll_every=poll_every)
        self.fire_after_polls = int(fire_after_polls)

    def expired_local(self) -> bool:
        return self.polls > self.fire_after_polls


class HangWatchdog:
    """Bounded-hang guard for chaos tests and serving loops: unless
    :meth:`disarm` runs within ``seconds``, dump the flight recorder
    (when enabled) and ``os._exit(exit_code)``.  A daemon timer —
    deliberately NOT a signal, so it fires even while the main thread is
    blocked inside a native collective."""

    def __init__(self, seconds: float, exit_code: int = HANG_EXIT,
                 reason: str = "hang-watchdog"):
        self.seconds = float(seconds)
        self.exit_code = int(exit_code)
        self.reason = reason
        self._timer = None

    def _fire(self):
        try:
            from superlu_dist_tpu.persist.checkpoint import flush_active
            flush_active(self.reason)
            from superlu_dist_tpu.obs.flightrec import get_flightrec
            fr = get_flightrec()
            if fr.enabled:
                fr.dump(self.reason)
        except Exception:
            pass
        os._exit(self.exit_code)

    def arm(self) -> "HangWatchdog":
        self._timer = threading.Timer(self.seconds, self._fire)
        self._timer.daemon = True
        self._timer.start()
        return self

    def disarm(self) -> None:
        if self._timer is not None:
            self._timer.cancel()
            self._timer = None

    def __enter__(self):
        return self.arm()

    def __exit__(self, *exc):
        self.disarm()
        return False
