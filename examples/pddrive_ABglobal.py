#!/usr/bin/env python
"""Replicated-input driver — analog of EXAMPLE/pddrive_ABglobal.c
(pdgssvx_ABglobal: A and B given replicated rather than distributed).

    python examples/pddrive_ABglobal.py [matrix.rua] [--backend cpu]
"""

import sys
import os

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
from examples._common import (pin_cpu_if_requested, load_matrix, make_rhs,
                              report)


def main():
    pin_cpu_if_requested()
    import superlu_dist_tpu as slu
    from superlu_dist_tpu.drivers.gssvx import gssvx_ABglobal

    a, src = load_matrix()
    print(f"matrix: {src}  n={a.n_rows} nnz={a.nnz}")
    xtrue, b = make_rhs(a)
    x, lu, stats, info = gssvx_ABglobal(slu.Options(), a, b)
    assert info == 0
    resid = report("pddrive_ABglobal", a, b, x, xtrue, stats)
    assert resid < 1e-10
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
