#!/usr/bin/env python
"""Emulated-double factorization — the f64-on-TPU story.

TPUs have no f64 unit; with x64 off, even requesting float64 silently
computes in f32.  For ill-conditioned systems past the f32+IR boundary
(kappa * 2^-24 > 1), factor_dtype="df64" factors in double-float (hi/lo
f32 pairs, ~2^-48) entirely on f32 hardware.  This example builds a
kappa ~ 1e7 system and compares raw factor quality (no equilibration,
no refinement) between f32 and df64.

    python examples/pddrive_df64.py [--backend cpu]

(On the CPU backend XLA's fusion breaks the error-free transforms; set
XLA_FLAGS=--xla_disable_hlo_passes=fusion,cpu-instruction-fusion as
documented in ops/df64.py.  TPU pipelines honor the barriers.)
"""

import sys
import os

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
if "--backend" in sys.argv and "cpu" in sys.argv:
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_disable_hlo_passes=fusion,"
                                 "cpu-instruction-fusion")
from examples._common import pin_cpu_if_requested


def main():
    pin_cpu_if_requested()
    import numpy as np
    import superlu_dist_tpu as slu
    import superlu_dist_tpu.sparse.formats as fmts
    from superlu_dist_tpu.models.gallery import poisson2d
    from superlu_dist_tpu.utils.options import Options, IterRefine

    a0 = poisson2d(10)
    s = np.logspace(0, 7, a0.n_rows)          # kappa ~ 1e7
    rows = np.repeat(np.arange(a0.n_rows), np.diff(a0.indptr))
    a = fmts.SparseCSR(a0.n_rows, a0.n_cols, a0.indptr, a0.indices,
                       a0.data * s[rows])
    xt = np.random.default_rng(0).standard_normal(a.n_rows)
    b = a.matvec(xt)
    opt = dict(equil=False, iter_refine=IterRefine.NOREFINE)

    results = {}
    for dt in ("float32", "df64"):
        x, lu, stats, info = slu.gssvx(Options(factor_dtype=dt, **opt),
                                       a, b)
        assert info == 0
        results[dt] = float(np.linalg.norm(b - a.matvec(x))
                            / np.linalg.norm(b))
        print(f"[pddrive_df64] {dt:8s} raw-factor residual "
              f"{results[dt]:.3e}")
    assert results["df64"] < 1e-11
    assert results["df64"] < results["float32"] / 1e3
    print("[pddrive_df64] residual check PASS: df64 delivers ~2^-48 "
          "factors on f32-only hardware")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
