"""Model-problem generators (the framework's benchmark "model family").

The reference ships small Harwell-Boeing fixtures (EXAMPLE/g20.rua etc.,
EXAMPLE/README:31-34) and BASELINE.md targets a 5-pt 3D Poisson with n≈1M.
These generators produce the same class of matrices directly, with grid
coordinates attached so the geometric nested-dissection ordering can be used.
"""

from __future__ import annotations

import numpy as np

from superlu_dist_tpu.sparse.formats import SparseCSR, coo_to_csr



class _Stencil:
    """Shared COO assembly for the grid generators: collect stamped
    slices, then build the CSR once (one definition of the add/concat/
    coo_to_csr pattern for every generator)."""

    def __init__(self, dtype):
        self.dtype = dtype
        self.rows, self.cols, self.vals = [], [], []

    def add(self, r, c, v):
        self.rows.append(r.ravel())
        self.cols.append(c.ravel())
        self.vals.append(np.full(r.size, v, dtype=self.dtype))

    def build(self, n, grid_shape):
        a = coo_to_csr(n, n, np.concatenate(self.rows),
                       np.concatenate(self.cols),
                       np.concatenate(self.vals))
        a.grid_shape = grid_shape
        return a


def poisson2d(nx: int, ny: int | None = None, dtype=np.float64) -> SparseCSR:
    """5-point 2D Laplacian on an nx×ny grid (n = nx*ny), Dirichlet."""
    ny = nx if ny is None else ny
    idx = np.arange(nx * ny).reshape(nx, ny)
    st = _Stencil(dtype)
    st.add(idx, idx, 4.0)
    st.add(idx[1:, :], idx[:-1, :], -1.0)
    st.add(idx[:-1, :], idx[1:, :], -1.0)
    st.add(idx[:, 1:], idx[:, :-1], -1.0)
    st.add(idx[:, :-1], idx[:, 1:], -1.0)
    # grid_shape is consumed by geometric nested dissection
    return st.build(nx * ny, (nx, ny))


def poisson3d(nx: int, ny: int | None = None, nz: int | None = None,
              dtype=np.float64) -> SparseCSR:
    """7-point 3D Laplacian (the BASELINE.md config-4 matrix class)."""
    ny = nx if ny is None else ny
    nz = nx if nz is None else nz
    idx = np.arange(nx * ny * nz).reshape(nx, ny, nz)
    st = _Stencil(dtype)
    st.add(idx, idx, 6.0)
    for axis in range(3):
        lo = [slice(None)] * 3
        hi = [slice(None)] * 3
        lo[axis] = slice(1, None)
        hi[axis] = slice(None, -1)
        st.add(idx[tuple(lo)], idx[tuple(hi)], -1.0)
        st.add(idx[tuple(hi)], idx[tuple(lo)], -1.0)
    return st.build(nx * ny * nz, (nx, ny, nz))


def convection_diffusion_2d(nx: int, ny: int | None = None, beta: float = 10.0,
                            dtype=np.float64) -> SparseCSR:
    """Unsymmetric 2D convection-diffusion (upwind), exercises the
    unsymmetric-value path (pattern stays structurally symmetric)."""
    ny = nx if ny is None else ny
    h = 1.0 / (nx + 1)
    idx = np.arange(nx * ny).reshape(nx, ny)
    st = _Stencil(dtype)
    st.add(idx, idx, 4.0 + beta * h)
    st.add(idx[1:, :], idx[:-1, :], -1.0 - beta * h)   # upwind in x
    st.add(idx[:-1, :], idx[1:, :], -1.0)
    st.add(idx[:, 1:], idx[:, :-1], -1.0)
    st.add(idx[:, :-1], idx[:, 1:], -1.0)
    return st.build(nx * ny, (nx, ny))


def random_sparse(n: int, density: float = 0.01, seed: int = 0,
                  diag_dominant: bool = True, dtype=np.float64,
                  pattern_symmetric: bool = False) -> SparseCSR:
    """Random square sparse matrix with a guaranteed nonzero diagonal.

    With diag_dominant=True the matrix is safe to factor without pivoting,
    which isolates structure bugs from numerics in tests.  Complex dtypes
    give the z-path (reference z-twin files) coverage.
    """
    rng = np.random.default_rng(seed)
    nnz_target = max(n, int(density * n * n))
    rows = rng.integers(0, n, size=nnz_target)
    cols = rng.integers(0, n, size=nnz_target)
    if pattern_symmetric:
        rows, cols = np.concatenate([rows, cols]), np.concatenate([cols, rows])

    def rand(size):
        if np.issubdtype(np.dtype(dtype), np.complexfloating):
            return (rng.standard_normal(size) + 1j * rng.standard_normal(size)).astype(dtype)
        return rng.standard_normal(size).astype(dtype)

    vals = rand(len(rows))
    # ensure full diagonal
    rows = np.concatenate([rows, np.arange(n)])
    cols = np.concatenate([cols, np.arange(n)])
    dval = rand(n)
    if diag_dominant:
        dval = dval + np.sign(dval.real + (dval.real == 0)) * (4.0 * n * density + 4.0)
    vals = np.concatenate([vals, dval])
    return coo_to_csr(n, n, rows, cols, vals)


def helmholtz_2d(nx: int, k: float = 5.0, dtype=np.complex128) -> SparseCSR:
    """2-D Helmholtz operator −Δ − k² with a complex absorbing shift —
    an indefinite complex test class (the z-path stressor; the
    reference's complex fixtures cg20.cua/cmat are this family's role).
    dtype must be complex (the absorbing shift is imaginary)."""
    dtype = np.dtype(dtype)
    if not np.issubdtype(dtype, np.complexfloating):
        raise ValueError("helmholtz_2d needs a complex dtype "
                         f"(absorbing shift), got {dtype}")
    a = poisson2d(nx, dtype=np.float64)
    vals = a.data.astype(dtype)
    diag = a.indices == np.repeat(np.arange(a.n_rows), np.diff(a.indptr))
    h2 = 1.0 / (nx + 1) ** 2
    vals[diag] -= (k * k - 0.5j * k) * h2
    out = SparseCSR(a.n_rows, a.n_cols, a.indptr, a.indices, vals)
    out.grid_shape = a.grid_shape     # keep geometric-ND eligibility
    return out


def anisotropic_poisson_2d(nx: int, eps: float = 1e-3,
                           dtype=np.float64) -> SparseCSR:
    """Anisotropic diffusion −u_xx − eps·u_yy: strong directional
    coupling makes the ordering/fill behavior very different from the
    isotropic Laplacian (a standard stress class for fill-reducing
    orderings)."""
    idx = np.arange(nx * nx).reshape(nx, nx)
    st = _Stencil(dtype)
    st.add(idx, idx, 2.0 + 2.0 * eps)
    st.add(idx[:, 1:], idx[:, :-1], -1.0)     # u_xx along rows
    st.add(idx[:, :-1], idx[:, 1:], -1.0)
    st.add(idx[1:, :], idx[:-1, :], -eps)     # eps * u_yy across rows
    st.add(idx[:-1, :], idx[1:, :], -eps)
    return st.build(nx * nx, (nx, nx))


def hilbert(n: int, dtype=np.float64) -> SparseCSR:
    """Hilbert matrix H[i,j] = 1/(i+j+1) stored sparse — the classic
    ill-conditioned class (κ₂ ~ e^{3.5n}): at n=8 already ~1.5e10, past
    f32+IR's reach but inside f64's.  Escalation-ladder fodder."""
    i, j = np.meshgrid(np.arange(n), np.arange(n), indexing="ij")
    vals = (1.0 / (i + j + 1.0)).astype(dtype)
    return coo_to_csr(n, n, i.ravel(), j.ravel(), vals.ravel())


def rank_deficient_arrowhead(n: int, delta: float = 0.0, seed: int = 0,
                             dtype=np.float64) -> SparseCSR:
    """Arrowhead matrix whose last row is an EXACT linear combination of
    rows 1 and 2 (delta=0: exactly singular, rank n−1) or a near one
    (delta>0: smallest pivot ~delta, κ ~ ‖A‖/delta).  The dependence is a
    row relation, so no diagonal re-scaling repairs it — the honest
    near-singular stressor for the recovery ladder (equilibration-proof,
    unlike graded matrices)."""
    if n < 4:
        raise ValueError("rank_deficient_arrowhead needs n >= 4")
    rng = np.random.default_rng(seed)
    m = np.zeros((n, n), dtype=np.float64)
    np.fill_diagonal(m, 1.0 + rng.random(n))
    m[0, 1:] = 0.25 * (1.0 + rng.random(n - 1))   # arrow row
    m[1:, 0] = 0.25 * (1.0 + rng.random(n - 1))   # arrow column
    m[n - 1] = m[1] + m[2]                        # exact row dependence
    m[n - 1, n - 1] += delta                      # near-singular escape
    r, c = np.nonzero(m)
    return coo_to_csr(n, n, r, c, m[r, c].astype(dtype))


def zero_row_col(nx: int = 8, k: int | None = None, which: str = "row",
                 dtype=np.float64) -> SparseCSR:
    """2-D Poisson matrix with row (or column, or both) k numerically
    zeroed — exactly singular with a structurally present but zero-valued
    slice, the reference's dgsequ/pdgstrf info>0 test class."""
    a = poisson2d(nx, dtype=dtype)
    n = a.n_rows
    if k is None:
        k = n // 2
    data = a.data.copy()
    rows = np.repeat(np.arange(n), np.diff(a.indptr))
    if which in ("row", "both"):
        data[rows == k] = 0.0
    if which in ("col", "both"):
        data[a.indices == k] = 0.0
    out = SparseCSR(a.n_rows, a.n_cols, a.indptr, a.indices, data)
    out.grid_shape = a.grid_shape
    return out


def random_geometric_3d(n: int, k: int = 12, seed: int = 0,
                        dtype=np.float64) -> SparseCSR:
    """Irregular FEM-like matrix: n points in the unit cube, each coupled
    to its k nearest neighbors, SPD-shifted values.  The audikw_1-class
    surrogate (BASELINE config 5): no grid structure, irregular degree
    distribution — the stress class for general-graph nested dissection."""
    rng = np.random.default_rng(seed)
    pts = rng.random((n, 3))
    # k-NN via cell binning (no scipy dependency): ~O(n·k)
    ncell = max(1, int(round(n ** (1.0 / 3.0) / 2)))
    cell = np.minimum((pts * ncell).astype(np.int64), ncell - 1)
    rows_l, cols_l = [], []
    # search own + neighbor cells
    from collections import defaultdict
    buckets = defaultdict(list)
    for i in range(n):
        buckets[(int(cell[i, 0]), int(cell[i, 1]), int(cell[i, 2]))].append(i)
    for i in range(n):
        cx, cy, cz = (int(cell[i, 0]), int(cell[i, 1]), int(cell[i, 2]))
        cand = []
        for dx in (-1, 0, 1):
            for dy in (-1, 0, 1):
                for dz in (-1, 0, 1):
                    cand.extend(buckets.get((cx + dx, cy + dy, cz + dz),
                                            ()))
        cand = np.asarray([c for c in cand if c != i])
        if len(cand) == 0:
            continue
        d = np.sum((pts[cand] - pts[i]) ** 2, axis=1)
        near = cand[np.argsort(d)[:k]]
        rows_l.append(np.full(len(near), i))
        cols_l.append(near)
    rows = np.concatenate(rows_l)
    cols = np.concatenate(cols_l)
    # symmetrize pattern, SPD-ish values: off-diag -1, diag = degree + 1
    rows, cols = (np.concatenate([rows, cols, np.arange(n)]),
                  np.concatenate([cols, rows, np.arange(n)]))
    vals = np.full(len(rows), -1.0, dtype=dtype)
    vals[-n:] = 0.0
    a = coo_to_csr(n, n, rows, cols, vals)    # dedups, sums dups
    # clamp duplicate-summed off-diagonals back to -1, then set the
    # diagonal to (number of off-diagonal entries + 1): strictly
    # diagonally dominant, hence nonsingular
    deg = np.diff(a.indptr)
    diag_mask = a.indices == np.repeat(np.arange(n), deg)
    a.data[~diag_mask] = np.maximum(a.data[~diag_mask], -1.0)
    a.data[diag_mask] = deg.astype(a.data.dtype)  # deg includes the diag
    return a
