#!/usr/bin/env bash
# check_tsan_native.sh — ThreadSanitizer gate for the native shared
# segment (slu_host.cpp): the one component whose thread-safety Python-
# level analysis (slulint SLU108-SLU110) cannot see.  Builds the
# sanitize_main.cpp harness with -fsanitize=thread and runs it — the
# harness drives the threaded symbolic/ND paths, the shm tree
# collectives, AND the PR 8 failure-detector surface (heartbeat/pid
# atomics + the .ftx bulletin-board seqlock) under deliberate
# cross-thread contention.
#
# Gate contract (scripts/ci_gates.sh): exit 0 = pass, non-zero = ANY
# regression, diagnostics on stdout/stderr.  When the toolchain cannot
# build TSan binaries the gate reports SKIP explicitly and exits 0 —
# never silent-green: the SKIP line is the evidence the gate ran.
set -uo pipefail
cd "$(dirname "$0")/.."

NATIVE=superlu_dist_tpu/native
TMP="$(mktemp -d /tmp/slu_tsan.XXXXXX)"
trap 'rm -rf "$TMP"' EXIT

# toolchain probe: only a missing compiler/TSan runtime may SKIP — a
# compile failure in OUR sources must FAIL the gate, not disable it
printf 'int main(){return 0;}\n' > "$TMP/probe.cpp"
if ! g++ -fsanitize=thread "$TMP/probe.cpp" -o "$TMP/probe" 2>/dev/null \
    || ! "$TMP/probe"; then
  echo "check_tsan_native: SKIP (TSan toolchain unavailable)"
  exit 0
fi

echo "check_tsan_native: building harness (-fsanitize=thread)..."
build() {
  g++ -O1 -g -fsanitize=thread -std=c++17 -pthread \
    "$NATIVE/sanitize_main.cpp" "$NATIVE/slu_host.cpp" \
    -o "$TMP/sanitize_tsan" "$@" 2> "$TMP/build.err"
}
# glibc < 2.34 keeps shm_open/shm_unlink in librt (the same fallback
# native/__init__.py uses for the production build)
if ! build && ! build -lrt; then
  echo "check_tsan_native: FAIL (harness build error)" >&2
  cat "$TMP/build.err" >&2
  exit 1
fi

# halt_on_error keeps the report next to the failure; exitcode != 0 on
# any race so the gate contract holds even without output scraping
out="$TMP/run.log"
if ! TSAN_OPTIONS="halt_on_error=1 exitcode=66" \
    timeout -k 10 300 "$TMP/sanitize_tsan" > "$out" 2>&1; then
  echo "check_tsan_native: FAIL (harness exited non-zero)" >&2
  cat "$out" >&2
  exit 1
fi
if grep -q "WARNING: ThreadSanitizer" "$out"; then
  echo "check_tsan_native: FAIL (ThreadSanitizer report)" >&2
  cat "$out" >&2
  exit 1
fi
if ! grep -q "PASS" "$out"; then
  echo "check_tsan_native: FAIL (harness did not report PASS)" >&2
  cat "$out" >&2
  exit 1
fi
echo "check_tsan_native: OK ($(grep -c . "$out") line(s); collectives + heartbeat/bulletin/seqlock stress clean under TSan)"
