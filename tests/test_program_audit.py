"""slulint v4 program-audit suite (docs/ANALYSIS.md).

Per-rule fixture pairs over real traced programs (donated vs not,
big-const vs argument-passed, matched vs divergent collective sequences
under shard_map), the SLU113 dispatch-loop fixtures, executor-
construction audits on stream/mega/fused/device-solve, a provoked
ProgramAuditError with its flight-recorder postmortem, the incremental
scan cache (warm-hit equivalence, invalidation), and the SARIF
round-trip.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from superlu_dist_tpu.analysis import default_rules
from superlu_dist_tpu.analysis.program import (ProgramSpec, audit_spec,
                                               collective_sequence,
                                               trace_spec)
from superlu_dist_tpu.analysis import rules_program as rp
from superlu_dist_tpu.utils import programaudit
from superlu_dist_tpu.utils.errors import ProgramAuditError

pytestmark = pytest.mark.program

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(REPO, "tests", "fixtures", "slulint")

BIG = 1 << 30     # "never fires" threshold for the rule not under test


@pytest.fixture
def fresh_auditor(monkeypatch):
    """SLU_TPU_VERIFY_PROGRAMS=1 with a fresh auditor + clean census
    audit notes, restored afterwards."""
    from superlu_dist_tpu.obs.compilestats import COMPILE_STATS
    monkeypatch.setenv("SLU_TPU_VERIFY_PROGRAMS", "1")
    programaudit._reset()
    with COMPILE_STATS._lock:
        saved = dict(COMPILE_STATS._audits)
        COMPILE_STATS._audits = {}
    yield
    programaudit._reset()
    with COMPILE_STATS._lock:
        COMPILE_STATS._audits = saved


# --------------------------------------------------------------------------
# SLU111 donation/aliasing
# --------------------------------------------------------------------------

def test_slu111_undonated_dead_input_flagged():
    f = jax.jit(lambda x: x + 1.0)
    x = np.zeros(1024, np.float64)
    spec = trace_spec(f, (x,), label="undonated", site="test", dead=(0,))
    findings, stats = audit_spec(spec, donate_min_bytes=1024,
                                 const_max_bytes=BIG)
    assert [f_.rule for f_ in findings] == ["SLU111"]
    assert "not donated" in findings[0].message.lower()
    assert stats["donation_coverage_pct"] == 0.0


def test_slu111_donated_twin_clean():
    f = jax.jit(lambda x: x + 1.0, donate_argnums=(0,))
    x = np.zeros(1024, np.float64)
    spec = trace_spec(f, (x,), label="donated", site="test", dead=(0,))
    assert spec.donated == (0,)       # read off Traced.args_info
    findings, stats = audit_spec(spec, donate_min_bytes=1024,
                                 const_max_bytes=BIG)
    assert findings == []
    assert stats["donation_coverage_pct"] == 100.0


def test_slu111_small_and_live_inputs_exempt():
    f = jax.jit(lambda x, y: (x * 2.0, y * 3.0))
    x = np.zeros(4, np.float64)          # dead but tiny
    y = np.zeros(4096, np.float64)       # big but live (not declared dead)
    spec = trace_spec(f, (x, y), label="exempt", site="test", dead=(0,))
    findings, _ = audit_spec(spec, donate_min_bytes=1024,
                             const_max_bytes=BIG)
    assert findings == []


# --------------------------------------------------------------------------
# SLU112 baked constants
# --------------------------------------------------------------------------

def test_slu112_closure_captured_const_flagged():
    big = jnp.arange(4096.0)
    f = jax.jit(lambda x: x + big)       # the per-matrix-capture pattern
    spec = trace_spec(f, (np.zeros(4096),), label="baked", site="test")
    findings, stats = audit_spec(spec, donate_min_bytes=BIG,
                                 const_max_bytes=1024)
    assert [f_.rule for f_ in findings] == ["SLU112"]
    assert stats["baked_const_bytes"] >= big.nbytes


def test_slu112_argument_passed_twin_clean():
    f = jax.jit(lambda x, c: x + c)      # the make_factor_fn fix shape
    spec = trace_spec(f, (np.zeros(4096), np.zeros(4096)),
                      label="bucket-closed", site="test")
    findings, stats = audit_spec(spec, donate_min_bytes=BIG,
                                 const_max_bytes=1024)
    assert findings == []
    assert stats["baked_const_bytes"] == 0


# --------------------------------------------------------------------------
# SLU114 SPMD collective lockstep
# --------------------------------------------------------------------------

def _shard_mapped(body):
    from jax.experimental.shard_map import shard_map
    from jax.sharding import Mesh, PartitionSpec as P
    mesh = Mesh(np.array(jax.devices()[:1]), ("x",))
    return jax.jit(shard_map(body, mesh=mesh, in_specs=P("x"),
                             out_specs=P("x")))


def test_slu114_divergent_branch_collectives_flagged():
    def body(a):
        return jax.lax.cond(a.sum() > 0,
                            lambda v: jax.lax.psum(v, "x"),
                            lambda v: v * 1.0, a)

    spec = trace_spec(_shard_mapped(body), (np.ones(4),),
                      label="divergent", site="test", mesh_axes=("x",))
    findings, _ = audit_spec(spec, donate_min_bytes=BIG,
                             const_max_bytes=BIG)
    assert [f_.rule for f_ in findings] == ["SLU114"]
    assert "divergent" in findings[0].message.lower()


def test_slu114_matched_branch_collectives_clean():
    def body(a):
        return jax.lax.cond(a.sum() > 0,
                            lambda v: jax.lax.psum(v * 2.0, "x"),
                            lambda v: jax.lax.psum(v * 0.5, "x"), a)

    spec = trace_spec(_shard_mapped(body), (np.ones(4),),
                      label="matched", site="test", mesh_axes=("x",))
    findings, _ = audit_spec(spec, donate_min_bytes=BIG,
                             const_max_bytes=BIG)
    assert findings == []
    # the agreed branch sequence is inlined once into the program's
    # collective sequence
    assert collective_sequence(spec.jaxpr) == [("psum2", ("x",))]


def test_slu114_off_mesh_axis_flagged_on_stub():
    """Axis-consistency check over a duck-typed jaxpr stub (the rules
    are jax-free by design, so a stub is a legal program)."""

    class Prim:
        name = "psum"

    class Eqn:
        primitive = Prim()
        params = {"axes": ("ghost",)}

    class Jaxpr:
        eqns = [Eqn()]

    class Closed:
        jaxpr = Jaxpr()
        consts = ()
        in_avals = ()

    spec = ProgramSpec(label="stub", site="test", jaxpr=Closed(),
                       mesh_axes=("x",))
    findings = rp.audit_collective_lockstep(spec)
    assert [f_.rule for f_ in findings] == ["SLU114"]
    assert "ghost" in findings[0].message


def test_slu114_two_shard_subprocess():
    """A REAL 2-shard shard_map program through the runtime auditor:
    the matched program audits clean and computes the right psum; the
    divergent one raises ProgramAuditError at submit."""
    code = r"""
import os
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=2")
os.environ["SLU_TPU_VERIFY_PROGRAMS"] = "1"
import numpy as np
import jax, jax.numpy as jnp
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, PartitionSpec as P
from superlu_dist_tpu.utils.programaudit import maybe_audit
from superlu_dist_tpu.utils.errors import ProgramAuditError

mesh = Mesh(np.array(jax.devices()[:2]), ("x",))

def matched(a):
    return jax.lax.psum(a, "x")

def divergent(a):
    return jax.lax.cond(a.sum() > 0,
                        lambda v: jax.lax.psum(v, "x"),
                        lambda v: v * 1.0, a)

x = np.arange(8.0)
ok = jax.jit(shard_map(matched, mesh=mesh, in_specs=P("x"),
                       out_specs=P("x")))
maybe_audit("test", "matched", ok, (x,), mesh_axes=("x",))
out = np.asarray(ok(x))
assert np.allclose(out[:4] + out[4:], x[:4] + x[4:] + out[:4]), out

bad = jax.jit(shard_map(divergent, mesh=mesh, in_specs=P("x"),
                        out_specs=P("x")))
try:
    maybe_audit("test", "divergent", bad, (x,), mesh_axes=("x",))
except ProgramAuditError as e:
    assert "SLU114" in str(e)
    print("AUDIT_RAISED")
else:
    raise SystemExit("divergent 2-shard program audited clean")
"""
    r = subprocess.run([sys.executable, "-c", code],
                       env=dict(os.environ, JAX_PLATFORMS="cpu"),
                       cwd=REPO, capture_output=True, text=True,
                       timeout=240)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "AUDIT_RAISED" in r.stdout


# --------------------------------------------------------------------------
# SLU113 dispatch-loop host round-trips (source rule, committed fixtures)
# --------------------------------------------------------------------------

def _scan_fixture(name):
    from superlu_dist_tpu.analysis import analyze_source
    path = os.path.join(FIXTURES, name)
    with open(path) as fh:
        return analyze_source(fh.read(), path, default_rules())


def test_slu113_fixture_flagged():
    findings = _scan_fixture("host_roundtrip_loop.py")
    assert sorted({f.rule for f in findings}) == ["SLU113"]
    # float() coercion, np.asarray materialization, bool-coerced test
    assert len([f for f in findings if f.rule == "SLU113"]) == 3


def test_slu113_clean_fixture():
    assert _scan_fixture("device_loop_clean.py") == []


# --------------------------------------------------------------------------
# executor-construction audits (the runtime twin on the real programs)
# --------------------------------------------------------------------------

@pytest.fixture(scope="module")
def analyzed():
    from superlu_dist_tpu.models.gallery import poisson2d
    from superlu_dist_tpu.ordering.dispatch import get_perm_c
    from superlu_dist_tpu.sparse.formats import symmetrize_pattern
    from superlu_dist_tpu.symbolic.symbfact import symbolic_factorize
    from superlu_dist_tpu.utils.options import Options
    a = poisson2d(7)
    sym = symmetrize_pattern(a)
    sf = symbolic_factorize(sym, get_perm_c(Options(), a, sym))
    return sf, sym.data[sf.value_perm], a.norm_max()


def _factor(analyzed, executor):
    from superlu_dist_tpu.numeric.factor import numeric_factorize
    from superlu_dist_tpu.numeric.plan import build_plan
    sf, vals, anorm = analyzed
    plan = build_plan(sf)
    return plan, numeric_factorize(plan, vals, anorm, executor=executor)


def _audit_state():
    from superlu_dist_tpu.obs.compilestats import COMPILE_STATS
    aud = programaudit._AUDITOR
    return aud, COMPILE_STATS.audit_block()


@pytest.mark.parametrize("executor", ["fused", "mega"])
def test_executor_construction_audit(fresh_auditor, analyzed, executor):
    _factor(analyzed, executor)
    aud, blk = _audit_state()
    assert aud is not None and len(aud.audited) > 0
    assert blk["programs"] == len(aud.audited)
    assert blk["findings"] == 0
    assert blk["donation_coverage_pct"] == 100.0
    assert blk["baked_const_bytes"] == 0


def test_stream_and_solve_audit(fresh_auditor, analyzed, monkeypatch):
    # the stream executor audits on census-cold builds only — reset the
    # process-wide censused-key set so this plan's keys count as cold
    from superlu_dist_tpu.numeric import stream
    monkeypatch.setattr(stream, "_CENSUSED_KEYS", set())
    from superlu_dist_tpu.solve.device import DeviceSolver
    plan, fact = _factor(analyzed, "stream")
    aud, _ = _audit_state()
    n_factor = len(aud.audited)
    assert n_factor > 0, "stream executor submitted no programs"
    for fused in (True, False):
        ds = DeviceSolver(fact, fused=fused)
        ds.solve(np.ones((plan.n, 3)))
        ds.solve_trans(np.ones(plan.n))
    aud, blk = _audit_state()
    assert len(aud.audited) > n_factor, "device solve submitted nothing"
    assert blk["findings"] == 0
    assert blk["donation_coverage_pct"] == 100.0
    assert blk["baked_const_bytes"] == 0


def test_off_path_allocates_nothing(analyzed, monkeypatch):
    monkeypatch.delenv("SLU_TPU_VERIFY_PROGRAMS", raising=False)
    programaudit._reset()
    _factor(analyzed, "fused")
    assert programaudit._AUDITOR is None
    assert programaudit.get_auditor() is None


# --------------------------------------------------------------------------
# provoked ProgramAuditError + flight-recorder postmortem
# --------------------------------------------------------------------------

def test_program_audit_error_with_flightrec(tmp_path, monkeypatch):
    from superlu_dist_tpu.obs import flightrec
    dump = tmp_path / "fr-%p.json"
    monkeypatch.setenv("SLU_TPU_FLIGHTREC", str(dump))
    flightrec._reset()
    try:
        aud = programaudit.ProgramAuditor(donate_min_bytes=8,
                                          const_max_bytes=BIG)
        f = jax.jit(lambda x: x * 2.0)
        with pytest.raises(ProgramAuditError) as ei:
            aud.submit("test.site", "undonated", f,
                       (np.zeros(64, np.float64),), dead=(0,))
        err = ei.value
        assert err.rules == ["SLU111"]
        assert err.site == "test.site" and err.program == "undonated"
        assert err.flightrec_dump and os.path.exists(err.flightrec_dump)
        doc = json.load(open(err.flightrec_dump))
        assert doc["reason"] == "ProgramAuditError"
        # the failed program was NOT memoized as audited-clean
        assert ("test.site", "undonated") not in aud.audited
    finally:
        flightrec._reset()


def test_slu112_error_names_capturing_site():
    aud = programaudit.ProgramAuditor(donate_min_bytes=BIG,
                                      const_max_bytes=64)
    big = jnp.arange(512.0)
    f = jax.jit(lambda x: x + big)
    with pytest.raises(ProgramAuditError) as ei:
        aud.submit("stream._kernel", "captured", f, (np.zeros(512),))
    assert "capturing build site" in str(ei.value)
    assert "stream.py" in str(ei.value)


# --------------------------------------------------------------------------
# incremental scan cache
# --------------------------------------------------------------------------

def _run_cli(args, cwd=REPO):
    return subprocess.run(
        [sys.executable, "-m", "superlu_dist_tpu.analysis", *args],
        capture_output=True, text=True, cwd=cwd, timeout=120)


def test_cache_warm_hit_equivalence(tmp_path):
    """Two scans of the same dirty tree: identical findings, second one
    served from the cache."""
    src = tmp_path / "dirty.py"
    src.write_text(open(os.path.join(
        FIXTURES, "host_roundtrip_loop.py")).read())
    cache = str(tmp_path / "cache.json")
    r1 = _run_cli([str(src), "--no-baseline", "--json", "--cache", cache])
    r2 = _run_cli([str(src), "--no-baseline", "--json", "--cache", cache])
    d1, d2 = json.loads(r1.stdout), json.loads(r2.stdout)
    assert r1.returncode == r2.returncode == 1
    assert d1["cache"] == "miss" and d2["cache"] == "hit"
    assert d1["findings"] == d2["findings"] and d1["findings"]


def test_cache_invalidated_on_content_and_ruleset(tmp_path, monkeypatch):
    from superlu_dist_tpu.analysis import cache as sc
    rules = default_rules()
    sources = {"a.py": "x = 1\n"}
    path = str(tmp_path / "c.json")
    sc.store(path, sources, rules, [])
    assert sc.lookup(path, sources, rules) == []
    # content change -> miss
    assert sc.lookup(path, {"a.py": "x = 2\n"}, rules) is None
    # path-set change -> miss
    assert sc.lookup(path, {"a.py": "x = 1\n", "b.py": ""}, rules) is None
    # rule-set / engine version change -> miss
    monkeypatch.setattr(sc, "ANALYSIS_VERSION", "999")
    assert sc.lookup(path, sources, rules) is None


def test_no_cache_flag_writes_nothing(tmp_path):
    src = tmp_path / "clean.py"
    src.write_text("x = 1\n")
    cache = tmp_path / "cache.json"
    r = _run_cli([str(src), "--no-baseline", "--no-cache",
                  "--cache", str(cache)])
    assert r.returncode == 0, r.stdout + r.stderr
    assert not cache.exists()


# --------------------------------------------------------------------------
# SARIF
# --------------------------------------------------------------------------

def test_sarif_roundtrip():
    from superlu_dist_tpu.analysis.sarif import from_sarif, to_sarif
    findings = _scan_fixture("host_roundtrip_loop.py")
    assert findings
    doc = json.loads(json.dumps(to_sarif(findings, default_rules(),
                                         baselined=2)))
    assert doc["version"] == "2.1.0" and "$schema" in doc
    run = doc["runs"][0]
    assert run["tool"]["driver"]["name"] == "slulint"
    ids = {r["id"] for r in run["tool"]["driver"]["rules"]}
    assert "SLU113" in ids and "SLU101" in ids
    assert run["properties"]["baselined"] == 2
    back = from_sarif(doc)
    assert [(f.rule, f.path, f.line, f.col, f.message, f.hint)
            for f in back] == \
        [(f.rule, f.path, f.line, f.col, f.message, f.hint)
         for f in sorted(findings,
                         key=lambda f: (f.path, f.line, f.col, f.rule))]


def test_sarif_cli(tmp_path):
    src = tmp_path / "dirty.py"
    src.write_text(open(os.path.join(
        FIXTURES, "host_roundtrip_loop.py")).read())
    r = _run_cli([str(src), "--no-baseline", "--no-cache",
                  "--format", "sarif"])
    assert r.returncode == 1
    doc = json.loads(r.stdout)
    assert doc["runs"][0]["results"]
    assert all(res["ruleId"] == "SLU113"
               for res in doc["runs"][0]["results"])


# --------------------------------------------------------------------------
# registration plumbing
# --------------------------------------------------------------------------

def test_verify_programs_knob_registered():
    from superlu_dist_tpu.utils.options import KNOB_REGISTRY
    assert "SLU_TPU_VERIFY_PROGRAMS" in KNOB_REGISTRY
    assert KNOB_REGISTRY["SLU_TPU_VERIFY_PROGRAMS"].kind == "flag"


def test_slu113_in_default_rules():
    assert "SLU113" in {r.rule_id for r in default_rules()}
