"""SLU120 clean twin of unregistered_axis.py: every axis name comes
from the utils/meshreg.py registry ("snode"/"panel"), the in_specs
arity mirrors the wrapped signature, and the donated argument carries
an explicit P(...) layout."""
import jax
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, PartitionSpec as P


def panel_update(pool, piv):
    return pool + piv


def good_mesh(devs):
    return Mesh(devs, axis_names=("snode", "panel"))


def good_specs(mesh, pool, piv):
    fn = shard_map(panel_update, mesh=mesh,
                   in_specs=(P("snode"), P(None)),
                   out_specs=P("snode"))
    return fn(pool, piv)


def good_donation(mesh):
    return jax.jit(shard_map(panel_update, mesh=mesh,
                             in_specs=(P("snode"), P("panel")),
                             out_specs=P("snode")),
                   donate_argnums=(0,))
