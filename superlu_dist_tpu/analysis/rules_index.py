"""SLU103 — index-width discipline (flow-based since v2).

The GESP analog of the reference's ``int_t`` audit (superlu_defs.h:80-93
/ XSDK_INDEX_SIZE): pattern indices may be 32-bit (``sparse.formats.INT``
— bounded by n), but anything that ACCUMULATES — indptr/offset cumsums,
nnz totals, dimension products — overflows int32 exactly in the n≈10^6
regime the config4 targets run at (nnz(L) > 2^31 long before n does).

Flagged, in symbolic/ sparse/ numeric/ inside the project tree (and
everywhere outside it, e.g. test fixtures):

* ``np.cumsum(..., dtype=D)`` with a possibly-32-bit D (``np.int32``,
  ``"int32"``, ``np.intc``, or the env-selected ``INT`` alias) — a
  running prefix sum is the canonical nnz accumulator;
* arithmetic (`*`, `+`) where an operand is an EXPLICIT int32 cast
  (``np.int32(x)``, ``x.astype(np.int32)``) — products of dimension-like
  quantities must be promoted before they multiply, not after;
* any assignment to an accumulator-named target (indptr / *off* / *ptr*
  / nnz* / *cnt* / count / total) whose value the forward dataflow pass
  (analysis/dataflow.py) proves int32-typed.  v1 only matched a 32-bit
  constructor written *directly* on the assignment; v2 follows the taint
  through temporaries (``tmp = np.zeros(n, np.int32); indptr = tmp``)
  and through function returns (``indptr = _alloc(n)`` where ``_alloc``
  returns an int32 array — resolved through the package call graph).
  ``.astype(np.int64)`` clears the taint: promotion is the fix.
"""

from __future__ import annotations

import ast
import re

from superlu_dist_tpu.analysis.core import Rule, dotted_name
from superlu_dist_tpu.analysis.dataflow import (FnFlow, TAINT_I32, dtype_kw,
                                                is_explicit_i32_expr,
                                                is_i32_dtype)

_ACCUM_TARGET = re.compile(
    r"(^|_)(indptr|offs?|offsets?|ptr|rows_ptr|nnz\w*|cnt|counts?|total)"
    r"(_|$)|(_ptr|_offs?|_cnt)$")

_ARRAY_CTORS = frozenset({"zeros", "empty", "full", "arange", "array",
                          "asarray", "ones"})


class IndexWidthRule(Rule):
    rule_id = "SLU103"
    title = "index-width"
    hint = ("accumulators must be int64 regardless of the pattern-index "
            "width: use formats.counts_to_indptr / symbfact.supernode_nnz "
            "or an explicit dtype=np.int64, and promote operands BEFORE "
            "products (.astype(np.int64) * ...)")
    package_dirs = ("symbolic", "sparse", "numeric")

    def __init__(self, interprocedural: bool = True):
        self.interprocedural = interprocedural

    def check(self, tree, source, path, project=None):
        findings = []
        flagged = set()       # (line, col) dedup across lexical + flow

        def add(node, message):
            key = (getattr(node, "lineno", 0), getattr(node, "col_offset",
                                                       0))
            if key in flagged:
                return
            flagged.add(key)
            findings.append(self.finding(path, node, message))

        for node in ast.walk(tree):
            if isinstance(node, ast.Call):
                self._check_call(node, add)
            elif isinstance(node, ast.Assign) \
                    and not (self.interprocedural and project is not None):
                self._check_assign(node, add)
            elif isinstance(node, ast.BinOp) \
                    and isinstance(node.op, (ast.Mult, ast.Add)):
                for side in (node.left, node.right):
                    if is_explicit_i32_expr(side):
                        add(node,
                            "int32-cast operand in arithmetic — the "
                            "product/sum wraps at 2^31 before any later "
                            "promotion can save it")
                        break

        if self.interprocedural and project is not None:
            self._check_flow(tree, path, project, add)
        return findings

    # ---- v2: the dataflow pass ------------------------------------------
    def _check_flow(self, tree, path, project, add):
        """Run the forward pass over the module body and every function
        body; flag accumulator-named targets receiving i32-tainted
        values (direct ctors, temporaries, and resolved returns)."""
        scopes = [FnFlow.for_module(project, path, tree)]
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                scopes.append(FnFlow(
                    node.body, path,
                    lambda c: project.call_target(path, c),
                    project.summaries))
        for flow in scopes:
            flow.run()
            for names, value_node, taints in flow.assigns.values():
                accum = [n for n in names if _ACCUM_TARGET.search(n)]
                if not accum or TAINT_I32 not in taints:
                    continue
                add(value_node,
                    f"accumulator `{', '.join(accum)}` receives an "
                    f"int32-typed value ({taints[TAINT_I32]}) — "
                    "offset/nnz accumulators must be int64")

    # ---- lexical checks (v1, still the base tier) -----------------------
    def _check_call(self, node, add):
        name = dotted_name(node.func)
        if name.endswith("cumsum"):
            dt = dtype_kw(node)
            if dt is not None and is_i32_dtype(dt):
                add(node,
                    f"cumsum with 32-bit dtype `{dotted_name(dt) or 'int32'}`"
                    " — a prefix-sum accumulator overflows at nnz > 2^31")

    def _check_assign(self, node, add):
        targets = [t.id for t in node.targets if isinstance(t, ast.Name)]
        if not any(_ACCUM_TARGET.search(t) for t in targets):
            return
        val = node.value
        if not isinstance(val, ast.Call):
            return
        dt = None
        fn = val.func
        if isinstance(fn, ast.Attribute) and fn.attr in _ARRAY_CTORS:
            dt = dtype_kw(val)
            if dt is None and len(val.args) >= 2 \
                    and fn.attr in ("zeros", "empty", "full", "arange",
                                    "array", "asarray", "ones"):
                dt = val.args[-1] if is_i32_dtype(val.args[-1]) else None
        elif isinstance(fn, ast.Attribute) and fn.attr == "astype" \
                and val.args:
            dt = val.args[0]
        if dt is not None and is_i32_dtype(dt):
            add(node.value,
                f"accumulator `{', '.join(targets)}` constructed with a "
                "32-bit dtype — offset/nnz accumulators must be int64")
