"""Crash-consistent persistence of solver state.

The reference solver's value proposition is "factor once, solve many"
(PAPER.md GESP pipeline) — but a factorization held only in process
memory dies with the process.  This package makes the two expensive
artifacts durable:

* :mod:`superlu_dist_tpu.persist.serial` — versioned, integrity-checked
  serialization of a full :class:`LUFactorization` handle (symbolic
  fact, :class:`FactorPlan` schedule, transforms, numeric L/U factors),
  so a warmed serving process can ``load_lu`` and go straight to solve;
* :mod:`superlu_dist_tpu.persist.checkpoint` — mid-factorization
  checkpoints of the completed-group frontier, written every
  ``SLU_TPU_CKPT_EVERY`` groups and on breakdown/SIGTERM/deadline, from
  which ``gssvx(resume_from=...)`` restarts instead of refactoring from
  scratch.

Both use the same bundle format: a directory of ``.npy`` array files
plus a ``MANIFEST.json`` carrying a format version and a per-array
sha256 digest, every file written atomically (tmp + rename, manifest
last) so a crash mid-write always leaves the previous consistent state.
Format rules and the resume semantics are documented in
docs/RELIABILITY.md.
"""

from superlu_dist_tpu.persist.serial import (          # noqa: F401
    FORMAT_VERSION, save_lu, load_lu, write_bundle, read_bundle,
    plan_fingerprint, values_digest, pattern_digest, lu_meta)
from superlu_dist_tpu.persist.checkpoint import (      # noqa: F401
    FactorCheckpointer, ResumeState, load_checkpoint, flush_active,
    last_checkpoint)
