"""ctypes seam to the native host-analysis library (slu_host.cpp).

The reference's host analysis is C (SRC/etree.c, symbfact.c, mc64ad_dist.c,
get_perm_c.c); ours is C++ compiled on first use with the toolchain baked
into the image.  Python implementations remain the specification and the
fallback: every entry point here degrades gracefully when the compiler is
unavailable, and the test suite cross-checks native vs Python output.

Set SLU_TPU_NO_NATIVE=1 to force the Python fallbacks.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

from superlu_dist_tpu.utils.lockwatch import make_lock

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "slu_host.cpp")
_LIB = os.path.join(_HERE, "_slu_host.so")

_lock = make_lock("native._lock")
_lib = None
_tried = False

_I64 = ctypes.POINTER(ctypes.c_int64)
_F64 = ctypes.POINTER(ctypes.c_double)


def _build(force: bool = False) -> str | None:
    """Compile the shared library if missing or stale; return path or None."""
    try:
        if (not force and os.path.exists(_LIB)
                and os.path.getmtime(_LIB) >= os.path.getmtime(_SRC)):
            return _LIB
        # per-process tmp name: concurrent first-use builds (pytest workers,
        # bench + tests) must not interleave writes; os.replace is atomic
        # (-lrt: shm_open lives in librt on glibc < 2.34; a no-op stub on
        # newer glibc, so linking it unconditionally is safe)
        tmp = f"{_LIB}.{os.getpid()}.tmp"
        subprocess.run(
            ["g++", "-O3", "-std=c++17", "-shared", "-fPIC", "-pthread",
             "-o", tmp, _SRC, "-lrt"],
            check=True, capture_output=True, timeout=300)
        os.replace(tmp, _LIB)
        return _LIB
    except Exception:
        return None


def _load():
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        from superlu_dist_tpu.utils.options import env_flag
        if env_flag("SLU_TPU_NO_NATIVE"):
            return None
        path = _build()
        if path is None:
            return None
        try:
            try:
                lib = ctypes.CDLL(path)
            except OSError:
                # a stale .so built for a different libc (e.g. shm_open
                # moved between librt and libc) loads nowhere — rebuild
                # against THIS toolchain and retry once
                path = _build(force=True)
                if path is None:
                    return None
                lib = ctypes.CDLL(path)
            lib.slu_etree.argtypes = [ctypes.c_int64, _I64, _I64, _I64]
            lib.slu_postorder.argtypes = [ctypes.c_int64, _I64, _I64]
            # (slu_symbolic — the serial alias — stays exported for the C
            # ABI but Python always calls the _mt entry, which dispatches
            # serial at nthreads=1)
            lib.slu_symbolic_mt.restype = ctypes.c_int64
            lib.slu_symbolic_mt.argtypes = [
                ctypes.c_int64, _I64, _I64, _I64, ctypes.c_int64,
                ctypes.c_int64, ctypes.c_int64, _I64, _I64, _I64, _I64,
                _I64, ctypes.POINTER(_I64)]
            lib.slu_free_i64.argtypes = [_I64]
            lib.slu_amalgamate.restype = ctypes.c_int64
            lib.slu_amalgamate.argtypes = [
                ctypes.c_int64, ctypes.c_int64, _I64, _I64, _I64,
                ctypes.c_double, ctypes.c_int64, ctypes.c_int64,
                ctypes.c_double, _I64, _I64, _I64, _I64, _I64,
                ctypes.POINTER(_I64)]
            lib.slu_mc64.restype = ctypes.c_int
            lib.slu_mc64.argtypes = [ctypes.c_int64, _I64, _I64, _F64,
                                     _I64, _F64, _F64]
            lib.slu_mlnd.argtypes = [ctypes.c_int64, _I64, _I64,
                                     ctypes.c_int64, ctypes.c_uint64, _I64]
            lib.slu_mlnd_mt.argtypes = [ctypes.c_int64, _I64, _I64,
                                        ctypes.c_int64, ctypes.c_uint64,
                                        ctypes.c_int64, _I64]
            lib.slu_positions.argtypes = [ctypes.c_int64, _I64, _I64, _I64,
                                          _I64, _I64, _I64, _I64, _I64]
            lib.slu_awpm.restype = ctypes.c_int
            lib.slu_awpm.argtypes = [ctypes.c_int64, _I64, _I64, _F64, _I64]
            lib.slu_mmd.argtypes = [ctypes.c_int64, _I64, _I64, _I64]
            lib.slu_colamd.argtypes = [ctypes.c_int64, ctypes.c_int64,
                                       _I64, _I64, _I64]
            lib.slu_tree_attach.restype = ctypes.c_void_p
            lib.slu_tree_attach.argtypes = [
                ctypes.c_char_p, ctypes.c_int64, ctypes.c_int64,
                ctypes.c_int64, ctypes.c_int64]
            lib.slu_tree_detach.argtypes = [ctypes.c_void_p,
                                            ctypes.c_char_p, ctypes.c_int64]
            lib.slu_tree_bcast.argtypes = [ctypes.c_void_p, ctypes.c_int64,
                                           _F64, ctypes.c_int64]
            lib.slu_tree_reduce_sum.argtypes = [ctypes.c_void_p,
                                                ctypes.c_int64, _F64,
                                                ctypes.c_int64]
            # bounded-wait collective legs + failure-detector surface
            # (ISSUE 8): timed variants return 0 ok / 1+rank on timeout;
            # pid + heartbeat slots feed the Python-side liveness poll;
            # post/peek are the wait-free ".ftx" agreement board
            lib.slu_tree_bcast_tw.restype = ctypes.c_int64
            lib.slu_tree_bcast_tw.argtypes = [
                ctypes.c_void_p, ctypes.c_int64, _F64, ctypes.c_int64,
                ctypes.c_double]
            lib.slu_tree_reduce_sum_tw.restype = ctypes.c_int64
            lib.slu_tree_reduce_sum_tw.argtypes = [
                ctypes.c_void_p, ctypes.c_int64, _F64, ctypes.c_int64,
                ctypes.c_double]
            lib.slu_tree_set_pid.argtypes = [ctypes.c_void_p,
                                             ctypes.c_int64]
            lib.slu_tree_get_pid.restype = ctypes.c_int64
            lib.slu_tree_get_pid.argtypes = [ctypes.c_void_p,
                                             ctypes.c_int64]
            lib.slu_tree_heartbeat.argtypes = [ctypes.c_void_p]
            lib.slu_tree_get_heartbeat.restype = ctypes.c_int64
            lib.slu_tree_get_heartbeat.argtypes = [ctypes.c_void_p,
                                                   ctypes.c_int64]
            lib.slu_tree_post.restype = ctypes.c_int64
            lib.slu_tree_post.argtypes = [ctypes.c_void_p, _F64,
                                          ctypes.c_int64]
            lib.slu_tree_peek.restype = ctypes.c_int64
            lib.slu_tree_peek.argtypes = [ctypes.c_void_p, ctypes.c_int64,
                                          _F64, ctypes.c_int64]
            lib.slu_ata_pattern.restype = ctypes.c_int64
            lib.slu_ata_pattern.argtypes = [
                ctypes.c_int64, ctypes.c_int64, _I64, _I64, ctypes.c_int64,
                _I64, ctypes.POINTER(_I64)]
            _lib = lib
        except Exception:
            _lib = None
        return _lib


def available() -> bool:
    return _load() is not None


def _as_i64(a: np.ndarray) -> np.ndarray:
    return np.ascontiguousarray(a, dtype=np.int64)


def _ptr_i64(a: np.ndarray):
    return a.ctypes.data_as(_I64)


def _ptr_f64(a: np.ndarray):
    return a.ctypes.data_as(_F64)


def etree(n: int, indptr: np.ndarray, indices: np.ndarray):
    """Native etree; returns parent array or None if unavailable."""
    lib = _load()
    if lib is None:
        return None
    indptr = _as_i64(indptr)
    indices = _as_i64(indices)
    parent = np.empty(n, dtype=np.int64)
    lib.slu_etree(n, _ptr_i64(indptr), _ptr_i64(indices), _ptr_i64(parent))
    return parent


def postorder(parent: np.ndarray):
    lib = _load()
    if lib is None:
        return None
    parent = _as_i64(parent)
    n = len(parent)
    post = np.empty(n, dtype=np.int64)
    lib.slu_postorder(n, _ptr_i64(parent), _ptr_i64(post))
    return post


def symbolic(n: int, indptr, indices, parent, relax: int, max_supernode: int,
             nthreads: int = 1):
    """Native supernodal symbolic (nthreads > 1 => the symbfact_dist
    analog, subtree-to-worker threads).  Returns (sn_start, col_to_sn,
    sn_parent, sn_level, rows_ptr, rows_data) or None."""
    lib = _load()
    if lib is None:
        return None
    indptr = _as_i64(indptr)
    indices = _as_i64(indices)
    parent = _as_i64(parent)
    sn_start = np.empty(n + 1, dtype=np.int64)
    col_to_sn = np.empty(n, dtype=np.int64)
    sn_parent = np.empty(n, dtype=np.int64)
    sn_level = np.empty(n, dtype=np.int64)
    rows_ptr = np.empty(n + 1, dtype=np.int64)
    rows_data_p = _I64()
    # slu_symbolic_mt with nthreads=1 IS the serial path (symbolic_impl
    # dispatches internally), so one call site serves both
    ns = lib.slu_symbolic_mt(n, _ptr_i64(indptr), _ptr_i64(indices),
                             _ptr_i64(parent), relax, max_supernode,
                             max(nthreads, 1), _ptr_i64(sn_start),
                             _ptr_i64(col_to_sn), _ptr_i64(sn_parent),
                             _ptr_i64(sn_level), _ptr_i64(rows_ptr),
                             ctypes.byref(rows_data_p))
    if ns < 0:
        return None
    total = int(rows_ptr[ns])
    rows_data = np.ctypeslib.as_array(rows_data_p, shape=(max(total, 1),))[
        :total].copy()
    lib.slu_free_i64(rows_data_p)
    return (sn_start[:ns + 1].copy(), col_to_sn, sn_parent[:ns].copy(),
            sn_level[:ns].copy(), rows_ptr[:ns + 1].copy(), rows_data)


def amalgamate(n: int, sn_start, rows_ptr, rows_data, tol: float,
               max_width: int, narrow: int, hard_tol: float):
    """Native fill-tolerant supernode amalgamation (twin of
    symbfact.amalgamate_supernodes).  Takes/returns structures in the
    `symbolic` output protocol; returns (sn_start, col_to_sn, sn_parent,
    sn_level, rows_ptr, rows_data) or None."""
    lib = _load()
    if lib is None:
        return None
    sn_start = _as_i64(sn_start)
    rows_ptr = _as_i64(rows_ptr)
    rows_data = _as_i64(rows_data)
    ns = len(sn_start) - 1
    o_sn_start = np.empty(n + 1, dtype=np.int64)
    o_col_to_sn = np.empty(n, dtype=np.int64)
    o_sn_parent = np.empty(max(ns, 1), dtype=np.int64)
    o_sn_level = np.empty(max(ns, 1), dtype=np.int64)
    o_rows_ptr = np.empty(n + 1, dtype=np.int64)
    o_rows_data_p = _I64()
    k = lib.slu_amalgamate(n, ns, _ptr_i64(sn_start), _ptr_i64(rows_ptr),
                           _ptr_i64(rows_data), float(tol), int(max_width),
                           int(narrow), float(hard_tol),
                           _ptr_i64(o_sn_start), _ptr_i64(o_col_to_sn),
                           _ptr_i64(o_sn_parent), _ptr_i64(o_sn_level),
                           _ptr_i64(o_rows_ptr),
                           ctypes.byref(o_rows_data_p))
    if k < 0:
        return None
    total = int(o_rows_ptr[k])
    out_rows = np.ctypeslib.as_array(o_rows_data_p,
                                     shape=(max(total, 1),))[:total].copy()
    lib.slu_free_i64(o_rows_data_p)
    return (o_sn_start[:k + 1].copy(), o_col_to_sn,
            o_sn_parent[:k].copy(), o_sn_level[:k].copy(),
            o_rows_ptr[:k + 1].copy(), out_rows)


def mc64(n: int, indptr, indices, absval):
    """Native MC64 job=5.  Returns (col_match, u, v) or None if unavailable.
    Raises ValueError on structural singularity."""
    lib = _load()
    if lib is None:
        return None
    indptr = _as_i64(indptr)
    indices = _as_i64(indices)
    absval = np.ascontiguousarray(absval, dtype=np.float64)
    col_match = np.empty(n, dtype=np.int64)
    u = np.empty(n, dtype=np.float64)
    v = np.empty(n, dtype=np.float64)
    rc = lib.slu_mc64(n, _ptr_i64(indptr), _ptr_i64(indices),
                      _ptr_f64(absval), _ptr_i64(col_match), _ptr_f64(u),
                      _ptr_f64(v))
    if rc != 0:
        raise ValueError("structurally singular")
    return col_match, u, v


def positions(s_arr, x_arr, first, last, snW, rows_ptr, rows_data):
    """Batched front-position queries (plan building); None if unavailable."""
    lib = _load()
    if lib is None:
        return None
    s_arr = _as_i64(s_arr)
    x_arr = _as_i64(x_arr)
    first = _as_i64(first)
    last = _as_i64(last)
    snW = _as_i64(snW)
    rows_ptr = _as_i64(rows_ptr)
    rows_data = _as_i64(rows_data)
    pos = np.empty(len(s_arr), dtype=np.int64)
    lib.slu_positions(len(s_arr), _ptr_i64(s_arr), _ptr_i64(x_arr),
                      _ptr_i64(first), _ptr_i64(last), _ptr_i64(snW),
                      _ptr_i64(rows_ptr), _ptr_i64(rows_data), _ptr_i64(pos))
    return pos


def mmd(n: int, indptr, indices):
    """Exact-external-degree minimum-degree ordering; None if unavailable."""
    lib = _load()
    if lib is None:
        return None
    indptr = _as_i64(indptr)
    indices = _as_i64(indices)
    order = np.empty(n, dtype=np.int64)
    lib.slu_mmd(n, _ptr_i64(indptr), _ptr_i64(indices), _ptr_i64(order))
    return order


def awpm(n: int, indptr, indices, absval):
    """Approximate-weight perfect matching (HWPM analog); None if
    unavailable.  Raises ValueError on structural singularity."""
    lib = _load()
    if lib is None:
        return None
    indptr = _as_i64(indptr)
    indices = _as_i64(indices)
    absval = np.ascontiguousarray(absval, dtype=np.float64)
    col_match = np.empty(n, dtype=np.int64)
    rc = lib.slu_awpm(n, _ptr_i64(indptr), _ptr_i64(indices),
                      _ptr_f64(absval), _ptr_i64(col_match))
    if rc != 0:
        raise ValueError("structurally singular")
    return col_match


def colamd(n_rows: int, n_cols: int, indptr, indices):
    """COLAMD-class approximate column MD ordering; None if unavailable."""
    lib = _load()
    if lib is None:
        return None
    indptr = _as_i64(indptr)
    indices = _as_i64(indices)
    order = np.empty(n_cols, dtype=np.int64)
    lib.slu_colamd(n_rows, n_cols, _ptr_i64(indptr), _ptr_i64(indices),
                   _ptr_i64(order))
    return order


def ata_pattern(n_rows: int, n_cols: int, indptr, indices,
                dense_row: int = 0):
    """Symmetric adjacency of AᵀA (getata_dist analog); None if
    unavailable.  dense_row > 0 drops rows longer than that."""
    lib = _load()
    if lib is None:
        return None
    indptr = _as_i64(indptr)
    indices = _as_i64(indices)
    out_ptr = np.empty(n_cols + 1, dtype=np.int64)
    buf = _I64()
    total = int(lib.slu_ata_pattern(n_rows, n_cols, _ptr_i64(indptr),
                                    _ptr_i64(indices), dense_row,
                                    _ptr_i64(out_ptr), ctypes.byref(buf)))
    try:
        out_idx = np.ctypeslib.as_array(buf, shape=(max(total, 1),))[
            :total].copy()
    finally:
        lib.slu_free_i64(buf)
    return out_ptr, out_idx


def mlnd(n: int, indptr, indices, leaf_size: int = 96, seed: int = 1,
         nthreads: int | None = None):
    """Native multilevel nested dissection; returns order or None.

    nthreads > 1 (or SLU_TPU_ND_THREADS) maps independent separator
    subtrees onto threads — the parallel-ordering capability analog of
    the reference's ParMETIS path (SRC/get_perm_c_parmetis.c:104,255:
    separator tree built by 2^q processes).  The result is deterministic
    for a given (seed, leaf_size) regardless of nthreads: every subtree
    derives its RNG stream from its tree path, not from thread timing.
    """
    lib = _load()
    if lib is None:
        return None
    if nthreads is None:
        from superlu_dist_tpu.utils.options import env_int
        nthreads = env_int("SLU_TPU_ND_THREADS")
    indptr = _as_i64(indptr)
    indices = _as_i64(indices)
    order = np.empty(n, dtype=np.int64)
    lib.slu_mlnd_mt(n, _ptr_i64(indptr), _ptr_i64(indices), leaf_size, seed,
                    max(int(nthreads), 1), _ptr_i64(order))
    return order
