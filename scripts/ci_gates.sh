#!/usr/bin/env bash
# ci_gates.sh — the ONE entry point for the repo's non-pytest CI gates.
#
# Consolidates (shared contract: each gate exits non-zero on ANY
# regression, produces its diagnostics on stdout/stderr, and runs under
# a hard per-gate timeout):
#
#   slulint         scripts/run_slulint.sh          static analysis
#                   (SLU101-SLU105 + SLU107-SLU110, interprocedural
#                   tier) over the package, scripts/, bench.py and
#                   examples/
#   nan-guards      scripts/check_nan_guards.sh     JAX_DEBUG_NANS smoke
#   trace-overhead  scripts/check_trace_overhead.py tracer off-path
#                   allocation + artifact well-formedness
#   verify-overhead scripts/check_verify_overhead.py  SLU106 lockstep
#                   verifier: disabled path allocates no verifier state,
#                   enabled path round-trips and counts checks; plus the
#                   SLU109 lock-order verifier (SLU_TPU_VERIFY_LOCKS):
#                   off path hands out plain locks and builds no watch,
#                   on path records the order graph
#   schedule-equiv  scripts/check_schedule_equiv.py   level vs dataflow
#                   dispatch schedules produce bitwise-identical L/U;
#                   dataflow never exceeds the level group count
#   perf-regress    scripts/check_perf_regress.py     micro-bench factor
#                   GFLOP/s vs the bench-history median (noise-tolerant,
#                   self-seeding on an empty history)
#   slo-gate        scripts/check_slo.py              serve-path p99
#                   latency per nrhs size (real SolveServer, always-on
#                   obs/slo accounter) vs the bench-history median —
#                   LOWER-is-better, noise-tolerant, self-seeding on an
#                   empty history
#   crash-resume    scripts/check_crash_resume.py     kill -9 a
#                   factorization mid-run, resume from the durable
#                   checkpoint frontier, assert bitwise-identical L/U
#                   vs an uninterrupted run
#   rank-failure    scripts/check_rank_failure.py     kill -9 a rank
#                   mid-factor: every survivor raises RankFailureError
#                   within 2x SLU_TPU_COMM_TIMEOUT_S (no watchdog
#                   exit-3), and ft=shrink resumes the checkpoint
#                   frontier with bitwise-identical L/U
#   solve-equiv     scripts/check_solve_equiv.py      device batched
#                   solve: fused vs streamed bitwise-identical, sweep
#                   schedules agree, device vs host solve within f64
#                   tightness, nrhs padding reported honestly
#   serve-robust    scripts/check_serve_robust.py     SolveServer
#                   reliability: a poisoned column in a 64-column
#                   backlog fails exactly its own ticket (survivors
#                   bitwise vs a clean run), and an overload storm
#                   against a bounded queue sheds with structured
#                   errors instead of hanging
#   compile-budget  scripts/compile_census.py --buckets  the closed
#                   bucket set stays O(1): the mega executor's
#                   compiled-program count must be CONSTANT across
#                   n = 4096/32768/110592 (the BENCH_r02 compile-wall
#                   gallery), every bucket program AOT-stageable
#   tsan-native     scripts/check_tsan_native.sh      -fsanitize=thread
#                   build of the native shared segment + a threaded
#                   heartbeat/bulletin/seqlock stress; SKIPs loudly
#                   (never silent-green) when the toolchain lacks TSan
#   program-audit   scripts/check_program_audit.py    slulint v4 IR
#                   rules over the REAL executors: every jitted program
#                   (fused/stream/mega factor + device solve sweeps)
#                   passes SLU111 donation, SLU112 baked-const and
#                   SLU114 collective-lockstep audits under
#                   SLU_TPU_VERIFY_PROGRAMS=1; donation coverage 100%,
#                   baked const bytes 0
#   precision-safety scripts/check_precision_safety.py  throughput
#                   ladder: the bf16 GEMM tier on an ill-conditioned
#                   gallery matrix passes the componentwise-BERR gate
#                   or escalates (never delivers a failing X, with and
#                   without iterative refinement), and the Pallas
#                   interpret-mode extend-add/assembly path is bitwise
#                   vs the .at[] lowering per executor
#   fleet-failover  scripts/check_fleet_failover.py   serving fleet:
#                   3 process replicas serving a mixed ≥8-matrix
#                   stream, kill -9 of one replica mid-stream loses
#                   zero accepted tickets with every delivered X
#                   bitwise vs an undisturbed run; a rolling deploy
#                   completes under traffic with zero dropped tickets
#                   and a poisoned bundle rolls back (preflight +
#                   per-replica canary)
#   precision-lint  scripts/check_precision_lint.py   slulint v5
#                   precision-flow rules: the whole tree is clean under
#                   SLU115 (implicit downcast), SLU116 (accumulation
#                   dtype), SLU117 (EFT purity) and SLU118 (tolerance
#                   hygiene); under SLU_TPU_VERIFY_DTYPES=1 every
#                   program the real executors submit (gate gallery,
#                   all three factor executors + device solve sweeps,
#                   plus a bf16-GEMM-tier run proving the sanctioned
#                   narrowing) passes the runtime dtype audit with zero
#                   findings and 100% census coverage
#   refactor-consistency scripts/check_refactor.py    crash-consistent
#                   same-pattern refactorization: refactor(handle,
#                   new_values) bitwise vs a SamePattern_SameRowPerm
#                   refresh with zero symbolic/fresh-compile seconds
#                   (fused/stream/mega); kill -9 MID-REFACTOR leaves
#                   the persisted state serving bitwise; a rolling
#                   fleet.refactor under live traffic drops zero
#                   tickets and a poisoned refactor rolls back every
#                   swapped replica
#   sharding-audit  scripts/check_sharding_audit.py   slulint v6
#                   sharding/memory rules: the whole tree is clean under
#                   SLU119 (implicit replication), SLU120 (mesh/spec
#                   hygiene vs utils/meshreg.py), SLU121 (static peak
#                   memory) and SLU122 (dispatch-loop cross-mesh
#                   transfers); under SLU_TPU_VERIFY_SHARDING=1 plus a
#                   generous SLU_TPU_MEM_BUDGET_BYTES every program the
#                   real executors submit (gate gallery, all three
#                   factor executors + device solve sweeps) audits
#                   clean with 100% census coverage and the mega bucket
#                   estimates within 2x of XLA memory_analysis; a tiny
#                   budget proves MemoryBudgetError fires BEFORE any
#                   program runs, naming the bucket rung
#   spmd-equiv      scripts/check_spmd_equiv.py       shard_map SPMD
#                   tier on the 8-virtual-device mesh: ONE compiled
#                   factor program regardless of n, L/U and solve/
#                   transpose-solve bitwise vs the fused+stream
#                   lockstep executors and the lockstep DeviceSolver,
#                   the demoted TreeComm tier still bitwise vs the
#                   gssvx driver (the A/B reference chain), and every
#                   mesh program audits clean (0 sharding findings,
#                   100% donation coverage) under the runtime auditors
#
# Scan sharing: the slulint gate (and any other in-tree slulint
# invocation) reads/writes the content-hash scan cache
# (.slulint-cache.json, analysis/cache.py), so the tree is parsed and
# dataflow-analyzed ONCE per content state — repeat gate invocations on
# an unchanged tree are sub-second cache hits.
#
# Usage:  scripts/ci_gates.sh [gate ...]      (default: all gates)
#         CI_GATE_TIMEOUT_S=900 scripts/ci_gates.sh
#
# Every gate runs even after an earlier one fails (CI wants the full
# picture); the exit code is the number of failed gates.  Wired for CI
# directly after the tier-1 pytest command (ROADMAP.md):
#
#   python -m pytest tests/ -q -m 'not slow' && scripts/ci_gates.sh
set -uo pipefail
cd "$(dirname "$0")/.."

TIMEOUT="${CI_GATE_TIMEOUT_S:-600}"

declare -A GATES=(
  [slulint]="scripts/run_slulint.sh"
  [nan-guards]="scripts/check_nan_guards.sh"
  [trace-overhead]="python scripts/check_trace_overhead.py"
  [verify-overhead]="python scripts/check_verify_overhead.py"
  [schedule-equiv]="python scripts/check_schedule_equiv.py"
  [solve-equiv]="python scripts/check_solve_equiv.py"
  [serve-robust]="python scripts/check_serve_robust.py"
  [perf-regress]="python scripts/check_perf_regress.py"
  [slo-gate]="python scripts/check_slo.py"
  [crash-resume]="python scripts/check_crash_resume.py"
  [rank-failure]="python scripts/check_rank_failure.py"
  [compile-budget]="python scripts/compile_census.py --buckets 16 32 48 --stage"
  [tsan-native]="scripts/check_tsan_native.sh"
  [program-audit]="python scripts/check_program_audit.py"
  [fleet-failover]="python scripts/check_fleet_failover.py"
  [precision-safety]="python scripts/check_precision_safety.py"
  [precision-lint]="python scripts/check_precision_lint.py"
  [refactor-consistency]="python scripts/check_refactor.py"
  [sharding-audit]="python scripts/check_sharding_audit.py"
  [spmd-equiv]="python scripts/check_spmd_equiv.py"
)
ORDER=(slulint precision-lint sharding-audit program-audit verify-overhead
       schedule-equiv solve-equiv spmd-equiv precision-safety serve-robust
       fleet-failover refactor-consistency crash-resume rank-failure
       compile-budget tsan-native trace-overhead nan-guards
       perf-regress slo-gate)

requested=("$@")
if [ ${#requested[@]} -eq 0 ]; then
  requested=("${ORDER[@]}")
fi

failed=0
for gate in "${requested[@]}"; do
  cmd="${GATES[$gate]:-}"
  if [ -z "$cmd" ]; then
    echo "ci_gates: unknown gate '$gate' (known: ${ORDER[*]})" >&2
    failed=$((failed + 1))
    continue
  fi
  echo "=== ci_gates: $gate (timeout ${TIMEOUT}s) ==="
  if timeout -k 10 "$TIMEOUT" $cmd; then
    echo "=== ci_gates: $gate OK ==="
  else
    rc=$?
    echo "=== ci_gates: $gate FAILED (rc=$rc) ===" >&2
    failed=$((failed + 1))
  fi
done

if [ "$failed" -ne 0 ]; then
  echo "ci_gates: $failed gate(s) failed" >&2
fi
exit "$failed"
