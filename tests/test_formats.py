import numpy as np
import pytest

from superlu_dist_tpu.sparse.formats import (
    SparseCSR, coo_to_csr, coo_to_csc, symmetrize_pattern, invert_perm,
)


def _rand_coo(n, m, nnz, seed=0, dtype=np.float64):
    rng = np.random.default_rng(seed)
    r = rng.integers(0, n, nnz)
    c = rng.integers(0, m, nnz)
    if np.issubdtype(dtype, np.complexfloating):
        v = (rng.standard_normal(nnz) + 1j * rng.standard_normal(nnz)).astype(dtype)
    else:
        v = rng.standard_normal(nnz).astype(dtype)
    return r, c, v


@pytest.mark.parametrize("dtype", [np.float64, np.complex128])
def test_coo_roundtrip_and_dense(dtype):
    n, m = 13, 17
    r, c, v = _rand_coo(n, m, 120, dtype=dtype)
    dense = np.zeros((n, m), dtype=dtype)
    np.add.at(dense, (r, c), v)
    a = coo_to_csr(n, m, r, c, v)
    np.testing.assert_allclose(a.to_dense(), dense, atol=1e-14)
    csc = coo_to_csc(n, m, r, c, v)
    np.testing.assert_allclose(csc.to_dense(), dense, atol=1e-14)
    np.testing.assert_allclose(a.tocsc().to_dense(), dense, atol=1e-14)
    np.testing.assert_allclose(csc.tocsr().to_dense(), dense, atol=1e-14)
    # rows sorted within columns and vice versa
    for j in range(m):
        col = csc.indices[csc.indptr[j]:csc.indptr[j + 1]]
        assert np.all(np.diff(col) > 0)


def test_matvec_and_norms():
    n, m = 11, 9
    r, c, v = _rand_coo(n, m, 60, seed=1)
    a = coo_to_csr(n, m, r, c, v)
    d = a.to_dense()
    x = np.random.default_rng(2).standard_normal(m)
    np.testing.assert_allclose(a.matvec(x), d @ x, atol=1e-12)
    X = np.random.default_rng(3).standard_normal((m, 4))
    np.testing.assert_allclose(a.matvec(X), d @ X, atol=1e-12)
    np.testing.assert_allclose(a.abs_matvec(np.abs(x[:n - 2]) * 0 + 1.0
                                            if False else np.ones(m)),
                               np.abs(d) @ np.ones(m), atol=1e-12)
    assert a.norm_inf() == pytest.approx(np.abs(d).sum(axis=1).max())
    assert a.norm_1() == pytest.approx(np.abs(d).sum(axis=0).max())


def test_permute_and_scale():
    n = 10
    r, c, v = _rand_coo(n, n, 40, seed=4)
    a = coo_to_csr(n, n, r, c, v)
    d = a.to_dense()
    rng = np.random.default_rng(5)
    pr = rng.permutation(n)
    pc = rng.permutation(n)
    np.testing.assert_allclose(a.permute(pr, pc).to_dense(), d[pr][:, pc],
                               atol=1e-14)
    rs = rng.uniform(0.5, 2.0, n)
    cs = rng.uniform(0.5, 2.0, n)
    np.testing.assert_allclose(a.row_scale(rs).to_dense(), rs[:, None] * d,
                               atol=1e-14)
    np.testing.assert_allclose(a.col_scale(cs).to_dense(), d * cs[None, :],
                               atol=1e-14)
    p = rng.permutation(n)
    assert np.array_equal(invert_perm(p)[p], np.arange(n))


def test_symmetrize_pattern():
    n = 8
    r, c, v = _rand_coo(n, n, 20, seed=6)
    a = coo_to_csr(n, n, r, c, v)
    s = symmetrize_pattern(a)
    d = a.to_dense()
    np.testing.assert_allclose(s.to_dense(), d, atol=1e-14)  # values kept
    pat = (s.to_dense() != 0)
    # pattern contains both A and A^T patterns... explicit zeros are invisible
    # in to_dense, so check structure arrays directly:
    dense_pat = np.zeros((n, n), dtype=bool)
    rows = np.repeat(np.arange(n), np.diff(s.indptr))
    dense_pat[rows, s.indices] = True
    want = (d != 0) | (d.T != 0)
    assert np.array_equal(dense_pat, want)
    assert np.array_equal(dense_pat, dense_pat.T)
