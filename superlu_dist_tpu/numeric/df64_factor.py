"""Double-float (df64) numeric factorization — true ~2^-48 factors on
hardware without an f64 MXU.

This closes SURVEY.md §7 hard-part 1 for the systems the default
mixed-precision path cannot handle: with f32 factors, iterative
refinement converges only while κ(A)·2⁻²⁴ ≲ 1; beyond that the
correction solves stop contracting.  Factoring in df64 (hi, lo f32
pairs, ~48-bit significands — ops/df64.py) pushes the boundary to
κ(A)·2⁻⁴⁸, the same class as native f64, at ~20-30 f32 flops per MAC on
the VPU.

Design: the same level-batched multifrontal plan as the fast path (the
index maps are dtype-blind), with a df64 twin of the group step.  The
pivot-block elimination runs the scatter-free masked loop over the
pivot columns of the WHOLE front — each step is a full-front exact
rank-1 update, so after w steps the trailing block IS the Schur
complement (no separate triangular solves needed; this trades ~3x
flops for having exactly one df64 kernel).  Factored panels are pulled
to host and recombined into exact float64 arrays (hi + lo), so every
downstream consumer — host triangular solves, transpose solves,
refinement, GetDiagU — runs the standard f64 path unchanged.

Accuracy caveat (see ops/df64.py header): XLA:CPU's instruction fusion
breaks the error-free transforms; on the CPU backend run with
XLA_FLAGS=--xla_disable_hlo_passes=fusion,cpu-instruction-fusion (the
tests do, in a subprocess).  TPU/GPU pipelines honor the barriers.
"""

from __future__ import annotations

import functools

import numpy as np
import jax
import jax.numpy as jnp

from superlu_dist_tpu.numeric.factor import NumericFactorization
from superlu_dist_tpu.numeric.plan import FactorPlan
from superlu_dist_tpu.ops.df64 import (df64_add, df64_div, df64_from_f64,
                                       df64_mul, df64_neg, df64_sub)


def _fix_pivot_df64(piv, thresh):
    """GESP tiny-pivot replacement on the df64 pivot (magnitude test and
    replacement value act on the hi word — the reference's thresh
    semantics, pdgstrf2.c:218-232)."""
    ph, pl = piv
    ap = jnp.abs(ph)
    safe = jnp.where(ap == 0, jnp.ones_like(ph), ap)
    unit = jnp.where(ap == 0, jnp.ones_like(ph), ph / safe)
    tiny = ap < thresh
    return ((jnp.where(tiny, unit * thresh, ph),
             jnp.where(tiny, jnp.zeros_like(pl), pl)),
            tiny.astype(jnp.int32))


def df64_partial_front_factor(fh, fl, thresh, w):
    """Masked partial LU of one (m, m) df64 front over its first w pivot
    columns.  Full-front rank-1 updates: after the loop the leading w
    rows/cols hold packed L\\U, L21, U12 and the trailing block holds
    the Schur complement.  Returns ((fh, fl), tiny_flags (w,))."""
    m = fh.shape[0]
    idx = jnp.arange(m)

    def step(i, carry):
        (ah, al), flags = carry
        sel = idx == i
        e = sel.astype(ah.dtype)
        # single-element masks: the sums select exactly one entry, so
        # they are exact in f32 (every other term is a true zero)
        row = (jnp.sum(ah * e[:, None], axis=0),
               jnp.sum(al * e[:, None], axis=0))
        col = (jnp.sum(ah * e[None, :], axis=1),
               jnp.sum(al * e[None, :], axis=1))
        piv = (jnp.sum(row[0] * e), jnp.sum(row[1] * e))
        piv, tiny = _fix_pivot_df64(piv, thresh)
        below = idx > i
        l = df64_div(col, (piv[0][None], piv[1][None]))
        l = (jnp.where(below, l[0], 0.0), jnp.where(below, l[1], 0.0))
        u = (jnp.where(below, row[0], 0.0), jnp.where(below, row[1], 0.0))
        upd = df64_mul((l[0][:, None], l[1][:, None]),
                       (u[0][None, :], u[1][None, :]))
        ah, al = df64_sub((ah, al), upd)
        # write multipliers + fixed pivot into column i by EXACT masked
        # select (0/1 products and disjoint-support sums round nothing;
        # the f32 path's delta-add trick would round the df64 low word
        # at the f32 ulp and collapse the factorization to f32 accuracy)
        above = idx < i
        new_col = (jnp.where(below, l[0], 0.0)
                   + jnp.where(above, col[0], 0.0) + piv[0] * e,
                   jnp.where(below, l[1], 0.0)
                   + jnp.where(above, col[1], 0.0) + piv[1] * e)
        keep = (1.0 - e)[None, :]
        ah = ah * keep + new_col[0][:, None] * e[None, :]
        al = al * keep + new_col[1][:, None] * e[None, :]
        return (ah, al), flags + tiny * sel.astype(jnp.int32)

    (fh, fl), flags = jax.lax.fori_loop(
        0, w, step, ((fh, fl), jnp.zeros(m, jnp.int32)))
    return (fh, fl), flags[:w]


@functools.lru_cache(maxsize=None)
def _df64_group_kernel(dims, child_shapes, pool_size, mesh=None,
                       pool_partition=False):
    """One (level, bucket) group in df64: assemble (hi, lo), factor,
    scatter the Schur block into the (hi, lo) pools.

    With a mesh, the batch dimension shards over "snode" (the vmapped
    elimination is per-front independent, so sharding cannot perturb the
    error-free transforms).  The "panel" axis is idle here — splitting
    the masked elimination's minor dims would turn every per-step
    row/column reduction into a collective.  pool_partition shards the
    hi/lo Schur pools 1-D across ALL mesh devices (same layout as the
    f32 path, factor.pool_spec): per-chip pool memory divides by the
    device count, so the df64 tier reaches the same n≈1M class as f32.
    Sharding a scatter/gather cannot perturb the error-free transforms
    either — each pool entry still receives exactly the same summands in
    the same order."""
    batch, m, w, u = dims
    front_sharding = pool_sharding = None
    if mesh is not None:
        from jax.sharding import NamedSharding, PartitionSpec as P
        from superlu_dist_tpu.numeric.factor import pool_spec
        front_sharding = NamedSharding(mesh, P("snode", None, None))
        pool_sharding = pool_spec(mesh, pool_partition)

    def step(avals_h, avals_l, pool_h, pool_l, thresh,
             a_slot, a_flat, a_src, ws, off, *child_arr):
        k = jnp.arange(m)
        diag = ((k[None, :] >= ws[:, None]) & (k[None, :] < w)).astype(
            jnp.float32)
        fh = jnp.zeros((batch, m * m), jnp.float32)
        fh = fh.at[:, k * m + k].add(diag)         # identity padding (hi)
        fl = jnp.zeros((batch, m * m), jnp.float32)
        if a_src.shape[0]:
            vh = avals_h.at[a_src].get(mode="fill", fill_value=0)
            vl = avals_l.at[a_src].get(mode="fill", fill_value=0)
            fh = fh.at[(a_slot, a_flat)].add(vh, mode="drop")
            fl = fl.at[(a_slot, a_flat)].add(vl, mode="drop")
        children = [(ub, child_arr[3 * i], child_arr[3 * i + 1],
                     child_arr[3 * i + 2])
                    for i, (ub, _) in enumerate(child_shapes)]
        # extend-add must stay exact: a plain f32 scatter-ADD would round
        # colliding sibling contributions at 2^-24 and cap the whole
        # factorization at f32 accuracy.  The caller pre-partitions the
        # children into passes with at most ONE child per batch slot
        # (child_shapes carries one entry per collision-free pass), so
        # each pass scatters into a fresh zero pair and is folded into
        # the front with an exact df64_add.
        for (ub, child_off, child_slot, rel) in children:
            src = child_off[:, None] + jnp.arange(ub * ub)
            vh = pool_h.at[src].get(mode="fill", fill_value=0)
            vl = pool_l.at[src].get(mode="fill", fill_value=0)
            ri, rj = rel[:, :, None], rel[:, None, :]
            dst = jnp.where((ri >= m) | (rj >= m), m * m,
                            ri * m + rj).reshape(-1, ub * ub)
            ph = jnp.zeros((batch, m * m), jnp.float32)
            pl = jnp.zeros((batch, m * m), jnp.float32)
            ph = ph.at[(child_slot[:, None], dst)].add(vh, mode="drop")
            pl = pl.at[(child_slot[:, None], dst)].add(vl, mode="drop")
            fh, fl = df64_add((fh, fl), (ph, pl))
        fh = fh.reshape(batch, m, m)
        fl = fl.reshape(batch, m, m)
        if front_sharding is not None:
            fh = jax.lax.with_sharding_constraint(fh, front_sharding)
            fl = jax.lax.with_sharding_constraint(fl, front_sharding)
            pool_h = jax.lax.with_sharding_constraint(pool_h, pool_sharding)
            pool_l = jax.lax.with_sharding_constraint(pool_l, pool_sharding)
        (fh, fl), counts = jax.vmap(
            lambda h, lo: df64_partial_front_factor(h, lo, thresh, w))(fh, fl)
        tiny = jnp.sum(jnp.where(jnp.arange(w)[None, :] < ws[:, None],
                                 counts, 0))
        if u > 0:
            sh = fh[:, w:, w:].reshape(batch, u * u)
            sl = fl[:, w:, w:].reshape(batch, u * u)
            dst = off[:, None] + jnp.arange(u * u)
            pool_h = pool_h.at[dst].set(sh, mode="drop")
            pool_l = pool_l.at[dst].set(sl, mode="drop")
        lp = (fh[:, :, :w], fl[:, :, :w])
        up = (fh[:, :w, w:], fl[:, :w, w:])
        if pool_sharding is not None:
            # pin the linearly-threaded pools replicated on OUTPUT too, so
            # sharding propagation from the snode-sharded fronts cannot
            # hand the next group a resharded pool (per-group transfers /
            # jit cache misses)
            pool_h = jax.lax.with_sharding_constraint(pool_h, pool_sharding)
            pool_l = jax.lax.with_sharding_constraint(pool_l, pool_sharding)
        return lp, up, pool_h, pool_l, tiny

    return jax.jit(step, donate_argnums=(2, 3))


class Df64Executor:
    """Cached df64 executor for a plan (the SamePattern reuse tier).

    Mirrors stream.StreamExecutor's discipline: all host-side index prep
    (bucket padding, collision-free child-pass partitioning) runs ONCE in
    __init__; repeated calls with new values reuse the uploaded index
    arrays and the lru-cached jitted kernels.  Obtain through
    `get_df64_executor` so gssvx's SamePattern tier hits the same
    executor across factorizations (the reference keeps its schedules in
    LUstruct across SamePattern calls, SRC/pdgssvx.c:1132-1166)."""

    def __init__(self, plan: FactorPlan, mesh=None,
                 pool_partition: bool = False):
        from superlu_dist_tpu.numeric.stream import _bucket_len, _pad_to

        plan.check_index_width()
        self.plan = plan
        self.mesh = mesh
        self.pool_partition = bool(pool_partition and mesh is not None)
        self.n_avals = len(plan.pattern_indices)
        self._groups = []     # (grp, a-arrays, child_arrs, kernel)
        for grp in plan.groups:
            b = _bucket_len(grp.batch, 1)
            la = _bucket_len(len(grp.a_src))
            a = (jnp.asarray(_pad_to(grp.a_slot, la, b)),
                 jnp.asarray(_pad_to(grp.a_flat, la, 0)),
                 jnp.asarray(_pad_to(grp.a_src, la, self.n_avals)),
                 jnp.asarray(_pad_to(grp.ws, b, 0)),
                 jnp.asarray(_pad_to(grp.off, b, plan.pool_size)))
            child_arrs = []
            child_shapes = []
            for cs in grp.children:
                # partition this child group into passes with at most one
                # child per batch slot, so each pass's scatter is
                # collision-free and the pass results combine by exact
                # df64_add (see _df64_group_kernel)
                passes = []          # list of lists of child indices
                for j, slot in enumerate(np.asarray(cs.child_slot)):
                    for p in passes:
                        if slot not in p[1]:
                            p[0].append(j)
                            p[1].add(int(slot))
                            break
                    else:
                        passes.append(([j], {int(slot)}))
                for p_idx, _slots in passes:
                    sel = np.asarray(p_idx, dtype=np.int64)
                    c = _bucket_len(len(sel), 1)
                    rel = np.full((c, cs.ub), grp.m, dtype=np.int64)
                    rel[:len(sel)] = np.asarray(cs.rel)[sel]
                    child_arrs.extend([
                        jnp.asarray(_pad_to(np.asarray(cs.child_off)[sel],
                                            c, plan.pool_size)),
                        jnp.asarray(_pad_to(np.asarray(cs.child_slot)[sel],
                                            c, b)),
                        jnp.asarray(rel)])
                    child_shapes.append((cs.ub, c))
            kern = _df64_group_kernel((b, grp.m, grp.w, grp.u),
                                      tuple(child_shapes), plan.pool_size,
                                      mesh, self.pool_partition)
            self._groups.append((grp, a, child_arrs, kern))

    def __call__(self, avals_h, avals_l, thresh):
        """Run the factorization; returns (fronts [host f64], tiny)."""
        pool_h = jnp.zeros(self.plan.pool_size, jnp.float32)
        pool_l = jnp.zeros(self.plan.pool_size, jnp.float32)
        if self.mesh is not None:
            # commit the pools to their mesh layout up front (partitioned
            # or replicated) so the first kernel starts from the right
            # sharding instead of inserting a reshard
            from superlu_dist_tpu.numeric.factor import pool_spec
            psh = pool_spec(self.mesh, self.pool_partition)
            pool_h = jax.device_put(pool_h, psh)
            pool_l = jax.device_put(pool_l, psh)
        fronts = []
        tiny = 0
        for grp, a, child_arrs, kern in self._groups:
            lp, up, pool_h, pool_l, t = kern(avals_h, avals_l, pool_h,
                                             pool_l, thresh, *a, *child_arrs)
            tiny += int(t)
            # recombine on host to exact f64; trim batch padding
            lp64 = (np.asarray(lp[0], np.float64)
                    + np.asarray(lp[1], np.float64))[:grp.batch]
            up64 = (np.asarray(up[0], np.float64)
                    + np.asarray(up[1], np.float64))[:grp.batch]
            fronts.append((lp64, up64))
        return fronts, tiny


def get_df64_executor(plan: FactorPlan, mesh=None,
                      pool_partition: bool = False) -> Df64Executor:
    """Df64Executor cached on the plan (same cache dict as
    factor.get_executor, keyed distinctly)."""
    cache = getattr(plan, "_factor_fns", None)
    if cache is None:
        cache = plan._factor_fns = {}
    key = ("df64", "df64", mesh, bool(pool_partition and mesh is not None))
    ex = cache.get(key)
    if ex is None:
        ex = cache[key] = Df64Executor(plan, mesh=mesh,
                                       pool_partition=pool_partition)
    return ex


def df64_numeric_factorize(plan: FactorPlan, pattern_values: np.ndarray,
                           anorm: float,
                           replace_tiny: bool = True,
                           mesh=None,
                           pool_partition: bool = False
                           ) -> NumericFactorization:
    """Factor with ~f64 accuracy on f32-only hardware.

    values must be float64 (split exactly into df64 pairs host-side).
    The GESP threshold uses the f64 epsilon — these factors genuinely
    carry ~48-bit significands.  Output fronts are host float64 arrays
    (hi + lo recombined), so the standard host solve/refine path runs
    unchanged; `on_host` is True by construction.
    """
    avals_h, avals_l = df64_from_f64(np.asarray(pattern_values, np.float64))
    eps64 = float(np.finfo(np.float64).eps)
    thresh = jnp.asarray(np.sqrt(eps64) * max(float(anorm), 1e-300)
                         if replace_tiny else 0.0, jnp.float32)
    ex = get_df64_executor(plan, mesh=mesh, pool_partition=pool_partition)
    fronts, tiny = ex(avals_h, avals_l, thresh)
    finite, info_col = (True, -1)
    if not replace_tiny:
        from superlu_dist_tpu.numeric.factor import localize_singularity
        finite, info_col = localize_singularity(plan, fronts)
    return NumericFactorization(plan=plan, fronts=fronts, tiny_pivots=tiny,
                                dtype=np.dtype(np.float64),
                                finite=finite, info_col=info_col)
