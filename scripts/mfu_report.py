#!/usr/bin/env python
"""Summarize tuning rows + kernel-shape traces into an MFU report.

Inputs: tune_results.jsonl (one JSON row per bench config) and a kernel
trace in EITHER format:

* the structured obs trace (preferred when present): the Chrome
  trace-event JSON or the JSONL sidecar written by ``SLU_TPU_TRACE``
  (superlu_dist_tpu/obs/trace.py) — kernel spans carry shape, executed
  vs structural flops and the padding ratio natively, no scraping;
* the legacy stderr log containing ``# lvl=... m=... w=... u=...``
  kernel-trace lines emitted by bench.py under (deprecated)
  SLU_TPU_PROFILE=1 — the reference's dgemm_mnk.dat analog
  (SRC/pdgstrf.c:380-387).

The second argument is sniffed: trace formats are parsed natively,
anything else falls back to the legacy regex.  Missing or empty inputs
produce an explicit "no trace rows found" diagnostic and exit 1 instead
of a silently empty report.

Prints: ranked result table, dispatch-vs-compute split, the top
kernel-time sinks — the "top-3 MFU thieves" evidence VERDICT r2 #9 asks
for — and, when the trace carries ``compile``-category spans (the
compile census, obs/compilestats.py), a compile-time section ranking
the shape-key buckets that dominated cold compile.  Pure text
processing; safe to run anywhere.
"""

import json
import os
import re
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from superlu_dist_tpu.utils.options import env_float  # noqa: E402
from superlu_dist_tpu.utils.peaks import table_peak_gflops  # noqa: E402


def _row_mfu(row: dict) -> float:
    """A row's MFU — recomputed against the per-backend/per-precision
    peak table (utils/peaks.py; SLU_TPU_PEAK_GFLOPS overrides) whenever
    the row itself carries none, so legacy rows stop printing the
    constant-denominator 0.0.  Rows measured on another machine's CPU
    stay at their recorded value (that machine's peak is unknowable
    here)."""
    mfu = row.get("mfu_pct") or 0.0
    if mfu:
        return float(mfu)
    value = row.get("value")
    if not value:
        return 0.0
    peak = env_float("SLU_TPU_PEAK_GFLOPS")
    if peak <= 0 and row.get("backend") not in (None, "cpu"):
        peak = table_peak_gflops(row.get("backend", "tpu"),
                                 row.get("gemm_precision", "highest")) or 0.0
    return round(100.0 * float(value) / peak, 4) if peak > 0 else 0.0


def _iter_trace_events(text: str):
    """Yield event dicts from a Chrome trace JSON or a JSONL sidecar;
    return None (not an empty iterator) when the text is neither."""
    text = text.strip()
    if not text:
        return None
    if text.startswith("{"):
        try:
            doc = json.loads(text)
        except json.JSONDecodeError:
            doc = None                # multi-line JSONL: parse per line
        if isinstance(doc, dict):
            if isinstance(doc.get("traceEvents"), list):
                return doc["traceEvents"]
            if "cat" not in doc:      # a single JSONL row IS an event
                return None
    events = []
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        try:
            ev = json.loads(line)
        except json.JSONDecodeError:
            return None
        if not isinstance(ev, dict) or "cat" not in ev:
            return None
        events.append(ev)
    return events or None


def load_trace_kernels(path: str):
    """Kernel rows [(ms, GF/s, lvl, batch, m, w, u), ...] from an obs
    trace artifact, or None when `path` is missing / not a trace file
    (the caller then tries the legacy format)."""
    try:
        with open(path) as f:
            text = f.read()
    except OSError:
        return None
    events = _iter_trace_events(text)
    if events is None:
        return None
    rows = []
    for ev in events:
        if ev.get("cat") != "kernel":
            continue
        args = ev.get("args") or {}
        ms = float(ev.get("dur", 0.0)) / 1e3          # trace dur is in us
        gflop = float(args.get("executed_flops",
                               args.get("structural_flops", 0.0))) / 1e9
        gfs = gflop / max(ms / 1e3, 1e-12)
        rows.append((ms, gfs, int(args.get("level", -1)),
                     int(args.get("batch", 0)), int(args.get("m", 0)),
                     int(args.get("w", 0)), int(args.get("u", 0))))
    return rows


def load_trace_compiles(path: str):
    """Compile-census rows [(seconds, site, key, persistent_hit), ...]
    from an obs trace artifact's ``compile``-category spans, or None
    when `path` is missing / not a trace file."""
    try:
        with open(path) as f:
            text = f.read()
    except OSError:
        return None
    events = _iter_trace_events(text)
    if events is None:
        return None
    rows = []
    for ev in events:
        if ev.get("cat") != "compile":
            continue
        args = ev.get("args") or {}
        rows.append((float(ev.get("dur", 0.0)) / 1e6,   # us -> s
                     str(ev.get("name", "?")).replace("compile ", "", 1),
                     str(args.get("key", "?")),
                     bool(args.get("persistent_hit"))))
    return rows


def main():
    # live session logs are gitignored; fall back to the committed
    # docs/ snapshot of the latest hardware session when absent
    out = sys.argv[1] if len(sys.argv) > 1 else "tune_results.jsonl"
    err = sys.argv[2] if len(sys.argv) > 2 else "tune_results.err"
    if len(sys.argv) <= 1 and not os.path.exists(out):
        out, err = "docs/tune_results_r3.jsonl", "docs/tune_results_r3.err"

    missing = []
    rows = []
    try:
        for line in open(out):
            line = line.strip()
            if not line:
                continue
            try:
                rows.append(json.loads(line))
            except json.JSONDecodeError:
                pass
    except FileNotFoundError:
        missing.append(out)

    tpu = [r for r in rows if r.get("value") is not None
           and r.get("backend") not in (None, "cpu")]
    tpu.sort(key=lambda r: -r["value"])
    if tpu:
        print("== TPU rows (ranked by factor GFLOP/s) ==")
    for r in tpu:
        disp = r.get("dispatch_seconds")
        fs = r.get("factor_seconds", 0.0) or 0.0
        dshare = (f" dispatch {100 * disp / fs:4.0f}%"
                  if disp is not None and fs else "")
        print(f"{r['value']:8.1f} GF/s  mfu {_row_mfu(r):7.4f}%  "
              f"gemm {r.get('gemm_precision', '?'):<7s} "
              f"pad {r.get('padding_factor', '?'):>4}  "
              f"{r.get('granularity', '?'):<6} "
              f"kern {r.get('n_kernels', '?'):>3}{dshare}  "
              f"resid {r.get('residual', float('nan')):.1e}  "
              f"{r['metric']}"
              + (f"  [{','.join(str(b) for b in r['blocking'])}]"
                 if r.get("blocking") else ""))

    # kernel rows: structured trace preferred, legacy stderr fallback
    # ("# lvl=3  B=16  m=512  w=256  u=256  12.34 ms  567.8 GF/s")
    kernels = load_trace_kernels(err)
    source = "structured trace" if kernels is not None else "legacy stderr"
    if kernels is None:
        pat = re.compile(
            r"# lvl=\s*(\d+)\s+B=\s*(\d+)\s+m=\s*(\d+)\s+w=\s*(\d+)\s+"
            r"u=\s*(\d+)\s+([\d.]+) ms\s+([\d.]+) GF/s")
        kernels = []
        try:
            for line in open(err):
                m = pat.search(line)
                if m:
                    lvl, B, mm, w, u = (int(m.group(i))
                                        for i in range(1, 6))
                    ms, gfs = float(m.group(6)), float(m.group(7))
                    kernels.append((ms, gfs, lvl, B, mm, w, u))
        except FileNotFoundError:
            missing.append(err)
    if kernels:
        total = sum(k[0] for k in kernels)
        print(f"\n== kernel trace ({source}): {len(kernels)} entries, "
              f"{total:.1f} ms profiled ==")
        print("top sinks (ms, GF/s, lvl, batch, m, w, u, % of profiled):")
        for ms, gfs, lvl, B, mm, w, u in sorted(kernels)[::-1][:12]:
            print(f"  {ms:8.2f} ms {gfs:8.1f} GF/s  lvl={lvl:<3d} B={B:<5d} "
                  f"m={mm:<5d} w={w:<5d} u={u:<5d}  {100 * ms / total:4.1f}%")

    # compile census (obs/compilestats.py): where COLD time went — the
    # BENCH_r02 question ("died in factor-compile, which buckets?")
    compiles = load_trace_compiles(err)
    if compiles:
        ctot = sum(c[0] for c in compiles)
        hits = sum(1 for c in compiles if c[3])
        print(f"\n== compile census: {len(compiles)} builds, "
              f"{ctot:.2f} s, {hits} persistent-cache hits ==")
        print("top builds (s, site, bucket key, % of compile):")
        for s, site, key, hit in sorted(compiles)[::-1][:12]:
            tag = " [disk hit]" if hit else ""
            print(f"  {s:8.3f} s  {site:<18s} {key:<26s} "
                  f"{100 * s / max(ctot, 1e-12):4.1f}%{tag}")

    if not rows and not kernels and not compiles:
        # the one failure mode this script must never have: silence
        detail = (f" (missing: {', '.join(missing)})" if missing
                  else " (inputs present but empty)")
        print(f"no trace rows found in {out!r} / {err!r}{detail}",
              file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
