import numpy as np
import jax.numpy as jnp
import pytest

from superlu_dist_tpu.ops.dense import lu_nopivot, make_front_kernel


def np_lu_nopiv(a):
    a = a.copy()
    n = a.shape[0]
    for i in range(n):
        a[i + 1:, i] /= a[i, i]
        a[i + 1:, i + 1:] -= np.outer(a[i + 1:, i], a[i, i + 1:])
    return a


@pytest.mark.parametrize("n", [1, 3, 16, 17, 40, 96])
@pytest.mark.parametrize("dtype", [np.float64, np.complex128])
def test_lu_nopivot_matches_numpy(n, dtype):
    rng = np.random.default_rng(n)
    a = rng.standard_normal((n, n)).astype(dtype)
    if np.issubdtype(dtype, np.complexfloating):
        a = a + 1j * rng.standard_normal((n, n))
    a += np.eye(n) * (2 * n)      # diagonally dominant: no tiny pivots
    got, count = lu_nopivot(jnp.asarray(a), jnp.asarray(1e-300))
    want = np_lu_nopiv(a.copy())
    np.testing.assert_allclose(np.asarray(got), want, rtol=1e-10, atol=1e-10)
    assert count.shape == (n,) and int(count.sum()) == 0


def test_tiny_pivot_replacement():
    a = np.array([[1.0, 1.0], [1.0, 1.0]])   # second pivot exactly 0
    out, count = lu_nopivot(jnp.asarray(a), jnp.asarray(1e-8))
    # per-column flags localize the tiny pivot to column 1
    assert list(np.asarray(count)) == [0, 1]
    assert abs(np.asarray(out)[1, 1]) == pytest.approx(1e-8)


@pytest.mark.parametrize("m,w,u_real,w_real", [(24, 8, 16, 8), (32, 16, 10, 13)])
def test_partial_front_factor(m, w, u_real, w_real):
    rng = np.random.default_rng(0)
    B = 3
    fronts = np.zeros((B, m, m))
    for b in range(B):
        f = np.zeros((m, m))
        # real data: pivot block w_real, rows u_real; identity padding in
        # pivot cols [w_real, w)
        blk = rng.standard_normal((w_real + u_real, w_real + u_real))
        blk += np.eye(w_real + u_real) * 2 * (w_real + u_real)
        f[:w_real, :w_real] = blk[:w_real, :w_real]
        f[w:w + u_real, :w_real] = blk[w_real:, :w_real]
        f[:w_real, w:w + u_real] = blk[:w_real, w_real:]
        f[w:w + u_real, w:w + u_real] = blk[w_real:, w_real:]
        for k in range(w_real, w):
            f[k, k] = 1.0
        fronts[b] = f
    kern = make_front_kernel(m, w, "float64")
    out, tiny = kern(jnp.asarray(fronts), jnp.asarray(1e-300))
    out = np.asarray(out)
    assert int(tiny) == 0
    for b in range(B):
        f = fronts[b]
        # reconstruct: dense partial LU on the real (w_real+u_real) block
        blk = np.zeros((w_real + u_real, w_real + u_real))
        blk[:w_real, :w_real] = f[:w_real, :w_real]
        blk[w_real:, :w_real] = f[w:w + u_real, :w_real]
        blk[:w_real, w_real:] = f[:w_real, w:w + u_real]
        blk[w_real:, w_real:] = f[w:w + u_real, w:w + u_real]
        ref = blk.copy()
        for i in range(w_real):
            ref[i + 1:, i] /= ref[i, i]
            ref[i + 1:, i + 1:] -= np.outer(ref[i + 1:, i], ref[i, i + 1:])
        np.testing.assert_allclose(out[b][:w_real, :w_real], ref[:w_real, :w_real],
                                   rtol=1e-10, atol=1e-10)
        np.testing.assert_allclose(out[b][w:w + u_real, :w_real], ref[w_real:, :w_real],
                                   rtol=1e-10, atol=1e-10)
        np.testing.assert_allclose(out[b][:w_real, w:w + u_real], ref[:w_real, w_real:],
                                   rtol=1e-10, atol=1e-10)
        np.testing.assert_allclose(out[b][w:w + u_real, w:w + u_real],
                                   ref[w_real:, w_real:], rtol=1e-10, atol=1e-10)
