#!/usr/bin/env python
"""Schedule-equivalence gate: SLU_TPU_SCHEDULE=level vs dataflow must
produce BITWISE-identical L/U.

The dataflow scheduler (numeric/plan.py) may only change WHEN a front
is factored — batch membership, dispatch count, pool layout — never the
arithmetic within a front.  This gate factors the same analyzed
structures under both schedules (both executors for the main case) and
compares every supernode's real L/U sub-blocks with np.array_equal (no
tolerance), then asserts the dataflow group count never exceeds the
level partition's.

Exit 0 = pass.  One gate of scripts/ci_gates.sh (the consolidated CI
entry point); a few seconds on CPU.  Gate contract (shared with
run_slulint.sh, check_nan_guards.sh, check_trace_overhead.py and
check_verify_overhead.py): any regression — a bitwise mismatch, a
group-count blowup, a child failure — raises/asserts, which exits
non-zero with the diagnostic on stderr.
"""

import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np  # noqa: E402


def _analyzed(a):
    from superlu_dist_tpu.ordering.dispatch import get_perm_c
    from superlu_dist_tpu.sparse.formats import symmetrize_pattern
    from superlu_dist_tpu.symbolic.symbfact import symbolic_factorize
    from superlu_dist_tpu.utils.options import Options

    sym = symmetrize_pattern(a)
    col_order = get_perm_c(Options(), a, sym)
    sf = symbolic_factorize(sym, col_order)
    return sf, sym.data[sf.value_perm], a.norm_max()


def _real_blocks(plan, fact, s, wr, ur):
    g, slot = int(plan.sn_group[s]), int(plan.sn_slot[s])
    grp = plan.groups[g]
    lp = np.asarray(fact.fronts[g][0][slot])
    up = np.asarray(fact.fronts[g][1][slot])
    return (np.concatenate([lp[:wr, :wr], lp[grp.w:grp.w + ur, :wr]]),
            up[:wr, :ur])


def check(name, a, executors=("fused",)):
    from superlu_dist_tpu.numeric.factor import numeric_factorize
    from superlu_dist_tpu.numeric.plan import build_plan

    sf, vals, anorm = _analyzed(a)
    widths = np.diff(sf.sn_start)
    us = np.array([len(r) for r in sf.sn_rows])
    plan_l = build_plan(sf, schedule="level")
    plan_d = build_plan(sf, schedule="dataflow")
    assert len(plan_d.groups) <= len(plan_l.groups), (
        f"{name}: dataflow produced MORE groups "
        f"({len(plan_d.groups)} > {len(plan_l.groups)})")
    for ex in executors:
        f_l = numeric_factorize(plan_l, vals, anorm, executor=ex)
        f_d = numeric_factorize(plan_d, vals, anorm, executor=ex)
        for s in range(sf.n_supernodes):
            La, Ua = _real_blocks(plan_l, f_l, s, int(widths[s]),
                                  int(us[s]))
            Lb, Ub = _real_blocks(plan_d, f_d, s, int(widths[s]),
                                  int(us[s]))
            assert np.array_equal(La, Lb) and np.array_equal(Ua, Ub), (
                f"{name}/{ex}: supernode {s} L/U differ between "
                "level and dataflow schedules (bitwise)")
    print(f"[schedule-equiv] {name}: OK "
          f"(groups {len(plan_l.groups)} -> {len(plan_d.groups)}, "
          f"{sf.n_supernodes} supernodes, executors {list(executors)})")


def main():
    import jax
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_enable_x64", True)
    from superlu_dist_tpu.models.gallery import (
        hilbert, poisson2d, rank_deficient_arrowhead)

    check("poisson2d(16)", poisson2d(16), executors=("fused", "stream"))
    check("hilbert(48)", hilbert(48))
    check("rank_deficient_arrowhead(40)", rank_deficient_arrowhead(40))
    print("[schedule-equiv] all checks passed")


if __name__ == "__main__":
    main()
