"""Rank-failure recovery: shrink-or-respawn epochs over the survivors.

The bounded-wait collectives (parallel/treecomm.py) turn a dead peer
into a structured :class:`RankFailureError` raised on EVERY surviving
rank with an agreed dead-set (the ULFM revoke→agree conversion).  This
module is the third leg — *recover*: re-form the communicator over the
survivors and finish the solve.

One recovery epoch (``Options.ft``):

* ``"shrink"`` — the survivors renumber into a dense rank set, attach a
  fresh epoch domain (``<name>.e<k>`` — its creator unlinks any stale
  segment first, so a crashed epoch can never be rejoined), re-deal the
  input rows over the surviving rank count (the ShyLU-style subdomain
  reassignment, arXiv:2506.05793 — which is exactly a re-run of the
  panalysis/row partitioning over the new rank set), and re-enter
  ``pgssvx`` — with the previous epoch's checkpoint frontier
  (persist/checkpoint.py) threaded through ``resume_from`` so the root
  factorization COMPLETES from where the dead epoch left off instead of
  starting over (bitwise-identical L/U, proven by
  scripts/check_rank_failure.py);
* ``"respawn"`` — the lowest surviving rank spawns one replacement
  process per dead rank (the sources must be picklable — see
  :class:`RowBlockSource` — and, per the standard multiprocessing
  "spawn" contract, the caller's ``__main__`` must be import-safe);
  the replacements take over the DEAD ranks' ids in the next epoch, so
  the world size never shrinks;
* ``"abort"`` — the error propagates (the default: policy belongs to
  the caller, not the transport).

Every recovery is recorded: a :class:`FtEvent` in the process-wide
:data:`FT_EVENTS` (bench.py reports ``ft_events``/``recovered``), an
``ft-shrink``/``ft-respawn`` rung on the SolveReport ladder, a
``slu_ft_recoveries_total`` metric, and a flight-recorder event on
every surviving rank (the RankFailureError construction already dumped
the postmortem ring).

The input contract makes re-dealing possible: ``a_source(n_ranks,
rank)`` / ``b_source(n_ranks, rank)`` return THIS rank's block for the
CURRENT rank count — the serving shape, where the rows come from a
request or a store and can be re-dealt to whoever is still standing.
A rank's private, unrecoverable rows would make shrink impossible by
definition (respawn still works: the world size is preserved).
"""

from __future__ import annotations

import dataclasses
import time

import numpy as np

from superlu_dist_tpu.parallel.treecomm import TreeComm, make_treecomm
from superlu_dist_tpu.utils.errors import (CheckpointError,
                                           RankFailureError, SuperLUError)

#: process-wide record of every rank-failure recovery this process took
#: part in (bench.py surfaces len() as the ``ft_events`` row field)
FT_EVENTS: list = []

#: replacement processes spawned by THIS process (respawn mode); join
#: them via :func:`reap_respawned` once the solve returns
_RESPAWNED: list = []

FT_MODES = ("abort", "shrink", "respawn")


@dataclasses.dataclass
class FtEvent:
    """One recovery the epoch loop performed."""

    epoch: int                 # the epoch that FAILED
    dead: list                 # original rank ids declared dead
    mode: str                  # "shrink" | "respawn"
    op: str = ""               # collective the failure surfaced in
    resumed: bool = False      # next epoch resumed a checkpoint frontier
    seconds: float = 0.0       # failure -> next-epoch entry


class RowBlockSource:
    """Picklable re-shardable matrix source: deals block rows of one
    global SparseCSR to the current rank set (parallel/dist.py
    partitioning — re-run per epoch, so a shrink re-partitions over the
    survivors)."""

    def __init__(self, a):
        self.a = a

    def __call__(self, n_ranks: int, rank: int):
        from superlu_dist_tpu.parallel.dist import distribute_rows
        return distribute_rows(self.a, n_ranks)[rank]


class VectorBlockSource:
    """Picklable RHS source matching :class:`RowBlockSource`'s row
    partition (the same ceil-step block bounds)."""

    def __init__(self, b):
        self.b = np.asarray(b)

    def __call__(self, n_ranks: int, rank: int):
        n = self.b.shape[0]
        step = -(-n // n_ranks)
        lo = min(rank * step, n)
        hi = min(lo + step, n)
        return self.b[lo:hi]


def _attach_epoch(name: str, n_ranks: int, rank: int, max_len: int,
                  attach_timeout_s: float = 30.0) -> TreeComm:
    """Form one epoch's communicator.  Rank 0 of the NEW numbering
    creates (unlinking any stale same-named segment); the others retry
    until the creator's segment exists — the rendezvous the failed
    epoch's survivors perform concurrently."""
    create = rank == 0
    deadline = time.monotonic() + attach_timeout_s
    while True:
        try:
            return make_treecomm(name, n_ranks, rank, max_len=max_len,
                                 create=create)
        except OSError:
            if create or time.monotonic() >= deadline:
                raise
            time.sleep(0.05)


def _checkpoint_resume_dir(options) -> str | None:
    """The durable frontier of the failed epoch, if one was flushed
    (shared filesystem assumption: the new root can read the old
    root's checkpoint directory)."""
    if not getattr(options, "ckpt_dir", ""):
        return None
    from superlu_dist_tpu.persist.checkpoint import peek
    try:
        meta = peek(options.ckpt_dir)
    except CheckpointError:
        return None
    return options.ckpt_dir if int(meta.get("k", 0)) > 0 else None


def _spawn_replacements(name, n_world, alive, dead, options, a_source,
                        b_source, max_len, epoch):
    """Respawn one process per dead rank (spawn context — a fork of a
    jax-warmed parent can deadlock on inherited XLA locks)."""
    import multiprocessing as mp
    ctx = mp.get_context("spawn")
    for d in dead:
        p = ctx.Process(
            target=_respawn_worker,
            args=(name, n_world, d, options, a_source, b_source,
                  max_len, epoch, tuple(alive)),
            name=f"slu-respawn-r{d}e{epoch}")
        p.start()
        _RESPAWNED.append(p)


def _respawn_worker(name, n_world, rank, options, a_source, b_source,
                    max_len, epoch, alive):
    """Entry point of a replacement process: join the given epoch as
    the dead rank's successor and run the same FT loop from there."""
    pgssvx_ft(name, n_world, rank, options, a_source, b_source,
              max_len=max_len, start_epoch=epoch, alive=alive)


def reap_respawned(timeout: float = 60.0) -> None:
    """Join replacement processes spawned by this process (they finish
    the same epoch collectives the spawner finished, so this is quick;
    called automatically on successful return of pgssvx_ft)."""
    while _RESPAWNED:
        p = _RESPAWNED.pop()
        p.join(timeout=timeout)


def _record_recovery(lu_out, events) -> None:
    """Stamp the recoveries onto the caller-visible artifacts: the
    lu_out dict, the SolveReport ladder, and the metrics registry."""
    if lu_out is None:
        return
    lu_out["ft_events"] = list(events)
    lu_out["recovered"] = bool(events)
    rep = lu_out.get("solve_report")
    if rep is None:
        stats = lu_out.get("stats")
        rep = getattr(stats, "solve_report", None) if stats else None
    if rep is not None:
        from superlu_dist_tpu.utils.stats import RungRecord
        for ev in events:
            rep.rungs.append(RungRecord(
                name=f"ft-{ev.mode}",
                detail=(f"epoch {ev.epoch} dead={ev.dead} op={ev.op} "
                        f"resumed={ev.resumed}"),
                seconds=ev.seconds))


def pgssvx_ft(name: str, n_ranks: int, rank: int, options, a_source,
              b_source, *, max_len: int = 4096, lu_out=None,
              start_epoch: int = 0, alive=None, max_epochs: int = 8):
    """Fault-tolerant collective solve: pgssvx epochs until success.

    Every participating process calls this with the shared domain
    ``name``, the WORLD size ``n_ranks`` and its own original ``rank``;
    ``a_source``/``b_source`` are the re-shardable input callables
    documented above.  Returns ``(x, info)`` like pgssvx, where ``x``
    is THIS epoch's global solution (every survivor gets it).

    On :class:`RankFailureError` the behavior follows ``options.ft``
    (``SLU_TPU_FT``): abort re-raises; shrink drops the dead ranks and
    re-enters with the survivors; respawn replaces them.  Either way
    the next epoch threads the failed epoch's checkpoint frontier into
    the root factorization (``resume_from``) when one was flushed, so
    completed factor groups are never recomputed — and a recovered
    solve is bitwise-identical to an undisturbed one.
    """
    mode = getattr(options, "ft", "abort") or "abort"
    if mode not in FT_MODES:
        raise SuperLUError(
            f"Options.ft must be one of {FT_MODES}, got {mode!r}")
    alive = list(range(n_ranks)) if alive is None else list(alive)
    epoch = start_epoch
    events: list = []
    x = info = None
    while True:
        sub_rank = alive.index(rank)
        nm = name if epoch == 0 else f"{name}.e{epoch}"
        tc = _attach_epoch(nm, len(alive), sub_rank, max_len)
        tc.epoch = epoch
        # chaos injections stay scoped to the ORIGINAL identity: a
        # survivor renumbered into a dead rank's slot (or a respawned
        # successor) must not inherit epoch-0 injections
        tc.chaos_rank = rank
        from superlu_dist_tpu.testing.chaos import bind_rank
        bind_rank(rank, epoch)
        resume = _checkpoint_resume_dir(options) if epoch > start_epoch \
            else None
        a_loc = a_source(len(alive), sub_rank)
        b_loc = b_source(len(alive), sub_rank)
        out = lu_out if lu_out is not None else {}
        t_fail = time.monotonic()
        try:
            from superlu_dist_tpu.parallel.pgssvx import pgssvx
            # an unusable frontier degrades ROOT-LOCALLY inside pgssvx
            # (CheckpointError fallback there) — retrying out here would
            # diverge the survivors' collective sequences
            x, info = pgssvx(tc, options, a_loc, b_loc, lu_out=out,
                             resume_from=resume)
            if events:
                events[-1].resumed = bool(resume)
            _record_recovery(out, events)
            tc.close(unlink=True)
            reap_respawned()
            return x, info
        except RankFailureError as exc:
            tc.close(unlink=True)
            if mode == "abort" or epoch - start_epoch >= max_epochs:
                raise
            dead_orig = sorted(alive[d] for d in exc.dead_ranks)
            ev = FtEvent(epoch=epoch, dead=dead_orig, mode=mode,
                         op=exc.op, seconds=time.monotonic() - t_fail)
            events.append(ev)
            FT_EVENTS.append(ev)
            from superlu_dist_tpu.obs.metrics import get_metrics
            m = get_metrics()
            if m.enabled:
                m.inc("slu_ft_recoveries_total", 1.0, mode=mode)
            from superlu_dist_tpu.obs.flightrec import get_flightrec
            fr = get_flightrec()
            if fr.enabled:
                fr.event("ft-recovery", cat="verify", mode=mode,
                         epoch=epoch, dead=",".join(map(str, dead_orig)))
            survivors = [r for r in alive if r not in dead_orig]
            if mode == "shrink":
                alive = survivors
            else:                      # respawn: world size preserved
                if rank == min(survivors):
                    _spawn_replacements(name, n_ranks, alive, dead_orig,
                                        options, a_source, b_source,
                                        max_len, epoch + 1)
            epoch += 1
