#!/usr/bin/env python
"""Complex factorization reuse: same A, new right-hand sides — analog of
EXAMPLE/pzdrive1.c (the z-twin of pddrive1; Fact=FACTORED re-solves
through the kept complex factors).

    python examples/pzdrive1.py [matrix.cua] [--backend cpu]
"""

import sys
import os

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
from examples._common import (pin_cpu_if_requested, load_matrix, make_rhs,
                              report)


def main():
    pin_cpu_if_requested()
    import superlu_dist_tpu as slu

    a, src = load_matrix(complex_=True)
    print(f"matrix: {src}  n={a.n_rows} nnz={a.nnz} dtype={a.data.dtype}")
    xtrue, b = make_rhs(a, seed=0)
    x, lu, stats, info = slu.gssvx(slu.Options(), a, b)
    assert info == 0

    # second solve: same complex factors, different b
    xtrue2, b2 = make_rhs(a, seed=1)
    x2, lu, stats2, info2 = slu.gssvx(
        slu.Options(fact=slu.Fact.FACTORED), a, b2, lu=lu)
    assert info2 == 0
    assert stats2.utime["FACT"] == 0.0, "FACTORED must skip refactorization"
    resid = report("pzdrive1 (FACTORED)", a, b2, x2, xtrue2, stats2)
    assert resid < 1e-10
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
