"""slulint v5 precision-flow suite (docs/ANALYSIS.md).

Per-rule fixture pairs for the source rules (SLU115 implicit downcast
with its witness chain, SLU116 accumulation-dtype pins, SLU117 EFT
purity both halves, SLU118 tolerance hygiene), the jaxpr twins over
real traced programs (sanctioned vs unsanctioned narrowing, pinned vs
unpinned dot_general), the ``SLU_TPU_VERIFY_DTYPES=1`` runtime auditor
(raise-before-run with flight-recorder postmortem, census ``#dtypes``
notes, off-path no-state), the utils/tols eps-model round trip
(including df64), and the complex-operand bf16 GEMM-tier degrade.
"""

import json
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax import lax

from superlu_dist_tpu.analysis.core import analyze_sources
from superlu_dist_tpu.analysis.program import trace_spec, audit_dtypes
from superlu_dist_tpu.analysis import rules_precision as rp
from superlu_dist_tpu.utils import programaudit, tols
from superlu_dist_tpu.utils.errors import PrecisionAuditError

pytestmark = pytest.mark.preclint

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(REPO, "tests", "fixtures", "slulint")


def _scan(name):
    path = os.path.join("tests", "fixtures", "slulint", name)
    with open(os.path.join(REPO, path)) as f:
        return analyze_sources({path: f.read()})


@pytest.fixture
def fresh_dtype_auditor(monkeypatch):
    """SLU_TPU_VERIFY_DTYPES=1 with fresh auditors + clean census audit
    notes, restored afterwards."""
    from superlu_dist_tpu.obs.compilestats import COMPILE_STATS
    monkeypatch.delenv("SLU_TPU_VERIFY_PROGRAMS", raising=False)
    monkeypatch.setenv("SLU_TPU_VERIFY_DTYPES", "1")
    programaudit._reset()
    with COMPILE_STATS._lock:
        saved = dict(COMPILE_STATS._audits)
        COMPILE_STATS._audits = {}
    yield
    programaudit._reset()
    with COMPILE_STATS._lock:
        COMPILE_STATS._audits = saved


# --------------------------------------------------------------------------
# SLU115 implicit downcast (source)
# --------------------------------------------------------------------------

def test_slu115_fixture_flagged_with_witness_chain():
    hits = [f for f in _scan("narrowing_cast_flagged.py")
            if f.rule == "SLU115"]
    assert len(hits) == 2, hits
    chained = [f for f in hits if "witness chain" in f.message]
    assert chained, hits
    # the chain names BOTH ends: the cast line and the consuming call
    assert "cast at line" in chained[0].message
    assert "`matmul`" in chained[0].message
    # the provenance-free 16-bit cast is flagged too (presumed downcast)
    assert any("f16" in f.message for f in hits)


def test_slu115_fixture_clean():
    assert [f for f in _scan("narrowing_cast_clean.py")
            if f.rule == "SLU115"] == []


# --------------------------------------------------------------------------
# SLU116 accumulation dtype (source)
# --------------------------------------------------------------------------

def test_slu116_fixture_flagged():
    hits = [f for f in _scan("pinned_accum_flagged.py")
            if f.rule == "SLU116"]
    assert len(hits) == 3, hits          # matmul, dot_general, segment_sum
    assert all("preferred_element_type" in f.message for f in hits)


def test_slu116_fixture_clean():
    assert [f for f in _scan("pinned_accum_clean.py")
            if f.rule == "SLU116"] == []


# --------------------------------------------------------------------------
# SLU117 EFT purity (source, both halves)
# --------------------------------------------------------------------------

def test_slu117_fixture_flagged():
    hits = [f for f in _scan("raw_eft_flagged.py") if f.rule == "SLU117"]
    raw = [f for f in hits if "raw arithmetic" in f.message]
    fence = [f for f in hits if "unfenced" in f.message]
    # half A: sh+sl, and hi*2.0-lo (taint flows through the nested
    # BinOp; the two ops share a position, so one finding)
    assert len(raw) == 2, hits
    assert any("two_sum" in f.message or "df64_add" in f.message
               for f in raw)
    # half B: the unfenced local quick_two_sum (s=a+b, s-a, b-(...))
    assert len(fence) >= 3, hits
    assert all("quick_two_sum" in f.message for f in fence)


def test_slu117_fixture_clean():
    assert [f for f in _scan("raw_eft_clean.py")
            if f.rule == "SLU117"] == []


# --------------------------------------------------------------------------
# SLU118 tolerance hygiene (source)
# --------------------------------------------------------------------------

def test_slu118_fixture_flagged():
    hits = [f for f in _scan("literal_tol_flagged.py")
            if f.rule == "SLU118"]
    # 1e-8 comparison, negated -1e-10, rtol=1e-9, atol=1e-12
    assert len(hits) == 4, hits
    assert all("utils/tols" in (f.message + (f.hint or ""))
               for f in hits)


def test_slu118_fixture_clean():
    assert [f for f in _scan("literal_tol_clean.py")
            if f.rule == "SLU118"] == []


def test_slu118_suppression_honored():
    src = "def gate(res):\n"
    src += "    return res < 1e-8  # slulint: disable=SLU118\n"
    assert analyze_sources({"scripts/x.py": src}) == []


# --------------------------------------------------------------------------
# jaxpr twins: audit_narrowing / audit_accumulation over traced programs
# --------------------------------------------------------------------------

def test_audit_narrowing_flags_unsanctioned_convert():
    f = jax.jit(lambda x: x.astype(jnp.bfloat16) + 1.0)
    spec = trace_spec(f, (np.ones((8, 8), np.float32),),
                      label="narrow", site="test")
    findings, stats = rp.audit_narrowing(spec)
    assert [x.rule for x in findings] == ["SLU115"]
    assert stats["n_narrowing"] >= 1
    assert "f32->f16" in findings[0].message


def test_audit_narrowing_sanctioned_gemm_input_clean():
    # the ops/dense.gemm bf16-tier shape: narrowed inputs are fine when
    # every consumer is a dot_general accumulating at >= f32
    def g(a, b):
        return jnp.matmul(a.astype(jnp.bfloat16), b.astype(jnp.bfloat16),
                          preferred_element_type=jnp.float32)
    spec = trace_spec(jax.jit(g),
                      (np.ones((8, 8), np.float32),
                       np.ones((8, 8), np.float32)),
                      label="gemm-in", site="test")
    findings, stats = rp.audit_narrowing(spec)
    assert findings == [], findings
    assert stats["n_narrowing"] >= 2      # counted, but sanctioned


def test_audit_accumulation_flags_unpinned_bf16_dot():
    def g(a, b):
        return lax.dot_general(a.astype(jnp.bfloat16),
                               b.astype(jnp.bfloat16),
                               (((1,), (0,)), ((), ())))
    spec = trace_spec(jax.jit(g),
                      (np.ones((8, 8), np.float32),
                       np.ones((8, 8), np.float32)),
                      label="unpinned", site="test")
    findings, stats = rp.audit_accumulation(spec)
    assert [x.rule for x in findings] == ["SLU116"]
    assert stats["n_dot_generals"] == 1
    assert "required >= f32" in findings[0].message


def test_audit_accumulation_pinned_twin_clean():
    def g(a, b):
        return lax.dot_general(a.astype(jnp.bfloat16),
                               b.astype(jnp.bfloat16),
                               (((1,), (0,)), ((), ())),
                               preferred_element_type=jnp.float32)
    spec = trace_spec(jax.jit(g),
                      (np.ones((8, 8), np.float32),
                       np.ones((8, 8), np.float32)),
                      label="pinned", site="test")
    findings, _ = rp.audit_accumulation(spec)
    assert findings == []


def test_audit_dtypes_merges_both_rule_stats():
    f = jax.jit(lambda a, b: jnp.matmul(a, b,
                                        preferred_element_type=a.dtype))
    spec = trace_spec(f, (np.ones((4, 4)), np.ones((4, 4))),
                      label="clean", site="test")
    findings, stats = audit_dtypes(spec)
    assert findings == []
    assert stats["findings"] == 0
    assert stats["n_dot_generals"] == 1
    assert "n_converts" in stats


# --------------------------------------------------------------------------
# runtime twin: SLU_TPU_VERIFY_DTYPES=1
# --------------------------------------------------------------------------

def test_runtime_auditor_raises_before_run(fresh_dtype_auditor, tmp_path,
                                           monkeypatch):
    from superlu_dist_tpu.obs import flightrec
    monkeypatch.setenv("SLU_TPU_FLIGHTREC", str(tmp_path / "fr-%p.json"))
    flightrec._reset()
    ran = []

    def bad(x):
        ran.append(True)      # traced once; never EXECUTED by the audit
        return x.astype(jnp.bfloat16) + 1.0

    try:
        with pytest.raises(PrecisionAuditError) as ei:
            programaudit.maybe_audit("test.site", "bad", jax.jit(bad),
                                     (np.ones((8, 8), np.float32),))
        err = ei.value
        assert err.rules == ["SLU115"]
        assert err.site == "test.site" and err.program == "bad"
        assert "SLU_TPU_VERIFY_DTYPES" in str(err)
        # flight-recorder postmortem dumped at construction
        assert err.flightrec_dump and os.path.exists(err.flightrec_dump)
        doc = json.load(open(err.flightrec_dump))
        assert doc["reason"] == "PrecisionAuditError"
        # the failing program was NOT memoized as audited-clean
        aud = programaudit.get_dtype_auditor()
        assert ("test.site", "bad") not in aud.audited
        assert aud.findings and aud.findings[0].rule == "SLU115"
    finally:
        flightrec._reset()


def test_runtime_auditor_clean_program_memoized(fresh_dtype_auditor):
    from superlu_dist_tpu.obs.compilestats import COMPILE_STATS
    f = jax.jit(lambda a, b: jnp.matmul(a, b,
                                        preferred_element_type=a.dtype))
    args = (np.ones((4, 4)), np.ones((4, 4)))
    s1 = programaudit.maybe_audit("test.site", "clean", f, args)
    assert s1["findings"] == 0
    aud = programaudit.get_dtype_auditor()
    assert ("test.site", "clean") in aud.audited
    # memoized: a second submit returns the same stats, no re-trace
    s2 = aud.submit("test.site", "clean", None, None)
    assert s2 is s1
    # census note lands under the #dtypes-suffixed label, so SLU111
    # coverage accounting (programs == len(notes)) never double-counts
    assert ("test.site", "clean#dtypes") in COMPILE_STATS._audits
    blk = COMPILE_STATS.audit_block()
    assert blk["programs"] == 1 and blk["findings"] == 0


def test_dtype_off_path_allocates_nothing(monkeypatch):
    monkeypatch.delenv("SLU_TPU_VERIFY_DTYPES", raising=False)
    monkeypatch.delenv("SLU_TPU_VERIFY_PROGRAMS", raising=False)
    programaudit._reset()
    f = jax.jit(lambda x: x.astype(jnp.bfloat16) + 1.0)  # would flag
    out = programaudit.maybe_audit("test.site", "off", f,
                                   (np.ones((8, 8), np.float32),))
    assert out is None
    assert programaudit._DTYPE_AUDITOR is None
    assert programaudit.get_dtype_auditor() is None


# --------------------------------------------------------------------------
# utils/tols: the eps(dtype) x factor model
# --------------------------------------------------------------------------

def test_eps_round_trip_per_dtype():
    for dt in (np.float64, np.float32, np.float16):
        assert tols.eps(dt) == float(np.finfo(dt).eps)
        assert tols.safmin(dt) == float(np.finfo(dt).tiny)
    # complex resolves to the component float
    assert tols.eps(np.complex128) == tols.eps(np.float64)
    assert tols.eps(np.complex64) == tols.eps(np.float32)
    # the emulated double-float pair formats and the MXU input dtypes
    assert tols.eps("df64") == 2.0 ** -48
    assert tols.eps("zdf64") == 2.0 ** -48
    assert tols.eps("bfloat16") == 2.0 ** -8
    assert tols.safmin("df64") == float(np.finfo(np.float32).tiny)
    with pytest.raises(TypeError):
        tols.eps(np.int32)


def test_tolerance_carries_provenance():
    t = tols.tol("float64", 2 ** 10, "unit test")
    assert isinstance(t, float)
    assert float(t) == 1024.0 * float(np.finfo(np.float64).eps)
    assert t.factor == 1024.0 and t.dtype == "float64"
    assert "1024*eps(float64)" in t.describe()
    assert "unit test" in repr(t)


def test_berr_target_matches_the_driver_gate():
    # bitwise the 10*eps the drivers/gssvx gate used to mint by hand
    assert float(tols.berr_target(np.float64)) == \
        10.0 * float(np.finfo(np.float64).eps)
    assert float(tols.berr_target(np.float32)) == \
        10.0 * float(np.finfo(np.float32).eps)


def test_named_gates_cover_the_migrated_literals():
    # each migration loosened-or-held its literal: no gate got stricter
    # by surprise (DEVICE_VS_HOST_RTOL is deliberately ~7% tighter)
    assert float(tols.RESID_GATE) > 1e-8
    assert float(tols.RESID_GATE_TIGHT) > 1e-10
    assert float(tols.SCHEDULE_DRIFT_RTOL) > 1e-11
    assert float(tols.SCHEDULE_DRIFT_ATOL) > 1e-13
    for t in (tols.RESID_GATE, tols.RESID_GATE_TIGHT,
              tols.SCHEDULE_DRIFT_RTOL, tols.DEVICE_VS_HOST_RTOL,
              tols.ONENORMEST_SLACK):
        assert t.dtype == "float64" and t.why
        # power-of-two factors: an explicit ulp budget
        assert t.factor == 2.0 ** round(np.log2(t.factor))


# --------------------------------------------------------------------------
# ops/dense: complex operands degrade the bf16 tier (asserted, recorded)
# --------------------------------------------------------------------------

def test_resolve_gemm_tier():
    from superlu_dist_tpu.ops.dense import resolve_gemm_tier
    assert resolve_gemm_tier("bf16", "complex64") == "default"
    assert resolve_gemm_tier("bf16", "complex128") == "default"
    assert resolve_gemm_tier("bf16", "float32") == "bf16"
    assert resolve_gemm_tier("f32", "complex128") == "f32"
    assert resolve_gemm_tier("highest", "float64") == "highest"


def test_gemm_complex_bf16_degrades_to_default():
    from superlu_dist_tpu.ops.dense import gemm
    rng = np.random.default_rng(3)
    a = (rng.standard_normal((6, 6))
         + 1j * rng.standard_normal((6, 6))).astype(np.complex64)
    b = (rng.standard_normal((6, 6))
         + 1j * rng.standard_normal((6, 6))).astype(np.complex64)
    got = np.asarray(gemm(jnp.asarray(a), jnp.asarray(b), prec="bf16"))
    want = np.asarray(gemm(jnp.asarray(a), jnp.asarray(b),
                           prec="default"))
    assert got.dtype == np.complex64
    assert np.array_equal(got, want)      # same resolved tier: same bits


def test_stream_executor_records_resolved_tier(tmp_path):
    from superlu_dist_tpu.models.gallery import poisson2d
    from superlu_dist_tpu.numeric.plan import build_plan
    from superlu_dist_tpu.numeric.stream import StreamExecutor
    from superlu_dist_tpu.obs import trace
    from superlu_dist_tpu.sparse.formats import symmetrize_pattern
    from superlu_dist_tpu.symbolic.symbfact import symbolic_factorize

    a = poisson2d(6)
    sym = symmetrize_pattern(a)
    sf = symbolic_factorize(sym, np.arange(a.n_rows), relax=4,
                            max_supernode=16)
    plan = build_plan(sf)
    avals = sym.data[sf.value_perm].astype(np.complex64)

    ex = StreamExecutor(plan, "complex64", gemm_prec="bf16")
    assert ex.gemm_prec == "bf16"
    assert ex.gemm_prec_resolved == "default"   # complex degrade

    t = trace.Tracer(str(tmp_path / "s.json"))
    prev = trace.install(t)
    try:
        ex(jnp.asarray(avals), jnp.asarray(0.0))
    finally:
        trace.install(prev)
        t.close()
    events = json.load(open(tmp_path / "s.json"))["traceEvents"]
    kernels = [e for e in events if e["cat"] == "kernel"]
    assert kernels
    # every kernel span reports the tier the arithmetic actually RAN
    assert all(k["args"]["gemm_prec"] == "default" for k in kernels)
