/* Self-checking C client of the slu_tpu API (the analog of the
 * reference's EXAMPLE/f_5x5-style binding smoke tests).  Builds a
 * diagonally-dominant tridiagonal system, solves it through the one-shot
 * path and the factor/solve-factored handle path, and verifies both
 * against the fabricated solution.  Exit code 0 = PASS. */

#include "slu_tpu.h"

#include <math.h>
#include <stdio.h>
#include <stdlib.h>

int main(void) {
  const int64_t n = 50;
  int64_t* indptr = malloc((n + 1) * sizeof(int64_t));
  int64_t* indices = malloc(3 * n * sizeof(int64_t));
  double* values = malloc(3 * n * sizeof(double));
  int64_t nnz = 0;
  indptr[0] = 0;
  for (int64_t i = 0; i < n; ++i) {
    if (i > 0) { indices[nnz] = i - 1; values[nnz++] = -1.0; }
    indices[nnz] = i; values[nnz++] = 4.0;
    if (i < n - 1) { indices[nnz] = i + 1; values[nnz++] = -1.0; }
    indptr[i + 1] = nnz;
  }
  double* xt = malloc(n * sizeof(double));
  double* b = malloc(n * sizeof(double));
  double* x = malloc(n * sizeof(double));
  for (int64_t i = 0; i < n; ++i) xt[i] = 1.0 + 0.01 * (double)i;
  for (int64_t i = 0; i < n; ++i) {
    b[i] = 4.0 * xt[i];
    if (i > 0) b[i] -= xt[i - 1];
    if (i < n - 1) b[i] -= xt[i + 1];
  }

  if (slu_tpu_init("cpu") != 0) { printf("init FAIL\n"); return 1; }

  int info = slu_tpu_solve(n, nnz, indptr, indices, values, b, x, 1);
  if (info != 0) { printf("solve info=%d FAIL\n", info); return 1; }
  double err = 0.0;
  for (int64_t i = 0; i < n; ++i) err = fmax(err, fabs(x[i] - xt[i]));
  if (err > 1e-10) { printf("one-shot err=%g FAIL\n", err); return 1; }

  int64_t h = 0;
  info = slu_tpu_factor(n, nnz, indptr, indices, values, &h);
  if (info != 0) { printf("factor info=%d FAIL\n", info); return 1; }
  for (int64_t i = 0; i < n; ++i) b[i] *= 2.0;   /* new rhs, same A */
  info = slu_tpu_solve_factored(h, n, b, x, 1);
  if (info != 0) { printf("refactored solve info=%d FAIL\n", info); return 1; }
  err = 0.0;
  for (int64_t i = 0; i < n; ++i) err = fmax(err, fabs(x[i] - 2.0 * xt[i]));
  if (err > 1e-10) { printf("factored err=%g FAIL\n", err); return 1; }
  if (slu_tpu_free_handle(h) != 0) { printf("free FAIL\n"); return 1; }
  if (slu_tpu_free_handle(h) != -3) { printf("double-free FAIL\n"); return 1; }

  printf("C API PASS (err one-shot + factored <= 1e-10)\n");
  return 0;
}
