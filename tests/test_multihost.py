"""Multi-process (multi-host-shaped) mesh smoke test.

The reference's defining capability is factoring across MPI processes
(pdgstrf over a Pr×Pc process grid, SRC/pdgstrf.c:243).  The TPU-native
analog: jax.distributed joins every process's devices into one global
mesh (parallel/grid.gridinit_multihost — the superlu_gridinit-over-
world-communicator analog), and the jitted factorization runs SPMD over
it, XLA inserting the inter-process collectives the reference issues by
hand.  This exercises the real multi-process runtime (2 OS processes,
Gloo transport, 1 CPU device each), not a virtual single-process mesh.
"""

import os
import socket
import subprocess
import sys


_WORKER = r"""
import sys
pid = int(sys.argv[1]); nproc = int(sys.argv[2]); port = sys.argv[3]
from superlu_dist_tpu.parallel.mhboot import boot
jax = boot(nproc, pid, port)
import numpy as np, jax.numpy as jnp
from superlu_dist_tpu.parallel.grid import gridinit_multihost
from superlu_dist_tpu.models.gallery import poisson2d
from superlu_dist_tpu.sparse.formats import symmetrize_pattern
from superlu_dist_tpu.utils.options import Options
from superlu_dist_tpu.ordering.dispatch import get_perm_c
from superlu_dist_tpu.symbolic.symbfact import symbolic_factorize
from superlu_dist_tpu.numeric.plan import build_plan
from superlu_dist_tpu.numeric.factor import make_factor_fn

grid = gridinit_multihost(1, nproc)
assert len(jax.devices()) == nproc, jax.devices()
assert grid.mesh.devices.size == nproc

a = poisson2d(12)
sym = symmetrize_pattern(a)
col_order = get_perm_c(Options(), a, sym)
sf = symbolic_factorize(sym, col_order, relax=16, max_supernode=64)
plan = build_plan(sf, min_bucket=8, growth=1.5)
avals = jnp.asarray(sym.data[sf.value_perm], dtype="float32")
thresh = jnp.asarray(np.sqrt(np.finfo(np.float32).eps) * a.norm_max(),
                     "float32")
fn = make_factor_fn(plan, "float32", mesh=grid.mesh)
fronts, tiny = fn(avals, thresh)
jax.block_until_ready(fronts)
assert int(tiny) == 0
for lp, up in fronts:
    for s in lp.addressable_shards:
        assert np.isfinite(np.asarray(s.data)).all()
print(f"proc {pid} ok", flush=True)
"""


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def _communicate_all(procs, timeout, shm=None):
    """Collect every rank's output; on ANY failure (timeout, crash) kill
    the survivors and unlink shm leftovers so no orphan rank keeps the
    /dev/shm tree segment alive (the pddrive_grid.py discipline)."""
    import glob
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=timeout)
            outs.append(out.decode())
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        if shm is not None:
            for leftover in glob.glob(f"/dev/shm/*{shm.strip('/')}*"):
                try:
                    os.unlink(leftover)
                except OSError:
                    pass
    return outs


_PGSSVX_WORKER = r"""
import os, sys, time
pid = int(sys.argv[1]); nproc = int(sys.argv[2]); port = sys.argv[3]
shm = sys.argv[4]; ngrid = int(sys.argv[5])
from superlu_dist_tpu.parallel.mhboot import boot, attach_tree
boot(nproc, pid, port)
import numpy as np
from superlu_dist_tpu.models.gallery import poisson2d
from superlu_dist_tpu.parallel.grid import gridinit_multihost
from superlu_dist_tpu.parallel.dist import distribute_rows
from superlu_dist_tpu.parallel.pgssvx import pgssvx
from superlu_dist_tpu.utils.options import Options

def note(msg):
    # progress is observable while the harness still holds our pipe;
    # the shm token makes the path unique per run (no stale lines from
    # a previous attempt when debugging a long run)
    tag = shm.strip("/")
    with open(f"/tmp/pgx_mesh_progress_{tag}_{pid}.log", "a") as fh:
        fh.write(f"{time.strftime('%H:%M:%S')} {msg}\n")

grid = gridinit_multihost(1, nproc)
assert grid.mesh.devices.size == nproc
note("mesh up")

# block-row input: each rank keeps ONLY its rows (the NR_loc shape);
# the global build here is test scaffolding for slicing + the residual
a = poisson2d(ngrid)
n = a.n_rows
parts = distribute_rows(a, nproc)
mine = parts[pid]
xt = np.random.default_rng(3).standard_normal(n)
b = a.matvec(xt)
b_loc = b[mine.fst_row:mine.fst_row + mine.m_loc]

# wide payload slots: n~1e5 vectors would otherwise chunk ~29x per
# collective through the default 4096-length domain, and the IR loop is
# dozens of spin-waiting collectives per iteration
tc = attach_tree(shm, nproc, pid, max_len=1 << 18)

note("inputs ready")
out = {}
x, info = pgssvx(tc, Options(relax=128, max_supernode=512,
                             min_bucket=32, bucket_growth=1.3,
                             amalg_tol=1.2),
                 mine, b_loc, grid=grid, lu_out=out)
note("pgssvx returned")
st = out.get("stats")
if st is not None:
    note("utime " + " ".join(f"{k}={v:.1f}" for k, v in st.utime.items()
                             if v > 0.5))
assert info == 0, info
resid = float(np.linalg.norm(b - a.matvec(x)) / np.linalg.norm(b))
assert resid < 1e-10, resid

# the defining property: factor shards live in DIFFERENT processes —
# the biggest front spans every process's device, and this process can
# address only its own piece of it
lu = out["lu"]
fronts = lu.numeric.fronts
big_lp, _ = max(fronts, key=lambda p: p[0].size)
assert len(big_lp.sharding.device_set) == nproc, big_lp.sharding
local = sum(s.data.size for s in big_lp.addressable_shards)
assert local < big_lp.size, (local, big_lp.size)

if os.environ.get("PGX_REUSE"):
    # Fact-reuse tiers over the grid (the pddrive1/pddrive2 time-
    # stepping loops at NR_loc input, EXAMPLE/pddrive1.c):
    # 1) FACTORED — same factors, new rhs, collective solve only
    from superlu_dist_tpu.utils.options import Fact
    import dataclasses as _dc
    xt2 = np.random.default_rng(11).standard_normal(n)
    b2 = a.matvec(xt2)
    b2_loc = b2[mine.fst_row:mine.fst_row + mine.m_loc]
    x2, info2 = pgssvx(tc, Options(fact=Fact.FACTORED), mine, b2_loc,
                       grid=grid, lu=lu)
    assert info2 == 0
    r2 = float(np.linalg.norm(b2 - a.matvec(x2)) / np.linalg.norm(b2))
    assert r2 < 1e-10, r2
    note(f"factored leg ok {r2:.2e}")
    # 2) SamePattern_SameRowPerm — new values, analysis products reuse
    #    (only the root holds the reusable skeleton pieces; other
    #    ranks pass their handle, which the root-analysis tier ignores)
    mine3 = _dc.replace(mine, data=np.asarray(mine.data) * 1.7)
    a3 = a.__class__(a.n_rows, a.n_cols, a.indptr, a.indices,
                     a.data * 1.7)
    b3 = a3.matvec(xt2)
    b3_loc = b3[mine.fst_row:mine.fst_row + mine.m_loc]
    out3 = {}
    x3, info3 = pgssvx(tc, Options(fact=Fact.SamePattern_SameRowPerm,
                                   relax=128, max_supernode=512,
                                   min_bucket=32, bucket_growth=1.3,
                                   amalg_tol=1.2),
                       mine3, b3_loc, grid=grid, lu=lu, lu_out=out3)
    assert info3 == 0
    r3 = float(np.linalg.norm(b3 - a3.matvec(x3)) / np.linalg.norm(b3))
    assert r3 < 1e-10, r3
    st3 = out3["stats"]
    if pid == 0:
        # the reuse contract: symbolic + plan phases drop to ~0
        assert st3.utime.get("SYMBFACT", 0) < 0.05, st3.utime
        assert st3.utime.get("DIST", 0) < 0.05, st3.utime
    note(f"samepattern leg ok {r3:.2e}")

tc.close(unlink=pid == 0)
print(f"proc {pid} pgssvx-mesh ok n={n} resid={resid:.2e}", flush=True)
"""


def _run_pgssvx_mesh(tmp_path, nproc, ngrid, timeout, extra_env=None):
    port = _free_port()
    script = tmp_path / "pgx_mesh_worker.py"
    script.write_text(_PGSSVX_WORKER)
    env = dict(os.environ, PYTHONPATH=os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    env.pop("XLA_FLAGS", None)
    env.update(extra_env or {})
    shm = f"/slu_mhpgx_{os.getpid()}"
    procs = [subprocess.Popen(
        [sys.executable, str(script), str(i), str(nproc), str(port),
         shm, str(ngrid)],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
        for i in range(nproc)]
    outs = _communicate_all(procs, timeout, shm=shm)
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"proc {i} failed:\n{out[-4000:]}"
        assert f"proc {i} pgssvx-mesh ok" in out


def test_pgssvx_mesh_par_symb_fact(tmp_path):
    """Distributed-factors tier WITH distributed analysis (ParSymbFact):
    ordering + symbolic partition across the 4 ranks (panalysis.py, the
    get_perm_c_parmetis + psymbfact shape) and the factors still come
    out sharded, solve to 1e-10, through the same driver surface."""
    _run_pgssvx_mesh(tmp_path, nproc=4, ngrid=24, timeout=900,
                     extra_env={"SLU_TPU_PAR_SYMB_FACT": "1"})


def test_pgssvx_mesh_reuse_tiers(tmp_path):
    """Fact reuse over the distributed-factors tier: FACTORED re-solves
    on the existing sharded factors; SamePattern_SameRowPerm refactors
    new values with SYMBFACT+DIST ~ 0 (the reference's pddrive1/2
    time-stepping loops at NR_loc input)."""
    _run_pgssvx_mesh(tmp_path, nproc=2, ngrid=24, timeout=900,
                     extra_env={"PGX_REUSE": "1"})


def test_pgssvx_mesh_two_processes_small(tmp_path):
    """Plumbing check at toy size: distributed-factors pgssvx over a
    2-process mesh — factor sharded across processes, collective device
    solve, distributed IR, residual at reference accuracy."""
    _run_pgssvx_mesh(tmp_path, nproc=2, ngrid=24, timeout=600)


_PGSSVX_SURFACE_WORKER = r"""
import sys
pid = int(sys.argv[1]); nproc = int(sys.argv[2]); port = sys.argv[3]
shm = sys.argv[4]
from superlu_dist_tpu.parallel.mhboot import boot, attach_tree
boot(nproc, pid, port)
import numpy as np
from superlu_dist_tpu.models.gallery import poisson2d
from superlu_dist_tpu.sparse.formats import SparseCSR
from superlu_dist_tpu.parallel.grid import gridinit_multihost
from superlu_dist_tpu.parallel.dist import distribute_rows
from superlu_dist_tpu.parallel.pgssvx import pgssvx
from superlu_dist_tpu.utils.options import Options, Trans

grid = gridinit_multihost(1, nproc)
a = poisson2d(16)
n = a.n_rows
tc = attach_tree(shm, nproc, pid, max_len=1 << 16)

rng = np.random.default_rng(7)
parts = distribute_rows(a, nproc)
mine = parts[pid]

# (a) multiple right-hand sides
xt = rng.standard_normal((n, 3))
b = np.stack([a.matvec(xt[:, j]) for j in range(3)], axis=1)
x, info = pgssvx(tc, Options(), mine,
                 b[mine.fst_row:mine.fst_row + mine.m_loc], grid=grid)
assert info == 0 and x.shape == (n, 3)
for j in range(3):
    r = np.linalg.norm(b[:, j] - a.matvec(x[:, j])) / np.linalg.norm(b[:, j])
    assert r < 1e-10, (j, r)

# (b) transpose solve through the same distributed pipeline — on a
# NONSYMMETRIC operator (poisson2d is symmetric, which would make a
# trans-ignoring implementation pass vacuously): scale the strictly
# upper triangle so A != A^T
rows = np.repeat(np.arange(n), np.diff(a.indptr))
nd = a.data.copy()
nd[a.indices > rows] *= 1.7
ans = SparseCSR(n, n, a.indptr, a.indices, nd)
nparts = distribute_rows(ans, nproc)
bt = ans.transpose().matvec(xt[:, 0])
xT, info = pgssvx(tc, Options(trans=Trans.TRANS), nparts[pid],
                  bt[mine.fst_row:mine.fst_row + mine.m_loc], grid=grid)
rT = (np.linalg.norm(bt - ans.transpose().matvec(xT))
      / np.linalg.norm(bt))
assert info == 0 and rT < 1e-10, rT

# (c) complex (the pzgssvx twin): off-diagonals rotated into the plane
cdata = a.data.astype(np.complex128)
cdata[rows != a.indices] *= (0.8 + 0.6j)
ac = SparseCSR(n, n, a.indptr, a.indices, cdata)
cparts = distribute_rows(ac, nproc)
bc = ac.matvec(xt[:, 1].astype(np.complex128))
xc, info = pgssvx(tc, Options(), cparts[pid],
                  bc[mine.fst_row:mine.fst_row + mine.m_loc], grid=grid)
rc = np.linalg.norm(bc - ac.matvec(xc)) / np.linalg.norm(bc)
assert info == 0 and rc < 1e-10, rc

tc.close(unlink=pid == 0)
print(f"proc {pid} surface ok nrhs={x.shape} rT={rT:.2e} rc={rc:.2e}",
      flush=True)
"""


def test_pgssvx_mesh_driver_surface(tmp_path):
    """The reference pdgssvx driver surface on the DISTRIBUTED-FACTORS
    tier: nrhs>1, transpose solves, and the complex twin all ride the
    mesh-sharded factorization + collective solve (2 processes)."""
    port = _free_port()
    script = tmp_path / "pgx_surface_worker.py"
    script.write_text(_PGSSVX_SURFACE_WORKER)
    env = dict(os.environ, PYTHONPATH=os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    env.pop("XLA_FLAGS", None)
    shm = f"/slu_mhsurf_{os.getpid()}"
    procs = [subprocess.Popen(
        [sys.executable, str(script), str(i), "2", str(port), shm],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
        for i in range(2)]
    outs = _communicate_all(procs, 1200, shm=shm)
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"proc {i} failed:\n{out[-4000:]}"
        assert f"proc {i} surface ok" in out


def test_pgssvx_mesh_four_processes_n100k(tmp_path):
    """The VERDICT-r3 'done' bar: 4 processes, n >= 1e5 (poisson2d(340)
    -> n=115,600), factor shards living in different processes, solve +
    distributed refinement, residual <= 1e-10.  Compile-dominated on a
    1-core box (4 ranks x the same fused SPMD program; the persistent
    compile cache makes reruns fast) — budget accordingly."""
    _run_pgssvx_mesh(tmp_path, nproc=4, ngrid=340, timeout=5400)


def test_multihost_factorization_two_processes(tmp_path):
    # self-bounded via communicate(timeout=540) — pytest-timeout is not
    # available in this environment
    port = _free_port()
    script = tmp_path / "mh_worker.py"
    script.write_text(_WORKER)
    env = dict(os.environ, PYTHONPATH=os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    # the suite's conftest forces an 8-device virtual host platform; this
    # test wants the REAL multi-process topology (1 device per process)
    env.pop("XLA_FLAGS", None)
    procs = [subprocess.Popen(
        [sys.executable, str(script), str(i), "2", str(port)],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
        for i in range(2)]
    outs = _communicate_all(procs, 540)
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"proc {i} failed:\n{out}"
        assert f"proc {i} ok" in out


import pytest  # noqa: E402

# slow tier: multi-process / native-build / at-scale — fast CI runs -m "not slow"
pytestmark = pytest.mark.slow
