"""Multi-process iterative refinement (pdgsrfs/pdgsmv analog).

Four real processes each own a block row of A (NRformat_loc analog) and
refine collectively through the shared-memory tree collectives — the
reference's shape: distributed residual, factor-owner correction solves.
"""

import multiprocessing as mp
import os

import numpy as np
import pytest

from superlu_dist_tpu import native

pytestmark = pytest.mark.skipif(not native.available(),
                                reason="native library unavailable")


def _worker(name, n_ranks, rank, part, b_loc, q):
    from superlu_dist_tpu.parallel.treecomm import TreeComm
    from superlu_dist_tpu.parallel.pgsrfs import pgsrfs
    with TreeComm(name, n_ranks, rank, max_len=part.n,
                  create=False) as tc:
        x = pgsrfs(tc, part, b_loc, None, None, root=0)
        q.put((rank, x))


def test_pgsrfs_four_processes_matches_serial():
    import jax
    jax.config.update("jax_platforms", "cpu")
    import superlu_dist_tpu as slu
    from superlu_dist_tpu.models.gallery import poisson2d
    from superlu_dist_tpu.parallel.dist import distribute_rows
    from superlu_dist_tpu.parallel.treecomm import TreeComm
    from superlu_dist_tpu.parallel.pgsrfs import pgsrfs
    from superlu_dist_tpu.utils.options import IterRefine

    a = poisson2d(12)
    n = a.n_rows
    xtrue = np.random.default_rng(0).standard_normal(n)
    b = a.matvec(xtrue)

    # factor WITHOUT refinement on the "root"; the distributed IR must
    # supply the refinement (deliberately coarse f32 factors so the IR
    # has real work to do)
    opts = slu.Options(iter_refine=IterRefine.NOREFINE,
                       factor_dtype="float32")
    x0, lu, stats, info = slu.gssvx(opts, a, b)
    assert info == 0
    coarse = float(np.linalg.norm(b - a.matvec(x0)) / np.linalg.norm(b))

    nranks = 4
    parts = distribute_rows(a, nranks)
    b_blocks = [b[p.fst_row:p.fst_row + p.m_loc] for p in parts]

    name = f"/slu_pgsrfs_{os.getpid()}"
    owner = TreeComm(name, nranks, 0, max_len=n, create=True)
    try:
        ctx = mp.get_context("fork")
        q = ctx.Queue()
        procs = [ctx.Process(target=_worker,
                             args=(name, nranks, r, parts[r], b_blocks[r], q))
                 for r in range(1, nranks)]
        for p in procs:
            p.start()
        x = pgsrfs(owner, parts[0], b_blocks[0], x0, lu.solve_factored,
                   root=0)
        others = [q.get(timeout=120) for _ in procs]
        for p in procs:
            p.join(timeout=120)
            assert p.exitcode == 0
    finally:
        owner.close(unlink=True)

    refined = float(np.linalg.norm(b - a.matvec(x)) / np.linalg.norm(b))
    assert refined < 1e-13, (coarse, refined)
    assert refined < coarse / 10 or coarse < 1e-13
    # every rank converged to the same solution
    for rank, xr in others:
        np.testing.assert_allclose(xr, x, rtol=0, atol=1e-12)


# slow tier: forks multi-process workers (mp fork under multithreaded jax)
pytestmark = pytest.mark.slow
