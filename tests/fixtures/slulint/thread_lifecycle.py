"""SLU110 true-positive fixture: a daemon started in __init__ before
its dependency exists, never joined, plus an event nothing ever waits
on."""
import threading


class Daemon:
    def __init__(self):
        self._stop = threading.Event()
        self._unused = threading.Event()
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()
        self._interval = 0.5

    def _loop(self):
        while not self._stop.wait(self._interval):
            pass

    def close(self):
        self._stop.set()
        self._unused.set()
