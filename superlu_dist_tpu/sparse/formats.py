"""Sparse matrix storage formats (host side, numpy).

Analog of the reference's SuperMatrix storage types (SRC/supermatrix.h):
``NCformat`` (compressed column) -> :class:`SparseCSC`, ``NRformat``
(compressed row) -> :class:`SparseCSR`.  The distributed row-block format
``NRformat_loc`` (supermatrix.h:175-188) is in
``superlu_dist_tpu.parallel.dist``.

scipy is deliberately not a dependency; conversions are implemented with
numpy counting sorts.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from superlu_dist_tpu.utils.options import env_flag

# analog of int_t (superlu_defs.h:80-93): the reference's XSDK_INDEX_SIZE=64
# build switches every index to 64-bit; here SLU_TPU_INT64=1 does.  Pattern
# INDICES only — each one is bounded by n.  Anything that ACCUMULATES
# (indptr prefix sums, nnz totals) is unconditionally int64 via
# counts_to_indptr: nnz(A) exceeds int32 long before n does, and an
# int32 indptr wraps silently (slulint SLU103 enforces this split).
INT = np.int64 if env_flag("SLU_TPU_INT64") else np.int32


def counts_to_indptr(counts: np.ndarray) -> np.ndarray:
    """(n,) or (n+1,) leading-zero per-row/col counts -> int64 indptr.

    The one prefix-sum accumulator for every CSR/CSC build: int64
    regardless of the INT index selection, so nnz > 2^31 structures keep
    exact offsets even in the default int32-index build (the regression
    tests/test_formats.py::test_counts_to_indptr_past_int32 constructs
    the wrap the old dtype=INT cumsum produced)."""
    return np.cumsum(np.asarray(counts), dtype=np.int64)


def _aggregate_coo(n_rows, n_cols, rows, cols, vals):
    """Sum duplicate (row, col) entries; return sorted-by-(major) arrays."""
    rows = np.asarray(rows, dtype=np.int64)
    cols = np.asarray(cols, dtype=np.int64)
    vals = np.asarray(vals)
    if rows.size == 0:
        return rows.astype(INT), cols.astype(INT), vals
    key = rows * n_cols + cols
    order = np.argsort(key, kind="stable")
    key, rows, cols, vals = key[order], rows[order], cols[order], vals[order]
    uniq_mask = np.empty(key.shape, dtype=bool)
    uniq_mask[0] = True
    np.not_equal(key[1:], key[:-1], out=uniq_mask[1:])
    group = np.cumsum(uniq_mask) - 1
    out_vals = np.zeros(int(group[-1]) + 1, dtype=vals.dtype)
    np.add.at(out_vals, group, vals)
    return rows[uniq_mask].astype(INT), cols[uniq_mask].astype(INT), out_vals


@dataclasses.dataclass
class SparseCSR:
    """Compressed sparse row (reference NRformat / NRformat_loc local part)."""

    n_rows: int
    n_cols: int
    indptr: np.ndarray   # (n_rows+1,)
    indices: np.ndarray  # column indices, sorted within each row
    data: np.ndarray

    @property
    def nnz(self) -> int:
        return int(self.indptr[-1])

    @property
    def shape(self):
        return (self.n_rows, self.n_cols)

    @property
    def dtype(self):
        return self.data.dtype

    def to_dense(self) -> np.ndarray:
        out = np.zeros((self.n_rows, self.n_cols), dtype=self.data.dtype)
        rows = np.repeat(np.arange(self.n_rows), np.diff(self.indptr))
        out[rows, self.indices] = self.data
        return out

    def matvec(self, x: np.ndarray) -> np.ndarray:
        """y = A @ x (supports (n,) and (n, k)).  Host SpMV — the analog of
        pdgsmv (SRC/pdgsmv.c:234) used by iterative refinement."""
        x = np.asarray(x)
        rows = np.repeat(np.arange(self.n_rows), np.diff(self.indptr))
        contrib = (self.data[:, None] * x[self.indices].reshape(len(self.indices), -1)
                   if x.ndim > 1 else self.data * x[self.indices])
        out_shape = (self.n_rows,) + x.shape[1:]
        out = np.zeros((self.n_rows,) + ((contrib.shape[1],) if x.ndim > 1 else ()),
                       dtype=np.result_type(self.data, x))
        np.add.at(out, rows, contrib)
        return out.reshape(out_shape)

    def abs_matvec(self, x: np.ndarray) -> np.ndarray:
        """|A| @ x, used for the backward-error bound (pdgsrfs.c:213-231)."""
        rows = np.repeat(np.arange(self.n_rows), np.diff(self.indptr))
        out = np.zeros(self.n_rows, dtype=np.result_type(self.data.real, x))
        np.add.at(out, rows, np.abs(self.data) * x[self.indices])
        return out

    def tocsc(self) -> "SparseCSC":
        rows = np.repeat(np.arange(self.n_rows), np.diff(self.indptr)).astype(np.int64)
        return coo_to_csc(self.n_rows, self.n_cols, rows, self.indices, self.data,
                          aggregate=False)

    def transpose(self) -> "SparseCSR":
        c = self.tocsc()
        return SparseCSR(self.n_cols, self.n_rows, c.indptr, c.indices, c.data)

    def row_scale(self, r: np.ndarray) -> "SparseCSR":
        rows = np.repeat(np.arange(self.n_rows), np.diff(self.indptr))
        return SparseCSR(self.n_rows, self.n_cols, self.indptr, self.indices,
                         self.data * np.asarray(r)[rows])

    def col_scale(self, c: np.ndarray) -> "SparseCSR":
        return SparseCSR(self.n_rows, self.n_cols, self.indptr, self.indices,
                         self.data * np.asarray(c)[self.indices])

    def permute(self, perm_r=None, perm_c=None) -> "SparseCSR":
        """Return A[perm_r, :][:, perm_c] (rows/cols of the result are the
        listed rows/cols of self)."""
        rows = np.repeat(np.arange(self.n_rows), np.diff(self.indptr)).astype(np.int64)
        cols = self.indices.astype(np.int64)
        if perm_r is not None:
            inv_r = invert_perm(perm_r)
            rows = inv_r[rows]
        if perm_c is not None:
            inv_c = invert_perm(perm_c)
            cols = inv_c[cols]
        return coo_to_csr(self.n_rows, self.n_cols, rows, cols, self.data,
                          aggregate=False)

    def norm_inf(self) -> float:
        """max row sum of |A| — 'I' norm of pdlangs (SRC/pdlangs.c)."""
        rows = np.repeat(np.arange(self.n_rows), np.diff(self.indptr))
        sums = np.zeros(self.n_rows, dtype=np.float64)
        np.add.at(sums, rows, np.abs(self.data))
        return float(sums.max(initial=0.0))

    def norm_1(self) -> float:
        """max col sum of |A| — '1' norm of pdlangs."""
        sums = np.zeros(self.n_cols, dtype=np.float64)
        np.add.at(sums, self.indices, np.abs(self.data))
        return float(sums.max(initial=0.0))

    def norm_max(self) -> float:
        return float(np.abs(self.data).max(initial=0.0))


@dataclasses.dataclass
class SparseCSC:
    """Compressed sparse column (reference NCformat, supermatrix.h)."""

    n_rows: int
    n_cols: int
    indptr: np.ndarray   # (n_cols+1,)
    indices: np.ndarray  # row indices, sorted within each column
    data: np.ndarray

    @property
    def nnz(self) -> int:
        return int(self.indptr[-1])

    @property
    def shape(self):
        return (self.n_rows, self.n_cols)

    @property
    def dtype(self):
        return self.data.dtype

    def to_dense(self) -> np.ndarray:
        out = np.zeros((self.n_rows, self.n_cols), dtype=self.data.dtype)
        cols = np.repeat(np.arange(self.n_cols), np.diff(self.indptr))
        out[self.indices, cols] = self.data
        return out

    def tocsr(self) -> SparseCSR:
        cols = np.repeat(np.arange(self.n_cols), np.diff(self.indptr)).astype(np.int64)
        return coo_to_csr(self.n_rows, self.n_cols, self.indices, cols, self.data,
                          aggregate=False)


def invert_perm(perm: np.ndarray) -> np.ndarray:
    perm = np.asarray(perm)
    inv = np.empty_like(perm)
    inv[perm] = np.arange(len(perm), dtype=perm.dtype)
    return inv


def coo_to_csr(n_rows, n_cols, rows, cols, vals, aggregate=True) -> SparseCSR:
    if aggregate:
        rows, cols, vals = _aggregate_coo(n_rows, n_cols, rows, cols, vals)
    else:
        rows = np.asarray(rows, dtype=np.int64)
        cols = np.asarray(cols, dtype=np.int64)
        vals = np.asarray(vals)
        key = rows * n_cols + cols
        order = np.argsort(key, kind="stable")
        rows, cols, vals = rows[order], cols[order], vals[order]
    counts = np.zeros(n_rows + 1, dtype=np.int64)
    np.add.at(counts, rows + 1, 1)
    return SparseCSR(int(n_rows), int(n_cols), counts_to_indptr(counts),
                     cols.astype(INT), vals)


def coo_to_csc(n_rows, n_cols, rows, cols, vals, aggregate=True) -> SparseCSC:
    if aggregate:
        rows, cols, vals = _aggregate_coo(n_rows, n_cols, rows, cols, vals)
    rows = np.asarray(rows, dtype=np.int64)
    cols = np.asarray(cols, dtype=np.int64)
    vals = np.asarray(vals)
    key = cols * n_rows + rows
    order = np.argsort(key, kind="stable")
    rows, cols, vals = rows[order], cols[order], vals[order]
    counts = np.zeros(n_cols + 1, dtype=np.int64)
    np.add.at(counts, cols + 1, 1)
    return SparseCSC(int(n_rows), int(n_cols), counts_to_indptr(counts),
                     rows.astype(INT), vals)


def symmetrize_pattern(a: SparseCSR) -> SparseCSR:
    """Pattern of A + Aᵀ with A's values (explicit zeros where only Aᵀ has an
    entry).

    Analog of at_plus_a_dist (SRC/get_perm_c.c:301), which the reference uses
    to build the graph for fill-reducing orderings.  We additionally *factor*
    on this symmetrized pattern: with static pivoting (GESP) the LU fill of a
    structurally-symmetric pattern equals the Cholesky fill of that pattern,
    which makes the symbolic phase and the multifrontal batching exact.
    """
    n = a.n_rows
    assert n == a.n_cols, "symmetrize_pattern requires a square matrix"
    rows = np.repeat(np.arange(n), np.diff(a.indptr)).astype(np.int64)
    cols = a.indices.astype(np.int64)
    all_rows = np.concatenate([rows, cols])
    all_cols = np.concatenate([cols, rows])
    all_vals = np.concatenate([a.data, np.zeros(len(rows), dtype=a.data.dtype)])
    # _aggregate_coo sums duplicates; transpose-added zeros do not perturb
    # values, and diagonal duplicates collapse (0 added once per mirror).
    return coo_to_csr(n, n, all_rows, all_cols, all_vals)
