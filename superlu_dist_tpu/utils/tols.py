"""Central dtype-aware tolerance model — eps(dtype)×factor with provenance.

Every numerical threshold in the package and its CI gates — BERR
acceptance targets, residual gates, equivalence tolerances, convergence
tests — silently encodes a dtype assumption: ``berr < 1e-6`` is "about
4.5e9 ulps of f64" and "under half an ulp of bf16" at once.  slulint
SLU118 therefore bans ad-hoc float comparison literals in package code
and CI gates; this module is the one place a threshold may be minted.

A :class:`Tolerance` IS a float (drop-in in comparisons and
``assert_allclose`` kwargs) that additionally carries its derivation —
the dtype whose eps it scales, the factor, and a one-line ``why`` — so a
failing gate can render *what the threshold meant*, not just its value.

``eps`` understands the emulated double-float dtypes (``df64``/``zdf64``
are (hi, lo) f32 pairs with a ~48-bit significand, ops/df64.py) and the
16-bit MXU input dtypes alongside everything ``np.finfo`` knows.
"""

from __future__ import annotations

import numpy as np

#: unit roundoffs numpy cannot (or may not) resolve by itself: the
#: double-float pair formats (value = hi + lo, |lo| <= ulp(hi)/2 gives
#: ~2·24 significand bits) and the 16-bit float inputs of the MXU
#: (resolved here so ``eps("bfloat16")`` needs no ml_dtypes import).
_SPECIAL_EPS = {
    "df64": float(2.0 ** -48),
    "zdf64": float(2.0 ** -48),
    "bfloat16": float(2.0 ** -8),
    "float16": float(2.0 ** -10),
}

#: smallest normal of the CARRIER format (underflow guards): the
#: double-float hi word is an f32, so df64 denormalizes where f32 does.
_SPECIAL_TINY = {
    "df64": float(np.finfo(np.float32).tiny),
    "zdf64": float(np.finfo(np.float32).tiny),
}


def _canon(dtype) -> tuple:
    """(name, numpy dtype or None) — complex dtypes resolve to their
    component float (a complex tolerance bounds each component)."""
    if isinstance(dtype, str) and dtype.strip().lower() in _SPECIAL_EPS:
        return dtype.strip().lower(), None
    dt = np.dtype(dtype)
    if dt.kind == "c":
        dt = np.dtype(f"float{dt.itemsize * 4}")
    return dt.name, dt


def eps(dtype) -> float:
    """Unit roundoff of ``dtype``: ``np.finfo(...).eps`` for the float
    and complex dtypes numpy resolves, with ``df64``/``zdf64`` (~2^-48,
    the paired-f32 significand) and the 16-bit floats special-cased."""
    name, dt = _canon(dtype)
    if name in _SPECIAL_EPS:
        return _SPECIAL_EPS[name]
    if dt is None or dt.kind != "f":
        raise TypeError(f"eps() needs a float/complex dtype, got {dtype!r}")
    return float(np.finfo(dt).eps)


def safmin(dtype) -> float:
    """Smallest normal ("safe minimum", the reference's ``dmach('S')``)
    of ``dtype``'s carrier format — the underflow-guard companion of
    :func:`eps` (componentwise-BERR denominators, refine/ir.py)."""
    name, dt = _canon(dtype)
    if name in _SPECIAL_TINY:
        return _SPECIAL_TINY[name]
    if dt is None or dt.kind != "f":
        raise TypeError(
            f"safmin() needs a float/complex dtype, got {dtype!r}")
    return float(np.finfo(dt).tiny)


class Tolerance(float):
    """A float threshold that remembers its derivation.

    Behaves exactly like its value in comparisons and arithmetic;
    ``.dtype``/``.factor``/``.why`` carry the provenance and
    :meth:`describe` renders it for gate diagnostics."""

    __slots__ = ("dtype", "factor", "why")

    def __new__(cls, value, dtype: str, factor: float, why: str = ""):
        self = super().__new__(cls, value)
        self.dtype = str(dtype)
        self.factor = float(factor)
        self.why = str(why)
        return self

    def describe(self) -> str:
        out = f"{float(self):.3e} = {self.factor:g}*eps({self.dtype})"
        if self.why:
            out += f" [{self.why}]"
        return out

    def __repr__(self) -> str:  # failing asserts render the derivation
        return f"Tolerance({self.describe()})"


def tol(dtype, factor: float, why: str = "") -> Tolerance:
    """``factor × eps(dtype)`` as a provenance-carrying float.  Factors
    are the honest part of a threshold — prefer powers of two (an ulp
    budget), and say *why* in ``why``."""
    name, _ = _canon(dtype)
    return Tolerance(eps(dtype) * float(factor), name, factor, why)


def berr_target(dtype, factor: float = 10.0) -> Tolerance:
    """The componentwise-BERR acceptance target of the escalation ladder
    and the serving gate: ``10·eps`` of the residual dtype — the
    classical IR convergence bound (pdgsrfs stops at eps; one order of
    headroom keeps the gate off the stagnation boundary)."""
    return tol(dtype, factor,
               "componentwise-BERR acceptance (IR converges to ~eps of "
               "the residual dtype; 10x is the ladder's headroom)")


# --- named gate tolerances --------------------------------------------------
# The CI gates share these so a gate and the ladder can never disagree
# about what "f64-tight" means.  Factors are powers of two: an explicit
# ulp budget, not a decimal that happens to pass today.

#: cross-schedule solve drift: batch membership reorders lsum
#: scatter-adds, so schedules agree to a small multiple of eps — not
#: bitwise (docs/SERVING.md; was the hand-typed 1e-11/1e-13 pair)
SCHEDULE_DRIFT_RTOL = tol("float64", 2 ** 16,
                          "cross-schedule lsum reassociation budget")
SCHEDULE_DRIFT_ATOL = tol("float64", 2 ** 9,
                          "cross-schedule absolute floor")

#: device batched solve vs the scipy-grade host loop: blocked TRSM +
#: padded batching against sequential host sweeps (was 1e-9/1e-11)
DEVICE_VS_HOST_RTOL = tol("float64", 2 ** 22,
                          "device blocked-TRSM vs host supernodal solve")
DEVICE_VS_HOST_ATOL = tol("float64", 2 ** 16,
                          "device-vs-host absolute floor")

#: residual gate of the smoke drivers/CLI (`‖Ax−b‖/((‖A‖‖x‖+‖b‖)n)`
#: style scaled residuals on well-conditioned gallery matrices; was the
#: hand-typed 1e-8 / 1e-10 pair scattered across scripts)
RESID_GATE = tol("float64", 2 ** 26, "scaled-residual smoke gate")
RESID_GATE_TIGHT = tol("float64", 2 ** 19,
                       "scaled-residual gate, well-conditioned gallery")

#: Hager–Higham subgradient convergence test (refine/condest.onenormest,
#: dlacon.f:130 uses a tiny relative slack; was the hand-typed 1e-12)
ONENORMEST_SLACK = tol("float64", 2 ** 12,
                       "onenormest subgradient convergence slack")
