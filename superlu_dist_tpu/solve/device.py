"""Device-resident supernodal triangular solves.

Analog of pdgstrs (SRC/pdgstrs.c:838) + the lsum kernels
(SRC/pdgstrs_lsum.c:413,1360): forward solve L·y = d walking the supernode
levels bottom-up, backward solve U·x = y walking them top-down.  Where the
reference runs an MPI event loop over per-supernode broadcast/reduce trees
with OpenMP-task lsum updates, here each (level, bucket) group is one
batched kernel: gather RHS segments, a vmapped triangular solve on the
MXU, and a scatter-add of the L21·y (resp. U12·x) contributions — the
lsum vector lives in device HBM, playing the role of the reference's
distributed lsum buffers.

Factors never leave the device (the reference's analog: factors stay in
each rank's memory between pdgstrf and pdgstrs); only the right-hand side
(n·nrhs) crosses the host boundary.  Like the factorization executors, one
kernel compiles per distinct (batch, m, w, u, nrhs) bucket and is cached
persistently.
"""

from __future__ import annotations

import functools
import time

import numpy as np
import jax
import jax.numpy as jnp

from superlu_dist_tpu.numeric.factor import NumericFactorization
from superlu_dist_tpu.obs.compilestats import COMPILE_STATS
from superlu_dist_tpu.obs.trace import get_tracer


def _sweep_kernel_builds() -> int:
    """Total jitted-closure builds across the solve kernel factories —
    the compile-census marker for one solve's sweeps (a fresh closure's
    first invocation compiles synchronously inside the sweep)."""
    return (_fwd_kernel.cache_info().misses
            + _bwd_kernel.cache_info().misses
            + _fwd_trans_kernel.cache_info().misses
            + _bwd_trans_kernel.cache_info().misses
            + _diag_inv_kernel.cache_info().misses)


def _bucket_nrhs(k: int) -> int:
    return 1 if k == 1 else 1 << int(np.ceil(np.log2(k)))


def _fwd_body(lpanel, x, lsum, first, rows, ws, w, u, n, use_inv, linv):
    """x[cols] <- L11⁻¹(x[cols] − lsum[cols]); lsum[rows] += L21·x[cols].

    With use_inv, L11⁻¹ arrives precomputed and the triangular solve
    becomes one batched GEMM (the reference's DiagInv fast path,
    pdgstrs.c:1252-1396: dense X(k) = Linv(k)·b via dgemm)."""
    k = jnp.arange(w)
    # padded pivot columns (k >= ws) would alias the NEXT supernode's
    # entries — clamp them to the dump row n-1 (factor cols/rows there
    # are exactly identity/zero, so the garbage never reaches real x)
    cols = jnp.where(k[None, :] < ws[:, None],
                     first[:, None] + k, n - 1)      # (B, w)
    rhs = (x.at[cols].get(mode="fill", fill_value=0)
           - lsum.at[cols].get(mode="fill", fill_value=0))
    if use_inv:
        y = jnp.matmul(linv, rhs, precision=jax.lax.Precision.HIGHEST)
    else:
        l11 = lpanel[:, :w, :w]
        y = jax.vmap(lambda l, b: jax.scipy.linalg.solve_triangular(
            l, b, lower=True, unit_diagonal=True))(l11, rhs)
    x = x.at[cols].set(y, mode="drop")
    if u:
        contrib = jnp.matmul(lpanel[:, w:, :], y,
                             precision=jax.lax.Precision.HIGHEST)
        lsum = lsum.at[rows].add(contrib, mode="drop")
    return x, lsum


def _bwd_body(lpanel, upanel, x, first, rows, ws, w, u, n, use_inv, uinv):
    """x[cols] <- U11⁻¹(x[cols] − U12·x[rows])."""
    k = jnp.arange(w)
    cols = jnp.where(k[None, :] < ws[:, None],
                     first[:, None] + k, n - 1)
    rhs = x.at[cols].get(mode="fill", fill_value=0)
    if u:
        xr = x.at[rows].get(mode="fill", fill_value=0)   # (B, u, nrhs)
        rhs = rhs - jnp.matmul(upanel, xr,
                               precision=jax.lax.Precision.HIGHEST)
    if use_inv:
        y = jnp.matmul(uinv, rhs, precision=jax.lax.Precision.HIGHEST)
    else:
        u11 = lpanel[:, :w, :w]
        y = jax.vmap(lambda r, b: jax.scipy.linalg.solve_triangular(
            r, b, lower=False))(u11, rhs)
    return x.at[cols].set(y, mode="drop")


def _fwd_body_trans(lpanel, upanel, x, lsum, first, rows, ws, w, u, n,
                    conj):
    """Transpose forward sweep: x[cols] <- U11⁻ᵀ(x[cols] − lsum[cols]);
    lsum[rows] += U12ᵀ·x[cols].  Mᵀ = UᵀLᵀ, so Uᵀ (lower) leads — the
    trans_t path through the same factors (superlu_defs.h:628-657)."""
    k = jnp.arange(w)
    cols = jnp.where(k[None, :] < ws[:, None],
                     first[:, None] + k, n - 1)
    rhs = (x.at[cols].get(mode="fill", fill_value=0)
           - lsum.at[cols].get(mode="fill", fill_value=0))
    u11 = lpanel[:, :w, :w]
    if conj:
        u11 = u11.conj()
    y = jax.vmap(lambda r, b: jax.scipy.linalg.solve_triangular(
        r, b, trans=1, lower=False))(u11, rhs)
    x = x.at[cols].set(y, mode="drop")
    if u:
        u12 = upanel.conj() if conj else upanel       # (B, w, u)
        contrib = jnp.matmul(jnp.swapaxes(u12, 1, 2), y,
                             precision=jax.lax.Precision.HIGHEST)
        lsum = lsum.at[rows].add(contrib, mode="drop")
    return x, lsum


def _bwd_body_trans(lpanel, x, first, rows, ws, w, u, n, conj):
    """Transpose backward sweep: x[cols] <- L11⁻ᵀ(x[cols] − L21ᵀ·x[rows])."""
    k = jnp.arange(w)
    cols = jnp.where(k[None, :] < ws[:, None],
                     first[:, None] + k, n - 1)
    rhs = x.at[cols].get(mode="fill", fill_value=0)
    if u:
        xr = x.at[rows].get(mode="fill", fill_value=0)
        l21 = lpanel[:, w:, :]                         # (B, u_pad, w)
        if conj:
            l21 = l21.conj()
        rhs = rhs - jnp.matmul(jnp.swapaxes(l21, 1, 2), xr,
                               precision=jax.lax.Precision.HIGHEST)
    l11 = lpanel[:, :w, :w]
    if conj:
        l11 = l11.conj()
    y = jax.vmap(lambda l, b: jax.scipy.linalg.solve_triangular(
        l, b, trans=1, lower=True, unit_diagonal=True))(l11, rhs)
    return x.at[cols].set(y, mode="drop")


@functools.lru_cache(maxsize=None)
def _fwd_kernel(batch, m, w, u, nrhs, n, dtype, use_inv=False):
    def step(lpanel, x, lsum, first, rows, ws, linv=None):
        return _fwd_body(lpanel, x, lsum, first, rows, ws, w, u, n,
                         use_inv, linv)

    return jax.jit(step, donate_argnums=(1, 2))


@functools.lru_cache(maxsize=None)
def _bwd_kernel(batch, m, w, u, nrhs, n, dtype, use_inv=False):
    def step(lpanel, upanel, x, first, rows, ws, uinv=None):
        return _bwd_body(lpanel, upanel, x, first, rows, ws, w, u, n,
                         use_inv, uinv)

    return jax.jit(step, donate_argnums=(2,))


@functools.lru_cache(maxsize=None)
def _fwd_trans_kernel(batch, m, w, u, nrhs, n, dtype, conj=False):
    def step(lpanel, upanel, x, lsum, first, rows, ws):
        return _fwd_body_trans(lpanel, upanel, x, lsum, first, rows, ws,
                               w, u, n, conj)

    return jax.jit(step, donate_argnums=(2, 3))


@functools.lru_cache(maxsize=None)
def _bwd_trans_kernel(batch, m, w, u, nrhs, n, dtype, conj=False):
    def step(lpanel, x, first, rows, ws):
        return _bwd_body_trans(lpanel, x, first, rows, ws, w, u, n, conj)

    return jax.jit(step, donate_argnums=(1,))


@functools.lru_cache(maxsize=None)
def _diag_inv_kernel(w, dtype):
    """Batched inverses of the packed diagonal blocks — the
    pdCompute_Diag_Inv analog (SRC/pdgstrs.c:647, dtrtri per block)."""

    def inv(lpanel):
        f11 = lpanel[:, :w, :w]
        eye = jnp.eye(w, dtype=lpanel.dtype)
        linv = jax.vmap(lambda l: jax.scipy.linalg.solve_triangular(
            l, eye, lower=True, unit_diagonal=True))(f11)
        uinv = jax.vmap(lambda r: jax.scipy.linalg.solve_triangular(
            r, eye, lower=False))(f11)
        return linv, uinv

    return jax.jit(inv)


class DeviceSolver:
    """Solve (L·U)x = d on the device, in the factor's permuted labeling.

    The dSOLVEstruct_t analog (superlu_ddefs.h:216-228): per-group index
    maps are built once and reused across repeated solves (the reference
    caches them behind SolveInitialized, pdgssvx.c:1330-1337).

    fused=True traces each whole sweep (all levels) into ONE jitted XLA
    program per nrhs bucket — one dispatch for the forward solve and one
    for the backward instead of one per (level, bucket) group.  The solve
    is latency-bound (tiny per-level GEMVs — SURVEY.md §7 hard-part 5:
    "tree-based trisolve is tiny-message dominated"), so collapsing the
    dispatch chain is the device analog of the reference's fully
    pipelined event loop.  Compile cost grows with the plan, so "auto"
    fuses only moderate plans.
    """

    def __init__(self, fact: NumericFactorization, diag_inv: bool = False,
                 fused: str | bool = "auto", mesh=None):
        """mesh: a jax.sharding.Mesh the factors are sharded over.  Needed
        when the mesh spans MULTIPLE PROCESSES (the pdgstrs-over-the-grid
        case): the RHS then uploads replicated over the global mesh and
        the index maps stay numpy (pjit treats identical host arrays as
        replicated global inputs), so every controller runs the same SPMD
        sweeps and reads the replicated result locally.  Single-process
        solves (including virtual meshes) don't need it."""
        self.fact = fact
        self.diag_inv = diag_inv
        self.mesh = mesh
        if fused == "auto":
            fused = len(fact.plan.groups) <= 256
        self.fused = bool(fused)
        self._fused_cache = {}
        self._replicate = None
        plan = fact.plan
        sf = plan.sf
        self.n = plan.n
        first = sf.sn_start[:-1]
        self._groups = []
        self._invs_cached = None
        # with a (multi-process) mesh the index arrays must not commit to
        # one local device — numpy args are what pjit accepts uniformly
        _put = (lambda x: np.asarray(x)) if mesh is not None else jnp.asarray
        # a host-share factorization (stream.py SLU_TPU_HOST_FLOPS) leaves
        # the leading leaf panels as numpy: upload those once so the
        # jitted sweeps don't re-transfer them on every solve.  The
        # uploaded list lives on the SOLVER (self.fronts) — assigning back
        # to fact.fronts would silently flip fact.on_host and force a
        # later host solve on the same factorization to re-pull everything
        if (any(isinstance(lp, np.ndarray) for lp, _ in fact.fronts)
                and not fact.on_host):
            # stream.py disables host-share under a mesh; enforce that
            # invariant HERE too — jnp.asarray would commit these fronts
            # to one local device and break a multi-process SPMD solve
            assert mesh is None, \
                "host-share fronts cannot meet a multi-process mesh solve"
            self.fronts = [(jnp.asarray(lp), jnp.asarray(up))
                           for lp, up in fact.fronts]
        else:
            self.fronts = fact.fronts
        for grp, (lp, up) in zip(plan.groups, self.fronts):
            firsts = _put(first[grp.sns])
            rows = np.full((grp.batch, grp.u), self.n, dtype=np.int64)
            for slot, s in enumerate(grp.sns):
                r = sf.sn_rows[s]
                rows[slot, :len(r)] = r
            self._groups.append((grp, firsts, _put(rows), _put(grp.ws)))

    @property
    def _invs(self):
        """Batched diagonal-block inverses (DiagInv), computed lazily on
        the first NON-transpose solve — transpose sweeps never read them,
        so a trans-only solver must not pay the inversion compiles or
        pin the inverse buffers in HBM."""
        if self._invs_cached is None:
            if self.diag_inv:
                self._invs_cached = [
                    _diag_inv_kernel(grp.w, str(jnp.dtype(self.fact.dtype)))(
                        jnp.asarray(lp))
                    for (grp, _, _, _), (lp, _) in zip(self._groups,
                                                       self.fronts)]
            else:
                self._invs_cached = [(None, None)] * len(self._groups)
        return self._invs_cached

    def _fused_fns(self, kb):
        """One jitted program per sweep (all levels) for this nrhs bucket.
        (jit re-traces on shape/dtype changes anyway; the kb key just
        avoids rebuilding the Python closures.)"""
        fns = self._fused_cache.get(kb)
        if fns is not None:
            return fns
        n1 = self.n + 1
        use_inv = self.diag_inv
        meta = [(grp.w, grp.u) for grp, _, _, _ in self._groups]

        def fwd(x, lsum, fronts, idx, invs):
            for (w, u), (lp, _), (firsts, rows, ws), (linv, _) in zip(
                    meta, fronts, idx, invs):
                x, lsum = _fwd_body(lp, x, lsum, firsts, rows, ws, w, u,
                                    n1, use_inv, linv)
            return x, lsum

        def bwd(x, fronts, idx, invs):
            for (w, u), (lp, up), (firsts, rows, ws), (_, uinv) in zip(
                    reversed(meta), reversed(fronts), reversed(idx),
                    reversed(invs)):
                x = _bwd_body(lp, up, x, firsts, rows, ws, w, u, n1,
                              use_inv, uinv)
            return x

        fns = (jax.jit(fwd, donate_argnums=(0, 1)),
               jax.jit(bwd, donate_argnums=(0,)))
        self._fused_cache[kb] = fns
        return fns

    def _fused_trans_fns(self, kb, conj):
        fns = self._fused_cache.get(("T", kb, conj))
        if fns is not None:
            return fns
        n1 = self.n + 1
        meta = [(grp.w, grp.u) for grp, _, _, _ in self._groups]

        def fwd(x, lsum, fronts, idx):
            for (w, u), (lp, up), (firsts, rows, ws) in zip(
                    meta, fronts, idx):
                x, lsum = _fwd_body_trans(lp, up, x, lsum, firsts, rows,
                                          ws, w, u, n1, conj)
            return x, lsum

        def bwd(x, fronts, idx):
            for (w, u), (lp, _), (firsts, rows, ws) in zip(
                    reversed(meta), reversed(fronts), reversed(idx)):
                x = _bwd_body_trans(lp, x, firsts, rows, ws, w, u, n1,
                                    conj)
            return x

        fns = (jax.jit(fwd, donate_argnums=(0, 1)),
               jax.jit(bwd, donate_argnums=(0,)))
        self._fused_cache[("T", kb, conj)] = fns
        return fns

    def _run_sweeps(self, rhs, sweeps):
        """Shared solve scaffolding: pad rhs into the (n+1, kb) buffer
        (slot n is the OOB dump row), run sweeps(x, lsum, kb) -> x, then
        unpad — one copy for the plain and transpose paths."""
        tracer = get_tracer()
        squeeze = rhs.ndim == 1
        r2 = rhs[:, None] if squeeze else rhs
        k = r2.shape[1]
        kb = _bucket_nrhs(k)
        pad = np.zeros((self.n + 1, kb), dtype=jnp.dtype(self.fact.dtype))
        pad[:self.n, :k] = r2
        # compile census: new sweep-kernel closures (streamed lru misses
        # or fresh fused programs) mean this call compiles — time the
        # sweep issue and account it per (n, nrhs-bucket, mode)
        builds0 = _sweep_kernel_builds() + len(self._fused_cache)
        t0_build = time.perf_counter()
        with tracer.span("device-solve", cat="kernel", n=self.n, nrhs=k,
                         padded_nrhs=kb, fused=self.fused,
                         n_groups=len(self._groups),
                         dtype=str(jnp.dtype(self.fact.dtype))):
            if self.mesh is not None:
                # replicated over the global mesh: every process supplies
                # the same host array, every process can read the result
                # locally
                from jax.sharding import NamedSharding, PartitionSpec as P
                rep = NamedSharding(self.mesh, P(None, None))
                if self._replicate is None:
                    # cached: a fresh lambda per solve would miss jax's
                    # trace cache on every IR correction solve
                    self._replicate = jax.jit(lambda a: a,
                                              out_shardings=rep)
                x = jax.device_put(pad, rep)
                lsum = jax.device_put(np.zeros_like(pad), rep)
                x = sweeps(x, lsum, kb)
                # normalize whatever sharding GSPMD inferred back to fully
                # replicated so np.asarray below is process-local
                x = self._replicate(x)
            else:
                x = jnp.asarray(pad)
                lsum = jnp.zeros_like(x)
                x = sweeps(x, lsum, kb)
            builds = (_sweep_kernel_builds() + len(self._fused_cache)
                      - builds0)
            if builds:
                COMPILE_STATS.record(
                    "solve.device",
                    f"solve n{self.n} nrhs{kb} "
                    f"{'fused' if self.fused else 'stream'}",
                    t0_build, time.perf_counter() - t0_build,
                    n_args=6, builds=builds)
            t0 = time.perf_counter()
            out = np.asarray(jax.block_until_ready(x))[:self.n, :k]
            if tracer.enabled:
                # the solution's D2H pull (the only factor-sized data
                # that ever crosses the boundary per solve)
                tracer.complete("solve-d2h", "comm", t0,
                                time.perf_counter() - t0, op="d2h",
                                bytes=int(out.nbytes))
        return out[:, 0] if squeeze else out

    def solve_trans(self, rhs: np.ndarray, conj: bool = False) -> np.ndarray:
        """Solve (L·U)ᵀ x = rhs (or (L·U)ᴴ with conj) on the device —
        Mᵀ = Uᵀ·Lᵀ through the same factors (the reference's trans_t,
        superlu_defs.h:628-657; host twin: trisolve.lu_solve_trans).
        Respects the same fused/streamed guard as solve()."""
        fact = self.fact
        n1 = self.n + 1
        dt = jnp.dtype(fact.dtype)
        conj = bool(conj)

        def sweeps(x, lsum, kb):
            if self.fused:
                fwd, bwd = self._fused_trans_fns(kb, conj)
                idx = [(firsts, rows, ws)
                       for _, firsts, rows, ws in self._groups]
                x, lsum = fwd(x, lsum, self.fronts, idx)
                return bwd(x, self.fronts, idx)
            # Uᵀ forward, levels ascending
            for (grp, firsts, rows, ws), (lp, up) in zip(
                    self._groups, self.fronts):
                kern = _fwd_trans_kernel(grp.batch, grp.m, grp.w, grp.u,
                                         kb, n1, str(dt), conj)
                x, lsum = kern(lp, up, x, lsum, firsts, rows, ws)
            # Lᵀ backward, levels descending
            for (grp, firsts, rows, ws), (lp, up) in zip(
                    reversed(self._groups), reversed(self.fronts)):
                kern = _bwd_trans_kernel(grp.batch, grp.m, grp.w, grp.u,
                                         kb, n1, str(dt), conj)
                x = kern(lp, x, firsts, rows, ws)
            return x

        return self._run_sweeps(rhs, sweeps)

    def solve(self, rhs: np.ndarray) -> np.ndarray:
        """rhs (n,) or (n, k) in permuted labeling -> solution, same shape."""
        fact = self.fact
        n1 = self.n + 1
        dt = jnp.dtype(fact.dtype)
        use_inv = self.diag_inv

        def sweeps(x, lsum, kb):
            if self.fused:
                fwd, bwd = self._fused_fns(kb)
                idx = [(firsts, rows, ws)
                       for _, firsts, rows, ws in self._groups]
                x, lsum = fwd(x, lsum, self.fronts, idx, self._invs)
                return bwd(x, self.fronts, idx, self._invs)
            # forward in dispatch order (topological: every descendant's
            # group precedes its ancestors' under either scheduler)
            for (grp, firsts, rows, ws), (lp, up), (linv, _) in zip(
                    self._groups, self.fronts, self._invs):
                kern = _fwd_kernel(grp.batch, grp.m, grp.w, grp.u, kb, n1,
                                   str(dt), use_inv)
                x, lsum = (kern(lp, x, lsum, firsts, rows, ws, linv)
                           if use_inv else
                           kern(lp, x, lsum, firsts, rows, ws))
            # backward, levels descending
            for (grp, firsts, rows, ws), (lp, up), (_, uinv) in zip(
                    reversed(self._groups), reversed(self.fronts),
                    reversed(self._invs)):
                kern = _bwd_kernel(grp.batch, grp.m, grp.w, grp.u, kb, n1,
                                   str(dt), use_inv)
                x = (kern(lp, up, x, firsts, rows, ws, uinv) if use_inv
                     else kern(lp, up, x, firsts, rows, ws))
            return x

        return self._run_sweeps(rhs, sweeps)
