#!/usr/bin/env python
"""slulint entry point — identical to `python -m superlu_dist_tpu.analysis`.

Kept as a script so the gates (run_slulint.sh / ci_gates.sh), editors,
and pre-commit hooks have a stable path that works from any cwd.  See
docs/ANALYSIS.md for the rule catalog (SLU101-SLU105 + SLU107-SLU110 +
SLU113 static; SLU106, the SLU109 lock-order verifier and the
SLU111/112/114 program auditor runtime), the call-graph/dataflow
engine (incl. the v4 device taint), the content-hash scan cache
(`--no-cache` bypasses), SARIF output (`--format sarif`),
suppressions, and the baseline workflow (`--update-baseline` prunes
fixed entries).
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from superlu_dist_tpu.analysis.__main__ import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main())
