"""Randomized option-surface sweep — the pdtest robustness discipline
(TEST/pdtest.c: cross every option axis, count failures) applied with
random matrices and random option combinations.  Every run must either
solve to the residual threshold or fail with a clean diagnostic
(info > 0 / SuperLUError) — never crash, never return garbage silently.
"""

import itertools

import numpy as np
import pytest

import superlu_dist_tpu as slu
from superlu_dist_tpu.models.gallery import (random_sparse, poisson2d,
                                             convection_diffusion_2d)
from superlu_dist_tpu.utils.options import (Options, ColPerm, RowPerm,
                                            IterRefine, Trans)
from superlu_dist_tpu.utils.errors import SuperLUError


def _mat(rng):
    kind = rng.integers(0, 4)
    if kind == 0:
        return poisson2d(int(rng.integers(5, 12)))
    if kind == 1:
        return convection_diffusion_2d(int(rng.integers(5, 11)))
    if kind == 2:
        return random_sparse(int(rng.integers(20, 70)),
                             density=float(rng.uniform(0.03, 0.12)),
                             seed=int(rng.integers(1 << 30)))
    vals_seed = int(rng.integers(1 << 30))
    a = random_sparse(int(rng.integers(20, 50)), density=0.08,
                      seed=vals_seed, dtype=np.complex128)
    return a


def _opts(rng):
    return Options(
        equil=bool(rng.integers(0, 2)),
        col_perm=rng.choice([ColPerm.NATURAL, ColPerm.MMD_AT_PLUS_A,
                             ColPerm.MMD_ATA, ColPerm.COLAMD,
                             ColPerm.ND_AT_PLUS_A]),
        row_perm=rng.choice([RowPerm.NOROWPERM, RowPerm.LargeDiag_MC64,
                             RowPerm.LargeDiag_AWPM]),
        iter_refine=rng.choice([IterRefine.NOREFINE,
                                IterRefine.SLU_DOUBLE]),
        trans=rng.choice([Trans.NOTRANS, Trans.TRANS]),
        diag_inv=bool(rng.integers(0, 2)),
        relax=int(rng.integers(2, 24)),
        max_supernode=int(rng.integers(8, 96)),
        min_bucket=int(rng.integers(2, 16)),
    )


@pytest.mark.parametrize("seed", range(12))
def test_random_options_random_matrix(seed):
    rng = np.random.default_rng(1000 + seed)
    a = _mat(rng)
    opts = _opts(rng)
    n = a.n_rows
    xt = rng.standard_normal(n)
    if np.iscomplexobj(a.data):
        xt = xt + 1j * rng.standard_normal(n)
    xt = xt.astype(a.data.dtype)
    op = a.transpose() if opts.trans == Trans.TRANS else a
    b = op.matvec(xt)
    try:
        x, lu, stats, info = slu.gssvx(opts, a, b)
    except SuperLUError:
        return                              # clean refusal is acceptable
    if info != 0:
        assert info > 0                     # localized singularity only
        return
    r = np.linalg.norm(b - op.matvec(x)) / max(np.linalg.norm(b), 1e-300)
    tol = 1e-8 if opts.iter_refine != IterRefine.NOREFINE else 1e-6
    assert np.isfinite(r) and r < tol, (r, opts)


import pytest  # noqa: E402

# slow tier: multi-process / native-build / at-scale — fast CI runs -m "not slow"
pytestmark = pytest.mark.slow
