"""Factorization plan: mapping supernodes onto level-batched padded fronts.

This is the TPU-native analog of the reference's *distribution* phase
(pddistribute, SRC/pddistribute.c:322): where the reference builds
dLocalLU_t index structures plus MPI send/recv schedules, we precompute —
entirely on the host, once per sparsity pattern — the gather/scatter maps
that let the numeric factorization run as a short sequence of XLA ops per
(level, bucket) group:

  assemble:   F[slot] += A entries            (host-built index triples)
              F[slot] += children's Schur     (extend-add, device-computed
                                               indices from per-child
                                               relative-position vectors —
                                               the dscatter.c:111 analog)
  factor:     batched partial LU (ops.dense)  (the pdgstrf hot loop)
  write-back: pool[off[slot]] = Schur block   (strided, device-computed)

Fronts are square (symmetrized pattern): index set = supernode columns +
below-diagonal rows, padded to bucket sizes (W for the pivot block, M = W+U
total).  Children's Schur blocks live in a device pool as zero-padded U×U
blocks whose offsets come from a size-class free-list allocator simulated
at plan time — pool memory is the live tree frontier (the multifrontal
"update stack"), not the sum over all supernodes.  Host-side index volume
is O(nnz(A) + nnz(L)): per-entry extend-add maps are never materialized
(they are broadcast-computed on device), which is what lets plans scale to
n ~ 10^6 (BASELINE.md config 4).

Like the reference's SamePattern path, a plan is reusable across numeric
refactorizations with the same sparsity pattern.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from superlu_dist_tpu.symbolic.symbfact import SymbolicFact


@dataclasses.dataclass
class ChildSet:
    """Children of one group's fronts, bucketed by child U size.

    The extend-add kernel gathers each child's padded ub×ub Schur block from
    the pool and scatter-adds it into the parent front at positions
    rel[c,i]·M + rel[c,j]; rel == M is the sentinel for padding (maps past
    the front, dropped)."""

    ub: int                 # child U bucket (block is ub*ub in the pool)
    child_off: np.ndarray   # (C,) pool offset of each child block
    child_slot: np.ndarray  # (C,) parent slot in this group
    rel: np.ndarray         # (C, ub) child row -> parent front position


@dataclasses.dataclass
class Group:
    """One (level, bucket) batch of fronts."""

    level: int
    m: int                  # padded front size
    w: int                  # padded pivot width
    u: int                  # padded Schur size (m - w); 0 => no write-back
    batch: int              # number of real fronts
    sns: np.ndarray         # supernode ids, slot order
    ws: np.ndarray          # (batch,) real pivot widths (identity padding)
    off: np.ndarray         # (batch,) pool offset of each front's Schur
                            # block (pool_size => no write-back for slot)
    # assembly of original matrix entries
    a_slot: np.ndarray
    a_flat: np.ndarray
    a_src: np.ndarray
    children: list          # list[ChildSet]


@dataclasses.dataclass
class FactorPlan:
    n: int
    sf: SymbolicFact
    pattern_indptr: np.ndarray     # permuted symmetrized pattern (CSR)
    pattern_indices: np.ndarray
    groups: list                   # Groups in level-ascending order
    pool_size: int                 # peak live Schur-pool entries
    sn_group: np.ndarray           # (ns,) group index of each supernode
    sn_slot: np.ndarray            # (ns,) slot within its group
    flops: float
    front_bytes: int               # total padded front storage (per dtype unit)

    @property
    def n_levels(self) -> int:
        return int(self.sf.sn_level.max()) + 1 if len(self.sf.sn_level) else 0

    def __getstate__(self):
        """Drop the volatile executor cache (factor.make_factor_fn hangs
        compiled closures on the plan — `_factor_fns`).  A plan that has
        already factored once would otherwise be unpicklable, which the
        distributed tier's skeleton broadcast hits on every Fact-reuse
        refactorization (the root's plan is warm by then)."""
        state = dict(self.__dict__)
        state.pop("_factor_fns", None)
        return state

    def check_index_width(self):
        """Flat pool offsets must fit the active jax integer width.
        Beyond 2^31 entries (n≳600k at f32) the int64 index maps need
        jax_enable_x64 — the XSDK_INDEX_SIZE=64 build analog
        (superlu_defs.h:85-88); without it jax silently downcasts them
        to int32 and scatters wrap.  Called by every executor."""
        import jax
        if self.pool_size >= 2 ** 31 and not jax.config.jax_enable_x64:
            raise ValueError(
                f"pool_size {self.pool_size} exceeds int32 index range; "
                "enable jax_enable_x64 (the XSDK_INDEX_SIZE=64 analog) — "
                "without it jax silently downcasts the int64 index maps")


def _bucket_sizes(max_needed: int, min_bucket: int, growth: float):
    sizes = []
    s = min_bucket
    while s < max_needed:
        sizes.append(s)
        s = max(s + 8, int(np.ceil(s * growth / 8.0) * 8))
    sizes.append(int(np.ceil(max_needed / 8.0) * 8) if max_needed > min_bucket
                 else min_bucket)
    return np.unique(np.array(sizes, dtype=np.int64))


def build_plan(sf: SymbolicFact, min_bucket: int = 8,
               growth: float = 1.5) -> FactorPlan:
    """Precompute all index maps.  Pure numpy; cost is O(nnz(A) + nnz(L))."""
    n = sf.n
    ns = sf.n_supernodes
    indptr, indices = sf.pattern_indptr, sf.pattern_indices

    widths = np.diff(sf.sn_start).astype(np.int64)
    us = np.array([len(r) for r in sf.sn_rows], dtype=np.int64)

    w_sizes = _bucket_sizes(int(widths.max(initial=1)), min_bucket, growth)
    u_sizes = _bucket_sizes(int(us.max(initial=1)), min_bucket, growth)

    sn_W = w_sizes[np.searchsorted(w_sizes, np.maximum(widths, 1))]
    sn_U = np.where(us == 0, 0,
                    u_sizes[np.searchsorted(u_sizes, np.maximum(us, 1))])

    # group supernodes by (level, W, U)
    key_order = np.lexsort((sn_U, sn_W, sf.sn_level))
    groups: list[Group] = []
    sn_group = np.empty(ns, dtype=np.int64)
    sn_slot = np.empty(ns, dtype=np.int64)
    i = 0
    while i < ns:
        s0 = key_order[i]
        lvl, W, U = int(sf.sn_level[s0]), int(sn_W[s0]), int(sn_U[s0])
        j = i
        members = []
        while (j < ns and sf.sn_level[key_order[j]] == lvl
               and sn_W[key_order[j]] == W and sn_U[key_order[j]] == U):
            members.append(key_order[j])
            j += 1
        sns = np.array(members, dtype=np.int64)
        for slot, s in enumerate(sns):
            sn_group[s] = len(groups)
            sn_slot[s] = slot
        groups.append(Group(level=lvl, m=W + U, w=W, u=U, batch=len(sns),
                            sns=sns, ws=widths[sns], off=None,
                            a_slot=None, a_flat=None, a_src=None,
                            children=[]))
        i = j

    # position helpers: global index x within the front of supernode s.
    # The vectorized form answers ALL (s, x) queries with one searchsorted
    # over segment-offset keys (sn_rows are sorted within each supernode and
    # supernode ids ascend, so s·(n+1)+row is globally sorted) — the
    # per-supernode Python-call version was the plan-build hot spot at
    # n ~ 1e6 (VERDICT r1 weak #4 class).
    first = sf.sn_start[:-1]
    last = sf.sn_start[1:] - 1
    rows_ptr = np.zeros(ns + 1, dtype=np.int64)
    np.cumsum(us, out=rows_ptr[1:])
    rows_concat = (np.concatenate(sf.sn_rows) if ns
                   else np.empty(0, dtype=np.int64))
    first64 = np.ascontiguousarray(first, dtype=np.int64)
    last64 = np.ascontiguousarray(last, dtype=np.int64)
    snW64 = np.ascontiguousarray(sn_W, dtype=np.int64)
    _fallback_keys = []          # built once, only if the native lib is out

    def positions_vec(s_arr: np.ndarray, x_arr: np.ndarray) -> np.ndarray:
        from superlu_dist_tpu import native
        out = native.positions(s_arr, x_arr, first64, last64, snW64,
                               rows_ptr, rows_concat)
        if out is not None:
            return out
        inpiv = x_arr <= last[s_arr]
        pos = np.where(inpiv, x_arr - first[s_arr], 0)
        below = ~inpiv
        if below.any():
            sb = s_arr[below]
            if not _fallback_keys:
                _fallback_keys.append(
                    np.repeat(np.arange(ns, dtype=np.int64), us) * (n + 1)
                    + rows_concat)
            idx = np.searchsorted(_fallback_keys[0],
                                  sb * (n + 1) + x_arr[below])
            pos[below] = sn_W[sb] + (idx - rows_ptr[sb])
        return pos

    # --- A-entry assembly maps (fully vectorized) -------------------------
    rows_all = np.repeat(np.arange(n), np.diff(indptr)).astype(np.int64)
    cols_all = indices.astype(np.int64)
    owner = sf.col_to_sn[np.minimum(rows_all, cols_all)]
    group_m = np.array([g.m for g in groups], dtype=np.int64)
    pi_all = positions_vec(owner, rows_all)
    pj_all = positions_vec(owner, cols_all)
    flat_all = pi_all * group_m[sn_group[owner]] + pj_all
    slot_all = sn_slot[owner]
    g_of_entry = sn_group[owner]
    by_group = np.argsort(g_of_entry, kind="stable")
    gbounds = np.searchsorted(g_of_entry[by_group],
                              np.arange(len(groups) + 1))
    ga_slot = [slot_all[by_group[gbounds[g]:gbounds[g + 1]]]
               for g in range(len(groups))]
    ga_flat = [flat_all[by_group[gbounds[g]:gbounds[g + 1]]]
               for g in range(len(groups))]
    ga_src = [by_group[gbounds[g]:gbounds[g + 1]]
              for g in range(len(groups))]

    # positions of every supernode's rows within its PARENT front (the
    # extend-add targets), one vectorized query for all children at once
    parent_rep = np.repeat(np.where(sf.sn_parent >= 0, sf.sn_parent, 0), us)
    rel_all = (positions_vec(parent_rep, rows_concat)
               if len(rows_concat) else rows_concat)

    # --- pool allocation (size-class free lists) --------------------------
    # Simulated in group execution order: a group's extend-add consumes its
    # children's blocks (freed), then its own Schur blocks are written
    # (allocated) — the multifrontal update-stack discipline, batched.
    free: dict[int, list] = {}
    top = 0

    def alloc(size: int) -> int:
        nonlocal top
        lst = free.get(size)
        if lst:
            return lst.pop()
        off = top
        top += size
        return off

    sn_off = np.empty(ns, dtype=np.int64)
    # children of each group, bucketed by child U size
    grp_children: list[dict[int, list]] = [dict() for _ in groups]
    for g, grp in enumerate(groups):
        # free children blocks (they are fully consumed by this group)
        for ub, lst in grp_children[g].items():
            for (c, _) in lst:
                free.setdefault(ub * ub, []).append(sn_off[c])
        # allocate this group's blocks and register with parents
        for slot, s in enumerate(grp.sns):
            if us[s] == 0:
                sn_off[s] = -1
                continue
            ub = int(sn_U[s])
            sn_off[s] = alloc(ub * ub)
            p = int(sf.sn_parent[s])
            assert p >= 0
            gp = int(sn_group[p])
            assert gp > g, "parent group must execute after child"
            grp_children[gp].setdefault(ub, []).append((s, p))

    pool_size = int(top)

    front_bytes = 0
    for g, grp in enumerate(groups):
        grp.a_slot, grp.a_flat, grp.a_src = ga_slot[g], ga_flat[g], ga_src[g]
        grp.off = np.where(us[grp.sns] > 0, sn_off[grp.sns], pool_size)
        for ub, lst in sorted(grp_children[g].items()):
            C = len(lst)
            cs = np.fromiter((c for c, _ in lst), dtype=np.int64, count=C)
            ps = np.fromiter((p for _, p in lst), dtype=np.int64, count=C)
            child_off = sn_off[cs]
            child_slot = sn_slot[ps]
            rel = np.full((C, ub), grp.m, dtype=np.int64)   # sentinel = M
            # scatter each child's precomputed parent-positions into row k
            kidx = np.repeat(np.arange(C), us[cs])
            cidx = np.concatenate([np.arange(us[c]) for c in cs]) \
                if C else np.empty(0, dtype=np.int64)
            src = np.concatenate([rel_all[rows_ptr[c]:rows_ptr[c + 1]]
                                  for c in cs]) \
                if C else np.empty(0, dtype=np.int64)
            rel[kidx, cidx] = src
            grp.children.append(ChildSet(ub=ub, child_off=child_off,
                                         child_slot=child_slot, rel=rel))
        front_bytes += grp.batch * grp.m * grp.m

    return FactorPlan(n=n, sf=sf, pattern_indptr=indptr,
                      pattern_indices=indices, groups=groups,
                      pool_size=pool_size, sn_group=sn_group, sn_slot=sn_slot,
                      flops=sf.flops, front_bytes=front_bytes)
