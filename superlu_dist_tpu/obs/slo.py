"""Request-scoped tracing + the serving SLO layer (ROADMAP item 3).

Two instruments live here, one per question the serving fleet must
answer:

* **"Where did this ticket's 40 ms go?"** — ``TicketContext``: a tiny
  per-request record (trace id + origin timestamp + recorded stages)
  minted at ``FleetRouter.submit`` / ``SolveServer.submit`` and carried
  with the ticket through re-routes, retries, batch coalescing, poison
  bisection, and the per-ticket BERR-refine rung.  When the ticket
  delivers, ``emit()`` writes one enclosing ``request``-category span
  plus one child span per stage (``queue_wait`` / ``coalesce`` /
  ``dispatch`` / ``device`` / ``refine`` / ``deliver`` at the server;
  ``route`` / ``reroute`` / ``serve`` at the router) into the process
  tracer — one Perfetto track per ticket, stages summing to the
  end-to-end latency by construction (each stage's end is the next
  stage's start).  Cross-process propagation is by trace id only
  (a ``parent_ref`` shim), joined offline by ``scripts/trace_merge.py``
  on the tracers' clock anchors.

* **"Is the fleet meeting its latency SLO?"** — ``LatencyAccounter``:
  an ALWAYS-ON streaming latency histogram per (traffic class, nrhs
  bucket) with fixed log-spaced ms buckets, so p50/p95/p99 are
  available at any moment without storing samples.  Fixed buckets make
  snapshots mergeable by elementwise addition (associative +
  commutative — the ``Stats.reduce`` fixed-layout discipline), so
  replica/rank histograms combine into exact fleet-wide quantile
  estimates.  ``SLOEvaluator`` turns the accounter into a health
  signal: per-class p99 targets (``SLU_TPU_SLO_P99_MS`` /
  ``SLU_TPU_SLO_TARGETS``) with burn-rate accounting over the
  evaluation window (fraction of requests over target, divided by the
  error budget ``SLU_TPU_SLO_BUDGET`` — burn > 1 means the budget is
  being spent faster than provisioned).

Disabled path (the NULL_TRACER discipline): when tracing is off the
serve path carries the module-level ``NULL_TICKET`` singleton — no
object is allocated per submit, no timestamp beyond the ones the
server already takes, no string is formatted.
``scripts/check_trace_overhead.py`` enforces the singleton identity in
CI.  The *accounter* is intentionally always-on: one histogram
increment per delivered ticket (a dict lookup + integer adds), the
price of never being blind to latency.
"""

from __future__ import annotations

import itertools
import os

from superlu_dist_tpu.utils.lockwatch import make_lock

#: Latency histogram bucket upper bounds in MILLISECONDS — a log-ish
#: ladder from 10 us to 10 s (the implicit +Inf bucket is always last).
#: FIXED layout: every accounter everywhere uses exactly these buckets,
#: which is what makes snapshots mergeable by elementwise addition.
LAT_BUCKETS_MS = (
    0.01, 0.02, 0.05, 0.1, 0.2, 0.5, 1.0, 2.0, 5.0, 10.0, 20.0, 50.0,
    100.0, 200.0, 500.0, 1000.0, 2000.0, 5000.0, 10000.0)

#: nrhs bucket lower bounds (a request with ``nrhs=k`` lands in the
#: largest bucket ≤ k) — powers of 8, matching BENCH_SOLVE_NRHS's
#: 1/64/1024 sweep so bench rows and serve metrics bucket identically.
NRHS_BUCKETS = (1, 8, 64, 512, 1024)


def nrhs_bucket(k: int) -> int:
    """The nrhs bucket label for a k-column request."""
    b = NRHS_BUCKETS[0]
    for lb in NRHS_BUCKETS:
        if k >= lb:
            b = lb
    return b


# ---- ticket context ---------------------------------------------------------

class NullTicketContext:
    """The reused no-op context: carrying/recording/emitting touches
    nothing.  ``enabled`` is False so hot paths skip even the stage
    timestamp reads."""

    __slots__ = ()
    enabled = False
    trace_id = ""

    def stage(self, name, t0, dur):
        return self

    def note(self, **attrs):
        return self

    def stages_ms(self):
        return {}

    def emit(self, tracer, t_end, name="request", **extra):
        pass


NULL_TICKET = NullTicketContext()

_seq = itertools.count()


class TicketContext:
    """One ticket's journey: trace id, origin timestamp, and the stage
    intervals recorded along the way.

    Stages are ``(name, t0, dur)`` with ``t0`` a ``time.perf_counter()``
    value (seconds) — the tracer's ``complete()`` contract.  The
    recording discipline is *contiguous coverage*: each stage starts
    where the previous one ended, so stage durations sum exactly to the
    end-to-end latency (the ISSUE's 5% acceptance bound is met by
    construction, not by luck).
    """

    __slots__ = ("trace_id", "ticket", "origin", "stages", "attrs")
    enabled = True

    def __init__(self, ticket, origin, parent=None):
        if parent is not None and getattr(parent, "trace_id", ""):
            self.trace_id = parent.trace_id
        else:
            self.trace_id = f"t{os.getpid():x}-{next(_seq):x}"
        self.ticket = ticket
        self.origin = float(origin)
        self.stages = []
        self.attrs = {}

    def stage(self, name, t0, dur):
        """Record one stage interval (idempotent append — re-routes may
        record ``reroute`` several times; ``stages_ms`` sums them)."""
        if dur > 0.0:
            self.stages.append((name, t0, dur))
        return self

    def note(self, **attrs):
        """Attach attributes discovered mid-flight (nrhs, replica id,
        berr...) — they land on the enclosing span's args."""
        self.attrs.update(attrs)
        return self

    def stages_ms(self) -> dict:
        """Per-stage total milliseconds (repeated stages summed), in
        first-occurrence order — the postmortem attachment format."""
        out = {}
        for name, _t0, dur in self.stages:
            out[name] = out.get(name, 0.0) + dur * 1e3
        return {k: round(v, 3) for k, v in out.items()}

    def emit(self, tracer, t_end, name="request", **extra):
        """Write the span chain: one child span per recorded stage plus
        the enclosing ``request`` span covering origin → ``t_end``.
        Stage spans carry the trace id so Perfetto queries (and
        trace_merge) can pull one ticket's track out of a fleet's."""
        tid = self.trace_id
        for sname, t0, dur in self.stages:
            tracer.complete(sname, "request", t0, dur, trace_id=tid)
        args = dict(self.attrs)
        args.update(extra)
        args["trace_id"] = tid
        args["ticket"] = self.ticket
        args["stages_ms"] = self.stages_ms()
        tracer.complete(name, "request", self.origin,
                        max(t_end - self.origin, 0.0), **args)


class _ParentRef:
    """A cross-process parent handle: carries ONLY the trace id (the
    one thing that must survive a pickle boundary), so a process
    replica's server-side context joins the router-side one."""

    __slots__ = ("trace_id",)
    enabled = True

    def __init__(self, trace_id):
        self.trace_id = str(trace_id)


def parent_ref(trace_id):
    """Wrap a wire-carried trace id as a ``parent=`` argument for
    ``SolveServer.submit`` (None/empty → no parent)."""
    return _ParentRef(trace_id) if trace_id else None


# ---- latency accounter ------------------------------------------------------

class LatencyAccounter:
    """Always-on streaming latency quantiles per (class, nrhs bucket).

    Internally one fixed-layout histogram per (klass, nrhs_bucket) key:
    ``[count, sum_ms, per-bucket counts]`` over ``LAT_BUCKETS_MS`` +
    +Inf.  Quantiles interpolate within the winning bucket (log-spaced
    buckets keep the relative error small).  ``merge_snapshot`` is
    elementwise addition, hence associative and commutative — the
    property tests/test_ticket_trace.py asserts.
    """

    def __init__(self):
        self._lock = make_lock("LatencyAccounter._lock")
        self._hists: dict[tuple, list] = {}

    # ---- producer ------------------------------------------------------
    def observe(self, nrhs, seconds, klass="serve"):
        """Record one request latency (``seconds``, converted to ms)."""
        ms = float(seconds) * 1e3
        key = (str(klass), nrhs_bucket(int(nrhs)))
        with self._lock:
            h = self._hists.get(key)
            if h is None:
                h = self._hists[key] = [
                    0, 0.0, [0] * (len(LAT_BUCKETS_MS) + 1)]
            h[0] += 1
            h[1] += ms
            for i, ub in enumerate(LAT_BUCKETS_MS):
                if ms <= ub:
                    h[2][i] += 1
                    break
            else:
                h[2][-1] += 1

    # ---- quantiles -----------------------------------------------------
    @staticmethod
    def _quantile_from(h, q):
        count = h[0]
        if count == 0:
            return None
        rank = q * count
        acc = 0.0
        lo = 0.0
        for i, b in enumerate(h[2]):
            if b == 0:
                continue
            hi = (LAT_BUCKETS_MS[i] if i < len(LAT_BUCKETS_MS)
                  else LAT_BUCKETS_MS[-1])
            if acc + b >= rank:
                # interpolate within the bucket
                frac = 0.0 if b == 0 else max(rank - acc, 0.0) / b
                return round(lo + (hi - lo) * min(frac, 1.0), 4)
            acc += b
            lo = hi
        return round(LAT_BUCKETS_MS[-1], 4)

    def quantile(self, q, klass="serve", nrhs=1):
        """Interpolated q-quantile in ms for one (class, bucket) series
        (None when the series has no samples)."""
        key = (str(klass), nrhs_bucket(int(nrhs)))
        with self._lock:
            h = self._hists.get(key)
            h = None if h is None else [h[0], h[1], list(h[2])]
        return None if h is None else self._quantile_from(h, q)

    # ---- snapshots / merge --------------------------------------------
    def snapshot(self) -> dict:
        """``{"class|nrhs": {"count", "sum_ms", "buckets"}}`` — the
        mergeable wire format (fixed bucket layout)."""
        with self._lock:
            return {
                f"{k[0]}|{k[1]}": {"count": h[0],
                                   "sum_ms": round(h[1], 6),
                                   "buckets": list(h[2])}
                for k, h in self._hists.items()}

    def merge_snapshot(self, snap: dict):
        """Fold another accounter's ``snapshot()`` in — elementwise
        addition over the fixed bucket layout (associative, so replica →
        router → export merges in any order/grouping agree)."""
        if not snap:
            return
        with self._lock:
            for skey, sh in snap.items():
                klass, _, nb = skey.partition("|")
                key = (klass, int(nb))
                h = self._hists.get(key)
                if h is None:
                    h = self._hists[key] = [
                        0, 0.0, [0] * (len(LAT_BUCKETS_MS) + 1)]
                h[0] += int(sh["count"])
                h[1] += float(sh["sum_ms"])
                buckets = sh["buckets"]
                for i in range(min(len(buckets), len(h[2]))):
                    h[2][i] += int(buckets[i])

    def series(self) -> list:
        """The (klass, nrhs_bucket) keys with samples."""
        with self._lock:
            return sorted(self._hists)

    def summary(self) -> dict:
        """Per-series {count, mean_ms, p50_ms, p95_ms, p99_ms}."""
        with self._lock:
            hists = {k: [h[0], h[1], list(h[2])]
                     for k, h in self._hists.items()}
        out = {}
        for (klass, nb), h in sorted(hists.items()):
            out[f"{klass}|{nb}"] = {
                "count": h[0],
                "mean_ms": round(h[1] / h[0], 4) if h[0] else None,
                "p50_ms": self._quantile_from(h, 0.50),
                "p95_ms": self._quantile_from(h, 0.95),
                "p99_ms": self._quantile_from(h, 0.99),
            }
        return out

    def report_lines(self) -> list:
        """Human lines for Stats.report() — empty when no samples."""
        lines = []
        for key, s in self.summary().items():
            if not s["count"]:
                continue
            klass, _, nb = key.partition("|")
            lines.append(
                f"  {klass:<8s} nrhs>={nb:<5s} n={s['count']:<7d} "
                f"mean {s['mean_ms']:8.3f} ms   p50 {s['p50_ms']:8.3f}"
                f"   p95 {s['p95_ms']:8.3f}   p99 {s['p99_ms']:8.3f}")
        return lines

    def publish(self, metrics):
        """Push per-series quantile gauges into a metrics registry
        (``slu_latency_p{50,95,99}_ms{class,nrhs}``) — the slu_top /
        Prometheus surface."""
        if metrics is None or not metrics.enabled:
            return
        for key, s in self.summary().items():
            klass, _, nb = key.partition("|")
            labels = {"class": klass, "nrhs": nb}
            metrics.set("slu_latency_requests_total", s["count"], **labels)
            for q in ("p50", "p95", "p99"):
                v = s[f"{q}_ms"]
                if v is not None:
                    metrics.set(f"slu_latency_{q}_ms", v, **labels)

    # ---- cross-rank aggregation ---------------------------------------
    def reduce(self, comm):
        """Collective fleet/rank-wide merge (the Stats.reduce fixed-
        layout discipline): every rank contributes its snapshot via
        bcast_obj, rank 0's accounter absorbs all of them, and the
        merged summary is broadcast back.  COLLECTIVE — every rank must
        call at the same point."""
        for r in range(comm.n_ranks):
            snap = comm.bcast_obj(
                self.snapshot() if comm.rank == r else None, root=r)
            if comm.rank == 0 and r != 0:
                self.merge_snapshot(snap)
        return comm.bcast_obj(
            self.summary() if comm.rank == 0 else None, root=0)


# ---- SLO evaluator ----------------------------------------------------------

class SLOEvaluator:
    """Burn-rate SLO evaluation over a LatencyAccounter.

    Targets come from two knobs: ``SLU_TPU_SLO_P99_MS`` (one global p99
    target in ms; 0 = no SLO) and ``SLU_TPU_SLO_TARGETS`` (per-class
    overrides, ``"class=ms,class=ms"``).  ``SLU_TPU_SLO_BUDGET`` is the
    error budget: the provisioned fraction of requests allowed over
    target (default 1%).  ``evaluate()`` is windowed on the DELTA since
    the previous call, so a long-healthy fleet's burn rate reflects
    current traffic, not its whole history.
    """

    def __init__(self, p99_ms=None, targets=None, budget=None):
        from superlu_dist_tpu.utils.options import env_float, env_str
        if p99_ms is None:
            p99_ms = env_float("SLU_TPU_SLO_P99_MS")
        self.p99_ms = float(p99_ms)
        self.budget = float(env_float("SLU_TPU_SLO_BUDGET")
                            if budget is None else budget)
        self.targets = dict(targets or {})
        if not targets:
            raw = env_str("SLU_TPU_SLO_TARGETS").strip()
            for part in raw.split(","):
                if "=" in part:
                    klass, _, ms = part.partition("=")
                    try:
                        self.targets[klass.strip()] = float(ms)
                    except ValueError:
                        pass
        self._prev: dict = {}

    @property
    def armed(self) -> bool:
        return self.p99_ms > 0.0 or bool(self.targets)

    def target_for(self, klass) -> float:
        return float(self.targets.get(klass, self.p99_ms))

    def evaluate(self, accounter) -> dict:
        """Per-series SLO state over the window since the last call:
        ``{"class|nrhs": {count, p99_ms, target_ms, over, burn, ok}}``.
        ``burn`` = (fraction of windowed requests over target) /
        budget; burn ≤ 1 means within budget (``ok``)."""
        snap = accounter.snapshot()
        out = {}
        for key, h in snap.items():
            klass, _, _nb = key.partition("|")
            target = self.target_for(klass)
            if target <= 0.0:
                continue
            prev = self._prev.get(key)
            if prev is None:
                dcount = h["count"]
                dbuckets = list(h["buckets"])
            else:
                dcount = h["count"] - prev["count"]
                dbuckets = [b - p for b, p in
                            zip(h["buckets"], prev["buckets"])]
            if dcount <= 0:
                continue
            over = 0
            for i, b in enumerate(dbuckets):
                lo = LAT_BUCKETS_MS[i - 1] if i > 0 else 0.0
                if lo >= target:
                    over += b
            frac_over = over / dcount
            burn = frac_over / self.budget if self.budget > 0 else (
                float("inf") if over else 0.0)
            win = [dcount, 0.0, dbuckets]
            out[key] = {
                "count": dcount,
                "p99_ms": LatencyAccounter._quantile_from(win, 0.99),
                "target_ms": target,
                "over": over,
                "burn": round(burn, 4),
                "ok": burn <= 1.0,
            }
        self._prev = snap
        return out


# ---- process-global accounter ----------------------------------------------

_accounter = None
_init_lock = make_lock("obs.slo._init_lock")


def get_accounter() -> LatencyAccounter:
    """The process latency accounter — ALWAYS enabled (one histogram
    increment per request is the observability floor)."""
    global _accounter
    a = _accounter
    if a is None:
        with _init_lock:
            if _accounter is None:
                _accounter = LatencyAccounter()
            a = _accounter
    return a


def install(accounter):
    """Install ``accounter`` as the process accounter (test hygiene);
    returns the previous one."""
    global _accounter
    prev = _accounter
    _accounter = accounter
    return prev


def _reset():
    global _accounter
    _accounter = None
