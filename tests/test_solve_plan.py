"""Solve-plan machinery (solve/plan.py): bounded nrhs buckets, dataflow
sweep scheduling over the factor plan, shape-key promotion padding, the
recursive blocked TRSM, and the padding-honesty telemetry."""

import numpy as np
import pytest

from superlu_dist_tpu.drivers.gssvx import gssvx
from superlu_dist_tpu.models.gallery import (
    poisson2d, random_sparse)
from superlu_dist_tpu.solve.plan import (
    SolvePlan, bucket_nrhs, build_solve_plan, chunk_nrhs, nrhs_buckets)
from superlu_dist_tpu.utils.options import IterRefine, Options

pytestmark = pytest.mark.solveplan


def _factor(a, **opt_kw):
    opts = Options(iter_refine=IterRefine.NOREFINE, **opt_kw)
    x, lu, stats, info = gssvx(opts, a, np.ones(a.n_rows))
    assert info == 0
    return lu


# ---------------------------------------------------------------------------
# nrhs bucket set
# ---------------------------------------------------------------------------

def test_nrhs_buckets_bounded_and_exact_small():
    bs = nrhs_buckets(1024, 1.5)
    assert bs[0] == 1 and bs[-1] == 1024
    assert list(bs) == sorted(set(bs))
    # the latency-critical rungs pad nothing
    for k in (1, 2, 4, 8, 16, 32, 64):
        assert bucket_nrhs(k, bs) == k
    # the set is CLOSED and small — the bounded-compile-set contract
    assert len(bs) <= 16
    # geometric rungs are multiples of 32 past the pow2 regime
    assert all(b % 32 == 0 for b in bs if b > 64)


def test_nrhs_bucket_lookup_and_cap():
    bs = nrhs_buckets(1024, 1.5)
    assert bucket_nrhs(65, bs) == 96
    assert bucket_nrhs(97, bs) > 97
    with pytest.raises(ValueError):
        bucket_nrhs(1025, bs)


def test_chunk_nrhs_splits_past_cap():
    bs = nrhs_buckets(1024, 1.5)
    chunks = chunk_nrhs(2500, bs)
    assert chunks[0] == (0, 1024, 1024) and chunks[1] == (1024, 2048, 1024)
    lo, hi, kb = chunks[-1]
    assert hi == 2500 and kb == bucket_nrhs(2500 - lo, bs)
    # contiguous cover
    assert all(c1[1] == c2[0] for c1, c2 in zip(chunks, chunks[1:]))
    assert chunk_nrhs(1, bs) == [(0, 1, 1)]
    # a tiny cap still yields a usable (single-bucket) set
    tiny = nrhs_buckets(4, 1.5)
    assert tiny == (1, 2, 4)
    assert chunk_nrhs(11, tiny) == [(0, 4, 4), (4, 8, 4), (8, 11, 4)]


# ---------------------------------------------------------------------------
# sweep schedule
# ---------------------------------------------------------------------------

def test_solve_plan_topological_and_bounded():
    lu = _factor(poisson2d(16))
    sp = build_solve_plan(lu.plan, schedule="dataflow", window=0)
    sf = lu.plan.sf
    # children strictly precede their parents' sweep batch (the lsum
    # correctness invariant: a descendant's scatter must land before the
    # ancestor's segment solves)
    pos = np.empty(sf.n_supernodes, dtype=np.int64)
    for i, g in enumerate(sp.groups):
        pos[g.sns] = i
    for s in range(sf.n_supernodes):
        p = int(sf.sn_parent[s])
        if p >= 0:
            assert pos[s] < pos[p], (s, p)
    # cross-level merging never produces MORE dispatches than the
    # factor grouping, and occupancy never degrades
    assert len(sp.groups) <= sp.n_factor_groups
    assert sp.mean_occupancy >= lu.plan.mean_occupancy - 1e-9
    assert sp.critical_path <= len(sp.groups)


def test_window_one_equals_level_partition():
    lu = _factor(poisson2d(12))
    sp1 = build_solve_plan(lu.plan, schedule="dataflow", window=1)
    spl = build_solve_plan(lu.plan, schedule="level")
    assert len(sp1.groups) == len(spl.groups)
    for g1, gl in zip(sp1.groups, spl.groups):
        assert np.array_equal(g1.sns, gl.sns)
        assert (g1.w, g1.u) == (gl.w, gl.u)


def test_factor_schedule_aliases_every_group():
    lu = _factor(poisson2d(12))
    sp = build_solve_plan(lu.plan, schedule="factor")
    assert len(sp.groups) == len(lu.plan.groups)
    for i, g in enumerate(sp.groups):
        assert g.reuse == i
        fg = lu.plan.groups[i]
        assert np.array_equal(g.sns, fg.sns)
        assert (g.w, g.u, g.m) == (fg.w, fg.u, fg.m)


def test_same_machinery_same_inputs_reproduces_factor_batches():
    """When the solve scheduler runs the factor scheduler's exact knobs
    (same window, alignment off), its batches ARE the factor groups —
    the all-zero-copy fast path."""
    lu = _factor(poisson2d(14))
    plan = lu.plan
    sp = build_solve_plan(plan, schedule=plan.schedule,
                          window=plan.sched_window, align=1.0)
    assert all(g.reuse >= 0 for g in sp.groups)
    assert len(sp.groups) == len(plan.groups)


def test_schedule_stats_fields_and_padding_honesty():
    lu = _factor(random_sparse(90, density=0.06, seed=3),
                 relax=4, max_supernode=12)
    sp = build_solve_plan(lu.plan)
    st = sp.schedule_stats(nrhs=130)
    for key in ("schedule", "n_groups", "n_factor_groups", "occupancy",
                "critical_path", "nrhs_buckets", "shape_padding",
                "reused_groups", "nrhs", "padded_nrhs", "padding_factor"):
        assert key in st, key
    # executed always covers structural — shape padding and nrhs
    # padding both count (the honesty-fix satellite)
    assert st["shape_padding"] >= 1.0
    assert st["padding_factor"] >= st["shape_padding"] - 1e-9
    kb = sum(b for _, _, b in chunk_nrhs(130, sp.nrhs_bucket_set))
    assert st["padded_nrhs"] == kb
    assert sp.executed_flops(130) == sp.executed_flops_per_rhs * kb
    assert sp.solve_flops(130) == sp.flops_per_rhs * 130


def test_env_knobs_drive_build(monkeypatch):
    lu = _factor(poisson2d(10))
    monkeypatch.setenv("SLU_TPU_SOLVE_SCHEDULE", "level")
    sp = build_solve_plan(lu.plan)
    assert sp.schedule == "level"
    monkeypatch.setenv("SLU_TPU_SOLVE_SCHEDULE", "bogus")
    with pytest.raises(ValueError):
        build_solve_plan(lu.plan)


def test_driver_threads_solve_schedule(monkeypatch):
    a = poisson2d(10)
    opts = Options(iter_refine=IterRefine.NOREFINE,
                   solve_schedule="level", solve_window=0)
    x, lu, stats, info = gssvx(opts, a, np.ones(a.n_rows))
    assert info == 0
    lu.solve_path = "device"
    lu.dev_solver = None
    lu.solve_factored(np.ones(a.n_rows))
    assert lu.dev_solver.splan.schedule == "level"


# ---------------------------------------------------------------------------
# promoted keys + merged batches still solve correctly
# ---------------------------------------------------------------------------

def test_promoted_keys_pad_benignly():
    """A large alignment tolerance merges shape keys, so some sweep
    batches gather identity/zero-padded panel stacks — the solution must
    not move."""
    from superlu_dist_tpu.solve.device import DeviceSolver
    from superlu_dist_tpu.solve.trisolve import lu_solve
    a = random_sparse(90, density=0.06, seed=5)
    lu = _factor(a, relax=4, max_supernode=12, min_bucket=8,
                 bucket_growth=1.5)
    sp = build_solve_plan(lu.plan, schedule="dataflow", window=0,
                          align=4.0)
    assert any(g.reuse < 0 for g in sp.groups), \
        "expected at least one merged/promoted batch"
    d = np.random.default_rng(11).standard_normal((a.n_rows, 3))
    got = DeviceSolver(lu.numeric, solve_plan=sp).solve(d)
    want = lu_solve(lu.numeric, d)
    np.testing.assert_allclose(got, want, rtol=1e-9, atol=1e-11)
    got_t = DeviceSolver(lu.numeric, solve_plan=sp).solve_trans(d)
    from superlu_dist_tpu.solve.trisolve import lu_solve_trans
    want_t = lu_solve_trans(lu.numeric, d)
    np.testing.assert_allclose(got_t, want_t, rtol=1e-9, atol=1e-11)


# ---------------------------------------------------------------------------
# recursive blocked TRSM
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("lower,unit,trans", [
    (True, True, 0), (False, False, 0), (True, True, 1), (False, False, 1),
])
def test_blocked_trsm_matches_unblocked(lower, unit, trans):
    from superlu_dist_tpu.solve.device import _trsm
    rng = np.random.default_rng(3)
    w, B, k = 37, 4, 5          # odd width exercises uneven splits
    a = rng.standard_normal((B, w, w))
    tri = np.tril(a) if lower else np.triu(a)
    tri += np.eye(w) * w        # well-conditioned diagonal
    b = rng.standard_normal((B, w, k))
    want = np.asarray(_trsm(tri, b, lower, unit, trans, leaf=0))
    got = np.asarray(_trsm(tri, b, lower, unit, trans, leaf=8))
    np.testing.assert_allclose(got, want, rtol=1e-11, atol=1e-13)


def test_blocked_trsm_leaf_knob_changes_nothing_numerically():
    """End-to-end: a solver with deep TRSM recursion agrees with the
    unblocked one to f64 tightness (wide supernodes force w past the
    leaf)."""
    from superlu_dist_tpu.solve.device import DeviceSolver
    from superlu_dist_tpu.solve.trisolve import lu_solve
    a = poisson2d(14)
    lu = _factor(a)             # default max_supernode=256 -> wide root
    d = np.random.default_rng(13).standard_normal((a.n_rows, 2))
    want = lu_solve(lu.numeric, d)
    for leaf in (0, 8, 64):
        got = DeviceSolver(lu.numeric, trsm_leaf=leaf).solve(d)
        np.testing.assert_allclose(got, want, rtol=1e-9, atol=1e-11)
