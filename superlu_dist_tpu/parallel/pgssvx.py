"""Multi-process expert driver over block-row distributed input.

Capability analog of pdgssvx with NR_loc input (SRC/pdgssvx.c:505): every
process holds a block of rows of A and of B (`DistributedCSR` — the
NRformat_loc analog), and all of them receive the solution.  Covers the
reference driver surface: multiple right-hand sides (nrhs ≥ 1, X returned
in B's shape), transpose solves (options.trans, pdgssvx.c's Trans
dispatch), and complex matrices (the pzgssvx twin — complex payloads ride
the f64 tree as re/im passes).

TPU-native split: the analysis + factorization are single-address-space
(they run where the accelerator is — rank 0), so the distributed input is
first assembled there, exactly like the reference's
pdCompRow_loc_to_CompCol_global gather before serial preprocessing
(pdgssvx.c:775).  The numeric work itself is SPMD-first: on a
single-controller mesh the factorization is ONE shard_map program and
each solve sweep one more (parallel/spmd.py — panels block-cyclic over
the flat device order, every extend-add/Schur/lsum exchange an
in-program collective; factor.get_executor's auto rule picks it), and
on a mesh spanning a jax.distributed world the GSPMD streamed kernels
shard over grid axes (parallel/grid.gridinit_multihost +
gssvx(grid=...)).  The host-mediated TreeComm lockstep tier is DEMOTED
to the A/B reference and recovery fallback: the root-gather path below
survives as the single-host fallback, its per-rank dispatch loop the
bitwise baseline the SPMD tier is gated against
(scripts/check_spmd_equiv.py, tests/test_spmd.py).  The
gather/broadcast ride the shared-memory tree collectives
(parallel/treecomm.py); refinement then runs distributed
(parallel/pgsrfs.py) so the residual work stays with the row owners —
the reference's pdgsrfs/pdgsmv shape.

Payloads larger than the tree domain's max_len stream through in chunks
(TreeComm.bcast_any/reduce_sum_any); integer index arrays travel on the
f64 mantissa (exact below 2^53 — dimensions and nnz counts are far
below).

Collective discipline: every rank must reach the same TreeComm
collective sequence.  slulint SLU101 verifies this statically
(interprocedurally since v2 — wrappers like bcast_result count as the
collectives they reach), and SLU_TPU_VERIFY_COLLECTIVES=1 verifies it
at runtime: each collective below then cross-checks a (call-site, op,
shape/dtype, seq) digest across ranks and raises
CollectiveMismatchError naming the divergent sites instead of
deadlocking (docs/ANALYSIS.md, rule SLU106).
"""

from __future__ import annotations

import numpy as np

from superlu_dist_tpu.parallel.dist import DistributedCSR
from superlu_dist_tpu.parallel.treecomm import TreeComm
from superlu_dist_tpu.sparse.formats import SparseCSR


def gather_distributed(tc: TreeComm, a_loc: DistributedCSR,
                       root: int = 0,
                       all_ranks: bool = False) -> SparseCSR | None:
    """Assemble the global CSR on `root` from every rank's block rows —
    the pdCompRow_loc_to_CompCol_global analog over tree collectives.
    Returns the matrix on root, None elsewhere.  all_ranks=True assembles
    on EVERY rank (all-reduce instead of reduce) — the analysis input for
    the mesh-sharded tier, where each controller must hold the same
    global pattern but no controller ever holds the factors."""
    n = a_loc.n
    # global nnz offsets: every rank's count, allreduced
    counts = np.zeros(tc.n_ranks)
    counts[tc.rank] = a_loc.nnz_loc
    counts = tc.allreduce_sum_any(counts, root=root)
    offs = np.zeros(tc.n_ranks + 1, dtype=np.int64)
    offs[1:] = np.cumsum(counts).astype(np.int64)
    total = int(offs[-1])
    lo = int(offs[tc.rank])
    _reduce = tc.allreduce_sum_any if all_ranks else tc.reduce_sum_any

    # row counts (for indptr) and flat index/value arrays, disjoint slots
    rowcnt = np.zeros(n)
    rowcnt[a_loc.fst_row:a_loc.fst_row + a_loc.m_loc] = \
        np.diff(a_loc.indptr)
    rowcnt = _reduce(rowcnt, root=root)
    idx = np.zeros(total)
    idx[lo:lo + a_loc.nnz_loc] = a_loc.indices
    idx = _reduce(idx, root=root)
    vdtype = (np.complex128 if np.issubdtype(np.asarray(a_loc.data).dtype,
                                             np.complexfloating)
              else np.float64)
    vals = np.zeros(total, dtype=vdtype)
    vals[lo:lo + a_loc.nnz_loc] = a_loc.data
    vals = _reduce(vals, root=root)

    if not all_ranks and tc.rank != root:
        return None
    indptr = np.zeros(n + 1, dtype=np.int64)
    indptr[1:] = np.cumsum(rowcnt).astype(np.int64)
    # ranks hold contiguous ascending row blocks, so the flat order by
    # rank offset IS row order
    return SparseCSR(n, n, indptr, idx.astype(np.int64), vals)


def _finish_stats(tc: TreeComm, lu_out):
    """Cross-rank stat epilogue — COLLECTIVE: every rank calls it at the
    same point, with NO dependence on per-rank ``lu_out`` presence (which
    may legitimately diverge across ranks).  Snapshots this rank's comm
    counters into its Stats, allreduces the fixed-layout stat vectors,
    and hands every rank the same StatsSummary: per-phase min/max/avg +
    load-balance factor — the sum-over-ranks PStatPrint the reference
    prints at PROFlevel≥1 (SRC/util.c:538-630).  ``SLU_TPU_STATS=1``
    prints the reduced report once, on rank 0."""
    from superlu_dist_tpu.utils.options import env_flag
    from superlu_dist_tpu.utils.stats import Stats

    stats = (lu_out or {}).get("stats")
    if stats is None:
        stats = Stats()
    stats.attach_comm(tc.comm_stats)
    summary = stats.reduce(tc)
    if lu_out is not None:
        lu_out["stats_summary"] = summary
    # serving metrics: cross-rank aggregation rides the same epilogue
    # (SLU_TPU_METRICS is env-driven, hence identical on every rank —
    # the branch is collective-safe)
    from superlu_dist_tpu.obs.metrics import get_metrics
    m = get_metrics()
    if m.enabled:
        reduced = m.reduce(tc)
        if lu_out is not None:
            lu_out["metrics_summary"] = reduced
    if env_flag("SLU_TPU_STATS") and tc.rank == 0:
        print(summary.report())
    return summary


def bcast_result(tc: TreeComm, fn, root: int = 0):
    """Run `fn()` on `root` and broadcast its result; a root-side
    exception is SHIPPED and re-raised on every rank instead of leaving
    the peers deadlocked in the broadcast (every root-serial section of
    the distributed tiers routes through this)."""
    payload = None
    if tc.rank == root:
        try:
            payload = (None, fn())
        except Exception as exc:
            payload = (exc, None)
    err, result = tc.bcast_obj(payload, root=root)
    if err is not None:
        raise err
    return result


def root_analyze_bcast(tc: TreeComm, options, a_loc: DistributedCSR,
                       stats, lu=None):
    """Gather the distributed rows on root, run the serial analysis
    there (honoring `lu` Fact-reuse), and broadcast the analyzed
    skeleton STRIPPED of the global matrix and the symmetrized-pattern
    copies (restored on root afterwards — they only serve future
    SamePattern reuse checks there).  Returns (lu, bvals) on every
    rank.  The one implementation behind _pgssvx_mesh's default tier,
    panalyze's small-problem fallback, and the A/B measurement script.
    """
    from superlu_dist_tpu.drivers.gssvx import analyze

    a_root = gather_distributed(tc, a_loc, root=0)
    sym_keep = None
    box = {}

    def _analyze():
        lu2, bvals, _ = analyze(options, a_root, lu=lu, stats=stats)
        lu2.a = None
        box["sym"] = (lu2.a_sym_indptr, lu2.a_sym_indices)
        lu2.a_sym_indptr = lu2.a_sym_indices = None
        return lu2, bvals

    lu2, bvals = bcast_result(tc, _analyze)
    if tc.rank == 0:
        lu2.a_sym_indptr, lu2.a_sym_indices = box["sym"]
    return lu2, bvals


def pgssvx(tc: TreeComm, options, a_loc: DistributedCSR,
           b_loc: np.ndarray, root: int = 0, grid=None, lu=None,
           lu_out=None, replicate_analysis: bool = False,
           resume_from: str | None = None):
    """Collectively solve op(A)·X = B from block-row distributed input.

    b_loc: (m_loc,) or (m_loc, nrhs) — this rank's block rows of B.
    Returns (x, info) on every rank, x of shape (n,) or (n, nrhs)
    matching b_loc.  options.trans selects op(A) (NOTRANS/TRANS/CONJ,
    the reference's pdgssvx trans dispatch); complex A/b take the
    pzgssvx path.

    `grid` (a parallel.grid.ProcessGrid whose mesh spans ALL the
    participating processes' devices, from gridinit_multihost) selects
    the distributed-factors tier: rank 0 assembles the global analysis
    input, runs the host analysis once, and broadcasts the analyzed
    skeleton; then all ranks run the SAME mesh-sharded factorization and
    collective device solve — the factors and the Schur pool live
    sharded across the processes' devices and NO process ever
    materializes them (the reference's defining NR_loc-in,
    distributed-factors-out property, SRC/pdgssvx.c:505 /
    pddistribute.c:322).  No non-root process assembles the global
    matrix or runs the analysis — it receives only the analysis products
    (plan/symbolic index maps + permuted values, O(nnz) data, measured
    ~2x lower peak host memory and wall time at n=110,592:
    docs/mesh_analysis_4proc_n110592.json; the psymbfact direction,
    SRC/psymbfact.c:228-242).  Without `grid`, the single-host fallback
    gathers to root and factors there (refinement stays distributed).

    `lu_out`: optional dict; on return, lu_out["lu"] holds this rank's
    LUFactorization handle (the reference's caller-owned LUstruct — on
    the fallback tier only the root has one) and lu_out["stats"] the
    factorization Stats (both tiers; on the fallback tier, root only).

    `lu`: a prior handle (this rank's lu_out["lu"] from an earlier
    call) activating options.fact's reuse tiers on the distributed
    input, the reference's time-stepping loop over NR_loc
    (EXAMPLE/pddrive1.c, pdgssvx.c Fact dispatch): SamePattern /
    SamePattern_SameRowPerm reuse the analysis products and refactor
    with the new values; FACTORED skips straight to the collective
    solve on the existing sharded factors.

    `resume_from` names a durable factor-checkpoint frontier
    (persist/checkpoint.py) for the ROOT factorization of the fallback
    tier — the rank-failure recovery path (parallel/recover.py,
    Options.ft="shrink"/"respawn") threads the previous epoch's
    checkpoint directory through here so the surviving ranks complete
    the factorization instead of redoing it; the fingerprint/digest
    verification inside gssvx guarantees the resumed frontier belongs
    to this exact analysis.  Rank failure itself surfaces here as
    RankFailureError on EVERY surviving rank (the bounded-wait
    collectives + failure detector in parallel/treecomm.py) — this
    driver never hangs on a dead peer once SLU_TPU_COMM_TIMEOUT_S is
    armed, and never retries on its own: recovery policy lives in
    parallel/recover.pgssvx_ft.

    Solve health: when refinement ran, lu_out["stats"].solve_report
    carries berr (+ history) from the distributed loop; if it stagnated
    above the recovery target and options.recovery is enabled, ONE
    escalated retry at the next factor-precision tier runs collectively
    (the decision is taken from allreduced quantities, so every rank
    agrees — no rank-divergent control flow) and is recorded as a rung.
    """
    from superlu_dist_tpu.drivers.gssvx import gssvx
    from superlu_dist_tpu.parallel.pgsrfs import pgsrfs
    from superlu_dist_tpu.utils.errors import CheckpointError
    from superlu_dist_tpu.utils.options import IterRefine, Trans
    import dataclasses

    n = a_loc.n
    b_loc = np.asarray(b_loc)
    one_d = b_loc.ndim == 1
    # NOT reshape(m_loc, -1): on an empty trailing block (m_loc == 0,
    # legitimate from distribute_rows' ceil stepping) reshape(0, -1)
    # raises and the surviving ranks would deadlock in the collectives
    b2 = b_loc[:, None] if one_d else b_loc
    nrhs = b2.shape[1]
    complex_in = (np.issubdtype(np.asarray(a_loc.data).dtype,
                                np.complexfloating)
                  or np.issubdtype(b2.dtype, np.complexfloating))
    wdtype = np.complex128 if complex_in else np.float64

    if grid is not None:
        x, info, rep = _pgssvx_mesh(tc, options, a_loc, b2, grid, one_d,
                                    wdtype, lu=lu, lu_out=lu_out,
                                    replicate_analysis=replicate_analysis)
        x, info = _maybe_escalate_distributed(
            tc, options, a_loc, b_loc, x, info, rep, lu_out, grid=grid,
            replicate_analysis=replicate_analysis)
        # cross-rank stat reduction (collective; the escalate decision
        # above is replicated, so every rank reaches this together)
        _finish_stats(tc, lu_out)
        return x, info

    a_root = gather_distributed(tc, a_loc, root=root)
    b_full = np.zeros((n, nrhs), dtype=wdtype)
    b_full[a_loc.fst_row:a_loc.fst_row + a_loc.m_loc] = b2
    b_full = tc.reduce_sum_any(b_full, root=root)

    x0 = np.zeros((n, nrhs), dtype=wdtype)
    info = np.zeros(1)
    solve_fn = None
    if tc.rank == root:
        # refinement happens distributed below — root factors only;
        # `lu` threads the Fact reuse tiers through (root-held handle)
        opts0 = dataclasses.replace(options,
                                    iter_refine=IterRefine.NOREFINE)
        try:
            x_r, lu, stats, info_r = gssvx(
                opts0, a_root, b_full if nrhs > 1 else b_full[:, 0],
                lu=lu, resume_from=resume_from)
        except CheckpointError:
            # an unusable recovery frontier (corrupt / wrong plan) must
            # degrade to a from-scratch factorization, not strand the
            # peers: the retry is root-LOCAL and leaves the collective
            # sequence untouched (the peers only see the info bcast)
            x_r, lu, stats, info_r = gssvx(
                opts0, a_root, b_full if nrhs > 1 else b_full[:, 0],
                lu=lu)
        info[0] = float(info_r)
        if lu_out is not None:
            lu_out["lu"] = lu
            lu_out["stats"] = stats
        if info_r == 0:
            x0 = np.asarray(x_r, dtype=wdtype).reshape(n, nrhs)
            trans = getattr(options, "trans", Trans.NOTRANS)
            if trans == Trans.NOTRANS:
                solve_fn = lu.solve_factored
            else:
                conj = trans == Trans.CONJ
                solve_fn = (lambda r:
                            lu.solve_factored_trans(r, conj=conj))
    info = tc.bcast_any(info, root=root)
    if int(info[0]) != 0:
        return None, int(info[0])
    x0 = tc.bcast_any(x0, root=root)
    x, info_out, rep = _refine_tail(tc, options, a_loc, b2, x0, solve_fn,
                                    root, one_d, nrhs, lu_out=lu_out)
    x, info_out = _maybe_escalate_distributed(tc, options, a_loc, b_loc, x,
                                              info_out, rep, lu_out,
                                              root=root)
    _finish_stats(tc, lu_out)
    return x, info_out


def _refine_tail(tc, options, a_loc, b2, x0, solve_fn, root, one_d, nrhs,
                 lu_out=None, collective_solve=False, stats=None):
    """Distributed refinement over the RHS columns; returns
    (x, info, SolveReport-or-None).  The report is identical on every
    rank (built from allreduced berr values), so callers may branch on
    it collectively."""
    from superlu_dist_tpu.parallel.pgsrfs import pgsrfs
    from superlu_dist_tpu.utils.options import IterRefine, Trans
    rep = None
    if options.iter_refine == IterRefine.NOREFINE:
        x = x0
    else:
        # per-RHS distributed refinement (the reference's pdgsrfs loops
        # RHS columns with per-RHS berr, pdgsrfs.c:205-235)
        trans = getattr(options, "trans", Trans.NOTRANS)
        cols = []
        rhs_stats = []
        for j in range(nrhs):
            so = {}
            cols.append(pgsrfs(tc, a_loc, b2[:, j], x0[:, j], solve_fn,
                               root=root, trans=trans,
                               collective_solve=collective_solve,
                               stats_out=so))
            rhs_stats.append(so)
        x = np.stack(cols, axis=1)
        rep = _attach_distributed_report(options, rhs_stats, x,
                                         lu_out=lu_out, stats=stats)
    return (x[:, 0] if one_d else x), 0, rep


def _attach_distributed_report(options, rhs_stats, x, lu_out=None,
                               stats=None):
    """Build the SolveReport of a distributed refinement (every rank sees
    the same allreduced berr values, so every rank builds the same
    report) and attach it to the Stats handed back via lu_out."""
    from superlu_dist_tpu.utils.stats import SolveReport
    berrs = [s["berr"] for s in rhs_stats if s.get("berr") is not None]
    target = (options.recovery.berr_target
              or 10.0 * float(np.finfo(np.float64).eps))
    rep = SolveReport(
        berr=max(berrs) if berrs else None,
        berr_history=[b for s in rhs_stats for b in s.get("berrs", [])],
        target=target,
        finite=bool(np.all(np.isfinite(x))))
    rep.refine_steps = sum(s.get("iters", 0) for s in rhs_stats)
    rep.converged = rep.berr is not None and rep.berr <= target
    if stats is None and lu_out is not None:
        stats = lu_out.get("stats")
    if stats is not None:
        # the root factorization's NOREFINE report carries the
        # factorization facts; the distributed refinement supersedes it
        # but inherits them
        prev = stats.solve_report
        if prev is not None:
            rep.tiny_pivots = prev.tiny_pivots
            rep.factor_dtype = prev.factor_dtype
            rep.rcond = prev.rcond
        stats.solve_report = rep
    if lu_out is not None:
        lu_out["solve_report"] = rep
    return rep


def _maybe_escalate_distributed(tc, options, a_loc, b_loc, x, info, rep,
                                lu_out, root=0, grid=None,
                                replicate_analysis=False):
    """One collective escalation rung for the distributed driver: when
    the distributed refinement stagnated above the recovery target,
    rerun the whole flow at the next factor-precision tier.  Every input
    to the decision (the report's berr/target, the shared options) is
    replicated, so all ranks take the same branch — rank-divergent
    control flow here would strand peers in the collectives (which is
    also why the decision must NOT depend on per-rank lu_out presence)."""
    import dataclasses

    from superlu_dist_tpu.drivers.gssvx import _escalation_dtype
    from superlu_dist_tpu.utils.options import Fact, IterRefine
    from superlu_dist_tpu.utils.stats import RungRecord

    recovery = options.recovery
    if (info != 0 or rep is None or rep.converged
            or not recovery.enabled
            or options.iter_refine == IterRefine.NOREFINE):
        return x, info
    from superlu_dist_tpu.utils.options import default_factor_dtype
    cur = options.factor_dtype or default_factor_dtype()
    esc = _escalation_dtype(cur)
    if esc is None:
        return x, info
    opts2 = dataclasses.replace(
        options, fact=Fact.DOFACT, factor_dtype=esc,
        recovery=dataclasses.replace(recovery, enabled=False))
    lu_out2 = {}
    x2, info2 = pgssvx(tc, opts2, a_loc, b_loc, root=root, grid=grid,
                       lu_out=lu_out2,
                       replicate_analysis=replicate_analysis)
    rep2 = lu_out2.get("solve_report")
    berr2 = rep2.berr if rep2 is not None and rep2.berr is not None \
        else float("inf")
    rung = RungRecord(name="distributed-hiprec", detail=str(esc),
                      berr_before=rep.berr, berr_after=berr2)
    rep.rungs.append(rung)
    if info2 == 0 and berr2 < rep.berr:
        rep.berr = berr2
        rep.berr_history.extend(rep2.berr_history if rep2 else [])
        rep.converged = berr2 <= rep.target
        rep.finite = bool(np.all(np.isfinite(x2)))
        if lu_out is not None:
            # the answer now rests on the escalated factors/handle
            lu_out.update(lu_out2)
            lu_out["solve_report"] = rep
        return x2, info2
    return x, info


def _pgssvx_mesh(tc, options, a_loc, b2, grid, one_d, wdtype,
                 lu=None, lu_out=None, replicate_analysis=False):
    """Distributed-factors tier: rank 0 assembles the global analysis
    input and runs the host analysis ONCE, then broadcasts the analyzed
    skeleton (symbolic + plan + transforms + permuted values) over the
    tree — O(nnz) transfer instead of O(nnz) redundant analysis work and
    graph memory on every rank, the wall the reference's distributed
    symbolic was built to break (SRC/psymbfact.c:140,228-242,
    get_perm_c_parmetis.c:104).  All ranks then run ONE mesh-sharded
    numeric factorization in lockstep — the factors, Schur pool, and
    triangular solves are SPMD programs over the grid's (multi-process)
    mesh, so the factors stay sharded across the processes' devices for
    their whole lifetime.  The collective correction solve also serves
    the distributed refinement loop (every rank calls it — the pdgsrfs
    shape where pdgstrs is itself parallel, SRC/pdgsrfs.c:205).

    replicate_analysis=True restores the round-4 every-rank-analyzes
    behavior (kept for A/B measurement, scripts/mesh_analysis_scale.py).
    """
    import dataclasses

    from superlu_dist_tpu.drivers.gssvx import analyze, factorize_numeric
    from superlu_dist_tpu.parallel.pgsrfs import pgsrfs
    from superlu_dist_tpu.utils.errors import SuperLUError
    from superlu_dist_tpu.utils.options import Fact, IterRefine, Trans
    from superlu_dist_tpu.utils.stats import Stats

    n = a_loc.n
    nrhs = b2.shape[1]
    b_full = np.zeros((n, nrhs), dtype=wdtype)
    b_full[a_loc.fst_row:a_loc.fst_row + a_loc.m_loc] = b2
    b_full = tc.allreduce_sum_any(b_full, root=0)

    # refinement runs distributed below (block rows stay with their
    # owners), so the skeleton travels WITHOUT the global matrix: a
    # non-root rank never materializes A, only the analysis products
    opts0 = dataclasses.replace(options, iter_refine=IterRefine.NOREFINE)
    stats = Stats()
    fact = getattr(options, "fact", Fact.DOFACT)
    if fact == Fact.FACTORED:
        # solve-only on the existing sharded factors (every rank holds
        # ITS handle from a prior call's lu_out — pdgssvx's Fact=
        # FACTORED over the grid); the solves below are collective, so
        # a missing handle must fail on EVERY rank, not strand the
        # others inside the SPMD solve
        ok = np.zeros(1)
        ok[0] = 1.0 if (lu is not None and lu.numeric is not None) \
            else 0.0
        ok = tc.allreduce_sum_any(ok)
        if int(ok[0]) != tc.n_ranks:
            raise SuperLUError(
                "Fact=FACTORED requires EVERY rank's prior lu handle "
                f"({int(ok[0])}/{tc.n_ranks} ranks have one)")
        info_r = 0
    elif replicate_analysis:
        a_all = gather_distributed(tc, a_loc, all_ranks=True)
        lu, bvals, _ = analyze(opts0, a_all, stats=stats)
        lu.a = None
    elif getattr(opts0, "par_symb_fact", False):
        # ParSymbFact tier: ordering + symbolic partition across the
        # ranks themselves (parallel/panalysis.py — the ParMETIS +
        # psymbfact shape); root only assembles and plans
        from superlu_dist_tpu.parallel.panalysis import panalyze
        lu, bvals = panalyze(tc, opts0, a_loc, stats=stats)
    else:
        # `lu` (root's prior handle) activates the SamePattern reuse
        # tiers inside analyze
        lu, bvals = root_analyze_bcast(tc, opts0, a_loc, stats, lu=lu)
    if fact != Fact.FACTORED:
        # deadline_comm=tc: Options.deadline_s expiry becomes a
        # COLLECTIVE decision (flag allreduce per poll inside the factor
        # loop, utils/deadline.py), so DeadlineExceededError raises on
        # every rank together — cancellation can never strand a peer in
        # a collective (the SLU101/SLU106 discipline)
        info_r = factorize_numeric(lu, bvals, stats, grid=grid,
                                   deadline_comm=tc)
    if lu_out is not None:
        lu_out["lu"] = lu
        lu_out["stats"] = stats
    if info_r != 0:
        return None, int(info_r), None
    trans = getattr(options, "trans", Trans.NOTRANS)
    if trans == Trans.NOTRANS:
        solve_fn = lu.solve_factored
    else:
        solve_fn = (lambda r: lu.solve_factored_trans(
            r, conj=trans == Trans.CONJ))
    with stats.timer("SOLVE"):
        x_r = solve_fn(b_full if nrhs > 1 else b_full[:, 0])
    x0 = np.asarray(x_r, dtype=wdtype).reshape(n, nrhs)
    # collective_solve=True: every rank calls solve_fn (the mesh solve is
    # an SPMD program all controllers must enter), so no dx broadcast
    return _refine_tail(tc, options, a_loc, b2, x0, solve_fn, 0, one_d,
                        nrhs, lu_out=lu_out, collective_solve=True,
                        stats=stats)
