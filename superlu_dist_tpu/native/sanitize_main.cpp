// Sanitizer harness for the threaded native paths.
//
// The reference has no race detection (SURVEY.md §5: "The new framework
// should add TSAN/ASAN CI instead") — this is that CI hook.  Built by
// tests/test_sanitize.py with -fsanitize=thread (and again with
// =address) against slu_host.cpp, it drives every code path that shares
// memory across threads or processes:
//   * slu_symbolic_mt  — subtree-range threaded symbolic factorization
//   * slu_mlnd_mt      — subtree-threaded multilevel nested dissection
//   * slu_tree_*       — shared-memory tree collectives (threads stand in
//                        for the ranks; the protocol is the same atomics)
// Exit code 0 + no sanitizer report = pass.
//
// Build: g++ -O1 -g -fsanitize=thread -std=c++17 -pthread \
//            sanitize_main.cpp slu_host.cpp -o sanitize_tsan

#include <unistd.h>

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <thread>
#include <vector>

using i64 = int64_t;

extern "C" {
i64 slu_symbolic_mt(i64 n, const i64* indptr, const i64* indices,
                    const i64* parent, i64 relax, i64 max_supernode,
                    i64 nthreads, i64* sn_start, i64* col_to_sn,
                    i64* sn_parent, i64* sn_level, i64* rows_ptr,
                    i64** rows_data);
void slu_etree(i64 n, const i64* indptr, const i64* indices, i64* parent);
void slu_postorder(i64 n, const i64* parent, i64* post);
void slu_free_i64(i64* p);
void slu_mlnd_mt(i64 n, const i64* indptr, const i64* indices,
                 i64 leaf_size, uint64_t seed, i64 nthreads, i64* order);
void* slu_tree_attach(const char* name, i64 n_ranks, i64 max_len, i64 rank,
                      i64 create);
void* slu_tree_attach_shared(void* creator_handle, i64 rank);
void slu_tree_detach(void* h, const char* name, i64 unlink_seg);
void slu_tree_bcast(void* h, i64 root, double* buf, i64 len);
void slu_tree_reduce_sum(void* h, i64 root, double* buf, i64 len);
void slu_tree_set_pid(void* h, i64 pid);
i64 slu_tree_get_pid(void* h, i64 rank);
void slu_tree_heartbeat(void* h);
i64 slu_tree_get_heartbeat(void* h, i64 rank);
i64 slu_tree_post(void* h, double* buf, i64 len);
i64 slu_tree_peek(void* h, i64 rank, double* out, i64 len);
}

// 2-D 5-point Poisson pattern (symmetrized, with diagonal), CSR
static void poisson2d(i64 g, std::vector<i64>& indptr,
                      std::vector<i64>& indices) {
  i64 n = g * g;
  indptr.assign(n + 1, 0);
  indices.clear();
  for (i64 i = 0; i < g; ++i)
    for (i64 j = 0; j < g; ++j) {
      i64 v = i * g + j;
      if (i > 0) indices.push_back(v - g);
      if (j > 0) indices.push_back(v - 1);
      indices.push_back(v);
      if (j + 1 < g) indices.push_back(v + 1);
      if (i + 1 < g) indices.push_back(v + g);
      indptr[v + 1] = (i64)indices.size();
    }
}

static int check_perm(const std::vector<i64>& p, i64 n, const char* what) {
  std::vector<char> seen(n, 0);
  for (i64 v : p) {
    if (v < 0 || v >= n || seen[v]) {
      std::fprintf(stderr, "FAIL: %s not a permutation\n", what);
      return 1;
    }
    seen[v] = 1;
  }
  return 0;
}

int main() {
  int rc = 0;
  std::vector<i64> indptr, indices;
  poisson2d(40, indptr, indices);     // n = 1600
  i64 n = (i64)indptr.size() - 1;

  // threaded ND, serial vs 4 threads must agree (determinism contract)
  std::vector<i64> o1(n), o4(n);
  slu_mlnd_mt(n, indptr.data(), indices.data(), 64, 1, 1, o1.data());
  slu_mlnd_mt(n, indptr.data(), indices.data(), 64, 1, 4, o4.data());
  rc |= check_perm(o4, n, "mlnd_mt");
  if (std::memcmp(o1.data(), o4.data(), n * sizeof(i64)) != 0) {
    std::fprintf(stderr, "FAIL: mlnd nthreads changed the ordering\n");
    rc |= 1;
  }

  // threaded symbolic on the ND-ordered pattern
  {
    // permute pattern by o4 (build CSR of P A P^T)
    std::vector<i64> inv(n);
    for (i64 k = 0; k < n; ++k) inv[o4[k]] = k;
    std::vector<std::vector<i64>> rows(n);
    for (i64 i = 0; i < n; ++i)
      for (i64 p = indptr[i]; p < indptr[i + 1]; ++p)
        rows[inv[i]].push_back(inv[indices[p]]);
    std::vector<i64> pp(n + 1, 0), pi;
    for (i64 i = 0; i < n; ++i) {
      for (i64 j : rows[i]) pi.push_back(j);
      pp[i + 1] = (i64)pi.size();
    }
    std::vector<i64> parent(n), post(n);
    slu_etree(n, pp.data(), pi.data(), parent.data());
    slu_postorder(n, parent.data(), post.data());
    // postorder-permute once more so labels are postordered
    std::vector<i64> inv2(n);
    for (i64 k = 0; k < n; ++k) inv2[post[k]] = k;
    std::vector<std::vector<i64>> rows2(n);
    for (i64 i = 0; i < n; ++i)
      for (i64 p = pp[i]; p < pp[i + 1]; ++p)
        rows2[inv2[i]].push_back(inv2[pi[p]]);
    std::vector<i64> qp(n + 1, 0), qi;
    for (i64 i = 0; i < n; ++i) {
      for (i64 j : rows2[i]) qi.push_back(j);
      qp[i + 1] = (i64)qi.size();
    }
    std::vector<i64> parent2(n);
    slu_etree(n, qp.data(), qi.data(), parent2.data());
    std::vector<i64> sn_start(n + 1), col_to_sn(n), sn_parent(n),
        sn_level(n), rows_ptr(n + 1);
    std::vector<i64> ref_c2s;
    std::vector<i64> ref_rows;
    for (i64 t : {1, 4}) {
      i64* rows_data = nullptr;
      i64 ns = slu_symbolic_mt(n, qp.data(), qi.data(), parent2.data(),
                               8, 64, t, sn_start.data(), col_to_sn.data(),
                               sn_parent.data(), sn_level.data(),
                               rows_ptr.data(), &rows_data);
      if (ns <= 0) {
        std::fprintf(stderr, "FAIL: symbolic_mt(t=%ld) ns=%ld\n",
                     (long)t, (long)ns);
        rc |= 1;
        slu_free_i64(rows_data);
        continue;
      }
      // per-column fill must be identical across thread counts (the
      // Python-level contract; chain merges may differ at boundaries,
      // so compare the per-column supernode ROW structures' footprint:
      // total row-list length and the col_to_sn-induced fill per column)
      std::vector<i64> rows_copy(rows_data, rows_data + rows_ptr[ns]);
      if (t == 1) {
        ref_c2s.assign(col_to_sn.begin(), col_to_sn.end());
        ref_rows = rows_copy;
      } else if (ref_c2s == std::vector<i64>(col_to_sn.begin(),
                                             col_to_sn.end())
                 && ref_rows != rows_copy) {
        // same partition but different row structures => real bug
        std::fprintf(stderr, "FAIL: symbolic_mt t=4 row structures "
                             "differ from t=1 on same partition\n");
        rc |= 1;
      }
      slu_free_i64(rows_data);
    }
  }

  // tree collectives: 6 threads as ranks (flat) then 12 (binary)
  for (i64 nr : {6, 12}) {
    char name[64];
    std::snprintf(name, sizeof name, "/slu_tsan_%d_%ld", getpid(),
                  (long)nr);
    void* root_h = slu_tree_attach(name, nr, 64, 0, 1);
    if (!root_h) {
      std::fprintf(stderr, "FAIL: tree attach (creator)\n");
      return rc | 1;
    }
    std::vector<std::thread> ts;
    std::vector<double> results(nr, 0.0);
    std::vector<char> attach_fail(nr, 0);
    for (i64 r = 1; r < nr; ++r)
      ts.emplace_back([&, r]() {
        // share the creator's mapping: TSAN shadow state is keyed by
        // virtual address, so per-thread mmaps of the same segment
        // would hide every race from it
        void* h = slu_tree_attach_shared(root_h, r);
        if (!h) {
          attach_fail[r] = 1;
          return;
        }
        double buf[8];
        for (int i = 0; i < 8; ++i) buf[i] = (double)r;
        slu_tree_bcast(h, 0, buf, 8);
        double acc[8];
        for (int i = 0; i < 8; ++i) acc[i] = 1.0;
        slu_tree_reduce_sum(h, 0, acc, 8);
        results[r] = buf[0];
        slu_tree_detach(h, nullptr, 0);
      });
    double buf[8] = {42, 42, 42, 42, 42, 42, 42, 42};
    slu_tree_bcast(root_h, 0, buf, 8);
    double acc[8];
    for (int i = 0; i < 8; ++i) acc[i] = 1.0;
    slu_tree_reduce_sum(root_h, 0, acc, 8);
    for (auto& t : ts) t.join();
    slu_tree_detach(root_h, name, 1);
    for (i64 r = 1; r < nr; ++r)
      if (attach_fail[r]) {
        std::fprintf(stderr, "FAIL: attach_shared rank %ld\n", (long)r);
        rc |= 1;
      }
    for (i64 r = 1; r < nr; ++r)
      if (results[r] != 42.0) {
        std::fprintf(stderr, "FAIL: bcast payload rank %ld\n", (long)r);
        rc |= 1;
      }
    if (acc[0] != (double)nr) {
      std::fprintf(stderr, "FAIL: reduce total %f != %ld\n", acc[0],
                   (long)nr);
      rc |= 1;
    }
  }

  // ---- heartbeat / bulletin-board / seqlock stress ----------------------
  // The PR 8 failure-detector surface (pid + heartbeat atomics in the
  // collective domain, wait-free post/peek seqlock on the .ftx board)
  // had never been raced ON PURPOSE: Python-level analysis cannot see
  // these atomics at all, so this is the one component whose
  // thread-safety only a sanitizer run can certify.  8 threads as
  // ranks, every rank concurrently: bumping its heartbeat, re-posting
  // a monotonically-versioned 4-double record into its own board slot,
  // and peeking every peer — asserting each snapshot is INTERNALLY
  // CONSISTENT (all four doubles carry the same value; a torn read the
  // seqlock failed to reject would mix versions).
  {
    const i64 nr = 8, kIters = 400;
    char name[64];
    std::snprintf(name, sizeof name, "/slu_tsan_ftx_%d", getpid());
    void* root_h = slu_tree_attach(name, nr, 8, 0, 1);
    if (!root_h) {
      std::fprintf(stderr, "FAIL: ftx stress attach (creator)\n");
      return rc | 1;
    }
    std::vector<char> fail(nr, 0);
    auto body = [&](void* h, i64 r) {
      slu_tree_set_pid(h, (i64)getpid() + r);
      double rec[4], got[4];
      for (i64 it = 1; it <= kIters; ++it) {
        double v = (double)(r * 1000000 + it);
        for (int j = 0; j < 4; ++j) rec[j] = v;
        slu_tree_post(h, rec, 4);
        slu_tree_heartbeat(h);
        i64 peer = (r + it) % nr;
        i64 ver = slu_tree_peek(h, peer, got, 4);
        if (ver > 0 &&
            (got[0] != got[1] || got[0] != got[2] || got[0] != got[3])) {
          fail[r] = 1;   // torn snapshot slipped past the seqlock
          return;
        }
        if (slu_tree_get_pid(h, peer) < 0) fail[r] = 1;
        (void)slu_tree_get_heartbeat(h, peer);
      }
    };
    std::vector<std::thread> ts;
    for (i64 r = 1; r < nr; ++r)
      ts.emplace_back([&, r]() {
        void* h = slu_tree_attach_shared(root_h, r);
        if (!h) {
          fail[r] = 1;
          return;
        }
        body(h, r);
        slu_tree_detach(h, nullptr, 0);
      });
    body(root_h, 0);
    for (auto& t : ts) t.join();
    // every rank's final post must be readable, committed and exact
    double got[4];
    for (i64 r = 0; r < nr && rc == 0; ++r) {
      i64 ver = slu_tree_peek(root_h, r, got, 4);
      if (ver <= 0 || got[0] != (double)(r * 1000000 + kIters)) {
        std::fprintf(stderr, "FAIL: board slot %ld ver=%ld val=%f\n",
                     (long)r, (long)ver, got[0]);
        rc |= 1;
      }
    }
    slu_tree_detach(root_h, name, 1);
    for (i64 r = 0; r < nr; ++r)
      if (fail[r]) {
        std::fprintf(stderr, "FAIL: ftx stress rank %ld (torn peek or "
                             "attach)\n", (long)r);
        rc |= 1;
      }
  }

  if (rc == 0) std::puts("sanitize harness PASS");
  return rc;
}
