#!/usr/bin/env python
"""SLU106 + SLU109 verify-mode overhead smoke.

Runs TreeComm collectives in fresh subprocesses:

* verify OFF — asserts the collective path allocates NO verifier state
  (``tc._verifier is None``), hands back the reused no-op guard
  singleton, and creates no sibling ``.vfy`` shared-memory segment —
  the acceptance criterion that the disabled path stays zero-overhead;
* verify ON  — asserts the verifier exists, every public collective is
  checked exactly once (composites/chunks exempt), and payloads
  round-trip bit-exactly through the digest-guarded path.

And the SLU109 runtime lock-order verifier (utils/lockwatch.py):

* locks OFF — ``make_lock`` hands out a PLAIN ``threading.Lock`` (no
  wrapper type) and ``lockwatch._WATCH`` stays None: the off path
  allocates no watch state at all;
* locks ON  — nested acquisitions land in the global order graph and
  the wrappers are the instrumented type.

And the SLU111/SLU112/SLU114 program auditor (utils/programaudit.py):

* programs OFF — a full factorization + device solve allocates NO
  auditor state (``programaudit._AUDITOR is None``), performs no extra
  tracing, and the compile census records no audit notes;
* programs ON  — the auditor exists, every distinct program was audited
  exactly once, and the census audit block reports full donation
  coverage.

And the SLU115/SLU116 precision twin (same module, separate knob):

* dtypes OFF — the same workload allocates NO dtype-auditor state
  (``programaudit._DTYPE_AUDITOR is None``) and the two knobs stay
  independent (``SLU_TPU_VERIFY_PROGRAMS=1`` alone must not arm the
  dtype twin, and vice versa);
* dtypes ON  — every submitted program passes ``audit_narrowing`` +
  ``audit_accumulation`` with zero findings and the census ``#dtypes``
  notes match the audit count.

Exit 0 = pass.  Gate contract (shared with run_slulint.sh,
check_nan_guards.sh and check_trace_overhead.py — see
scripts/ci_gates.sh): any regression raises/asserts, which exits
non-zero.  The collective half skips cleanly (exit 0 with a notice)
when the native library is unavailable — the verifier rides the native
tree transport; the lock half has no native dependency and always runs.
"""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

CHILD = r"""
import json, os
import numpy as np
from superlu_dist_tpu import native
if not native.available():
    print(json.dumps({"skip": "native library unavailable"}))
    raise SystemExit(0)
from superlu_dist_tpu.parallel import treecomm

name = f"/slu_vfy_gate_{os.getpid()}"
with treecomm.TreeComm(name, 1, 0, max_len=64, create=True) as tc:
    payload = np.arange(48.0).reshape(6, 8)
    got = tc.bcast_any(payload.copy())
    ok_payload = bool((got == payload).all())
    got = tc.allreduce_sum_any(payload.copy())
    ok_payload &= bool((got == payload).all())
    blob = b"\x01gate\xff" * 13
    ok_payload &= tc.bcast_bytes(blob) == blob
    v = tc._verifier
    print(json.dumps({
        "verifier": type(v).__name__ if v is not None else None,
        # with verification off (and no comm timeout / chaos armed) the
        # public-op entry must have allocated NOTHING: no verifier, no
        # failure detector, no chaos monkey
        "null_guard": (tc._detector is None and tc._chaos is None)
                      if v is None else False,
        "checks": v.checks if v is not None else 0,
        "payload_ok": ok_payload,
    }))
"""


LOCK_CHILD = r"""
import json, threading
from superlu_dist_tpu.utils import lockwatch

a = lockwatch.make_lock("gate.A")
b = lockwatch.make_lock("gate.B")
with a:
    with b:
        pass
plain = type(a) is type(threading.Lock())
print(json.dumps({
    "plain_lock": plain,
    "no_watch": lockwatch._WATCH is None,
    "graph": lockwatch.order_graph(),
    "lock_type": type(a).__name__,
}))
"""


PROG_CHILD = r"""
import json, os
os.environ.setdefault("JAX_PLATFORMS", "cpu")
import jax
jax.config.update("jax_enable_x64", True)
import numpy as np
from superlu_dist_tpu.models.gallery import poisson2d
from superlu_dist_tpu.ordering.dispatch import get_perm_c
from superlu_dist_tpu.sparse.formats import symmetrize_pattern
from superlu_dist_tpu.symbolic.symbfact import symbolic_factorize
from superlu_dist_tpu.utils.options import Options
from superlu_dist_tpu.numeric.plan import build_plan
from superlu_dist_tpu.numeric.factor import numeric_factorize
from superlu_dist_tpu.solve.device import DeviceSolver

a = poisson2d(8)
sym = symmetrize_pattern(a)
sf = symbolic_factorize(sym, get_perm_c(Options(), a, sym))
plan = build_plan(sf)
fact = numeric_factorize(plan, sym.data[sf.value_perm], a.norm_max(),
                         executor="stream")
DeviceSolver(fact).solve(np.ones(plan.n))

from superlu_dist_tpu.utils import programaudit
from superlu_dist_tpu.obs.compilestats import COMPILE_STATS
aud = programaudit._AUDITOR
daud = programaudit._DTYPE_AUDITOR
blk = COMPILE_STATS.audit_block()
print(json.dumps({
    "auditor": aud is not None,
    "audited": len(aud.audited) if aud is not None else 0,
    "dtype_auditor": daud is not None,
    "dtype_audited": len(daud.audited) if daud is not None else 0,
    "dtype_findings": len(daud.findings) if daud is not None else 0,
    "census_programs": blk["programs"],
    "coverage": blk["donation_coverage_pct"],
}))
"""


def run_child(extra_env, code=None):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    for k in ("SLU_TPU_VERIFY_COLLECTIVES", "SLU_TPU_COMM_TIMEOUT_S",
              "SLU_TPU_CHAOS", "SLU_TPU_VERIFY_LOCKS",
              "SLU_TPU_VERIFY_PROGRAMS", "SLU_TPU_VERIFY_DTYPES"):
        env.pop(k, None)
    env.update(extra_env)
    r = subprocess.run([sys.executable, "-c", code or CHILD], env=env,
                       cwd=REPO, stdout=subprocess.PIPE,
                       stderr=subprocess.PIPE)
    if r.returncode != 0:
        sys.stderr.write(r.stderr.decode())
        raise SystemExit(f"child failed (rc={r.returncode})")
    return json.loads(r.stdout.decode().strip().splitlines()[-1])


def fail(msg):
    print(f"FAIL: {msg}", file=sys.stderr)
    raise SystemExit(1)


def main():
    # ---- SLU109 lock-order verifier (no native dependency) --------------
    loff = run_child({}, code=LOCK_CHILD)
    if not loff["plain_lock"]:
        fail(f"lock off-path allocated a wrapper: {loff['lock_type']}")
    if not loff["no_watch"]:
        fail("lock off-path allocated the order-graph watch")
    if loff["graph"]:
        fail(f"lock off-path recorded order edges: {loff['graph']}")
    lon = run_child({"SLU_TPU_VERIFY_LOCKS": "1"}, code=LOCK_CHILD)
    if lon["lock_type"] != "InstrumentedLock":
        fail(f"lock verify mode handed out: {lon['lock_type']}")
    if lon["graph"].get("gate.A") != ["gate.B"]:
        fail(f"lock verify mode missed the A->B edge: {lon['graph']}")
    print("check_verify_overhead: locks OK (off path plain+stateless; "
          "on path records the order graph)")

    # ---- SLU111/112/114 program auditor ---------------------------------
    poff = run_child({}, code=PROG_CHILD)
    if poff["auditor"]:
        fail("program-audit off-path allocated an auditor")
    if poff["dtype_auditor"]:
        fail("dtype-audit off-path allocated an auditor")
    if poff["census_programs"] != 0:
        fail(f"program-audit off-path left census audit notes: {poff}")
    pon = run_child({"SLU_TPU_VERIFY_PROGRAMS": "1"}, code=PROG_CHILD)
    if not pon["auditor"] or pon["audited"] == 0:
        fail(f"program-audit verify mode audited nothing: {pon}")
    if pon["dtype_auditor"]:
        fail("SLU_TPU_VERIFY_PROGRAMS=1 alone armed the dtype twin")
    if pon["census_programs"] != pon["audited"]:
        fail(f"census audit notes disagree with the auditor: {pon}")
    if pon["coverage"] != 100.0:
        fail(f"executors' declared-dead buffers not fully donated: {pon}")
    print(f"check_verify_overhead: programs OK (off path allocates no "
          f"auditor; on path audited {pon['audited']} programs at "
          f"{pon['coverage']}% donation coverage)")

    # ---- SLU115/116 precision (dtype) auditor ---------------------------
    don = run_child({"SLU_TPU_VERIFY_DTYPES": "1"}, code=PROG_CHILD)
    if not don["dtype_auditor"] or don["dtype_audited"] == 0:
        fail(f"dtype-audit verify mode audited nothing: {don}")
    if don["dtype_findings"] != 0:
        fail(f"dtype audit flagged the real executors: {don}")
    if don["auditor"]:
        fail("SLU_TPU_VERIFY_DTYPES=1 alone armed the program auditor")
    if don["census_programs"] != don["dtype_audited"]:
        fail(f"#dtypes census notes disagree with the auditor: {don}")
    print(f"check_verify_overhead: dtypes OK (off path allocates no "
          f"auditor; on path audited {don['dtype_audited']} programs, "
          f"0 findings)")

    # ---- SLU106 collective lockstep verifier ----------------------------
    off = run_child({})
    if off.get("skip"):
        print(f"check_verify_overhead: SKIP ({off['skip']})")
        return
    # ---- off path: no verifier state, no-op guard singleton -------------
    if off["verifier"] is not None:
        fail(f"disabled path allocated a verifier: {off['verifier']}")
    if not off["null_guard"]:
        fail("disabled path allocated detector/chaos state")
    if not off["payload_ok"]:
        fail("payload mismatch with verification off")

    # ---- on path: verifier present, one check per public op -------------
    on = run_child({"SLU_TPU_VERIFY_COLLECTIVES": "1"})
    if on["verifier"] != "LockstepVerifier":
        fail(f"verify mode did not install a verifier: {on['verifier']}")
    if on["checks"] != 3:
        fail(f"expected 3 digest checks (one per public op), got "
             f"{on['checks']}")
    if not on["payload_ok"]:
        fail("payload mismatch with verification on")
    print("check_verify_overhead: OK (off path allocates no verifier "
          "state; on path checks each public collective once)")


if __name__ == "__main__":
    main()
