from superlu_dist_tpu.sparse.formats import (
    SparseCSR, SparseCSC, coo_to_csr, coo_to_csc, symmetrize_pattern,
)
