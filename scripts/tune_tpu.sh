#!/bin/bash
# On-hardware tuning sweep: runs bench.py over problem size x executor
# granularity x blocking x dtype and appends one JSON line per config to
# tune_results.jsonl.  Run when a real chip is reachable:
#
#   bash scripts/tune_tpu.sh [results_file]
#
# Each run reuses the persistent compile cache (.cache/jax), so later
# configs that share kernel shapes start fast.  The bench's watchdog
# guarantees a line per config even if a run degrades.
set -u
cd "$(dirname "$0")/.."
OUT="${1:-tune_results.jsonl}"
run() {
  echo "== $* ==" >&2
  env "$@" BENCH_REPS=3 timeout 1800 python bench.py >> "$OUT" 2>> "${OUT%.jsonl}.err"
  echo >> "$OUT"
}

# problem-size ladder at default blocking
run BENCH_NX=32
run BENCH_NX=40
run BENCH_NX=48

# dispatch granularity at the big size
run BENCH_NX=48 BENCH_GRANULARITY=level

# blocking variants (panel width vs batch count)
run BENCH_NX=48 BENCH_RELAX=128 BENCH_MAXSUPER=512
run BENCH_NX=48 BENCH_RELAX=512 BENCH_MAXSUPER=2048

# native-MXU-rate factors (IR recovers f64 residuals; more steps)
run BENCH_NX=48 BENCH_DTYPE=bfloat16

# past single-chip factor memory: host offload engages automatically
run BENCH_NX=56

grep -h '"value"' "$OUT" | python -c '
import json, sys
rows = [json.loads(l) for l in sys.stdin if l.strip()]
rows.sort(key=lambda r: -(r.get("value") or 0))
for r in rows:
    print(f"{r.get('"'"'value'"'"'):>10} GF/s  {r.get('"'"'metric'"'"','"'"''"'"')}  "
          f"blocking={r.get('"'"'blocking'"'"')} gran={r.get('"'"'granularity'"'"')} "
          f"resid={r.get('"'"'residual'"'"')}")
'
