"""Expert driver: the full solve pipeline with factorization-reuse tiers.

Analog of pdgssvx (SRC/pdgssvx.c:505): equilibrate → row-permute (maximum
product matching with scalings) → column-order → symbolic → plan ("distribute")
→ numeric factor → solve → iterative refinement, with the reference's Fact
reuse modes (superlu_defs.h:489-510):

  DOFACT                  — everything from scratch
  SamePattern             — reuse column order + symbolic + plan
  SamePattern_SameRowPerm — additionally reuse scalings + row permutation,
                            only redo the numeric factorization
  FACTORED                — reuse the numeric factors; solve + refine only

Permutation algebra (careful!): with equilibration scalings Dr, Dc, matching
scalings r1, c1 and row order ρ, the factored matrix is
    M = Pπ · (diag(R) A diag(C))[ρ] · Pπᵀ,  R = r1·dr, C = dc·c1
where π is the fill-reducing + postorder column permutation.  Then
A·x = b is solved as
    d = (R ⊙ b)[ρ][π] ;  M·ẑ = d ;  z[π] = ẑ ;  x = C ⊙ z.
"""

from __future__ import annotations

import dataclasses
import time

import numpy as np

from superlu_dist_tpu.sparse.formats import SparseCSR, symmetrize_pattern
from superlu_dist_tpu.utils.options import (
    Options, Fact, RowPerm, IterRefine, Trans, default_factor_dtype,
    print_options)
from superlu_dist_tpu.utils.stats import Stats, SolveReport, RungRecord
from superlu_dist_tpu.utils.errors import (
    SuperLUError, SingularMatrixError, NumericBreakdownError,
    PatternMismatchError, RefactorRollbackError)
from superlu_dist_tpu.rowperm.equil import gsequ, laqgs
from superlu_dist_tpu.rowperm.matching import (
    maximum_product_matching, approximate_weight_matching)
from superlu_dist_tpu.ordering.dispatch import get_perm_c
from superlu_dist_tpu.symbolic.symbfact import symbolic_factorize, SymbolicFact
from superlu_dist_tpu.numeric.plan import build_plan, FactorPlan
from superlu_dist_tpu.numeric.factor import numeric_factorize, NumericFactorization
from superlu_dist_tpu.solve.trisolve import lu_solve, lu_solve_trans
from superlu_dist_tpu.refine.ir import iterative_refinement
from superlu_dist_tpu.utils import tols


@dataclasses.dataclass
class LUFactorization:
    """Persistent factorization handle — the {ScalePermstruct, LUstruct,
    SOLVEstruct} bundle of the reference API (superlu_ddefs.h:76-82,186-228)."""

    n: int
    options: Options
    equed: str
    dr: np.ndarray            # equilibration row scaling (or ones)
    dc: np.ndarray
    r1: np.ndarray            # matching scalings (or ones)
    c1: np.ndarray
    row_order: np.ndarray     # ρ: position j <- original row ρ[j]
    col_order: np.ndarray     # fill-reducing order fed to symbolic
    sf: SymbolicFact = None
    plan: FactorPlan = None
    numeric: NumericFactorization = None
    anorm: float = 0.0
    a: SparseCSR = None       # original matrix (for refinement SpMV)
    berrs: list = None        # backward errors of the last refinement
    a_sym_indptr: np.ndarray = None    # symmetrized pattern the symbolic
    a_sym_indices: np.ndarray = None   # factorization was built on
    dev_spmv: object = None            # cached DeviceSpMV per (trans,
                                       # dtype) — pdgsmv_init discipline
    dev_solver: object = None          # lazy DeviceSolver (SolveInitialized
                                       # analog, pdgssvx.c:1330-1337)
    solve_path: str = "auto"           # "auto" | "host" | "device"; "auto"
                                       # falls back to host if the device
                                       # solve ever fails (robustness over
                                       # crash — the pdtest harness survives
                                       # partial failures, TEST/pdtest.c)
    solve_fallback_reason: str = None  # why the device path was abandoned
    mesh: object = None                # the grid mesh the factors are
                                       # sharded over (None off-grid).  When
                                       # it spans multiple PROCESSES the
                                       # solve must run collectively on it —
                                       # no process can pull the whole
                                       # factor (pdgstrs over the process
                                       # grid, SRC/pdgstrs.c:838)
    pattern_digest: str = None         # identity latch for the refactor
    plan_fp: str = None                # pipeline: sha256 of the symmetrized
                                       # permuted pattern + the plan
                                       # fingerprint, latched lazily on
                                       # first refactor (persist/serial.py
                                       # computes both; bundles record the
                                       # pattern digest in their meta)

    def identity(self) -> tuple:
        """Latch and return ``(pattern_digest, plan_fingerprint)`` — the
        refactor pipeline's identity discipline: a values-only refactor
        reuses symbolic + plan + compiled programs by OBJECT identity,
        so the handle carries a durable fingerprint of both and drift
        raises :class:`PatternMismatchError` instead of silently
        re-running symbolic."""
        from superlu_dist_tpu.persist.serial import (
            pattern_digest, plan_fingerprint)
        if self.pattern_digest is None and self.a_sym_indptr is not None:
            self.pattern_digest = pattern_digest(self.a_sym_indptr,
                                                 self.a_sym_indices)
        if self.plan_fp is None and self.plan is not None:
            self.plan_fp = plan_fingerprint(self.plan)
        return self.pattern_digest, self.plan_fp

    # -- combined transforms --------------------------------------------------
    @property
    def R(self):
        return self.r1 * self.dr

    @property
    def C(self):
        return self.dc * self.c1

    @property
    def sigma(self):
        """Composite row order: M rows <- original rows sigma[k]."""
        return self.row_order[self.sf.perm]

    def solve_factored(self, b: np.ndarray) -> np.ndarray:
        """Solve A·x = b through the factored M (no refinement).

        On an accelerator backend the triangular solves run device-side
        (solve/device.py, the pdgstrs analog) so the factors never cross
        the host boundary; on CPU the host supernodal solve is used (f64,
        which also serves the refinement's correction solves)."""
        if not self.numeric.finite:
            raise SingularMatrixError(self.numeric.info_col)
        b = np.asarray(b)
        d = b * (self.R[:, None] if b.ndim > 1 else self.R)
        d = d[self.sigma]
        z_hat = self._solve_permuted(d)
        z = np.empty_like(z_hat)
        z[self.sf.perm] = z_hat
        return z * (self.C[:, None] if b.ndim > 1 else self.C)

    def solve_factored_trans(self, b: np.ndarray,
                             conj: bool = False) -> np.ndarray:
        """Solve Aᵀ·x = b (or Aᴴ·x with conj) through the same factors.

        The reference's trans_t path (superlu_defs.h:628-657): with
        M = P_σ·diag(R)·A·diag(C)·P_πᵀ the transpose system becomes
        Mᵀ·(P_σ (x⊘R)) = P_π (C ⊙ b) — same transforms, mirrored order,
        solved via Uᵀ then Lᵀ sweeps (solve/trisolve.lu_solve_trans)."""
        if not self.numeric.finite:
            raise SingularMatrixError(self.numeric.info_col)
        b = np.asarray(b)
        C = self.C[:, None] if b.ndim > 1 else self.C
        R = self.R[:, None] if b.ndim > 1 else self.R
        d = (b * C)[self.sf.perm]
        w_hat = self._solve_permuted_trans(d, conj)
        w = np.empty_like(w_hat)
        w[self.sigma] = w_hat
        return w * R

    def _solve_permuted_trans(self, d: np.ndarray, conj: bool) -> np.ndarray:
        return self._dispatch_solve(
            lambda s: s.solve_trans(d, conj=conj),
            lambda: lu_solve_trans(self.numeric, d, conj=conj))

    def _dispatch_solve(self, device_call, host_call):
        """Shared device-vs-host solve dispatch with the auto-fallback
        discipline (one copy — the plain and transpose paths must never
        drift)."""
        import warnings

        import jax
        # a mesh spanning multiple processes means no process holds the
        # whole factor: the solve MUST run collectively on the mesh (and
        # a host fallback is impossible — it would read non-addressable
        # shards), exactly like the reference's pdgstrs event loop over
        # the process grid (SRC/pdgstrs.c:838)
        multiproc = self.mesh is not None and jax.process_count() > 1
        use_device = (multiproc
                      or self.solve_path == "device"
                      or (self.solve_path == "auto"
                          and jax.default_backend() != "cpu"
                          # offloaded (host-resident) factors solve on the
                          # host — re-uploading them each solve would cost
                          # more than the device solve saves
                          and not self.numeric.on_host))
        # a SINGLE-process mesh routes to the shard_map SPMD tier
        # (parallel/spmd.SpmdSolver): the whole fwd+bwd sweep is ONE
        # compiled program per nrhs bucket, bitwise-identical to the
        # local DeviceSolver (so the lockstep fallback below stays a
        # valid recovery path)
        spmd = False
        if (self.mesh is not None and not multiproc
                and self.solve_path != "host"
                and not self.numeric.on_host):
            from superlu_dist_tpu.parallel.spmd import spmd_mode
            spmd = spmd_mode()
            use_device = use_device or spmd
        if use_device:
            try:
                if self.dev_solver is None:
                    if spmd:
                        from superlu_dist_tpu.parallel.spmd import SpmdSolver
                        self.dev_solver = SpmdSolver(
                            self.numeric, self.mesh,
                            schedule=self.options.solve_schedule,
                            window=self.options.solve_window,
                            align=self.options.solve_align,
                            gemm_prec=getattr(self.options, "gemm_prec",
                                              None))
                    else:
                        from superlu_dist_tpu.solve.device import DeviceSolver
                        # multiproc: streamed sweeps (fused=False) — the
                        # whole-sweep programs at n≈1e5 hit the same compile
                        # wall as the fused factor executor (see
                        # factor.get_executor's auto rule)
                        self.dev_solver = DeviceSolver(
                            self.numeric, diag_inv=self.options.diag_inv,
                            mesh=self.mesh if multiproc else None,
                            fused=False if multiproc else "auto",
                            schedule=self.options.solve_schedule,
                            window=self.options.solve_window,
                            align=self.options.solve_align,
                            gemm_prec=getattr(self.options, "gemm_prec",
                                              None))
                return device_call(self.dev_solver)
            except Exception as e:
                if self.solve_path != "auto" or multiproc:
                    raise
                # device path failed — permanently fall back to the host
                # solve for this factorization rather than crash the run
                self.solve_path = "host"
                self.solve_fallback_reason = f"{type(e).__name__}: {e}"
                warnings.warn("device solve failed; falling back to host "
                              f"solve ({self.solve_fallback_reason})",
                              RuntimeWarning, stacklevel=3)
        return host_call()

    def _solve_permuted(self, d: np.ndarray) -> np.ndarray:
        return self._dispatch_solve(lambda s: s.solve(d),
                                    lambda: lu_solve(self.numeric, d))


def analyze(options: Options, a: SparseCSR,
            lu: LUFactorization | None = None,
            stats: Stats | None = None):
    """The host analysis phases only: EQUIL → ROWPERM → COLPERM →
    SYMBFACT → DIST/plan (pdgssvx.c:647-1166 before pdgstrf).

    Returns ``(lu, bvals, stats)``: `lu` is an LUFactorization skeleton
    (numeric=None) carrying every transform plus the symbolic/plan, and
    `bvals` the structurally-permuted matrix values ready for
    factorize_numeric.  The split exists so the distributed-factors tier
    can run the analysis ONCE (on root) and broadcast the skeleton —
    O(nnz) transfer instead of O(nnz) redundant work and memory on every
    rank, the wall the reference's symbfact_dist was built to break
    (SRC/psymbfact.c:140,228-242).
    """
    if stats is None:
        stats = Stats()
    n = a.n_rows
    if a.n_cols != n:
        raise SuperLUError("A must be square")
    fact = options.fact

    reuse_rowperm = fact == Fact.SamePattern_SameRowPerm and lu is not None
    reuse_colperm = fact in (Fact.SamePattern, Fact.SamePattern_SameRowPerm) \
        and lu is not None
    if reuse_colperm and lu.sf is not None and lu.sf.value_perm is None:
        # a panalyze (ParSymbFact) skeleton assembles values directly and
        # records no value-gather map; the reuse tiers need one
        raise SuperLUError(
            "Fact reuse tiers require a serial-analysis skeleton; this one "
            "came from the distributed analysis (parallel/panalysis.py) — "
            "re-analyze with Fact=DOFACT")
    # Symbolic/plan reuse tiers.  Our symbolic runs on the row-permuted
    # pattern, so reuse is sound iff the row permutation is unchanged:
    # always true under SamePattern_SameRowPerm, and detected dynamically
    # under plain SamePattern after the fresh matching below (the common
    # time-stepping case — values drift, MC64 returns the same matching).
    # The reference's own plain-SamePattern tier likewise re-runs symbfact
    # (the pdgssvx.c:1034 gate skips it only for SamePattern_SameRowPerm)
    # and reuses perm_c + etree; detecting the equal-row-perm case reuses
    # strictly more than the reference whenever it fires.
    reuse_symbolic = reuse_rowperm

    # ---- EQUIL (pdgssvx.c:647-760) -----------------------------------------
    with stats.timer("EQUIL"):
        if reuse_rowperm:
            dr, dc, equed = lu.dr, lu.dc, lu.equed
            a1 = a.row_scale(dr).col_scale(dc) if equed != "N" else a
        elif options.equil:
            r, c, rowcnd, colcnd, amax = gsequ(a)
            a1, equed = laqgs(a, r, c, rowcnd, colcnd, amax)
            dr = r if equed in ("R", "B") else np.ones(n)
            dc = c if equed in ("C", "B") else np.ones(n)
        else:
            a1, equed = a, "N"
            dr = dc = np.ones(n)

    # ---- ROWPERM (pdgssvx.c:793-937) ---------------------------------------
    with stats.timer("ROWPERM"):
        if reuse_rowperm:
            row_order, r1, c1 = lu.row_order, lu.r1, lu.c1
            a2 = a1.row_scale(r1).col_scale(c1).permute(perm_r=row_order)
        elif options.row_perm == RowPerm.LargeDiag_MC64:
            row_order, r1, c1 = maximum_product_matching(a1)
            a2 = a1.row_scale(r1).col_scale(c1).permute(perm_r=row_order)
        elif options.row_perm == RowPerm.LargeDiag_AWPM:
            row_order = approximate_weight_matching(a1)
            r1 = c1 = np.ones(n)
            a2 = a1.permute(perm_r=row_order)
        elif options.row_perm == RowPerm.MY_PERMR:
            row_order = np.asarray(options.user_perm_r, dtype=np.int64)
            r1 = c1 = np.ones(n)
            a2 = a1.permute(perm_r=row_order)
        else:
            row_order = np.arange(n, dtype=np.int64)
            r1 = c1 = np.ones(n)
            a2 = a1

    if reuse_colperm and not reuse_symbolic and lu.sf is not None \
            and np.array_equal(row_order, lu.row_order):
        # plain SamePattern, and the fresh matching reproduced the prior
        # row order: the permuted pattern is unchanged, so the symbolic
        # and plan carry over (verified structurally by the DIST check
        # below) — SYMBFACT+DIST drop to ~0 while ROWPERM re-ran
        reuse_symbolic = True

    anorm = a2.norm_max()
    sym = symmetrize_pattern(a2)

    # ---- COLPERM (pdgssvx.c:958-1031) --------------------------------------
    with stats.timer("COLPERM"):
        if reuse_colperm:
            col_order = lu.col_order
        else:
            col_order = get_perm_c(options, a2, sym)

    # ---- ETREE + SYMBFACT (pdgssvx.c:1034-1118) ----------------------------
    et0 = stats.utime["ETREE"]
    with stats.timer("SYMBFACT"):
        if reuse_symbolic:
            sf = lu.sf
        else:
            sf = symbolic_factorize(sym, col_order, relax=options.relax,
                                    max_supernode=options.max_supernode,
                                    stats=stats, amalg_tol=options.amalg_tol)
    # phases are disjoint like the reference's PhaseType: the etree part
    # timed inside symbolic_factorize is carved out of SYMBFACT
    stats.utime["SYMBFACT"] -= stats.utime["ETREE"] - et0

    # ---- DIST / plan (pdgssvx.c:1132-1166) ---------------------------------
    with stats.timer("DIST"):
        if reuse_symbolic:
            plan = lu.plan
        else:
            plan = build_plan(sf, min_bucket=options.min_bucket,
                              growth=options.bucket_growth,
                              schedule=options.schedule,
                              window=options.sched_window,
                              align=options.sched_align,
                              closed=options.bucket_closed)
        pattern_mismatch = sym.nnz != len(sf.value_perm)
        if not pattern_mismatch and reuse_symbolic:
            # nnz equality is not enough: a moved entry with equal count
            # would gather values into wrong structural slots silently
            pattern_mismatch = not (
                np.array_equal(sym.indptr, lu.a_sym_indptr)
                and np.array_equal(sym.indices, lu.a_sym_indices))
        if pattern_mismatch:
            raise SuperLUError(
                f"Fact={fact.name} reuse requires the same sparsity pattern "
                f"as the factorization being reused")
        bvals = sym.data[sf.value_perm]

    lu = LUFactorization(n=n, options=options, equed=equed, dr=dr, dc=dc,
                         r1=r1, c1=c1, row_order=row_order,
                         col_order=col_order, sf=sf, plan=plan,
                         numeric=None, anorm=anorm, a=a,
                         a_sym_indptr=sym.indptr, a_sym_indices=sym.indices)
    return lu, bvals, stats


def factorize_numeric(lu: LUFactorization, bvals: np.ndarray,
                      stats: Stats | None = None, grid=None,
                      resume_from: str | None = None,
                      deadline_comm=None) -> int:
    """Numeric factorization (pdgssvx.c:1176 → pdgstrf, SRC/pdgstrf.c:243)
    on an analyzed skeleton from `analyze`.

    With `grid`, the factorization runs sharded over the grid's mesh —
    when that mesh spans multiple processes this is an SPMD collective
    every rank must enter with the SAME skeleton and values (the
    distributed-factors tier broadcasts them first).  Fills lu.numeric in
    place; returns info (0, or 1-based first zero-pivot column).

    Crash consistency (docs/RELIABILITY.md): ``Options.ckpt_every`` arms
    mid-factor frontier checkpoints; ``resume_from`` restarts from a
    durable checkpoint instead of from scratch (recorded on
    ``stats.resume`` and as a SolveReport rung by the solve tail);
    ``Options.deadline_s`` bounds the factor loop, with ``deadline_comm``
    (a TreeComm on the distributed tier) making expiry a collective
    decision so cancellation can never strand a rank in a collective."""
    if stats is None:
        stats = Stats()
    options = lu.options
    plan = lu.plan
    from superlu_dist_tpu.numeric.stream import RETRACE_SENTINEL
    from superlu_dist_tpu.obs.compilestats import COMPILE_STATS
    retr0 = RETRACE_SENTINEL.total
    comp0 = COMPILE_STATS.marker()
    dtype = options.factor_dtype or default_factor_dtype()
    if np.issubdtype(np.asarray(bvals).dtype, np.complexfloating):
        dtype = {"float32": "complex64", "float64": "complex128"}.get(str(dtype), dtype)
    deadline = None
    if options.deadline_s:
        from superlu_dist_tpu.utils.deadline import Deadline
        from superlu_dist_tpu.utils.options import env_int
        deadline = Deadline(options.deadline_s, comm=deadline_comm,
                            poll_every=env_int("SLU_TPU_DEADLINE_POLL"))
    # checkpoints need a single-process pool boundary; the multi-process
    # mesh shards it, so only the deadline travels onto the grid tier
    want_ckpt = options.ckpt_every > 0 and grid is None
    with stats.timer("FACT"):
        if str(dtype) == "df64":
            if resume_from:
                raise SuperLUError(
                    "resume_from is not supported for df64 factorization "
                    "(its factor loop has no checkpoint boundaries yet)")
            # emulated-double factorization for f32-only hardware (true
            # ~2^-48 factors; SURVEY.md §7 hard-part 1), real AND complex
            # (zdf64, the pzgstrf twin — SRC/pzgstrf.c:243); host
            # f64/c128 factors come back, so the standard solve path
            # applies
            from superlu_dist_tpu.numeric.df64_factor import (
                df64_numeric_factorize)
            numeric = df64_numeric_factorize(
                plan, bvals, lu.anorm,
                replace_tiny=options.replace_tiny_pivot,
                mesh=grid.mesh if grid is not None else None,
                pool_partition=options.pool_partition,
                check_finite=options.recovery.sentinels)
        else:
            numeric = numeric_factorize(
                plan, bvals, lu.anorm, dtype=dtype,
                replace_tiny=options.replace_tiny_pivot,
                executor=getattr(options, "executor", "auto") or "auto",
                mesh=grid.mesh if grid is not None else None,
                pool_partition=options.pool_partition,
                check_finite=options.recovery.sentinels,
                ckpt_dir=(options.ckpt_dir or None) if want_ckpt else None,
                ckpt_every=options.ckpt_every if want_ckpt else 0,
                resume_from=resume_from,
                deadline=deadline,
                gemm_prec=getattr(options, "gemm_prec", None))
        for lp, up in numeric.fronts:
            if hasattr(lp, "block_until_ready"):
                lp.block_until_ready()
                up.block_until_ready()
    stats.ops["FACT"] += plan.flops
    stats.tiny_pivots += numeric.tiny_pivots
    # dispatch-schedule telemetry (numeric/plan.py): surfaced on the
    # same Stats the PStatPrint-analog report prints; bytes_moved uses
    # the factor dtype's real itemsize (df64 = paired f64 components)
    try:
        _isz = np.dtype(dtype).itemsize
    except TypeError:
        _isz = 16
    stats.sched = plan.schedule_stats(itemsize=_isz)
    # retrace sentinel (runtime SLU106): unexpected recompiles during
    # THIS factorization, surfaced on the same Stats the report prints
    stats.retraces += RETRACE_SENTINEL.total - retr0
    # compile census (obs/compilestats.py): the jit builds THIS
    # factorization paid, as a stats.compile block in the same report
    stats.compile = COMPILE_STATS.block(since=comp0)
    from superlu_dist_tpu.obs.metrics import get_metrics
    m = get_metrics()
    if m.enabled:
        sched = stats.sched
        m.inc("slu_factorizations_total", 1.0,
              schedule=sched.get("schedule", "?"))
        # throughput-ladder telemetry: which GEMM tier the factors ran
        # at (the escalation rung increments this again per refactor)
        m.inc("slu_gemm_precision_total", 1.0,
              tier=getattr(numeric, "gemm_prec", "highest"))
        m.set("slu_schedule_groups", sched.get("n_groups", 0))
        m.set("slu_schedule_occupancy", sched.get("occupancy", 0.0))
        m.set("slu_schedule_critical_path", sched.get("critical_path", 0))
        m.inc("slu_compile_builds_total",
              float(stats.compile.get("builds", 0)))
        m.inc("slu_compile_seconds_total",
              float(stats.compile.get("seconds", 0.0)))
    # memory observability (dQuerySpace_dist analog, SRC/dmemory_dist.c:73)
    from superlu_dist_tpu.numeric.factor import query_space
    space = query_space(numeric)
    stats.observe_memory(space["total_bytes"])
    stats.for_lu_bytes = space["for_lu_bytes"]
    stats.pool_bytes = space["pool_bytes"]

    if getattr(numeric, "resumed_groups", 0):
        # resume telemetry: surfaced in the Stats report and recorded as
        # an escalation-ladder rung on the SolveReport by the solve tail
        stats.resume = {"groups": int(numeric.resumed_groups),
                        "of": len(plan.groups),
                        "path": str(resume_from)}
    lu.numeric = numeric
    lu.mesh = grid.mesh if grid is not None else None
    # invalidate solve-side caches from any prior factorization the
    # skeleton was reused from
    lu.dev_solver = None
    if not numeric.finite:
        # exactly singular U and no tiny-pivot replacement: info is the
        # 1-based first zero-pivot column, like the reference's Allreduce-MIN
        # of the first i with U(i,i)==0 (pdgstrf.c:1920-1924)
        return numeric.info_col + 1
    return 0


# per-process refactor counter: the chaos harness's `kill_refactor@step=K`
# spec is scoped to the Kth refactor of the victim process (0-based)
_REFACTOR_SEQ = [0]


def refactor(lu: LUFactorization, new_values,
             stats: Stats | None = None, canary_b: np.ndarray = None,
             berr_max: float | None = None):
    """Values-only refactorization — the middle rung of the Fact ladder
    (SamePattern_SameRowPerm economics as a first-class crash-consistent
    verb, ROADMAP item 2).

    ``new_values`` is either a :class:`SparseCSR` with the SAME sparsity
    pattern the handle was analyzed on, or a raw data array replacing
    ``lu.a.data`` entry-for-entry.  The symbolic structure, FactorPlan,
    bucket set AND compiled programs are reused by object identity —
    zero symbolic seconds and zero fresh-compile seconds by construction
    (the executor cache on ``plan._factor_fns`` is keyed by the plan
    object; ``stats.compile['fresh_seconds']`` proves it per call).

    Identity discipline: the pattern digest + plan fingerprint are
    latched on the handle (:meth:`LUFactorization.identity`); a matrix
    whose symmetrized permuted pattern drifts from the latch raises a
    structured :class:`PatternMismatchError` instead of silently
    re-running symbolic.

    Commit protocol (adopt-only-on-improvement): the numeric
    factorization runs against a SHADOW copy of the handle — in-flight
    solves keep the previous panels — and is adopted onto ``lu`` only
    after (a) the factorization finished finite (breakdown sentinels /
    singularity reject at ``stage='factor'``), and (b) the BERR canary
    passed: one un-refined solve of ``canary_b`` (default: ones) must
    come back finite, and — when a gate is armed via ``berr_max`` /
    ``SLU_TPU_REFACTOR_BERR_MAX`` — with componentwise backward error
    at or below it.  A canary miss at a reduced GEMM tier first climbs
    the PR 15 escalation ladder (``SLU_TPU_REFACTOR_ESCALATE``) one
    tier per rung; if the ladder tops out the refactor raises
    :class:`RefactorRollbackError` and ``lu`` is untouched.  An
    interrupted refactor (kill -9, deadline, poisoned values — the
    ``kill_refactor``/``poison_values`` chaos specs) always leaves the
    previous consistent handle serving.

    Returns ``stats``; on success ``lu`` serves the new factors (its
    ``numeric``/``a``/``anorm`` swapped, device caches invalidated)."""
    if stats is None:
        stats = Stats()
    step = _REFACTOR_SEQ[0]
    _REFACTOR_SEQ[0] += 1
    from superlu_dist_tpu.obs.metrics import get_metrics
    m = get_metrics()
    if m.enabled:
        m.inc("slu_refactor_total", 1.0)

    if lu.sf is None or lu.plan is None:
        raise SuperLUError(
            "refactor requires an analyzed handle (lu.sf/lu.plan is "
            "None — run analyze/gssvx first)")
    if lu.sf.value_perm is None:
        raise SuperLUError(
            "refactor requires a serial-analysis skeleton; this one came "
            "from the distributed analysis (parallel/panalysis.py) — "
            "re-analyze with Fact=DOFACT")
    if lu.a_sym_indptr is None:
        raise SuperLUError(
            "refactor requires the handle's analyzed pattern "
            "(a_sym_indptr is None — e.g. a hand-built skeleton); "
            "re-analyze with Fact=DOFACT")
    expected_digest, _ = lu.identity()

    # ---- new-values intake + pattern identity check ------------------------
    a_new = new_values
    if not hasattr(a_new, "indptr"):
        vals = np.asarray(new_values)
        if lu.a is None:
            raise SuperLUError(
                "refactor from a raw value array needs the handle's "
                "matrix for its pattern (lu.a is None — pass a SparseCSR "
                "instead)")
        if vals.ndim != 1 or vals.shape[0] != lu.a.nnz:
            raise PatternMismatchError(
                f"value array has {vals.shape} entries, the handle's "
                f"pattern has {lu.a.nnz} nonzeros",
                expected_digest=expected_digest, n=lu.n, nnz=lu.a.nnz)
        a_new = SparseCSR(lu.a.n_rows, lu.a.n_cols, lu.a.indptr,
                          lu.a.indices, vals)
    if a_new.n_rows != lu.n or a_new.n_cols != lu.n:
        raise PatternMismatchError(
            f"matrix is {a_new.n_rows}x{a_new.n_cols}, the handle was "
            f"analyzed at n={lu.n}", expected_digest=expected_digest,
            n=lu.n)
    # apply the handle's stored transforms to the new matrix (the
    # SamePattern_SameRowPerm recipe: reuse scalings + row order), then
    # verify the symmetrized permuted pattern is EXACTLY the analyzed one
    # — nnz equality is not enough, a moved entry with equal count would
    # gather values into wrong structural slots silently
    a1 = (a_new.row_scale(lu.dr).col_scale(lu.dc)
          if lu.equed != "N" else a_new)
    a2 = a1.row_scale(lu.r1).col_scale(lu.c1).permute(perm_r=lu.row_order)
    sym = symmetrize_pattern(a2)
    if sym.nnz != len(lu.sf.value_perm) or not (
            np.array_equal(sym.indptr, lu.a_sym_indptr)
            and np.array_equal(sym.indices, lu.a_sym_indices)):
        from superlu_dist_tpu.persist.serial import pattern_digest
        raise PatternMismatchError(
            "the matrix's symmetrized permuted pattern differs from the "
            "one the handle's symbolic structure was built on",
            expected_digest=expected_digest,
            got_digest=pattern_digest(sym.indptr, sym.indices),
            n=lu.n, nnz=sym.nnz)
    bvals = sym.data[lu.sf.value_perm]
    anorm = a2.norm_max()

    # ---- chaos hooks (testing/chaos.py, consulted once per refactor) -------
    from superlu_dist_tpu.testing.chaos import get_refactor_chaos
    monkey = get_refactor_chaos()
    if monkey is not None:
        bvals = monkey.poison_refactor_values(lu.plan, bvals)
        if monkey.refactor_kill_due(step):
            # mid-refactor: the new values are staged, nothing adopted —
            # crash consistency demands the previous handle (and any
            # bundle on disk) survive this untouched
            monkey.kill_now()

    # ---- shadow numeric factorization (adopt-only-on-improvement) ----------
    from superlu_dist_tpu.refine.ir import request_berrs
    from superlu_dist_tpu.ops.dense import next_gemm_precision
    from superlu_dist_tpu.utils.options import env_flag, env_float
    if berr_max is None:
        berr_max = env_float("SLU_TPU_REFACTOR_BERR_MAX")
    escalate = env_flag("SLU_TPU_REFACTOR_ESCALATE")
    if canary_b is None:
        canary_b = np.ones(lu.n, dtype=np.asarray(a_new.data).dtype)

    def rollback(stage, cause="", berr=-1.0):
        if m.enabled:
            m.inc("slu_refactor_rollbacks_total", 1.0, stage=stage)
        return RefactorRollbackError(
            "handle", stage=stage, cause=cause, berr=berr,
            berr_target=berr_max if berr_max > 0 else -1.0)

    tier = None                    # None = the handle's configured tier
    rungs = max(int(lu.options.recovery.max_rungs), 1)
    shadow = None
    for rung in range(rungs):
        opts = (lu.options if tier is None
                else dataclasses.replace(lu.options, gemm_prec=tier))
        shadow = dataclasses.replace(
            lu, numeric=None, dev_solver=None, dev_spmv=None, berrs=None,
            a=a_new, anorm=anorm, options=opts)
        try:
            info = factorize_numeric(shadow, bvals, stats)
        except SuperLUError as e:
            raise rollback("factor", f"{type(e).__name__}: {e}") from e
        if info != 0:
            raise rollback("factor", f"singular: info={info}")
        # ---- BERR canary (refine/ir.py — one solve + one SpMV pair) ----
        try:
            x = shadow.solve_factored(canary_b)
            finite = bool(np.all(np.isfinite(np.asarray(x))))
            berr = (float(request_berrs(a_new, canary_b, x).max())
                    if finite else float("inf"))
        except SuperLUError as e:
            raise rollback("canary", f"{type(e).__name__}: {e}") from e
        if finite and (berr_max <= 0 or berr <= berr_max):
            break
        nxt = next_gemm_precision(
            getattr(shadow.numeric, "gemm_prec", "highest"))
        if not escalate or nxt is None or rung == rungs - 1:
            raise rollback(
                "canary",
                "non-finite canary X" if not finite else
                "canary backward error above the gate", berr=berr)
        # the PR 15 escalation machinery: retry the shadow one GEMM
        # tier up — same plan, same programs at that tier's cache slot
        tier = nxt
        if m.enabled:
            m.inc("slu_recovery_rungs_total", 1.0,
                  rung="refactor-gemm-precision", improved="pending")

    # ---- atomic adoption ---------------------------------------------------
    # single-field rebinds onto the live handle: a concurrent solve holds
    # either the complete old numeric or the complete new one (the serve
    # tier additionally serializes via its swap lock)
    lu.numeric = shadow.numeric
    lu.mesh = shadow.mesh
    lu.dev_solver = None
    lu.dev_spmv = None
    lu.berrs = None
    lu.a = a_new
    lu.anorm = anorm
    if tier is not None:
        lu.options = shadow.options
    if m.enabled:
        m.inc("slu_refactor_adopted_total", 1.0)
    from superlu_dist_tpu.obs.flightrec import get_flightrec
    get_flightrec().event(
        "refactor-adopted", cat="refactor", step=step,
        pattern=expected_digest[:12] if expected_digest else "",
        fresh_compile_s=float(stats.compile.get("fresh_seconds", 0.0))
        if stats.compile else 0.0)
    return stats


def gssvx(options: Options, a: SparseCSR, b: np.ndarray,
          lu: LUFactorization | None = None, stats: Stats | None = None,
          grid=None, resume_from: str | None = None):
    """Solve A·X = B.  Returns (x, lu, stats, info).

    info = 0 on success; > 0 mirrors the reference's singularity reporting
    via tiny-pivot counts in stats (with ReplaceTinyPivot the factorization
    always completes, pdgstrf2.c:218-232).

    `grid` is a parallel.grid.ProcessGrid (the reference passes gridinfo_t
    to pdgssvx): the numeric factorization and device solve then run
    sharded over the grid's mesh.

    `resume_from` names a factor checkpoint (persist/checkpoint.py —
    written by a prior run that died mid-factorization under
    Options.ckpt_every, a deadline, or SIGTERM): the analysis re-runs
    (cheap, deterministic), the checkpoint's plan fingerprint and value
    digest are verified against it, and the numeric factorization
    restarts from the durable frontier instead of from scratch — the
    factors come out bitwise-identical to an uninterrupted run.  The
    resume is recorded on stats.resume and as a 'resume-from-checkpoint'
    rung in the SolveReport ladder.
    """
    if stats is None:
        stats = Stats()
    if options.print_stat:
        print(print_options(options))
    ft = getattr(options, "ft", "abort") or "abort"
    if ft not in ("abort", "shrink", "respawn"):
        # fail the typo'd SLU_TPU_FT here, on every driver, instead of
        # silently aborting the first real rank failure
        raise SuperLUError(
            f"Options.ft must be abort|shrink|respawn, got {ft!r}")
    n = a.n_rows
    if a.n_cols != n:
        raise SuperLUError("A must be square")
    b = np.asarray(b)
    if b.shape[0] != n:
        raise SuperLUError("B leading dimension must match A")

    if options.fact == Fact.FACTORED:
        if lu is None or lu.numeric is None:
            raise SuperLUError("Fact=FACTORED requires a prior factorization")
        return _solve_and_refine(options, a, b, lu, stats)

    lu, bvals, stats = analyze(options, a, lu=lu, stats=stats)
    info = factorize_numeric(lu, bvals, stats, grid=grid,
                             resume_from=resume_from)
    if info != 0:
        return None, lu, stats, info
    return _solve_and_refine(options, a, b, lu, stats)


def gssvx_ABglobal(options: Options, a: SparseCSR, b: np.ndarray,
                   lu: LUFactorization | None = None,
                   stats: Stats | None = None):
    """pdgssvx_ABglobal analog (SRC/pdgssvx_ABglobal.c:472).

    The reference maintains two pipelines because its main driver takes a
    *distributed* NRformat_loc matrix while ABglobal takes a *replicated*
    one.  Here the host analysis always sees the global matrix (the
    distributed input path is gssvx_dist below), so ABglobal coincides
    with gssvx — kept as a named entry point for API parity.
    """
    return gssvx(options, a, b, lu=lu, stats=stats)


def gssvx_dist(options: Options, parts, b: np.ndarray,
               lu: LUFactorization | None = None,
               stats: Stats | None = None):
    """Solve from a distributed row-block matrix (the reference's primary
    pdgssvx signature: NRformat_loc input, SRC/pdgssvx.c:505).

    `parts` is a list of parallel.dist.DistributedCSR row blocks; they are
    assembled host-side (the dReDistribute_A role, SRC/pddistribute.c:61 —
    one gather instead of two all-to-alls, since the analysis is
    single-address-space) and solved with the standard pipeline.
    """
    from superlu_dist_tpu.parallel.dist import gather_rows
    return gssvx(options, gather_rows(parts), b, lu=lu, stats=stats)


def _adjoint_solver(lu: LUFactorization, trans, cplx: bool):
    """op⁻ᴴ through the stored factors (for the FERR estimator); None when
    the trans/complex combination has no clean adjoint through them."""
    if trans == Trans.NOTRANS:
        return lambda r: lu.solve_factored_trans(r, conj=cplx)
    if not cplx:
        return lu.solve_factored     # real: (Aᵀ)ᴴ = A
    return None


def _trans_solver(lu: LUFactorization, trans, a_dtype):
    """The op(A)⁻¹ apply matching options.trans, on an arbitrary handle."""
    if trans == Trans.NOTRANS:
        return lu.solve_factored
    conj = trans == Trans.CONJ and np.issubdtype(a_dtype,
                                                 np.complexfloating)
    return lambda rhs: lu.solve_factored_trans(rhs, conj=conj)


def _escalation_dtype(cur) -> str | None:
    """The next factor-precision tier above `cur`, or None at the top:
    f64/c128 on a CPU backend with x64, emulated-double df64 on f32-only
    hardware (numeric/df64_factor.py — true ~2^-48 factors)."""
    cur = str(cur)
    if cur in ("float64", "complex128") or "df64" in cur:
        return None
    import jax
    if jax.default_backend() == "cpu":
        try:
            if jax.config.read("jax_enable_x64"):
                return "float64"
        except Exception:
            pass
    return "df64"


def _permuted_values(lu: LUFactorization):
    """Recompute analyze()'s structurally-permuted value array from the
    stored transforms (so an escalation rung can refactor on the SAME
    skeleton without redoing the analysis).  None when the skeleton cannot
    reproduce it — panalyze skeletons (no value-gather map), stripped
    handles, or pattern drift."""
    if lu.a is None or lu.sf is None or lu.sf.value_perm is None:
        return None
    a1 = (lu.a.row_scale(lu.dr).col_scale(lu.dc)
          if lu.equed != "N" else lu.a)
    a2 = a1.row_scale(lu.r1).col_scale(lu.c1).permute(perm_r=lu.row_order)
    sym = symmetrize_pattern(a2)
    if sym.nnz != len(lu.sf.value_perm):
        return None
    if (lu.a_sym_indptr is not None
            and not (np.array_equal(sym.indptr, lu.a_sym_indptr)
                     and np.array_equal(sym.indices, lu.a_sym_indices))):
        return None
    return sym.data[lu.sf.value_perm]


def _escalate(options: Options, a: SparseCSR, op, b: np.ndarray,
              lu: LUFactorization, stats: Stats, trans, solve_fn,
              x: np.ndarray, residual_dtype, report: SolveReport,
              target: float):
    """The automatic escalation ladder (the ShyLU fallback-ladder shape:
    low-precision node solves wrapped in quality checks).  Runs when
    refinement stagnated above `target` or produced non-finite values:

      1. residual-precision — same factors, exact f64 residual;
      2. hiprec-factors     — refactor the SAME skeleton at the next
                              precision tier (f64 / df64) and redo the
                              correction solves through it;
      3. refactor-rescale   — full re-analysis with equilibration +
                              MC64 re-scaling/ordering forced on, at the
                              escalated precision.

    Every rung is recorded in report.rungs whether or not it helped; a
    rung's result is only ADOPTED when it strictly improved berr.
    Returns (x, lu_effective, solve_fn, residual_dtype)."""
    import time

    recovery = options.recovery
    rungs0 = len(report.rungs)
    cur_x = np.asarray(x)
    cur_berr = report.berr if report.berr is not None else float("inf")
    if not np.all(np.isfinite(cur_x)):
        cur_berr = float("inf")
    lu_eff = lu
    a_dtype = np.asarray(a.data).dtype

    def attempt(name, detail, solve2, res_dtype, start_x):
        """Run IR with `solve2` corrections; record; adopt on improvement.
        Returns True when the target is reached."""
        nonlocal cur_x, cur_berr, solve_fn, residual_dtype
        t0 = time.perf_counter()
        try:
            x0 = (start_x if np.all(np.isfinite(start_x))
                  else np.asarray(solve2(b)))
            x2, errs = iterative_refinement(op, b, x0, solve2,
                                            residual_dtype=res_dtype)
        except SuperLUError as e:
            report.rungs.append(RungRecord(
                name=name, detail=f"{detail}: {type(e).__name__}",
                berr_before=cur_berr,
                seconds=time.perf_counter() - t0))
            return False
        berr2 = errs[-1] if errs else float("inf")
        if not np.all(np.isfinite(np.asarray(x2))):
            berr2 = float("inf")
        report.rungs.append(RungRecord(
            name=name, detail=detail, berr_before=cur_berr,
            berr_after=berr2, seconds=time.perf_counter() - t0))
        report.berr_history.extend(errs)
        stats.refine_steps += len(errs)
        if berr2 < cur_berr:
            cur_x, cur_berr = np.asarray(x2), berr2
            solve_fn, residual_dtype = solve2, res_dtype
            report.berr = berr2
        return cur_berr <= target

    done = False
    # ---- rung 1: escalate residual precision --------------------------------
    # (SLU_SINGLE's f32 residual can't see below single eps; same factors,
    # exact residual is the cheapest repair)
    if (np.dtype(residual_dtype) != np.float64
            and len(report.rungs) < recovery.max_rungs):
        done = attempt("residual-precision", "float64 residual",
                       solve_fn, np.float64, cur_x)

    # ---- rung 1.5: gemm-precision ladder ------------------------------------
    # The throughput-ladder safety net (docs/PERFORMANCE.md): a reduced
    # GEMM tier (bf16 / the tensorfloat-analog default) that missed the
    # BERR gate refactors the SAME skeleton — same dtype, same scalings,
    # same plan — one tier up per rung until the gate passes or the
    # ladder tops out at "highest".  This is what makes the fast tier
    # safe to run default-on: delivered accuracy is gated, never assumed.
    from superlu_dist_tpu.ops.dense import next_gemm_precision
    tier = getattr(lu.numeric, "gemm_prec", "highest")
    while not done and len(report.rungs) < recovery.max_rungs:
        nxt = next_gemm_precision(tier)
        if nxt is None:
            break
        bvals = _permuted_values(lu)
        if bvals is None:
            break
        t0 = time.perf_counter()
        lu_prec = dataclasses.replace(
            lu, numeric=None, dev_solver=None, dev_spmv=None, berrs=None,
            options=dataclasses.replace(options, gemm_prec=nxt))
        try:
            info_p = factorize_numeric(lu_prec, bvals, stats)
        except SuperLUError as e:
            report.rungs.append(RungRecord(
                name="gemm-precision", detail=f"{nxt}: {type(e).__name__}",
                berr_before=cur_berr,
                seconds=time.perf_counter() - t0))
            break
        if info_p != 0:
            report.rungs.append(RungRecord(
                name="gemm-precision", detail=f"{nxt}: info={info_p}",
                berr_before=cur_berr,
                seconds=time.perf_counter() - t0))
            break
        solve_p = _trans_solver(lu_prec, trans, a_dtype)
        done = attempt("gemm-precision", nxt, solve_p, np.float64, cur_x)
        adopted = solve_fn is solve_p
        if adopted:                   # adopted: the answer now rests on
            lu_eff = lu_prec          # the higher-tier factors
        tier = nxt
        if not done and not adopted:
            # the tier step bought nothing: the GEMM precision is not
            # the binding error source (factor DTYPE usually is) —
            # leave the remaining rung budget to the dtype escalation
            break

    # ---- rung 2: higher-precision correction factors ------------------------
    esc = _escalation_dtype(lu.numeric.dtype)
    if (not done and esc is not None
            and len(report.rungs) < recovery.max_rungs):
        bvals = _permuted_values(lu)
        if bvals is not None:
            # dtype escalation subsumes the gemm ladder: the hiprec
            # refactor always runs at the top GEMM tier
            lu_esc = dataclasses.replace(
                lu, numeric=None, dev_solver=None, dev_spmv=None,
                berrs=None,
                options=dataclasses.replace(options, factor_dtype=esc,
                                            gemm_prec="highest"))
            try:
                info2 = factorize_numeric(lu_esc, bvals, stats)
            except SuperLUError:
                info2 = -1
            if info2 == 0:
                solve2 = _trans_solver(lu_esc, trans, a_dtype)
                done = attempt("hiprec-factors", esc, solve2,
                               np.float64, cur_x)
                if solve_fn is solve2:    # adopted: hand the caller the
                    lu_eff = lu_esc       # factors the answer rests on

    # ---- rung 3: refactor with re-scaling / re-ordering ---------------------
    # only when it would actually change something the first pass didn't do
    would_change = (not options.equil
                    or options.row_perm != RowPerm.LargeDiag_MC64
                    or not options.replace_tiny_pivot
                    or esc is not None)
    if not done and would_change and len(report.rungs) < recovery.max_rungs:
        t0 = time.perf_counter()
        opts3 = dataclasses.replace(
            options, fact=Fact.DOFACT, equil=True,
            row_perm=RowPerm.LargeDiag_MC64, replace_tiny_pivot=True,
            factor_dtype=esc if esc is not None else options.factor_dtype,
            gemm_prec="highest",        # the last rung gambles nothing
            iter_refine=IterRefine.SLU_DOUBLE, print_stat=False,
            user_perm_r=None,
            # no recursion, no mid-ladder raises: the ladder itself is
            # the consumer of this sub-solve's report
            recovery=dataclasses.replace(recovery, enabled=False,
                                         condest="never", sentinels=False))
        try:
            x3, lu3, stats3, info3 = gssvx(opts3, a, b)
        except SuperLUError as e:
            x3, lu3, stats3, info3 = None, None, None, -1
            err3 = type(e).__name__
        if info3 == 0 and x3 is not None:
            rep3 = stats3.solve_report
            berr3 = (rep3.berr if rep3 is not None
                     and rep3.berr is not None else float("inf"))
            if not np.all(np.isfinite(np.asarray(x3))):
                berr3 = float("inf")
            report.rungs.append(RungRecord(
                name="refactor-rescale", detail=str(opts3.factor_dtype),
                berr_before=cur_berr, berr_after=berr3,
                seconds=time.perf_counter() - t0))
            if rep3 is not None:
                report.berr_history.extend(rep3.berr_history)
            if berr3 < cur_berr:
                cur_x, cur_berr, lu_eff = np.asarray(x3), berr3, lu3
                solve_fn = _trans_solver(lu3, trans, a_dtype)
                residual_dtype = np.float64
                report.berr = berr3
                report.tiny_pivots = rep3.tiny_pivots if rep3 else 0
        else:
            report.rungs.append(RungRecord(
                name="refactor-rescale",
                detail=f"failed: info={info3}"
                       + (f" ({err3})" if info3 == -1 else ""),
                berr_before=cur_berr,
                seconds=time.perf_counter() - t0))

    # the tier/dtype the delivered answer actually rests on (lu_eff may
    # be an escalated handle from any rung above)
    if lu_eff.numeric is not None:
        report.gemm_precision = getattr(lu_eff.numeric, "gemm_prec",
                                        report.gemm_precision)
        report.factor_dtype = str(lu_eff.numeric.dtype)

    # serving metrics: one rung-transition counter per ladder action
    # this solve took (labeled by rung and whether it was adopted)
    from superlu_dist_tpu.obs.metrics import get_metrics
    m = get_metrics()
    if m.enabled:
        for r in report.rungs[rungs0:]:
            m.inc("slu_recovery_rungs_total", 1.0, rung=r.name,
                  improved=str(r.berr_after < r.berr_before).lower())
            m.observe("slu_recovery_rung_seconds", r.seconds, rung=r.name)
    return cur_x, lu_eff, solve_fn, residual_dtype


def _solve_and_refine(options: Options, a: SparseCSR, b: np.ndarray,
                      lu: LUFactorization, stats: Stats):
    t_req0 = time.perf_counter()
    n = a.n_rows
    # trans dispatch (reference trans_t, superlu_defs.h:628-657): TRANS and
    # CONJ solve AᵀX=B / AᴴX=B through the same factors; refinement then
    # needs the transposed operator for its residual SpMV
    trans = options.trans
    if trans == Trans.NOTRANS:
        solve_fn, op = lu.solve_factored, a
    else:
        conj = trans == Trans.CONJ and np.issubdtype(
            a.data.dtype, np.complexfloating)
        solve_fn = lambda rhs: lu.solve_factored_trans(rhs, conj=conj)  # noqa: E731
        op = a.transpose()
        if conj:
            op = SparseCSR(op.n_rows, op.n_cols, op.indptr, op.indices,
                           op.data.conj())
    with stats.timer("SOLVE"):
        x = solve_fn(b)
    nrhs = 1 if b.ndim == 1 else b.shape[1]
    stats.ops["SOLVE"] += 4.0 * lu.sf.nnz_L * nrhs  # fwd+back L,U sweeps

    info = 0
    report = SolveReport(factor_dtype=str(lu.numeric.dtype),
                         tiny_pivots=lu.numeric.tiny_pivots,
                         gemm_precision=getattr(lu.numeric, "gemm_prec",
                                                "highest"))
    if stats.resume:
        # a factorization resumed from a durable checkpoint is a ladder
        # action in its own right: the report must show the answer rests
        # partly on restored state (and where that state came from)
        report.rungs.append(RungRecord(
            name="resume-from-checkpoint",
            detail=f"{stats.resume['groups']}/{stats.resume['of']} groups "
                   f"from {stats.resume['path']}"))
    stats.solve_report = report
    recovery = options.recovery
    if options.iter_refine != IterRefine.NOREFINE:
        # SLU_SINGLE rounds the residual/correction to f32 (refinement
        # stops at single eps); SLU_DOUBLE uses options.ir_dtype (f64
        # default) — the reference's IterRefine tiers
        residual_dtype = (np.float32
                          if options.iter_refine == IterRefine.SLU_SINGLE
                          else np.dtype(options.ir_dtype))
        # device-resident residual SpMV (pdgsmv analog, SRC/pdgsmv.c:234)
        # when an accelerator is present and A is big enough for the
        # upload to pay for itself; host numpy otherwise or on failure
        ir_op = op
        import jax
        if (jax.default_backend() != "cpu"
                and op.nnz >= 100_000 and not lu.numeric.on_host):
            # cached per (trans, residual dtype) on the factorization —
            # the pdgsmv_init / SOLVEstruct discipline (SRC/pdgsmv.c:31).
            # The hit is guarded by data-array identity: FACTORED reuse
            # with a same-pattern matrix carrying NEW values must not
            # refine against the stale uploaded operator.  (In-place
            # mutation of a.data defeats any caching scheme — also true
            # of the reference's cached SOLVEstruct.)
            # identity-guard on the SOURCE a.data (op is derived from a
            # deterministically per trans, so transpose solves still hit)
            key = (trans, str(residual_dtype))
            cache = lu.dev_spmv if lu.dev_spmv is not None else {}
            hit = cache.get(key)
            ir_op = hit[1] if hit is not None and hit[0] is a.data else None
            if ir_op is None:
                try:
                    from superlu_dist_tpu.parallel.dist import DeviceSpMV
                    ir_op = DeviceSpMV(
                        op,
                        dtype=np.result_type(op.data.dtype, residual_dtype))
                except Exception:          # x64 off / upload failure —
                    ir_op = op             # host residual stays correct
                cache[key] = (a.data, ir_op)
                lu.dev_spmv = cache
        with stats.timer("REFINE"):
            x, berrs = iterative_refinement(ir_op, b, x, solve_fn,
                                            residual_dtype=residual_dtype)
        stats.refine_steps += len(berrs)
        lu.berrs = berrs
        report.berr_history = list(berrs)
        report.berr = berrs[-1] if berrs else None
        target = (recovery.berr_target if recovery.berr_target
                  else float(tols.berr_target(residual_dtype)))
        report.target = target
        bad = (report.berr is None or report.berr > target
               or not np.all(np.isfinite(np.asarray(x))))
        if recovery.enabled and bad:
            # the escalation ladder: each rung buys accuracy the previous
            # tier could not, and is recorded so the caller sees what
            # degraded and why the answer is still trustworthy
            x, lu_final, solve_fn, residual_dtype = _escalate(
                options, a, op, b, lu, stats, trans, solve_fn, x,
                residual_dtype, report, target)
        else:
            lu_final = lu
        report.refine_steps = len(report.berr_history)
        report.converged = (report.berr is not None
                            and report.berr <= target)
    else:
        lu_final = lu
        # NOREFINE + a reduced GEMM tier: the throughput ladder still
        # owes the caller a gated answer — one componentwise-BERR probe
        # (refine/ir.request_berrs, a single SpMV pair) stands in for
        # the refinement loop's measurement, and a miss runs the same
        # escalation ladder (which refines internally; opting out of IR
        # is not opting out of "never deliver a failing X")
        tier0 = getattr(lu.numeric, "gemm_prec", "highest")
        from superlu_dist_tpu.ops.dense import next_gemm_precision
        # armed only when the tier is a REAL gamble on this backend
        # (next_gemm_precision is None when the remaining rungs are
        # arithmetic no-ops — CPU's default tier IS the exact baseline,
        # and gating it would escalate answers the caller's NOREFINE +
        # factor_dtype choice deliberately left at factor precision)
        if recovery.enabled and next_gemm_precision(tier0) is not None:
            from superlu_dist_tpu.refine.ir import request_berrs
            target = (recovery.berr_target if recovery.berr_target
                      else float(tols.berr_target(np.float64)))
            report.target = target
            try:
                report.berr = float(request_berrs(op, b, x).max())
            except Exception:
                report.berr = None       # probe must never kill a solve
            bad = (report.berr is None or report.berr > target
                   or not np.all(np.isfinite(np.asarray(x))))
            if bad:
                x, lu_final, solve_fn, _ = _escalate(
                    options, a, op, b, lu, stats, trans, solve_fn, x,
                    np.float64, report, target)
            report.converged = (report.berr is not None
                                and report.berr <= target)

    # rcond/ferr (the pdgscon + dgsrfs-FERR reporting): "always", or on
    # "auto" only when the answer needs defending — the ladder fired,
    # tiny pivots were replaced, or refinement missed its target
    want_cond = (recovery.condest == "always"
                 or (recovery.condest == "auto"
                     and (report.rungs or report.tiny_pivots
                          or not report.converged)))
    if want_cond:
        from superlu_dist_tpu.refine.condest import (
            condition_estimate, ferr_estimate)
        report.rcond = condition_estimate(lu_final)
        cplx = np.issubdtype(np.asarray(a.data).dtype, np.complexfloating)
        adj_fn = _adjoint_solver(lu_final, trans, cplx)
        if adj_fn is not None and options.iter_refine != IterRefine.NOREFINE:
            try:
                report.ferr = ferr_estimate(op, b, x, solve_fn, adj_fn)
            except Exception:
                report.ferr = None       # estimation must never kill a solve

    # final non-finite sentinel: a silent NaN/Inf solution is the one
    # outcome the health subsystem exists to prevent
    report.finite = bool(np.all(np.isfinite(np.asarray(x))))
    if not report.finite and recovery.sentinels:
        raise NumericBreakdownError(where="solve")
    # end-to-end driver latency (SOLVE + refine + ladder + condest):
    # the "driver" series of the always-on latency accounter, so batch
    # users get the same quantile surface the serving fleet does
    lat = time.perf_counter() - t_req0
    report.latency_ms = round(lat * 1e3, 3)
    from superlu_dist_tpu.obs.slo import get_accounter
    get_accounter().observe(nrhs, lat, klass="driver")
    if options.print_stat:
        stats.print()
    return x, lu_final, stats, info
