import numpy as np
import pytest

from superlu_dist_tpu.models.gallery import poisson2d, random_sparse
from superlu_dist_tpu.ordering.dissection import geometric_nd
from superlu_dist_tpu.sparse.formats import symmetrize_pattern
from superlu_dist_tpu.symbolic.symbfact import symbolic_factorize


def dense_fill(pat):
    """Filled pattern (L+U) of no-pivoting elimination on a symmetric pattern."""
    n = pat.shape[0]
    f = pat.copy()
    np.fill_diagonal(f, True)
    for j in range(n):
        below = np.flatnonzero(f[j + 1:, j]) + j + 1
        f[np.ix_(below, below)] = True
    return f


def sym_dense_pattern(a, order):
    n = a.n_rows
    pat = np.zeros((n, n), dtype=bool)
    rows = np.repeat(np.arange(n), np.diff(a.indptr))
    pat[rows, a.indices] = True
    pat |= pat.T
    return pat[np.ix_(order, order)]


def check_symbolic(a, order, relax=4, max_supernode=16):
    s = symmetrize_pattern(a)
    sf = symbolic_factorize(s, order, relax=relax, max_supernode=max_supernode)
    n = a.n_rows
    # perm must be a permutation refining the given order's fill (postorder
    # does not change fill)
    assert sorted(sf.perm) == list(range(n))
    filled = dense_fill(sym_dense_pattern(a, sf.perm))
    # supernode structure must COVER the exact fill, and within the claimed
    # structure the supernodal blocks are dense supersets
    ns = sf.n_supernodes
    cover = np.zeros((n, n), dtype=bool)
    for t in range(ns):
        f, e = sf.sn_start[t], sf.sn_start[t + 1]
        cols = np.arange(f, e)
        rows = sf.sn_rows[t]
        cover[np.ix_(cols, cols)] = True
        if len(rows):
            cover[np.ix_(rows, cols)] = True    # L block
            cover[np.ix_(cols, rows)] = True    # U block
    missing = filled & ~cover
    assert not missing.any(), f"symbolic misses {missing.sum()} filled entries"
    # supernode widths within cap; levels consistent; parents above children
    widths = np.diff(sf.sn_start)
    assert widths.max(initial=1) <= max_supernode
    for t in range(ns):
        p = sf.sn_parent[t]
        if p >= 0:
            assert p > t
            assert sf.sn_level[p] > sf.sn_level[t]
            # multifrontal invariant: child's rows land inside parent's front
            pcols = set(range(sf.sn_start[p], sf.sn_start[p + 1]))
            pfront = pcols | set(sf.sn_rows[p].tolist())
            assert set(sf.sn_rows[t].tolist()) <= pfront
        else:
            assert len(sf.sn_rows[t]) == 0
    return sf, filled, cover


@pytest.mark.parametrize("seed", [0, 1, 2, 3])
def test_symbolic_random(seed):
    a = random_sparse(40, density=0.06, seed=seed)
    check_symbolic(a, np.arange(40))


def test_symbolic_poisson_natural_and_nd():
    a = poisson2d(7)
    n = a.n_rows
    check_symbolic(a, np.arange(n))
    sf_nd, filled_nd, _ = check_symbolic(a, geometric_nd(a.grid_shape))
    # ND should not be worse than natural by much; sanity only
    assert sf_nd.nnz_L > 0


def test_supernodes_exact_on_dense_block():
    # an arrow matrix: last column/row full => all columns chain into
    # supernodes; fill coverage should be tight-ish for the tail
    n = 12
    rows = np.concatenate([np.arange(n), np.full(n, n - 1), np.arange(n)])
    cols = np.concatenate([np.arange(n), np.arange(n), np.full(n, n - 1)])
    vals = np.ones(len(rows))
    from superlu_dist_tpu.sparse.formats import coo_to_csr
    a = coo_to_csr(n, n, rows, cols, vals)
    sf, filled, cover = check_symbolic(a, np.arange(n), relax=1, max_supernode=4)
    # overcount ratio stays small for this structure
    assert cover.sum() <= filled.sum() * 2.0


def test_relaxation_reduces_supernode_count():
    a = poisson2d(10)
    s = symmetrize_pattern(a)
    sf1 = symbolic_factorize(s, np.arange(100), relax=1, max_supernode=64)
    sf8 = symbolic_factorize(s, np.arange(100), relax=8, max_supernode=64)
    assert sf8.n_supernodes <= sf1.n_supernodes


def test_relaxed_overlapping_windows_no_zero_width_supernode():
    """build_supernodes_py with strict=False and non-postordered labels:
    relaxed-root subtree windows may OVERLAP (parent=[3,-1,3,-1] with
    relax=3 puts root 3's window [1,3] across root 1's [1,1]).  The walk
    must degrade overlapped windows to singleton starts — the historical
    bug re-appended the same start after skipping a stale root, creating
    a zero-width duplicate supernode (ADVICE round 5)."""
    from superlu_dist_tpu.sparse.formats import coo_to_csr
    from superlu_dist_tpu.symbolic.symbfact import build_supernodes_py

    n = 4
    parent = np.array([3, -1, 3, -1], dtype=np.int64)
    r = np.array([0, 1, 2, 3, 0, 3, 2, 3])
    c = np.array([0, 1, 2, 3, 3, 0, 3, 2])
    a = coo_to_csr(n, n, r, c, np.zeros(len(r)))
    sn_start, col_to_sn, sn_rows, sn_parent = build_supernodes_py(
        n, a.indptr, a.indices, parent, relax=3, max_supernode=64,
        strict=False)
    widths = np.diff(sn_start)
    assert np.all(widths > 0), widths
    assert sn_start[0] == 0 and sn_start[-1] == n
    assert len(col_to_sn) == n
    assert np.all(np.diff(col_to_sn) >= 0)
    # parents stay strictly ahead of children (or roots)
    for s, p in enumerate(sn_parent):
        assert p == -1 or p > s
