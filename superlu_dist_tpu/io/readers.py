"""Sparse matrix file readers/writers.

Capability parity with the reference's I/O layer (SURVEY.md L10):
Harwell-Boeing (dreadhb.c:107), Rutherford-Boeing (dreadrb.c), MatrixMarket
(dreadMM.c), triples with/without header (dreadtriple.c,
dreadtriple_noheader.c), raw binary (dbinary_io.c).  Fresh implementations
against the published format specs, not translations.

All readers return a :class:`SparseCSC` (the reference's NCformat analog) —
use ``.tocsr()`` for the row-major pipeline entry.
"""

from __future__ import annotations

import re

import numpy as np

from superlu_dist_tpu.sparse.formats import SparseCSC, SparseCSR, coo_to_csc
from superlu_dist_tpu.utils.errors import SuperLUError

_FMT_RE = re.compile(
    r"\(\s*(?:(\d+)\s*[Pp][A-Za-z]*\s*,?\s*)?(\d+)\s*([IiEeDdFfGg])\s*(\d+)(?:\.(\d+))?\s*\)")


def _parse_fortran_format(fmt: str):
    """Parse e.g. '(16I5)' / '(5E15.8)' / '(1P,5E16.8)' -> (per_line, width, kind)."""
    m = _FMT_RE.search(fmt)
    if not m:
        raise SuperLUError(f"unsupported Fortran format: {fmt!r}")
    _, count, kind, width = m.group(1), int(m.group(2)), m.group(3).upper(), int(m.group(4))
    return count, width, kind


def _read_fixed(lines_iter, fmt, total, numeric):
    """Read `total` fixed-width fields laid out `per_line` per line."""
    per_line, width, kind = _parse_fortran_format(fmt)
    vals = []
    while len(vals) < total:
        line = next(lines_iter).rstrip("\n")
        for k in range(per_line):
            if len(vals) >= total:
                break
            field = line[k * width:(k + 1) * width]
            if not field.strip():
                continue
            if numeric == "int":
                vals.append(int(field))
            else:
                vals.append(float(field.replace("D", "E").replace("d", "e")))
    return np.array(vals)


def _hb_like(text: str, rutherford: bool) -> SparseCSC:
    lines = iter(text.splitlines())
    next(lines)                      # title + key
    card2 = next(lines).split()
    totcrd, ptrcrd, indcrd, valcrd = (int(x) for x in card2[:4])
    rhscrd = int(card2[4]) if len(card2) > 4 and not rutherford else 0
    card3 = next(lines).split()
    mxtype = card3[0].upper()
    nrow, ncol, nnz = int(card3[1]), int(card3[2]), int(card3[3])
    card4 = next(lines)
    # formats occupy fixed 16-char columns, but splitting on whitespace works
    fmts = card4.split()
    ptrfmt, indfmt = fmts[0], fmts[1]
    valfmt = fmts[2] if len(fmts) > 2 else "(5E15.8)"
    if (not rutherford) and rhscrd > 0:
        next(lines)                  # card 5: RHS descriptor (ignored)
    colptr = _read_fixed(lines, ptrfmt, ncol + 1, "int") - 1
    rowind = _read_fixed(lines, indfmt, nnz, "int") - 1
    is_complex = mxtype[0] == "C"
    is_pattern = mxtype[0] == "P"
    if is_pattern or valcrd == 0:
        data = np.ones(nnz)
    else:
        raw = _read_fixed(lines, valfmt, nnz * (2 if is_complex else 1), "float")
        data = raw[0::2] + 1j * raw[1::2] if is_complex else raw
    a = SparseCSC(nrow, ncol, colptr.astype(np.int32), rowind.astype(np.int32),
                  data)
    if mxtype[1] == "S":             # symmetric: only lower triangle stored
        a = _expand_symmetric(a, hermitian=False)
    elif mxtype[1] == "H":
        a = _expand_symmetric(a, hermitian=True)
    elif mxtype[1] == "Z":           # skew-symmetric
        a = _expand_symmetric(a, skew=True)
    return a


def _expand_symmetric(a: SparseCSC, hermitian=False, skew=False) -> SparseCSC:
    cols = np.repeat(np.arange(a.n_cols), np.diff(a.indptr)).astype(np.int64)
    rows = a.indices.astype(np.int64)
    off = rows != cols
    mrows = np.concatenate([rows, cols[off]])
    mcols = np.concatenate([cols, rows[off]])
    mirror = a.data[off]
    if hermitian:
        mirror = np.conj(mirror)
    if skew:
        mirror = -mirror
    mdata = np.concatenate([a.data, mirror])
    return coo_to_csc(a.n_rows, a.n_cols, mrows, mcols, mdata)


def read_harwell_boeing(path_or_text) -> SparseCSC:
    """Harwell-Boeing (.rua/.cua) reader — dreadhb_dist analog (dreadhb.c:107)."""
    return _hb_like(_as_text(path_or_text), rutherford=False)


def read_rutherford_boeing(path_or_text) -> SparseCSC:
    """Rutherford-Boeing (.rb) reader — dreadrb_dist analog (dreadrb.c)."""
    return _hb_like(_as_text(path_or_text), rutherford=True)


def read_matrix_market(path_or_text) -> SparseCSC:
    """MatrixMarket coordinate reader — dreadMM_dist analog (dreadMM.c)."""
    text = _as_text(path_or_text)
    lines = [l for l in text.splitlines()]
    header = lines[0].split()
    if len(header) < 5 or header[0] not in ("%%MatrixMarket", "%MatrixMarket"):
        raise SuperLUError("not a MatrixMarket file")
    _, obj, fmt, field, symm = (h.lower() for h in header[:5])
    if obj != "matrix" or fmt != "coordinate":
        raise SuperLUError("only coordinate matrices supported")
    body = (l for l in lines[1:] if l.strip() and not l.lstrip().startswith("%"))
    nrow, ncol, nnz = (int(x) for x in next(body).split()[:3])
    rows = np.empty(nnz, dtype=np.int64)
    cols = np.empty(nnz, dtype=np.int64)
    is_complex = field == "complex"
    is_pattern = field == "pattern"
    data = np.empty(nnz, dtype=np.complex128 if is_complex else np.float64)
    for k in range(nnz):
        parts = next(body).split()
        rows[k], cols[k] = int(parts[0]) - 1, int(parts[1]) - 1
        if is_pattern:
            data[k] = 1.0
        elif is_complex:
            data[k] = float(parts[2]) + 1j * float(parts[3])
        else:
            data[k] = float(parts[2])
    a = coo_to_csc(nrow, ncol, rows, cols, data)
    if symm in ("symmetric", "hermitian", "skew-symmetric"):
        a = _expand_symmetric(a, hermitian=symm == "hermitian",
                              skew=symm == "skew-symmetric")
    return a


def read_triples(path_or_text, zero_based=False, header=True,
                 dtype=np.float64) -> SparseCSC:
    """'i j value' triples — dreadtriple_dist / _noheader analog.

    With header=True the first line is 'n nnz' (reference convention,
    dreadtriple.c); otherwise dimensions are inferred from the data
    (dreadtriple_noheader.c behavior, which also auto-detects 0/1-base).
    """
    text = _as_text(path_or_text)
    rows_l, cols_l, vals_l = [], [], []
    lines = (l for l in text.splitlines() if l.strip())
    n = None
    if header:
        hdr = next(lines).split()
        n = int(hdr[0])
    is_complex = np.issubdtype(np.dtype(dtype), np.complexfloating)
    for line in lines:
        parts = line.split()
        rows_l.append(int(parts[0]))
        cols_l.append(int(parts[1]))
        if len(parts) < 3:
            vals_l.append(1.0)
        elif is_complex and len(parts) >= 4:
            vals_l.append(float(parts[2]) + 1j * float(parts[3]))
        else:
            vals_l.append(float(parts[2]))
    rows = np.array(rows_l, dtype=np.int64)
    cols = np.array(cols_l, dtype=np.int64)
    vals = np.array(vals_l, dtype=dtype)
    if not zero_based and (header or (rows.min(initial=1) >= 1 and
                                      cols.min(initial=1) >= 1)):
        rows -= 1
        cols -= 1
    if n is None:
        n = int(max(rows.max(initial=-1), cols.max(initial=-1))) + 1
    return coo_to_csc(n, n, rows, cols, vals)


_BIN_MAGIC = b"SLUTPU1\0"


def write_binary(path, a) -> None:
    """Raw binary writer (dbinary_io.c capability analog; own format:
    magic, int64 nrow/ncol/nnz/iscomplex, then indptr/indices/data)."""
    csc = a if isinstance(a, SparseCSC) else a.tocsc()
    with open(path, "wb") as f:
        f.write(_BIN_MAGIC)
        is_c = int(np.issubdtype(csc.data.dtype, np.complexfloating))
        np.array([csc.n_rows, csc.n_cols, csc.nnz, is_c], dtype=np.int64).tofile(f)
        csc.indptr.astype(np.int64).tofile(f)
        csc.indices.astype(np.int64).tofile(f)
        csc.data.astype(np.complex128 if is_c else np.float64).tofile(f)


def read_binary(path) -> SparseCSC:
    with open(path, "rb") as f:
        if f.read(8) != _BIN_MAGIC:
            raise SuperLUError("bad binary matrix file")
        nrow, ncol, nnz, is_c = np.fromfile(f, dtype=np.int64, count=4)
        indptr = np.fromfile(f, dtype=np.int64, count=ncol + 1)
        indices = np.fromfile(f, dtype=np.int64, count=nnz)
        data = np.fromfile(f, dtype=np.complex128 if is_c else np.float64,
                           count=nnz)
    return SparseCSC(int(nrow), int(ncol), indptr.astype(np.int32),
                     indices.astype(np.int32), data)


def write_matrix_market(path, a) -> None:
    csc = a if isinstance(a, SparseCSC) else a.tocsc()
    is_c = np.issubdtype(csc.data.dtype, np.complexfloating)
    field = "complex" if is_c else "real"
    with open(path, "w") as f:
        f.write(f"%%MatrixMarket matrix coordinate {field} general\n")
        f.write(f"{csc.n_rows} {csc.n_cols} {csc.nnz}\n")
        cols = np.repeat(np.arange(csc.n_cols), np.diff(csc.indptr))
        for i, j, v in zip(csc.indices, cols, csc.data):
            if is_c:
                f.write(f"{i + 1} {j + 1} {v.real:.17g} {v.imag:.17g}\n")
            else:
                f.write(f"{i + 1} {j + 1} {v:.17g}\n")


def read_matrix(path) -> SparseCSC:
    """Extension-dispatched reader (the EXAMPLE drivers' '-f file' behavior,
    dcreate_matrix_postfix, EXAMPLE/dcreate_matrix.c:239)."""
    p = str(path)
    if p.endswith((".rua", ".cua", ".hb", ".rsa", ".csa")):
        return read_harwell_boeing(p)
    if p.endswith(".rb"):
        return read_rutherford_boeing(p)
    if p.endswith(".mtx"):
        return read_matrix_market(p)
    if p.endswith(".bin"):
        return read_binary(p)
    if p.endswith((".triple", ".txt")):
        return read_triples(p)
    raise SuperLUError(f"cannot infer matrix format from {p}")


def _as_text(path_or_text) -> str:
    s = str(path_or_text)
    if "\n" in s:
        return s
    with open(s) as f:
        return f.read()
