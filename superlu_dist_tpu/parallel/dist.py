"""Distributed row-block matrix format + sharded SpMV.

Analog of the reference's ``NRformat_loc`` (SRC/supermatrix.h:175-188) — the
distributed CSR each MPI rank holds — and of the distributed SpMV used by
iterative refinement (pdgsmv_init/pdgsmv, SRC/pdgsmv.c:31,234).

TPU-first redesign: the "ranks" are positions along the mesh's "snode"
axis.  Row blocks are the contiguous block-row partition the reference's
example drivers create (EXAMPLE/dcreate_matrix.c:239: read on rank 0,
scatter block rows).  For the SpMV, where the reference exchanges only the
needed x-entries via precomputed index lists (ind_tosend/ind_torecv), here
x is replicated across the mesh and each device computes its row block —
the gather that the reference does by point-to-point messages becomes an
XLA all-gather over ICI, which is both simpler and faster at TPU
interconnect bandwidths for the n·nrhs vectors involved.

CSR padding makes the local blocks static-shape so one jitted kernel
serves every shard.

Where this sits in the SPMD-first stack: these row blocks are the
INPUT/OUTPUT distribution only (matrix assembly, refinement SpMV).  The
factor/solve numeric path no longer walks a per-rank host dispatch
loop over them — on a single-controller mesh it is one shard_map
program per factor/solve (parallel/spmd.py) and on multi-process
meshes the GSPMD streamed kernels; the TreeComm host-lockstep tier
that used to carry this traffic is the A/B reference and recovery
fallback (parallel/pgssvx.py).
"""

from __future__ import annotations

import dataclasses

import numpy as np

from superlu_dist_tpu.sparse.formats import SparseCSR


@dataclasses.dataclass
class DistributedCSR:
    """One rank's row block (NRformat_loc analog).

    Attributes mirror the reference fields: m_loc (local rows), fst_row
    (first global row), nnz_loc implicit in indptr.
    """

    n: int                 # global dimension
    m_loc: int
    fst_row: int
    indptr: np.ndarray     # (m_loc+1,) local row pointers
    indices: np.ndarray    # global column indices
    data: np.ndarray

    @property
    def nnz_loc(self) -> int:
        return int(self.indptr[-1])

    def matvec_local(self, x_global: np.ndarray) -> np.ndarray:
        """Local rows of A·x given the full x, (n,) or (n, nrhs)
        (pdgsmv's compute phase)."""
        rows = np.repeat(np.arange(self.m_loc), np.diff(self.indptr))
        x = np.asarray(x_global)
        if x.ndim > 1:
            contrib = self.data[:, None] * x[self.indices]
            out = np.zeros((self.m_loc, x.shape[1]),
                           dtype=np.result_type(self.data, x))
            np.add.at(out, rows, contrib)
            return out
        contrib = self.data * x[self.indices]
        if np.iscomplexobj(contrib):
            return (np.bincount(rows, weights=contrib.real,
                                minlength=self.m_loc)
                    + 1j * np.bincount(rows, weights=contrib.imag,
                                       minlength=self.m_loc))
        return np.bincount(rows, weights=contrib, minlength=self.m_loc)

    def abs_matvec_local(self, x: np.ndarray) -> np.ndarray:
        """Local rows of |A|·x (the berr denominator in refinement)."""
        rows = np.repeat(np.arange(self.m_loc), np.diff(self.indptr))
        contrib = np.abs(self.data) * np.asarray(x)[self.indices]
        return np.bincount(rows, weights=contrib, minlength=self.m_loc)

    def matvec_trans_local(self, x_global: np.ndarray,
                           conj: bool = False) -> np.ndarray:
        """This rank's full-length contribution to op(A)·x, op = Aᵀ/Aᴴ:
        out[j] += v̄·x[i] over local entries (i, j, v).  Sum the ranks'
        returns (tree all-reduce) to get op(A)·x — block rows of A are
        block *columns* of op(A), so every rank touches all of out."""
        rows = np.repeat(np.arange(self.m_loc), np.diff(self.indptr))
        vals = np.conj(self.data) if conj else self.data
        contrib = vals * np.asarray(x_global)[self.fst_row + rows]
        out = np.zeros(self.n, dtype=np.result_type(contrib, np.float64))
        np.add.at(out, self.indices, contrib)
        return out

    def abs_matvec_trans_local(self, x: np.ndarray) -> np.ndarray:
        """Full-length contribution to |op(A)|·x (|Aᵀ| = |A|ᵀ = |Aᴴ|)."""
        rows = np.repeat(np.arange(self.m_loc), np.diff(self.indptr))
        contrib = np.abs(self.data) * np.asarray(x)[self.fst_row + rows]
        out = np.zeros(self.n)
        np.add.at(out, self.indices, contrib)
        return out


def distribute_rows(a: SparseCSR, nparts: int) -> list[DistributedCSR]:
    """Block-row partition of A (the dcreate_matrix scatter,
    EXAMPLE/dcreate_matrix.c:66): part p gets rows [p·⌈n/P⌉, ...)."""
    n = a.n_rows
    step = -(-n // nparts)
    out = []
    for p in range(nparts):
        lo = min(p * step, n)
        hi = min(lo + step, n)
        indptr = a.indptr[lo:hi + 1].astype(np.int64)
        s, e = int(indptr[0]), int(indptr[-1])
        out.append(DistributedCSR(
            n=n, m_loc=hi - lo, fst_row=lo,
            indptr=indptr - s,
            indices=a.indices[s:e].copy(),
            data=a.data[s:e].copy()))
    return out


def gather_rows(parts: list[DistributedCSR]) -> SparseCSR:
    """Inverse of distribute_rows (pdCompRow_loc_to_CompCol_global analog,
    SRC/pdutil.c)."""
    parts = sorted(parts, key=lambda p: p.fst_row)
    n = parts[0].n
    indptr = [np.zeros(1, dtype=np.int64)]
    indices, data = [], []
    base = 0
    for p in parts:
        indptr.append(p.indptr[1:].astype(np.int64) + base)
        base += p.nnz_loc
        indices.append(p.indices)
        data.append(p.data)
    return SparseCSR(n, n, np.concatenate(indptr),
                     np.concatenate(indices), np.concatenate(data))


class DeviceSpMV:
    """Single-device y = A·x with the pattern resident in HBM — the
    pdgsmv analog (SRC/pdgsmv.c:234) used by iterative refinement when
    the backend is an accelerator: the residual SpMV runs next to the
    factors instead of round-tripping A through host numpy each step.

    Setup cost (uploading rows/cols/vals once) is amortized across all
    refinement steps and repeated solves, exactly the pdgsmv_init /
    SOLVEstruct caching discipline (SRC/pdgsmv.c:31).  Computation is in
    the value dtype as uploaded (f64 residuals stay f64 — XLA emulates
    f64 on the TPU VPU; the SpMV is O(nnz), negligible next to solves).

    Presents the same matvec/abs_matvec/nnz surface the refinement loop
    uses, so it can stand in for SparseCSR there.
    """

    def __init__(self, a: SparseCSR, dtype=None):
        import jax
        import jax.numpy as jnp

        self.n_rows, self.n_cols = a.n_rows, a.n_cols
        self._nnz = a.nnz
        dtype = np.dtype(dtype or np.result_type(a.data.dtype, np.float64))
        real_width = np.dtype(dtype).type(0).real.dtype.itemsize
        if real_width >= 8 and not jax.config.read("jax_enable_x64"):
            # without x64, jnp silently downcasts f64 -> f32 and the
            # refinement residual loses exactly the digits it exists to
            # recover — refuse, so the caller falls back to the host SpMV
            raise RuntimeError(
                "DeviceSpMV needs jax_enable_x64 for a 64-bit residual")
        rows = np.repeat(np.arange(a.n_rows, dtype=np.int64),
                         np.diff(a.indptr))
        self._rows = jnp.asarray(rows)
        self._cols = jnp.asarray(a.indices.astype(np.int64))
        self._vals = jnp.asarray(a.data.astype(dtype))
        self._avals = jnp.asarray(np.abs(a.data).astype(
            dtype if not np.issubdtype(dtype, np.complexfloating)
            else np.dtype(dtype).type(0).real.dtype))
        n = self.n_rows

        @jax.jit
        def spmv(vals, rows, cols, x):
            contrib = vals[:, None] * x[cols]
            y = jnp.zeros((n, x.shape[1]), dtype=contrib.dtype)
            return y.at[rows].add(contrib)

        self._fn = spmv

    @property
    def nnz(self) -> int:
        return self._nnz

    def _apply(self, vals, x):
        import jax.numpy as jnp
        x = np.asarray(x)
        squeeze = x.ndim == 1
        x2 = x[:, None] if squeeze else x
        y = np.asarray(self._fn(vals, self._rows, self._cols,
                                jnp.asarray(x2)))
        return y[:, 0] if squeeze else y

    def matvec(self, x: np.ndarray) -> np.ndarray:
        return self._apply(self._vals, x)

    def abs_matvec(self, x: np.ndarray) -> np.ndarray:
        # |A|·x, NOT |A|·|x| — same contract as SparseCSR.abs_matvec
        return self._apply(self._avals, x)


class ShardedSpMV:
    """Mesh-sharded y = A·x — the pdgsmv analog for refinement at scale.

    Rows are sharded along the mesh's "snode" axis (padded to equal block
    sizes so shapes are static); x is replicated, so XLA inserts no
    communication for the gather and one all-gather-free elementwise for
    the result.  Built once per pattern, reused across solves — the
    pdgsmv_init / SOLVEstruct caching discipline (SRC/pdgsmv.c:31).
    """

    def __init__(self, a: SparseCSR, mesh):
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        self.n = a.n_rows
        nshards = int(np.prod(mesh.devices.shape))
        rows_all = np.repeat(np.arange(self.n), np.diff(a.indptr))
        nnz = a.nnz
        pad_nnz = -(-nnz // nshards) * nshards
        # pad entries: row n-1? No — use a dump row == n (result sliced off)
        rows_p = np.full(pad_nnz, self.n, dtype=np.int64)
        cols_p = np.zeros(pad_nnz, dtype=np.int64)
        vals_p = np.zeros(pad_nnz, dtype=a.data.dtype)
        rows_p[:nnz] = rows_all
        cols_p[:nnz] = a.indices
        vals_p[:nnz] = a.data
        flat = NamedSharding(mesh, P(("snode", "panel")))
        rep = NamedSharding(mesh, P())
        self._rows = jax.device_put(jnp.asarray(rows_p), flat)
        self._cols = jax.device_put(jnp.asarray(cols_p), flat)
        self._vals = jax.device_put(jnp.asarray(vals_p), flat)
        self._rep = rep
        n1 = self.n + 1

        @jax.jit
        def spmv(rows, cols, vals, x):
            contrib = vals * x[cols]
            y = jnp.zeros(n1, dtype=contrib.dtype)
            return y.at[rows].add(contrib)[:-1]

        self._fn = spmv

    def __call__(self, x: np.ndarray) -> np.ndarray:
        import jax
        import jax.numpy as jnp
        xd = jax.device_put(jnp.asarray(x), self._rep)
        return np.asarray(self._fn(self._rows, self._cols, self._vals, xd))
