from superlu_dist_tpu.models.gallery import (
    poisson2d, poisson3d, random_sparse, convection_diffusion_2d,
)
