"""Command-line driver — the pddrive / pdtest analog.

Solve A·X = B for a matrix file from the shell with a PStatPrint-style
report (reference EXAMPLE/pddrive.c:51-238), or sweep option combinations
pdtest-style (reference TEST/pdtest.c: fact modes × orderings × nrhs on one
matrix, failures counted and summarized).

Examples:
  python -m superlu_dist_tpu -f /root/reference/EXAMPLE/g20.rua
  python -m superlu_dist_tpu -f big.rua --nrhs 3 --colperm MMD --dtype float32
  python -m superlu_dist_tpu -f g20.rua --sweep        # pdtest-style matrix
  python -m superlu_dist_tpu -f g20.rua --backend cpu --trans
"""

from __future__ import annotations

import argparse
import sys
import time

import numpy as np

from superlu_dist_tpu.utils import tols


def _build_parser():
    p = argparse.ArgumentParser(
        prog="python -m superlu_dist_tpu",
        description="TPU-native sparse direct solve (pddrive analog)")
    p.add_argument("-f", "--file", required=True,
                   help="matrix file (.rua/.rb/.mtx/.dat/.bin auto-detected)")
    p.add_argument("-s", "--nrhs", type=int, default=1,
                   help="number of right-hand sides (pdtest -s)")
    p.add_argument("--colperm", default="METIS_AT_PLUS_A",
                   choices=["NATURAL", "MMD", "MMD_AT_PLUS_A", "MMD_ATA",
                            "COLAMD", "ND", "METIS_AT_PLUS_A"],
                   help="fill-reducing column ordering")
    p.add_argument("--rowperm", default="MC64",
                   choices=["NOROWPERM", "MC64", "LargeDiag_MC64",
                            "AWPM", "LargeDiag_AWPM"],
                   help="numerical row pivoting strategy")
    p.add_argument("--no-equil", action="store_true",
                   help="disable equilibration (pdtest -e)")
    p.add_argument("--no-refine", action="store_true",
                   help="disable iterative refinement")
    p.add_argument("--trans", action="store_true", help="solve A^T X = B")
    p.add_argument("--dtype", default=None,
                   choices=["float32", "float64", "bfloat16", "df64"],
                   help="factorization dtype (default: f32 on TPU, f64 "
                        "CPU; df64 = emulated double on f32 hardware)")
    p.add_argument("-x", "--relax", type=int, default=None,
                   help="supernode relaxation (sp_ienv(2) / pdtest -x)")
    p.add_argument("--amalg-tol", type=float, default=None,
                   help="fill-tolerant supernode amalgamation tolerance "
                        "(SLU_TPU_AMALG_TOL; 0 disables)")
    p.add_argument("-m", "--maxsuper", type=int, default=None,
                   help="max supernode size (sp_ienv(3) / pdtest -m)")
    p.add_argument("--backend", default=None, choices=["cpu", "tpu"],
                   help="force a JAX backend (default: session default)")
    p.add_argument("--seed", type=int, default=0, help="xtrue RNG seed")
    p.add_argument("--sweep", action="store_true",
                   help="pdtest-style sweep: Fact tiers x orderings x nrhs")
    p.add_argument("--stats", action="store_true",
                   help="print the full PStatPrint analog after the run: "
                        "Stats.report() plus the SolveReport health "
                        "summary (SLU_TPU_STATS=1 does the same, plus "
                        "the options banner, without the flag)")
    p.add_argument("-q", "--quiet", action="store_true",
                   help="suppress the PStatPrint report")
    return p


def _options(args, **overrides):
    from superlu_dist_tpu.utils.options import (
        Options, ColPerm, RowPerm, IterRefine, Trans)
    kw = dict(
        equil=not args.no_equil,
        col_perm={"NATURAL": ColPerm.NATURAL,
                  "MMD": ColPerm.MMD_AT_PLUS_A,
                  "MMD_AT_PLUS_A": ColPerm.MMD_AT_PLUS_A,
                  "MMD_ATA": ColPerm.MMD_ATA,
                  "COLAMD": ColPerm.COLAMD,
                  "ND": ColPerm.ND_AT_PLUS_A,
                  "METIS_AT_PLUS_A": ColPerm.ND_AT_PLUS_A}[args.colperm],
        row_perm={"NOROWPERM": RowPerm.NOROWPERM,
                  "MC64": RowPerm.LargeDiag_MC64,
                  "LargeDiag_MC64": RowPerm.LargeDiag_MC64,
                  "AWPM": RowPerm.LargeDiag_AWPM,
                  "LargeDiag_AWPM": RowPerm.LargeDiag_AWPM}[args.rowperm],
        iter_refine=(IterRefine.NOREFINE if args.no_refine
                     else IterRefine.SLU_DOUBLE),
        trans=Trans.TRANS if args.trans else Trans.NOTRANS,
    )
    if args.dtype:
        kw["factor_dtype"] = args.dtype
    if args.relax is not None:
        kw["relax"] = args.relax
    if args.maxsuper is not None:
        kw["max_supernode"] = args.maxsuper
    if args.amalg_tol is not None:
        kw["amalg_tol"] = args.amalg_tol
    kw.update(overrides)
    return Options(**kw)


def _fabricate(a, nrhs, seed, trans=False):
    """xtrue + b = A·xtrue, like the EXAMPLE drivers
    (dcreate_matrix.c:147-148)."""
    from superlu_dist_tpu.utils.precision import gen_xtrue, fill_rhs
    xtrue = gen_xtrue(a.n_rows, nrhs, a.data.dtype, seed)
    return xtrue, fill_rhs(a, xtrue, trans=trans)


def _resid(a, x, b, trans=False):
    op = a.transpose() if trans else a
    r = b - op.matvec(x)
    return float(np.linalg.norm(np.ravel(r))
                 / max(float(np.linalg.norm(np.ravel(b))), 1e-300))


def run_once(a, args) -> int:
    import superlu_dist_tpu as slu

    opts = _options(args)
    xtrue, b = _fabricate(a, args.nrhs, args.seed, trans=args.trans)
    t0 = time.perf_counter()
    x, lu, stats, info = slu.gssvx(opts, a, b)
    wall = time.perf_counter() - t0
    if info != 0:
        print(f"FAILED: info = {info} (first zero pivot, 1-based)")
        return 1
    from superlu_dist_tpu.utils.precision import inf_norm_error
    res = _resid(a, x, b, trans=args.trans)
    err = inf_norm_error(x, xtrue)
    if not args.quiet or args.stats:
        print(stats.report())
        if args.stats and stats.solve_report is not None:
            # the SolveReport on its own line (the report() embeds it in
            # "solve health:"; --stats promises the explicit summary)
            print(f"    solve report: {stats.solve_report.summary()}")
        berr = lu.berrs[-1] if lu.berrs else None
        print(f"    residual ||b-Ax||/||b||  {res:.3e}")
        print(f"    ||x-xtrue||_inf/||x||_inf {err:.3e}"
              f"   (pdinf_norm_error analog)")
        if berr is not None:
            print(f"    backward error (IR)      {berr:.3e}")
        print(f"    total wall time          {wall:.4f} s")
    ok = res < tols.RESID_GATE
    if not ok:
        print(f"RESIDUAL TOO LARGE: {res:.3e}")
    return 0 if ok else 1


def run_sweep(a, args) -> int:
    """pdtest analog: cross Fact tiers x nrhs x equil; count failures."""
    import superlu_dist_tpu as slu
    from superlu_dist_tpu.utils.options import ColPerm, Fact, Trans

    n_pass = n_fail = 0
    rows = []
    for equil in (True, False):
        for nrhs in (1, 3):
            lu = None
            for fact in (Fact.DOFACT, Fact.SamePattern,
                         Fact.SamePattern_SameRowPerm, Fact.FACTORED):
                # the sweep fabricates b = A·xtrue and checks the
                # untransposed residual — pin trans off regardless of the
                # top-level flag (run_once handles --trans)
                opts = _options(args, equil=equil, fact=fact,
                                trans=Trans.NOTRANS)
                xtrue, b = _fabricate(a, nrhs, args.seed + nrhs)
                try:
                    x, lu, stats, info = slu.gssvx(opts, a, b, lu=lu)
                    res = _resid(a, x, b) if info == 0 else np.inf
                    ok = info == 0 and res < tols.RESID_GATE
                except Exception as e:          # robustness: keep sweeping
                    res, ok = float("nan"), False
                    print(f"  exception in {fact.name}: {e}")
                rows.append((fact.name, "", equil, nrhs, res, ok))
                n_pass += ok
                n_fail += not ok
    # ordering axis (the pdtest -s/-b/-x parameter family crossed the
    # blocking knobs; the modern capability axis is the colperm choice)
    for cp in (ColPerm.NATURAL, ColPerm.MMD_AT_PLUS_A, ColPerm.MMD_ATA,
               ColPerm.COLAMD, ColPerm.ND_AT_PLUS_A):
        opts = _options(args, equil=True, fact=Fact.DOFACT,
                        trans=Trans.NOTRANS, col_perm=cp)
        xtrue, b = _fabricate(a, 1, args.seed)
        try:
            x, _, stats, info = slu.gssvx(opts, a, b)
            res = _resid(a, x, b) if info == 0 else np.inf
            ok = info == 0 and res < tols.RESID_GATE
        except Exception as e:
            res, ok = float("nan"), False
            print(f"  exception in colperm {cp.name}: {e}")
        rows.append(("DOFACT", cp.name, True, 1, res, ok))
        n_pass += ok
        n_fail += not ok
    print(f"{'Fact':<24}{'ColPerm':<16}{'Equil':<7}{'nrhs':<6}"
          f"{'residual':<12}ok")
    for name, cp, equil, nrhs, res, ok in rows:
        print(f"{name:<24}{cp:<16}{str(equil):<7}{nrhs:<6}{res:<12.3e}"
              f"{'PASS' if ok else 'FAIL'}")
    print(f"summary: {n_pass} passed, {n_fail} failed "
          f"(PrintSumm analog, TEST/pdtest.c:84)")
    return 0 if n_fail == 0 else 1


def main(argv=None) -> int:
    args = _build_parser().parse_args(argv)
    if args.backend == "cpu":
        import jax
        jax.config.update("jax_platforms", "cpu")
        jax.config.update("jax_enable_x64", True)
    from superlu_dist_tpu.io import read_matrix
    a = read_matrix(args.file).tocsr()
    print(f"matrix {args.file}: {a.n_rows}x{a.n_cols}, nnz={a.nnz}, "
          f"dtype={a.data.dtype}")
    return run_sweep(a, args) if args.sweep else run_once(a, args)


if __name__ == "__main__":
    sys.exit(main())
