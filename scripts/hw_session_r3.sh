#!/bin/bash
# Round-3 hardware session: serialized, probe-gated, idempotent.
#
# Tunnel-wedge lesson (observed twice, r2 + r3): killing a bench client
# mid-compile (the bench watchdog's os._exit, or an outer `timeout`)
# aborts the in-flight remote compile RPC and wedges the relay for
# minutes-to-hours — the next probe then fails even though nothing OOMed.
# So this session NEVER kills a running client: deadlines sit far above
# worst-case compile (~20-40 s/kernel through the tunnel), exactly one
# client runs at a time, and when the tunnel is down we wait, not retry-
# kill.  Each config is marked done (.hw_done/) only when it yields a
# non-null TPU row, so the script can be re-run after any interruption.
#
# Order: the driver-critical config first (BENCH_NX=48 default blocking —
# the exact kernel set BENCH_r03.json needs warm in .cache/jax), then the
# MFU variants smallest-first, then big sizes, then the auxiliary
# measurement scripts (BASELINE fixtures 1-3, df64 cost).
set -u
cd "$(dirname "$0")/.."
OUT=tune_results.jsonl
LOG=tune_results.err
MARK=.hw_done
mkdir -p "$MARK"

probe() {
  python - <<'EOF' >/dev/null 2>&1
import subprocess, sys
try:
    r = subprocess.run([sys.executable, "-c",
        "import jax, jax.numpy as jnp; "
        "(jnp.ones((64,64)) @ jnp.ones((64,64))).block_until_ready()"],
        timeout=240, capture_output=True)
    sys.exit(r.returncode)
except Exception:
    sys.exit(1)
EOF
}

wait_up() {
  until probe; do
    echo "[hw] $(date -u +%H:%M:%S) tunnel down; retry in 180s" >&2
    sleep 180
  done
}

row_ok() {
  tail -1 "$OUT" | python -c '
import json, sys
try:
    r = json.loads(sys.stdin.read())
except Exception:
    sys.exit(1)
sys.exit(0 if r.get("value") is not None and r.get("backend") != "cpu"
         else 1)'
}

run() {  # run <marker> <deadline_s> [ENV=VAL ...]
  local mark="$1" deadline="$2"; shift 2
  [ -e "$MARK/$mark" ] && return 0
  wait_up
  echo "[hw] $(date -u +%H:%M:%S) start $mark: $*" >&2
  # a crashed bench emits no row: require a NEW line AND rc=0 before
  # marking done, else tail -1 would re-judge the previous config's row
  local n0
  n0=$(wc -l < "$OUT" 2>/dev/null || echo 0)
  env "$@" BENCH_REPS=3 BENCH_REQUIRE_TPU=1 BENCH_DEADLINE_S="$deadline" \
      python bench.py >> "$OUT" 2>> "$LOG"
  local rc=$?
  if [ "$rc" -eq 0 ] && [ "$(wc -l < "$OUT")" -gt "$n0" ] && row_ok; then
    touch "$MARK/$mark"
    echo "[hw] $(date -u +%H:%M:%S) done $mark" >&2
  else
    echo "[hw] $(date -u +%H:%M:%S) $mark yielded no TPU number (rc=$rc)" >&2
  fi
}

script_once() {  # script_once <marker> <script> [env...]
  local mark="$1" scr="$2"; shift 2
  [ -e "$MARK/$mark" ] && return 0
  wait_up
  echo "[hw] $(date -u +%H:%M:%S) start $mark ($scr)" >&2
  if env "$@" python "$scr" >> "$LOG" 2>&1; then
    touch "$MARK/$mark"
  else
    echo "[hw] $(date -u +%H:%M:%S) $mark FAILED (rc=$?)" >&2
  fi
}

# ---- 1. driver-critical: the exact BENCH_r03 config (NX=48 defaults) ----
run nx48_default 10800 BENCH_NX=48

# ---- 2. MFU variants at NX=32 (cheap compiles, fast reps) ----
run nx32_default 4000 BENCH_NX=32
run nx32_profile 4000 BENCH_NX=32 SLU_TPU_PROFILE=1
run nx32_fused   6000 BENCH_NX=32 BENCH_GRANULARITY=fused
run nx32_level   4000 BENCH_NX=32 BENCH_GRANULARITY=level
run nx32_prec_hi 4000 BENCH_NX=32 SLU_TPU_PRECISION=high
run nx32_bf16    4000 BENCH_NX=32 BENCH_DTYPE=bfloat16
run nx32_host3e7 4000 BENCH_NX=32 SLU_TPU_HOST_FLOPS=3e7
run nx32_amalg0  4000 BENCH_NX=32 BENCH_AMALG=0
run nx32_amalg15 4000 BENCH_NX=32 BENCH_AMALG=1.5
run nx32_ms512   4000 BENCH_NX=32 BENCH_MAXSUPER=512
run nx32_geo3d   6000 BENCH_NX=32 BENCH_MATRIX=geo3d
# solve ladder (VERDICT r3 weak #4): DiagInv turns the device solve's
# triangular solves into batched GEMMs — bench already reports
# solve_seconds/solve_gflops per row, so these rows A/B the knob
run nx32_diaginv 4000 BENCH_NX=32 SLU_TPU_DIAG_INV=1
run nx48_diaginv 6000 BENCH_NX=48 SLU_TPU_DIAG_INV=1

# ---- 3. best-variant checks at the driver size ----
run nx48_fused   10800 BENCH_NX=48 BENCH_GRANULARITY=fused
run nx48_prec_hi 6000  BENCH_NX=48 SLU_TPU_PRECISION=high
run nx48_profile 6000  BENCH_NX=48 SLU_TPU_PROFILE=1

# ---- 4. size ladder upward (config-4 class) ----
run nx24_default 3000 BENCH_NX=24
run nx56 12000 BENCH_NX=56
run nx64 14400 BENCH_NX=64
run nx72 14400 BENCH_NX=72 SLU_TPU_FRONT_BYTES_LIMIT=4000000000
run nx80 14400 BENCH_NX=80 SLU_TPU_FRONT_BYTES_LIMIT=4000000000

# ---- 5. auxiliary hardware measurements ----
script_once baseline_fixtures scripts/baseline_fixtures_tpu.py
script_once df64_cost scripts/df64_cost_tpu.py

# ---- 6. hardware-only tests (complex on the accelerator etc.) ----
# test list collected from the file so new tests are picked up; the
# legacy combined marker (hw_tests) counts as done for all of them
HW_TESTS=$(SLU_TPU_HW_TESTS=1 python -m pytest tests/test_tpu_hw.py \
           --collect-only -q 2>/dev/null | grep '::' | sed 's/.*:://')
for t in $HW_TESTS; do
  if [ ! -e "$MARK/hw_$t" ] && [ ! -e "$MARK/hw_tests" ]; then
    wait_up
    if SLU_TPU_HW_TESTS=1 python -m pytest "tests/test_tpu_hw.py::$t" -v \
        >> "$LOG" 2>&1; then
      touch "$MARK/hw_$t"
    else
      echo "[hw] hw test $t FAILED" >&2
    fi
  fi
done

echo "[hw] session complete $(date -u +%H:%M:%S)" >&2
