"""Solver options.

Mirrors the reference's runtime option struct ``superlu_dist_options_t``
(SRC/superlu_defs.h:628-657) and its defaults ``set_default_options_dist``
(SRC/util.c:376-401), re-expressed for the TPU-native pipeline.  TPU-specific
knobs (factor dtype, bucket geometry) replace the CPU/GPU tuning env vars
(sp_ienv_dist, SRC/sp_ienv.c:70-123; get_cublas_nb etc., SRC/util.c:932-972).
"""

from __future__ import annotations

import dataclasses
import enum
import os


class YesNo(enum.Enum):
    NO = 0
    YES = 1


# ---------------------------------------------------------------------------
# Environment-knob registry — the single source of truth for every env var
# the project reads (the sp_ienv_dist environment tier generalized,
# SRC/sp_ienv.c:70-123).  Every read routes through env_int/env_float/
# env_str/env_flag below, so slulint rule SLU104 (analysis/rules_env.py)
# can flag any os.environ read whose key is not declared here, and
# SLU_TPU_STRICT_ENV=1 turns a typo'd SLU_TPU_* knob name into a hard
# error instead of a silently-ignored setting.  docs/ANALYSIS.md carries
# the generated table (knob_table_md).
# ---------------------------------------------------------------------------


class UnknownKnobError(KeyError):
    """An env knob was read or set that the registry does not declare."""


@dataclasses.dataclass(frozen=True)
class Knob:
    name: str
    kind: str            # "int" | "float" | "str" | "flag"
    default: object
    help: str
    group: str = "solver"
    choices: tuple | None = None


KNOB_REGISTRY: dict[str, Knob] = {}


def register_knob(name: str, kind: str, default, help: str,
                  group: str = "solver", choices: tuple | None = None) -> None:
    assert kind in ("int", "float", "str", "flag"), kind
    KNOB_REGISTRY[name] = Knob(name, kind, default, help, group, choices)


def _register_all() -> None:
    r = register_knob
    # --- symbolic / blocking (sp_ienv analogs) -----------------------------
    r("NREL", "int", 20, "leaf-subtree relaxation width (reference sp_ienv(2))")
    r("NSUP", "int", 256, "max supernode width (reference sp_ienv(3))")
    r("SLU_TPU_MIN_BUCKET", "int", 8,
      "smallest padded front dimension for size-class bucketing")
    r("SLU_TPU_AMALG_TOL", "float", 1.2,
      "fill-tolerant amalgamation flop-growth tolerance (0 disables)")
    r("SLU_TPU_SYMB_THREADS", "int", 1,
      "threads for the native symbolic factorization (psymbfact analog)")
    # --- numeric executors -------------------------------------------------
    r("SLU_TPU_PRECISION", "str", "highest",
      "MXU pass count for f32 Schur GEMMs (legacy; superseded by "
      "SLU_TPU_GEMM_PREC — an explicitly-set value still maps onto the "
      "tier ladder: default->default, high->f32, highest->highest)",
      group="numeric", choices=("default", "high", "highest"))
    r("SLU_TPU_GEMM_PREC", "str", "",
      "Schur-update GEMM precision tier for the factor hot path "
      "(ops/dense.gemm_precision): bf16 = bf16 inputs with f32 "
      "accumulation (native MXU rate), default = single-pass bf16 on "
      "native inputs (the tensorfloat analog), f32 = 3-pass "
      "(~f32-mantissa), highest = 6-pass full f32.  Empty = 'default' "
      "unless a legacy SLU_TPU_PRECISION is explicitly set.  Reduced "
      "tiers are BERR-gated: the escalation ladder refactors the same "
      "skeleton at the next tier when delivered accuracy misses the "
      "gate (docs/PERFORMANCE.md throughput ladder)", group="numeric",
      choices=("", "bf16", "default", "f32", "highest"))
    r("SLU_TPU_PALLAS", "str", "auto",
      "Pallas fused gather/scatter kernels for the extend-add and "
      "A-assembly hot spots (numeric/pallas_kernels.py): auto = on "
      "when a TPU backend is present, 1/on = force (interprets on "
      "CPU), interpret = force interpreter mode, 0/off = the .at[] "
      "lowering.  Both paths are bitwise-identical "
      "(tests/test_precision_ladder.py pins it)", group="numeric",
      choices=("auto", "0", "1", "on", "off", "interpret"))
    r("SLU_TPU_PIVOT_KERNEL", "str", "blocked",
      "panel factorization kernel", group="numeric",
      choices=("blocked", "recursive"))
    r("SLU_TPU_FRONT_BYTES_LIMIT", "float", 6e9,
      "padded-front bytes above which the stream executor offloads to host",
      group="numeric")
    r("SLU_TPU_OFFLOAD_LAG", "int", 8,
      "in-flight group window of the host-offload pipeline", group="numeric")
    r("SLU_TPU_HOST_FLOPS", "float", 0.0,
      "run leading levels below this flop count on the host CPU (0=off)",
      group="numeric")
    r("SLU_TPU_SCHEDULE", "str", "dataflow",
      "factor-group scheduler: earliest-ready dataflow batching or "
      "strict elimination-level lockstep", group="numeric",
      choices=("dataflow", "level"))
    r("SLU_TPU_SCHED_WINDOW", "int", 8,
      "dataflow look-ahead window in elimination levels (1 degenerates "
      "to the level partition, 0 = unbounded)", group="numeric")
    r("SLU_TPU_SCHED_ALIGN", "float", 1.1,
      "shape-key coalescing flop tolerance for batch packing "
      "(<= 1 disables)", group="numeric")
    # --- bucket-ladder closure / mega executor (numeric/{plan,mega}.py) -----
    r("SLU_TPU_BUCKET_BASE", "int", 8,
      "smallest rung of the canonical bucket ladder shared by the plan "
      "bucketing and every executor's pad-to-rung rounding "
      "(numeric/plan.bucket_rung — the one source of truth)",
      group="numeric")
    r("SLU_TPU_BUCKET_GROWTH", "float", 2.0,
      "geometric growth of the canonical bucket ladder (rungs rounded "
      "to multiples of 8 above the base)", group="numeric")
    r("SLU_TPU_BUCKET_CLOSED", "flag", False,
      "close the factor plan's shape-key set: merge every (W, U) "
      "dispatch key onto <= SLU_TPU_BUCKET_KEYS canonical ladder rungs "
      "so the compiled-program count is independent of matrix size "
      "(the mega-executor prerequisite)", group="numeric")
    r("SLU_TPU_BUCKET_KEYS", "int", 6,
      "maximum distinct (W, U) shape keys a closed plan may carry "
      "(SLU_TPU_BUCKET_CLOSED=1); the mega executor compiles exactly "
      "one program per key", group="numeric")
    r("SLU_TPU_EXECUTOR", "str", "auto",
      "numeric-factorization executor: one whole-program jit (fused), "
      "one kernel per shape key (stream), one data-driven program per "
      "closed shape bucket (mega), the shard_map mesh tier with "
      "in-program collectives (spmd — needs a single-process mesh), or "
      "the backend-dependent default (auto).  df64 factorization keeps "
      "its own executor",
      group="numeric", choices=("auto", "fused", "stream", "mega",
                                "spmd"))
    r("SLU_TPU_SPMD", "str", "auto",
      "shard_map SPMD tier gate (parallel/spmd.py): auto/empty = on "
      "for single-process meshes (one compiled program per factor and "
      "per solve-sweep bucket, bitwise-identical to the lockstep "
      "path), 0/off = keep the GSPMD stream/fused tiers, anything "
      "else = force on", group="numeric",
      choices=("auto", "0", "1", "on", "off"))
    r("SLU_TPU_DIAG_INV", "flag", False,
      "precompute inverted diagonal blocks (reference DiagInv)",
      group="numeric")
    # --- device solve / serving tier (solve/plan.py, serve/) ---------------
    r("SLU_TPU_SOLVE_SCHEDULE", "str", "dataflow",
      "sweep-batch scheduler for the device triangular solve: "
      "earliest-ready dataflow batching, strict level lockstep, or the "
      "factor plan's grouping 1:1", group="solve",
      choices=("dataflow", "level", "factor"))
    r("SLU_TPU_SOLVE_WINDOW", "int", 0,
      "dataflow look-ahead window of the solve scheduler in elimination "
      "levels (0 = unbounded — the solve holds no Schur pool, so "
      "liveness does not bound it; 1 degenerates to the level partition)",
      group="solve")
    r("SLU_TPU_SOLVE_ALIGN", "float", 1.25,
      "solve-side shape-key coalescing flop tolerance, applied on top "
      "of the factor keys (<= 1 disables; promoted members get "
      "identity/zero panel padding)", group="solve")
    r("SLU_TPU_SOLVE_NRHS_MAX", "int", 1024,
      "largest nrhs bucket — the column-chunking cap that closes the "
      "solve-kernel compile set", group="solve")
    r("SLU_TPU_SOLVE_NRHS_GROWTH", "float", 1.5,
      "geometric nrhs bucket growth past the power-of-two rungs "
      "(rounded to multiples of 32)", group="solve")
    r("SLU_TPU_SOLVE_TRSM_LEAF", "int", 64,
      "recursive blocked-TRSM leaf width for supernode diagonal blocks "
      "(0 = unblocked vmapped triangular solves)", group="solve")
    r("SLU_TPU_SERVE_MAX_BATCH", "int", 0,
      "SolveServer micro-batch column cap (0 = the nrhs bucket cap)",
      group="serve")
    r("SLU_TPU_SERVE_MAX_WAIT_MS", "float", 2.0,
      "SolveServer coalescing window: how long the dispatcher holds the "
      "oldest pending request open for co-batching before dispatching",
      group="serve")
    r("SLU_TPU_SERVE_QUEUE_MAX", "int", 0,
      "SolveServer admission cap in pending COLUMNS: a submit that "
      "would exceed it is shed with ServeOverloadError instead of "
      "queueing (0 = unbounded, the legacy behavior)", group="serve")
    r("SLU_TPU_SERVE_DEADLINE_MS", "float", 0.0,
      "per-request serving deadline: columns still queued past it are "
      "expired with ServeDeadlineError and removed from the queue "
      "(0 = off)", group="serve")
    r("SLU_TPU_SERVE_BERR_MAX", "float", 0.0,
      "per-request componentwise-berr quality gate: a served ticket "
      "whose berr exceeds it is routed through a per-ticket iterative-"
      "refinement rung (refine/ir.refine_ticket) before delivery "
      "(0 = off; needs the original matrix on the handle)",
      group="serve")
    r("SLU_TPU_SERVE_SCRUB_S", "float", 0.0,
      "factor-integrity scrub period: a background thread re-hashes "
      "the handle's panel stacks against their persist-bundle sha256 "
      "digests every this-many seconds, quarantining the handle with "
      "FactorCorruptError on mismatch (0 = off)", group="serve")
    # --- serving fleet -----------------------------------------------------
    r("SLU_TPU_FLEET_REPLICAS", "int", 2,
      "FleetRouter default replica count (serve/fleet.py): how many "
      "SolveServer replicas the routing front fans submits across",
      group="fleet")
    r("SLU_TPU_FLEET_KIND", "str", "thread",
      "fleet replica isolation: in-process worker threads or spawned "
      "worker processes behind the same interface", group="fleet",
      choices=("thread", "process"))
    r("SLU_TPU_FLEET_HANDLE_BYTES", "int", 0,
      "per-replica resident-handle byte budget for the multi-handle "
      "LRU cache (serve/handlecache.py, sized via the persist lu_meta "
      "cheap peek): least-recently-used idle handles are evicted and "
      "scrub-verified on reload (0 = unbounded)", group="fleet")
    r("SLU_TPU_FLEET_QUEUE_MAX", "int", 0,
      "fleet-level admission cap in undelivered COLUMNS across all "
      "replicas: a submit past it is shed with ServeOverloadError "
      "(reason fleet_queue_full) at the router, before any replica "
      "queues it (0 = unbounded)", group="fleet")
    r("SLU_TPU_FLEET_DEADLINE_MS", "float", 0.0,
      "end-to-end per-ticket fleet deadline: a ticket undelivered past "
      "it — queued, in flight, or mid-failover — is expired with "
      "ServeDeadlineError by the health monitor or the waiting ticket "
      "itself (0 = off)", group="fleet")
    r("SLU_TPU_FLEET_HEALTH_S", "float", 0.05,
      "fleet health-monitor poll period: replica process/thread "
      "liveness (pid_alive — the PR 8 detector verdict), failover "
      "re-routing of undelivered tickets, and deadline sweeps run on "
      "this cadence", group="fleet")
    r("SLU_TPU_POOL_PARTITION", "flag", False,
      "shard the Schur update pool across all mesh devices", group="numeric")
    # --- distributed tier --------------------------------------------------
    r("SLU_TPU_PAR_SYMB_FACT", "flag", False,
      "partition ordering+symbolic across ranks (ParSymbFact analog)",
      group="parallel")
    r("SLU_TPU_FAULTS", "str", "",
      "fault-injection spec for TreeComm (e.g. 'drop=0.2,seed=7')",
      group="parallel")
    r("SLU_TPU_VERIFY_COLLECTIVES", "flag", False,
      "TreeComm lockstep-verify mode: cross-check every collective's "
      "(call-site, op, shape/dtype, seq) digest across ranks and raise "
      "CollectiveMismatchError instead of deadlocking (runtime SLU106)",
      group="parallel")
    r("SLU_TPU_VERIFY_PROGRAMS", "flag", False,
      "program-audit mode (utils/programaudit.py): every jitted "
      "program the executors build is traced once at construction/"
      "AOT-stage time and walked against the slulint v4 IR rules — "
      "SLU111 donation/aliasing, SLU112 baked-constant blowup, SLU114 "
      "SPMD collective lockstep — raising ProgramAuditError before the "
      "program runs; feeds slu_program_audit_total and the compile "
      "census's donation-coverage / baked-const-bytes fields",
      group="parallel")
    r("SLU_TPU_VERIFY_DTYPES", "flag", False,
      "precision-audit mode (utils/programaudit.py): every jitted "
      "program the executors build is additionally walked against the "
      "slulint v5 precision rules — SLU115 narrowing converts outside "
      "the sanctioned GEMM-input pattern, SLU116 dot_general "
      "accumulation width below the widest operand (or below f32 on "
      "16-bit inputs) — raising PrecisionAuditError before the program "
      "runs; feeds slu_precision_audit_total and `label#dtypes` census "
      "audit notes.  Independent of SLU_TPU_VERIFY_PROGRAMS",
      group="parallel")
    r("SLU_TPU_VERIFY_SHARDING", "flag", False,
      "sharding-audit mode (utils/programaudit.py): every jitted "
      "program the executors build is additionally walked against the "
      "slulint v6 sharding/memory rules — SLU119 implicit replication/"
      "reshard blowup (an op whose operand shardings force an implicit "
      "all-gather or a >= 1 MiB reshard), SLU121 static peak-live-bytes "
      "against SLU_TPU_MEM_BUDGET_BYTES — raising ShardingAuditError/"
      "MemoryBudgetError before the program runs; feeds "
      "slu_sharding_audit_total and `label#sharding` census audit notes "
      "(peak_bytes_est, replicated_bytes).  Independent of "
      "SLU_TPU_VERIFY_PROGRAMS/SLU_TPU_VERIFY_DTYPES", group="parallel")
    r("SLU_TPU_MEM_BUDGET_BYTES", "int", 0,
      "per-program static peak-memory budget in bytes (0 = off): the "
      "SLU121 liveness walk's high-water live-byte estimate (args + "
      "consts + intermediates, free-after-last-use) must fit it or the "
      "submit raises MemoryBudgetError naming the program — the mega "
      "executor's padded-rung bucket programs are the first real "
      "consumer (the error names the offending bucket rung).  Setting "
      "it implies the sharding audit even without "
      "SLU_TPU_VERIFY_SHARDING=1", group="parallel")
    r("SLU_TPU_VERIFY_LOCKS", "flag", False,
      "lock-order verify mode (utils/lockwatch.py): instrument every "
      "make_lock/make_condition lock, record per-thread acquisition "
      "stacks into a global order graph, and raise LockOrderError "
      "naming both call sites on the first inversion instead of "
      "deadlocking (runtime SLU109); feeds the slu_lock_hold_seconds "
      "histogram when metrics are on", group="parallel")
    # --- rank-failure tolerance (parallel/recover.py, docs/RELIABILITY.md) --
    r("SLU_TPU_COMM_TIMEOUT_S", "float", 0.0,
      "bounded-wait collectives: every native tree leg's spin loop gets "
      "this deadline (exponential backoff + jitter); on expiry the "
      "failure detector is consulted — dead peer => RankFailureError on "
      "every survivor, live peer => retry.  0 = unbounded (legacy)",
      group="parallel")
    r("SLU_TPU_COMM_RETRIES", "int", 0,
      "timed-out-but-peer-alive retry budget per collective leg; "
      "exhausting it raises CommTimeoutError.  0 = unlimited (a slow "
      "peer is waited out; only DEATH fails the collective)",
      group="parallel")
    r("SLU_TPU_HEARTBEAT_S", "float", 0.5,
      "failure-detector heartbeat interval (epoch bumps in the shared "
      "segment + heartbeat-age gauge); the thread only starts when "
      "SLU_TPU_COMM_TIMEOUT_S > 0.  0 disables the thread (pid "
      "liveness still detects death)", group="parallel")
    r("SLU_TPU_FT", "str", "abort",
      "rank-failure policy for fault-tolerant drivers "
      "(parallel/recover.pgssvx_ft): abort = raise RankFailureError; "
      "shrink = survivors re-partition and resume from the checkpoint "
      "frontier; respawn = replacement processes take the dead ranks",
      group="parallel", choices=("abort", "shrink", "respawn"))
    # --- index width -------------------------------------------------------
    r("SLU_TPU_INT64", "flag", False,
      "64-bit pattern indices (reference XSDK_INDEX_SIZE=64 analog)")
    # --- solver health & recovery ------------------------------------------
    r("SLU_TPU_RECOVERY", "flag", True,
      "automatic escalation ladder on refinement stagnation",
      group="recovery")
    r("SLU_TPU_SENTINELS", "flag", True,
      "non-finite isfinite sentinels in the numeric layer", group="recovery")
    r("SLU_TPU_REFACTOR_BERR_MAX", "float", 0.0,
      "componentwise-BERR adoption gate for refactor(handle, new_values): "
      "the shadow factorization's canary solve must come in at or under "
      "this backward error or the refactor rolls back (0 = finite-only "
      "gate; an explicit berr_max argument overrides)", group="recovery")
    r("SLU_TPU_REFACTOR_ESCALATE", "flag", True,
      "let a BERR-gated refactor climb the GEMM-precision ladder "
      "(ops/dense.next_gemm_precision, up to recovery.max_rungs shadow "
      "attempts) before rolling back; off = single attempt at the "
      "handle's tier", group="recovery")
    # --- persistence / crash consistency -----------------------------------
    r("SLU_TPU_CKPT_EVERY", "int", 0,
      "flush a factor checkpoint every K completed dispatch groups "
      "(0 = interval checkpoints off; breakdown/deadline/SIGTERM "
      "flushes stay armed once a checkpointer exists)", group="persist")
    r("SLU_TPU_CKPT_DIR", "str", "",
      "factor-checkpoint bundle directory (default .slu_ckpt in the "
      "working directory)", group="persist")
    r("SLU_TPU_DEADLINE_S", "float", 0.0,
      "cooperative factorization deadline in seconds (0 = off): checked "
      "between dispatch groups, checkpoint flushed first, raises "
      "DeadlineExceededError — collectively on the multi-rank path",
      group="persist")
    r("SLU_TPU_DEADLINE_POLL", "int", 1,
      "poll cadence of the collective deadline flag allreduce "
      "(one exchange per N dispatch groups)", group="persist")
    # --- observability -----------------------------------------------------
    r("SLU_TPU_TRACE", "str", "",
      "structured span trace output path ('%p' expands to the pid)",
      group="obs")
    r("SLU_TPU_STATS", "flag", False,
      "print the PStatPrint-analog report from any driver run", group="obs")
    r("SLU_TPU_PROFILE", "flag", False,
      "deprecated legacy '# lvl=' stderr kernel trace", group="obs")
    r("SLU_TPU_PROGRESS", "int", 0,
      "log every K groups/levels issued (0=silent)", group="obs")
    r("SLU_TPU_PEAK_GFLOPS", "float", 0.0,
      "peak GFLOP/s override for the MFU denominator (bench.py, "
      "scripts/mfu_report.py); 0 = auto-detect from the per-backend/"
      "per-precision peak table (utils/peaks.py — TPU kinds tabulated, "
      "CPU calibrated with a one-shot micro-GEMM)", group="obs")
    r("SLU_TPU_METRICS", "str", "",
      "metrics registry: '1' enables; a path additionally dumps the "
      "JSON/Prometheus export there at exit ('%p' expands to the pid)",
      group="obs")
    r("SLU_TPU_FLIGHTREC", "str", "",
      "flight recorder: '1' enables (default flightrec-%p.json dump); "
      "a path names the postmortem artifact ('%p' expands to the pid)",
      group="obs")
    r("SLU_TPU_FLIGHTREC_DEPTH", "int", 512,
      "flight-recorder ring depth (events kept for the postmortem)",
      group="obs")
    r("SLU_TPU_SLO_P99_MS", "float", 0.0,
      "global p99 latency SLO target in ms for the serving tier "
      "(obs/slo.py SLOEvaluator, fleet health model; 0 = no SLO)",
      group="obs")
    r("SLU_TPU_SLO_TARGETS", "str", "",
      "per-traffic-class p99 SLO overrides, 'class=ms,class=ms' "
      "(classes: serve, fleet, driver, bench; overrides "
      "SLU_TPU_SLO_P99_MS for the named class)", group="obs")
    r("SLU_TPU_SLO_BUDGET", "float", 0.01,
      "SLO error budget: provisioned fraction of requests allowed over "
      "the p99 target; burn rate = over-target fraction / budget",
      group="obs")
    # --- native layer ------------------------------------------------------
    r("SLU_TPU_NO_NATIVE", "flag", False,
      "disable the native C++ host-analysis library", group="native")
    r("SLU_TPU_ND_THREADS", "int", 1,
      "threads for native nested dissection", group="native")
    # --- env discipline ----------------------------------------------------
    r("SLU_TPU_STRICT_ENV", "flag", False,
      "raise on SLU_TPU_* env vars the registry does not declare")
    # --- test / CI harness -------------------------------------------------
    r("SLU_TPU_CHAOS", "str", "",
      "failure-domain chaos-injection spec (testing/chaos.py, e.g. "
      "'kill_group=5', 'nan_supernode=3', 'kill_refactor@step=0', "
      "'poison_values=2'); empty = off", group="test")
    r("SLU_TPU_SKIP_PROBE", "flag", False,
      "__graft_entry__: skip the accelerator probe", group="test")
    r("SLU_TPU_DRYRUN_BIG", "str", "1",
      "__graft_entry__: include the n=1e5 pool-partition dryrun phase",
      group="test")
    r("SLU_TPU_ORIG_PLATFORMS", "str", "",
      "test harness stash of the session's original JAX_PLATFORMS pin",
      group="test")
    # --- external (read, not owned, by this project) -----------------------
    for name, help_ in (
            ("JAX_PLATFORMS", "jax backend selection"),
            ("XLA_FLAGS", "XLA compiler/runtime flags"),
            ("JAX_ENABLE_X64", "jax 64-bit mode"),
            ("JAX_DEBUG_NANS", "raise on NaN production in jitted code"),
            ("PYTHONPATH", "module search path for subprocesses")):
        r(name, "str", "", help_, group="external")
    # --- bench.py ----------------------------------------------------------
    r("BENCH_DEADLINE_S", "float", 1350.0,
      "bench watchdog deadline (seconds)", group="bench")
    for name, help_ in (
            ("BENCH_NO_PROBE", "skip the TPU probe subprocess"),
            ("BENCH_REQUIRE_TPU", "fail instead of falling back to CPU"),
            ("BENCH_FORCE_CPU", "pin the bench to the CPU backend")):
        r(name, "flag", False, help_, group="bench")
    for name, kind, default, help_ in (
            ("BENCH_NX", "int", 48, "Poisson grid edge (n = NX^3)"),
            ("BENCH_REPS", "int", 3, "timed repetitions"),
            ("BENCH_DTYPE", "str", "float32", "factor dtype"),
            ("BENCH_PEAK_F32_TFLOPS", "float", 49.0,
             "peak f32 TFLOP/s for the MFU denominator"),
            ("BENCH_RELAX", "int", None, "NREL override for the bench"),
            ("BENCH_MAXSUPER", "int", None, "NSUP override for the bench"),
            ("BENCH_MINBUCKET", "int", None, "min bucket override"),
            ("BENCH_GROWTH", "float", None, "bucket growth override"),
            ("BENCH_AMALG", "float", None, "amalgamation tol override"),
            ("BENCH_MATRIX", "str", "poisson3d", "bench matrix family"),
            ("BENCH_GRANULARITY", "str", None, "stream granularity"),
            ("BENCH_SOLVE_NRHS", "str", "1,64,1024",
             "device-solve bench nrhs sweep (comma list; empty skips)"),
            ("BENCH_MESH", "str", "",
             "mesh mode: a 'RxC' spec (e.g. 1x8) factors and solves on "
             "that virtual/real device grid through the shard_map SPMD "
             "tier and emits mesh_shape/n_devices/spmd row fields; "
             "empty = single-device bench")):
        r(name, kind, default, help_, group="bench")
    # --- measurement scripts ----------------------------------------------
    for name, kind, default, help_ in (
            ("CONFIG4_MESH", "str", "1", "config4_virtual mesh spec"),
            ("CONFIG4_NX", "int", 100, "config4_virtual grid edge"),
            ("CONFIG4_DTYPE", "str", "float32", "config4_virtual dtype"),
            ("PGS_NX", "int", 48, "pgssvx_scale grid edge"),
            ("MAS_DEADLINE_S", "float", 14400.0,
             "mesh_analysis_scale deadline"),
            ("MAS_NX", "int", 48, "mesh_analysis_scale grid edge"),
            ("MAS_MODES", "str", "replicated,root_bcast,parsymb",
             "mesh_analysis_scale mode list"),
            ("DF64_NX", "str", "12,16,20", "df64_cost_tpu grid edges"),
            ("DF64S_MESH", "str", "1", "df64_scale mesh spec"),
            ("DF64S_NX", "int", 16, "df64_scale grid edge"),
            ("DF64S_KAPPA", "float", 1e10, "df64_scale condition target"),
            ("DF64S_COMPLEX", "str", "0", "df64_scale complex twin"),
            ("SLU_TPU_BENCH_HISTORY", "str", "",
             "bench-history JSONL DB path (default .cache/"
             "bench_history.jsonl; scripts/bench_history.py + "
             "check_perf_regress.py)"),
            ("PERF_GATE_NX", "int", 8,
             "check_perf_regress micro-bench grid edge"),
            ("PERF_GATE_TOL", "float", 0.5,
             "check_perf_regress noise tolerance (fail below "
             "(1-tol)*median)"),
            ("PERF_GATE_MIN_SAMPLES", "int", 3,
             "check_perf_regress history rows required before enforcing"),
            ("SLO_GATE_NRHS", "str", "1,8",
             "check_slo served-workload nrhs sweep (comma list)"),
            ("SLO_GATE_REQUESTS", "int", 48,
             "check_slo requests per nrhs bucket"),
            ("SLO_GATE_TOL", "float", 1.0,
             "check_slo noise tolerance (fail above (1+tol)*median p99)"),
            ("SLO_GATE_MIN_SAMPLES", "int", 3,
             "check_slo history rows required before enforcing")):
        r(name, kind, default, help_, group="scripts")


_register_all()

_FLAG_FALSE = ("", "0", "false", "no", "off")
_strict_checked = False


def _check_strict_env() -> None:
    """Under SLU_TPU_STRICT_ENV=1, an SLU_TPU_* env var the registry does
    not declare raises (with a did-you-mean) instead of being silently
    ignored — a typo'd knob name can otherwise invalidate a whole
    hardware sweep.  Checked once, on the first registry read."""
    global _strict_checked
    if _strict_checked:
        return
    _strict_checked = True
    raw = os.environ.get("SLU_TPU_STRICT_ENV", "")
    if raw.strip().lower() in _FLAG_FALSE:
        return
    unknown = sorted(k for k in os.environ
                     if k.startswith("SLU_TPU_") and k not in KNOB_REGISTRY)
    if unknown:
        import difflib
        hints = []
        for k in unknown:
            close = difflib.get_close_matches(k, KNOB_REGISTRY, n=1)
            hints.append(f"{k}" + (f" (did you mean {close[0]}?)"
                                   if close else ""))
        raise UnknownKnobError(
            "unknown SLU_TPU_* environment knob(s) under "
            f"SLU_TPU_STRICT_ENV=1: {', '.join(hints)}")


_UNSET = object()


def _knob_raw(name: str, default):
    if name not in KNOB_REGISTRY:
        raise UnknownKnobError(
            f"env knob {name!r} is not declared in the registry "
            "(superlu_dist_tpu/utils/options.py) — register it there")
    _check_strict_env()
    raw = os.environ.get(name)
    d = KNOB_REGISTRY[name].default if default is _UNSET else default
    return raw, d


def env_int(name: str, default=_UNSET) -> int:
    """Registered integer knob; unset or unparsable values yield the
    default (the historical _env_int contract)."""
    raw, d = _knob_raw(name, default)
    if raw is None:
        return d
    try:
        return int(raw)
    except ValueError:
        return d


def env_float(name: str, default=_UNSET) -> float:
    raw, d = _knob_raw(name, default)
    if raw is None:
        return d
    try:
        return float(raw)
    except ValueError:
        return d


def env_str(name: str, default=_UNSET) -> str:
    raw, d = _knob_raw(name, default)
    return d if raw is None else raw


def env_flag(name: str, default=_UNSET) -> bool:
    """Registered on/off knob: unset -> default; '', '0', 'false', 'no',
    'off' (any case) -> False; anything else -> True."""
    raw, d = _knob_raw(name, default)
    if raw is None:
        return bool(d)
    return raw.strip().lower() not in _FLAG_FALSE


_deprecation_warned: set = set()


def deprecated_knob_warning(name: str, hint: str) -> None:
    """One-shot ``DeprecationWarning`` for a deprecated-but-still-honored
    knob (at most once per process per knob, and only when the knob is
    actually set in the environment) — the knob's OUTPUT stays unchanged
    so downstream parsers (scripts/mfu_report.py) keep working."""
    if name in _deprecation_warned or os.environ.get(name) is None:
        return
    _deprecation_warned.add(name)
    import warnings
    warnings.warn(f"{name} is deprecated: {hint}",
                  DeprecationWarning, stacklevel=3)


def knob_table_md(groups: tuple | None = None) -> str:
    """Markdown table of the registry (docs/ANALYSIS.md carries it; the
    doc test asserts it stays in sync with the registry)."""
    lines = ["| Knob | Kind | Default | Group | Meaning |",
             "|---|---|---|---|---|"]
    for k in sorted(KNOB_REGISTRY.values(),
                    key=lambda k: (k.group, k.name)):
        if groups is not None and k.group not in groups:
            continue
        extra = (f" ({'/'.join(map(str, k.choices))})" if k.choices else "")
        lines.append(f"| `{k.name}` | {k.kind} | `{k.default}` | {k.group} "
                     f"| {k.help}{extra} |")
    return "\n".join(lines)


class Fact(enum.Enum):
    """Factorization reuse tiers (reference fact_t, superlu_defs.h:489-510).

    These are the reference API's main performance feature for time-stepping
    users (SURVEY.md §5 checkpoint/resume): each tier skips more of the
    pipeline on a repeated solve.
    """

    DOFACT = 0                      # factor from scratch
    SamePattern = 1                 # reuse column perm + symbolic + plan
    SamePattern_SameRowPerm = 2     # additionally reuse row perm + scalings
    FACTORED = 3                    # reuse the numeric factors (solve only)


class ColPerm(enum.Enum):
    """Fill-reducing column orderings (reference colperm_t; dispatch
    get_perm_c_dist, SRC/get_perm_c.c:463-530)."""

    NATURAL = 0
    MMD_AT_PLUS_A = 1       # minimum degree on pattern of A^T + A
    ND_AT_PLUS_A = 2        # multilevel nested dissection (METIS analog)
    METIS_AT_PLUS_A = 2     # alias: the reference default maps to our ND
    MY_PERMC = 3            # user-supplied permutation
    MMD_ATA = 4             # minimum degree on pattern of A^T A
    COLAMD = 5              # approximate column MD directly on A


class RowPerm(enum.Enum):
    """Numerical row pivoting strategy (reference rowperm_t;
    dldperm_dist, SRC/dldperm_dist.c:95)."""

    NOROWPERM = 0
    LargeDiag_MC64 = 1      # maximum-product weighted bipartite matching
    LargeDiag_AWPM = 2      # approximate-weight perfect matching (the
                            # CombBLAS HWPM analog — perm only, no scalings)
    MY_PERMR = 3


class IterRefine(enum.Enum):
    """Iterative refinement (reference IterRefine_t; pdgsrfs.c:120)."""

    NOREFINE = 0
    SLU_SINGLE = 1
    SLU_DOUBLE = 2


class Trans(enum.Enum):
    NOTRANS = 0
    TRANS = 1
    CONJ = 2


@dataclasses.dataclass
class RecoveryPolicy:
    """Solver health & recovery policy — the pdgscon/pdgsrfs repair loop
    made automatic (PAPER.md L4/L8: GESP trades pivoting stability for
    speed, then detects and repairs the damage afterwards).

    ``enabled`` drives the escalation ladder in drivers/gssvx.py: when
    iterative refinement stagnates above ``berr_target`` the driver
    escalates residual precision, retries the correction solves on
    higher-precision factors (f64 on CPU, emulated-double df64 on f32-only
    hardware), and finally refactors with diagnostics-informed re-scaling /
    re-ordering.  Every rung is recorded in the SolveReport
    (utils/stats.py) so callers see what degraded and why the answer is
    still trustworthy.

    ``sentinels`` arms the cheap isfinite reductions on factored panels
    (numeric/factor.py, numeric/stream.py) that trip NumericBreakdownError
    at the offending supernode, and the final solution check in the driver.

    ``condest`` selects when the Hager–Higham condition estimate (rcond,
    the pdgscon analog) and the normwise forward-error bound (ferr) are
    computed: "always", "never", or "auto" (only when the ladder fired or
    tiny pivots were replaced — the cases where the answer needs defending).
    """

    enabled: bool = dataclasses.field(
        default_factory=lambda: env_flag("SLU_TPU_RECOVERY"))
    sentinels: bool = dataclasses.field(
        default_factory=lambda: env_flag("SLU_TPU_SENTINELS"))
    condest: str = "auto"              # "always" | "auto" | "never"
    berr_target: float | None = None   # None => 10·eps(residual dtype)
    max_rungs: int = 3                 # ladder depth cap


def _env_int(name: str, default: int) -> int:
    """Back-compat alias for env_int (the knob must be registered)."""
    return env_int(name, default)


def _env_float(name: str, default: float) -> float:
    """Back-compat alias for env_float (the knob must be registered)."""
    return env_float(name, default)


@dataclasses.dataclass
class Options:
    """Runtime options (analog of superlu_dist_options_t).

    Defaults follow set_default_options_dist (SRC/util.c:376-401):
    Fact=DOFACT, Equil=YES, ColPerm=METIS_AT_PLUS_A, RowPerm=LargeDiag_MC64,
    ReplaceTinyPivot, IterRefine=DOUBLE, PrintStat=YES.  The blocking knobs
    read the sp_ienv environment tier (SRC/sp_ienv.c:70-123) at
    construction: NREL (relax), NSUP (max supernode),
    SLU_TPU_MIN_BUCKET — so `NSUP=99 python -m superlu_dist_tpu ...`
    behaves like the reference.
    """

    fact: Fact = Fact.DOFACT
    equil: bool = True
    col_perm: ColPerm = ColPerm.ND_AT_PLUS_A
    row_perm: RowPerm = RowPerm.LargeDiag_MC64
    replace_tiny_pivot: bool = True
    iter_refine: IterRefine = IterRefine.SLU_DOUBLE
    trans: Trans = Trans.NOTRANS
    # DiagInv (reference default YES-iff-LAPACK, SRC/util.c:397-401):
    # precompute inverted diagonal blocks so device solves replace
    # triangular solves with batched GEMMs — pays off for repeated /
    # many-RHS solves.  Env SLU_TPU_DIAG_INV=1 flips the default (the
    # hardware solve-ladder sweep knob).
    diag_inv: bool = dataclasses.field(
        default_factory=lambda: env_flag("SLU_TPU_DIAG_INV"))
    # PStatPrint analog reachable without code: SLU_TPU_STATS=1 flips the
    # default so any driver run (CLI, examples, embedding callers) prints
    # the options banner + full Stats.report (incl. the solve-health
    # line) — see docs/OBSERVABILITY.md
    print_stat: bool = dataclasses.field(
        default_factory=lambda: env_flag("SLU_TPU_STATS"))
    # --- symbolic / blocking tuning (sp_ienv analogs, SRC/sp_ienv.c:70-123) ---
    # NREL: amalgamate subtrees with <= relax cols
    relax: int = dataclasses.field(
        default_factory=lambda: _env_int("NREL", 20))
    # NSUP: cap supernode width.  The reference uses 128 (CPU-cache-sized);
    # the MXU wants wider panels (SURVEY.md §7 step 10).
    max_supernode: int = dataclasses.field(
        default_factory=lambda: _env_int("NSUP", 256))
    # --- TPU-native knobs -----------------------------------------------------
    factor_dtype: str | None = None   # None => float32 on TPU, float64 on CPU
    ir_dtype: str = "float64"         # residual precision for refinement
    # fill-tolerant supernode amalgamation (symbfact.amalgamate_supernodes):
    # merged-front flops may grow up to this factor per merge.  The MXU
    # wants wide pivots; the measured padding/dispatch win dwarfs the
    # ≤ tol structural-flop cost.  0 disables (reference-style zero-fill
    # supernodes + leaf relaxation only).
    amalg_tol: float = dataclasses.field(
        default_factory=lambda: _env_float("SLU_TPU_AMALG_TOL", 1.2))
    bucket_growth: float = 1.5        # geometric padding factor for front
                                      # size buckets (static-shape batching)
    min_bucket: int = dataclasses.field(   # smallest padded front dimension
        default_factory=lambda: _env_int("SLU_TPU_MIN_BUCKET", 8))
    # factor-group scheduler (numeric/plan.py): "dataflow" packs ready
    # supernodes into maximal same-shape batches across elimination
    # levels (dispatch-count collapse); "level" is the strict
    # level-lockstep partition kept selectable for A/B — the two produce
    # bitwise-identical L/U (tests/test_schedule.py)
    schedule: str = dataclasses.field(
        default_factory=lambda: env_str("SLU_TPU_SCHEDULE"))
    # dataflow look-ahead window in elimination levels: bounds how far
    # past the oldest incomplete level ready work may be pulled forward,
    # so Schur-pool liveness stays bounded (1 = level order, 0 = unbounded)
    sched_window: int = dataclasses.field(
        default_factory=lambda: env_int("SLU_TPU_SCHED_WINDOW"))
    # shape-key coalescing tolerance: merged batches may execute up to
    # this factor of their members' original padded flops (<= 1
    # disables).  Applied before the schedule branch, so "level" and
    # "dataflow" pad identically and stay bitwise-comparable.
    sched_align: float = dataclasses.field(
        default_factory=lambda: env_float("SLU_TPU_SCHED_ALIGN"))
    # Schur-update GEMM precision tier (ops/dense.gemm_precision):
    # None resolves the SLU_TPU_GEMM_PREC knob (empty knob = "default",
    # the single-pass tensorfloat-analog fast path, with legacy
    # SLU_TPU_PRECISION interop).  Reduced tiers are made safe by the
    # gemm-precision escalation rung: delivered BERR above the gate
    # refactors the same skeleton at the next-higher tier
    # (drivers/gssvx._escalate, docs/PERFORMANCE.md)
    gemm_prec: str | None = dataclasses.field(
        default_factory=lambda: env_str("SLU_TPU_GEMM_PREC") or None)
    # numeric executor selection (numeric/factor.get_executor): "mega"
    # is the bucketed data-driven executor whose compiled-program count
    # is bounded by the closed shape-key set (numeric/mega.py) — pair it
    # with SLU_TPU_BUCKET_CLOSED=1 for the O(1)-in-n compile guarantee.
    # "auto" keeps the backend default (fused on CPU, stream elsewhere).
    executor: str = dataclasses.field(
        default_factory=lambda: env_str("SLU_TPU_EXECUTOR"))
    # close the shape-key set at plan build (numeric/plan._close_shape_keys)
    bucket_closed: bool = dataclasses.field(
        default_factory=lambda: env_flag("SLU_TPU_BUCKET_CLOSED"))
    # device-solve sweep scheduler (solve/plan.py): "dataflow" regroups
    # supernodes across levels into maximal same-shape sweep batches
    # (the serving hot path); "level" and "factor" are the A/B tiers —
    # all three produce the same solution through the same factors
    # (tests/test_solve_plan.py)
    solve_schedule: str = dataclasses.field(
        default_factory=lambda: env_str("SLU_TPU_SOLVE_SCHEDULE"))
    # solve-scheduler look-ahead window (0 = unbounded: no Schur pool
    # bounds the solve, unlike the factor's sched_window)
    solve_window: int = dataclasses.field(
        default_factory=lambda: env_int("SLU_TPU_SOLVE_WINDOW"))
    # solve-side shape-key coalescing tolerance on top of the factor
    # keys (<= 1 disables; promoted panels get identity/zero padding)
    solve_align: float = dataclasses.field(
        default_factory=lambda: env_float("SLU_TPU_SOLVE_ALIGN"))
    # shard the Schur update pool across ALL mesh devices (the n≈1M
    # memory path; only meaningful with a grid) — SLU_TPU_POOL_PARTITION=1
    pool_partition: bool = dataclasses.field(
        default_factory=lambda: env_flag("SLU_TPU_POOL_PARTITION"))
    # distributed analysis for the multi-process tier (the reference's
    # options->ParSymbFact: ParMETIS ordering + psymbfact): ordering and
    # symbolic work/memory partition across the ranks instead of running
    # on root (parallel/panalysis.py) — SLU_TPU_PAR_SYMB_FACT=1
    par_symb_fact: bool = dataclasses.field(
        default_factory=lambda: env_flag("SLU_TPU_PAR_SYMB_FACT"))
    # user-supplied permutations for MY_PERMC / MY_PERMR (real dataclass
    # fields so Options(user_perm_c=...) works — the reference reads these
    # from ScalePermstruct->perm_c/perm_r when ColPerm/RowPerm say MY_*).
    # compare=False: ndarray values would make the generated __eq__ raise.
    user_perm_c: object = dataclasses.field(default=None, compare=False)
    user_perm_r: object = dataclasses.field(default=None, compare=False)
    # solver health & recovery: condition estimation, non-finite sentinels,
    # and the automatic escalation ladder (see RecoveryPolicy)
    recovery: RecoveryPolicy = dataclasses.field(
        default_factory=RecoveryPolicy)
    # --- crash consistency (persist/, docs/RELIABILITY.md) -----------------
    # cooperative factorization deadline: checked between dispatch
    # groups, checkpoint flushed first, DeadlineExceededError raised —
    # collectively (flag allreduce) on the multi-rank path so
    # cancellation can never strand a peer in a collective.  None = off.
    deadline_s: float | None = dataclasses.field(
        default_factory=lambda: env_float("SLU_TPU_DEADLINE_S") or None)
    # factor-checkpoint interval in completed dispatch groups (0 = off);
    # arming it forces the streamed executor (the fused whole-program
    # jit has no group boundaries to checkpoint at)
    ckpt_every: int = dataclasses.field(
        default_factory=lambda: env_int("SLU_TPU_CKPT_EVERY"))
    # checkpoint bundle directory ("" = .slu_ckpt in the working dir)
    ckpt_dir: str = dataclasses.field(
        default_factory=lambda: env_str("SLU_TPU_CKPT_DIR"))
    # --- rank-failure tolerance (parallel/recover.py) ----------------------
    # what a declared-dead peer rank does to a fault-tolerant driver
    # (pgssvx_ft): "abort" re-raises RankFailureError, "shrink" resumes
    # on the survivors, "respawn" replaces the dead rank with a fresh
    # process.  Only consulted by the FT epoch loop — plain pgssvx
    # always surfaces the structured error to its caller.
    ft: str = dataclasses.field(
        default_factory=lambda: env_str("SLU_TPU_FT"))


def set_default_options() -> Options:
    """Analog of set_default_options_dist (SRC/util.c:376).  The sp_ienv
    environment tier applies to every Options() construction (see the
    class docstring), so this is a plain constructor alias."""
    return Options()


def print_options(o: Options) -> str:
    """print_options_dist analog (SRC/util.c:405-439)."""
    lines = ["**************************************************",
             ".. options:"]
    for f in dataclasses.fields(o):
        v = getattr(o, f.name)
        if f.name in ("user_perm_c", "user_perm_r"):
            # summarize, never dump an n-entry permutation into the banner
            v = None if v is None else f"<perm len={len(v)}>"
        elif f.name == "recovery":
            v = (f"enabled={v.enabled} sentinels={v.sentinels} "
                 f"condest={v.condest}")
        lines.append(f"**    {f.name:<20s} {getattr(v, 'name', v)}")
    lines.append("**************************************************")
    return "\n".join(lines)


def default_factor_dtype() -> str:
    """float32 on TPU (no fp64 MXU), float64 elsewhere."""
    try:
        import jax
        platform = jax.default_backend()
    except Exception:  # pragma: no cover - jax always present in practice
        platform = "cpu"
    if platform == "cpu" and os.environ.get("JAX_ENABLE_X64", "").lower() not in ("0", "false"):
        import jax
        if jax.config.read("jax_enable_x64"):
            return "float64"
    return "float32"
