"""Failure-domain chaos tests (testing/chaos.py — docs/RELIABILITY.md).

Each failure domain from ISSUE 7 is injected deterministically and its
contracted outcome asserted: a mid-factor SIGKILL leaves a resumable
frontier; a SIGTERM chains checkpoint flush -> flight dump -> previous
handler; a NaN poke trips the sentinel AT the chosen supernode; 2-rank
deadline cancellation raises on BOTH ranks (collective flag allreduce,
clean under SLU_TPU_VERIFY_COLLECTIVES=1); and a dead rank converts an
infinite collective hang into a bounded, diagnosable abort.
"""

import hashlib
import json
import multiprocessing as mp
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

from superlu_dist_tpu import native
from superlu_dist_tpu.models.gallery import poisson3d
from superlu_dist_tpu.utils.options import Options

pytestmark = pytest.mark.chaos

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
needs_native = pytest.mark.skipif(not native.available(),
                                  reason="native library unavailable")


def _analyzed(nx=8):
    from superlu_dist_tpu.ordering.dispatch import get_perm_c
    from superlu_dist_tpu.sparse.formats import symmetrize_pattern
    from superlu_dist_tpu.symbolic.symbfact import symbolic_factorize
    from superlu_dist_tpu.numeric.plan import build_plan
    a = poisson3d(nx)
    sym = symmetrize_pattern(a)
    sf = symbolic_factorize(sym, get_perm_c(Options(), a, sym))
    return a, build_plan(sf), sym.data[sf.value_perm]


def _digest(fronts):
    h = hashlib.sha256()
    for lp, up in fronts:
        h.update(np.ascontiguousarray(np.asarray(lp)).tobytes())
        h.update(np.ascontiguousarray(np.asarray(up)).tobytes())
    return h.hexdigest()


# ---------------------------------------------------------------------------
# spec parsing
# ---------------------------------------------------------------------------

def test_parse_chaos_spec():
    from superlu_dist_tpu.testing.chaos import parse_chaos_spec
    p = parse_chaos_spec("kill_group=5,signal=term")
    assert p.kill_group == 5 and p.signal == "term" and p.armed
    p = parse_chaos_spec("nan_supernode=3")
    assert p.nan_supernode == 3 and p.kill_group == -1
    assert not parse_chaos_spec("").armed
    with pytest.raises(ValueError, match="unknown"):
        parse_chaos_spec("kill_gruop=5")
    with pytest.raises(ValueError, match="signal"):
        parse_chaos_spec("signal=hup")


def test_chaos_off_is_none(monkeypatch):
    from superlu_dist_tpu.testing.chaos import get_chaos
    monkeypatch.delenv("SLU_TPU_CHAOS", raising=False)
    assert get_chaos() is None


# ---------------------------------------------------------------------------
# NaN-poke domain
# ---------------------------------------------------------------------------

def test_nan_poke_trips_sentinel_at_chosen_supernode(monkeypatch):
    from superlu_dist_tpu.numeric.factor import numeric_factorize
    from superlu_dist_tpu.utils.errors import NumericBreakdownError

    a, plan, vals = _analyzed(nx=6)
    target = 2
    monkeypatch.setenv("SLU_TPU_CHAOS", f"nan_supernode={target}")
    with pytest.raises(NumericBreakdownError) as ei:
        numeric_factorize(plan, vals, a.norm_max(), dtype="float64")
    assert ei.value.supernode == target
    assert ei.value.col == int(plan.sf.sn_start[target])


def test_nan_poke_breakdown_flushes_checkpoint(tmp_path, monkeypatch):
    """Breakdown leaves a crash-consistent frontier behind, the error
    carries its path, and resuming against the SAME (poisoned) inputs
    deterministically reproduces the breakdown."""
    from superlu_dist_tpu.numeric.factor import numeric_factorize
    from superlu_dist_tpu.persist.checkpoint import peek
    from superlu_dist_tpu.utils.errors import NumericBreakdownError

    a, plan, vals = _analyzed(nx=6)
    ck = str(tmp_path / "ck")
    monkeypatch.setenv("SLU_TPU_CHAOS", "nan_supernode=2")
    with pytest.raises(NumericBreakdownError) as ei:
        numeric_factorize(plan, vals, a.norm_max(), dtype="float64",
                          ckpt_dir=ck, ckpt_every=1)
    assert ei.value.checkpoint_path == os.path.abspath(ck)
    meta = peek(ck)
    assert meta["reason"] in ("interval", "numeric-breakdown")
    with pytest.raises(NumericBreakdownError):
        numeric_factorize(plan, vals, a.norm_max(), dtype="float64",
                          resume_from=ck)


# ---------------------------------------------------------------------------
# checkpoint-corruption domain
# ---------------------------------------------------------------------------

def test_corrupted_checkpoint_refuses_resume(tmp_path):
    from superlu_dist_tpu.numeric.factor import numeric_factorize
    from superlu_dist_tpu.testing.chaos import (CountdownDeadline,
                                                corrupt_file)
    from superlu_dist_tpu.utils.errors import (CheckpointCorruptError,
                                               DeadlineExceededError)

    a, plan, vals = _analyzed(nx=8)
    ck = str(tmp_path / "ck")
    with pytest.raises(DeadlineExceededError):
        numeric_factorize(plan, vals, a.norm_max(), dtype="float64",
                          ckpt_dir=ck, deadline=CountdownDeadline(3))
    corrupt_file(os.path.join(ck, "pool.npy"), mode="flip")
    with pytest.raises(CheckpointCorruptError):
        numeric_factorize(plan, vals, a.norm_max(), dtype="float64",
                          resume_from=ck)


# ---------------------------------------------------------------------------
# mid-factor process-kill domain (subprocess victims)
# ---------------------------------------------------------------------------

_VICTIM = r"""
import sys
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)
sys.path.insert(0, {repo!r})
from superlu_dist_tpu.numeric.factor import numeric_factorize
from superlu_dist_tpu.utils.options import env_int, env_str
import tests.test_chaos as T
a, plan, vals = T._analyzed(nx=8)
numeric_factorize(plan, vals, a.norm_max(), dtype="float64",
                  executor="stream",
                  ckpt_dir=env_str("SLU_TPU_CKPT_DIR"),
                  ckpt_every=env_int("SLU_TPU_CKPT_EVERY"))
sys.exit(7)   # the injected kill must prevent us ever getting here
"""


def _run_victim(ck_dir, chaos, flightrec=None, every=2):
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               SLU_TPU_CHAOS=chaos, SLU_TPU_CKPT_DIR=ck_dir,
               SLU_TPU_CKPT_EVERY=str(every))
    if flightrec:
        env["SLU_TPU_FLIGHTREC"] = flightrec
    return subprocess.run(
        [sys.executable, "-c", _VICTIM.format(repo=REPO)],
        env=env, cwd=REPO, capture_output=True, text=True, timeout=240)


def test_sigkill_mid_factor_leaves_resumable_frontier(tmp_path):
    """The kill -9 domain (the acceptance case; the CI gate
    scripts/check_crash_resume.py runs the same scenario standalone):
    nothing flushes at death, the interval frontier is the durable
    state, resume is bitwise-identical."""
    from superlu_dist_tpu.numeric.factor import numeric_factorize
    from superlu_dist_tpu.persist.checkpoint import peek

    a, plan, vals = _analyzed(nx=8)
    ref = numeric_factorize(plan, vals, a.norm_max(), dtype="float64",
                            executor="stream")
    kill = len(plan.groups) // 2
    ck = str(tmp_path / "ck")
    r = _run_victim(ck, f"kill_group={kill}")
    assert r.returncode == -signal.SIGKILL, (r.returncode, r.stderr)
    k = int(peek(ck)["k"])
    assert 0 < k <= kill + 1
    res = numeric_factorize(plan, vals, a.norm_max(), dtype="float64",
                            resume_from=ck)
    assert res.resumed_groups == k
    assert _digest(res.fronts) == _digest(ref.fronts)


def test_sigterm_mid_factor_chains_flush_dump_and_dies(tmp_path):
    """SIGTERM domain: the chained handlers flush the LATEST frontier
    (no interval checkpoints armed here), dump the flight ring with a
    reference to that checkpoint, then the default disposition kills
    the process — and the frontier resumes bitwise."""
    from superlu_dist_tpu.numeric.factor import numeric_factorize
    from superlu_dist_tpu.persist.checkpoint import peek

    a, plan, vals = _analyzed(nx=8)
    ref = numeric_factorize(plan, vals, a.norm_max(), dtype="float64",
                            executor="stream")
    kill = len(plan.groups) // 2
    ck = str(tmp_path / "ck")
    dump = str(tmp_path / "flight.json")
    r = _run_victim(ck, f"kill_group={kill},signal=term",
                    flightrec=dump, every=0)
    assert r.returncode == -signal.SIGTERM, (r.returncode, r.stderr)
    # every=0: ONLY the SIGTERM flush can have written this frontier
    meta = peek(ck)
    assert meta["reason"] == "SIGTERM"
    assert int(meta["k"]) == kill + 1
    doc = json.loads(open(dump).read())
    assert doc["reason"] == "SIGTERM"
    assert doc["checkpoint"] == os.path.abspath(ck)
    res = numeric_factorize(plan, vals, a.norm_max(), dtype="float64",
                            resume_from=ck)
    assert _digest(res.fronts) == _digest(ref.fronts)


def test_sigterm_chains_previously_installed_handler():
    """Satellite fix pinned in-process: arming the flight recorder's
    SIGTERM hook must CHAIN a previously-installed Python handler (it
    still runs, and the process survives because that handler returns)."""
    from superlu_dist_tpu.obs import flightrec

    prev = signal.getsignal(signal.SIGTERM)
    seen = []
    try:
        signal.signal(signal.SIGTERM, lambda s, f: seen.append(s))
        fr = flightrec.FlightRecorder(dump_path="/dev/null")
        flightrec._arm_sigterm(fr)
        os.kill(os.getpid(), signal.SIGTERM)
        for _ in range(100):
            if seen:
                break
            time.sleep(0.01)
        assert seen == [signal.SIGTERM]
    finally:
        signal.signal(signal.SIGTERM, prev)


def test_sigterm_respects_sig_ign():
    """A process that chose to ignore SIGTERM must keep ignoring it
    after the flight recorder arms (the old handler converted SIG_IGN
    into a kill)."""
    code = r"""
import os, signal, sys
signal.signal(signal.SIGTERM, signal.SIG_IGN)
from superlu_dist_tpu.obs import flightrec
fr = flightrec.FlightRecorder(dump_path="/dev/null")
flightrec._arm_sigterm(fr)
os.kill(os.getpid(), signal.SIGTERM)
print("SURVIVED")
"""
    r = subprocess.run([sys.executable, "-c", code], cwd=REPO,
                       env=dict(os.environ, JAX_PLATFORMS="cpu"),
                       capture_output=True, text=True, timeout=120)
    assert r.returncode == 0, (r.returncode, r.stderr)
    assert "SURVIVED" in r.stdout


# ---------------------------------------------------------------------------
# 2-rank cooperative deadline: both ranks raise, no deadlock
# ---------------------------------------------------------------------------

_DEADLINE_RANK1 = r"""
import sys
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)
sys.path.insert(0, {repo!r})
from superlu_dist_tpu.parallel.treecomm import TreeComm
from superlu_dist_tpu.testing.chaos import CountdownDeadline
from superlu_dist_tpu.numeric.factor import numeric_factorize
from superlu_dist_tpu.utils.errors import DeadlineExceededError
import tests.test_chaos as T
name, fire_after = sys.argv[1], int(sys.argv[2])
tc = TreeComm(name, 2, 1, max_len=64, create=False)
try:
    a, plan, vals = T._analyzed(nx=6)
    dl = CountdownDeadline(fire_after, comm=tc)
    try:
        numeric_factorize(plan, vals, a.norm_max(), dtype="float64",
                          executor="stream", deadline=dl)
        print("OUTCOME no-error")
    except DeadlineExceededError as e:
        print("OUTCOME deadline", e.expired_ranks)
finally:
    tc.close()
"""


@needs_native
def test_two_rank_deadline_raises_on_both_ranks(monkeypatch):
    """Acceptance: rank 1's deadline expires, rank 0's never would —
    the collective flag allreduce makes BOTH ranks raise
    DeadlineExceededError together (no deadlock), clean under
    SLU_TPU_VERIFY_COLLECTIVES=1.  Rank 1 runs in a FRESH subprocess
    (not a fork: a forked child of a jax-warmed pytest process can
    deadlock on inherited XLA locks when it compiles)."""
    monkeypatch.setenv("SLU_TPU_VERIFY_COLLECTIVES", "1")
    from superlu_dist_tpu.parallel.treecomm import TreeComm
    from superlu_dist_tpu.testing.chaos import CountdownDeadline
    from superlu_dist_tpu.numeric.factor import numeric_factorize
    from superlu_dist_tpu.utils.errors import DeadlineExceededError

    name = f"/slu_chaos_dl_{os.getpid()}"
    owner = TreeComm(name, 2, 0, max_len=64, create=True)
    # rank 1 expires after 3 polls; rank 0 would never expire on its own
    p = subprocess.Popen(
        [sys.executable, "-c", _DEADLINE_RANK1.format(repo=REPO),
         name, "3"],
        env=dict(os.environ, JAX_PLATFORMS="cpu"), cwd=REPO,
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)
    try:
        a, plan, vals = _analyzed(nx=6)
        dl = CountdownDeadline(10 ** 9, comm=owner)
        with pytest.raises(DeadlineExceededError) as ei:
            numeric_factorize(plan, vals, a.norm_max(), dtype="float64",
                              executor="stream", deadline=dl)
        # the owner was NOT locally expired: the raise came from the
        # collective decision
        assert ei.value.expired_ranks == 1
        out, err = p.communicate(timeout=120)
        assert p.returncode == 0, (p.returncode, err)
        assert "OUTCOME deadline 1" in out, (out, err)
    finally:
        if p.poll() is None:                    # pragma: no cover
            p.kill()
        owner.close(unlink=True)


# ---------------------------------------------------------------------------
# simulated rank death: bounded abort instead of infinite hang
# ---------------------------------------------------------------------------

def _dying_rank(name, ready):
    from superlu_dist_tpu.testing.chaos import DyingTreeComm
    tc = DyingTreeComm(name, 2, 1, max_len=64, create=False,
                       die_after=2)
    ready.set()
    x = np.ones(4)
    tc.allreduce_sum_any(x)          # 1
    tc.allreduce_sum_any(x)          # 2
    tc.allreduce_sum_any(x)          # dies with RANK_DEATH_EXIT here
    os._exit(99)                     # unreachable


def _surviving_rank(name, q):
    from superlu_dist_tpu.parallel.treecomm import TreeComm
    from superlu_dist_tpu.testing.chaos import HangWatchdog
    tc = TreeComm(name, 2, 0, max_len=64, create=False)
    x = np.ones(4)
    with HangWatchdog(5.0):
        tc.allreduce_sum_any(x)      # 1
        tc.allreduce_sum_any(x)      # 2
        q.put("pre-hang")
        tc.allreduce_sum_any(x)      # peer is dead: hangs -> watchdog
    os._exit(0)                      # unreachable when the peer died


@needs_native
def test_rank_death_converts_hang_into_bounded_abort():
    """A rank dying mid-protocol (DyingTreeComm) leaves its peer hung in
    the abandoned collective — HangWatchdog bounds that hang: the
    survivor exits with the watchdog code within its budget instead of
    hanging forever."""
    from superlu_dist_tpu.parallel.treecomm import TreeComm
    from superlu_dist_tpu.testing.chaos import HANG_EXIT, RANK_DEATH_EXIT

    name = f"/slu_chaos_rd_{os.getpid()}"
    # the parent owns (and later unlinks) the segment; both workers
    # attach — the creator's constructor completes before any attacher
    # starts (the TreeComm rendezvous contract)
    seg = TreeComm(name, 2, 0, max_len=64, create=True)
    ctx = mp.get_context("fork")
    q = ctx.Queue()
    ready = ctx.Event()
    dier = ctx.Process(target=_dying_rank, args=(name, ready))
    dier.start()
    assert ready.wait(timeout=30)
    surv = ctx.Process(target=_surviving_rank, args=(name, q))
    surv.start()
    try:
        assert q.get(timeout=60) == "pre-hang"
        dier.join(timeout=60)
        surv.join(timeout=60)
        assert dier.exitcode == RANK_DEATH_EXIT
        assert surv.exitcode == HANG_EXIT
    finally:
        seg.close(unlink=True)


def test_hang_watchdog_disarm_keeps_process_alive():
    from superlu_dist_tpu.testing.chaos import HangWatchdog
    wd = HangWatchdog(0.05).arm()
    wd.disarm()
    time.sleep(0.15)        # were it still armed, os._exit would fire
    with HangWatchdog(0.05):
        pass
    time.sleep(0.15)
