#!/usr/bin/env python
"""Crash-resume gate: kill -9 a factorization mid-run, resume, compare.

The crash-consistency acceptance case (ISSUE 7 / docs/RELIABILITY.md):

  1. factor the gate matrix UNINTERRUPTED (streamed executor) — the
     reference L/U;
  2. run the same factorization in a subprocess with interval
     checkpointing armed (``SLU_TPU_CKPT_EVERY``) and the chaos
     injector (``SLU_TPU_CHAOS=kill_group=K``) SIGKILL-ing the process
     mid-factor — the kill -9 failure domain, nothing flushes at death;
  3. assert the child died by SIGKILL and left a durable frontier
     0 < k <= K+1 on disk;
  4. resume via ``numeric_factorize(resume_from=...)`` (plan
     fingerprint + value digest verified) and assert every supernode's
     L/U panel is BITWISE identical to the uninterrupted run
     (np.array_equal, no tolerance).

Exit 0 = pass.  One gate of scripts/ci_gates.sh; a few seconds on CPU.
Gate contract (shared with the other gates): any regression — a wrong
exit signal, a missing/invalid checkpoint, a bitwise mismatch — raises/
asserts, which exits non-zero with the diagnostic on stderr.
"""

import os
import shutil
import subprocess
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np  # noqa: E402

NX = 10          # n = 1000: enough dispatch groups to kill mid-run


def _problem():
    from superlu_dist_tpu.models.gallery import poisson3d
    from superlu_dist_tpu.ordering.dispatch import get_perm_c
    from superlu_dist_tpu.sparse.formats import symmetrize_pattern
    from superlu_dist_tpu.symbolic.symbfact import symbolic_factorize
    from superlu_dist_tpu.numeric.plan import build_plan
    from superlu_dist_tpu.utils.options import Options

    a = poisson3d(NX)
    sym = symmetrize_pattern(a)
    col_order = get_perm_c(Options(), a, sym)
    sf = symbolic_factorize(sym, col_order)
    plan = build_plan(sf)
    return plan, sym.data[sf.value_perm], a.norm_max()


def worker():
    """The victim: factor with checkpointing armed; the env-driven chaos
    injector SIGKILLs us mid-stream (we never reach the prints)."""
    from superlu_dist_tpu.numeric.factor import numeric_factorize
    from superlu_dist_tpu.utils.options import env_int, env_str

    plan, vals, anorm = _problem()
    numeric_factorize(plan, vals, anorm, dtype="float64",
                      executor="stream",
                      ckpt_dir=env_str("SLU_TPU_CKPT_DIR"),
                      ckpt_every=env_int("SLU_TPU_CKPT_EVERY"))
    print("worker: factorization completed (chaos kill did NOT fire)",
          file=sys.stderr)
    sys.exit(7)      # distinct code: the parent must see SIGKILL instead


def main():
    import jax
    jax.config.update("jax_enable_x64", True)
    from superlu_dist_tpu.numeric.factor import numeric_factorize
    from superlu_dist_tpu.persist.checkpoint import peek

    plan, vals, anorm = _problem()
    n_groups = len(plan.groups)
    assert n_groups >= 4, f"gate matrix too small ({n_groups} groups)"
    kill_group = n_groups // 2
    print(f"crash-resume gate: {n_groups} groups, SIGKILL after group "
          f"{kill_group}, checkpoint every 2")

    ref = numeric_factorize(plan, vals, anorm, dtype="float64",
                            executor="stream")

    ck_dir = tempfile.mkdtemp(prefix="slu_crash_resume_")
    try:
        env = dict(os.environ,
                   JAX_PLATFORMS="cpu", JAX_ENABLE_X64="1",
                   SLU_TPU_CHAOS=f"kill_group={kill_group}",
                   SLU_TPU_CKPT_DIR=ck_dir, SLU_TPU_CKPT_EVERY="2")
        r = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--worker"],
            env=env, cwd=REPO, capture_output=True, text=True,
            timeout=300)
        if r.returncode != -9:
            print(r.stdout, file=sys.stderr)
            print(r.stderr, file=sys.stderr)
        assert r.returncode == -9, (
            f"victim exited {r.returncode}, expected SIGKILL (-9) — the "
            "chaos kill_group injection did not fire")

        meta = peek(ck_dir)
        k = int(meta["k"])
        assert 0 < k <= kill_group + 1, (
            f"durable frontier k={k} inconsistent with a kill after "
            f"group {kill_group}")
        assert k < n_groups, "frontier covers the whole plan — no crash?"
        print(f"victim killed by SIGKILL; durable frontier k={k}")

        res = numeric_factorize(plan, vals, anorm, dtype="float64",
                                resume_from=ck_dir)
        assert res.resumed_groups == k, (
            f"resume restored {res.resumed_groups} groups, frontier "
            f"says {k}")
        mismatches = [
            g for g, ((rl, ru), (ll, lu_)) in enumerate(
                zip(ref.fronts, res.fronts))
            if not (np.array_equal(np.asarray(rl), np.asarray(ll))
                    and np.array_equal(np.asarray(ru), np.asarray(lu_)))]
        assert not mismatches, (
            f"resumed L/U differs bitwise from the uninterrupted run in "
            f"group(s) {mismatches[:8]}")
        assert res.tiny_pivots == ref.tiny_pivots, (
            f"tiny-pivot counts diverged: resumed {res.tiny_pivots} vs "
            f"uninterrupted {ref.tiny_pivots}")
        print(f"resume from k={k}: all {n_groups} groups bitwise "
              "identical to the uninterrupted run")
        print("crash-resume gate OK")
    finally:
        shutil.rmtree(ck_dir, ignore_errors=True)


if __name__ == "__main__":
    if "--worker" in sys.argv:
        worker()
    else:
        main()
