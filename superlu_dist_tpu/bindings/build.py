"""Build libslu_tpu.so (the C/Fortran binding shim, see slu_tpu.h).

Usage: python -m superlu_dist_tpu.bindings.build [outdir]
"""

from __future__ import annotations

import os
import subprocess
import sys
import sysconfig

_HERE = os.path.dirname(os.path.abspath(__file__))


def build(outdir: str | None = None) -> str:
    outdir = outdir or _HERE
    out = os.path.join(outdir, "libslu_tpu.so")
    src = os.path.join(_HERE, "slu_tpu_capi.c")
    if (os.path.exists(out)
            and os.path.getmtime(out) >= os.path.getmtime(src)):
        return out
    inc = sysconfig.get_path("include")
    libdir = sysconfig.get_config_var("LIBDIR")
    pyver = f"python{sys.version_info.major}.{sys.version_info.minor}"
    tmp = f"{out}.{os.getpid()}.tmp"
    subprocess.run(
        ["gcc", "-O2", "-shared", "-fPIC", f"-I{inc}", f"-I{_HERE}",
         "-o", tmp, src, f"-L{libdir}", f"-l{pyver}", "-ldl", "-lm",
         f"-Wl,-rpath,{libdir}"],
        check=True, capture_output=True)
    os.replace(tmp, out)
    return out


if __name__ == "__main__":
    print(build(sys.argv[1] if len(sys.argv) > 1 else None))
