"""SolveServer (serve/server.py): micro-batch coalescing, column
splitting, persist-loaded serving with zero refactorization, metrics
and trace visibility, shutdown semantics."""

import json
import threading

import numpy as np
import pytest

from superlu_dist_tpu.drivers.gssvx import gssvx
from superlu_dist_tpu.models.gallery import poisson2d
from superlu_dist_tpu.serve import ServerClosedError, SolveServer
from superlu_dist_tpu.utils.errors import SuperLUError
from superlu_dist_tpu.utils.options import Fact, IterRefine, Options

pytestmark = pytest.mark.serve


@pytest.fixture(scope="module")
def factored():
    a = poisson2d(10)
    rng = np.random.default_rng(0)
    xt = rng.standard_normal(a.n_rows)
    b = a.matvec(xt)
    x, lu, stats, info = gssvx(
        Options(iter_refine=IterRefine.NOREFINE), a, b)
    assert info == 0
    return a, lu, b, x


def test_coalescing_one_batch(factored):
    """A backlog submitted before the dispatcher starts lands in ONE
    device dispatch — the micro-batching contract."""
    a, lu, b, x = factored
    rng = np.random.default_rng(1)
    srv = SolveServer(lu, max_wait_s=0.05, start=False)
    rhss = [a.matvec(rng.standard_normal(a.n_rows)) for _ in range(5)]
    tickets = [srv.submit(r) for r in rhss]
    wide = srv.submit(np.stack([b, b], axis=1))
    assert srv.stats()["queue_depth"] == 7
    srv.start()
    for t, r in zip(tickets, rhss):
        got = t.result(60)
        res = np.linalg.norm(r - a.matvec(got)) / np.linalg.norm(r)
        assert res < 1e-10, res
    got_w = wide.result(60)
    assert got_w.shape == (a.n_rows, 2)
    np.testing.assert_allclose(got_w[:, 0], x, rtol=1e-8, atol=1e-10)
    st = srv.stats()
    assert st["requests"] == 6 and st["columns"] == 7
    assert st["batches"] == 1, st       # everything coalesced
    assert st["mean_batch_columns"] == 7.0
    srv.close()


def test_wide_request_splits_across_batches(factored):
    """A request wider than the batch cap drains over several
    dispatches and reassembles in column order."""
    a, lu, b, x = factored
    n = a.n_rows
    rng = np.random.default_rng(2)
    xs = rng.standard_normal((n, 20))
    bs = np.stack([a.matvec(xs[:, j]) for j in range(20)], axis=1)
    srv = SolveServer(lu, max_batch=8, max_wait_s=0.0)
    got = srv.solve(bs, timeout=120)
    srv.close()
    np.testing.assert_allclose(got, xs, rtol=1e-8, atol=1e-10)
    assert srv.stats()["batches"] >= 3   # ceil(20 / 8)


def test_concurrent_submitters(factored):
    a, lu, b, x = factored
    rng = np.random.default_rng(3)
    srv = SolveServer(lu, max_wait_s=0.01)
    errs = []

    def worker(seed):
        try:
            r = np.random.default_rng(seed).standard_normal(a.n_rows)
            rhs = a.matvec(r)
            got = srv.solve(rhs, timeout=120)
            np.testing.assert_allclose(got, r, rtol=1e-7, atol=1e-9)
        except Exception as e:              # noqa: BLE001
            errs.append(e)

    ts = [threading.Thread(target=worker, args=(s,)) for s in range(8)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(120)
    srv.close()
    assert not errs, errs
    assert srv.stats()["requests"] == 8


def test_from_bundle_serves_without_refactorization(factored, tmp_path):
    """The persist-loaded handle serves immediately: FACT time stays
    0.0 through a FACTORED driver solve, and the server's own solves
    run on the loaded factors as-is."""
    from superlu_dist_tpu.persist.serial import lu_meta, save_lu
    a, lu, b, x = factored
    d = str(tmp_path / "handle")
    save_lu(lu, d)
    meta = lu_meta(d)
    assert meta["n"] == a.n_rows and meta["n_groups"] > 0
    srv = SolveServer.from_bundle(d, max_wait_s=0.0)
    assert srv.source == d
    got = srv.solve(b, timeout=60)
    np.testing.assert_allclose(got, x, rtol=1e-8, atol=1e-10)
    srv.close()
    # the FACTORED tier through the driver proves zero refactorization
    from superlu_dist_tpu.persist.serial import load_lu
    from superlu_dist_tpu.utils.stats import Stats
    lu2 = load_lu(d)
    lu2.a = a
    stats = Stats()
    x2, lu2, stats, info = gssvx(
        Options(fact=Fact.FACTORED, iter_refine=IterRefine.NOREFINE),
        a, b, lu=lu2, stats=stats)
    assert info == 0
    assert stats.utime.get("FACT", 0.0) == 0.0
    np.testing.assert_allclose(x2, x, rtol=1e-8, atol=1e-10)


def test_metrics_and_trace_rows(factored, tmp_path):
    """Serving emits the scrapeable series and a serve-batch dispatch
    span wrapping the solve."""
    from superlu_dist_tpu.obs import metrics as metrics_mod
    from superlu_dist_tpu.obs import trace
    a, lu, b, x = factored
    m = metrics_mod.Metrics()
    prev_m = metrics_mod.install(m)
    path = str(tmp_path / "serve_trace.json")
    t = trace.Tracer(path)
    prev_t = trace.install(t)
    try:
        srv = SolveServer(lu, max_wait_s=0.0)
        srv.solve(b, timeout=60)
        srv.solve(np.stack([b, b, b], axis=1), timeout=60)
        srv.close()
    finally:
        trace.install(prev_t)
        metrics_mod.install(prev_m)
        t.close()
    snap = m.snapshot()
    assert snap["counters"].get("slu_serve_requests_total") == 2.0
    assert snap["counters"].get("slu_serve_columns_total") == 4.0
    assert snap["counters"].get("slu_serve_batches_total") == 2.0
    assert snap["gauges"].get("slu_serve_queue_depth") == 0.0
    hist = snap["histograms"].get("slu_serve_request_seconds")
    assert hist and hist["count"] == 2
    fill = snap["histograms"].get("slu_serve_batch_fill")
    assert fill and fill["count"] == 2
    rows = json.load(open(path))
    events = rows["traceEvents"] if isinstance(rows, dict) else rows
    serve_spans = [e for e in events
                   if e.get("name") == "serve-batch"]
    assert len(serve_spans) == 2
    assert all(e.get("cat") == "dispatch" for e in serve_spans)
    assert {e["args"]["columns"] for e in serve_spans} == {1, 3}


def test_submit_validation_and_close(factored):
    a, lu, b, x = factored
    srv = SolveServer(lu, max_wait_s=0.0)
    with pytest.raises(SuperLUError):
        srv.submit(np.ones(a.n_rows + 1))
    with pytest.raises(SuperLUError):
        srv.submit(np.ones((a.n_rows, 0)))
    srv.close()
    with pytest.raises(ServerClosedError):
        srv.submit(b)
    # unfactored handle refused up front
    import dataclasses
    with pytest.raises(SuperLUError):
        SolveServer(dataclasses.replace(lu, numeric=None))


def test_batch_error_reaches_every_ticket(factored):
    a, lu, b, x = factored
    srv = SolveServer(lu, max_wait_s=0.05, start=False)

    def boom(mat):
        raise RuntimeError("injected solve failure")

    srv._solve = boom
    t1, t2 = srv.submit(b), srv.submit(b)
    srv.start()
    for t in (t1, t2):
        with pytest.raises(RuntimeError, match="injected"):
            t.result(60)
    assert srv.stats()["errors"] >= 1
    srv.close()


def test_transpose_server(factored):
    a, lu, b, x = factored
    r = np.random.default_rng(7).standard_normal(a.n_rows)
    bt = a.transpose().matvec(r)
    srv = SolveServer(lu, trans=True, max_wait_s=0.0)
    got = srv.solve(bt, timeout=60)
    srv.close()
    res = (np.linalg.norm(bt - a.transpose().matvec(got))
           / np.linalg.norm(bt))
    assert res < 1e-9, res


def test_requested_nrhs_is_unpadded_in_results(factored):
    """Padding is internal: a 5-column request returns exactly 5
    columns, while the device solve underneath buckets to 8 (visible in
    its padding telemetry when the device path runs)."""
    a, lu, b, x = factored
    bs = np.stack([b] * 5, axis=1)
    srv = SolveServer(lu, max_wait_s=0.0)
    got = srv.solve(bs, timeout=60)
    srv.close()
    assert got.shape == (a.n_rows, 5)
    for j in range(5):
        np.testing.assert_allclose(got[:, j], x, rtol=1e-8, atol=1e-10)
