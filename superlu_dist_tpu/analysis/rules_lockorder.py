"""SLU109 — lock-order and hold-discipline.

Two families of finding over the package-wide lock-acquisition graph
(analysis/concurrency.py — nodes are class-qualified lock identities,
edges ``A -> B`` mean "B acquired while holding A", directly or through
a resolved call chain):

* **ordering** — a cycle in the graph is a potential deadlock: two
  threads entering the cycle from different ends block forever.  Each
  edge of the cycle is reported at its acquisition site, naming the
  witness for the inverse order.  Lexical re-acquisition of the SAME
  (non-reentrant) lock inside its own ``with`` is the degenerate cycle
  and flagged too.
* **blocking-while-holding** — operations with unbounded or external
  latency inside a held lock stall every contending thread and, when
  the blocked-on party needs the same lock, deadlock outright.  Flagged
  inside a ``with <lock>:`` body: TreeComm collectives (direct or
  call-graph-reachable — the other ranks may be blocked on THIS rank's
  lock holder), ``.block_until_ready()`` (jit dispatch), no-timeout
  ``Condition``/``Event`` ``.wait()``, no-timeout ``Thread.join()``,
  ``time.sleep``, and file I/O (a direct ``open`` or a call whose
  callee chain reaches one — the exact shape of the PR 10 close-storm
  bug).

The runtime twin is ``utils/lockwatch.py`` (``SLU_TPU_VERIFY_LOCKS=1``):
the same order graph maintained on live acquisitions, raising
``LockOrderError`` at the first cycle — SLU106's mold, for locks.
"""

from __future__ import annotations

import ast

from superlu_dist_tpu.analysis.concurrency import get_model
from superlu_dist_tpu.analysis.core import Finding, Rule
from superlu_dist_tpu.analysis.dataflow import (COLLECTIVE_METHODS,
                                                _blocking_candidate)

#: blocking kinds that propagate through the call graph (file I/O hides
#: behind helpers routinely; the interactive kinds are flagged only
#:  where they are spelled — false-negative-leaning)
_TRANSITIVE_KINDS = ("open",)


def _reaches_blocking(model):
    """qname -> (kind, witness-site, owner) fixpoint for the transitive
    blocking kinds, cached on the model."""
    cached = getattr(model, "_reaches_blocking", None)
    if cached is not None:
        return cached
    proj = model.proj
    out = {}
    for q, s in proj.summaries.items():
        for kind, recv, line in s.blocking_raw:
            if kind in _TRANSITIVE_KINDS:
                fi = proj.functions[q]
                out[q] = (kind, f"{fi.path}:{line}", q)
                break
    changed = True
    while changed:
        changed = False
        for q, fi in proj.functions.items():
            if q in out:
                continue
            for callee in fi.calls:
                hit = out.get(model._callable_fn(callee))
                if hit is not None:
                    out[q] = hit
                    changed = True
                    break
    model._reaches_blocking = out
    return out


class LockOrderRule(Rule):
    rule_id = "SLU109"
    title = "lock-order + hold-discipline"
    hint = ("acquire locks in one global order (document it where the "
            "locks are created), and move blocking work — collectives, "
            "jit dispatch, unbounded waits, file I/O — outside the "
            "`with` block: snapshot state under the lock, block outside")

    def check(self, tree, source, path, project=None):
        if project is None:
            return []
        model = get_model(project)
        out = []
        out.extend(self._cycle_findings(model, path))
        out.extend(self._hold_findings(model, path))
        return out

    # ---- ordering ------------------------------------------------------
    def _cycle_findings(self, model, path):
        out = []
        for cyc in model.cycles():
            for i, (a, b, site, via) in enumerate(cyc):
                fpath, _, line = site.rpartition(":")
                if fpath != path:
                    continue
                others = "; ".join(
                    f"`{b2}` -> `{a2}` at {s2}" for j, (b2, a2, s2, _)
                    in enumerate(cyc) if j != i) or "inverse order"
                out.append(Finding(
                    self.rule_id, path, int(line), 1,
                    f"lock-order inversion: `{b}` acquired while "
                    f"holding `{a}` ({via}), but the inverse order "
                    f"exists — {others} — two threads entering from "
                    "different ends deadlock",
                    self.hint))
        return out

    # ---- hold discipline -----------------------------------------------
    def _hold_findings(self, model, path):
        proj = model.proj
        reaches = _reaches_blocking(model)
        out = []
        for q, fi in proj.functions.items():
            if fi.path != path:
                continue
            cm = model.class_for(fi)
            for node, held in model._held_spans(cm, fi):
                if isinstance(node, (ast.With, ast.AsyncWith)) and held:
                    for item in node.items:
                        lid = model._lock_identity(cm, fi,
                                                   item.context_expr)
                        if lid is not None and lid in held:
                            out.append(Finding(
                                self.rule_id, path, node.lineno,
                                node.col_offset + 1,
                                f"re-acquisition of non-reentrant lock "
                                f"`{lid}` inside its own `with` — "
                                "guaranteed self-deadlock",
                                self.hint))
                if not held or not isinstance(node, ast.Call):
                    continue
                desc = self._blocking_desc(model, cm, fi, node, reaches)
                if desc is None:
                    continue
                out.append(Finding(
                    self.rule_id, path, node.lineno,
                    node.col_offset + 1,
                    f"{desc} while holding `{held[-1]}` — blocks every "
                    "thread contending for the lock (deadlock when the "
                    "blocked-on party needs it)",
                    self.hint))
        return out

    def _blocking_desc(self, model, cm, fi, node, reaches):
        fn = node.func
        # collectives: direct or call-graph-reachable
        if isinstance(fn, ast.Attribute) and fn.attr in COLLECTIVE_METHODS:
            return f"TreeComm collective `{fn.attr}`"
        target = model.proj.call_target(fi.path, node)
        if target:
            tq = model._callable_fn(target)
            s = model.proj.summaries.get(tq)
            if s is not None and s.reaches_collective is not None:
                owner, witness = s.reaches_collective
                return (f"call to `{tq.rsplit('.', 1)[-1]}` reaching "
                        f"collective `{witness}`")
            hit = reaches.get(tq)
            if hit is not None:
                kind, site, owner = hit
                return (f"call to `{tq.rsplit('.', 1)[-1]}` reaching "
                        f"file I/O (`{kind}` at {site})")
        cand = _blocking_candidate(node)
        if cand is None:
            return None
        kind, recv, _ = cand
        if kind == "open":
            return "file I/O (`open`)"
        if kind == "block_until_ready":
            return "jit dispatch sync (`.block_until_ready()`)"
        if kind == "sleep":
            return "`time.sleep`"
        # wait/join: only when the receiver is a known sync/thread attr
        # of this class (arbitrary .wait()/.join() receivers are opaque)
        if cm is not None and isinstance(fn.value, ast.Attribute) \
                and isinstance(fn.value.value, ast.Name) \
                and fn.value.value.id == "self":
            attr = fn.value.attr
            if kind == "wait" and (attr in cm.event_attrs
                                   or cm.lock_attrs.get(attr) == "cond"):
                return f"unbounded `self.{attr}.wait()` (no timeout)"
            if kind == "join" and attr in cm.thread_attrs:
                return f"unbounded `self.{attr}.join()` (no timeout)"
        return None
