#!/usr/bin/env python
"""BASELINE config-4 class (3D Poisson, target n=1M) executed end-to-end
on the CPU backend at full problem size.

The point is EXECUTION at scale, not speed: n=1M's ~22 GB pool exceeds
one v5e chip's HBM, so the single-tunneled-chip environment cannot run
it.  Two modes (CONFIG4_MESH):

- "1" (default): single-device execution — the fastest path to a
  numeric-at-n=1M artifact (the pool partition is separately proven
  bit-equal at n=102,400, tests/test_pool_partition.py).  Artifact:
  docs/config4_virtual_n{n}_1dev.json.
- "RxC" (e.g. "4x2"): partitioned Schur pool over the R*C-device
  virtual mesh — the real multi-chip recipe (pool_partition +
  host-offloaded fronts); proves the sharded program compiles AND
  executes with the per-device pool share genuinely smaller than the
  whole (the no-rank-holds-the-whole-factor property, reference
  SRC/pddistribute.c:322).  On this 1-core box the collectives are
  hours of memcpy at n=1M.  Artifact: docs/config4_virtual_n{n}.json.

Env: CONFIG4_NX (default 100 -> n=1e6), CONFIG4_MESH (default "1"),
CONFIG4_DTYPE (default float32; a complex dtype, e.g. complex64, runs
the z-twin class — off-diagonals rotated into the complex plane — and
suffixes the artifact with the canonical dtype name, e.g.
docs/config4_virtual_n{n}_complex64_1dev.json).
"""

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from _common import (REPO, cpu_session, parse_mesh_spec,  # noqa: E402
                     raise_collective_timeouts)


def main():
    raise_collective_timeouts()
    # parse + validate the mesh spec BEFORE anything expensive (and
    # before the device count is pinned)
    mesh_spec = os.environ.get("CONFIG4_MESH", "1")
    mesh_r, mesh_c, n_dev = parse_mesh_spec(mesh_spec)
    # x64: n=1M's Schur pool exceeds 2^31 entries — flat pool indices
    # need int64 (the reference's XSDK_INDEX_SIZE=64 build,
    # superlu_defs.h:85-88)
    jax = cpu_session(n_devices=n_dev)
    import jax.numpy as jnp

    from superlu_dist_tpu.models.gallery import poisson3d
    from superlu_dist_tpu.sparse.formats import symmetrize_pattern
    from superlu_dist_tpu.utils.options import Options
    from superlu_dist_tpu.ordering.dispatch import get_perm_c
    from superlu_dist_tpu.symbolic.symbfact import symbolic_factorize
    from superlu_dist_tpu.numeric.plan import build_plan
    from superlu_dist_tpu.numeric.stream import StreamExecutor
    from superlu_dist_tpu.numeric.factor import NumericFactorization
    from superlu_dist_tpu.drivers.gssvx import LUFactorization
    from superlu_dist_tpu.refine.ir import iterative_refinement
    from superlu_dist_tpu.parallel.grid import gridinit

    nx = int(os.environ.get("CONFIG4_NX", "100"))
    dtype = os.environ.get("CONFIG4_DTYPE", "float32")
    t_all = time.perf_counter()

    def log(msg):
        print(f"[config4 +{time.perf_counter() - t_all:8.1f}s] {msg}",
              file=sys.stderr, flush=True)

    a = poisson3d(nx)
    jdt = np.dtype(dtype)
    if np.issubdtype(jdt, np.complexfloating):
        # complex variant (the z-twin class, reference pzgstrf.c): rotate
        # the off-diagonals into the complex plane — non-Hermitian, same
        # pattern, still diagonally dominant
        from superlu_dist_tpu.sparse.formats import SparseCSR
        cdata = a.data.astype(np.complex128)
        off = a.indices != np.repeat(np.arange(a.n_rows),
                                     np.diff(a.indptr))
        cdata[off] *= (0.8 + 0.6j)
        a = SparseCSR(a.n_rows, a.n_cols, a.indptr, a.indices, cdata)
    n = a.n_rows
    log(f"matrix n={n} nnz={a.nnz} dtype={dtype}")

    t0 = time.perf_counter()
    sym = symmetrize_pattern(a)
    col_order = get_perm_c(Options(), a, sym)
    sf = symbolic_factorize(sym, col_order, relax=256, max_supernode=1024,
                            amalg_tol=1.2)
    plan = build_plan(sf, min_bucket=32, growth=1.3)
    t_analyze = time.perf_counter() - t0
    # complex MACs are ~4 real flops (reference z-routines count 6+2 per
    # mult+add); one real-equivalent figure feeds both the log and the
    # artifact so they cannot diverge
    flops_req = plan.flops * (4.0 if np.issubdtype(
        jdt, np.complexfloating) else 1.0)
    log(f"analysis {t_analyze:.1f}s; groups={len(plan.groups)} "
        f"pool={plan.pool_size * jdt.itemsize / 1e9:.1f} GB({dtype}) "
        f"flops={flops_req / 1e12:.2f} TF (real-equivalent)")

    if mesh_spec == "1":
        grid = None
        share = plan.pool_size
        ex = StreamExecutor(plan, dtype, offload="none")
    else:
        grid = gridinit(mesh_r, mesh_c)
        share = -(-plan.pool_size // grid.mesh.size)
        assert share < plan.pool_size, "pool must exceed one device share"
        ex = StreamExecutor(plan, dtype, mesh=grid.mesh,
                            pool_partition=True, offload="host")
    avals = np.asarray(sym.data[sf.value_perm], dtype=jdt)
    real_dt = np.finfo(jdt).dtype          # f32 for c64, identity for real
    eps = float(np.finfo(real_dt).eps)
    thresh = np.asarray(np.sqrt(eps) * a.norm_max(), real_dt)

    t0 = time.perf_counter()
    fronts, tiny = ex(jnp.asarray(avals), jnp.asarray(thresh))
    jax.block_until_ready(
        [lp for lp, _ in fronts if not isinstance(lp, np.ndarray)])
    t_factor = time.perf_counter() - t0
    log(f"factor (incl. compile) {t_factor:.1f}s  tiny={int(tiny)}")

    numeric = NumericFactorization(plan=plan, fronts=list(fronts),
                                   tiny_pivots=int(tiny),
                                   dtype=jnp.dtype(dtype))
    ones = np.ones(n)
    ident = np.arange(n, dtype=np.int64)
    lu = LUFactorization(n=n, options=Options(), equed="N", dr=ones,
                         dc=ones, r1=ones, c1=ones, row_order=ident,
                         col_order=None, sf=sf, plan=plan,
                         numeric=numeric, a=a)
    xt = np.random.default_rng(0).standard_normal(n)
    b = a.matvec(xt)
    t0 = time.perf_counter()
    x, steps = iterative_refinement(a, b, lu.solve_factored(b),
                                    lu.solve_factored)
    t_solve = time.perf_counter() - t0
    resid = float(np.linalg.norm(b - a.matvec(x))
                  / max(np.linalg.norm(b), 1e-300))
    log(f"solve+IR {t_solve:.1f}s  residual {resid:.2e}")

    rec = {"config": "4-virtual", "matrix": f"poisson3d nx={nx}", "n": n,
           "mesh": (f"{mesh_spec} virtual-cpu" if grid is not None
                    else "single-device cpu"),
           "pool_partition": grid is not None,
           "pool_bytes_total": plan.pool_size * jdt.itemsize,
           "pool_share_per_device": int(share) * jdt.itemsize,
           "dtype": jdt.name,
           "flops": flops_req,
           "analyze_seconds": round(t_analyze, 1),
           "factor_seconds_incl_compile": round(t_factor, 1),
           "solve_ir_seconds": round(t_solve, 1),
           "residual": resid, "tiny_pivots": int(tiny),
           "backend": ("cpu-virtual-mesh" if grid is not None
                       else "cpu-single-device"),
           "note": ("execution-at-scale artifact: single-core host, "
                    "timing not a perf claim"
                    + ("; the same sharded program runs on a real "
                       "multi-chip mesh" if grid is not None else ""))}
    # the unsuffixed path is reserved for the partitioned-mesh artifact
    # (the stronger claim); single-device runs carry the _1dev suffix
    suffix = "_1dev" if grid is None else ""
    if jdt != np.dtype(np.float32):
        suffix = f"_{jdt.name}" + suffix
    out = os.path.join(REPO, "docs", f"config4_virtual_n{n}{suffix}.json")
    with open(out, "w") as f:
        json.dump(rec, f, indent=1)
    print(json.dumps(rec), flush=True)
    from superlu_dist_tpu.utils import tols
    assert resid < tols.RESID_GATE_TIGHT, resid


if __name__ == "__main__":
    main()
