#!/usr/bin/env python
"""Multi-process distributed-factors driver — the reference's canonical
`mpiexec -n 2 pddrive -r 1 -c 2 g20.rua` flow (EXAMPLE/pddrive.c:29):
every process owns a block of rows of A and b, the factorization and
solves run SPMD over the mesh spanning all the processes' devices, and
no process ever holds the whole factor (SRC/pddistribute.c:322).

This launcher forks the worker below once per rank (the mpiexec role);
each worker boots via parallel.mhboot (jax.distributed world + Gloo
timeout + compile cache), attaches the shared-memory tree domain for
the host-side analysis collectives, and calls `pgssvx(..., grid=...)`.

    python examples/pddrive_grid.py [matrix.rua] [--nproc 2]
"""

import glob
import os
import socket
import subprocess
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

_WORKER = r"""
import sys
pid = int(sys.argv[1]); nproc = int(sys.argv[2]); port = sys.argv[3]
shm = sys.argv[4]; path = sys.argv[5]
from superlu_dist_tpu.parallel.mhboot import boot, attach_tree
boot(nproc, pid, port)
import numpy as np
from superlu_dist_tpu.parallel.grid import gridinit_multihost
from superlu_dist_tpu.parallel.dist import distribute_rows
from superlu_dist_tpu.parallel.pgssvx import pgssvx
from superlu_dist_tpu.utils.options import Options

grid = gridinit_multihost(1, nproc)
if path == "@poisson2d":
    from superlu_dist_tpu.models.gallery import poisson2d
    a = poisson2d(20)
else:
    from superlu_dist_tpu.io import read_matrix
    a = read_matrix(path).tocsr()
n = a.n_rows
tc = attach_tree(shm, nproc, pid, max_len=1 << 16)

# this rank's block rows only (the NR_loc shape)
parts = distribute_rows(a, nproc)
mine = parts[pid]
xt = np.random.default_rng(0).standard_normal(n)
b = a.matvec(xt)
out = {}
x, info = pgssvx(tc, Options(), mine,
                 b[mine.fst_row:mine.fst_row + mine.m_loc],
                 grid=grid, lu_out=out)
assert info == 0, info
resid = float(np.linalg.norm(b - a.matvec(x)) / np.linalg.norm(b))
big_lp, _ = max(out["lu"].numeric.fronts, key=lambda p: p[0].size)
assert len(big_lp.sharding.device_set) == nproc    # factors span ranks
tc.close(unlink=pid == 0)
print(f"rank {pid}: residual {resid:.2e}; largest front sharded over "
      f"{len(big_lp.sharding.device_set)} process devices", flush=True)
assert resid < 1e-10, resid
"""

_REF_FIXTURE = "/root/reference/EXAMPLE/g20.rua"


def main():
    import argparse
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("matrix", nargs="?", default=None,
                    help="matrix file (HB/RB/MM); defaults to the "
                         "reference g20.rua fixture, else @poisson2d")
    ap.add_argument("--nproc", type=int, default=2)
    ap.add_argument("--backend", default=None,
                    help="accepted for _common.py symmetry; unused here")
    ns = ap.parse_args()          # rejects unknown --flags, supports '='
    nproc = ns.nproc
    if ns.matrix:
        path = ns.matrix
    elif os.path.exists(_REF_FIXTURE):
        path = _REF_FIXTURE
    else:
        path = "@poisson2d"        # generated fallback: always runs
    with socket.socket() as s:
        s.bind(("localhost", 0))
        port = s.getsockname()[1]
    import tempfile
    shm = f"/slu_exgrid_{os.getpid()}"
    rc = 0
    with tempfile.TemporaryDirectory() as td:
        wf = os.path.join(td, "worker.py")
        with open(wf, "w") as fh:
            fh.write(_WORKER)
        env = dict(os.environ, PYTHONPATH=os.path.join(
            os.path.dirname(os.path.abspath(__file__)), ".."))
        env.pop("XLA_FLAGS", None)
        procs = [subprocess.Popen(
            [sys.executable, wf, str(i), str(nproc), str(port), shm, path],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
            for i in range(nproc)]
        try:
            for i, p in enumerate(procs):
                # stay under CI's outer 600 s budget so a wedged rank is
                # reaped HERE (no orphaned grandchildren holding the shm)
                out, _ = p.communicate(timeout=480)
                txt = out.decode()
                print(txt.strip().splitlines()[-1] if txt.strip() else
                      f"rank {i}: (no output)")
                rc |= p.returncode
        finally:
            for p in procs:
                if p.poll() is None:
                    p.kill()
            for leftover in glob.glob(f"/dev/shm/*{shm.strip('/')}*"):
                try:
                    os.unlink(leftover)
                except OSError:
                    pass
    assert rc == 0, "a rank failed"
    print("pddrive_grid OK")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
