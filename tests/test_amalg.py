"""Fill-tolerant supernode amalgamation (symbfact.amalgamate_supernodes).

The TPU-first relaxation beyond the reference's leaf-only relax_snode
(SRC/symbfact.c:224): merged supernodes trade bounded extra fill for the
wide pivot panels the MXU needs.  These tests pin (a) structural
invariants of the merged partition, (b) end-to-end numerical equivalence
with the unamalgamated path, and (c) that the merge actually coarsens the
schedule (fewer supernodes/levels) within the flop tolerance.
"""

import numpy as np
import pytest

from superlu_dist_tpu.models.gallery import poisson2d, poisson3d, random_sparse
from superlu_dist_tpu.sparse.formats import symmetrize_pattern
from superlu_dist_tpu.symbolic.symbfact import (
    symbolic_factorize, amalgamate_supernodes)
from superlu_dist_tpu.utils.options import Options


def _structure_ok(sf):
    ns = sf.n_supernodes
    assert sf.sn_start[0] == 0 and sf.sn_start[-1] == sf.n
    assert np.all(np.diff(sf.sn_start) > 0)
    for s in range(ns):
        last = sf.sn_start[s + 1] - 1
        rows = sf.sn_rows[s]
        assert np.all(np.diff(rows) > 0)          # sorted, unique
        if len(rows):
            assert rows[0] > last                  # strictly below-diagonal
            p = sf.sn_parent[s]
            assert p > s                           # parents execute later
            assert sf.col_to_sn[rows[0]] == p      # parent owns first row
        else:
            assert sf.sn_parent[s] == -1
        p = sf.sn_parent[s]
        if p >= 0:
            assert sf.sn_level[p] > sf.sn_level[s]


@pytest.mark.parametrize("mk", [lambda: poisson2d(24),
                                lambda: poisson3d(8),
                                lambda: random_sparse(300, density=0.03,
                                                      seed=3)])
def test_amalg_structure_invariants(mk):
    sym = symmetrize_pattern(mk())
    n = sym.n_rows
    sf0 = symbolic_factorize(sym, np.arange(n), relax=4, max_supernode=64,
                             amalg_tol=0)
    sf = amalgamate_supernodes(sf0, tol=1.3, max_width=128)
    _structure_ok(sf)
    assert sf.n_supernodes <= sf0.n_supernodes
    # fill only grows, and column coverage is exact
    assert sf.nnz_L >= sf0.nnz_L


def test_amalg_coarsens_schedule():
    """3D mesh problems are where unamalgamated supernodes degenerate
    (median width 1); the merge must collapse both count and depth."""
    sym = symmetrize_pattern(poisson3d(12))
    n = sym.n_rows
    sf0 = symbolic_factorize(sym, np.arange(n), relax=1, max_supernode=256,
                             amalg_tol=0)
    sf = amalgamate_supernodes(sf0, tol=1.2, max_width=256)
    assert sf.n_supernodes < 0.3 * sf0.n_supernodes
    assert sf.sn_level.max() < 0.5 * sf0.sn_level.max()
    widths = np.diff(sf.sn_start)
    assert np.median(widths) > np.median(np.diff(sf0.sn_start))


@pytest.mark.slow
def test_amalg_solve_matches_unamalgamated():
    """Same solution through merged fronts (explicit zeros are factored
    like any entry; GESP semantics unchanged)."""
    from superlu_dist_tpu.drivers.gssvx import gssvx
    rng = np.random.default_rng(7)
    a = poisson2d(20)
    n = a.n_rows
    b = rng.standard_normal((n,))
    x0, lu0, st0, info0 = gssvx(Options(amalg_tol=0.0), a, b)
    x1, lu1, st1, info1 = gssvx(Options(amalg_tol=1.4), a, b)
    assert info0 == 0 and info1 == 0
    r0 = np.linalg.norm(b - a.matvec(x0)) / np.linalg.norm(b)
    r1 = np.linalg.norm(b - a.matvec(x1)) / np.linalg.norm(b)
    assert r0 <= 1e-10 and r1 <= 1e-10
    np.testing.assert_allclose(x1, x0, rtol=1e-8, atol=1e-10)
    assert lu1.sf.n_supernodes <= lu0.sf.n_supernodes


def test_amalg_respects_flop_tolerance():
    sym = symmetrize_pattern(poisson3d(10))
    n = sym.n_rows
    sf0 = symbolic_factorize(sym, np.arange(n), relax=1, max_supernode=512,
                             amalg_tol=0)
    for tol in (1.05, 1.2, 1.5):
        sf = amalgamate_supernodes(sf0, tol=tol, max_width=512)
        # every merge is tested against its constituents' ORIGINAL flops,
        # so the aggregate is bounded by max(tol, hard_tol=4 inside the
        # narrow-width escape) times the input structure
        assert sf.flops <= 4.0 * sf0.flops
    # monotone-ish: a tighter tolerance never produces more flops
    f_tight = amalgamate_supernodes(sf0, tol=1.05, max_width=512).flops
    f_loose = amalgamate_supernodes(sf0, tol=1.5, max_width=512).flops
    assert f_tight <= f_loose * 1.01


def test_amalg_max_width_cap():
    sym = symmetrize_pattern(poisson2d(30))
    n = sym.n_rows
    sf0 = symbolic_factorize(sym, np.arange(n), relax=4, max_supernode=64,
                             amalg_tol=0)
    sf = amalgamate_supernodes(sf0, tol=2.0, max_width=48)
    assert np.diff(sf.sn_start).max() <= 48


def test_amalg_native_matches_python(monkeypatch):
    """The native slu_amalgamate must reproduce the Python amalgamation
    exactly (same greedy order, same budget test) — the same parity
    discipline as the native symbolic."""
    from superlu_dist_tpu import native
    if not native.available():
        pytest.skip("native library unavailable")
    sym = symmetrize_pattern(poisson3d(10))
    n = sym.n_rows
    sf0 = symbolic_factorize(sym, np.arange(n), relax=8, max_supernode=256,
                             amalg_tol=0)
    sf_nat = amalgamate_supernodes(sf0, tol=1.3, max_width=256)
    monkeypatch.setenv("SLU_TPU_NO_NATIVE", "1")
    native._tried, native._lib = False, None
    try:
        sf_py = amalgamate_supernodes(sf0, tol=1.3, max_width=256)
    finally:
        monkeypatch.delenv("SLU_TPU_NO_NATIVE")
        native._tried, native._lib = False, None
    assert np.array_equal(sf_nat.sn_start, sf_py.sn_start)
    assert np.array_equal(sf_nat.sn_parent, sf_py.sn_parent)
    assert np.array_equal(sf_nat.sn_level, sf_py.sn_level)
    assert np.array_equal(sf_nat.col_to_sn, sf_py.col_to_sn)
    for rn, rp in zip(sf_nat.sn_rows, sf_py.sn_rows):
        assert np.array_equal(rn, rp)
    assert sf_nat.flops == sf_py.flops
