"""Factorization plan: mapping supernodes onto level-batched padded fronts.

This is the TPU-native analog of the reference's *distribution* phase
(pddistribute, SRC/pddistribute.c:322): where the reference builds
dLocalLU_t index structures plus MPI send/recv schedules, we precompute —
entirely on the host, once per sparsity pattern — the flat gather/scatter
index maps that let the whole numeric factorization run as a short sequence
of XLA ops per (level, bucket) group:

  assemble:   F[slot, pos] += A_vals[a_src]          (original entries)
              F[slot, pos] += pool[e_src]            (children's Schur pieces,
                                                      the extend-add /
                                                      dscatter.c:111 analog)
  factor:     batched partial LU (ops.dense)         (the pdgstrf hot loop)
  write-back: pool[s_dst] = F[slot, s_src]           (Schur to update pool)

Fronts are square (symmetrized pattern): index set = supernode columns +
below-diagonal rows, padded to bucket sizes (W for the pivot block, M
total) so every group is one static-shape vmapped kernel.  The reference's
GEMM aggregation-and-padding trick (dSchCompUdt-2Ddynamic.c:212-237) is the
same idea at single-GEMM granularity; here it covers the entire level.

Like the reference's SamePattern path, a plan is reusable across numeric
refactorizations with the same sparsity pattern.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from superlu_dist_tpu.sparse.formats import SparseCSR
from superlu_dist_tpu.symbolic.symbfact import SymbolicFact


@dataclasses.dataclass
class Group:
    """One (level, bucket) batch of fronts."""

    level: int
    m: int                  # padded front size
    w: int                  # padded pivot width
    batch: int              # number of real fronts
    sns: np.ndarray         # supernode ids, slot order
    # assembly of original matrix entries
    a_slot: np.ndarray
    a_flat: np.ndarray
    a_src: np.ndarray
    # identity padding for unused pivot columns
    pad_slot: np.ndarray
    pad_flat: np.ndarray
    # extend-add gathers from the update pool
    e_slot: np.ndarray
    e_flat: np.ndarray
    e_src: np.ndarray
    # Schur write-back into the update pool
    s_slot: np.ndarray
    s_src_flat: np.ndarray
    s_dst: np.ndarray


@dataclasses.dataclass
class FactorPlan:
    n: int
    sf: SymbolicFact
    pattern_indptr: np.ndarray     # permuted symmetrized pattern (CSR)
    pattern_indices: np.ndarray
    groups: list                   # Groups in level-ascending order
    pool_size: int
    sn_group: np.ndarray           # (ns,) group index of each supernode
    sn_slot: np.ndarray            # (ns,) slot within its group
    flops: float
    front_bytes: int               # total padded front storage (per dtype unit)

    @property
    def n_levels(self) -> int:
        return int(self.sf.sn_level.max()) + 1 if len(self.sf.sn_level) else 0


def _bucket_sizes(max_needed: int, min_bucket: int, growth: float):
    sizes = []
    s = min_bucket
    while s < max_needed:
        sizes.append(s)
        s = max(s + 8, int(np.ceil(s * growth / 8.0) * 8))
    sizes.append(int(np.ceil(max_needed / 8.0) * 8) if max_needed > min_bucket
                 else min_bucket)
    return np.unique(np.array(sizes, dtype=np.int64))


def _round_to_bucket(x: int, sizes: np.ndarray) -> int:
    return int(sizes[np.searchsorted(sizes, max(x, 1))])


def build_plan(sf: SymbolicFact, min_bucket: int = 8,
               growth: float = 1.5) -> FactorPlan:
    """Precompute all index maps.  Pure numpy; cost is O(nnz(L) + pool)."""
    n = sf.n
    ns = sf.n_supernodes
    indptr, indices = sf.pattern_indptr, sf.pattern_indices

    widths = np.diff(sf.sn_start).astype(np.int64)
    us = np.array([len(r) for r in sf.sn_rows], dtype=np.int64)

    w_sizes = _bucket_sizes(int(widths.max(initial=1)), min_bucket, growth)
    u_sizes = _bucket_sizes(int(us.max(initial=1)), min_bucket, growth)

    sn_W = np.array([_round_to_bucket(int(w), w_sizes) for w in widths])
    sn_U = np.array([0 if u == 0 else _round_to_bucket(int(u), u_sizes)
                     for u in us])
    sn_M = sn_W + sn_U

    # pool offsets (real u^2 strides, not padded)
    off = np.zeros(ns + 1, dtype=np.int64)
    np.cumsum(us * us, out=off[1:])
    pool_size = int(off[-1])

    # group supernodes by (level, W, U)
    key_order = np.lexsort((sn_U, sn_W, sf.sn_level))
    groups: list[Group] = []
    sn_group = np.empty(ns, dtype=np.int64)
    sn_slot = np.empty(ns, dtype=np.int64)
    i = 0
    while i < ns:
        s0 = key_order[i]
        lvl, W, U = int(sf.sn_level[s0]), int(sn_W[s0]), int(sn_U[s0])
        j = i
        members = []
        while (j < ns and sf.sn_level[key_order[j]] == lvl
               and sn_W[key_order[j]] == W and sn_U[key_order[j]] == U):
            members.append(key_order[j])
            j += 1
        sns = np.array(members, dtype=np.int64)
        for slot, s in enumerate(sns):
            sn_group[s] = len(groups)
            sn_slot[s] = slot
        groups.append(Group(level=lvl, m=W + U, w=W, batch=len(sns), sns=sns,
                            a_slot=None, a_flat=None, a_src=None,
                            pad_slot=None, pad_flat=None,
                            e_slot=None, e_flat=None, e_src=None,
                            s_slot=None, s_src_flat=None, s_dst=None))
        i = j

    # position helper: global index x within front of supernode s
    first = sf.sn_start[:-1]
    last = sf.sn_start[1:] - 1

    def positions(s: int, xs: np.ndarray) -> np.ndarray:
        inpiv = xs <= last[s]
        pos = np.where(inpiv, xs - first[s], 0)
        below = ~inpiv
        if below.any():
            pos_below = np.searchsorted(sf.sn_rows[s], xs[below])
            pos = pos.copy()
            pos[below] = sn_W[s] + pos_below
        return pos

    # --- A-entry assembly maps -------------------------------------------
    rows_all = np.repeat(np.arange(n), np.diff(indptr)).astype(np.int64)
    cols_all = indices.astype(np.int64)
    owner = sf.col_to_sn[np.minimum(rows_all, cols_all)]
    order_by_owner = np.argsort(owner, kind="stable")
    bounds = np.searchsorted(owner[order_by_owner], np.arange(ns + 1))
    ga_slot = [[] for _ in groups]
    ga_flat = [[] for _ in groups]
    ga_src = [[] for _ in groups]
    for s in range(ns):
        sel = order_by_owner[bounds[s]:bounds[s + 1]]
        if len(sel) == 0:
            continue
        pi = positions(s, rows_all[sel])
        pj = positions(s, cols_all[sel])
        g = sn_group[s]
        M = groups[g].m
        ga_slot[g].append(np.full(len(sel), sn_slot[s], dtype=np.int64))
        ga_flat[g].append(pi * M + pj)
        ga_src[g].append(sel)

    # --- identity padding + extend-add + write-back maps ------------------
    ge_slot = [[] for _ in groups]
    ge_flat = [[] for _ in groups]
    ge_src = [[] for _ in groups]
    gs_slot = [[] for _ in groups]
    gs_srcf = [[] for _ in groups]
    gs_dst = [[] for _ in groups]
    gp_slot = [[] for _ in groups]
    gp_flat = [[] for _ in groups]
    for s in range(ns):
        g = sn_group[s]
        grp = groups[g]
        M, W = grp.m, grp.w
        w, u = int(widths[s]), int(us[s])
        slot = sn_slot[s]
        if w < W:
            ks = np.arange(w, W, dtype=np.int64)
            gp_slot[g].append(np.full(len(ks), slot, dtype=np.int64))
            gp_flat[g].append(ks * M + ks)
        if u > 0:
            # write-back of the real u×u Schur block into the pool
            kk = np.arange(u, dtype=np.int64)
            src = ((W + kk)[:, None] * M + (W + kk)[None, :]).ravel()
            gs_slot[g].append(np.full(u * u, slot, dtype=np.int64))
            gs_srcf[g].append(src)
            gs_dst[g].append(off[s] + np.arange(u * u, dtype=np.int64))
            # extend-add into the parent front
            p = int(sf.sn_parent[s])
            assert p >= 0
            gp_ = sn_group[p]
            pgrp = groups[gp_]
            posp = positions(p, sf.sn_rows[s])
            eflat = (posp[:, None] * pgrp.m + posp[None, :]).ravel()
            ge_slot[gp_].append(np.full(u * u, sn_slot[p], dtype=np.int64))
            ge_flat[gp_].append(eflat)
            ge_src[gp_].append(off[s] + np.arange(u * u, dtype=np.int64))

    def cat(lst, dtype=np.int64):
        return (np.concatenate(lst).astype(dtype) if lst
                else np.empty(0, dtype=dtype))

    front_bytes = 0
    for g, grp in enumerate(groups):
        grp.a_slot, grp.a_flat, grp.a_src = cat(ga_slot[g]), cat(ga_flat[g]), cat(ga_src[g])
        grp.pad_slot, grp.pad_flat = cat(gp_slot[g]), cat(gp_flat[g])
        grp.e_slot, grp.e_flat, grp.e_src = cat(ge_slot[g]), cat(ge_flat[g]), cat(ge_src[g])
        grp.s_slot, grp.s_src_flat, grp.s_dst = cat(gs_slot[g]), cat(gs_srcf[g]), cat(gs_dst[g])
        front_bytes += grp.batch * grp.m * grp.m

    return FactorPlan(n=n, sf=sf, pattern_indptr=indptr,
                      pattern_indices=indices, groups=groups,
                      pool_size=pool_size, sn_group=sn_group, sn_slot=sn_slot,
                      flops=sf.flops, front_bytes=front_bytes)
