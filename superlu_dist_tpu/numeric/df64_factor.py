"""Double-float (df64) numeric factorization — true ~2^-48 factors on
hardware without an f64 MXU, real AND complex.

This closes SURVEY.md §7 hard-part 1 for the systems the default
mixed-precision path cannot handle: with f32 factors, iterative
refinement converges only while κ(A)·2⁻²⁴ ≲ 1; beyond that the
correction solves stop contracting.  Factoring in df64 (hi, lo f32
pairs, ~48-bit significands — ops/df64.py) pushes the boundary to
κ(A)·2⁻⁴⁸, the same class as native f64, at ~20-30 f32 flops per MAC on
the VPU.

Design: the same level-batched multifrontal plan as the fast path (the
index maps are dtype-blind), with a df64 twin of the group step.  The
pivot-block elimination runs the scatter-free masked loop over the
pivot columns of the WHOLE front — each step is a full-front exact
rank-1 update, so after w steps the trailing block IS the Schur
complement (no separate triangular solves needed; this trades ~3x
flops for having exactly one df64 kernel).  Factored panels are pulled
to host and recombined into exact float64/complex128 arrays, so every
downstream consumer — host triangular solves, transpose solves,
refinement, GetDiagU — runs the standard f64/c128 path unchanged.

Precision scheme: ONE generic kernel over a small "component algebra" —
real df64 values are (hi, lo) f32 pairs, complex zdf64 values are
(re_hi, re_lo, im_hi, im_lo) quadruples (ops/df64.py zdf64_*).  This is
the templating-by-dtype answer to the reference's hand-expanded d/z twin
files (pdgstrf.c / pzgstrf.c:243): the scatter/assembly machinery is
component-blind, only the scalar arithmetic dispatches.

Accuracy caveat (see ops/df64.py header): XLA:CPU's instruction fusion
breaks the error-free transforms; on the CPU backend run with
XLA_FLAGS=--xla_disable_hlo_passes=fusion,cpu-instruction-fusion (the
tests do, in a subprocess).  TPU/GPU pipelines honor the barriers.
"""

from __future__ import annotations

import functools

import numpy as np
import jax
import jax.numpy as jnp

from superlu_dist_tpu.numeric.factor import NumericFactorization
from superlu_dist_tpu.numeric.plan import FactorPlan
from superlu_dist_tpu.ops.df64 import (df64_add, df64_div, df64_from_f64,
                                       df64_mul, df64_sub, df64_to_f64,
                                       zdf64_add, zdf64_div,
                                       zdf64_from_c128, zdf64_mul,
                                       zdf64_sub, zdf64_to_c128)


class _RealDf64:
    """Real df64 algebra: components (hi, lo)."""

    name = "df64"
    ncomp = 2
    out_dtype = np.float64
    add = staticmethod(lambda x, y: df64_add((x[0], x[1]), (y[0], y[1])))
    sub = staticmethod(lambda x, y: df64_sub((x[0], x[1]), (y[0], y[1])))
    mul = staticmethod(lambda x, y: df64_mul((x[0], x[1]), (y[0], y[1])))
    div = staticmethod(lambda x, y: df64_div((x[0], x[1]), (y[0], y[1])))

    @staticmethod
    def mag_hi(x):
        """Pivot magnitude from the hi word(s) — the GESP threshold test
        semantics (pdgstrf2.c:218-232)."""
        return jnp.abs(x[0])

    @staticmethod
    def unit_hi(x, safe):
        """Unit direction (phase) with zero lo words; |x|==0 -> 1."""
        return (jnp.where(safe == 0, jnp.ones_like(x[0]), x[0] / safe),
                jnp.zeros_like(x[1]))

    @staticmethod
    def split(values):
        return df64_from_f64(np.asarray(values, np.float64))

    @staticmethod
    def join(comps):
        return df64_to_f64(comps)


class _ComplexDf64:
    """Complex zdf64 algebra: components (re_hi, re_lo, im_hi, im_lo) —
    the pzgstrf twin discipline without twin files."""

    name = "zdf64"
    ncomp = 4
    out_dtype = np.complex128
    add = staticmethod(zdf64_add)
    sub = staticmethod(zdf64_sub)
    mul = staticmethod(zdf64_mul)
    div = staticmethod(zdf64_div)

    @staticmethod
    def mag_hi(x):
        return jnp.sqrt(x[0] * x[0] + x[2] * x[2])

    @staticmethod
    def unit_hi(x, safe):
        s = jnp.where(safe == 0, jnp.ones_like(safe), safe)
        return (jnp.where(safe == 0, jnp.ones_like(x[0]), x[0] / s),
                jnp.zeros_like(x[1]),
                jnp.where(safe == 0, jnp.zeros_like(x[2]), x[2] / s),
                jnp.zeros_like(x[3]))

    @staticmethod
    def split(values):
        return zdf64_from_c128(values)

    @staticmethod
    def join(comps):
        return zdf64_to_c128(comps)


_ALGEBRAS = {"df64": _RealDf64, "zdf64": _ComplexDf64}


def _fix_pivot_df64(piv, thresh, alg=_RealDf64):
    """GESP tiny-pivot replacement on the df64 pivot: magnitude test on
    the hi word(s), replacement phase(piv)·thresh with zeroed lo words
    (the reference's thresh semantics, pdgstrf2.c:218-232)."""
    ap = alg.mag_hi(piv)
    safe = jnp.where(ap == 0, jnp.ones_like(ap), ap)
    unit = alg.unit_hi(piv, jnp.where(ap == 0, jnp.zeros_like(ap), safe))
    tiny = ap < thresh
    out = tuple(jnp.where(tiny, u * thresh, p)
                for u, p in zip(unit, piv))
    return out, tiny.astype(jnp.int32)


def df64_partial_front_factor(*args):
    """Masked partial LU of one (m, m) df64 front over its first w pivot
    columns.  Full-front rank-1 updates: after the loop the leading w
    rows/cols hold packed L\\U, L21, U12 and the trailing block holds
    the Schur complement.

    Signatures: (fh, fl, thresh, w) for real (back-compat), or
    (comps_tuple, thresh, w, alg) generically; returns (comps, tiny
    flags (w,))."""
    if len(args) == 4 and not isinstance(args[0], tuple):
        fh, fl, thresh, w = args
        return _partial_front_factor((fh, fl), thresh, w, _RealDf64)
    return _partial_front_factor(*args)


def _partial_front_factor(comps, thresh, w, alg):
    m = comps[0].shape[0]
    idx = jnp.arange(m)

    def step(i, carry):
        cs, flags = carry
        sel = idx == i
        e = sel.astype(cs[0].dtype)
        # single-element masks: the sums select exactly one entry, so
        # they are exact in f32 (every other term is a true zero)
        row = tuple(jnp.sum(c * e[:, None], axis=0) for c in cs)
        col = tuple(jnp.sum(c * e[None, :], axis=1) for c in cs)
        piv = tuple(jnp.sum(r * e) for r in row)
        piv, tiny = _fix_pivot_df64(piv, thresh, alg)
        below = idx > i
        l = alg.div(col, tuple(p[None] for p in piv))
        l = tuple(jnp.where(below, c, 0.0) for c in l)
        u = tuple(jnp.where(below, r, 0.0) for r in row)
        upd = alg.mul(tuple(c[:, None] for c in l),
                      tuple(r[None, :] for r in u))
        cs = alg.sub(cs, upd)
        # write multipliers + fixed pivot into column i by EXACT masked
        # select (0/1 products and disjoint-support sums round nothing;
        # the f32 path's delta-add trick would round the df64 low word
        # at the f32 ulp and collapse the factorization to f32 accuracy)
        above = idx < i
        new_col = tuple(jnp.where(below, lc, 0.0)
                        + jnp.where(above, cc, 0.0) + pv * e
                        for lc, cc, pv in zip(l, col, piv))
        keep = (1.0 - e)[None, :]
        cs = tuple(c * keep + nc[:, None] * e[None, :]
                   for c, nc in zip(cs, new_col))
        return cs, flags + tiny * sel.astype(jnp.int32)

    comps, flags = jax.lax.fori_loop(
        0, w, step, (comps, jnp.zeros(m, jnp.int32)))
    return comps, flags[:w]


@functools.lru_cache(maxsize=None)
def _df64_group_kernel(dims, child_shapes, pool_size, mesh=None,
                       pool_partition=False, alg_name="df64"):
    """One (level, bucket) group in df64/zdf64: assemble the component
    arrays, factor, scatter the Schur block into the component pools.

    With a mesh, the batch dimension shards over "snode" (the vmapped
    elimination is per-front independent, so sharding cannot perturb the
    error-free transforms).  The "panel" axis is idle here — splitting
    the masked elimination's minor dims would turn every per-step
    row/column reduction into a collective.  pool_partition shards the
    component Schur pools 1-D across ALL mesh devices (same layout as
    the f32 path, factor.pool_spec): per-chip pool memory divides by the
    device count, so the df64 tier reaches the same n≈1M class as f32.
    Sharding a scatter/gather cannot perturb the error-free transforms
    either — each pool entry still receives exactly the same summands in
    the same order."""
    alg = _ALGEBRAS[alg_name]
    nc = alg.ncomp
    batch, m, w, u = dims
    front_sharding = pool_sharding = None
    if mesh is not None:
        from jax.sharding import NamedSharding, PartitionSpec as P
        from superlu_dist_tpu.numeric.factor import pool_spec
        front_sharding = NamedSharding(mesh, P("snode", None, None))
        pool_sharding = pool_spec(mesh, pool_partition)

    def step(avals, pools, thresh, a_slot, a_flat, a_src, ws, off,
             *child_arr):
        k = jnp.arange(m)
        diag = ((k[None, :] >= ws[:, None]) & (k[None, :] < w)).astype(
            jnp.float32)
        fs = [jnp.zeros((batch, m * m), jnp.float32) for _ in range(nc)]
        fs[0] = fs[0].at[:, k * m + k].add(diag)   # identity padding
        if a_src.shape[0]:
            for c in range(nc):
                v = avals[c].at[a_src].get(mode="fill", fill_value=0)
                fs[c] = fs[c].at[(a_slot, a_flat)].add(v, mode="drop")
        children = [(ub, child_arr[3 * i], child_arr[3 * i + 1],
                     child_arr[3 * i + 2])
                    for i, (ub, _) in enumerate(child_shapes)]
        # extend-add must stay exact: a plain f32 scatter-ADD would round
        # colliding sibling contributions at 2^-24 and cap the whole
        # factorization at f32 accuracy.  The caller pre-partitions the
        # children into passes with at most ONE child per batch slot
        # (child_shapes carries one entry per collision-free pass), so
        # each pass scatters into fresh zero components and is folded
        # into the front with an exact df64 add.
        for (ub, child_off, child_slot, rel) in children:
            src = child_off[:, None] + jnp.arange(ub * ub)
            ri, rj = rel[:, :, None], rel[:, None, :]
            dst = jnp.where((ri >= m) | (rj >= m), m * m,
                            ri * m + rj).reshape(-1, ub * ub)
            ps = []
            for c in range(nc):
                v = pools[c].at[src].get(mode="fill", fill_value=0)
                p = jnp.zeros((batch, m * m), jnp.float32)
                ps.append(p.at[(child_slot[:, None], dst)].add(
                    v, mode="drop"))
            fs = list(alg.add(tuple(fs), tuple(ps)))
        fs = [f.reshape(batch, m, m) for f in fs]
        if front_sharding is not None:
            fs = [jax.lax.with_sharding_constraint(f, front_sharding)
                  for f in fs]
            pools = tuple(jax.lax.with_sharding_constraint(p, pool_sharding)
                          for p in pools)
        fs, counts = jax.vmap(
            lambda *cs: _partial_front_factor(cs, thresh, w, alg))(*fs)
        tiny = jnp.sum(jnp.where(jnp.arange(w)[None, :] < ws[:, None],
                                 counts, 0))
        if u > 0:
            dst = off[:, None] + jnp.arange(u * u)
            pools = tuple(
                p.at[dst].set(f[:, w:, w:].reshape(batch, u * u),
                              mode="drop")
                for p, f in zip(pools, fs))
        lp = tuple(f[:, :, :w] for f in fs)
        up = tuple(f[:, :w, w:] for f in fs)
        if pool_sharding is not None:
            # pin the linearly-threaded pools replicated on OUTPUT too, so
            # sharding propagation from the snode-sharded fronts cannot
            # hand the next group a resharded pool (per-group transfers /
            # jit cache misses)
            pools = tuple(jax.lax.with_sharding_constraint(p, pool_sharding)
                          for p in pools)
        return lp, up, pools, tiny

    return jax.jit(step, donate_argnums=(1,))


class Df64Executor:
    """Cached df64/zdf64 executor for a plan (the SamePattern reuse tier).

    Mirrors stream.StreamExecutor's discipline: all host-side index prep
    (bucket padding, collision-free child-pass partitioning) runs ONCE in
    __init__; repeated calls with new values reuse the uploaded index
    arrays and the lru-cached jitted kernels.  Obtain through
    `get_df64_executor` so gssvx's SamePattern tier hits the same
    executor across factorizations (the reference keeps its schedules in
    LUstruct across SamePattern calls, SRC/pdgssvx.c:1132-1166)."""

    def __init__(self, plan: FactorPlan, mesh=None,
                 pool_partition: bool = False, alg=_RealDf64):
        from superlu_dist_tpu.numeric.stream import _bucket_len, _pad_to

        plan.check_index_width()
        self.plan = plan
        self.mesh = mesh
        self.alg = alg
        self.pool_partition = bool(pool_partition and mesh is not None)
        self.n_avals = len(plan.pattern_indices)
        self._groups = []     # (grp, a-arrays, child_arrs, kernel)
        for grp in plan.groups:
            b = _bucket_len(grp.batch, 1)
            la = _bucket_len(len(grp.a_src))
            a = (jnp.asarray(_pad_to(grp.a_slot, la, b)),
                 jnp.asarray(_pad_to(grp.a_flat, la, 0)),
                 jnp.asarray(_pad_to(grp.a_src, la, self.n_avals)),
                 jnp.asarray(_pad_to(grp.ws, b, 0)),
                 jnp.asarray(_pad_to(grp.off, b, plan.pool_size)))
            child_arrs = []
            child_shapes = []
            for cs in grp.children:
                # partition this child group into passes with at most one
                # child per batch slot, so each pass's scatter is
                # collision-free and the pass results combine by exact
                # df64 add (see _df64_group_kernel)
                passes = []          # list of lists of child indices
                for j, slot in enumerate(np.asarray(cs.child_slot)):
                    for p in passes:
                        if slot not in p[1]:
                            p[0].append(j)
                            p[1].add(int(slot))
                            break
                    else:
                        passes.append(([j], {int(slot)}))
                for p_idx, _slots in passes:
                    sel = np.asarray(p_idx, dtype=np.int64)
                    c = _bucket_len(len(sel), 1)
                    rel = np.full((c, cs.ub), grp.m, dtype=np.int64)
                    rel[:len(sel)] = np.asarray(cs.rel)[sel]
                    child_arrs.extend([
                        jnp.asarray(_pad_to(np.asarray(cs.child_off)[sel],
                                            c, plan.pool_size)),
                        jnp.asarray(_pad_to(np.asarray(cs.child_slot)[sel],
                                            c, b)),
                        jnp.asarray(rel)])
                    child_shapes.append((cs.ub, c))
            kern = _df64_group_kernel((b, grp.m, grp.w, grp.u),
                                      tuple(child_shapes), plan.pool_size,
                                      mesh, self.pool_partition, alg.name)
            self._groups.append((grp, a, child_arrs, kern))

    def __call__(self, avals, thresh):
        """Run the factorization on component-split values; returns
        (fronts [host f64/c128], tiny).  `avals` is the alg.ncomp tuple
        from alg.split()."""
        alg = self.alg
        pools = tuple(jnp.zeros(self.plan.pool_size, jnp.float32)
                      for _ in range(alg.ncomp))
        if self.mesh is not None:
            # commit the pools to their mesh layout up front (partitioned
            # or replicated) so the first kernel starts from the right
            # sharding instead of inserting a reshard
            from superlu_dist_tpu.numeric.factor import pool_spec
            psh = pool_spec(self.mesh, self.pool_partition)
            pools = tuple(jax.device_put(p, psh) for p in pools)
        fronts = []
        tiny = 0
        for grp, a, child_arrs, kern in self._groups:
            lp, up, pools, t = kern(avals, pools, thresh, *a, *child_arrs)
            tiny += int(t)
            # recombine on host to exact f64/c128; trim batch padding
            fronts.append((alg.join(lp)[:grp.batch],
                           alg.join(up)[:grp.batch]))
        return fronts, tiny


def get_df64_executor(plan: FactorPlan, mesh=None,
                      pool_partition: bool = False,
                      alg=_RealDf64) -> Df64Executor:
    """Df64Executor cached on the plan (same cache dict as
    factor.get_executor, keyed distinctly)."""
    cache = getattr(plan, "_factor_fns", None)
    if cache is None:
        cache = plan._factor_fns = {}
    key = (alg.name, alg.name, mesh,
           bool(pool_partition and mesh is not None))
    ex = cache.get(key)
    if ex is None:
        ex = cache[key] = Df64Executor(plan, mesh=mesh,
                                       pool_partition=pool_partition,
                                       alg=alg)
    return ex


def df64_numeric_factorize(plan: FactorPlan, pattern_values: np.ndarray,
                           anorm: float,
                           replace_tiny: bool = True,
                           mesh=None,
                           pool_partition: bool = False,
                           check_finite: bool = True
                           ) -> NumericFactorization:
    """Factor with ~f64 accuracy on f32-only hardware (real or complex).

    Real float64 values split exactly into df64 pairs host-side; complex
    values into zdf64 quadruples (the pzgstrf z-twin capability,
    SRC/pzgstrf.c:243).  The GESP threshold uses the f64 epsilon — these
    factors genuinely carry ~48-bit significands.  Output fronts are
    host float64/complex128 arrays (components recombined), so the
    standard host solve/refine path runs unchanged; `on_host` is True by
    construction.
    """
    vals = np.asarray(pattern_values)
    alg = (_ComplexDf64 if np.issubdtype(vals.dtype, np.complexfloating)
           else _RealDf64)
    avals = alg.split(vals)
    eps64 = float(np.finfo(np.float64).eps)
    thresh = jnp.asarray(np.sqrt(eps64) * max(float(anorm), 1e-300)
                         if replace_tiny else 0.0, jnp.float32)
    ex = get_df64_executor(plan, mesh=mesh, pool_partition=pool_partition,
                           alg=alg)
    fronts, tiny = ex(avals, thresh)
    finite, info_col = (True, -1)
    if not replace_tiny:
        from superlu_dist_tpu.numeric.factor import localize_singularity
        finite, info_col = localize_singularity(plan, fronts)
    elif check_finite:
        # non-finite sentinel (same contract as numeric_factorize): with
        # tiny-pivot replacement active, NaN/Inf means breakdown
        from superlu_dist_tpu.numeric.factor import (
            fronts_finite, localize_nonfinite)
        if not fronts_finite(fronts):
            from superlu_dist_tpu.utils.errors import NumericBreakdownError
            sn, col = localize_nonfinite(plan, fronts)
            raise NumericBreakdownError(supernode=sn, col=col,
                                        where="df64 numeric factorization")
    return NumericFactorization(plan=plan, fronts=fronts, tiny_pivots=tiny,
                                dtype=np.dtype(alg.out_dtype),
                                finite=finite, info_col=info_col)
