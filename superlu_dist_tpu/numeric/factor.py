"""Level-batched multifrontal numeric factorization on the accelerator.

The execution analog of pdgstrf (SRC/pdgstrf.c:243) — but where the
reference runs an MPI look-ahead pipeline of per-panel BLAS calls, this
walks the elimination-tree levels bottom-up and, per (level, bucket) group,
issues three scatter/gather ops and one batched dense kernel (ops.dense).
All arrays stay resident on the device; the update pool plays the role of
the reference's bigU/bigV GEMM buffers (pdgstrf.c:770-884) and the
extend-add indices the role of the dscatter_l/u index arithmetic
(SRC/dscatter.c:111-290).
"""

from __future__ import annotations

import dataclasses

import numpy as np
import jax
import jax.numpy as jnp

from superlu_dist_tpu.numeric.plan import FactorPlan
from superlu_dist_tpu.ops.dense import group_partial_factor


@dataclasses.dataclass
class NumericFactorization:
    """LU factors as packed front batches (the dLUstruct_t analog,
    superlu_ddefs.h:186-191)."""

    plan: FactorPlan
    fronts: list              # per group: (B, M, M) device array, packed LU
    tiny_pivots: int
    dtype: object
    finite: bool = True       # False => an exact zero pivot propagated
                              # (only possible with replace_tiny=False)
    host_fronts: list = None  # lazily pulled numpy copies for the host solve

    def pull_to_host(self):
        """Transfer factors to host once (the dSolveInit analog,
        SRC/pdutil.c:690 — solve-side setup cached across solves)."""
        if self.host_fronts is None:
            self.host_fronts = [np.asarray(f) for f in self.fronts]
        return self.host_fronts


def make_factor_fn(plan: FactorPlan, dtype="float64", mesh=None):
    """Build the whole numeric factorization as ONE jittable function.

    Where the reference's pdgstrf is an MPI pipeline of thousands of BLAS
    calls (SRC/pdgstrf.c:1100-1745), the plan's level groups let the entire
    factorization trace into a single XLA program: per group one gather
    (assembly + extend-add), one batched partial LU, one scatter to the
    Schur pool.  XLA then owns scheduling, fusion, and buffer reuse.

    Returns fn(avals, thresh) -> (fronts_tuple, tiny_count).  The plan's
    index maps are closed over as device constants (hoisted to args by jit).
    If `mesh` is a jax.sharding.Mesh with axes ("snode", "panel"), each
    group's front batch is sharded batch-over-"snode" and columns-over-
    "panel" — the 2D block-cyclic layout analog (SURVEY.md §2.4) — and the
    Schur pool is replicated (extend-add plays the role of the reference's
    cross-rank scatter, pddistribute.c:61).
    """
    dtype = jnp.dtype(dtype)
    one = jnp.ones((), dtype=dtype)
    sharding = pivot_sharding = None
    if mesh is not None:
        from jax.sharding import NamedSharding, PartitionSpec as P
        # Only the dense factor math (triangular solves + Schur GEMM) is
        # sharded; every irregular scatter/gather (assembly, extend-add,
        # pool write-back) is pinned replicated — XLA's SPMD partitioner
        # miscompiles scatter/gather with sharded operand dims (jax 0.9.0),
        # and these ops are bandwidth-trivial next to the GEMMs anyway.
        sharding = NamedSharding(mesh, P("snode", None, "panel"))
        pivot_sharding = NamedSharding(mesh, P("snode", None, None))
        pool_sharding = NamedSharding(mesh, P(None))
        flat_repl = NamedSharding(mesh, P(None, None))
    # hoist index maps to device arrays once (jit passes them as consts)
    idx = []
    for grp in plan.groups:
        idx.append(tuple(jnp.asarray(a) for a in (
            grp.pad_slot, grp.pad_flat, grp.a_slot, grp.a_flat, grp.a_src,
            grp.e_slot, grp.e_flat, grp.e_src,
            grp.s_slot, grp.s_src_flat, grp.s_dst)))

    def fn(avals, thresh):
        avals = avals.astype(dtype)
        pool = jnp.zeros(plan.pool_size, dtype=dtype)
        if sharding is not None:
            pool = jax.lax.with_sharding_constraint(pool, pool_sharding)
        fronts = []
        tiny = jnp.zeros((), jnp.int32)
        for grp, (pad_slot, pad_flat, a_slot, a_flat, a_src,
                  e_slot, e_flat, e_src, s_slot, s_src_flat, s_dst) in zip(
                plan.groups, idx):
            f = jnp.zeros((grp.batch, grp.m * grp.m), dtype=dtype)
            if sharding is not None:
                f = jax.lax.with_sharding_constraint(f, flat_repl)
            if len(grp.pad_flat):
                f = f.at[(pad_slot, pad_flat)].set(one)
            if len(grp.a_src):
                f = f.at[(a_slot, a_flat)].add(avals[a_src])
            if len(grp.e_src):
                f = f.at[(e_slot, e_flat)].add(pool[e_src])
            f = f.reshape(grp.batch, grp.m, grp.m)
            if sharding is not None:
                f = jax.lax.with_sharding_constraint(f, sharding)
            packed, counts = group_partial_factor(
                f, thresh, grp.w, front_sharding=sharding,
                pivot_sharding=pivot_sharding)
            fronts.append(packed)
            tiny = tiny + counts
            if len(grp.s_dst):
                flat = packed.reshape(grp.batch, -1)
                if sharding is not None:
                    flat = jax.lax.with_sharding_constraint(flat, flat_repl)
                pool = pool.at[s_dst].set(flat[(s_slot, s_src_flat)])
                if sharding is not None:
                    pool = jax.lax.with_sharding_constraint(pool, pool_sharding)
        return tuple(fronts), tiny

    return jax.jit(fn)


def numeric_factorize(plan: FactorPlan, pattern_values: np.ndarray,
                      anorm: float, dtype="float64",
                      replace_tiny: bool = True) -> NumericFactorization:
    """Factor with values aligned to plan.pattern_indices.

    anorm: ‖A‖ for the GESP tiny-pivot threshold sqrt(eps)·‖A‖
    (reference pdgstrf2.c:218: thresh = eps·‖A‖; we use the sqrt variant of
    ReplaceTinyPivot so f32 factors retain half their digits).
    With replace_tiny=False an exact zero pivot propagates inf/nan; the
    result is flagged non-finite (the reference's info>0 singularity path,
    pdgstrf.c:234-241).
    """
    dtype = jnp.dtype(dtype)
    real_dtype = jnp.dtype(dtype).type(0).real.dtype
    eps = jnp.finfo(real_dtype).eps
    thresh = jnp.asarray(
        np.sqrt(float(eps)) * max(anorm, 1e-300) if replace_tiny else 0.0,
        dtype=real_dtype)
    avals = jnp.asarray(pattern_values, dtype=dtype)
    cache = getattr(plan, "_factor_fns", None)
    if cache is None:
        cache = plan._factor_fns = {}
    fn = cache.get(str(dtype))
    if fn is None:
        fn = cache[str(dtype)] = make_factor_fn(plan, dtype)
    fronts_out, tiny_total = fn(avals, thresh)
    fronts_out = list(fronts_out)
    finite = True
    if not replace_tiny:
        # singularity check: non-finite factors OR an exact zero on the U
        # diagonal (a zero pivot in the last column of an unpadded front
        # divides nothing during factorization, so isfinite alone misses it)
        for grp, f in zip(plan.groups, fronts_out):
            diag = jnp.diagonal(f[:, :grp.w, :grp.w], axis1=1, axis2=2)
            if not bool(jnp.isfinite(f).all()) or bool((diag == 0).any()):
                finite = False
                break
    return NumericFactorization(plan=plan, fronts=fronts_out,
                                tiny_pivots=int(tiny_total), dtype=dtype,
                                finite=finite)


def factor_flops(plan: FactorPlan) -> float:
    """Flop count for stats (the ops[FACT] analog, SRC/util.c:513)."""
    return plan.flops
