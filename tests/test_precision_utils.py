"""Precision-utility tests (dutil_dist.c / pdGetDiagU analogs)."""

import numpy as np

from superlu_dist_tpu.drivers.gssvx import gssvx
from superlu_dist_tpu.models.gallery import poisson2d, random_sparse
from superlu_dist_tpu.utils.options import Options, IterRefine
from superlu_dist_tpu.utils.precision import (
    gen_xtrue, fill_rhs, inf_norm_error, get_diag_u)


def test_gen_fill_err_roundtrip():
    a = poisson2d(6)
    xt = gen_xtrue(a.n_rows, seed=3)
    b = fill_rhs(a, xt)
    x, lu, stats, info = gssvx(Options(), a, b)
    assert info == 0
    assert inf_norm_error(x, xt) < 1e-10
    assert inf_norm_error(x, xt + 1.0) > 0.1


def test_get_diag_u_matches_determinant():
    """|det M| must equal prod |U_ii| — M is the scaled/permuted matrix the
    factors represent (the pdGetDiagU use case: determinants, condition
    estimates)."""
    a = random_sparse(40, density=0.15, seed=9)
    b = np.ones(a.n_rows)
    x, lu, stats, info = gssvx(Options(iter_refine=IterRefine.NOREFINE), a, b)
    assert info == 0
    du = get_diag_u(lu.numeric)
    assert du.shape == (a.n_rows,)
    # reconstruct M = P_sigma diag(R) A diag(C) P_pi^T densely
    A = a.to_dense()
    M = (np.diag(lu.R) @ A @ np.diag(lu.C))[lu.sigma][:, lu.sf.perm]
    sign, logdet = np.linalg.slogdet(M)
    np.testing.assert_allclose(np.sum(np.log(np.abs(du))), logdet,
                               rtol=1e-8)
