"""Host-side async tree broadcast / reduction.

Capability analog of the reference's C++11 tree-collective engine
(TreeBcast_slu.hpp, TreeReduce_slu.hpp, TreeInterface.cpp) — the
per-supernode broadcast and reduction trees that drive its distributed
triangular solve (pdgstrs.c:1444-1670).  Same topology rule: flat tree up
to 8 ranks, binary beyond (TreeBcast_slu.hpp:17-29).

TPU-native split of responsibilities: *on-device* solve collectives ride
XLA over the mesh (solve/device.py on sharded factors); this module is
the *host-process* orchestration layer — multi-process single-node runs
coordinate through a POSIX shared-memory segment (native slu_tree_*,
slu_host.cpp) instead of MPI point-to-point, with per-rank atomic
sequence/ack counters providing the async pipeline the reference gets
from Isend/Irecv.
"""

from __future__ import annotations

import contextlib
import ctypes
import os
import sys
import threading
import time

import numpy as np

from superlu_dist_tpu import native
from superlu_dist_tpu.obs.metrics import get_metrics
from superlu_dist_tpu.obs.trace import get_tracer
from superlu_dist_tpu.utils.stats import CommStats

_NULL_CTX = contextlib.nullcontext()


class LockstepVerifier:
    """Runtime collective-lockstep verification (slulint rule SLU106).

    With ``SLU_TPU_VERIFY_COLLECTIVES=1`` every public TreeComm
    collective is preceded by a digest exchange: each rank contributes a
    fixed-layout record of (sequence number, op kind, payload
    shape/dtype, root, call site) into its own slot of an
    ``n_ranks × REC`` matrix, summed over a SIBLING tree domain
    (``<name>.vfy`` — same native transport, its own segment so digests
    never perturb payload slots) and broadcast back.  Because the digest
    exchange has the identical native-leg structure on every rank
    regardless of WHICH public op the rank is entering, ranks that have
    diverged into different collectives still complete the exchange —
    and then every rank sees every rank's record, detects the mismatch,
    and raises :class:`CollectiveMismatchError` naming the divergent
    call sites, instead of hanging inside mismatched payload legs (the
    MUST-style deadlock-to-diagnosis conversion; the reference's
    collectives offer no such guard).

    Composite ops verify ONCE at their public entry (``depth`` guards
    the inner legs), so the digest carries the caller's intent —
    ``allreduce_sum_any`` with the real payload shape/dtype — not the
    transport decomposition.  The digest exchange rides the owning
    TreeComm's bounded-wait legs, so with ``SLU_TPU_COMM_TIMEOUT_S`` set
    a rank that stops calling collectives altogether (died, hung)
    surfaces as :class:`RankFailureError` on every peer — SILENCE is
    covered by the failure detector the same way DIVERGENCE is covered
    by the digest cross-check; neither hangs the fleet.
    """

    SHAPE_SLOTS = 3
    DTYPE_CHARS = 12
    SITE_CHARS = 48
    REC = 5 + SHAPE_SLOTS + DTYPE_CHARS + SITE_CHARS

    _OPCODES = {op: i + 1 for i, op in enumerate((
        "bcast", "reduce", "reduce_sum", "allreduce", "allreduce_sum",
        "bcast_any", "reduce_sum_any", "allreduce_sum_any",
        "bcast_bytes", "bcast_obj"))}

    def __init__(self, lib, name: bytes, n_ranks: int, rank: int,
                 create: bool):
        self._lib = lib
        self.name = bytes(name) + b".vfy"
        self.n_ranks = int(n_ranks)
        self.rank = int(rank)
        self.seq = 0
        self.depth = 0
        self.checks = 0
        self._h = lib.slu_tree_attach(self.name, self.n_ranks,
                                      self.n_ranks * self.REC, self.rank,
                                      1 if create else 0)
        if not self._h:
            raise OSError(f"slu_tree_attach failed for verifier domain "
                          f"{self.name!r}")
        self._created = bool(create)
        # set by the owning TreeComm: routes the digest exchange through
        # its bounded-wait leg policy (timeout + failure detector), so a
        # silent rank fails this exchange structurally too
        self.comm = None

    # ---- lifecycle -----------------------------------------------------
    def close(self, unlink: bool | None = None):
        if self._h:
            if unlink is None:
                unlink = self._created
            self._lib.slu_tree_detach(self._h, self.name,
                                      1 if unlink else 0)
            self._h = None

    # ---- the check -----------------------------------------------------
    @contextlib.contextmanager
    def guard(self, op, shape, dtype, root):
        """Verify once at the outermost public op; inner legs (composite
        decomposition, chunking, fault-injection retries) are exempt —
        their structure is a deterministic function of the verified
        public op."""
        if self.depth == 0:
            self.check(op, shape, dtype, root)
        self.depth += 1
        try:
            yield
        finally:
            self.depth -= 1

    def check(self, op, shape, dtype, root):
        rec = self._encode(op, shape, dtype, root, _call_site())
        buf = np.zeros(self.n_ranks * self.REC, dtype=np.float64)
        buf[self.rank * self.REC:(self.rank + 1) * self.REC] = rec
        # digest allreduce over the sibling domain: identical native-leg
        # structure for every public op, so it completes even when the
        # public sequences have diverged; routed through the owning
        # TreeComm's bounded-wait policy so a SILENT (dead) rank raises
        # RankFailureError here instead of hanging the exchange
        if self.comm is not None:
            self.comm._native_leg("reduce_sum", buf, 0, handle=self._h,
                                  op_name=f"verify:{op}")
            self.comm._native_leg("bcast", buf, 0, handle=self._h,
                                  op_name=f"verify:{op}")
        else:
            ptr = buf.ctypes.data_as(ctypes.POINTER(ctypes.c_double))
            self._lib.slu_tree_reduce_sum(self._h, 0, ptr, buf.size)
            self._lib.slu_tree_bcast(self._h, 0, ptr, buf.size)
        self.seq += 1
        self.checks += 1
        mat = buf.reshape(self.n_ranks, self.REC)
        # the call-site chars are informational (SPMD peers legitimately
        # reach the SAME collective from different source lines — owner
        # vs worker driver code); semantic lockstep is (seq, op, root,
        # shape, dtype)
        sem = mat[:, :5 + self.SHAPE_SLOTS + self.DTYPE_CHARS]
        if (sem == sem[0]).all():
            return
        from superlu_dist_tpu.utils.errors import CollectiveMismatchError
        records = [self._decode(r, mat[r]) for r in range(self.n_ranks)]
        tr = get_tracer()
        if tr.enabled:
            t0 = time.perf_counter()
            tr.complete("collective-mismatch", "verify", t0, 0.0,
                        rank=self.rank, seq=self.seq - 1,
                        sites=";".join(x["site"] for x in records))
        raise CollectiveMismatchError(records, rank=self.rank)

    # ---- record layout --------------------------------------------------
    def _encode(self, op, shape, dtype, root, site):
        rec = np.zeros(self.REC, dtype=np.float64)
        shape = tuple(int(s) for s in tuple(shape)[:self.SHAPE_SLOTS])
        rec[0] = self.seq
        rec[1] = self._OPCODES.get(op, 0)
        rec[2] = int(root)
        rec[3] = len(shape)
        rec[4] = float(np.prod(shape, dtype=np.float64)) if shape else 0.0
        rec[5:5 + len(shape)] = shape
        base = 5 + self.SHAPE_SLOTS
        for i, ch in enumerate(str(dtype)[:self.DTYPE_CHARS]):
            rec[base + i] = ord(ch)
        base += self.DTYPE_CHARS
        for i, ch in enumerate(site[-self.SITE_CHARS:]):
            rec[base + i] = ord(ch)
        return rec

    def _decode(self, rank, rec):
        ndim = int(rec[3])
        base = 5 + self.SHAPE_SLOTS
        names = {v: k for k, v in self._OPCODES.items()}
        chars = (lambda lo, n: "".join(
            chr(int(c)) for c in rec[lo:lo + n] if int(c) > 0))
        return {
            "rank": rank,
            "seq": int(rec[0]),
            "op": names.get(int(rec[1]), f"op#{int(rec[1])}"),
            "root": int(rec[2]),
            "shape": tuple(int(s) for s in
                           rec[5:5 + min(ndim, self.SHAPE_SLOTS)]),
            "dtype": chars(base, self.DTYPE_CHARS),
            "site": chars(base + self.DTYPE_CHARS, self.SITE_CHARS),
        }


def _call_site() -> str:
    """First stack frame outside this module (and outside contextlib —
    the guard is a generator context manager, so its immediate caller is
    ``contextlib.__enter__``): the caller-level source location the
    mismatch report names, kept to the trailing two path components so
    records fit the fixed digest slot."""
    skip = {os.path.abspath(__file__),
            os.path.abspath(contextlib.__file__)}
    f = sys._getframe(1)
    while f is not None and os.path.abspath(f.f_code.co_filename) in skip:
        f = f.f_back
    if f is None:
        return "<unknown>"
    parts = f.f_code.co_filename.replace(os.sep, "/").split("/")
    return "/".join(parts[-2:]) + f":{f.f_lineno}"


def _is_zombie(pid: int) -> bool:
    """True when /proc says the process is a zombie (Linux; False where
    /proc is unavailable — there kill(pid, 0) alone decides)."""
    try:
        with open(f"/proc/{pid}/stat", "rb") as f:
            data = f.read()
        # field 3, after the parenthesized comm (which may contain spaces)
        return data.rsplit(b")", 1)[1].split()[0] == b"Z"
    except (OSError, IndexError):
        return False


def pid_alive(pid: int) -> bool:
    """The process-liveness verdict of the failure detector, factored
    out so the serving fleet (serve/fleet.py) judges replica processes
    by the SAME discipline it judges ranks: ``kill(pid, 0)`` raising
    ``ProcessLookupError`` is death, EPERM is alive, and an unreaped
    ZOMBIE (dead child the detecting parent has not waited on) counts
    as dead for every communication purpose.  A pid that is merely
    slow ALWAYS answers alive — slow-not-dead is decided here, nowhere
    else."""
    if pid <= 0:
        return False
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except PermissionError:
        return True
    return not _is_zombie(pid)


class FailureDetector:
    """Per-rank heartbeat + pid liveness + the ``.ftx`` agreement board.

    The shared segment of the COLLECTIVE domain carries, per rank, a
    pid slot (written once at attach) and a heartbeat epoch (bumped by
    a daemon thread every ``SLU_TPU_HEARTBEAT_S``).  Liveness is judged
    by the *process*, not the heartbeat: ``os.kill(pid, 0)`` raising
    ``ProcessLookupError`` is the death verdict, so a rank whose
    heartbeat thread died with it is still detected — and a STALLED
    rank (alive pid, stale heartbeat) is never declared failed, only
    waited on (the slow-not-dead discipline; ``heartbeat_age`` is a
    gauge, not a verdict).

    The agreement board is a SIBLING shared-memory domain
    (``<name>.ftx``) used only through the wait-free post/peek
    primitives: each survivor publishes its observed dead-set into its
    OWN slot and polls the others — by construction nothing on this
    domain ever blocks on the dead rank, which is how the survivors
    converge on one dead-set (ULFM's revoke→agree shape) and all raise
    the same :class:`RankFailureError`.
    """

    BOARD_LEN = 4          # [MAGIC, epoch, dead-mask, pad]
    MAGIC = 7355.0

    def __init__(self, lib, name: bytes, n_ranks: int, rank: int,
                 create: bool, main_handle):
        if n_ranks > 52:
            raise ValueError("failure detector dead-mask rides the f64 "
                             f"mantissa: n_ranks {n_ranks} > 52")
        self._lib = lib
        self.name = bytes(name) + b".ftx"
        self.n_ranks = int(n_ranks)
        self.rank = int(rank)
        self._main = main_handle      # pid/hb slots live in the MAIN domain
        self._h = lib.slu_tree_attach(self.name, self.n_ranks,
                                      self.BOARD_LEN, self.rank,
                                      1 if create else 0)
        if not self._h:
            raise OSError(f"slu_tree_attach failed for detector domain "
                          f"{self.name!r}")
        self._created = bool(create)
        self._hb_stop = threading.Event()
        self._hb_thread = None
        # rank -> (last seen hb count, monotonic time it changed): the
        # heartbeat-age gauge's bookkeeping
        self._hb_seen: dict = {}

    # ---- heartbeat ------------------------------------------------------
    def start_heartbeat(self, interval: float) -> None:
        if self._hb_thread is not None or interval <= 0:
            return

        def run():
            m = get_metrics()
            while not self._hb_stop.wait(interval):
                h = self._h
                if h is None:
                    return
                self._lib.slu_tree_heartbeat(self._main)
                if m.enabled:
                    for r in range(self.n_ranks):
                        m.set("slu_heartbeat_age_seconds",
                              self.heartbeat_age(r), rank=str(r))

        self._hb_thread = threading.Thread(
            target=run, name="slu-heartbeat", daemon=True)
        self._hb_thread.start()

    def heartbeat_age(self, rank: int) -> float:
        """Seconds since ``rank``'s heartbeat epoch last advanced (0.0
        for my own rank and for counters seen to move this poll)."""
        now = time.monotonic()
        if rank == self.rank:
            return 0.0
        cur = int(self._lib.slu_tree_get_heartbeat(self._main, rank))
        seen = self._hb_seen.get(rank)
        if seen is None or seen[0] != cur:
            self._hb_seen[rank] = (cur, now)
            return 0.0
        return now - seen[1]

    # ---- liveness -------------------------------------------------------
    def pid(self, rank: int) -> int:
        return int(self._lib.slu_tree_get_pid(self._main, rank))

    def dead_ranks(self) -> set:
        """Ranks whose registered pid no longer exists.  A rank that
        never registered (pid 0) is UNKNOWN, not dead; a pid we may not
        signal (EPERM) is alive.  An unreaped ZOMBIE (a dead child
        whose parent — often the detecting test harness itself — has
        not waited on it yet) still answers ``kill(pid, 0)``, so on
        Linux the /proc state is consulted too: Z is dead for every
        communication purpose."""
        out = set()
        for r in range(self.n_ranks):
            if r == self.rank:
                continue
            p = self.pid(r)
            if p <= 0:
                continue
            if not pid_alive(p):
                out.add(r)
        return out

    # ---- agreement board ------------------------------------------------
    def post_failure(self, dead: set, epoch: int) -> None:
        buf = np.zeros(self.BOARD_LEN, dtype=np.float64)
        buf[0] = self.MAGIC
        buf[1] = float(epoch)
        buf[2] = float(sum(1 << int(r) for r in dead))
        self._lib.slu_tree_post(
            self._h, buf.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
            buf.size)

    def posted_failures(self, epoch: int) -> dict:
        """{rank: dead-set} of every peer that has posted a failure
        declaration for this epoch (non-blocking)."""
        out = {}
        buf = np.zeros(self.BOARD_LEN, dtype=np.float64)
        ptr = buf.ctypes.data_as(ctypes.POINTER(ctypes.c_double))
        for r in range(self.n_ranks):
            if r == self.rank:
                continue
            v = int(self._lib.slu_tree_peek(self._h, r, ptr, buf.size))
            if v <= 0 or buf[0] != self.MAGIC or int(buf[1]) != epoch:
                continue
            mask = int(buf[2])
            out[r] = {i for i in range(self.n_ranks) if mask >> i & 1}
        return out

    def close(self, unlink: bool | None = None) -> None:
        self._hb_stop.set()
        if self._hb_thread is not None:
            # bounded join (SLU110): the daemon wakes from its
            # stop-event wait immediately; never leave it racing the
            # segment unmap below or interpreter teardown
            self._hb_thread.join(2.0)
            self._hb_thread = None
        if self._h:
            if unlink is None:
                unlink = self._created
            self._lib.slu_tree_detach(self._h, self.name,
                                      1 if unlink else 0)
            self._h = None


class TreeComm:
    """One rank's attachment to a named tree-collective domain.

    Every participating process constructs TreeComm with the same name,
    n_ranks and max_len; rank 0 creates the segment.  All ranks must
    reach the collectives in the same order (the usual collective
    contract — the reference's trees are likewise matched per supernode).

    Rendezvous contract: the creator's constructor must COMPLETE before
    any attacher's starts (spawn workers after constructing the creator,
    as the tests do).  An attacher racing an in-flight create could bind
    a stale same-named segment from a crashed earlier run — the creator
    unlinks and re-creates exclusively, so such an attacher would wait
    on an orphan.  This happens-before requirement is what MPI_Init
    provides the reference for free; here it is the caller's.
    """

    def __init__(self, name: str, n_ranks: int, rank: int,
                 max_len: int = 4096, create: bool | None = None):
        lib = native._load()
        if lib is None:
            raise RuntimeError("native library unavailable for TreeComm")
        self._lib = lib
        self.name = name.encode() if isinstance(name, str) else name
        self.n_ranks = int(n_ranks)
        self.rank = int(rank)
        self.max_len = int(max_len)
        if create is None:
            create = rank == 0
        self._h = lib.slu_tree_attach(self.name, self.n_ranks,
                                      self.max_len, self.rank,
                                      1 if create else 0)
        if not self._h:
            raise OSError(f"slu_tree_attach failed for {name!r}")
        self._created = bool(create)
        # per-op comm telemetry (the PROFlevel≥1 comm split): every
        # native collective leg accounts calls/bytes/seconds here, split
        # by op kind; composite ops (allreduce, bcast_bytes/bcast_obj)
        # relabel their legs via _op_label so attribution follows the
        # caller's intent, not the transport decomposition
        self.comm_stats = CommStats()
        self._op_label = None
        # serving metrics (obs/metrics.py): latched once — the disabled
        # path costs ONE `is None` test per collective leg, allocates
        # nothing (the NULL_TRACER discipline)
        m = get_metrics()
        self._metrics = m if m.enabled else None
        # lockstep-verify mode (runtime SLU106): OFF means NO verifier
        # state at all — self._verifier stays None and the collective
        # path pays one attribute test (see _entered)
        from superlu_dist_tpu.utils.options import (env_flag, env_float,
                                                    env_int)
        self._verifier = None
        if env_flag("SLU_TPU_VERIFY_COLLECTIVES"):
            self._verifier = LockstepVerifier(lib, self.name, self.n_ranks,
                                              self.rank, bool(create))
            self._verifier.comm = self
        # rank-failure tolerance (ISSUE 8): register my pid in the shared
        # segment (peers poll it for liveness), and with a comm timeout
        # armed build the failure detector + heartbeat.  Timeout unset
        # (the default) keeps the legacy unbounded waits and allocates
        # NO detector state.
        lib.slu_tree_set_pid(self._h, os.getpid())
        self.epoch = 0                 # bumped by recovery rebuilds
        self.seq = 0                   # public collective count
        self._depth = 0                # public-entry nesting guard
        self._timeout_s = float(env_float("SLU_TPU_COMM_TIMEOUT_S"))
        self._retries = int(env_int("SLU_TPU_COMM_RETRIES"))
        self._detector = None
        if self._timeout_s > 0:
            self._detector = FailureDetector(lib, self.name, self.n_ranks,
                                             self.rank, bool(create),
                                             self._h)
            self._detector.start_heartbeat(env_float("SLU_TPU_HEARTBEAT_S"))
        # comm-layer chaos injection (testing/chaos.py kill_rank/stall_rank
        # specs), latched once — None is the production fast path; the
        # bind gives rank-scoped FACTOR-loop injections (kill_rank@group)
        # this process's distributed identity
        from superlu_dist_tpu.testing.chaos import bind_rank, get_comm_chaos
        bind_rank(self.rank, self.epoch)
        self._chaos = get_comm_chaos()

    @contextlib.contextmanager
    def _entered(self, op: str, shape, dtype, root: int):
        """Public-collective entry: ONE nesting-guarded hook where, at
        the outermost op only, (a) the comm-chaos injector ticks, (b) a
        peer's posted rank-failure is joined (so ranks that are sailing
        ahead of the stuck subtree still raise promptly), and (c) the
        SLU106 lockstep digest is exchanged.  Inner legs of composite
        ops skip all three — their structure is a deterministic function
        of the verified public op."""
        outer = self._depth == 0
        self._depth += 1
        try:
            if outer:
                self.seq += 1
                c = self._chaos
                if c is not None:
                    c.on_collective(self.seq,
                                    getattr(self, "chaos_rank", self.rank),
                                    self.epoch)
                if self._detector is not None:
                    self._join_posted(op)
            v = self._verifier
            if v is None or not outer:
                yield
            else:
                with v.guard(op, shape, str(dtype), root):
                    yield
        finally:
            self._depth -= 1

    # ---- bounded-wait transport policy ---------------------------------
    def _native_leg(self, kind: str, buf: np.ndarray, root: int,
                    handle=None, op_name: str | None = None) -> None:
        """One native tree leg.  Without a comm timeout this is the
        legacy unbounded spin.  With ``SLU_TPU_COMM_TIMEOUT_S`` armed,
        the leg waits at most the timeout, then consults the failure
        detector: a DEAD peer converts the hang into a
        :class:`RankFailureError` on every survivor (agreement via the
        .ftx board); a live peer is retried — indefinitely by default,
        or up to ``SLU_TPU_COMM_RETRIES`` before
        :class:`CommTimeoutError` (the slow-not-dead verdict never
        declares a live rank failed)."""
        h = self._h if handle is None else handle
        ptr = buf.ctypes.data_as(ctypes.POINTER(ctypes.c_double))
        lib = self._lib
        if self._detector is None:
            if kind == "bcast":
                lib.slu_tree_bcast(h, int(root), ptr, buf.size)
            else:
                lib.slu_tree_reduce_sum(h, int(root), ptr, buf.size)
            return
        fn = (lib.slu_tree_bcast_tw if kind == "bcast"
              else lib.slu_tree_reduce_sum_tw)
        op = op_name or kind
        m = self._metrics
        attempts = 0
        while True:
            rc = int(fn(h, int(root), ptr, buf.size,
                        float(self._timeout_s)))
            if rc == 0:
                return
            stuck = rc - 1          # == n_ranks: unidentified (ack drain)
            attempts += 1
            if m is not None:
                m.inc("slu_comm_timeouts_total", 1.0, op=op)
            dead = self._detector.dead_ranks()
            posted = self._detector.posted_failures(self.epoch)
            if dead or posted:
                self._rank_failure(op, dead)
            if self._retries and attempts >= self._retries:
                from superlu_dist_tpu.utils.errors import CommTimeoutError
                raise CommTimeoutError(op, stuck, self._timeout_s,
                                       attempts, seq=self.seq,
                                       site=_call_site())
            if m is not None:
                m.inc("slu_comm_retries_total", 1.0)

    def _join_posted(self, op: str) -> None:
        """Cheap board peek at public-collective entry: a peer already
        declared a failure for this epoch — join the agreement and raise
        here too, instead of discovering it only when MY leg eventually
        blocks on the stuck subtree."""
        if self._detector.posted_failures(self.epoch):
            self._rank_failure(op, set())

    def _rank_failure(self, op: str, dead: set):
        """Agreement + raise (never returns).  Converge on the union of
        every survivor's observed dead-set: post mine, merge the board
        and fresh pid scans, and wait (bounded by ~1 timeout) until
        every live peer has posted a matching set or died — then every
        survivor raises the SAME RankFailureError."""
        d = self._detector
        dead = set(dead) | d.dead_ranks()
        deadline = time.monotonic() + max(self._timeout_s, 0.5)
        posted_mask = None
        while True:
            posted = d.posted_failures(self.epoch)
            for peers in posted.values():
                dead |= peers
            if posted_mask != dead:
                d.post_failure(dead, self.epoch)
                posted_mask = set(dead)
                posted = d.posted_failures(self.epoch)
            live = [r for r in range(self.n_ranks)
                    if r != self.rank and r not in dead]
            # convergence on POSTS first: a survivor that already agreed
            # (posted this dead-set) and then exited — e.g. its caller
            # chose ft="abort" — must not be folded into THIS failure's
            # dead-set; only scan pids while still unconverged
            if all(posted.get(r) == dead for r in live):
                break
            dead |= d.dead_ranks()
            if time.monotonic() >= deadline:
                break               # late peers join via their own
            time.sleep(0.005)       # timeout or board check
        if self._metrics is not None:
            self._metrics.inc("slu_ft_failures_total", 1.0, op=op)
        from superlu_dist_tpu.utils.errors import RankFailureError
        raise RankFailureError(dead, op=op, seq=self.seq,
                               site=_call_site(), rank=self.rank,
                               n_ranks=self.n_ranks, epoch=self.epoch)

    def _account(self, op: str, nbytes: int, t0: float, root: int):
        """One collective leg completed: count it, and emit a comm span
        when tracing is enabled (no formatting otherwise)."""
        dt = time.perf_counter() - t0
        self.comm_stats.add(op, nbytes, dt)
        tr = get_tracer()
        if tr.enabled:
            tr.complete(f"tree-{op}", "comm", t0, dt, op=op,
                        bytes=int(nbytes), root=int(root), rank=self.rank,
                        n_ranks=self.n_ranks)
        m = self._metrics
        if m is not None:
            m.inc("slu_comm_calls_total", 1.0, op=op)
            m.inc("slu_comm_bytes_total", float(nbytes), op=op)
            m.observe("slu_comm_seconds", dt, op=op)

    def _prep(self, buf: np.ndarray) -> np.ndarray:
        out = np.ascontiguousarray(buf, dtype=np.float64)
        if out.size > self.max_len:     # a real check — the native side
            raise ValueError(           # memcpys into a max_len slot
                f"payload {out.size} > max_len {self.max_len}")
        return out

    def bcast(self, buf: np.ndarray, root: int = 0) -> np.ndarray:
        """Broadcast root's buf to every rank.  USE THE RETURN VALUE:
        when the input is contiguous float64 the operation is in place,
        otherwise the result lives in the returned copy."""
        buf = self._prep(buf)
        op = self._op_label or "bcast"
        with self._entered("bcast", buf.shape, buf.dtype, root):
            t0 = time.perf_counter()
            self._native_leg("bcast", buf, root)
            self._account(op, buf.nbytes, t0, root)
        return buf

    def reduce_sum(self, buf: np.ndarray, root: int = 0) -> np.ndarray:
        """Elementwise sum onto root (the RETURNED array holds the total
        on the root; see bcast for the in-place caveat)."""
        buf = self._prep(buf)
        op = self._op_label or "reduce"
        with self._entered("reduce_sum", buf.shape, buf.dtype, root):
            t0 = time.perf_counter()
            self._native_leg("reduce_sum", buf, root)
            self._account(op, buf.nbytes, t0, root)
        return buf

    @contextlib.contextmanager
    def _labeled(self, op: str):
        """Attribute nested collective legs to the composite op that
        issued them (outermost label wins)."""
        prev = self._op_label
        self._op_label = prev or op
        try:
            yield
        finally:
            self._op_label = prev

    def allreduce_sum(self, buf: np.ndarray, root: int = 0) -> np.ndarray:
        """reduce_sum then bcast — the composite the reference builds from
        its RdTree + BcTree pair per supernode."""
        with self._entered("allreduce_sum", np.shape(buf),
                            getattr(buf, "dtype", "float64"), root):
            with self._labeled("allreduce"):
                buf = self.reduce_sum(buf, root)
                return self.bcast(buf, root)

    # ---- typed payload layer -------------------------------------------
    # The native segment is f64 (the reference's trees are likewise typed,
    # TreeBcast_slu.hpp:34).  These wrappers carry any shape/dtype payload:
    # complex splits into re/im passes, integers ride the f64 mantissa
    # (exact below 2^53 — dimensions/indices are far below), and payloads
    # longer than max_len stream through in chunks.

    def _f64_op(self, flat: np.ndarray, root: int, op) -> np.ndarray:
        out = np.empty(flat.size, dtype=np.float64)
        step = self.max_len
        for lo in range(0, flat.size, step):
            hi = min(lo + step, flat.size)
            out[lo:hi] = op(np.ascontiguousarray(flat[lo:hi],
                                                 dtype=np.float64),
                            root=root)[:hi - lo]
        return out

    def _payload_op(self, arr: np.ndarray, root: int, op) -> np.ndarray:
        arr = np.asarray(arr)
        flat = arr.reshape(-1)
        if np.issubdtype(arr.dtype, np.complexfloating):
            re = self._f64_op(flat.real, root, op)
            im = self._f64_op(flat.imag, root, op)
            out = (re + 1j * im).astype(arr.dtype)
        else:
            out = self._f64_op(flat, root, op).astype(arr.dtype)
        return out.reshape(arr.shape)

    def bcast_any(self, arr: np.ndarray, root: int = 0) -> np.ndarray:
        """Broadcast a payload of any dtype/shape (returns a new array)."""
        arr = np.asarray(arr)
        with self._entered("bcast_any", arr.shape, arr.dtype, root):
            return self._payload_op(arr, root, self.bcast)

    def reduce_sum_any(self, arr: np.ndarray, root: int = 0) -> np.ndarray:
        """Sum-reduce a payload of any dtype/shape onto root."""
        arr = np.asarray(arr)
        with self._entered("reduce_sum_any", arr.shape, arr.dtype, root):
            return self._payload_op(arr, root, self.reduce_sum)

    def allreduce_sum_any(self, arr: np.ndarray, root: int = 0) -> np.ndarray:
        arr = np.asarray(arr)
        with self._entered("allreduce_sum_any", arr.shape, arr.dtype,
                            root):
            return self._payload_op(arr, root, self.allreduce_sum)

    # ---- byte / object layer -------------------------------------------
    # The native bcast is a pure memcpy through the f64 slots, so raw
    # bytes ride bit-exactly reinterpreted as float64 (no arithmetic ever
    # touches them — reductions would, so only broadcast is offered).

    def bcast_bytes(self, data: bytes | None, root: int = 0) -> bytes:
        """Broadcast a byte string from root (non-root passes None)."""
        # digest carries op/site/seq only: non-root ranks don't know the
        # length yet (the inner length bcast is depth-exempt)
        with self._entered("bcast_bytes", (), "bytes", root):
            with self._labeled("bcast_bytes"):
                return self._bcast_bytes(data, root)

    def _bcast_bytes(self, data: bytes | None, root: int = 0) -> bytes:
        if self.rank == root:
            n = len(data)
            payload = np.frombuffer(
                data + b"\0" * (-n % 8), dtype=np.float64)
        else:
            n = 0
            payload = None
        n = int(self.bcast_any(np.array([n], dtype=np.int64),
                               root=root)[0])
        if self.rank != root:
            payload = np.zeros((n + 7) // 8, dtype=np.float64)
        out = self._f64_op(payload, root, self.bcast)
        return out.tobytes()[:n]

    def bcast_obj(self, obj=None, root: int = 0):
        """Broadcast a picklable object from root (non-root passes None).
        Carries the analysis artifacts of the distributed-factors tier —
        the role the reference's MPI_Bcast of perm vectors plays
        (pdgssvx.c:816-831), widened to whole symbolic/plan structures.
        The root gets its ORIGINAL object back (no redundant second copy
        through pickle on the rank whose memory matters most)."""
        import pickle
        with self._entered("bcast_obj", (), "obj", root):
            blob = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL) \
                if self.rank == root else None
            data = self.bcast_bytes(blob, root=root)
            return obj if self.rank == root else pickle.loads(data)

    def close(self, unlink: bool | None = None):
        if self._h:
            if unlink is None:
                unlink = self._created
            if self._verifier is not None:
                self._verifier.close(unlink)
            if self._detector is not None:
                self._detector.close(unlink)
            self._lib.slu_tree_detach(self._h, self.name,
                                      1 if unlink else 0)
            self._h = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class FaultyTreeComm(TreeComm):
    """Fault-injection wrapper for the distributed tier's robustness tests.

    Simulates an unreliable transport at the *chunk* layer of the typed
    collectives (_f64_op — every bcast_any/reduce_sum_any/allreduce/
    bcast_obj payload streams through it):

      * reorder — a payload's chunks are delivered in a shuffled order
        (each result still lands in its own slice, the sequence-number
        reassembly a real transport would do);
      * drop    — a chunk's collective runs but its delivery is discarded;
        after a simulated timeout (`delay` seconds) the chunk is
        retransmitted, up to `max_retries` times — the timeout-with-retry
        discipline on collectives;
      * dup     — a chunk is delivered twice; the duplicate overwrites the
        same slice with the same data (idempotent receive).

    The fault schedule is a deterministic function of (seed, draw index)
    and every rank draws in the same order, so ALL ranks agree on which
    chunk operations run and how many times: faults perturb ordering and
    repetition, never collective matching (a mismatched schedule would
    deadlock the shared-memory trees, exactly like mismatched MPI
    collectives).  Counts land in .fault_counts.

    Enable via make_treecomm + SLU_TPU_FAULTS (see below) or construct
    directly in tests.
    """

    def __init__(self, name, n_ranks, rank, max_len: int = 4096,
                 create: bool | None = None, drop: float = 0.0,
                 dup: float = 0.0, reorder: float = 0.0,
                 delay: float = 0.0, seed: int = 0, max_retries: int = 3):
        super().__init__(name, n_ranks, rank, max_len=max_len,
                         create=create)
        self._p_drop = float(drop)
        self._p_dup = float(dup)
        self._p_reorder = float(reorder)
        self._delay = float(delay)
        self._max_retries = int(max_retries)
        # one stream, consumed in lock-step on every rank (all ranks make
        # the same collective calls with the same payload sizes)
        self._frng = np.random.default_rng(seed)
        self.fault_counts = {"drop": 0, "dup": 0, "reorder": 0}

    def _f64_op(self, flat: np.ndarray, root: int, op) -> np.ndarray:
        out = np.empty(flat.size, dtype=np.float64)
        step = self.max_len
        m = self._metrics
        offsets = list(range(0, flat.size, step))
        if len(offsets) > 1 and self._frng.random() < self._p_reorder:
            self._frng.shuffle(offsets)
            self.fault_counts["reorder"] += 1
            if m is not None:
                m.inc("slu_comm_faults_total", 1.0, fault="reorder")
        for lo in offsets:
            hi = min(lo + step, flat.size)
            for attempt in range(self._max_retries + 1):
                # each attempt re-slices the ORIGINAL payload: a
                # retransmission carries the same contribution, so the
                # reduction result is identical (idempotent resend)
                res = op(np.ascontiguousarray(flat[lo:hi],
                                              dtype=np.float64),
                         root=root)[:hi - lo]
                if (attempt < self._max_retries
                        and self._frng.random() < self._p_drop):
                    self.fault_counts["drop"] += 1
                    if m is not None:
                        m.inc("slu_comm_faults_total", 1.0, fault="drop")
                        m.inc("slu_comm_retries_total", 1.0)
                    if self._delay:
                        time.sleep(self._delay)   # the simulated timeout
                    continue
                break
            if self._frng.random() < self._p_dup:
                self.fault_counts["dup"] += 1
                if m is not None:
                    m.inc("slu_comm_faults_total", 1.0, fault="dup")
                res = op(np.ascontiguousarray(flat[lo:hi],
                                              dtype=np.float64),
                         root=root)[:hi - lo]
            out[lo:hi] = res
        return out


def parse_fault_spec(spec: str) -> dict:
    """Parse 'drop=0.1,dup=0.05,reorder=0.2,delay=0.001,seed=7' into
    FaultyTreeComm kwargs; unknown keys raise (a typo'd knob silently
    injecting nothing would defeat the test)."""
    out = {}
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        key, _, val = part.partition("=")
        key = key.strip()
        if key in ("seed", "max_retries"):
            out[key] = int(val)
        elif key in ("drop", "dup", "reorder", "delay"):
            out[key] = float(val)
        else:
            raise ValueError(f"unknown fault-injection knob {key!r}")
    return out


def make_treecomm(name, n_ranks, rank, max_len: int = 4096,
                  create: bool | None = None) -> TreeComm:
    """Env-gated TreeComm factory: with SLU_TPU_FAULTS set (e.g.
    'drop=0.2,reorder=0.2,seed=7') every attachment becomes a
    FaultyTreeComm — all ranks read the same environment, so the
    deterministic schedules agree.  Unset/empty: a plain TreeComm."""
    from superlu_dist_tpu.utils.options import env_str
    spec = env_str("SLU_TPU_FAULTS").strip()
    if not spec:
        return TreeComm(name, n_ranks, rank, max_len=max_len, create=create)
    return FaultyTreeComm(name, n_ranks, rank, max_len=max_len,
                          create=create, **parse_fault_spec(spec))
