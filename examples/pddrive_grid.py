#!/usr/bin/env python
"""Multi-process distributed-factors driver — the reference's canonical
`mpiexec -n 2 pddrive -r 1 -c 2 g20.rua` flow (EXAMPLE/pddrive.c:29):
every process owns a block of rows of A and b, the factorization and
solves run SPMD over the mesh spanning all the processes' devices, and
no process ever holds the whole factor (SRC/pddistribute.c:322).

This launcher forks the worker below once per rank (the mpiexec role);
each worker boots via parallel.mhboot (jax.distributed world + Gloo
timeout + compile cache), attaches the shared-memory tree domain for
the host-side analysis collectives, and calls `pgssvx(..., grid=...)`.

    python examples/pddrive_grid.py [matrix.rua] [--nproc 2]
                                    [--parsymb] [--resolve]

--parsymb selects the distributed analysis (options ParSymbFact: the
get_perm_c_parmetis + psymbfact shape, parallel/panalysis.py) — no
rank assembles the full graph or does the full symbolic work.
--resolve appends the reference's pddrive1 time-stepping loop: a
FACTORED re-solve with a new rhs on the SAME sharded factors, then a
SamePattern_SameRowPerm refactorization with new values (SYMBFACT and
DIST drop to ~0; EXAMPLE/pddrive1.c / pddrive2.c over NR_loc input).
"""

import glob
import os
import socket
import subprocess
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

_WORKER = r"""
import dataclasses
import sys
pid = int(sys.argv[1]); nproc = int(sys.argv[2]); port = sys.argv[3]
shm = sys.argv[4]; path = sys.argv[5]
parsymb = sys.argv[6] == "1"; resolve = sys.argv[7] == "1"
from superlu_dist_tpu.parallel.mhboot import boot, attach_tree
boot(nproc, pid, port)
import numpy as np
from superlu_dist_tpu.parallel.grid import gridinit_multihost
from superlu_dist_tpu.parallel.dist import distribute_rows
from superlu_dist_tpu.parallel.pgssvx import pgssvx
from superlu_dist_tpu.utils.options import Fact, Options

grid = gridinit_multihost(1, nproc)
if path == "@poisson2d":
    from superlu_dist_tpu.models.gallery import poisson2d
    a = poisson2d(20)
else:
    from superlu_dist_tpu.io import read_matrix
    a = read_matrix(path).tocsr()
n = a.n_rows
tc = attach_tree(shm, nproc, pid, max_len=1 << 16)

# this rank's block rows only (the NR_loc shape)
parts = distribute_rows(a, nproc)
mine = parts[pid]
xt = np.random.default_rng(0).standard_normal(n)
b = a.matvec(xt)
opts = Options(par_symb_fact=parsymb)
out = {}
x, info = pgssvx(tc, opts, mine,
                 b[mine.fst_row:mine.fst_row + mine.m_loc],
                 grid=grid, lu_out=out)
assert info == 0, info
resid = float(np.linalg.norm(b - a.matvec(x)) / np.linalg.norm(b))
big_lp, _ = max(out["lu"].numeric.fronts, key=lambda p: p[0].size)
assert len(big_lp.sharding.device_set) == nproc    # factors span ranks
print(f"rank {pid}: residual {resid:.2e}; largest front sharded over "
      f"{len(big_lp.sharding.device_set)} process devices"
      + (" [ParSymbFact analysis]" if parsymb else ""), flush=True)
assert resid < 1e-10, resid

if resolve:
    # pddrive1: same factors, new rhs — collective solve only
    lu = out["lu"]
    b2 = a.matvec(xt * 3.0)
    x2, info2 = pgssvx(tc, Options(fact=Fact.FACTORED), mine,
                       b2[mine.fst_row:mine.fst_row + mine.m_loc],
                       grid=grid, lu=lu)
    assert info2 == 0
    r2 = float(np.linalg.norm(b2 - a.matvec(x2)) / np.linalg.norm(b2))
    if parsymb:
        # a panalyze skeleton records no value-gather map, so the
        # SamePattern tiers are serial-analysis-only (analyze() raises
        # explicitly); the FACTORED tier above works on either skeleton
        print(f"rank {pid}: FACTORED re-solve {r2:.2e} "
              "(SamePattern reuse needs a serial-analysis skeleton)",
              flush=True)
        assert r2 < 1e-10
        tc.close(unlink=pid == 0)
        raise SystemExit(0)
    # pddrive2: same pattern + row perm, NEW VALUES — refactor with the
    # analysis products reused
    vals2 = np.asarray(mine.data) * 1.5
    mine2 = dataclasses.replace(mine, data=vals2)
    a2 = a.__class__(n, n, a.indptr, a.indices, a.data * 1.5)
    b3 = a2.matvec(xt)
    out3 = {}
    x3, info3 = pgssvx(tc, Options(fact=Fact.SamePattern_SameRowPerm),
                       mine2, b3[mine.fst_row:mine.fst_row + mine.m_loc],
                       grid=grid, lu=lu, lu_out=out3)
    assert info3 == 0
    r3 = float(np.linalg.norm(b3 - a2.matvec(x3)) / np.linalg.norm(b3))
    st = out3["stats"]
    print(f"rank {pid}: FACTORED re-solve {r2:.2e}; SamePattern "
          f"refactor {r3:.2e} (SYMBFACT {st.utime.get('SYMBFACT', 0):.2f}s "
          f"DIST {st.utime.get('DIST', 0):.2f}s)", flush=True)
    assert r2 < 1e-10 and r3 < 1e-10

tc.close(unlink=pid == 0)
"""

_REF_FIXTURE = "/root/reference/EXAMPLE/g20.rua"


def main():
    import argparse
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("matrix", nargs="?", default=None,
                    help="matrix file (HB/RB/MM); defaults to the "
                         "reference g20.rua fixture, else @poisson2d")
    ap.add_argument("--nproc", type=int, default=2)
    ap.add_argument("--parsymb", action="store_true",
                    help="distributed analysis (options ParSymbFact)")
    ap.add_argument("--resolve", action="store_true",
                    help="append the pddrive1/2 reuse legs")
    ap.add_argument("--backend", default=None,
                    help="accepted for _common.py symmetry; unused here")
    ns = ap.parse_args()          # rejects unknown --flags, supports '='
    nproc = ns.nproc
    if ns.matrix:
        path = ns.matrix
    elif os.path.exists(_REF_FIXTURE):
        path = _REF_FIXTURE
    else:
        path = "@poisson2d"        # generated fallback: always runs
    with socket.socket() as s:
        s.bind(("localhost", 0))
        port = s.getsockname()[1]
    import tempfile
    shm = f"/slu_exgrid_{os.getpid()}"
    rc = 0
    with tempfile.TemporaryDirectory() as td:
        wf = os.path.join(td, "worker.py")
        with open(wf, "w") as fh:
            fh.write(_WORKER)
        env = dict(os.environ, PYTHONPATH=os.path.join(
            os.path.dirname(os.path.abspath(__file__)), ".."))
        env.pop("XLA_FLAGS", None)
        procs = [subprocess.Popen(
            [sys.executable, wf, str(i), str(nproc), str(port), shm, path,
             "1" if ns.parsymb else "0", "1" if ns.resolve else "0"],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
            for i in range(nproc)]
        try:
            for i, p in enumerate(procs):
                # stay under CI's outer 600 s budget so a wedged rank is
                # reaped HERE (no orphaned grandchildren holding the shm)
                out, _ = p.communicate(timeout=480)
                txt = out.decode()
                print(txt.strip().splitlines()[-1] if txt.strip() else
                      f"rank {i}: (no output)")
                rc |= p.returncode
        finally:
            for p in procs:
                if p.poll() is None:
                    p.kill()
            for leftover in glob.glob(f"/dev/shm/*{shm.strip('/')}*"):
                try:
                    os.unlink(leftover)
                except OSError:
                    pass
    assert rc == 0, "a rank failed"
    print("pddrive_grid OK")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
