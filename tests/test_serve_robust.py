"""Serving-tier survival kit (serve/server.py reliability layer):
admission control / load shedding, per-request deadlines under a
stalled dispatcher, poisoned-request isolation (bitwise-preserving),
the per-ticket BERR gate, hot handle swap under traffic, factor-
integrity scrubbing with quarantine, drain semantics, and the
deterministic ServerClosedError delivery at close."""

import threading
import time

import numpy as np
import pytest

from superlu_dist_tpu.drivers.gssvx import gssvx
from superlu_dist_tpu.models.gallery import poisson2d
from superlu_dist_tpu.serve import (FactorCorruptError, ServeDeadlineError,
                                    ServeOverloadError, ServePoisonedError,
                                    ServerClosedError, SolveServer)
from superlu_dist_tpu.utils.errors import NumericBreakdownError
from superlu_dist_tpu.utils.options import IterRefine, Options

pytestmark = pytest.mark.serve


@pytest.fixture(scope="module")
def factored():
    a = poisson2d(10)
    rng = np.random.default_rng(0)
    xs = rng.standard_normal((a.n_rows, 70))
    bs = np.stack([a.matvec(xs[:, j]) for j in range(70)], axis=1)
    x, lu, stats, info = gssvx(
        Options(iter_refine=IterRefine.NOREFINE), a, bs[:, 0])
    assert info == 0
    return a, lu, bs, xs


def _refactor(a):
    b = a.matvec(np.ones(a.n_rows))
    x, lu, stats, info = gssvx(
        Options(iter_refine=IterRefine.NOREFINE), a, b)
    assert info == 0
    return lu


# ---------------------------------------------------------------------------
# admission control / shedding
# ---------------------------------------------------------------------------

def test_shed_at_queue_cap(factored):
    """A submit that would exceed SLU_TPU_SERVE_QUEUE_MAX columns is
    shed with a structured ServeOverloadError — it never queues, and
    already-admitted work still completes."""
    a, lu, bs, xs = factored
    srv = SolveServer(lu, queue_max=4, start=False)
    t1 = srv.submit(bs[:, :3])
    with pytest.raises(ServeOverloadError) as ei:
        srv.submit(bs[:, 3:6])          # 3 + 3 > 4
    assert ei.value.pending_cols == 3 and ei.value.queue_max == 4
    assert ei.value.reason == "queue_full"
    t2 = srv.submit(bs[:, 3])           # one more column still fits
    srv.start()
    np.testing.assert_allclose(t1.result(60), xs[:, :3],
                               rtol=1e-7, atol=1e-9)
    np.testing.assert_allclose(t2.result(60), xs[:, 3],
                               rtol=1e-7, atol=1e-9)
    st = srv.stats()
    assert st["shed"] == 1 and st["queue_depth"] == 0
    srv.close()


def test_shed_metric_and_env_knob(factored, monkeypatch):
    from superlu_dist_tpu.obs import metrics as metrics_mod
    a, lu, bs, xs = factored
    monkeypatch.setenv("SLU_TPU_SERVE_QUEUE_MAX", "2")
    m = metrics_mod.Metrics()
    prev = metrics_mod.install(m)
    try:
        srv = SolveServer(lu, start=False)
        assert srv.queue_max == 2
        srv.submit(bs[:, 0])
        with pytest.raises(ServeOverloadError):
            srv.submit(bs[:, 1:3])
        srv.start()
        srv.close()
    finally:
        metrics_mod.install(prev)
    snap = m.snapshot()
    assert snap["counters"].get(
        'slu_serve_shed_total{reason="queue_full"}') == 1.0


# ---------------------------------------------------------------------------
# deadlines
# ---------------------------------------------------------------------------

def test_deadline_expiry_under_stalled_dispatcher(factored):
    """With the dispatcher stalled (never started), an armed per-request
    deadline surfaces as ServeDeadlineError at the deadline — the waiter
    itself expires the request instead of hanging to its timeout."""
    a, lu, bs, xs = factored
    srv = SolveServer(lu, deadline_s=0.08, start=False)
    t = srv.submit(bs[:, 0])
    t0 = time.perf_counter()
    with pytest.raises(ServeDeadlineError) as ei:
        t.result(10)
    waited = time.perf_counter() - t0
    assert waited < 5.0, "expiry must come from the deadline, not timeout"
    assert ei.value.waited_s >= 0.08 and ei.value.columns == 1
    assert srv.stats()["deadline_miss"] == 1
    assert srv.stats()["queue_depth"] == 0    # expired work left the queue
    srv.close()


def test_dispatcher_expires_stale_requests_before_batching(factored):
    """The dispatcher sweeps expired requests out of the queue before
    carving a batch: a dead backlog never reaches the solver, live
    requests still do."""
    a, lu, bs, xs = factored
    srv = SolveServer(lu, deadline_s=0.05, start=False)
    dead = [srv.submit(bs[:, j]) for j in range(3)]
    time.sleep(0.12)                    # all three expire while stalled
    srv.start()
    live = srv.submit(bs[:, 3])         # fresh deadline, dispatcher live
    np.testing.assert_allclose(live.result(60), xs[:, 3],
                               rtol=1e-7, atol=1e-9)
    for t in dead:
        with pytest.raises(ServeDeadlineError):
            t.result(10)
    assert srv.stats()["deadline_miss"] == 3
    srv.close()


# ---------------------------------------------------------------------------
# poisoned-request isolation
# ---------------------------------------------------------------------------

def _serve_all(srv, cols):
    tickets = [srv.submit(c) for c in cols]
    srv.start()
    srv.flush()
    out = []
    for t in tickets:
        try:
            out.append(("ok", t.result(120)))
        except Exception as e:          # noqa: BLE001
            out.append(("err", e))
    return out


def test_poisoned_column_isolates_bitwise(factored):
    """One NaN column inside a coalesced 64-column micro-batch: exactly
    that ticket fails with ServePoisonedError naming its column, and
    every neighbor's X is BITWISE identical to an unpoisoned run."""
    a, lu, bs, xs = factored
    clean = SolveServer(lu, start=False)
    ref = _serve_all(clean, [bs[:, j] for j in range(64)])
    clean.close()
    assert all(kind == "ok" for kind, _ in ref)
    assert clean.stats()["batches"] == 1

    bp = bs.copy()
    bp[:, 17] = np.nan
    pois = SolveServer(lu, start=False)
    got = _serve_all(pois, [bp[:, j] for j in range(64)])
    assert pois.stats()["batches"] >= 1
    for j, (kind, val) in enumerate(got):
        if j == 17:
            assert kind == "err" and isinstance(val, ServePoisonedError)
            assert val.columns == [0]       # request-relative
            assert val.flightrec_dump is None  # flightrec off here
        else:
            assert kind == "ok"
            assert np.array_equal(val, ref[j][1]), \
                f"neighbor column {j} drifted"
    assert pois.stats()["poisoned_columns"] == 1
    pois.close()


def test_poisoned_columns_inside_wide_request(factored):
    """A multi-column request with one bad column fails alone, naming
    its request-relative column; the batch's other requests survive."""
    a, lu, bs, xs = factored
    wide = bs[:, :5].copy()
    wide[:, 3] = np.inf
    srv = SolveServer(lu, start=False)
    got = _serve_all(srv, [wide, bs[:, 10], bs[:, 11]])
    kind, err = got[0]
    assert kind == "err" and isinstance(err, ServePoisonedError)
    assert err.columns == [3]
    for (kind, val), j in zip(got[1:], (10, 11)):
        assert kind == "ok"
        np.testing.assert_allclose(val, xs[:, j], rtol=1e-7, atol=1e-9)
    srv.close()


def test_batch_raise_bisects_to_offending_ticket(factored):
    """When the batch solve RAISES NumericBreakdownError (instead of
    returning NaN), bisection pins the offending column and the healthy
    tickets are re-served at the original batch width — bitwise equal
    to an undisturbed run."""
    a, lu, bs, xs = factored
    clean = SolveServer(lu, start=False)
    ref = _serve_all(clean, [bs[:, j] for j in range(8)])
    clean.close()

    srv = SolveServer(lu, start=False)
    base = srv._solve

    def strict(mat):
        out = np.asarray(base(mat))
        if not np.isfinite(out).all():
            raise NumericBreakdownError(where="serve-test")
        return out

    srv._solve = strict
    bp = [bs[:, j].copy() for j in range(8)]
    bp[5][0] = np.nan
    got = _serve_all(srv, bp)
    for j, (kind, val) in enumerate(got):
        if j == 5:
            assert kind == "err" and isinstance(val, ServePoisonedError)
        else:
            assert kind == "ok" and np.array_equal(val, ref[j][1])
    srv.close()


def test_chaos_poison_rhs_spec(factored, monkeypatch):
    """SLU_TPU_CHAOS=poison_rhs=C NaNs the Cth submitted column
    deterministically — the injection drives the same isolation path."""
    a, lu, bs, xs = factored
    monkeypatch.setenv("SLU_TPU_CHAOS", "poison_rhs=5")
    srv = SolveServer(lu, start=False)
    got = _serve_all(srv, [bs[:, j] for j in range(8)])
    bad = [j for j, (kind, _) in enumerate(got) if kind == "err"]
    assert bad == [5]
    assert isinstance(got[5][1], ServePoisonedError)
    srv.close()


# ---------------------------------------------------------------------------
# BERR gate
# ---------------------------------------------------------------------------

def test_berr_gate_escalates_one_ticket_only(factored):
    """A ticket whose componentwise berr exceeds SLU_TPU_SERVE_BERR_MAX
    is routed through the per-ticket IR rung; its neighbors in the same
    micro-batch are untouched (no rung, no extra work)."""
    a, lu, bs, xs = factored
    srv = SolveServer(lu, berr_max=1e-6, start=False)
    base = srv._solve
    state = {"fired": False}

    def perturbed(mat):
        out = np.asarray(base(mat))
        if not state["fired"] and mat.shape[1] == 8:
            state["fired"] = True
            out = out.copy()
            out[:, 2] += 1e-2           # degrade exactly ticket 2
        return out

    srv._solve = perturbed
    tickets = [srv.submit(bs[:, j]) for j in range(8)]
    srv.start()
    srv.flush()
    res = [t.result(60) for t in tickets]
    assert state["fired"]
    assert len(tickets[2].rungs) == 1
    rung = tickets[2].rungs[0]
    assert rung["rung"] == "serve-ir" and rung["adopted"]
    assert rung["berr_before"] > 1e-6 > rung["berr_after"]
    assert all(not t.rungs for j, t in enumerate(tickets) if j != 2), \
        "only the degraded ticket may escalate"
    np.testing.assert_allclose(res[2], xs[:, 2], rtol=1e-8, atol=1e-10)
    assert srv.stats()["refined"] == 1
    srv.close()


def test_berr_gate_requires_matrix(factored):
    a, lu, bs, xs = factored
    import dataclasses
    bare = dataclasses.replace(lu, a=None)
    from superlu_dist_tpu.utils.errors import SuperLUError
    with pytest.raises(SuperLUError, match="original matrix"):
        SolveServer(bare, berr_max=1e-8, start=False)
    # passing the matrix explicitly satisfies the gate
    srv = SolveServer(bare, berr_max=1e-12, a=a, max_wait_s=0.0)
    srv.solve(bs[:, 0], timeout=60)
    srv.close()


# ---------------------------------------------------------------------------
# hot swap
# ---------------------------------------------------------------------------

def test_hot_swap_mid_traffic_loses_nothing(factored):
    """server.swap() under concurrent traffic: every ticket submitted
    before, during and after the swap resolves correctly — zero lost
    tickets — and the swap is visible in the stats."""
    a, lu, bs, xs = factored
    lu2 = _refactor(a)
    srv = SolveServer(lu, max_wait_s=0.001)
    errs, done = [], []
    stop = threading.Event()

    def client(seed):
        rng = np.random.default_rng(seed)
        while not stop.is_set():
            j = int(rng.integers(0, 64))
            try:
                got = srv.solve(bs[:, j], timeout=60)
                np.testing.assert_allclose(got, xs[:, j],
                                           rtol=1e-7, atol=1e-9)
                done.append(j)
            except Exception as e:      # noqa: BLE001
                errs.append(e)
                return

    ts = [threading.Thread(target=client, args=(s,)) for s in range(4)]
    for t in ts:
        t.start()
    time.sleep(0.05)
    srv.swap(lu2)
    time.sleep(0.05)
    stop.set()
    for t in ts:
        t.join(60)
    srv.close()
    assert not errs, errs
    assert len(done) > 0
    assert srv.stats()["swaps"] == 1
    assert srv.lu is lu2


def test_swap_validates_handle(factored):
    a, lu, bs, xs = factored
    from superlu_dist_tpu.utils.errors import SuperLUError
    srv = SolveServer(lu, start=False)
    import dataclasses
    with pytest.raises(SuperLUError, match="FACTORED"):
        srv.swap(dataclasses.replace(lu, numeric=None))
    big = poisson2d(11)
    with pytest.raises(SuperLUError, match="same-sized"):
        srv.swap(_refactor(big))
    srv.close()


def test_swap_from_bundle(factored, tmp_path):
    from superlu_dist_tpu.persist.serial import save_lu
    a, lu, bs, xs = factored
    d = str(tmp_path / "swap_handle")
    save_lu(_refactor(a), d)
    srv = SolveServer(lu, max_wait_s=0.0)
    srv.swap(d)
    assert srv.source == d
    np.testing.assert_allclose(srv.solve(bs[:, 0], timeout=60), xs[:, 0],
                               rtol=1e-7, atol=1e-9)
    srv.close()


# ---------------------------------------------------------------------------
# factor-integrity scrubbing
# ---------------------------------------------------------------------------

def _flip_front_byte(numeric, g=0, off=7):
    lp, up = numeric.fronts[g]
    buf = np.array(np.asarray(lp), copy=True)
    buf.view(np.uint8).reshape(-1)[off] ^= 0xFF
    numeric.fronts[g] = (buf, up)


def test_scrub_detects_flipped_byte_and_quarantines(factored):
    """A single flipped byte in a resident panel stack fails the next
    scrub: the handle quarantines (queued tickets errored, submits
    refused) and a fresh swap() restores service."""
    a, lu, bs, xs = factored
    lu2 = _refactor(a)
    srv = SolveServer(lu2, scrub_s=3600, start=False)  # baseline latched
    assert srv.scrub_now() == []                       # clean pass
    queued = srv.submit(bs[:, 0])
    _flip_front_byte(srv.lu.numeric)
    with pytest.raises(FactorCorruptError) as ei:
        srv.scrub_now()
    assert ei.value.groups == [0]
    with pytest.raises(FactorCorruptError):            # queued ticket too
        queued.result(10)
    with pytest.raises(FactorCorruptError):            # admission refused
        srv.submit(bs[:, 1])
    st = srv.stats()
    assert st["quarantined"] and st["scrub_failures"] == 1
    assert st["scrub_runs"] == 2
    # recovery: swap in a fresh handle, service resumes, scrub is clean
    srv.swap(_refactor(a))
    srv.start()
    np.testing.assert_allclose(srv.solve(bs[:, 0], timeout=60), xs[:, 0],
                               rtol=1e-7, atol=1e-9)
    assert srv.scrub_now() == []
    assert not srv.stats()["quarantined"]
    srv.close()


def test_scrub_baseline_from_bundle(factored, tmp_path):
    """from_bundle servers scrub against the bundle manifest's sha256
    digests — the durable ground truth — and a corruption of the
    resident copy is caught even though the bundle itself is intact."""
    from superlu_dist_tpu.persist.serial import bundle_front_digests, save_lu
    a, lu, bs, xs = factored
    d = str(tmp_path / "scrub_handle")
    save_lu(_refactor(a), d)
    srv = SolveServer.from_bundle(d, scrub_s=3600, start=False)
    assert srv._digests == bundle_front_digests(d)
    assert srv.scrub_now() == []
    _flip_front_byte(srv.lu.numeric, g=1)
    with pytest.raises(FactorCorruptError) as ei:
        srv.scrub_now()
    assert ei.value.groups == [1] and d in ei.value.source
    srv.close()


def test_chaos_corrupt_panel_spec(factored, monkeypatch):
    """SLU_TPU_CHAOS=corrupt_panel=F flips a byte in front group F's
    resident stack right before the scrub — the detection path end to
    end, with the flight-recorder postmortem attached when armed."""
    from superlu_dist_tpu.obs import flightrec
    a, lu, bs, xs = factored
    monkeypatch.setenv("SLU_TPU_CHAOS", "corrupt_panel=1")
    monkeypatch.setenv("SLU_TPU_FLIGHTREC", "1")
    flightrec._reset()
    try:
        srv = SolveServer(_refactor(a), scrub_s=3600, start=False)
        with pytest.raises(FactorCorruptError) as ei:
            srv.scrub_now()
        assert ei.value.groups == [1]
        assert ei.value.flightrec_dump        # postmortem dumped
        import os
        os.unlink(ei.value.flightrec_dump)
        srv.close()
    finally:
        monkeypatch.delenv("SLU_TPU_FLIGHTREC")
        flightrec._reset()


def test_scrub_background_thread_runs(factored):
    a, lu, bs, xs = factored
    srv = SolveServer(_refactor(a), scrub_s=0.05, start=False)
    deadline = time.perf_counter() + 10
    while srv.stats()["scrub_runs"] < 2:
        assert time.perf_counter() < deadline, "scrub thread never ran"
        time.sleep(0.02)
    srv.close()
    assert srv.stats()["scrub_failures"] == 0


# ---------------------------------------------------------------------------
# drain / close semantics
# ---------------------------------------------------------------------------

def test_drain_semantics(factored):
    """drain() finishes queued work, rejects new submissions with the
    structured draining error, and resume() lifts it."""
    a, lu, bs, xs = factored
    srv = SolveServer(lu, start=False)
    tickets = [srv.submit(bs[:, j]) for j in range(3)]
    srv.start()
    assert srv.drain(timeout=60)
    for t, j in zip(tickets, range(3)):
        np.testing.assert_allclose(t.result(10), xs[:, j],
                                   rtol=1e-7, atol=1e-9)
    with pytest.raises(ServeOverloadError) as ei:
        srv.submit(bs[:, 0])
    assert ei.value.reason == "draining"
    assert srv.stats()["draining"]
    srv.resume()
    np.testing.assert_allclose(srv.solve(bs[:, 0], timeout=60), xs[:, 0],
                               rtol=1e-7, atol=1e-9)
    srv.close()


def test_close_delivers_closed_error_to_stranded_tickets(factored):
    """The satellite bug fix: tickets that no dispatcher will ever serve
    (never-started server) receive ServerClosedError at close() instead
    of hanging their waiters."""
    a, lu, bs, xs = factored
    srv = SolveServer(lu, start=False)
    tickets = [srv.submit(bs[:, j]) for j in range(4)]
    srv.close()
    for t in tickets:
        with pytest.raises(ServerClosedError):
            t.result(5)


def test_submit_close_storm_never_hangs(factored):
    """Submit/close storm: concurrent submitters racing close() — every
    ticket either yields a result or a structured error within a bound;
    no waiter hangs (the close-window race regression)."""
    a, lu, bs, xs = factored
    for _ in range(3):                  # repeat to shake the race window
        srv = SolveServer(lu, max_wait_s=0.0)
        outcomes = []
        lock = threading.Lock()

        def client(seed):
            rng = np.random.default_rng(seed)
            for _ in range(8):
                j = int(rng.integers(0, 16))
                try:
                    t = srv.submit(bs[:, j])
                    got = t.result(30)
                    ok = np.allclose(got, xs[:, j], rtol=1e-6, atol=1e-8)
                    with lock:
                        outcomes.append("ok" if ok else "WRONG")
                except (ServerClosedError, ServeOverloadError):
                    with lock:
                        outcomes.append("closed")
                except TimeoutError:
                    with lock:
                        outcomes.append("HANG")

        ts = [threading.Thread(target=client, args=(s,))
              for s in range(6)]
        for t in ts:
            t.start()
        time.sleep(0.01)
        srv.close()
        for t in ts:
            t.join(60)
            assert not t.is_alive(), "submitter thread hung"
        assert "HANG" not in outcomes and "WRONG" not in outcomes, outcomes


def test_close_wins_over_inflight_swap(factored):
    """The close()/swap() ordering contract (ISSUE 14 satellite): a
    close() that takes the server lock while a swap is still preparing
    its target makes the swap raise ServerClosedError — the target is
    RELEASED (never installed), and every queued ticket got its
    deterministic ServerClosedError from close()'s purge."""
    a, lu, bs, xs = factored
    srv = SolveServer(_refactor(a), start=False)
    srv.scrub_now()                     # digest baseline → swap rebases
    in_swap = threading.Event()
    release = threading.Event()
    orig = srv._compute_digests

    def stalled_digests(lu_arg=None):
        in_swap.set()                   # swap is mid-flight, target not
        release.wait(10)                # yet installed
        return orig(lu_arg)

    srv._compute_digests = stalled_digests
    old_lu = srv.lu
    ticket = srv.submit(bs[:, 0])
    result = {}

    def do_swap():
        try:
            srv.swap(_refactor(a))
            result["r"] = "installed"
        except Exception as e:          # noqa: BLE001 — asserted below
            result["r"] = e

    th = threading.Thread(target=do_swap)
    th.start()
    assert in_swap.wait(10)
    srv.close(timeout=5)                # close wins: linearizes first
    release.set()
    th.join(10)
    assert not th.is_alive()
    assert isinstance(result["r"], ServerClosedError), result
    assert srv.lu is old_lu             # swap target released
    assert srv.stats()["swaps"] == 0
    with pytest.raises(ServerClosedError):
        ticket.result(5)                # delivered deterministically


def test_swap_after_close_raises(factored):
    """The degenerate ordering: a swap that starts after close() raises
    the same ServerClosedError (and a swap that installs BEFORE close
    simply completes — covered by test_hot_swap_* above)."""
    a, lu, bs, xs = factored
    srv = SolveServer(_refactor(a), start=False)
    srv.close()
    with pytest.raises(ServerClosedError):
        srv.swap(_refactor(a))


def test_chaos_slow_client_spec(factored, monkeypatch):
    """SLU_TPU_CHAOS=slow_client=T: the Tth ticket's client stalls
    before collecting — the server must close without waiting on it and
    the delivered result must outlive the server."""
    a, lu, bs, xs = factored
    monkeypatch.setenv("SLU_TPU_CHAOS", "slow_client=1,secs=0.2")
    srv = SolveServer(lu, max_wait_s=0.0)
    fast = srv.submit(bs[:, 0])
    slow = srv.submit(bs[:, 1])         # ticket index 1: the slow one
    np.testing.assert_allclose(fast.result(60), xs[:, 0],
                               rtol=1e-7, atol=1e-9)
    t0 = time.perf_counter()
    srv.close(timeout=30)               # must not block on the collector
    assert time.perf_counter() - t0 < 10
    got = slow.result(60)               # stalls ~0.2 s, then delivers
    assert time.perf_counter() - t0 >= 0.0
    np.testing.assert_allclose(got, xs[:, 1], rtol=1e-7, atol=1e-9)


# ---------------------------------------------------------------------------
# metrics
# ---------------------------------------------------------------------------

def test_reliability_metrics_series(factored):
    """The survival-kit series land in the registry: shed, deadline
    miss, poisoned, swaps, scrub runs/failures, queue-wait histogram."""
    from superlu_dist_tpu.obs import metrics as metrics_mod
    a, lu, bs, xs = factored
    m = metrics_mod.Metrics()
    prev = metrics_mod.install(m)
    try:
        srv = SolveServer(_refactor(a), queue_max=2, deadline_s=0.05,
                          scrub_s=3600, start=False)
        srv.scrub_now()
        srv.submit(bs[:, 0])
        with pytest.raises(ServeOverloadError):
            srv.submit(bs[:, 1:4])
        time.sleep(0.1)
        srv.start()
        srv.flush()
        time.sleep(0.05)
        bp = bs[:, :2].copy()
        bp[:, 1] = np.nan
        t = srv.submit(bp)
        with pytest.raises((ServePoisonedError, ServeDeadlineError)):
            t.result(30)
        srv.swap(_refactor(a))
        _flip_front_byte(srv.lu.numeric)
        with pytest.raises(FactorCorruptError):
            srv.scrub_now()
        srv.close()
    finally:
        metrics_mod.install(prev)
    snap = m.snapshot()
    c = snap["counters"]
    assert c.get('slu_serve_shed_total{reason="queue_full"}') == 1.0
    assert c.get("slu_serve_deadline_miss_total", 0) >= 1.0
    assert c.get("slu_serve_swaps_total") == 1.0
    assert c.get("slu_serve_scrub_runs_total") == 2.0
    assert c.get("slu_serve_scrub_failures_total") == 1.0
    wait = snap["histograms"].get("slu_serve_queue_wait_seconds")
    assert wait and wait["count"] >= 1


def test_poisoned_error_flightrec_postmortem(factored, monkeypatch):
    """ServePoisonedError construction dumps the flight recorder — the
    postmortem exists even when the caller swallows the error."""
    from superlu_dist_tpu.obs import flightrec
    a, lu, bs, xs = factored
    monkeypatch.setenv("SLU_TPU_FLIGHTREC", "1")
    flightrec._reset()
    try:
        srv = SolveServer(lu, start=False)
        bp = bs[:, 0].copy()
        bp[0] = np.nan
        got = _serve_all(srv, [bp])
        kind, err = got[0]
        assert kind == "err" and isinstance(err, ServePoisonedError)
        assert err.flightrec_dump
        import json
        import os
        doc = json.load(open(err.flightrec_dump))
        assert doc["reason"].startswith("ServePoisonedError")
        os.unlink(err.flightrec_dump)
        srv.close()
    finally:
        monkeypatch.delenv("SLU_TPU_FLIGHTREC")
        flightrec._reset()
