"""Machine-parameter and timer sanity probes.

Capability analog of the reference's INSTALL tests (INSTALL/dmachtst.c:
machine epsilon / underflow / overflow probes; INSTALL/timertst.c: timer
resolution), driven by install.csh.  Here they guard the assumptions the
GESP threshold arithmetic makes: thresh = sqrt(eps)·‖A‖ must be
representable and monotone in both working precisions, and the phase
timers must actually resolve the phases they time.
"""

import time

import numpy as np


def _probe_eps(dtype):
    """Smallest e with 1 + e != 1 — must match np.finfo."""
    one = dtype(1.0)
    e = dtype(1.0)
    while one + e / dtype(2.0) != one:
        e = e / dtype(2.0)
    return e


def test_machine_epsilon_f64():
    assert _probe_eps(np.float64) == np.finfo(np.float64).eps


def test_machine_epsilon_f32():
    assert _probe_eps(np.float32) == np.finfo(np.float32).eps


def test_underflow_overflow_bounds():
    for dt in (np.float32, np.float64):
        fi = np.finfo(dt)
        assert fi.tiny > 0 and np.isfinite(fi.tiny)
        assert np.isfinite(fi.max)
        with np.errstate(over="ignore"):
            assert np.isinf(dt(fi.max) * dt(2.0))
        # GESP threshold must stay representable across the anorm range
        for anorm in (fi.tiny, 1.0, fi.max ** 0.5):
            t = np.sqrt(fi.eps) * dt(anorm)
            assert np.isfinite(t) and t >= 0


def test_timer_resolution():
    """perf_counter must resolve well under one solver phase (~ms)."""
    res = time.get_clock_info("perf_counter").resolution
    assert res < 1e-4
    t0 = time.perf_counter()
    while time.perf_counter() == t0:
        pass
    assert time.perf_counter() - t0 < 1e-3


def test_stats_timer_accumulates():
    from superlu_dist_tpu.utils.stats import Stats
    s = Stats()
    with s.timer("FACT"):
        time.sleep(0.01)
    assert s.utime["FACT"] >= 0.009


# ---- compile-cache machine scoping (round-4 poisoned-cache class) --------

def test_machine_fingerprint_stable_and_scoped(tmp_path, monkeypatch):
    """The persistent compile cache must be keyed by a machine/toolchain
    fingerprint: XLA:CPU AOT entries written on a different machine hang
    multi-device runs (cpu_aot 'machine features don't match' / SIGILL
    class).  Scoping the directory makes foreign entries unreachable by
    construction — a foreign box's entries live under a different
    fingerprint and are never opened here."""
    import superlu_dist_tpu.utils.jaxcache as jc

    fp = jc.machine_fingerprint()
    assert fp == jc.machine_fingerprint()          # memoized + stable
    assert len(fp) == 10 and all(c in "0123456789abcdef" for c in fp)

    d = jc.cache_dir_for_machine(str(tmp_path))
    assert d == str(tmp_path / f"jax-mach-{fp}")

    # simulated foreign-entry injection: entries under another machine's
    # fingerprint directory must not be visible from this machine's dir
    foreign = tmp_path / "jax-mach-deadbeef00"
    foreign.mkdir()
    (foreign / "xla_aot_entry").write_bytes(b"\x90" * 64)
    import os
    assert not os.path.exists(d) or "xla_aot_entry" not in os.listdir(d)

    # the fingerprint reacts to the inputs it hashes (cpuinfo flags):
    # recompute with the memo cleared and a faked cpuinfo
    monkeypatch.setattr(jc, "_FP_CACHE", None)
    real_open = open

    def fake_open(path, *a, **k):
        if path == "/proc/cpuinfo":
            import io
            return io.StringIO("model name: other-cpu\nflags: none\n")
        return real_open(path, *a, **k)

    monkeypatch.setattr("builtins.open", fake_open)
    fp2 = jc.machine_fingerprint()
    monkeypatch.setattr(jc, "_FP_CACHE", None)
    assert fp2 != fp


def test_cache_dir_host_feature_stamp(tmp_path):
    """enable_compile_cache stamps the directory with the raw host
    features and refuses to reuse a directory stamped by a different
    host: a mismatch re-scopes to a feature-exact subdirectory (the
    poisoned entries are never opened) and bumps isa_mismatch_count —
    the counter the bench asserts stays 0 (BENCH_r05 'machine features
    don't match ... SIGILL' tail)."""
    import os

    import superlu_dist_tpu.utils.jaxcache as jc

    prior = jc.current_cache_dir()
    mine = str(tmp_path / "cache")
    try:
        base = jc.isa_mismatch_count()
        jc.enable_compile_cache(mine)
        stamp = os.path.join(mine, ".host_features")
        assert os.path.exists(stamp)
        assert open(stamp).read() == jc.host_features()
        # matching stamp: same dir, no mismatch recorded
        jc.enable_compile_cache(mine)
        assert jc.current_cache_dir() == mine
        assert jc.isa_mismatch_count() == base
        # foreign stamp: re-scope to a feature-exact subdir, count it
        with open(stamp, "w") as fh:
            fh.write("some-other-host|other-flags")
        jc.enable_compile_cache(mine)
        used = jc.current_cache_dir()
        assert used != mine and used.startswith(mine)
        assert os.path.basename(used).startswith("isa-")
        assert open(os.path.join(used, ".host_features")).read() \
            == jc.host_features()
        assert jc.isa_mismatch_count() == base + 1
    finally:
        if prior:
            jc.enable_compile_cache(prior)
        else:
            jc.disable_compile_cache()


def test_dryrun_throwaway_cache_never_outlives_its_directory(monkeypatch,
                                                             tmp_path):
    """dryrun_multichip uses a deliberately throwaway compile cache; on
    exit it must restore the caller's policy EXACTLY.  With a prior
    cache configured, that cache comes back; with none, the cache must
    end up DISABLED — the historical bug left the rmtree'd temp dir
    active, so a later same-process compile silently resurrected it and
    wrote/reloaded XLA:CPU AOT entries (ADVICE round 5)."""
    import importlib.util
    import os

    import superlu_dist_tpu.utils.jaxcache as jc

    path = os.path.join(os.path.dirname(__file__), "..",
                        "__graft_entry__.py")
    spec = importlib.util.spec_from_file_location("__graft_entry__", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    # the cache policy is what's under test, not the dryrun body
    monkeypatch.setattr(mod, "_dryrun_body", lambda n: None)

    prior = jc.current_cache_dir()
    try:
        # case 1: no prior cache -> disabled afterwards (and NOT the
        # temp dir, which no longer exists)
        jc.disable_compile_cache()
        mod.dryrun_multichip(2)
        after = jc.current_cache_dir()
        assert not after, after
        # case 2: a prior cache -> restored verbatim
        mine = str(tmp_path / "prior-cache")
        jc.enable_compile_cache(mine)
        mod.dryrun_multichip(2)
        assert jc.current_cache_dir() == mine
    finally:
        if prior:
            jc.enable_compile_cache(prior)
        else:
            jc.disable_compile_cache()
