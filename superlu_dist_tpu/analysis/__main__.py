"""slulint CLI — `python -m superlu_dist_tpu.analysis [paths...]`.

Exit codes: 0 = clean (or every finding baselined/suppressed),
1 = new findings, 2 = usage error.  Pure host-side text processing: no
jax import, safe anywhere, fast enough for a pre-commit hook (the CI
budget in scripts/ci_gates.sh is 10 s for the whole tree).

The scan is two-pass since v2: pass one builds the package-wide call
graph + dataflow summaries (analysis/callgraph.py, analysis/dataflow.py)
over every scanned file, pass two runs the rules with that project in
hand so SLU101/SLU103/SLU105 resolve cross-module indirection.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from superlu_dist_tpu.analysis import baseline as bl
from superlu_dist_tpu.analysis.core import (analyze_source, default_rules,
                                            read_sources)

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

DEFAULT_PATHS = ["superlu_dist_tpu", "scripts", "bench.py", "examples"]


def _build_parser():
    p = argparse.ArgumentParser(
        prog="python -m superlu_dist_tpu.analysis",
        description="slulint: project-native static analysis "
                    "(collective-safety SLU101, trace-purity SLU102, "
                    "index-width SLU103, env-knob registry SLU104, "
                    "jit-cache-key hygiene SLU105, jit-key shape "
                    "diversity SLU107, shared-mutable access SLU108, "
                    "lock-order/hold-discipline SLU109, thread "
                    "lifecycle SLU110, dispatch-loop host round-trips "
                    "SLU113, implicit downcast SLU115, accumulation "
                    "dtype SLU116, EFT purity SLU117, tolerance hygiene "
                    "SLU118, mesh/spec hygiene SLU120, dispatch-loop "
                    "cross-mesh transfers SLU122; the SLU106 runtime "
                    "twin lives in parallel/treecomm.py under "
                    "SLU_TPU_VERIFY_COLLECTIVES=1, the SLU109 runtime "
                    "twin in utils/lockwatch.py under "
                    "SLU_TPU_VERIFY_LOCKS=1, the program-level IR "
                    "rules SLU111/SLU112/SLU114 in utils/programaudit.py "
                    "under SLU_TPU_VERIFY_PROGRAMS=1, the SLU115/SLU116 "
                    "precision twin there too under "
                    "SLU_TPU_VERIFY_DTYPES=1, and the SLU119/SLU121 "
                    "sharding/peak-memory twin under "
                    "SLU_TPU_VERIFY_SHARDING=1 + "
                    "SLU_TPU_MEM_BUDGET_BYTES)")
    p.add_argument("paths", nargs="*", default=DEFAULT_PATHS,
                   help="files/directories to scan (default: the package, "
                        "scripts/, bench.py, examples/)")
    p.add_argument("--rules", default=None,
                   help="comma-separated rule ids to run (default: all)")
    p.add_argument("--baseline", default=None,
                   help="baseline JSON path (default: .slulint-baseline."
                        "json next to the repo root when present)")
    p.add_argument("--no-baseline", action="store_true",
                   help="ignore any baseline file")
    p.add_argument("--write-baseline", action="store_true",
                   help="write the current findings to the baseline and "
                        "exit 0")
    p.add_argument("--update-baseline", action="store_true",
                   help="prune baseline entries no longer matched by any "
                        "current finding (fixed findings), print the "
                        "drift, and exit 0 — never adds new entries")
    p.add_argument("--no-dataflow", action="store_true",
                   help="restore the PR-3 lexical-only behavior (no call "
                        "graph, no taint propagation) — for measuring "
                        "what the interprocedural tier adds")
    p.add_argument("--format", default=None, dest="fmt",
                   choices=("text", "json", "sarif"),
                   help="output format (default text; sarif = SARIF "
                        "2.1.0 for PR-annotation tooling)")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="machine-readable output (alias of --format json)")
    p.add_argument("--no-cache", action="store_true",
                   help="bypass the content-hash scan cache "
                        "(.slulint-cache.json) — reads AND writes")
    p.add_argument("--cache", default=None,
                   help="cache file path (default: .slulint-cache.json "
                        "next to the repo root)")
    p.add_argument("--list-rules", action="store_true",
                   help="print the rule catalog and exit")
    return p


def _update_baseline(baseline_path, findings, sources) -> int:
    """Drop baseline entries that no current finding matches (they were
    fixed) and report the drift.  New findings are NOT added — that is
    --write-baseline's explicit, deliberate act."""
    if not os.path.exists(baseline_path):
        print(f"no baseline at {baseline_path} — nothing to update")
        return 0
    entries = bl.load(baseline_path)
    new, matched = bl.filter_new(findings, sources, entries,
                                 root=_REPO_ROOT)
    kept = [bl.entry(f, sources[f.path], root=_REPO_ROOT) for f in matched]
    stale = len(entries) - len(kept)
    bl.write(baseline_path, kept)
    print(f"baseline {baseline_path}: {len(entries)} -> {len(kept)} "
          f"entries ({stale} stale pruned)")
    if new:
        print(f"note: {len(new)} NEW finding(s) not added (fix them or "
              "use --write-baseline deliberately):")
        for f in new:
            print("  " + f.render().splitlines()[0])
    return 0


def main(argv=None) -> int:
    args = _build_parser().parse_args(argv)
    rules = default_rules()
    if args.no_dataflow:
        from superlu_dist_tpu.analysis.rules_collective import CollectiveRule
        from superlu_dist_tpu.analysis.rules_index import IndexWidthRule
        from superlu_dist_tpu.analysis.rules_trace import JitCacheKeyRule
        for r in rules:
            if isinstance(r, (CollectiveRule, IndexWidthRule,
                              JitCacheKeyRule)):
                r.interprocedural = False
    if args.list_rules:
        for r in rules:
            print(f"{r.rule_id}  {r.title}")
        return 0
    if args.rules:
        wanted = {x.strip() for x in args.rules.split(",") if x.strip()}
        unknown = wanted - {r.rule_id for r in rules}
        if unknown:
            print(f"unknown rule id(s): {', '.join(sorted(unknown))}",
                  file=sys.stderr)
            return 2
        rules = [r for r in rules if r.rule_id in wanted]

    missing = [p for p in args.paths if not os.path.exists(p)]
    if missing:
        print(f"no such path(s): {', '.join(missing)}", file=sys.stderr)
        return 2

    sources = read_sources(args.paths)
    # incremental scan: a warm content-hash cache skips parse, call
    # graph, dataflow AND rules for the unchanged tree (analysis/
    # cache.py); a filtered rule set or the lexical tier bypasses it
    # (the cache stores full-default-scan results only)
    from superlu_dist_tpu.analysis import cache as sc
    cache_path = args.cache or os.path.join(_REPO_ROOT,
                                            sc.DEFAULT_CACHE_NAME)
    use_cache = not (args.no_cache or args.rules or args.no_dataflow)
    cache_state = "off"
    findings = None
    if use_cache:
        findings = sc.lookup(cache_path, sources, rules)
        if findings is not None:
            cache_state = "hit"
    if findings is None:
        project = None
        if not args.no_dataflow:
            from superlu_dist_tpu.analysis.callgraph import build_project
            project = build_project(sources)
        findings = []
        for path, source in sources.items():
            findings.extend(analyze_source(source, path, rules, project))
        if use_cache:
            sc.store(cache_path, sources, rules, findings)
            cache_state = "miss"

    baseline_path = args.baseline or os.path.join(
        _REPO_ROOT, bl.DEFAULT_BASELINE_NAME)
    if args.write_baseline:
        bl.write(baseline_path,
                 [bl.entry(f, sources[f.path], root=_REPO_ROOT)
                  for f in findings])
        print(f"wrote {len(findings)} finding(s) to {baseline_path}")
        return 0
    if args.update_baseline:
        return _update_baseline(baseline_path, findings, sources)

    baselined = []
    if not args.no_baseline and os.path.exists(baseline_path):
        entries = bl.load(baseline_path)
        findings, baselined = bl.filter_new(findings, sources, entries,
                                            root=_REPO_ROOT)

    fmt = args.fmt or ("json" if args.as_json else "text")
    if fmt == "json":
        print(json.dumps({
            "findings": [vars(f) for f in findings],
            "baselined": len(baselined),
            "cache": cache_state}, indent=1))
    elif fmt == "sarif":
        from superlu_dist_tpu.analysis.sarif import to_sarif
        print(json.dumps(to_sarif(findings, rules,
                                  baselined=len(baselined)), indent=1))
    else:
        for f in findings:
            print(f.render())
        tail = f" ({len(baselined)} baselined)" if baselined else ""
        cached = f" [cache {cache_state}]" if cache_state != "off" else ""
        print(f"slulint: {len(findings)} finding(s){tail} in "
              f"{len(sources)} file(s){cached}")
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
