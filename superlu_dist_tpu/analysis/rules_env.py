"""SLU104 — env-knob registry discipline.

Every environment variable the project reads must be declared in the
central knob registry (``utils/options.py:KNOB_REGISTRY``) — the single
source of truth that feeds the generated docs table, the
``SLU_TPU_STRICT_ENV=1`` typo guard, and this rule.  An undeclared read
is either a typo (silently-ignored knob — the classic wasted hardware
sweep) or a new knob that skipped registration (scattered parse points,
no docs row).

Flagged: ``os.environ.get('K')`` / ``os.environ['K']`` / ``os.getenv``
/ ``setdefault`` / ``'K' in os.environ`` with a literal key not in the
registry.  Writes (``os.environ['K'] = ...``) are exempt — exporting to
subprocesses is not a knob read.  Non-literal keys are exempt lexically;
the registry helpers cover them at runtime (env_int & co. raise
UnknownKnobError for unregistered names).
"""

from __future__ import annotations

import ast

from superlu_dist_tpu.analysis.core import Rule, is_env_read


def _registry_keys() -> frozenset:
    from superlu_dist_tpu.utils.options import KNOB_REGISTRY
    return frozenset(KNOB_REGISTRY)


class EnvKnobRule(Rule):
    rule_id = "SLU104"
    title = "env-knob-registry"
    hint = ("declare the knob in utils/options.py (register_knob) and "
            "read it via env_int/env_float/env_str/env_flag — that one "
            "registration feeds the docs table, SLU_TPU_STRICT_ENV typo "
            "detection, and this rule")

    def __init__(self, extra_keys=()):
        self._extra = frozenset(extra_keys)
        self._keys = None

    @property
    def keys(self) -> frozenset:
        if self._keys is None:
            self._keys = _registry_keys() | self._extra
        return self._keys

    def check(self, tree, source, path, project=None):
        findings = []
        for node in ast.walk(tree):
            env = is_env_read(node)
            if env is None:
                continue
            key, anchor = env
            if key is None or key in self.keys:
                continue
            findings.append(self.finding(
                path, anchor,
                f"env read of {key!r} which is not declared in the knob "
                "registry (utils/options.py)"))
        return findings
