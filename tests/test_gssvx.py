"""End-to-end driver tests.

Mirrors the reference's test strategy (SURVEY.md §4 / TEST/pdtest.c): real
small matrices, residual thresholds ‖b−Ax‖/(‖A‖·‖x‖·ε·m) < THRESH=20, a
sweep over option combinations and Fact-reuse modes, plus fabricated-xtrue
accuracy checks like the EXAMPLE drivers (dcreate_matrix.c:147-148).
"""

import os

import numpy as np
import pytest

from superlu_dist_tpu.drivers.gssvx import gssvx
from superlu_dist_tpu.io.readers import read_harwell_boeing
from superlu_dist_tpu.models.gallery import (
    poisson2d, poisson3d, random_sparse, convection_diffusion_2d)
from superlu_dist_tpu.utils.options import (
    Options, Fact, ColPerm, RowPerm, IterRefine)

REF = "/root/reference/EXAMPLE"
THRESH = 20.0


def resid_test(a, x, b):
    """pdcompute_resid analog (TEST/pdcompute_resid.c:18)."""
    r = b - a.matvec(x)
    eps = np.finfo(np.float64).eps
    denom = a.norm_inf() * np.linalg.norm(x, np.inf) * eps * a.n_rows
    return np.linalg.norm(r, np.inf) / max(denom, 1e-300)


def run_and_check(a, opts=None, nrhs=1, seed=0):
    n = a.n_rows
    rng = np.random.default_rng(seed)
    dtype = a.data.dtype
    xtrue = rng.standard_normal((n, nrhs)).astype(dtype)
    if np.issubdtype(dtype, np.complexfloating):
        xtrue = xtrue + 1j * rng.standard_normal((n, nrhs))
    xtrue = xtrue[:, 0] if nrhs == 1 else xtrue
    b = a.matvec(xtrue)
    opts = opts or Options()
    x, lu, stats, info = gssvx(opts, a, b)
    assert info == 0
    res = resid_test(a, x, b)
    assert res < THRESH, f"residual ratio {res} over threshold"
    return x, xtrue, lu, stats


def test_poisson2d_default():
    x, xtrue, lu, stats = run_and_check(poisson2d(12))
    np.testing.assert_allclose(x, xtrue, rtol=1e-8, atol=1e-8)
    assert stats.utime["FACT"] > 0 and stats.ops["FACT"] > 0


def test_poisson3d():
    run_and_check(poisson3d(6))


def test_unsymmetric_convection():
    run_and_check(convection_diffusion_2d(10, beta=100.0))


@pytest.mark.parametrize("colperm", [ColPerm.NATURAL, ColPerm.MMD_AT_PLUS_A,
                                     ColPerm.ND_AT_PLUS_A])
@pytest.mark.parametrize("rowperm", [RowPerm.NOROWPERM, RowPerm.LargeDiag_MC64])
def test_option_sweep(colperm, rowperm):
    """The pdtest-style parameter sweep (TEST/CMakeLists.txt:9-18)."""
    a = random_sparse(60, density=0.05, seed=11)
    opts = Options(col_perm=colperm, row_perm=rowperm)
    run_and_check(a, opts)


@pytest.mark.parametrize("nrhs", [1, 3])
def test_multiple_rhs(nrhs):
    run_and_check(poisson2d(8), nrhs=nrhs, seed=3)


def test_needs_pivoting_matrix():
    """A matrix whose natural diagonal is terrible: matching must fix it."""
    n = 50
    rng = np.random.default_rng(4)
    # permuted diagonal: A[perm[i], i] large, diagonal tiny/zero
    perm = rng.permutation(n)
    rows = np.concatenate([perm, rng.integers(0, n, 150)])
    cols = np.concatenate([np.arange(n), rng.integers(0, n, 150)])
    vals = np.concatenate([10.0 + rng.random(n), 0.1 * rng.standard_normal(150)])
    from superlu_dist_tpu.sparse.formats import coo_to_csr
    a = coo_to_csr(n, n, rows, cols, vals)
    run_and_check(a)


def test_fact_reuse_modes():
    a = poisson2d(9)
    n = a.n_rows
    b1 = np.ones(n)
    b2 = np.arange(n, dtype=np.float64)
    opts = Options()
    x1, lu, stats, _ = gssvx(opts, a, b1)

    # FACTORED: same A, new b — solve only (pddrive1 scenario)
    opts_f = Options(fact=Fact.FACTORED)
    x2, lu, stats2, _ = gssvx(opts_f, a, b2, lu=lu)
    np.testing.assert_allclose(a.matvec(x2), b2, atol=1e-8)
    assert stats2.utime["FACT"] == 0

    # SamePattern_SameRowPerm: new values, same pattern (pddrive3 scenario)
    a3 = poisson2d(9)
    a3.data = a3.data * 2.0
    opts_s = Options(fact=Fact.SamePattern_SameRowPerm)
    x3, lu3, stats3, _ = gssvx(opts_s, a3, b1, lu=lu)
    np.testing.assert_allclose(a3.matvec(x3), b1, atol=1e-8)
    assert lu3.sf is lu.sf          # symbolic reused
    assert lu3.plan is lu.plan      # plan reused

    # SamePattern: new values, may re-pivot rows (pddrive2 scenario)
    opts_p = Options(fact=Fact.SamePattern)
    x4, lu4, _, _ = gssvx(opts_p, a3, b2, lu=lu)
    np.testing.assert_allclose(a3.matvec(x4), b2, atol=1e-8)
    assert lu4.col_order is lu.col_order


def test_f32_factor_with_f64_refinement():
    """The TPU mixed-precision design: f32 factors + IR reach f64 accuracy."""
    a = poisson2d(10)
    opts = Options(factor_dtype="float32")
    x, xtrue, lu, stats = run_and_check(a, opts)
    r = a.matvec(x) - a.matvec(xtrue)
    rel = np.linalg.norm(r) / np.linalg.norm(a.matvec(xtrue))
    assert rel < 1e-10
    assert stats.refine_steps >= 1


def test_no_refine_option():
    a = poisson2d(6)
    opts = Options(iter_refine=IterRefine.NOREFINE)
    run_and_check(a, opts)


def test_complex_end_to_end():
    a = random_sparse(40, density=0.08, seed=6, dtype=np.complex128)
    run_and_check(a)


def test_complex64_factor_with_refinement():
    """The TPU-class complex path: c64 factors + c128 IR must recover full
    accuracy (the z-twin of the f32+IR design; reference SRC/pzgstrf.c)."""
    a = random_sparse(60, density=0.08, seed=7, dtype=np.complex128)
    opts = Options(factor_dtype="float32")     # maps to complex64 factors
    x, xtrue, lu, stats = run_and_check(a, opts)
    assert str(lu.numeric.dtype) == "complex64"
    np.testing.assert_allclose(x, xtrue, rtol=1e-9, atol=1e-9)
    assert stats.refine_steps >= 1


def test_complex64_device_solver_matches_host():
    """DeviceSolver on complex factors (the pzgstrs analog path)."""
    from superlu_dist_tpu.solve.device import DeviceSolver
    from superlu_dist_tpu.solve.trisolve import lu_solve
    a = random_sparse(50, density=0.1, seed=8, dtype=np.complex128)
    opts = Options(iter_refine=IterRefine.NOREFINE, factor_dtype="float64")
    b = np.ones(a.n_rows, dtype=np.complex128)
    x, lu, stats, info = gssvx(opts, a, b)
    assert info == 0
    rng = np.random.default_rng(4)
    d = rng.standard_normal(a.n_rows) + 1j * rng.standard_normal(a.n_rows)
    got = DeviceSolver(lu.numeric).solve(d)
    want = lu_solve(lu.numeric, d)
    np.testing.assert_allclose(got, want, rtol=1e-9, atol=1e-11)


def test_exact_singularity_reported_without_replacement():
    """ReplaceTinyPivot=NO + singular A => info>0, like pdgstrf.c:234-241."""
    from superlu_dist_tpu.sparse.formats import coo_to_csr
    z = coo_to_csr(2, 2, [0, 0, 1, 1], [0, 1, 0, 1], np.ones(4))  # rank 1
    opts = Options(replace_tiny_pivot=False, row_perm=RowPerm.NOROWPERM,
                   equil=False, iter_refine=IterRefine.NOREFINE)
    x, lu, stats, info = gssvx(opts, z, np.ones(2))
    assert info > 0 and x is None


def test_pattern_mismatch_reuse_raises():
    """Reusing a factorization against a different sparsity pattern must
    raise, not silently produce wrong factors."""
    a = random_sparse(36, density=0.08, seed=1)
    _, lu, _, _ = gssvx(Options(), a, np.ones(36))
    other = random_sparse(36, density=0.12, seed=2)   # same n, new pattern
    with pytest.raises(Exception):
        gssvx(Options(fact=Fact.SamePattern_SameRowPerm), other,
              np.ones(36), lu=lu)


@pytest.mark.skipif(not os.path.exists(f"{REF}/g20.rua"), reason="no fixtures")
def test_g20_rua():
    """The reference CI's canonical matrix (.travis_tests.sh)."""
    a = read_harwell_boeing(f"{REF}/g20.rua").tocsr()
    x, xtrue, lu, stats = run_and_check(a)
    err = np.linalg.norm(x - xtrue, np.inf) / np.linalg.norm(x, np.inf)
    assert err < 1e-8        # pdinf_norm_error analog


@pytest.mark.skipif(not os.path.exists(f"{REF}/cg20.cua"), reason="no fixtures")
@pytest.mark.slow
def test_cg20_cua_complex():
    a = read_harwell_boeing(f"{REF}/cg20.cua").tocsr()
    run_and_check(a)


@pytest.mark.skipif(not os.path.exists(f"{REF}/big.rua"), reason="no fixtures")
@pytest.mark.slow
def test_big_rua():
    a = read_harwell_boeing(f"{REF}/big.rua").tocsr()
    x, xtrue, lu, stats = run_and_check(a)
    err = np.linalg.norm(x - xtrue, np.inf) / np.linalg.norm(x, np.inf)
    assert err < 1e-6


@pytest.mark.slow
def test_bfloat16_factors_recover_f64_residual():
    """bf16 factorization (the MXU's native-rate mode) + f64 IR must still
    reach reference accuracy on a well-conditioned system — the GESP+IR
    design stretched to 8 mantissa bits (SURVEY.md §7 hard-part 1)."""
    from superlu_dist_tpu.models.gallery import poisson2d
    a = poisson2d(14)
    xt = np.random.default_rng(5).standard_normal(a.n_rows)
    b = a.matvec(xt)
    x, lu, stats, info = gssvx(Options(factor_dtype="bfloat16"), a, b)
    assert info == 0
    r = np.linalg.norm(b - a.matvec(x)) / np.linalg.norm(b)
    assert r < 1e-12, r
    assert stats.refine_steps > 2   # bf16 genuinely needs the IR


@pytest.mark.slow
def test_helmholtz_and_anisotropic_end_to_end():
    """Indefinite complex (Helmholtz) and anisotropic diffusion classes
    through the full pipeline — the model-family breadth the reference's
    fixture set exercises."""
    from superlu_dist_tpu.models.gallery import (helmholtz_2d,
                                                 anisotropic_poisson_2d)
    for a in (helmholtz_2d(12), anisotropic_poisson_2d(12)):
        rng = np.random.default_rng(0)
        xt = rng.standard_normal(a.n_rows).astype(a.data.dtype)
        if np.iscomplexobj(a.data):
            xt = xt + 1j * rng.standard_normal(a.n_rows)
        b = a.matvec(xt)
        x, lu, stats, info = gssvx(Options(), a, b)
        assert info == 0
        r = np.linalg.norm(b - a.matvec(x)) / np.linalg.norm(b)
        assert r < 1e-12, (a.data.dtype, r)


@pytest.mark.slow
def test_int64_index_configuration():
    """SLU_TPU_INT64=1 switches every index to 64-bit (the reference's
    XSDK_INDEX_SIZE=64 build, superlu_defs.h:80-93) — verified in a
    subprocess so the env snapshot is honored from import."""
    import subprocess
    import sys
    code = """
import jax; jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)
import numpy as np
from superlu_dist_tpu.sparse import formats
assert formats.INT == np.int64, formats.INT
import superlu_dist_tpu as slu
from superlu_dist_tpu.models.gallery import poisson2d
a = poisson2d(10)
b = a.matvec(np.ones(a.n_rows))
x, lu, stats, info = slu.gssvx(slu.Options(), a, b)
assert info == 0
r = float(np.linalg.norm(b - a.matvec(x)) / np.linalg.norm(b))
assert r < 1e-12, r
print("INT64 OK", r)
"""
    env = dict(os.environ, SLU_TPU_INT64="1")
    r = subprocess.run([sys.executable, "-c", code], env=env,
                       capture_output=True, timeout=600,
                       cwd=os.path.join(os.path.dirname(__file__), ".."))
    assert r.returncode == 0, r.stdout.decode() + r.stderr.decode()
    assert b"INT64 OK" in r.stdout


def test_ill_conditioned_f32_ir_behavior():
    """Precision-boundary documentation test: with f32 factors, IR
    converges while kappa(A)*eps_f32 < 1 and the berr history reports
    honestly when it cannot (the GESP contract — the reference relies on
    the same IR safety net, pdgsrfs.c:232)."""
    from superlu_dist_tpu.models.gallery import poisson2d
    n = 0
    a = poisson2d(12)
    d = a.to_dense()
    # scale rows geometrically to raise the condition number (~1e6)
    s = np.logspace(0, 6, a.n_rows)
    import superlu_dist_tpu.sparse.formats as fmts
    rows = np.repeat(np.arange(a.n_rows), np.diff(a.indptr))
    ac = fmts.SparseCSR(a.n_rows, a.n_cols, a.indptr, a.indices,
                        a.data * s[rows])
    xt = np.random.default_rng(0).standard_normal(a.n_rows)
    b = ac.matvec(xt)
    x, lu, stats, info = gssvx(Options(factor_dtype="float32"), ac, b)
    assert info == 0
    r = np.linalg.norm(b - ac.matvec(x)) / np.linalg.norm(b)
    # equilibration + matching + f32 factors + f64 IR must still deliver
    # a backward-stable solution at kappa ~ 1e6
    assert r < 1e-10, r
    assert lu.berrs, "refinement must have run"
