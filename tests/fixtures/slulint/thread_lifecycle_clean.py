"""SLU110 clean negative: dependencies assigned before start(), the
daemon joined with a bounded timeout, every event both set and
waited."""
import threading


class Daemon:
    def __init__(self):
        self._stop = threading.Event()
        self._interval = 0.5
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def _loop(self):
        while not self._stop.wait(self._interval):
            pass

    def close(self):
        self._stop.set()
        self._thread.join(1.0)
