#!/usr/bin/env bash
# slulint CI gate: exit 1 on any finding that is neither inline-suppressed
# (# slulint: disable=SLUxxx with a justification) nor grandfathered in
# the committed baseline (.slulint-baseline.json — target state: empty).
#
# Pure host-side AST analysis, no jax import: a cold whole-tree scan is
# ~5-7 s (interprocedural + concurrency + device lattices); REPEAT scans
# of an unchanged tree are sub-second via the content-hash result cache
# (.slulint-cache.json, analysis/cache.py) — the gates share ONE scan
# per content state.  `--no-cache` forces a fresh scan; `--format sarif`
# passes through for PR-annotation tooling.  The 60 s timeout is a hard
# ceiling (a slow scan is itself a regression — rules must stay
# lexical).
#
# One gate of scripts/ci_gates.sh (the consolidated CI entry point).
# Shared gate contract: non-zero exit on ANY regression, diagnostics on
# stdout/stderr, hard timeout.  Scope: the package, scripts/, bench.py
# AND examples/ (the CLI's default path set).
set -euo pipefail
cd "$(dirname "$0")/.."

exec timeout -k 5 60 python -m superlu_dist_tpu.analysis \
  superlu_dist_tpu/ scripts/ bench.py examples/ "$@"
