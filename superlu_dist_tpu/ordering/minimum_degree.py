"""Minimum-degree fill-reducing ordering.

Capability analog of the reference's MMD (genmmd_dist_, SRC/mmd.c, 1025 LoC
of f2c'd Fortran) dispatched for ColPerm=MMD_AT_PLUS_A
(SRC/get_perm_c.c:463-530).  This is a fresh implementation of exact-external-
degree minimum degree on a quotient graph with element absorption — not a
translation — in Python for now (C++ accelerator planned).  Intended for
small/medium graphs and test leaves; large problems should use nested
dissection (ordering.dissection).
"""

from __future__ import annotations

import heapq

import numpy as np


def minimum_degree(n: int, indptr: np.ndarray, indices: np.ndarray) -> np.ndarray:
    """Return an elimination order (order[k] = k-th pivot, old index).

    Input is the symmetric adjacency pattern (diagonal ignored).  Uses the
    native implementation (slu_host.cpp slu_mmd — same algorithm and
    tie-breaking, compiled) when available; this Python version is the
    specification and fallback.
    """
    from superlu_dist_tpu import native
    order = native.mmd(n, indptr, indices)
    if order is not None:
        return order

    adj = [set() for _ in range(n)]
    for i in range(n):
        for j in indices[indptr[i]:indptr[i + 1]]:
            j = int(j)
            if j != i:
                adj[i].add(j)
                adj[j].add(i)

    var_elems = [set() for _ in range(n)]   # elements adjacent to variable
    elem_vars = {}                           # element id -> variable set
    alive = np.ones(n, dtype=bool)
    order = np.empty(n, dtype=np.int64)

    def external(v):
        s = set(adj[v])
        for e in var_elems[v]:
            s |= elem_vars[e]
        s.discard(v)
        return s

    heap = [(len(adj[v]), v) for v in range(n)]
    heapq.heapify(heap)
    degree = np.array([len(adj[v]) for v in range(n)], dtype=np.int64)

    for k in range(n):
        while True:
            d, v = heapq.heappop(heap)
            if alive[v] and d == degree[v]:
                break
        order[k] = v
        alive[v] = False
        le = external(v)                 # the new element's variable set
        # absorb v's elements
        for e in var_elems[v]:
            del elem_vars[e]
        eid = n + k
        elem_vars[eid] = le
        absorbed = set(var_elems[v])
        for u in le:
            adj[u].discard(v)
            adj[u] -= le                 # edges now covered by the element
            var_elems[u] -= absorbed
            var_elems[u].add(eid)
            s = set(adj[u])
            for e in var_elems[u]:
                s |= elem_vars[e]
            s.discard(u)
            nd = len(s)
            if nd != degree[u]:
                degree[u] = nd
                heapq.heappush(heap, (nd, u))
            else:
                heapq.heappush(heap, (nd, u))
    return order
