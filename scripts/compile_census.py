#!/usr/bin/env python
"""Compile census report: which shape-key buckets dominate cold compile.

The diagnostic ROADMAP item 3 needs before anyone attempts the bucketed
mega-kernel: the n=110592 TPU factor died inside factor-compile
(BENCH_r02, 119 kernels / 455 groups) with no record of which buckets
ate the budget.  This script aggregates compile-census evidence from
any of the artifacts the telemetry layer now produces, or measures the
exact trace/lower/compile stage split live.

Usage:
  compile_census.py ARTIFACT [ARTIFACT ...]
      Aggregate ``compile`` records from any mix of:
        * obs trace artifacts (Chrome trace JSON or the JSONL sidecar,
          SLU_TPU_TRACE) — the ``compile``-category spans;
        * bench JSON rows — the ``compile_census`` field;
        * flight-recorder dumps — the embedded ``compile`` block.
  compile_census.py --live [NX]
      Build the bench plan for a poisson3d grid of edge NX (default 8)
      on the CPU backend and AOT-stage every distinct streamed-executor
      shape key, timing jaxpr trace, StableHLO lowering, and XLA
      compile SEPARATELY per bucket (the exact split the in-band census
      approximates with first-call wall time).  CPU compile cost ranks
      buckets the same way the TPU tunnel does, ~proportionally.

  compile_census.py --buckets [NX ...] [--stage]
      The compile-BUDGET check (ci_gates.sh gate `compile-budget`):
      build the CLOSED bench plan (SLU_TPU_BUCKET_CLOSED semantics,
      numeric/plan._close_shape_keys) for a gallery of poisson3d sizes
      (default 16 32 48 — n = 4096 / 32768 / 110592, the BENCH_r02
      acceptance ladder) and FAIL (exit 1) unless the mega executor's
      compiled-program count is CONSTANT in n.  This is the invariant
      that killed BENCH_r02: the streamed kernel count grew with the
      matrix (119 kernels at n=110592) until compile time, not
      arithmetic, was the scaling wall.  --stage additionally
      AOT-stages (trace+lower, no backend compile) every bucket
      program, proving the closed set is buildable.

Output: per-bucket ranked table (seconds, share, builds, disk hits, and
— when the sharding twin audited the programs or --live staged them —
the SLU121 static peak-live-bytes estimate as a ``peak MiB`` column)
and the totals line.  Exit 1 when no census evidence is found.
"""

import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


# ---------------------------------------------------------------------------
# artifact parsing
# ---------------------------------------------------------------------------

def _iter_events(text: str):
    """Trace events from a Chrome trace JSON or JSONL sidecar, or None."""
    text = text.strip()
    if not text:
        return None
    if text.startswith("{"):
        try:
            doc = json.loads(text)
        except json.JSONDecodeError:
            doc = None
        if isinstance(doc, dict) and isinstance(doc.get("traceEvents"),
                                                list):
            return doc["traceEvents"]
        if isinstance(doc, dict):
            return None                # handled by the dict sniffers
    events = []
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        try:
            ev = json.loads(line)
        except json.JSONDecodeError:
            return None
        if not isinstance(ev, dict) or "cat" not in ev:
            return None
        events.append(ev)
    return events or None


def rows_from_artifact(path: str) -> list:
    """[{site, key, seconds, builds, persistent_hits}] from one file, []
    when the file carries no census evidence."""
    try:
        text = open(path).read()
    except OSError as e:
        print(f"compile_census: cannot read {path!r}: {e}",
              file=sys.stderr)
        return []
    # bench row / flight dump: a single JSON dict with a census block
    try:
        doc = json.loads(text)
    except json.JSONDecodeError:
        doc = None
    if isinstance(doc, dict):
        census = doc.get("compile_census")
        if census is None and isinstance(doc.get("compile"), dict):
            census = doc["compile"].get("census")
        if isinstance(census, list):
            return [dict(site=r.get("site", "?"), key=r.get("key", "?"),
                         seconds=float(r.get("seconds", 0.0)),
                         builds=int(r.get("builds", r.get("n", 1))),
                         persistent_hits=int(r.get("persistent_hits", 0)),
                         peak_bytes_est=int(r.get("peak_bytes_est", 0)))
                    for r in census]
    # trace artifact: compile-category spans
    events = _iter_events(text)
    if events is None:
        return []
    rows = []
    for ev in events:
        if ev.get("cat") != "compile":
            continue
        args = ev.get("args") or {}
        rows.append(dict(
            site=str(ev.get("name", "?")).replace("compile ", "", 1),
            key=str(args.get("key", "?")),
            seconds=float(ev.get("dur", 0.0)) / 1e6,   # trace dur is us
            builds=int(args.get("builds", 1)),
            persistent_hits=1 if args.get("persistent_hit") else 0))
    return rows


# ---------------------------------------------------------------------------
# live AOT staging
# ---------------------------------------------------------------------------

def live_rows(nx: int) -> list:
    """AOT-stage every distinct streamed shape key of the bench plan and
    time trace / lower / compile separately (CPU backend; double work is
    fine offline — the in-band census never does this)."""
    import time

    import numpy as np

    import jax
    jax.config.update("jax_platforms", "cpu")
    from jax import ShapeDtypeStruct as Sds
    import jax.numpy as jnp

    from superlu_dist_tpu.models.gallery import poisson3d
    from superlu_dist_tpu.numeric import stream
    from superlu_dist_tpu.numeric.plan import build_plan
    from superlu_dist_tpu.ordering.dispatch import get_perm_c
    from superlu_dist_tpu.sparse.formats import symmetrize_pattern
    from superlu_dist_tpu.symbolic.symbfact import symbolic_factorize
    from superlu_dist_tpu.utils.options import Options

    a = poisson3d(nx)
    sym = symmetrize_pattern(a)
    sf = symbolic_factorize(sym, get_perm_c(Options(), a, sym),
                            relax=128, max_supernode=256, amalg_tol=1.05)
    plan = build_plan(sf, min_bucket=16, growth=1.05)
    ex = stream.StreamExecutor(plan, "float32")
    n_avals = len(plan.pattern_indices)
    print(f"live census: n={a.n_rows}, {len(plan.groups)} groups, "
          f"{ex.n_kernels} distinct shape keys")

    rows, seen = [], set()
    f32 = jnp.dtype("float32")
    i64 = jnp.dtype("int64")
    for key, _, child_arrs, _, _ in ex._steps:
        if key in seen:
            continue
        seen.add(key)
        (b, m, w, u), la, child_shapes, pool_size, dtype = key
        # the step signature of stream._kernel, as ShapeDtypeStructs
        args = [Sds((n_avals,), f32), Sds((pool_size,), f32),
                Sds((), f32),
                Sds((la,), i64), Sds((la,), i64), Sds((la,), i64),
                Sds((b,), i64), Sds((b,), i64)]
        for (ub, c) in child_shapes:
            args += [Sds((c,), i64), Sds((c,), i64), Sds((c, ub), i64)]
        kern = stream._kernel(key[0], la, child_shapes, pool_size, dtype,
                              None, False, "blocked")
        peak = _static_peak(kern, args, f"lu b{b} m{m} w{w} u{u}")
        t0 = time.perf_counter()
        try:
            traced = kern.trace(*args)       # jaxpr trace (jax >= 0.4.31)
            t1 = time.perf_counter()
            lowered = traced.lower()
        except AttributeError:
            t1 = t0                          # older jax: trace+lower fused
            lowered = kern.lower(*args)
        t2 = time.perf_counter()
        lowered.compile()
        t3 = time.perf_counter()
        rows.append(dict(site="stream._kernel",
                         key=f"lu b{b} m{m} w{w} u{u}",
                         seconds=t3 - t0, builds=1, persistent_hits=0,
                         peak_bytes_est=peak,
                         trace_s=t1 - t0, lower_s=t2 - t1,
                         compile_s=t3 - t2))
    return rows


def _static_peak(kern, args, label: str) -> int:
    """SLU121 static high-water live bytes of one abstractly-traced
    kernel (analysis/program.py liveness walk) — the census memory
    column.  0 when the trace fails (older jax)."""
    try:
        from superlu_dist_tpu.analysis.program import (audit_sharding,
                                                       trace_spec)
        spec = trace_spec(kern, tuple(args), label=label, site="census")
        _, stats = audit_sharding(spec, 1 << 20)
        return int(stats.get("peak_bytes_est", 0))
    except Exception as e:
        print(f"compile_census: static peak unavailable for {label}: {e}",
              file=sys.stderr)
        return 0


# ---------------------------------------------------------------------------
# report
# ---------------------------------------------------------------------------

def report(rows: list, staged: bool) -> int:
    if not rows:
        print("compile_census: no census evidence found (pass a trace "
              "artifact, bench row, or flight dump — or use --live)",
              file=sys.stderr)
        return 1
    agg: dict[tuple, dict] = {}
    for r in rows:
        row = agg.setdefault((r["site"], r["key"]), dict(
            site=r["site"], key=r["key"], seconds=0.0, builds=0,
            persistent_hits=0, peak_bytes_est=0,
            trace_s=0.0, lower_s=0.0, compile_s=0.0))
        row["seconds"] += r["seconds"]
        row["builds"] += r.get("builds", 1)
        row["persistent_hits"] += r.get("persistent_hits", 0)
        row["peak_bytes_est"] = max(row["peak_bytes_est"],
                                    r.get("peak_bytes_est", 0))
        for k in ("trace_s", "lower_s", "compile_s"):
            row[k] += r.get(k, 0.0)
    ranked = sorted(agg.values(), key=lambda row: -row["seconds"])
    total = sum(row["seconds"] for row in ranked) or 1e-12
    builds = sum(row["builds"] for row in ranked)
    hits = sum(row["persistent_hits"] for row in ranked)
    # memory column (slulint v6): the SLU121 static peak-live-bytes
    # estimate, present when the sharding twin audited the program or
    # --live staged it — the will-it-fit-HBM axis next to compile cost
    have_mem = any(row["peak_bytes_est"] for row in ranked)
    print(f"\n== compile census: {builds} builds, {total:.2f} s total, "
          f"{hits} persistent-cache hits ==")
    hdr = "   seconds  share  builds  hits"
    if have_mem:
        hdr += "  peak MiB"
    hdr += "  site                key"
    if staged:
        hdr += "                        trace/lower/compile"
    print(hdr)
    for row in ranked:
        line = (f"  {row['seconds']:8.3f}  {100 * row['seconds'] / total:4.1f}%"
                f"  {row['builds']:6d}  {row['persistent_hits']:4d}")
        if have_mem:
            line += f"  {row['peak_bytes_est'] / (1 << 20):8.2f}"
        line += f"  {row['site']:<18s}  {row['key']:<24s}"
        if staged:
            line += (f"  {row['trace_s']:.3f}/{row['lower_s']:.3f}"
                     f"/{row['compile_s']:.3f} s")
        print(line)
    top = ranked[0]
    print(f"\ndominant bucket: {top['key']} ({top['site']}) — "
          f"{100 * top['seconds'] / total:.1f}% of compile time")
    if have_mem:
        worst = max(ranked, key=lambda row: row["peak_bytes_est"])
        print(f"peak static memory: {worst['key']} ({worst['site']}) — "
              f"{worst['peak_bytes_est'] / (1 << 20):.2f} MiB estimated "
              f"live high-water (SLU121 model)")
    return 0


# ---------------------------------------------------------------------------
# closed-bucket budget check (the `compile-budget` CI gate)
# ---------------------------------------------------------------------------

def bucket_budget(nxs: list, stage: bool) -> int:
    """Closed bucket sets across a size gallery: print one line per
    size, fail unless the mega program count is constant in n."""
    import jax
    jax.config.update("jax_platforms", "cpu")

    from superlu_dist_tpu.models.gallery import poisson3d
    from superlu_dist_tpu.numeric.mega import MegaExecutor, _mega_kernel
    from superlu_dist_tpu.numeric.plan import build_plan
    from superlu_dist_tpu.ordering.dispatch import get_perm_c
    from superlu_dist_tpu.sparse.formats import symmetrize_pattern
    from superlu_dist_tpu.symbolic.symbfact import symbolic_factorize
    from superlu_dist_tpu.utils.options import Options

    import numpy as np
    import jax.numpy as jnp
    import time

    counts = {}
    for nx in nxs:
        t0 = time.perf_counter()
        a = poisson3d(nx)
        sym = symmetrize_pattern(a)
        sf = symbolic_factorize(sym, get_perm_c(Options(), a, sym),
                                relax=128, max_supernode=256,
                                amalg_tol=1.05)
        plan = build_plan(sf, min_bucket=16, growth=1.05, closed=True)
        ex = MegaExecutor(plan, "float32")
        staged, peak = 0, 0
        if stage:
            idt = jnp.asarray(np.zeros(0, dtype=np.int64)).dtype
            from jax import ShapeDtypeStruct as Sds
            f32 = jnp.dtype("float32")
            for key in sorted({k for k, _, _, _, _ in ex._steps},
                              key=str):
                (b, m, w, u), la, (ns_, cm, ub), pl, av, dt = key
                args = (Sds((av,), f32), Sds((pl,), f32), Sds((), f32),
                        Sds((la,), idt), Sds((la,), idt),
                        Sds((la,), idt), Sds((b,), idt), Sds((b,), idt),
                        Sds((ns_, cm), idt), Sds((ns_, cm), idt),
                        Sds((ns_,), idt), Sds((ns_, cm, ub), idt))
                kern = _mega_kernel(*key, "blocked")
                try:
                    kern.trace(*args).lower()
                except AttributeError:
                    kern.lower(*args)
                # static peak (SLU121) of the worst bucket program: the
                # budget gate's compile-count invariant says nothing
                # about whether the rung-padded pool still FITS — this
                # column does
                peak = max(peak, _static_peak(
                    kern, args, f"lu b{b} m{m} w{w} u{u} P{pl}"))
                staged += 1
        counts[nx] = ex.n_kernels
        mem = (f"peak={peak / (1 << 20):.2f}MiB " if peak else "")
        print(f"nx={nx:3d} n={a.n_rows:7d} groups={len(plan.groups):4d} "
              f"mega_kernels={ex.n_kernels} "
              f"digest={plan.bucket_set_digest()} "
              f"staged={staged} {mem}({time.perf_counter() - t0:.1f}s)",
              flush=True)
    distinct = sorted(set(counts.values()))
    if len(distinct) != 1:
        print(f"compile-budget: FAIL — compiled-program count is NOT "
              f"constant in n: {counts} (the closure pass must clamp "
              f"every gallery size to the same SLU_TPU_BUCKET_KEYS "
              f"bucket count)", file=sys.stderr)
        return 1
    print(f"compile-budget: OK — {distinct[0]} programs at every "
          f"gallery size (streamed-executor comparison: BENCH_r02 "
          f"needed 119 at n=110592)")
    return 0


def main(argv) -> int:
    if argv and argv[0] == "--buckets":
        rest = [a for a in argv[1:] if a != "--stage"]
        stage = "--stage" in argv[1:]
        nxs = [int(x) for x in rest] or [16, 32, 48]
        return bucket_budget(nxs, stage)
    if argv and argv[0] == "--live":
        nx = int(argv[1]) if len(argv) > 1 else 8
        return report(live_rows(nx), staged=True)
    if not argv:
        print(__doc__, file=sys.stderr)
        return 2
    rows = []
    for path in argv:
        rows.extend(rows_from_artifact(path))
    return report(rows, staged=False)


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
