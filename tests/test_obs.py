"""Observability subsystem (obs/trace.py + comm/kernel telemetry +
cross-rank stat reduction) — the PROFlevel analog.

Covers: span nesting/ordering and both artifact formats (Chrome
trace-event JSON, JSONL sidecar), the guaranteed-negligible disabled
path (no file, reused no-op span), comm counters against a 2-rank
TreeComm exchange with known byte counts, kernel-shape records from
both factorization executors and the device solve, Stats.timer
reentrancy, and Stats.reduce min/max/avg + load-balance factors.
"""

import json
import multiprocessing as mp
import os
import subprocess
import sys
import time

import numpy as np
import pytest

from superlu_dist_tpu import native
from superlu_dist_tpu.obs import trace
from superlu_dist_tpu.utils.stats import (
    COMM_OPS, CommStats, PHASES, Stats, StatsSummary)

pytestmark = pytest.mark.obs

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _tracer_hygiene(monkeypatch):
    """Every test starts and ends with the env-driven tracer state reset
    (the global is latched on first use)."""
    monkeypatch.delenv("SLU_TPU_TRACE", raising=False)
    trace._reset()
    yield
    trace._reset()


# ---------------------------------------------------------------------------
# span tracer
# ---------------------------------------------------------------------------

def test_span_nesting_and_jsonl(tmp_path):
    t = trace.Tracer(str(tmp_path / "t.json"))
    with t.span("outer", cat="phase", who="test"):
        time.sleep(0.002)
        with t.span("inner", cat="kernel", m=8, w=4):
            time.sleep(0.002)
        with t.span("inner2", cat="comm", bytes=64):
            pass
    t.close()
    rows = [json.loads(line) for line in open(tmp_path / "t.jsonl")]
    assert [r["name"] for r in rows] == ["inner", "inner2", "outer"]
    by = {r["name"]: r for r in rows}
    outer, inner = by["outer"], by["inner"]
    # nesting: children start after and end before the parent
    assert inner["ts"] >= outer["ts"]
    assert inner["ts"] + inner["dur"] <= outer["ts"] + outer["dur"]
    assert by["inner2"]["ts"] >= inner["ts"] + inner["dur"]
    # depth reflects nesting at record time
    assert outer["depth"] == 0 and inner["depth"] == 1
    assert inner["args"] == {"m": 8, "w": 4}
    assert outer["args"] == {"who": "test"}


def test_chrome_trace_artifact_valid(tmp_path):
    path = str(tmp_path / "t.json")
    t = trace.Tracer(path)
    with t.span("a", cat="phase"):
        with t.span("b", cat="kernel"):
            pass
    t.complete("c", "comm", time.perf_counter() - 0.5, 0.01, bytes=3)
    t.close()
    doc = json.load(open(path))
    events = doc["traceEvents"]
    assert len(events) == 3
    for ev in events:
        assert ev["ph"] == "X"
        for key in ("name", "cat", "ts", "dur", "pid", "tid"):
            assert key in ev
        assert ev["cat"] in trace.CATEGORIES
    # events are sorted: ts monotone per (pid, tid)
    last = {}
    for ev in events:
        key = (ev["pid"], ev["tid"])
        assert ev["ts"] >= last.get(key, float("-inf"))
        last[key] = ev["ts"]


def test_span_set_attaches_midspan_attrs(tmp_path):
    t = trace.Tracer(str(tmp_path / "t.json"))
    with t.span("s", cat="dispatch") as sp:
        sp.set(result_bytes=128)
    t.close()
    rows = [json.loads(line) for line in open(tmp_path / "t.jsonl")]
    assert rows[0]["args"] == {"result_bytes": 128}


def test_disabled_path_is_noop(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    t = trace.get_tracer()
    assert t is trace.NULL_TRACER
    assert not t.enabled
    # one reused no-op span object, regardless of args
    assert t.span("a") is t.span("b", cat="kernel", x=1)
    with t.span("a") as sp:
        sp.set(ignored=True)
    t.complete("x", "comm", 0.0, 1.0)
    t.flush()
    t.close()
    assert os.listdir(tmp_path) == []        # nothing written, ever
    # near-zero overhead: a hundred thousand disabled spans in well under
    # a second (they allocate nothing and read no clock)
    t0 = time.perf_counter()
    for _ in range(100_000):
        with t.span("hot", cat="kernel"):
            pass
    assert time.perf_counter() - t0 < 1.0


def test_env_gated_tracer(tmp_path, monkeypatch):
    path = str(tmp_path / "run.json")
    monkeypatch.setenv("SLU_TPU_TRACE", path)
    trace._reset()
    t = trace.get_tracer()
    assert isinstance(t, trace.Tracer) and t.enabled
    with trace.span("gated", cat="phase"):
        pass
    trace._reset()                            # closes + flushes
    doc = json.load(open(path))
    assert doc["traceEvents"][0]["name"] == "gated"
    assert (tmp_path / "run.jsonl").exists()


def test_install_programmatic(tmp_path):
    t = trace.Tracer(str(tmp_path / "p.json"))
    prev = trace.install(t)
    try:
        assert trace.enabled()
        with trace.span("prog", cat="phase"):
            pass
    finally:
        trace.install(prev)
        t.close()
    rows = [json.loads(line) for line in open(tmp_path / "p.jsonl")]
    assert rows[0]["name"] == "prog"


# ---------------------------------------------------------------------------
# kernel-shape telemetry (both executors + device solve)
# ---------------------------------------------------------------------------

def _small_plan():
    from superlu_dist_tpu.models.gallery import poisson2d
    from superlu_dist_tpu.numeric.plan import build_plan
    from superlu_dist_tpu.sparse.formats import symmetrize_pattern
    from superlu_dist_tpu.symbolic.symbfact import symbolic_factorize

    a = poisson2d(6)
    sym = symmetrize_pattern(a)
    sf = symbolic_factorize(sym, np.arange(a.n_rows), relax=4,
                            max_supernode=16)
    plan = build_plan(sf)
    return plan, sym.data[sf.value_perm]


def test_stream_executor_kernel_spans(tmp_path):
    import jax.numpy as jnp
    from superlu_dist_tpu.numeric.stream import StreamExecutor

    plan, avals = _small_plan()
    t = trace.Tracer(str(tmp_path / "s.json"))
    prev = trace.install(t)
    try:
        ex = StreamExecutor(plan, "float64")
        ex(jnp.asarray(avals), jnp.asarray(0.0))
    finally:
        trace.install(prev)
        t.close()
    events = json.load(open(tmp_path / "s.json"))["traceEvents"]
    kernels = [e for e in events if e["cat"] == "kernel"]
    dispatch = [e for e in events if e["cat"] == "dispatch"]
    assert len(kernels) == len(plan.groups)
    assert len(dispatch) == len(plan.groups)
    for k in kernels:
        args = k["args"]
        for key in ("level", "batch", "padded_batch", "m", "w", "u",
                    "executed_flops", "structural_flops", "padding"):
            assert key in args, (key, args)
        assert args["executed_flops"] >= args["structural_flops"] > 0
        assert args["padding"] >= 1.0
    # tracing implies the profile record too (no stderr scraping needed,
    # but the legacy consumer keeps working)
    assert len(ex.last_profile) == len(plan.groups)


def test_fused_executor_kernel_span(tmp_path):
    import jax.numpy as jnp
    from superlu_dist_tpu.numeric.factor import make_factor_fn

    plan, avals = _small_plan()
    fn = make_factor_fn(plan, "float64")
    t = trace.Tracer(str(tmp_path / "f.json"))
    prev = trace.install(t)
    try:
        fn(jnp.asarray(avals), jnp.asarray(0.0))
    finally:
        trace.install(prev)
        t.close()
    events = json.load(open(tmp_path / "f.json"))["traceEvents"]
    kernels = [e for e in events if e["cat"] == "kernel"]
    assert len(kernels) == 1 and kernels[0]["name"] == "factor-fused"
    args = kernels[0]["args"]
    assert args["aggregate"] and args["structural_flops"] == plan.flops
    assert any(e["cat"] == "dispatch" for e in events)


def test_device_solve_spans(tmp_path):
    from superlu_dist_tpu.drivers.gssvx import gssvx
    from superlu_dist_tpu.models.gallery import poisson2d
    from superlu_dist_tpu.solve.device import DeviceSolver
    from superlu_dist_tpu.utils.options import IterRefine, Options

    a = poisson2d(7)
    b = np.ones(a.n_rows)
    x, lu, stats, info = gssvx(Options(iter_refine=IterRefine.NOREFINE),
                               a, b)
    assert info == 0
    t = trace.Tracer(str(tmp_path / "d.json"))
    prev = trace.install(t)
    try:
        DeviceSolver(lu.numeric).solve(np.ones(a.n_rows))
    finally:
        trace.install(prev)
        t.close()
    events = json.load(open(tmp_path / "d.json"))["traceEvents"]
    solve = [e for e in events if e["name"] == "device-solve"]
    assert len(solve) == 1 and solve[0]["cat"] == "kernel"
    assert solve[0]["args"]["nrhs"] == 1
    d2h = [e for e in events if e["name"] == "solve-d2h"]
    assert len(d2h) == 1 and d2h[0]["cat"] == "comm"
    assert d2h[0]["args"]["bytes"] > 0


def test_gssvx_emits_phase_spans(tmp_path):
    import superlu_dist_tpu as slu
    from superlu_dist_tpu.models.gallery import poisson2d

    t = trace.Tracer(str(tmp_path / "g.json"))
    prev = trace.install(t)
    try:
        a = poisson2d(6)
        x, lu, stats, info = slu.gssvx(slu.Options(), a,
                                       np.ones(a.n_rows))
        assert info == 0
    finally:
        trace.install(prev)
        t.close()
    events = json.load(open(tmp_path / "g.json"))["traceEvents"]
    phases = {e["name"] for e in events if e["cat"] == "phase"}
    assert {"EQUIL", "ROWPERM", "COLPERM", "SYMBFACT", "DIST", "FACT",
            "SOLVE"} <= phases


# ---------------------------------------------------------------------------
# Stats.timer reentrancy (satellite regression)
# ---------------------------------------------------------------------------

def test_stats_timer_reentrant_same_phase():
    """Nested enters of the SAME phase must not double-count: the outer
    enter owns the accumulation (the old implementation added the inner
    elapsed a second time)."""
    s = Stats()
    with s.timer("FACT"):
        time.sleep(0.05)
        with s.timer("FACT"):
            time.sleep(0.05)
    assert 0.09 <= s.utime["FACT"] < 0.14, s.utime["FACT"]
    assert s._timer_depth["FACT"] == 0


def test_stats_timer_sequential_accumulates():
    s = Stats()
    for _ in range(2):
        with s.timer("SOLVE"):
            time.sleep(0.02)
    assert s.utime["SOLVE"] >= 0.04


def test_stats_timer_reentrant_under_exception():
    s = Stats()
    with pytest.raises(RuntimeError):
        with s.timer("FACT"):
            with s.timer("FACT"):
                raise RuntimeError("boom")
    assert s._timer_depth["FACT"] == 0
    with s.timer("FACT"):        # still usable afterwards
        pass
    assert s.utime["FACT"] > 0


# ---------------------------------------------------------------------------
# cross-rank stat reduction
# ---------------------------------------------------------------------------

class _FakeComm:
    """Two-rank comm stub: rank 0's matrix summed with a preloaded rank-1
    row — exercises the reduce math without the native transport."""

    n_ranks = 2
    rank = 0

    def __init__(self, peer_stats: Stats):
        self._peer_vec = peer_stats._pack()

    def allreduce_sum_any(self, arr, root=0):
        out = np.array(arr, dtype=np.float64)
        out[1] += self._peer_vec
        return out


def test_stats_reduce_min_max_avg_balance():
    s0, s1 = Stats(), Stats()
    s0.utime["FACT"], s1.utime["FACT"] = 1.0, 3.0
    s0.ops["FACT"] = s1.ops["FACT"] = 50.0
    s0.tiny_pivots, s1.tiny_pivots = 2, 3
    s1.comm = {"bcast": {"calls": 4, "bytes": 256, "seconds": 0.5}}
    summary = s0.reduce(_FakeComm(s1))
    assert isinstance(summary, StatsSummary)
    f = summary.utime["FACT"]
    assert f.min == 1.0 and f.max == 3.0 and f.avg == 2.0
    assert abs(f.balance - 1.5) < 1e-12
    assert abs(summary.balance("FACT") - 1.5) < 1e-12
    assert summary.ops["FACT"].total == 100.0
    assert summary.tiny_pivots == 5
    assert summary.comm["bcast"]["calls"] == 4
    assert summary.comm["bcast"]["bytes"] == 256
    rep = summary.report()
    assert "FACT" in rep and "balance" in rep.splitlines()[2]
    # untouched phases don't clutter the report
    assert "EQUIL" not in rep


def test_comm_stats_accounting_and_report():
    cs = CommStats()
    cs.add("bcast", 64, 0.01)
    cs.add("bcast", 64, 0.01)
    cs.add("allreduce", 128, 0.02)
    t = cs.totals()
    assert t["bcast"] == {"calls": 2, "bytes": 128, "seconds": 0.02}
    assert "reduce" not in t                  # zero ops stay out
    assert "bcast" in cs.report()
    s = Stats()
    s.attach_comm(cs)
    assert "comm bcast" in s.report()


# ---------------------------------------------------------------------------
# 2-rank native transport: comm counters with known byte counts + reduce
# ---------------------------------------------------------------------------

def _exchange(tc):
    """The scripted 2-rank exchange: 1 bcast, 1 reduce, 1 allreduce of
    8 float64 each (single chunk at max_len=64)."""
    from superlu_dist_tpu.utils.stats import Stats

    buf = np.arange(8.0) if tc.rank == 0 else np.zeros(8)
    tc.bcast(buf, root=0)
    ok = bool(np.array_equal(buf, np.arange(8.0)))
    buf2 = np.full(8, float(tc.rank + 1))
    tc.reduce_sum(buf2, root=0)
    buf3 = np.ones(8)
    tc.allreduce_sum(buf3, root=0)
    totals = tc.comm_stats.totals()
    st = Stats()
    st.utime["FACT"] = float(tc.rank + 1)
    st.ops["FACT"] = 100.0
    st.tiny_pivots = tc.rank
    st.attach_comm(tc.comm_stats)
    summary = st.reduce(tc)
    return ok, totals, summary


def _obs_rank_worker(name, n_ranks, rank, q):
    from superlu_dist_tpu.parallel.treecomm import TreeComm
    tc = TreeComm(name, n_ranks, rank, max_len=64, create=False)
    try:
        q.put((rank,) + _exchange(tc))
    finally:
        tc.close()


@pytest.mark.skipif(not native.available(),
                    reason="native library unavailable")
def test_comm_counters_and_reduce_two_ranks():
    from superlu_dist_tpu.parallel.treecomm import TreeComm

    name = f"/slu_obs_comm_{os.getpid()}"
    owner = TreeComm(name, 2, 0, max_len=64, create=True)
    try:
        ctx = mp.get_context("spawn")     # no fork of the jax-laden parent
        q = ctx.Queue()
        p = ctx.Process(target=_obs_rank_worker, args=(name, 2, 1, q))
        p.start()
        ok0, totals0, summary0 = _exchange(owner)
        rank1, ok1, totals1, summary1 = q.get(timeout=120)
        p.join(timeout=120)
        assert p.exitcode == 0
    finally:
        owner.close(unlink=True)
    assert ok0 and ok1
    for totals in (totals0, totals1):
        # known byte counts: 8 float64 = 64 bytes per leg
        assert totals["bcast"] == {"calls": 1, "bytes": 64,
                                   "seconds": totals["bcast"]["seconds"]}
        assert totals["reduce"]["calls"] == 1
        assert totals["reduce"]["bytes"] == 64
        # the composite attributes BOTH its legs to "allreduce"
        assert totals["allreduce"]["calls"] == 2
        assert totals["allreduce"]["bytes"] == 128
    # every rank computed the SAME cross-rank summary
    for summary in (summary0, summary1):
        f = summary.utime["FACT"]
        assert f.min == 1.0 and f.max == 2.0 and f.avg == 1.5
        assert abs(f.balance - 2.0 / 1.5) < 1e-12
        assert summary.tiny_pivots == 1
        assert summary.ops["FACT"].total == 200.0
        # comm totals summed over ranks
        assert summary.comm["bcast"]["bytes"] == 128
        assert summary.comm["allreduce"]["bytes"] == 256


# ---------------------------------------------------------------------------
# comm spans from the tree collectives
# ---------------------------------------------------------------------------

@pytest.mark.skipif(not native.available(),
                    reason="native library unavailable")
def test_single_rank_comm_spans(tmp_path):
    from superlu_dist_tpu.parallel.treecomm import TreeComm

    t = trace.Tracer(str(tmp_path / "c.json"))
    prev = trace.install(t)
    try:
        name = f"/slu_obs_span_{os.getpid()}"
        with TreeComm(name, 1, 0, max_len=16, create=True) as tc:
            tc.bcast(np.ones(4))
            tc.allreduce_sum(np.ones(4))
            tc.bcast_bytes(b"hello")
    finally:
        trace.install(prev)
        t.close()
    events = json.load(open(tmp_path / "c.json"))["traceEvents"]
    comm = [e for e in events if e["cat"] == "comm"]
    ops = {e["args"]["op"] for e in comm}
    assert {"bcast", "allreduce", "bcast_bytes"} <= ops
    for e in comm:
        assert e["args"]["bytes"] > 0
        assert e["name"].startswith("tree-")


# ---------------------------------------------------------------------------
# mfu_report: structured-trace parsing + explicit empty-input diagnostic
# ---------------------------------------------------------------------------

def _run_mfu(*args):
    return subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "mfu_report.py"),
         *args],
        cwd=REPO, stdout=subprocess.PIPE, stderr=subprocess.PIPE)


def test_mfu_report_missing_inputs_diagnostic(tmp_path):
    r = _run_mfu(str(tmp_path / "no.jsonl"), str(tmp_path / "no.err"))
    assert r.returncode == 1
    assert b"no trace rows found" in r.stderr


def test_mfu_report_prefers_structured_trace(tmp_path):
    t = trace.Tracer(str(tmp_path / "k.json"))
    t.complete("lu b4 m32 w16 u16", "kernel", 0.0, 0.005, level=2,
               batch=3, padded_batch=4, m=32, w=16, u=16,
               executed_flops=4.0e7, structural_flops=3.0e7, padding=1.33)
    t.close()
    for artifact in ("k.json", "k.jsonl"):
        r = _run_mfu(str(tmp_path / "no.jsonl"), str(tmp_path / artifact))
        assert r.returncode == 0, r.stderr
        out = r.stdout.decode()
        assert "structured trace" in out
        assert "m=32" in out and "lvl=2" in out


def test_mfu_report_legacy_stderr_still_parses(tmp_path):
    err = tmp_path / "legacy.err"
    err.write_text("# lvl=3  B=16  m=512  w=256  u=256  12.34 ms  "
                   "567.8 GF/s\n")
    r = _run_mfu(str(tmp_path / "no.jsonl"), str(err))
    assert r.returncode == 0, r.stderr
    out = r.stdout.decode()
    assert "legacy stderr" in out and "m=512" in out
