"""Hardware-only tests (opt-in): the accelerator-backend paths that the
CPU-pinned suite cannot exercise — complex (c64) factors on the real
chip (VERDICT r2 missing #6: the z-twin set `pzgstrf.c` runs on the
accelerator in the reference, so complex must run on the device here),
and the f32 device pipeline end-to-end.

Opt-in via SLU_TPU_HW_TESTS=1 because (a) the suite must never touch the
tunnel implicitly, and (b) an aborted client mid-compile wedges the
remote relay (PLAN.md hazards).  Each test runs in a subprocess WITHOUT
the conftest CPU pin and with a generous timeout; the hardware session
(scripts/hw_session_r3.sh) is the intended caller:

    SLU_TPU_HW_TESTS=1 python -m pytest tests/test_tpu_hw.py -v
"""

import os
import subprocess
import sys

import pytest

pytestmark = pytest.mark.skipif(
    os.environ.get("SLU_TPU_HW_TESTS") != "1",
    reason="hardware tests are opt-in (SLU_TPU_HW_TESTS=1)")

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run_on_hw(code: str, timeout: float = 7200.0):
    """Run `code` in a subprocess on the session's real backend (no CPU
    pin).  The timeout exists only as a last-resort bound against a truly
    hung client; it sits FAR above worst-case compile (~40 s/kernel ×
    tens of kernels) because expiry hard-kills the child, and a kill
    mid-remote-compile wedges the relay (PLAN.md hazards)."""
    env = dict(os.environ)
    # conftest set JAX_PLATFORMS=cpu for children and stashed the
    # session's original pin; restore it (unsetting would allow a silent
    # CPU fallback if the accelerator plugin half-fails to register)
    orig = env.pop("SLU_TPU_ORIG_PLATFORMS", "")
    if orig:
        env["JAX_PLATFORMS"] = orig
    else:
        env.pop("JAX_PLATFORMS", None)
    r = subprocess.run([sys.executable, "-c", code], capture_output=True,
                       text=True, timeout=timeout, cwd=REPO, env=env)
    assert r.returncode == 0, (r.stdout[-2000:], r.stderr[-2000:])
    return r.stdout


_PRELUDE = """
import jax
from superlu_dist_tpu.utils.jaxcache import enable_compile_cache
enable_compile_cache()
import numpy as np
import superlu_dist_tpu as slu
assert jax.default_backend() != "cpu", jax.default_backend()
"""


def test_complex_c64_on_accelerator():
    """cg20.cua (BASELINE config 3) through the device path: c64 factors
    + IR to c128 accuracy, residual at reference level (<=1e-10)."""
    out = _run_on_hw(_PRELUDE + """
from superlu_dist_tpu.io import read_matrix
a = read_matrix("/root/reference/EXAMPLE/cg20.cua").tocsr()
rng = np.random.default_rng(0)
xt = rng.standard_normal(a.n_rows) + 1j * rng.standard_normal(a.n_rows)
b = a.matvec(xt)
x, lu, stats, info = slu.gssvx(slu.Options(factor_dtype="complex64"), a, b)
resid = float(np.linalg.norm(b - a.matvec(x)) / np.linalg.norm(b))
print("RESID", info, resid)
assert info == 0 and resid < 1e-10, (info, resid)
""")
    assert "RESID 0" in out


def test_f32_device_pipeline():
    """poisson3d through factor + device solve + IR on the accelerator."""
    out = _run_on_hw(_PRELUDE + """
from superlu_dist_tpu.models.gallery import poisson3d
a = poisson3d(12)
xt = np.random.default_rng(1).standard_normal(a.n_rows)
b = a.matvec(xt)
x, lu, stats, info = slu.gssvx(slu.Options(factor_dtype="float32"), a, b)
resid = float(np.linalg.norm(b - a.matvec(x)) / np.linalg.norm(b))
print("RESID", info, resid)
assert info == 0 and resid < 1e-10, (info, resid)
""")
    assert "RESID 0" in out
