"""Race/memory sanitizer CI for the native layer.

The reference ships no race detection (SURVEY.md §5); its correctness
rests on ownership partitioning.  Here every threaded/shared-memory
native path (threaded symbolic, threaded ND, shm tree collectives) runs
under ThreadSanitizer and AddressSanitizer via a standalone C++ harness
(native/sanitize_main.cpp) — a clean report is part of the test suite.
"""

import os
import subprocess

import pytest

HERE = os.path.dirname(os.path.abspath(__file__))
NATIVE = os.path.join(HERE, "..", "superlu_dist_tpu", "native")


def _sanitizer_available(tmp_path, flag) -> bool:
    """Probe with a trivial program: only a missing toolchain/runtime may
    skip — a compile failure in OUR sources must FAIL the test, not
    silently disable the sanitizer CI."""
    probe = tmp_path / "probe.cpp"
    probe.write_text("int main() { return 0; }\n")
    try:
        r = subprocess.run(
            ["g++", f"-fsanitize={flag}", str(probe), "-o",
             str(tmp_path / "probe")],
            capture_output=True)
    except FileNotFoundError:
        return False
    return r.returncode == 0


def _build_and_run(tmp_path, flag, name):
    if not _sanitizer_available(tmp_path, flag):
        pytest.skip(f"-fsanitize={flag} toolchain unavailable")
    exe = str(tmp_path / name)
    cmd = ["g++", "-O1", "-g", f"-fsanitize={flag}", "-std=c++17",
           "-pthread", os.path.join(NATIVE, "sanitize_main.cpp"),
           os.path.join(NATIVE, "slu_host.cpp"), "-o", exe]
    r = subprocess.run(cmd, capture_output=True)
    if r.returncode != 0:
        # glibc < 2.34 keeps shm_open/shm_unlink in librt (the
        # native/__init__.py production-build fallback)
        r = subprocess.run(cmd + ["-lrt"], capture_output=True)
    assert r.returncode == 0, r.stderr.decode()
    out = subprocess.run([exe], capture_output=True, timeout=600)
    text = out.stdout.decode() + out.stderr.decode()
    assert out.returncode == 0, text
    assert "PASS" in text, text
    assert "WARNING: ThreadSanitizer" not in text, text
    assert "ERROR: AddressSanitizer" not in text, text


def test_native_under_tsan(tmp_path):
    _build_and_run(tmp_path, "thread", "sanitize_tsan")


def test_native_under_asan(tmp_path):
    _build_and_run(tmp_path, "address", "sanitize_asan")


import pytest  # noqa: E402

# slow tier: multi-process / native-build / at-scale — fast CI runs -m "not slow"
pytestmark = pytest.mark.slow
