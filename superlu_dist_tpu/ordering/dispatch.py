"""Column-ordering dispatch — analog of get_perm_c_dist (SRC/get_perm_c.c:463).

All orderings operate on the symmetrized pattern A + Aᵀ (at_plus_a_dist
analog) of the row-permuted matrix, and return an *order* array:
order[k] = old index of the k-th pivot column.
"""

from __future__ import annotations

import numpy as np

from superlu_dist_tpu.sparse.formats import SparseCSR, symmetrize_pattern
from superlu_dist_tpu.utils.options import ColPerm, Options
from superlu_dist_tpu.utils.errors import SuperLUError
from superlu_dist_tpu.ordering.minimum_degree import minimum_degree
from superlu_dist_tpu.ordering.dissection import geometric_nd, bfs_nd


def get_perm_c(options: Options, a: SparseCSR,
               sym: SparseCSR | None = None) -> np.ndarray:
    n = a.n_rows
    cp = options.col_perm
    if cp == ColPerm.NATURAL:
        return np.arange(n, dtype=np.int64)
    if cp == ColPerm.MY_PERMC:
        if options.user_perm_c is None:
            raise SuperLUError("ColPerm=MY_PERMC but user_perm_c is None")
        return np.asarray(options.user_perm_c, dtype=np.int64)
    if cp == ColPerm.COLAMD:
        # approximate column MD directly on A — no AᵀA, no symmetrization
        from superlu_dist_tpu.ordering.colamd import colamd_order
        return colamd_order(a.n_rows, a.n_cols, a.indptr, a.indices)
    if cp == ColPerm.MMD_ATA:
        # exact MD on the explicit AᵀA pattern (getata_dist analog)
        from superlu_dist_tpu.ordering.colamd import (ata_adjacency,
                                                      dense_row_threshold)
        ptr, idx = ata_adjacency(a.n_rows, a.n_cols, a.indptr, a.indices,
                                 dense_row=dense_row_threshold(a.n_cols))
        return minimum_degree(n, ptr, idx)
    if sym is None:
        sym = symmetrize_pattern(a)
    if cp == ColPerm.MMD_AT_PLUS_A:
        return minimum_degree(n, sym.indptr, sym.indices)
    if cp == ColPerm.ND_AT_PLUS_A:
        grid_shape = getattr(a, "grid_shape", None)
        if grid_shape is not None:
            return geometric_nd(grid_shape)
        if n <= 400:
            # MD beats any ND on small irregular graphs, and is cheap there
            return minimum_degree(n, sym.indptr, sym.indices)
        # multilevel ND (the METIS_AT_PLUS_A-quality path): coarsen →
        # bisect → FM-refine → vertex separator, native/slu_host.cpp
        from superlu_dist_tpu import native
        order = native.mlnd(n, sym.indptr, sym.indices)
        if order is not None:
            return order
        return bfs_nd(n, sym.indptr, sym.indices)
    raise SuperLUError(f"unsupported ColPerm {cp}")
