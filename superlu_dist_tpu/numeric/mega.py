"""Mega-kernel factor executor — O(1) compiled programs in matrix size.

The streamed executor (numeric/stream.py) bounded compile count by
distinct shape keys, but its keys still carry per-group axes — padded
batch, A-entry count, the child-set shape tuple — so the compiled-kernel
count grows with the matrix (BENCH_r02: 119 kernels for 455 groups at
n=110592, dead in `factor-compile` at the 1350 s watchdog without one
factor FLOP executed).  This executor closes the program set the way
fixed-function hardware closes it (one medium-granularity dataflow
engine serving every front shape, arXiv:2406.10511; one uniform kernel
amortized over many heterogeneous small systems, arXiv:1909.04539):

* the plan's shape-key CLOSURE pass (numeric/plan._close_shape_keys,
  ``SLU_TPU_BUCKET_CLOSED``/``SLU_TPU_BUCKET_KEYS``) maps every (W, U)
  dispatch key onto a small fixed set of canonical ladder rungs;
* per closed bucket, ONE jitted program whose per-group variability is
  DATA, not code: batch, A-entry and child-table axes are padded to the
  bucket's canonical rungs, the child extend-add runs as a ``lax.scan``
  over stacked per-set tables (factor.group_step's tuple branch — the
  same ``extend_add_set`` arithmetic the other executors unroll), and
  the Schur pool / pattern values are rung-padded so the program shapes
  do not encode exact matrix sizes;
* programs are AOT-staged (trace → lower → compile) at first use, so
  the compile census records the exact stage split and the persistent
  XLA cache (utils/jaxcache.py) serves the whole set from disk on any
  later run whose buckets are already resident — the cross-run warm
  start ``scripts/warm_compile_cache.py`` prebakes for a serving fleet.

Equivalence contract: padding is index-sentinel no-ops (OOB drops/zero
fills) and batch slots are identity fronts, so the factors are BITWISE
identical to the streamed and fused executors on the same plan
(tests/test_megakernel.py; the PR 5 schedule guarantee carries over
because closure runs before the schedule branch).  The PR 7 checkpoint
/ resume splice is preserved: frontiers store the UNPADDED pool, so a
mega checkpoint resumes under stream and vice versa.

Mesh runs: the per-bucket programs shard exactly like the streamed
kernels (stream._kernel) — batch-over-"snode", columns-over-"panel" on
the dense factor math, replicated index metadata, the Schur pool
replicated or 1-D partitioned via ``factor.pool_spec`` — so a mesh no
longer downgrades mega→stream: the closed program set and the GSPMD
sharding compose.  The bitwise guarantee above is a SINGLE-DEVICE
contract; under GSPMD the partitioner re-tiles the batched triangular
solves, which (like stream-under-mesh) perturbs low-order bits — mesh
runs carry the allclose-class contract instead, and the BITWISE mesh
tier is the shard_map executor (parallel/spmd.py), whose full-order
replay sidesteps the partitioner entirely
(tests/test_spmd.py exercises mega-under-mesh both ways).
"""

from __future__ import annotations

import functools
import time

import numpy as np
import jax
import jax.numpy as jnp

from superlu_dist_tpu.numeric.factor import group_step
from superlu_dist_tpu.numeric.plan import FactorPlan, bucket_rung
from superlu_dist_tpu.numeric.stream import StreamExecutor, _pad_to
from superlu_dist_tpu.obs.compilestats import COMPILE_STATS
from superlu_dist_tpu.symbolic.symbfact import _front_flops

#: ladder growth for the pool / pattern-value rungs: these pad real HBM
#: (not index no-op space), so the rung is fine — <= 25% overhead buys
#: program shapes that don't encode exact matrix sizes (cross-matrix
#: cache hits for the fleet warm start)
_STORE_GROWTH = 1.25


@functools.lru_cache(maxsize=None)
def _mega_kernel(dims, la, child_dims, pool_len, avals_len, dtype, pivot,
                 gemm_prec="highest", pallas="off", mesh=None,
                 pool_partition=False):
    """ONE jitted program for a closed shape bucket.

    Everything per-group — which fronts, which A entries, which children
    — arrives as device-array arguments at canonical shapes; the program
    itself is pure dataflow.  `pivot`/`gemm_prec`/`pallas` are the
    caller-resolved SLU_TPU_PIVOT_KERNEL / SLU_TPU_GEMM_PREC /
    SLU_TPU_PALLAS choices (part of this cache key — slulint SLU105).
    The stacked-children extend-add keeps the .at[] scan under every
    pallas mode (its per-set ub is traced); the A-assembly takes the
    fused path — bitwise-identical either way.  With a mesh, the dense
    math shards exactly like stream._kernel (batch-over-"snode",
    columns-over-"panel", pool via factor.pool_spec)."""
    batch, m, w, u = dims
    front_sharding = pivot_sharding = replicated = pool_sharding = None
    if mesh is not None:
        from jax.sharding import NamedSharding, PartitionSpec as P
        from superlu_dist_tpu.numeric.factor import pool_spec
        front_sharding = NamedSharding(mesh, P("snode", None, "panel"))
        pivot_sharding = NamedSharding(mesh, P("snode", None, None))
        replicated = NamedSharding(mesh, P(None, None))
        pool_sharding = pool_spec(mesh, pool_partition)

    def step(avals, pool, thresh, a_slot, a_flat, a_src, ws, off,
             child_off, child_slot, child_ub, rel):
        if pool_sharding is not None:
            pool = jax.lax.with_sharding_constraint(pool, pool_sharding)
        out, pool, tiny = group_step(
            (batch, m, w, u), avals, pool, thresh,
            a_slot, a_flat, a_src, ws, off,
            (child_off, child_slot, child_ub, rel),
            front_sharding=front_sharding, pivot_sharding=pivot_sharding,
            replicated=replicated, pivot=pivot,
            gemm_prec=gemm_prec, pallas=pallas)
        if pool_sharding is not None:
            pool = jax.lax.with_sharding_constraint(pool, pool_sharding)
        return out, pool, tiny

    # pool donated exactly like the streamed kernels: XLA scatters the
    # Schur write-back in place instead of copying pool_len entries
    return jax.jit(step, donate_argnums=(1,))


class MegaExecutor(StreamExecutor):
    """Callable factorization with a CLOSED compiled-program set.

    Drop-in for StreamExecutor on a single device (same call contract,
    same checkpoint/deadline/chaos/sentinel hooks, same async dispatch
    stream); ``n_kernels`` == the plan's bucket-set size, independent of
    group count and — on a closed plan — of matrix size."""

    _census_site = "mega._kernel"

    @staticmethod
    def _census_label(key) -> str:
        # the pool rung P is part of the label: the SLU121 peak-memory
        # verdict is dominated by the rung-padded Schur pool, so a
        # MemoryBudgetError (and the census memory column) must name the
        # offending bucket RUNG, not just the front geometry
        (b, m, w, u) = key[0]
        return f"lu b{b} m{m} w{w} u{u} P{key[3]}"

    def __init__(self, plan: FactorPlan, dtype="float64", mesh=None,
                 offload: str = "auto", pool_partition: bool = False,
                 host_flops=None, gemm_prec=None, pallas=None):
        self._mega_fns = {}
        self._spec = {}
        # host-share is off by construction: the per-bucket programs are
        # device-resident and the leading-leaf split would need per-group
        # placement of the packed metadata
        super().__init__(plan, dtype, mesh=mesh, offload=offload,
                         pool_partition=pool_partition,
                         granularity="group", host_flops=0.0,
                         gemm_prec=gemm_prec, pallas=pallas)
        self.granularity = "mega"

    # ---- canonical metadata packing -------------------------------------
    def _build_steps(self) -> list:
        plan = self.plan
        n_avals = len(plan.pattern_indices)
        # store rungs: program shapes must not encode exact matrix sizes
        self._pool_len = bucket_rung(max(plan.pool_size, 1), lo=8,
                                     growth=_STORE_GROWTH)
        self._avals_len = bucket_rung(max(n_avals, 1), lo=8,
                                      growth=_STORE_GROWTH)
        P, AV = self._pool_len, self._avals_len
        by_key: dict = {}
        for grp in plan.groups:
            by_key.setdefault((grp.w, grp.u), []).append(grp)
        for (w, u), grps in by_key.items():
            # per-bucket canonical axes: maxima over the bucket's groups,
            # rung-rounded so same-size-class matrices share programs
            B = bucket_rung(max(g.batch for g in grps), lo=1, growth=2.0)
            la = bucket_rung(max(len(g.a_src) for g in grps) or 1,
                             lo=64, growth=4.0)
            nset = max(len(g.children) for g in grps)
            cmax = max((len(cs.child_off) for g in grps
                        for cs in g.children), default=0)
            ubmax = max((cs.ub for g in grps for cs in g.children),
                        default=0)
            if nset:
                nset = bucket_rung(nset, lo=1, growth=2.0)
                cmax = bucket_rung(cmax, lo=1, growth=4.0)
            self._spec[(w, u)] = (B, la, (nset, cmax, ubmax))
        steps = []
        for grp in plan.groups:
            B, la, (nset, cmax, ubmax) = self._spec[(grp.w, grp.u)]
            # sentinels re-based onto the PADDED stores: the plan's
            # pool_size sentinel would land INSIDE the rung-padded pool
            off = np.where(np.asarray(grp.off) >= plan.pool_size, P,
                           grp.off)
            a = (_pad_to(grp.a_slot, la, B), _pad_to(grp.a_flat, la, 0),
                 _pad_to(grp.a_src, la, AV), _pad_to(grp.ws, B, 0),
                 _pad_to(off, B, P))
            co = np.full((nset, cmax), P, dtype=np.int64)
            csl = np.full((nset, cmax), B, dtype=np.int64)
            cub = np.ones(max(nset, 0), dtype=np.int64)
            rel = np.full((nset, cmax, ubmax), grp.m, dtype=np.int64)
            for si, cs in enumerate(grp.children):
                c = len(cs.child_off)
                co[si, :c] = cs.child_off
                csl[si, :c] = cs.child_slot
                cub[si] = cs.ub
                rel[si, :c, :cs.ub] = cs.rel
            key = ((B, grp.m, grp.w, grp.u), la, (nset, cmax, ubmax),
                   P, AV, self.dtype)
            steps.append((key, tuple(jnp.asarray(x) for x in a),
                          (jnp.asarray(co), jnp.asarray(csl),
                           jnp.asarray(cub), jnp.asarray(rel)),
                          grp.batch, False))
        return steps

    # ---- AOT program acquisition + census -------------------------------
    def _get_kernel(self, key, pivot, args):
        """AOT-stage the bucket's program on first use: trace → lower →
        XLA compile, timed SEPARATELY so the census (and the bench row)
        can distinguish a persistent-cache disk hit (compile ~0) from a
        cold build — the warm-start acceptance measurement."""
        fn = self._mega_fns.get((key, pivot))
        if fn is not None:
            return fn
        jfn = _mega_kernel(*key, pivot, self.gemm_prec, self.pallas,
                           self.mesh, self.pool_partition)
        sds = tuple(jax.ShapeDtypeStruct(x.shape, x.dtype) for x in args)
        # program audit at AOT-stage time: a finding raises BEFORE the
        # XLA compile below ever runs (SLU_TPU_VERIFY_PROGRAMS=1)
        self._audit_program(self._census_site, self._census_label(key),
                            jfn, sds)
        t0 = time.perf_counter()
        try:
            traced = jfn.trace(*sds)          # jax >= 0.4.31
            t1 = time.perf_counter()
            lowered = traced.lower()
        except AttributeError:                # older jax: fused stages
            t1 = t0
            lowered = jfn.lower(*sds)
        t2 = time.perf_counter()
        compiled = lowered.compile()
        t3 = time.perf_counter()
        COMPILE_STATS.record(
            self._census_site, self._census_label(key), t0, t3 - t0,
            n_args=len(args), trace_seconds=t1 - t0,
            lower_seconds=t2 - t1, compile_seconds=t3 - t2)
        self._mega_fns[(key, pivot)] = compiled
        return compiled

    def _census_pending(self, key, pivot) -> bool:
        return False            # accounted inside _get_kernel (AOT)

    def prebake(self) -> int:
        """Compile every bucket program WITHOUT running a factorization
        (shape specs only) — the fleet warm-start primitive
        (scripts/warm_compile_cache.py): with the persistent compile
        cache enabled the whole closed set lands on disk, so any later
        process whose buckets match compiles nothing.  Returns the
        number of programs now resident."""
        from superlu_dist_tpu.ops.dense import pivot_kernel
        pivot = pivot_kernel()
        idt = jnp.asarray(np.zeros(0, dtype=np.int64)).dtype
        dts = jnp.dtype(self.dtype)
        rdt = dts.type(0).real.dtype
        Sds = jax.ShapeDtypeStruct
        for key in sorted({k for k, _, _, _, _ in self._steps}, key=str):
            (B, m, w, u), la, (nset, cmax, ubmax), P, AV, _ = key
            args = (Sds((AV,), dts), Sds((P,), dts), Sds((), rdt),
                    Sds((la,), idt), Sds((la,), idt), Sds((la,), idt),
                    Sds((B,), idt), Sds((B,), idt),
                    Sds((nset, cmax), idt), Sds((nset, cmax), idt),
                    Sds((nset,), idt), Sds((nset, cmax, ubmax), idt))
            self._get_kernel(key, pivot, args)
        return len(self._mega_fns)

    # ---- padded-store plumbing ------------------------------------------
    def _prep_avals(self, avals):
        av = jnp.asarray(avals, dtype=self.dtype)
        return jnp.zeros(self._avals_len,
                         dtype=self.dtype).at[:av.shape[0]].set(av)

    def _ckpt_pool(self, pool):
        # frontiers must stay executor-portable (stream resumes a mega
        # checkpoint bitwise and vice versa): store the UNPADDED pool
        return pool[:self.plan.pool_size]

    def _apply_resume(self, resume, pool):
        start, fronts, pool, tiny = super()._apply_resume(resume, pool)
        if pool.shape[0] < self._pool_len:
            pool = jnp.zeros(self._pool_len,
                             dtype=self.dtype).at[:pool.shape[0]].set(pool)
        return start, fronts, pool, tiny

    def _retrace_begin(self) -> int:
        return len(self._mega_fns)

    @property
    def executed_flops(self) -> float:
        return float(sum(self._spec[(g.w, g.u)][0] * _front_flops(g.w, g.u)
                         for g in self.plan.groups))
