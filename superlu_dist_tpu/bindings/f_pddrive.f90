! Fortran example driver — capability analog of the reference's
! FORTRAN/f_pddrive.f90 + f_5x5.f90: solve a small sparse system through
! the handle-based Fortran interface (superlu_mod.f90 -> slu_tpu.h C API).
!
! The 5x5 test system is the same shape the reference's f_5x5 example
! uses: an unsymmetric pattern with a known solution of all ones.
!
! Build (needs gfortran; the CI skips when absent):
!   python -m superlu_dist_tpu.bindings.build          # libslu_tpu.so
!   gfortran -o f_pddrive superlu_mod.f90 f_pddrive.f90 \
!       -L. -lslu_tpu $(python3-config --embed --ldflags)
!   ./f_pddrive

program f_pddrive
  use superlu_tpu
  use iso_c_binding
  implicit none

  integer(c_int64_t), parameter :: n = 5, nnz = 12, nrhs = 1
  integer(c_int64_t) :: indptr(n + 1), indices(nnz)
  real(c_double) :: values(nnz), b(n), x(n)
  real(c_double) :: err
  integer(c_int) :: info
  integer :: i

  ! CSR of the 5x5 example matrix (rows: diagonal plus off-diagonals)
  indptr  = [0_c_int64_t, 3_c_int64_t, 5_c_int64_t, 8_c_int64_t, &
             10_c_int64_t, 12_c_int64_t]
  indices = [0_c_int64_t, 2_c_int64_t, 4_c_int64_t, &
             1_c_int64_t, 3_c_int64_t, &
             0_c_int64_t, 2_c_int64_t, 4_c_int64_t, &
             1_c_int64_t, 3_c_int64_t, &
             0_c_int64_t, 4_c_int64_t]
  values  = [19.0d0, 21.0d0, 21.0d0, &
             12.0d0, 12.0d0, &
             12.0d0, 16.0d0, 12.0d0, &
             5.0d0, 18.0d0, &
             12.0d0, 18.0d0]

  ! b = A * ones  =>  expected x = ones
  b = 0.0d0
  do i = 1, int(n)
     block
       integer :: k
       do k = int(indptr(i)) + 1, int(indptr(i + 1))
          b(i) = b(i) + values(k)
       end do
     end block
  end do

  info = slu_tpu_init(c_char_"cpu" // c_null_char)
  if (info /= 0) stop "slu_tpu_init failed"

  info = slu_tpu_solve(n, nnz, indptr, indices, values, b, x, nrhs)
  if (info /= 0) stop "slu_tpu_solve failed"

  err = maxval(abs(x - 1.0d0))
  print "(a, es10.3)", "f_pddrive: ||x - ones||_inf = ", err
  if (err > 1.0d-10) stop "accuracy check FAILED"
  print *, "f_pddrive: PASS"
  call slu_tpu_finalize()
end program f_pddrive
