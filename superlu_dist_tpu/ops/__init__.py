from superlu_dist_tpu.ops.dense import make_front_kernel, lu_nopivot
