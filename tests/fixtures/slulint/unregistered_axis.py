"""SLU120 true-positive fixture (mesh/spec hygiene): axis names that
are not declared in utils/meshreg.py, an in_specs arity that does not
match the wrapped function, and a donated spec-less argument.  jax
rejects NONE of these — a typo'd axis just silently replicates the
dimension, which is why the registry check exists."""
import jax
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, PartitionSpec as P


def panel_update(pool, piv):
    return pool + piv


def bad_mesh(devs):
    # flagged twice: neither "row" nor "col" is a registered axis
    return Mesh(devs, axis_names=("row", "col"))


def bad_specs(mesh, pool, piv):
    # flagged twice: "rows" (in_specs) and "rows" (out_specs) are not
    # registered axes ("snode" is — the typo the registry catches)
    fn = shard_map(panel_update, mesh=mesh,
                   in_specs=(P("rows"), P(None)),
                   out_specs=P("rows"))
    return fn(pool, piv)


def bad_arity(mesh, pool, piv):
    # flagged once: one spec for a two-argument function — jax reports
    # this as an opaque tree mismatch at trace time
    fn = shard_map(panel_update, mesh=mesh,
                   in_specs=(P("snode"),),
                   out_specs=P("snode"))
    return fn(pool, piv)


def bad_donation(mesh):
    # flagged once: donated argument 1 carries no P(...) spec — the
    # aliased buffer is replicated, so every device still reads it
    return jax.jit(shard_map(panel_update, mesh=mesh,
                             in_specs=(P("snode"), None),
                             out_specs=P("snode")),
                   donate_argnums=(1,))
