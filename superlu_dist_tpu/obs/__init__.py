"""Observability subsystem — the PROFlevel analog.

One layer owns all measurement machinery:

* ``obs.trace``   — structured span tracer (``SLU_TPU_TRACE=<path>``):
  nested spans with categories (phase / dispatch / kernel / comm /
  host-offload / verify / compile), emitted as Chrome trace-event JSON
  (Perfetto-loadable) plus a crash-safe JSONL sidecar, with a
  wall-clock anchor event for cross-rank alignment;
* ``obs.compilestats`` — the compile census: per-shape-key build
  records from every jit build site (trace/lower/compile seconds,
  persistent-cache hit/miss, bucket key, param count);
* ``obs.flightrec`` — the always-on-able flight recorder
  (``SLU_TPU_FLIGHTREC``): a bounded ring of recent spans dumped as a
  postmortem JSON artifact on structured errors, the bench watchdog,
  and SIGTERM;
* ``obs.metrics`` — serving-grade labeled counters/gauges/histograms
  (``SLU_TPU_METRICS``) with JSON + Prometheus exports and cross-rank
  aggregation;
* comm telemetry  — per-op counters on the tree collectives
  (``parallel/treecomm.py`` → ``utils.stats.CommStats``), the
  PROFlevel≥1 comm split;
* kernel-shape telemetry — structured per-dispatch records from both
  factorization executors and the device solve (the dgemm_mnk.dat
  analog);
* cross-rank stat reduction — ``utils.stats.Stats.reduce`` (min/max/avg
  + load-balance factor per phase, the sum-over-ranks PStatPrint).

See docs/OBSERVABILITY.md for the artifact formats and a worked
Perfetto example.
"""

from superlu_dist_tpu.obs.trace import (      # noqa: F401
    CATEGORIES, NULL_SPAN, NULL_TRACER, NullTracer, TeeTracer, Tracer,
    complete, enabled, get_tracer, install, span)
