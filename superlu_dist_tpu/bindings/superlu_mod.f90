! Fortran interface to the TPU-native SuperLU_DIST framework.
!
! Capability analog of the reference's handle-based Fortran-90 wrapper
! (FORTRAN/superlu_mod.f90 + superlu_c2f_dwrap.c): thin ISO_C_BINDING
! interfaces over the C API in slu_tpu.h.  Matrices are CSR with int64
! indices; B/X are column-major n x nrhs, as a Fortran caller lays them
! out naturally.
!
! Usage:
!   use superlu_tpu
!   info = slu_tpu_init(c_null_char)
!   info = slu_tpu_solve(n, nnz, indptr, indices, values, b, x, nrhs)
! Link against libslu_tpu.so (bindings/build.py) and the embedded-python
! libs: $(python3-config --embed --ldflags).

module superlu_tpu
  use iso_c_binding
  implicit none

  interface
     integer(c_int) function slu_tpu_init(backend) bind(C, name="slu_tpu_init")
       import :: c_int, c_char
       character(kind=c_char), dimension(*) :: backend
     end function slu_tpu_init

     integer(c_int) function slu_tpu_solve(n, nnz, indptr, indices, values, &
          b, x, nrhs) bind(C, name="slu_tpu_solve")
       import :: c_int, c_int64_t, c_double
       integer(c_int64_t), value :: n, nnz, nrhs
       integer(c_int64_t), dimension(*) :: indptr, indices
       real(c_double), dimension(*) :: values, b
       real(c_double), dimension(*) :: x
     end function slu_tpu_solve

     integer(c_int) function slu_tpu_factor(n, nnz, indptr, indices, values, &
          handle) bind(C, name="slu_tpu_factor")
       import :: c_int, c_int64_t, c_double
       integer(c_int64_t), value :: n, nnz
       integer(c_int64_t), dimension(*) :: indptr, indices
       real(c_double), dimension(*) :: values
       integer(c_int64_t) :: handle
     end function slu_tpu_factor

     integer(c_int) function slu_tpu_solve_factored(handle, n, b, x, nrhs) &
          bind(C, name="slu_tpu_solve_factored")
       import :: c_int, c_int64_t, c_double
       integer(c_int64_t), value :: handle, n, nrhs
       real(c_double), dimension(*) :: b
       real(c_double), dimension(*) :: x
     end function slu_tpu_solve_factored

     integer(c_int) function slu_tpu_free_handle(handle) &
          bind(C, name="slu_tpu_free_handle")
       import :: c_int, c_int64_t
       integer(c_int64_t), value :: handle
     end function slu_tpu_free_handle

     ! ---- full-surface API (superlu_c2f_dwrap.c:51-327 analog) --------
     ! Option handles carry the reference options surface: keys like
     ! "ColPerm", "RowPerm", "Fact", "IterRefine", "Trans", "Equil",
     ! "DiagInv"; values are enum names / "YES"/"NO" / numbers.

     integer(c_int) function slu_tpu_options_create(opt) &
          bind(C, name="slu_tpu_options_create")
       import :: c_int, c_int64_t
       integer(c_int64_t) :: opt
     end function slu_tpu_options_create

     integer(c_int) function slu_tpu_options_set(opt, key, val) &
          bind(C, name="slu_tpu_options_set")
       import :: c_int, c_int64_t, c_char
       integer(c_int64_t), value :: opt
       character(kind=c_char), dimension(*) :: key, val
     end function slu_tpu_options_set

     integer(c_int) function slu_tpu_options_get(opt, key, buf, buflen) &
          bind(C, name="slu_tpu_options_get")
       import :: c_int, c_int64_t, c_char
       integer(c_int64_t), value :: opt, buflen
       character(kind=c_char), dimension(*) :: key
       character(kind=c_char), dimension(*) :: buf
     end function slu_tpu_options_get

     integer(c_int) function slu_tpu_options_free(opt) &
          bind(C, name="slu_tpu_options_free")
       import :: c_int, c_int64_t
       integer(c_int64_t), value :: opt
     end function slu_tpu_options_free

     integer(c_int) function slu_tpu_solve_opts(opt, n, nnz, indptr, &
          indices, values, b, ldb, x, ldx, nrhs) &
          bind(C, name="slu_tpu_solve_opts")
       import :: c_int, c_int64_t, c_double
       integer(c_int64_t), value :: opt, n, nnz, ldb, ldx, nrhs
       integer(c_int64_t), dimension(*) :: indptr, indices
       real(c_double), dimension(*) :: values, b
       real(c_double), dimension(*) :: x
     end function slu_tpu_solve_opts

     integer(c_int) function slu_tpu_factor_opts(opt, n, nnz, indptr, &
          indices, values, handle) bind(C, name="slu_tpu_factor_opts")
       import :: c_int, c_int64_t, c_double
       integer(c_int64_t), value :: opt, n, nnz
       integer(c_int64_t), dimension(*) :: indptr, indices
       real(c_double), dimension(*) :: values
       integer(c_int64_t) :: handle
     end function slu_tpu_factor_opts

     ! Refactor with new values, same pattern: tier 1 = SamePattern,
     ! tier 2 = SamePattern_SameRowPerm (fact_t reuse tiers)
     integer(c_int) function slu_tpu_refactor(handle, nnz, values, tier) &
          bind(C, name="slu_tpu_refactor")
       import :: c_int, c_int64_t, c_double
       integer(c_int64_t), value :: handle, nnz, tier
       real(c_double), dimension(*) :: values
     end function slu_tpu_refactor

     integer(c_int) function slu_tpu_solve_factored_opts(handle, opt, n, &
          b, ldb, x, ldx, nrhs) bind(C, name="slu_tpu_solve_factored_opts")
       import :: c_int, c_int64_t, c_double
       integer(c_int64_t), value :: handle, opt, n, ldb, ldx, nrhs
       real(c_double), dimension(*) :: b
       real(c_double), dimension(*) :: x
     end function slu_tpu_solve_factored_opts

     ! Named statistics (PStatPrint analog): "FACT", "SOLVE", "REFINE",
     ! "FACT_FLOPS", "TINY_PIVOTS", "BERR", "NNZ_L", ...
     integer(c_int) function slu_tpu_stat_get(handle, name, val) &
          bind(C, name="slu_tpu_stat_get")
       import :: c_int, c_int64_t, c_char, c_double
       integer(c_int64_t), value :: handle
       character(kind=c_char), dimension(*) :: name
       real(c_double) :: val
     end function slu_tpu_stat_get

     subroutine slu_tpu_finalize() bind(C, name="slu_tpu_finalize")
     end subroutine slu_tpu_finalize
  end interface
end module superlu_tpu
