"""superlu_dist_tpu — a TPU-native distributed sparse direct solver framework.

A brand-new framework with the capabilities of SuperLU_DIST 6.4 (reference:
``pdgssvx``, SRC/pdgssvx.c:505): solve sparse A·X = B by supernodal Gaussian
elimination with static pivoting (GESP), followed by iterative refinement.

Architecture (TPU-first, not a port):

* **Host analysis layer** (native C++ behind a ctypes seam, Python twins
  as the specification oracle): equilibration, MC64-style maximum-product
  row matching (+ AWPM), fill-reducing column orderings (multilevel ND
  with threaded subtrees, MMD, MMD_ATA, COLAMD), elimination tree,
  threaded supernodal symbolic factorization.  This mirrors the
  reference's L4 preprocessing layer (SURVEY.md §1) but is organised
  around building *static-shape batched compute plans* for XLA instead
  of MPI message schedules.
* **TPU numeric core**: a level-batched supernodal *multifrontal*
  factorization.  All frontal matrices at one elimination-tree level are
  independent; they are bucketed into padded static shapes and factored as a
  single vmapped dense partial-LU + Schur-complement GEMM on the MXU
  (the reference's flops hot spot, dSchCompUdt-2Ddynamic.c:566).  Extend-add
  ("scatter", dscatter.c:111) becomes precomputed flat gather/scatter-add.
* **Distribution**: a 2D logical device mesh (``jax.sharding.Mesh``) is the
  analog of the reference's 2D MPI process grid (superlu_grid.c:31); fronts
  are sharded over the mesh with ``shard_map`` and extend-add contributions
  combined with ``psum`` over ICI — XLA collectives instead of MPI.
* **Precision**: TPUs have no fp64 MXU; the default TPU path factors in
  float32 and recovers double-precision residuals via iterative refinement
  in float64 — the reference's own GESP + ReplaceTinyPivot + IR design
  (pdgstrf2.c:218, pdgsrfs.c:120) is the justification.  Full f64/c128
  paths run on the CPU backend.
"""

from superlu_dist_tpu.utils.options import (
    Options, Fact, ColPerm, RowPerm, IterRefine, Trans, YesNo,
    RecoveryPolicy, set_default_options,
)
from superlu_dist_tpu.utils.stats import Stats, SolveReport
from superlu_dist_tpu.utils.errors import (
    SuperLUError, SingularMatrixError, NumericBreakdownError,
    PatternMismatchError, RefactorRollbackError)
from superlu_dist_tpu.sparse.formats import SparseCSR, SparseCSC


def __getattr__(name):
    # lazy: the driver pulls in jax; keep light imports (io, formats) fast
    if name in ("gssvx", "gssvx_ABglobal", "gssvx_dist", "LUFactorization",
                "refactor"):
        import importlib
        mod = importlib.import_module("superlu_dist_tpu.drivers.gssvx")
        return getattr(mod, name)
    if name == "read_matrix":
        import importlib
        mod = importlib.import_module("superlu_dist_tpu.io.readers")
        return mod.read_matrix
    if name in ("save_lu", "load_lu"):
        # crash-consistent handle persistence (docs/RELIABILITY.md)
        import importlib
        mod = importlib.import_module("superlu_dist_tpu.persist")
        return getattr(mod, name)
    raise AttributeError(name)

__version__ = "0.1.0"


def get_version_number():
    """Analog of superlu_dist_GetVersionNumber (superlu_dist_version.c)."""
    major, minor, bugfix = (int(x) for x in __version__.split("."))
    return major, minor, bugfix
