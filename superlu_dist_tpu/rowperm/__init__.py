from superlu_dist_tpu.rowperm.equil import gsequ, laqgs
from superlu_dist_tpu.rowperm.matching import maximum_product_matching
