#!/usr/bin/env python
"""Rank-failure gate: kill -9 a rank mid-factor, diagnose, shrink, resume.

The ISSUE 8 acceptance cases, end to end (gate contract shared with the
other scripts/ci_gates.sh gates: any regression asserts/raises, exiting
non-zero with the diagnostic on stderr):

  Phase A — diagnosis (ft=abort, 3 ranks): rank 1 is SIGKILLed before
     its 4th public collective while rank 0 factors.  BOTH survivors
     must raise RankFailureError naming rank 1 + op + call site within
     2x SLU_TPU_COMM_TIMEOUT_S of the death (wall-clocked from the
     victim's exit), with an armed HangWatchdog that must NOT fire
     (exit code 3 = the old unbounded-hang behavior = gate failure).

  Phase B — recovery (ft=shrink, 2 ranks): rank 0 (the factoring root)
     is SIGKILLed after dispatch group 3 with interval checkpoints
     armed; the survivor shrinks to a solo epoch, RESUMES the durable
     checkpoint frontier, and completes — and its L/U digest is
     BITWISE-identical to an undisturbed run's.

Exit 0 = pass.  A few tens of seconds on CPU.
"""

import os
import signal
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")

from superlu_dist_tpu.utils import tols  # noqa: E402

TIMEOUT_S = 1.0          # SLU_TPU_COMM_TIMEOUT_S for the victims
DETECT_BUDGET_S = 2 * TIMEOUT_S + 5.0   # 2x timeout + subprocess slack

_RANK = r"""
import os, sys, time, hashlib
import numpy as np
sys.path.insert(0, {repo!r})

def main():
    import jax
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_enable_x64", True)
    rank, n_ranks, name = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]
    from superlu_dist_tpu.models.gallery import poisson3d
    from superlu_dist_tpu.parallel.recover import (
        pgssvx_ft, RowBlockSource, VectorBlockSource, FT_EVENTS)
    from superlu_dist_tpu.utils.errors import RankFailureError
    from superlu_dist_tpu.utils.options import Options
    from superlu_dist_tpu.testing.chaos import HANG_EXIT, HangWatchdog

    a = poisson3d(6)
    xt = np.random.default_rng(0).standard_normal(a.n_rows)
    b = a.matvec(xt)
    opts = Options(factor_dtype="float64", ckpt_every=2,
                   ckpt_dir=os.environ.get("FT_CKDIR", ""))
    lu_out = {{}}
    with HangWatchdog(90.0, exit_code=HANG_EXIT):
        try:
            x, info = pgssvx_ft(name, n_ranks, rank, opts,
                                RowBlockSource(a), VectorBlockSource(b),
                                max_len=a.n_rows, lu_out=lu_out)
        except RankFailureError as e:
            print("OUTCOME", rank, "rank-failure", time.time(),
                  ",".join(map(str, e.dead_ranks)), e.op, e.site,
                  flush=True)
            return
    h = hashlib.sha256()
    lu = lu_out.get("lu")
    if lu is not None and getattr(lu, "numeric", None) is not None:
        for lp, up in lu.numeric.fronts:
            h.update(np.ascontiguousarray(np.asarray(lp)).tobytes())
            h.update(np.ascontiguousarray(np.asarray(up)).tobytes())
    print("OUTCOME", rank, "solved", time.time(), info, len(FT_EVENTS),
          float(np.abs(x - xt).max()), h.hexdigest(),
          lu_out.get("recovered"), flush=True)

if __name__ == "__main__":
    main()
"""


def _spawn(workdir, name, rank, n_ranks, ft, chaos=None):
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               SLU_TPU_COMM_TIMEOUT_S=str(TIMEOUT_S),
               SLU_TPU_FT=ft,
               FT_CKDIR=os.path.join(workdir, "ck"))
    env.pop("SLU_TPU_CHAOS", None)
    if chaos:
        env["SLU_TPU_CHAOS"] = chaos
    script = os.path.join(workdir, f"rank{rank}.py")
    with open(script, "w") as f:
        f.write(_RANK.format(repo=REPO))
    return subprocess.Popen(
        [sys.executable, script, str(rank), str(n_ranks), name],
        env=env, cwd=REPO, stdout=subprocess.PIPE,
        stderr=subprocess.PIPE, text=True)


def _finish(p, timeout=240):
    out, err = p.communicate(timeout=timeout)
    lines = [ln.split() for ln in out.splitlines()
             if ln.startswith("OUTCOME")]
    return p.returncode, (lines[-1] if lines else None), err


def phase_a(workdir):
    print("phase A: 3 ranks, kill -9 rank 1 mid-factor (ft=abort)")
    name = f"/slu_gate_ftA_{os.getpid()}"
    procs = {0: _spawn(workdir, name, 0, 3, "abort")}
    time.sleep(0.3)
    procs[1] = _spawn(workdir, name, 1, 3, "abort",
                      chaos="kill_rank=1,kill_op=4")
    procs[2] = _spawn(workdir, name, 2, 3, "abort")
    rc1, _, err1 = _finish(procs[1])
    t_death = time.time()
    assert rc1 == -signal.SIGKILL, \
        f"victim rank 1 exited {rc1}, expected SIGKILL: {err1[-2000:]}"
    for r in (0, 2):
        rc, line, err = _finish(procs[r])
        assert rc == 0, (f"survivor rank {r} exited {rc} "
                         f"(3 = HangWatchdog fired): {err[-2000:]}")
        assert line is not None and line[2] == "rank-failure", (r, line)
        t_raise = float(line[3])
        assert t_raise - t_death <= DETECT_BUDGET_S, \
            (f"survivor rank {r} took {t_raise - t_death:.1f}s "
             f"> {DETECT_BUDGET_S:.1f}s after the death")
        assert line[4] == "1", f"dead set {line[4]!r} != victim rank 1"
        assert line[5] and line[6], f"op/site missing: {line}"
        print(f"  rank {r}: RankFailureError dead=1 op={line[5]} "
              f"site={line[6]} (+{t_raise - t_death:.1f}s)")


def phase_b(workdir):
    print("phase B: shrink recovery resumes the frontier bitwise")
    # undisturbed reference (same options incl. checkpoint arming)
    name = f"/slu_gate_ftBr_{os.getpid()}"
    rc, line, err = _finish(_spawn(workdir, name, 0, 1, "shrink"))
    assert rc == 0 and line[2] == "solved", (rc, line, err[-2000:])
    ref_digest = line[7]

    name = f"/slu_gate_ftB_{os.getpid()}"
    procs = {0: _spawn(workdir, name, 0, 2, "shrink",
                       chaos="kill_rank=0@group=3")}
    time.sleep(0.3)
    procs[1] = _spawn(workdir, name, 1, 2, "shrink")
    rc0, _, _ = _finish(procs[0])
    assert rc0 == -signal.SIGKILL, f"root exited {rc0}, expected SIGKILL"
    rc, line, err = _finish(procs[1])
    assert rc == 0, f"survivor exited {rc}: {err[-2000:]}"
    assert line[2] == "solved" and line[4] == "0", line
    assert line[5] == "1", f"ft_events {line[5]!r} != 1"
    assert float(line[6]) < tols.RESID_GATE, f"solution error {line[6]}"
    assert line[7] == ref_digest, "recovered L/U differs from the " \
        "undisturbed run (resume was not bitwise)"
    assert line[8] == "True", "lu_out['recovered'] not set"
    print(f"  survivor: shrink epoch solved, digest {line[7][:12]}… "
          "== undisturbed (bitwise)")


def main():
    with tempfile.TemporaryDirectory(prefix="slu_ft_gate_") as workdir:
        phase_a(workdir)
    with tempfile.TemporaryDirectory(prefix="slu_ft_gate_") as workdir:
        phase_b(workdir)
    print("rank-failure gate OK: survivors diagnose within budget, "
          "shrink resumes bitwise")


if __name__ == "__main__":
    main()
