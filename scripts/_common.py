"""Shared setup for the CPU-backend measurement scripts in this
directory (config4_virtual, df64_scale, pgssvx_scale).

Not used by the TPU-session scripts (baseline_fixtures_tpu,
df64_cost_tpu) — those must NOT pin the CPU platform.
"""

import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def cpu_session(n_devices: int = 1, x64: bool = True):
    """Pin the CPU platform (with `n_devices` virtual devices), enable
    x64, and point jax at the persistent compile cache.  Must run before
    the first jax operation; any XLA_FLAGS the caller needs go into the
    environment BEFORE this call (backend init snapshots them).
    Returns the configured jax module."""
    sys.path.insert(0, REPO)
    import jax
    jax.config.update("jax_platforms", "cpu")
    if n_devices > 1:
        jax.config.update("jax_num_cpu_devices", n_devices)
    if x64:
        jax.config.update("jax_enable_x64", True)
    from superlu_dist_tpu.utils.jaxcache import enable_compile_cache
    enable_compile_cache()
    return jax
