#!/usr/bin/env python
"""Complex-valued solve — analog of EXAMPLE/pzdrive.c (the z-twin of
pddrive; here the same templated pipeline handles complex dtypes).

    python examples/pzdrive.py [matrix.cua] [--backend cpu]
"""

import sys
import os

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
from examples._common import (pin_cpu_if_requested, load_matrix, make_rhs,
                              report)


def main():
    pin_cpu_if_requested()
    import superlu_dist_tpu as slu

    a, src = load_matrix(complex_=True)
    print(f"matrix: {src}  n={a.n_rows} nnz={a.nnz} dtype={a.data.dtype}")
    xtrue, b = make_rhs(a)
    x, lu, stats, info = slu.gssvx(slu.Options(), a, b)
    assert info == 0
    resid = report("pzdrive", a, b, x, xtrue, stats)
    assert resid < 1e-10
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
