#!/usr/bin/env python
"""Two independent COMPLEX solves on two device grids — analog of
EXAMPLE/pzdrive4.c (the z-twin of pddrive4: two sub-grids of the global
communicator each solve their own system).  TPU-native: the mesh's
devices partition into two sub-meshes; each runs a full gssvx pipeline
on the complex fixture (cg20.cua).

    python examples/pzdrive4.py [matrix.cua] [--backend cpu]

Run with the CPU backend (8 virtual devices via the test conftest
recipe) to see both sub-grids active; on one real chip the grids
degenerate to 1x1 and the example still runs both solves.
"""

import sys
import os

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
from examples._common import (pin_cpu_if_requested, load_matrix, make_rhs,
                              report)


def main():
    pin_cpu_if_requested()
    import jax
    import superlu_dist_tpu as slu
    from superlu_dist_tpu.parallel.grid import gridinit

    a, src = load_matrix(complex_=True)
    print(f"matrix: {src}  n={a.n_rows} nnz={a.nnz}")
    devices = jax.devices()
    half = max(len(devices) // 2, 1)
    if len(devices) >= 2:
        grids = [gridinit(half, 1, devices[:half]),
                 gridinit(len(devices) - half, 1, devices[half:])]
    else:
        grids = [None, None]     # single device: two plain solves

    rc = 0
    for g, seed in zip(grids, (0, 1)):
        xtrue, b = make_rhs(a, seed=seed)
        x, lu, stats, info = slu.gssvx(slu.Options(), a, b, grid=g)
        assert info == 0
        shape = (None if g is None else
                 tuple(int(s) for s in g.mesh.devices.shape))
        resid = report(f"pzdrive4 grid={shape} seed={seed}", a, b, x,
                       xtrue, stats)
        rc |= resid > 1e-10
    return rc


if __name__ == "__main__":
    raise SystemExit(main())
