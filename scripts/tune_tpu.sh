#!/bin/bash
# On-hardware tuning sweep: runs bench.py over problem size x executor
# granularity x blocking x dtype/precision and appends one JSON line per
# config to tune_results.jsonl.  Run when a real chip is reachable:
#
#   bash scripts/tune_tpu.sh [results_file]
#
# Ordered SMALLEST-FIRST so every row yields data even if the session dies
# mid-sweep (round-2 lesson: a sweep that opens with the largest size can
# time out in compile and produce zero rows).  Each run reuses the
# persistent compile cache (.cache/jax), so later configs sharing kernel
# shapes start fast; per-config watchdogs (BENCH_DEADLINE_S) are sized to
# the problem, inside an outer timeout.
set -u
cd "$(dirname "$0")/.."
OUT="${1:-tune_results.jsonl}"
run() {
  local deadline="$1"; shift
  echo "== $* ==" >&2
  env "$@" BENCH_REPS=3 BENCH_DEADLINE_S="$deadline" \
    timeout $((deadline + 120)) python bench.py \
    >> "$OUT" 2>> "${OUT%.jsonl}.err"
}

# problem-size ladder at default blocking — small sizes compile in minutes
# and validate the chip before anything expensive starts
run 600  BENCH_NX=16
run 600  BENCH_NX=24
run 900  BENCH_NX=32
run 1200 BENCH_NX=40
run 1500 BENCH_NX=48

# dispatch granularity: level = one program per elimination level (~13
# after amalgamation); fused = the whole factorization as ONE XLA
# program (zero dispatch overhead, no batch padding — viable again at
# ~45 groups)
run 900  BENCH_NX=32 BENCH_GRANULARITY=level
run 1500 BENCH_NX=48 BENCH_GRANULARITY=level
run 1200 BENCH_NX=32 BENCH_GRANULARITY=fused
run 1800 BENCH_NX=48 BENCH_GRANULARITY=fused

# amalgamation tolerance (the round-3 MFU lever) and padding ladder
run 900  BENCH_NX=32 BENCH_AMALG=0
run 900  BENCH_NX=32 BENCH_AMALG=1.5
run 900  BENCH_NX=32 BENCH_GROWTH=1.2
run 1500 BENCH_NX=48 BENCH_GROWTH=1.2

# blocking variants (panel width cap)
run 900  BENCH_NX=32 BENCH_MAXSUPER=512
run 900  BENCH_NX=32 BENCH_MAXSUPER=2048

# MXU pass count for the f32 Schur GEMMs (HIGH halves the passes; IR
# absorbs the precision loss on well-conditioned systems)
run 900  BENCH_NX=32 SLU_TPU_PRECISION=high
run 1500 BENCH_NX=48 SLU_TPU_PRECISION=high

# native-MXU-rate factors (IR recovers f64 residuals; more steps)
run 900  BENCH_NX=32 BENCH_DTYPE=bfloat16

# irregular-graph family (audikw_1-class surrogate, BASELINE config 5)
run 1200 BENCH_NX=32 BENCH_MATRIX=geo3d

# largest single-chip sizes (compact fronts; offload auto-engages if the
# factor bytes outgrow HBM).  NX=80 is n=512,000 — the BASELINE config-4
# class pushed as far as one chip + host offload goes: pool 8.9 GB +
# fronts 5.8 GB ~ 14.7 GB padded f32, so the factor panels are forced to
# stream to host RAM to leave transient headroom.
run 1800 BENCH_NX=56
run 2400 BENCH_NX=64
run 3000 BENCH_NX=72 SLU_TPU_FRONT_BYTES_LIMIT=4000000000
run 3600 BENCH_NX=80 SLU_TPU_FRONT_BYTES_LIMIT=4000000000

grep -h '"value"' "$OUT" | python -c '
import json, sys
rows = [json.loads(l) for l in sys.stdin if l.strip()]
rows.sort(key=lambda r: -(r.get("value") or 0))
for r in rows:
    print(f"{r.get('"'"'value'"'"'):>10} GF/s  {r.get('"'"'metric'"'"','"'"''"'"')}  "
          f"blocking={r.get('"'"'blocking'"'"')} gran={r.get('"'"'granularity'"'"')} "
          f"resid={r.get('"'"'residual'"'"')}")
'
