"""Options-surface coverage: trans, MY_PERMC/MY_PERMR, SLU_SINGLE,
singularity localization.

These are the reference's superlu_dist_options_t semantics
(SRC/superlu_defs.h:628-657, defaults SRC/util.c:376-401) that VERDICT r1
flagged as accepted-but-ignored or untestable.
"""

import numpy as np
import pytest

from superlu_dist_tpu.drivers.gssvx import gssvx
from superlu_dist_tpu.models.gallery import (
    poisson2d, random_sparse, convection_diffusion_2d)
from superlu_dist_tpu.sparse.formats import SparseCSR, coo_to_csr
from superlu_dist_tpu.utils.options import (
    Options, ColPerm, RowPerm, IterRefine, Trans)
from superlu_dist_tpu.utils.errors import SingularMatrixError


def test_trans_solve_unsymmetric():
    """options.trans=TRANS must solve AᵀX = B through the same factors."""
    a = convection_diffusion_2d(9)           # genuinely unsymmetric
    n = a.n_rows
    rng = np.random.default_rng(0)
    xtrue = rng.standard_normal(n)
    b = a.transpose().matvec(xtrue)          # b = Aᵀ·xtrue
    x, lu, stats, info = gssvx(Options(trans=Trans.TRANS), a, b)
    assert info == 0
    np.testing.assert_allclose(x, xtrue, rtol=1e-8, atol=1e-8)
    # and the same factorization still solves A·x = b2 via NOTRANS handle
    b2 = a.matvec(xtrue)
    x2 = lu.solve_factored(b2)
    np.testing.assert_allclose(x2, xtrue, rtol=1e-6, atol=1e-6)


def test_trans_solve_multiple_rhs():
    a = convection_diffusion_2d(8)
    n = a.n_rows
    rng = np.random.default_rng(1)
    xtrue = rng.standard_normal((n, 3))
    b = a.transpose().matvec(xtrue)
    x, _, _, info = gssvx(Options(trans=Trans.TRANS), a, b)
    assert info == 0
    np.testing.assert_allclose(x, xtrue, rtol=1e-8, atol=1e-8)


def test_conj_trans_complex():
    """CONJ solves Aᴴ·x = b."""
    a = random_sparse(48, density=0.1, seed=3, dtype=np.complex128)
    n = a.n_rows
    rng = np.random.default_rng(2)
    xtrue = rng.standard_normal(n) + 1j * rng.standard_normal(n)
    at = a.transpose()
    ah = SparseCSR(n, n, at.indptr, at.indices, at.data.conj())
    b = ah.matvec(xtrue)
    x, _, _, info = gssvx(Options(trans=Trans.CONJ), a, b)
    assert info == 0
    np.testing.assert_allclose(x, xtrue, rtol=1e-8, atol=1e-8)


def test_my_permc_and_permr():
    """MY_PERMC/MY_PERMR must honor user-supplied permutations (these were
    untestable in r1: the fields were class attributes, not dataclass
    fields).  The user row perm is one that restores diagonal dominance of
    a row-scrambled Laplacian — the reference's use case of feeding a
    known-good pivot order back in."""
    from superlu_dist_tpu.sparse.formats import invert_perm
    a = poisson2d(8)
    n = a.n_rows
    rng = np.random.default_rng(4)
    p = rng.permutation(n).astype(np.int64)
    ap = a.permute(perm_r=p)                 # rows scrambled
    perm_r = invert_perm(p)                  # un-scrambles: ap[perm_r] = a
    perm_c = rng.permutation(n).astype(np.int64)   # any symmetric reorder
    xtrue = rng.standard_normal(n)
    b = ap.matvec(xtrue)
    opts = Options(col_perm=ColPerm.MY_PERMC, user_perm_c=perm_c,
                   row_perm=RowPerm.MY_PERMR, user_perm_r=perm_r)
    x, lu, stats, info = gssvx(opts, ap, b)
    assert info == 0
    np.testing.assert_allclose(x, xtrue, rtol=1e-7, atol=1e-7)
    assert np.array_equal(lu.row_order, perm_r)


def test_awpm_rowperm():
    """LargeDiag_AWPM (the HWPM analog) must produce a valid row order that
    solves matrices needing pivoting, without scalings."""
    from superlu_dist_tpu.models.gallery import random_sparse
    from superlu_dist_tpu.rowperm.matching import (
        approximate_weight_matching)
    a = random_sparse(80, density=0.08, seed=12)
    order = approximate_weight_matching(a)
    assert sorted(order) == list(range(80))
    # the matched diagonal must be structurally nonzero everywhere
    ad = a.permute(perm_r=order).to_dense()
    assert (np.abs(np.diag(ad)) > 0).all()
    xt = np.random.default_rng(1).standard_normal(80)
    b = a.matvec(xt)
    x, lu, stats, info = gssvx(
        Options(row_perm=RowPerm.LargeDiag_AWPM), a, b)
    assert info == 0
    np.testing.assert_allclose(x, xt, rtol=1e-7, atol=1e-7)
    assert np.all(lu.r1 == 1) and np.all(lu.c1 == 1)


@pytest.mark.slow
def test_slu_single_refinement():
    """SLU_SINGLE refines with an f32 residual: converges to ~single eps,
    not double."""
    a = poisson2d(10)
    n = a.n_rows
    xtrue = np.random.default_rng(5).standard_normal(n)
    b = a.matvec(xtrue)
    opts = Options(iter_refine=IterRefine.SLU_SINGLE, factor_dtype="float32")
    x, lu, stats, info = gssvx(opts, a, b)
    assert info == 0
    rel = np.linalg.norm(x - xtrue) / np.linalg.norm(xtrue)
    assert rel < 1e-4                         # single-precision class
    assert lu.berrs and lu.berrs[-1] < 1e-5


def test_sp_ienv_env_tier(monkeypatch):
    """NREL/NSUP env overrides (the sp_ienv_dist tier, SRC/sp_ienv.c)."""
    from superlu_dist_tpu.utils.options import set_default_options
    monkeypatch.setenv("NREL", "7")
    monkeypatch.setenv("NSUP", "99")
    o = set_default_options()
    assert o.relax == 7 and o.max_supernode == 99
    monkeypatch.setenv("NREL", "bogus")
    assert set_default_options().relax == Options().relax


def test_print_options_echo(capsys):
    """print_options_dist analog + PrintStat echo."""
    from superlu_dist_tpu.utils.options import print_options
    s = print_options(Options())
    assert "col_perm" in s and "ND_AT_PLUS_A" in s
    a = poisson2d(5)
    gssvx(Options(print_stat=True), a, np.ones(a.n_rows))
    out = capsys.readouterr().out
    assert ".. options:" in out and "FACT" in out


def test_singularity_info_is_localized():
    """info must be the 1-based first zero-pivot column in the final
    labeling (pdgstrf.c:1920-1924), not a bare flag."""
    n = 6
    rows = list(range(n)) + [0]
    cols = list(range(n)) + [5]
    vals = [1.0, 1.0, 1.0, 0.0, 1.0, 1.0, 0.5]    # exact zero at column 3
    a = coo_to_csr(n, n, rows, cols, np.array(vals))
    opts = Options(replace_tiny_pivot=False, row_perm=RowPerm.NOROWPERM,
                   equil=False, col_perm=ColPerm.NATURAL,
                   iter_refine=IterRefine.NOREFINE)
    x, lu, stats, info = gssvx(opts, a, np.ones(n))
    assert x is None and info > 0
    # original column 3 in the final (postordered) labeling:
    expected = int(np.flatnonzero(lu.sf.perm == 3)[0]) + 1
    assert info == expected
    with pytest.raises(SingularMatrixError):
        lu.solve_factored(np.ones(n))
