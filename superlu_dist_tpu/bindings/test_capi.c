/* Self-checking C client of the slu_tpu API (the analog of the
 * reference's EXAMPLE/f_5x5-style binding smoke tests).  Builds a
 * diagonally-dominant tridiagonal system, solves it through the one-shot
 * path and the factor/solve-factored handle path, and verifies both
 * against the fabricated solution.  Exit code 0 = PASS. */

#include "slu_tpu.h"

#include <math.h>
#include <stdio.h>
#include <stdlib.h>

int main(void) {
  const int64_t n = 50;
  int64_t* indptr = malloc((n + 1) * sizeof(int64_t));
  int64_t* indices = malloc(3 * n * sizeof(int64_t));
  double* values = malloc(3 * n * sizeof(double));
  int64_t nnz = 0;
  indptr[0] = 0;
  for (int64_t i = 0; i < n; ++i) {
    if (i > 0) { indices[nnz] = i - 1; values[nnz++] = -1.0; }
    indices[nnz] = i; values[nnz++] = 4.0;
    if (i < n - 1) { indices[nnz] = i + 1; values[nnz++] = -1.0; }
    indptr[i + 1] = nnz;
  }
  double* xt = malloc(n * sizeof(double));
  double* b = malloc(n * sizeof(double));
  double* x = malloc(n * sizeof(double));
  for (int64_t i = 0; i < n; ++i) xt[i] = 1.0 + 0.01 * (double)i;
  for (int64_t i = 0; i < n; ++i) {
    b[i] = 4.0 * xt[i];
    if (i > 0) b[i] -= xt[i - 1];
    if (i < n - 1) b[i] -= xt[i + 1];
  }

  if (slu_tpu_init("cpu") != 0) { printf("init FAIL\n"); return 1; }

  int info = slu_tpu_solve(n, nnz, indptr, indices, values, b, x, 1);
  if (info != 0) { printf("solve info=%d FAIL\n", info); return 1; }
  double err = 0.0;
  for (int64_t i = 0; i < n; ++i) err = fmax(err, fabs(x[i] - xt[i]));
  if (err > 1e-10) { printf("one-shot err=%g FAIL\n", err); return 1; }

  int64_t h = 0;
  info = slu_tpu_factor(n, nnz, indptr, indices, values, &h);
  if (info != 0) { printf("factor info=%d FAIL\n", info); return 1; }
  for (int64_t i = 0; i < n; ++i) b[i] *= 2.0;   /* new rhs, same A */
  info = slu_tpu_solve_factored(h, n, b, x, 1);
  if (info != 0) { printf("refactored solve info=%d FAIL\n", info); return 1; }
  err = 0.0;
  for (int64_t i = 0; i < n; ++i) err = fmax(err, fabs(x[i] - 2.0 * xt[i]));
  if (err > 1e-10) { printf("factored err=%g FAIL\n", err); return 1; }
  if (slu_tpu_free_handle(h) != 0) { printf("free FAIL\n"); return 1; }
  if (slu_tpu_free_handle(h) != -3) { printf("double-free FAIL\n"); return 1; }

  /* ---- full-surface: options + trans + strided nrhs + refactor + stats */
  int64_t opt = 0;
  if (slu_tpu_options_create(&opt) != 0) { printf("optc FAIL\n"); return 1; }
  if (slu_tpu_options_set(opt, "ColPerm", "MMD_AT_PLUS_A") != 0 ||
      slu_tpu_options_set(opt, "Trans", "TRANS") != 0 ||
      slu_tpu_options_set(opt, "IterRefine", "SLU_DOUBLE") != 0) {
    printf("optset FAIL\n"); return 1;
  }
  if (slu_tpu_options_set(opt, "NoSuchKey", "1") != -5) {
    printf("optset bad-key FAIL\n"); return 1;
  }
  char buf[32];
  if (slu_tpu_options_get(opt, "Trans", buf, sizeof buf) != 0 ||
      buf[0] != 'T') { printf("optget FAIL\n"); return 1; }

  /* A is symmetric here, so the TRANS solve must reproduce xt; use a
   * strided (ldb=n+3) 2-RHS layout to exercise the ld contract */
  const int64_t ld = n + 3;
  double* b2 = calloc(ld * 2, sizeof(double));
  double* x2 = calloc(ld * 2, sizeof(double));
  for (int64_t i = 0; i < n; ++i) {      /* b columns: b, 3b (b was 2x) */
    b2[i] = b[i] / 2.0;
    b2[ld + i] = 3.0 * b[i] / 2.0;
  }
  int64_t h2 = 0;
  info = slu_tpu_factor_opts(opt, n, nnz, indptr, indices, values, &h2);
  if (info != 0) { printf("factor_opts info=%d FAIL\n", info); return 1; }
  info = slu_tpu_solve_factored_opts(h2, opt, n, b2, ld, x2, ld, 2);
  if (info != 0) { printf("sfo info=%d FAIL\n", info); return 1; }
  err = 0.0;
  for (int64_t i = 0; i < n; ++i) {
    err = fmax(err, fabs(x2[i] - xt[i]));
    err = fmax(err, fabs(x2[ld + i] - 3.0 * xt[i]));
  }
  if (err > 1e-10) { printf("strided trans err=%g FAIL\n", err); return 1; }

  /* refactor with scaled values (SamePattern tier), re-solve */
  double* v2 = malloc(nnz * sizeof(double));
  for (int64_t k = 0; k < nnz; ++k) v2[k] = 4.0 * values[k];
  if (slu_tpu_refactor(h2, nnz, v2, 1) != 0) {
    printf("refactor FAIL\n"); return 1;
  }
  info = slu_tpu_solve_factored_opts(h2, opt, n, b2, ld, x2, ld, 2);
  if (info != 0) { printf("post-refactor info=%d FAIL\n", info); return 1; }
  err = 0.0;
  for (int64_t i = 0; i < n; ++i)
    err = fmax(err, fabs(x2[i] - 0.25 * xt[i]));
  if (err > 1e-10) { printf("refactor err=%g FAIL\n", err); return 1; }

  double sv = -1.0;
  if (slu_tpu_stat_get(h2, "FACT", &sv) != 0 || sv < 0.0) {
    printf("stat FACT FAIL\n"); return 1;
  }
  if (slu_tpu_stat_get(h2, "NNZ_L", &sv) != 0 || sv < (double)n) {
    printf("stat NNZ_L FAIL\n"); return 1;
  }
  if (slu_tpu_stat_get(h2, "NoSuchStat", &sv) != -5) {
    printf("stat bad-name FAIL\n"); return 1;
  }
  if (slu_tpu_free_handle(h2) != 0 || slu_tpu_options_free(opt) != 0) {
    printf("free2 FAIL\n"); return 1;
  }

  printf("C API PASS (err one-shot + factored + full-surface <= 1e-10)\n");
  return 0;
}
