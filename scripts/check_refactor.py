#!/usr/bin/env python
"""Refactor-consistency gate: the crash-consistent same-pattern
refactorization contract, proven end to end (CPU, tens of seconds).

Four phases:

1. **Refactor ≡ fresh factor, bitwise** — ``refactor(handle,
   new_values)`` over a drifted-values gallery matrix must produce
   factors whose solves are bitwise identical to an independent handle
   refreshed through the driver's ``Fact=SamePattern_SameRowPerm``
   tier, with ZERO symbolic seconds and ZERO fresh-compile seconds
   (symbolic fact, FactorPlan, and compiled programs reused by
   construction).

2. **kill -9 mid-refactor, old state serves** — a child process
   refactors a persisted bundle's handle under
   ``SLU_TPU_CHAOS=kill_refactor@step=0`` (a REAL SIGKILL after the
   new values are staged, before anything is adopted): the parent must
   see rc=-9, and the bundle must still load and solve **bitwise
   identical** to before — an interrupted refactor leaves the previous
   consistent state.

3. **Rolling fleet refactor under chaos, zero dropped** — a live
   3-replica fleet takes ``fleet.refactor(key, values)`` under
   concurrent traffic: every ticket delivered (zero dropped/errored),
   post-roll answers bitwise vs the SamePattern baseline.

4. **Failed canary rolls back every swapped replica** — a
   ``poison_values`` chaos refactor must raise
   ``RefactorRollbackError`` with the fleet still serving the previous
   factors bitwise (no replica left on a poisoned bundle).

Exit 0 = pass.  One gate of scripts/ci_gates.sh (the consolidated CI
entry point, shared timeout/exit contract): any regression — a recompile,
a drifted X, a lost ticket, a poisoned refactor surviving its gate —
raises/asserts, which exits non-zero with the diagnostic on stderr.
"""

import os
import subprocess
import sys
import tempfile
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np  # noqa: E402


def _drift(a, scale=2.0, shift=0.01):
    return type(a)(a.n_rows, a.n_cols, a.indptr, a.indices,
                   a.data * scale + shift)


def _check_bitwise_and_zero_recompile():
    import dataclasses

    from superlu_dist_tpu.drivers.gssvx import gssvx, refactor
    from superlu_dist_tpu.models.gallery import poisson2d
    from superlu_dist_tpu.obs.compilestats import COMPILE_STATS
    from superlu_dist_tpu.utils.options import Fact, Options
    from superlu_dist_tpu.utils.stats import Stats

    for executor in ("fused", "stream", "mega"):
        a = poisson2d(8)
        b = np.arange(1, a.n_rows + 1, dtype=np.float64)
        opts = Options(executor=executor)
        a2 = _drift(a)
        _, lu_base, _, info = gssvx(opts, a, b, stats=Stats())
        assert info == 0
        _, lu_base2, _, info2 = gssvx(
            dataclasses.replace(opts, fact=Fact.SamePattern_SameRowPerm),
            a2, b, lu=lu_base, stats=Stats())
        assert info2 == 0

        _, lu, _, _ = gssvx(opts, a, b, stats=Stats())
        marker = COMPILE_STATS.marker()
        st = Stats()
        refactor(lu, a2, stats=st)
        assert np.array_equal(
            np.asarray(lu.solve_factored(b)),
            np.asarray(lu_base2.solve_factored(b))), \
            f"{executor}: refactor drifted from the SamePattern baseline"
        sym = float(st.utime.get("SYMBFACT", 0.0))
        fresh = float(COMPILE_STATS.block(since=marker)["fresh_seconds"])
        assert sym == 0.0, f"{executor}: refactor re-ran symbolic ({sym}s)"
        assert fresh == 0.0, f"{executor}: refactor recompiled ({fresh}s)"
        print(f"  [1] {executor}: bitwise OK, symbolic=0.0s, "
              "fresh_compile=0.0s")


def _check_kill9_mid_refactor(tmp):
    from superlu_dist_tpu.drivers.gssvx import gssvx
    from superlu_dist_tpu.models.gallery import poisson2d
    from superlu_dist_tpu.persist.serial import load_lu, save_lu
    from superlu_dist_tpu.utils.options import Options
    from superlu_dist_tpu.utils.stats import Stats

    d = os.path.join(tmp, "kill9")
    a = poisson2d(7)
    b = np.ones(a.n_rows)
    _, lu, _, _ = gssvx(Options(), a, b, stats=Stats())
    save_lu(lu, d)
    x_before = np.asarray(load_lu(d).solve_factored(b))
    child = (
        "import numpy as np\n"
        "from superlu_dist_tpu.drivers.gssvx import refactor\n"
        "from superlu_dist_tpu.persist.serial import load_lu\n"
        "from superlu_dist_tpu.models.gallery import poisson2d\n"
        f"lu = load_lu({d!r})\n"
        "a = poisson2d(7)\n"
        "a2 = type(a)(a.n_rows, a.n_cols, a.indptr, a.indices,\n"
        "             a.data * 2.0)\n"
        "refactor(lu, a2)\n"
        "print('UNREACHABLE')\n")
    env = dict(os.environ, SLU_TPU_CHAOS="kill_refactor@step=0",
               JAX_PLATFORMS="cpu")
    r = subprocess.run([sys.executable, "-c", child], env=env, cwd=REPO,
                       capture_output=True, timeout=300)
    assert r.returncode == -9, (
        f"child should die by SIGKILL mid-refactor, got rc={r.returncode}:"
        f"\n{r.stdout.decode()}\n{r.stderr.decode()}")
    assert b"UNREACHABLE" not in r.stdout
    x_after = np.asarray(load_lu(d).solve_factored(b))
    assert np.array_equal(x_before, x_after), \
        "interrupted refactor corrupted the persisted state"
    print("  [2] kill -9 mid-refactor: rc=-9, bundle serves bitwise")


def _check_fleet_rolling_refactor(tmp):
    import dataclasses

    from superlu_dist_tpu.drivers.gssvx import gssvx
    from superlu_dist_tpu.models.gallery import poisson2d
    from superlu_dist_tpu.persist.serial import save_lu
    from superlu_dist_tpu.serve import FleetRouter, RefactorRollbackError
    from superlu_dist_tpu.serve.fleet import FLEET_SERVER_KW
    from superlu_dist_tpu.utils.options import Fact, IterRefine, Options
    from superlu_dist_tpu.utils.stats import Stats

    a = poisson2d(8)
    b = a.matvec(np.ones(a.n_rows))
    opts = Options(iter_refine=IterRefine.NOREFINE)
    _, lu, _, _ = gssvx(opts, a, b, stats=Stats())
    d = os.path.join(tmp, "fleet-k0")
    save_lu(lu, d)
    a2 = _drift(a)
    _, lu_b, _, _ = gssvx(opts, a, b, stats=Stats())
    _, lu_b2, _, _ = gssvx(
        dataclasses.replace(opts, fact=Fact.SamePattern_SameRowPerm),
        a2, b, lu=lu_b, stats=Stats())
    x_expect = np.asarray(lu_b2.solve_factored(b))

    fleet = FleetRouter({"k0": d}, n_replicas=3, kind="thread",
                        server_kw=FLEET_SERVER_KW)
    stop = threading.Event()
    outcomes = []
    lock = threading.Lock()

    def client():
        while not stop.is_set():
            try:
                fleet.solve("k0", b, timeout=120)
                tag = "ok"
            except Exception as e:      # noqa: BLE001 — tallied
                tag = type(e).__name__
            with lock:
                outcomes.append(tag)

    th = threading.Thread(target=client)
    th.start()
    try:
        time.sleep(0.05)
        summary = fleet.refactor("k0", a2)
        time.sleep(0.05)
    finally:
        stop.set()
        th.join(30)
    try:
        assert outcomes and set(outcomes) == {"ok"}, (
            f"rolling refactor dropped/errored tickets: {outcomes}")
        assert summary["replicas_swapped"] == [0, 1, 2], summary
        x_got = np.asarray(fleet.solve("k0", b))
        assert np.array_equal(x_got, x_expect), \
            "post-refactor fleet answer drifted from the baseline"
        print(f"  [3] rolling refactor: {len(outcomes)} live tickets all "
              "ok, 3 replicas swapped, bitwise OK")

        # phase 4: poisoned refactor rolls back, old factors keep serving
        os.environ["SLU_TPU_CHAOS"] = "poison_values=1"
        try:
            fleet.refactor("k0", _drift(a, scale=3.0))
            raise AssertionError(
                "poisoned refactor survived its canary gate")
        except RefactorRollbackError as e:
            assert e.stage in ("factor", "canary"), e.stage
        finally:
            os.environ.pop("SLU_TPU_CHAOS", None)
        assert np.array_equal(np.asarray(fleet.solve("k0", b)), x_got), \
            "a replica was left serving the rolled-back refactor"
        st = fleet.stats()
        assert st["errors"] == 0, st
        assert st["refactors"] == 1 and st["rollbacks"] == 1, st
        print("  [4] poisoned refactor: RefactorRollbackError, fleet "
              "serves previous factors bitwise")
    finally:
        fleet.close()


def main():
    t0 = time.perf_counter()
    with tempfile.TemporaryDirectory(prefix="slu-refactor-gate-") as tmp:
        _check_bitwise_and_zero_recompile()
        _check_kill9_mid_refactor(tmp)
        _check_fleet_rolling_refactor(tmp)
    print(f"check_refactor: ALL OK ({time.perf_counter() - t0:.1f}s)")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
