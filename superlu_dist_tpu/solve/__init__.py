from superlu_dist_tpu.solve.trisolve import lu_solve
from superlu_dist_tpu.solve.plan import (   # noqa: F401
    SolvePlan, build_solve_plan, nrhs_buckets, bucket_nrhs, chunk_nrhs)
