"""shard_map SPMD tier (parallel/spmd.py) — one compiled program per
factor (and per solve-sweep bucket) over a real jax.Mesh.

The bitwise contract this suite pins (the PR 5 pattern): the SPMD
program's L/U factors AND solve vectors are bit-identical to the
single-device lockstep executors (fused/stream/mega are already bitwise
twins of each other) on the 8-virtual-device CPU mesh.  That is what
lets the TreeComm host-lockstep tier stand as the A/B reference: any
SPMD result can be re-derived lockstep and compared exactly.

Also covered: the two composition debts this tier cleared — the mega
executor runs its bucketed programs UNDER the mesh (no auto-downgrade
to stream; GSPMD re-tiling makes that an allclose-class contract, see
numeric/mega.py), and Pallas interpret-mode kernels ride through
shard_map bitwise — plus auditor cleanliness (SLU_TPU_VERIFY_SHARDING
/ SLU_TPU_VERIFY_PROGRAMS) and checkpoint-frontier portability between
the lockstep and SPMD entry points.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from superlu_dist_tpu.models.gallery import (helmholtz_2d, hilbert,
                                             poisson2d,
                                             rank_deficient_arrowhead)
from superlu_dist_tpu.numeric.factor import get_executor, numeric_factorize
from superlu_dist_tpu.numeric.plan import build_plan
from superlu_dist_tpu.ordering.dispatch import get_perm_c
from superlu_dist_tpu.parallel.grid import gridinit
from superlu_dist_tpu.parallel.spmd import (SpmdFactorExecutor, SpmdSolver,
                                            spmd_mode)
from superlu_dist_tpu.solve.device import DeviceSolver
from superlu_dist_tpu.sparse.formats import symmetrize_pattern
from superlu_dist_tpu.symbolic.symbfact import symbolic_factorize
from superlu_dist_tpu.utils.options import Options

pytestmark = pytest.mark.spmd


def _mesh():
    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-device virtual mesh (conftest XLA_FLAGS)")
    return gridinit(1, 8).mesh


def _analyzed(a, dtype="float64"):
    sym = symmetrize_pattern(a)
    col_order = get_perm_c(Options(), a, sym)
    sf = symbolic_factorize(sym, col_order)
    plan = build_plan(sf, schedule="dataflow")
    return plan, sym.data[sf.value_perm], a.norm_max()


def _bitwise_fronts(f0, f1):
    return all(np.array_equal(np.asarray(l0), np.asarray(l1))
               and np.array_equal(np.asarray(u0), np.asarray(u1))
               for (l0, u0), (l1, u1) in zip(f0.fronts, f1.fronts))


_GALLERY = [("poisson", lambda: poisson2d(16)),
            ("hilbert", lambda: hilbert(48)),
            ("arrowhead", lambda: rank_deficient_arrowhead(40))]


# ---------------------------------------------------------------------------
# bitwise L/U/X vs the lockstep executors
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("name,make", _GALLERY)
def test_spmd_bitwise_vs_lockstep(name, make):
    """One shard_map program per factor, bit-identical L/U to EVERY
    single-device lockstep executor, and bit-identical solve/solveT."""
    mesh = _mesh()
    plan, vals, anorm = _analyzed(make())
    fs = numeric_factorize(plan, vals, anorm, executor="spmd", mesh=mesh)
    for lockstep in ("fused", "stream", "mega"):
        f0 = numeric_factorize(plan, vals, anorm, executor=lockstep)
        assert _bitwise_fronts(f0, fs), (name, lockstep)
        assert f0.tiny_pivots == fs.tiny_pivots, (name, lockstep)
    rng = np.random.default_rng(7)
    rhs = rng.standard_normal((plan.n, 3))
    f0 = numeric_factorize(plan, vals, anorm, executor="fused")
    s0, s1 = DeviceSolver(f0), SpmdSolver(fs, mesh)
    assert np.array_equal(s0.solve(rhs), s1.solve(rhs)), name
    assert np.array_equal(s0.solve_trans(rhs), s1.solve_trans(rhs)), name


def test_spmd_bitwise_complex_conjugate_sweeps():
    """complex128 factor + Aᵀ/Aᴴ sweeps stay bitwise (the conjugate
    sweep bodies share operands with DeviceSolver exactly)."""
    mesh = _mesh()
    plan, vals, anorm = _analyzed(helmholtz_2d(10))
    f0 = numeric_factorize(plan, vals, anorm, executor="fused",
                           dtype="complex128")
    fs = numeric_factorize(plan, vals, anorm, executor="spmd", mesh=mesh,
                           dtype="complex128")
    assert _bitwise_fronts(f0, fs)
    rng = np.random.default_rng(3)
    rhs = (rng.standard_normal((plan.n, 2))
           + 1j * rng.standard_normal((plan.n, 2)))
    s0, s1 = DeviceSolver(f0), SpmdSolver(fs, mesh)
    assert np.array_equal(s0.solve(rhs), s1.solve(rhs))
    assert np.array_equal(s0.solve_trans(rhs), s1.solve_trans(rhs))
    assert np.array_equal(s0.solve_trans(rhs, conj=True),
                          s1.solve_trans(rhs, conj=True))


def test_spmd_is_one_program():
    mesh = _mesh()
    plan, vals, anorm = _analyzed(poisson2d(16))
    ex = get_executor(plan, "float64", executor="spmd", mesh=mesh)
    assert isinstance(ex, SpmdFactorExecutor)
    assert ex.n_kernels == 1 and ex.granularity == "program"


# ---------------------------------------------------------------------------
# dispatch rules: auto picks spmd on a mesh; knob + no-mesh downgrades
# ---------------------------------------------------------------------------

def test_auto_rule_and_knob(monkeypatch):
    mesh = _mesh()
    plan, _, _ = _analyzed(poisson2d(16))
    monkeypatch.delenv("SLU_TPU_SPMD", raising=False)
    assert spmd_mode() is True                # auto on single process
    ex = get_executor(plan, "float64", executor="auto", mesh=mesh)
    assert isinstance(ex, SpmdFactorExecutor)
    # the knob gates the auto rule off
    monkeypatch.setenv("SLU_TPU_SPMD", "0")
    assert spmd_mode() is False
    ex = get_executor(plan, "float64", executor="auto", mesh=mesh)
    assert not isinstance(ex, SpmdFactorExecutor)
    monkeypatch.setenv("SLU_TPU_SPMD", "1")
    assert spmd_mode() is True
    # no mesh / partitioned pool: explicit spmd downgrades to stream
    ex = get_executor(plan, "float64", executor="spmd", mesh=None)
    assert not isinstance(ex, SpmdFactorExecutor)
    ex = get_executor(plan, "float64", executor="spmd", mesh=mesh,
                      pool_partition=True)
    assert not isinstance(ex, SpmdFactorExecutor)


def test_knobs_registered():
    from superlu_dist_tpu.utils.options import KNOB_REGISTRY
    assert "SLU_TPU_SPMD" in KNOB_REGISTRY
    assert "BENCH_MESH" in KNOB_REGISTRY
    assert "spmd" in KNOB_REGISTRY["SLU_TPU_EXECUTOR"].choices


# ---------------------------------------------------------------------------
# composition debt 1: mega runs UNDER the mesh (no downgrade)
# ---------------------------------------------------------------------------

def test_mega_under_mesh_no_downgrade():
    """MegaExecutor keeps its mesh instead of auto-downgrading to
    stream.  GSPMD re-tiles the batched triangular solves, so (exactly
    like stream-under-mesh) this is an allclose-class contract — the
    BITWISE mesh tier is the shard_map executor above."""
    from superlu_dist_tpu.numeric.mega import MegaExecutor
    mesh = _mesh()
    plan, vals, anorm = _analyzed(rank_deficient_arrowhead(40))
    ex = get_executor(plan, "float64", executor="mega", mesh=mesh)
    assert isinstance(ex, MegaExecutor)       # the old ValueError is gone
    assert ex.mesh is mesh
    f0 = numeric_factorize(plan, vals, anorm, executor="fused")
    f1 = numeric_factorize(plan, vals, anorm, executor="mega", mesh=mesh)
    assert f0.tiny_pivots == f1.tiny_pivots
    for (l0, u0), (l1, u1) in zip(f0.fronts, f1.fronts):
        for x0, x1 in ((l0, l1), (u0, u1)):
            assert np.allclose(np.asarray(x0), np.asarray(x1),
                               rtol=1e-12, atol=0)


# ---------------------------------------------------------------------------
# composition debt 2: Pallas rides through under the mesh
# ---------------------------------------------------------------------------

def test_pallas_interpret_under_mesh_bitwise():
    """Interpret-mode Pallas kernels inside the shard_map program are
    bitwise twins of the .at[] path — the old pin-OFF-under-mesh guard
    is gone (numeric/pallas_kernels.py)."""
    mesh = _mesh()
    plan, vals, anorm = _analyzed(rank_deficient_arrowhead(40))
    th = jnp.asarray(np.sqrt(np.finfo(np.float64).eps) * anorm)
    v = jnp.asarray(vals)
    ex0 = SpmdFactorExecutor(plan, "float64", mesh, pallas="off")
    ex1 = SpmdFactorExecutor(plan, "float64", mesh, pallas="interpret")
    assert ex1.pallas == "interpret"          # no silent pin to off
    f0, t0 = ex0(v, th)
    f1, t1 = ex1(v, th)
    assert int(t0) == int(t1)
    for (l0, u0), (l1, u1) in zip(f0, f1):
        assert np.array_equal(np.asarray(l0), np.asarray(l1))
        assert np.array_equal(np.asarray(u0), np.asarray(u1))


# ---------------------------------------------------------------------------
# auditors: the SPMD programs are clean under the runtime verify tiers
# ---------------------------------------------------------------------------

def test_spmd_clean_under_runtime_auditors(monkeypatch):
    """SLU_TPU_VERIFY_SHARDING=1 + SLU_TPU_VERIFY_PROGRAMS=1: the
    factor program and the solve sweeps audit clean — 0 sharding
    findings (SLU119 replication included) and full donation coverage
    on declared-dead inputs."""
    from superlu_dist_tpu.obs.compilestats import COMPILE_STATS
    from superlu_dist_tpu.utils import programaudit
    mesh = _mesh()
    monkeypatch.setenv("SLU_TPU_VERIFY_SHARDING", "1")
    monkeypatch.setenv("SLU_TPU_VERIFY_PROGRAMS", "1")
    monkeypatch.delenv("SLU_TPU_VERIFY_DTYPES", raising=False)
    monkeypatch.delenv("SLU_TPU_MEM_BUDGET_BYTES", raising=False)
    programaudit._reset()
    with COMPILE_STATS._lock:
        saved = dict(COMPILE_STATS._audits)
        COMPILE_STATS._audits = {}
    try:
        plan, vals, anorm = _analyzed(poisson2d(16))
        f = numeric_factorize(plan, vals, anorm, executor="spmd",
                              mesh=mesh)
        s = SpmdSolver(f, mesh)
        s.solve(np.ones((plan.n, 2)))
        s.solve_trans(np.ones(plan.n))
        sh = programaudit.get_sharding_auditor()
        assert sh is not None and sh.findings == []
        pa = programaudit.get_auditor()
        assert pa is not None and not getattr(pa, "findings", [])
        blk = COMPILE_STATS.audit_block()
        assert blk["programs_sharding_audited"] >= 1
        assert blk["programs"] >= 1
        assert blk["donation_coverage_pct"] == 100.0
        # replicated traffic is PRICED, not forbidden: the tier
        # replicates the tiny pivot stacks / index vectors by design
        # (the bitwise contract) — what must hold is 0 findings above
        assert blk["replicated_bytes"] >= 0
    finally:
        programaudit._reset()
        with COMPILE_STATS._lock:
            COMPILE_STATS._audits = saved


# ---------------------------------------------------------------------------
# checkpoint frontiers are portable between the lockstep and SPMD tiers
# ---------------------------------------------------------------------------

def test_checkpoint_frontier_portable_lockstep_spmd(tmp_path):
    """A frontier written by an interrupted lockstep run resumes under
    an executor="spmd" request (and vice versa) to bitwise-identical
    factors: checkpointing has group boundaries only on the stream
    executor, so both entry points downgrade to it for the durable
    part, and the frontier format is shared."""
    from superlu_dist_tpu.testing.chaos import CountdownDeadline
    from superlu_dist_tpu.utils.errors import DeadlineExceededError
    _mesh()                                   # same env as the rest
    plan, vals, anorm = _analyzed(poisson2d(16))
    assert len(plan.groups) >= 4
    ref = numeric_factorize(plan, vals, anorm, executor="stream")
    # lockstep writes, spmd request resumes
    ck = str(tmp_path / "ck-lockstep")
    with pytest.raises(DeadlineExceededError):
        numeric_factorize(plan, vals, anorm, executor="stream",
                          ckpt_dir=ck, deadline=CountdownDeadline(3))
    res = numeric_factorize(plan, vals, anorm, executor="spmd",
                            resume_from=ck)
    assert res.resumed_groups == 3
    assert _bitwise_fronts(ref, res) and res.tiny_pivots == ref.tiny_pivots
    # spmd request writes (forced onto stream by the ckpt arm), lockstep
    # resumes
    ck2 = str(tmp_path / "ck-spmd")
    with pytest.raises(DeadlineExceededError):
        numeric_factorize(plan, vals, anorm, executor="spmd",
                          ckpt_dir=ck2, deadline=CountdownDeadline(3))
    res2 = numeric_factorize(plan, vals, anorm, executor="stream",
                             resume_from=ck2)
    assert res2.resumed_groups == 3
    assert _bitwise_fronts(ref, res2)
