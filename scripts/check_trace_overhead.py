#!/usr/bin/env python
"""Trace/metrics/flight-recorder overhead smoke run (check_nan_guards
style).

Runs a small factor+solve in fresh subprocesses:

* everything OFF — asserts the disabled paths allocate NO per-event
  telemetry objects: the process-global tracer stays the NULL_TRACER
  singleton (reused no-op span), ``obs.metrics.get_metrics()`` stays
  the NULL_METRICS singleton (no counter dict entries), and
  ``obs.flightrec.get_flightrec()`` stays the NULL_FLIGHTREC singleton
  (no ring, no signal handler, no artifact file);
* tracing ON   — validates the artifacts: the Chrome trace JSON loads,
  carries phase + kernel + compile spans whose timestamps are monotone
  per thread, the kernel spans inside each FACT phase sum to its
  duration (within a slack factor), and the JSONL sidecar parses line
  by line;
* metrics + flight recorder ON — asserts the registry fills (scheduler
  gauges from the factorization) and a provoked dump leaves a
  well-formed postmortem (reason, anchor, events, compile census).

Exit 0 = pass.  One gate of scripts/ci_gates.sh (the consolidated CI
entry point); a few seconds on CPU.  Gate contract (shared with
run_slulint.sh, check_nan_guards.sh and check_verify_overhead.py): any
regression — a child failure, telemetry allocated on a disabled path,
a malformed artifact — raises/asserts, which exits non-zero.
"""

import json
import os
import subprocess
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# the child: one small factor+solve through the expert driver, then a
# JSON line reporting what tracer the process ended up with
CHILD = r"""
import json, os, sys
import numpy as np
import superlu_dist_tpu as slu
from superlu_dist_tpu.models.gallery import poisson2d
from superlu_dist_tpu.obs import flightrec, metrics, trace
from superlu_dist_tpu.utils import tols

a = poisson2d(10)
b = np.ones(a.n_rows)
x, lu, stats, info = slu.gssvx(slu.Options(), a, b)
assert info == 0, info
res = float(np.linalg.norm(b - a.matvec(x)) / np.linalg.norm(b))
assert res < tols.RESID_GATE, res
t = trace.get_tracer()
m = metrics.get_metrics()
fr = flightrec.get_flightrec()
snap = m.snapshot()
out = {
    "tracer": type(t).__name__,
    "null_singleton": t is trace.NULL_TRACER,
    "span_reused": t.span("a") is t.span("b"),
    "fact_seconds": stats.utime["FACT"],
    "compile_builds": stats.compile.get("builds", 0),
    "metrics": type(m).__name__,
    "metrics_null": m is metrics.NULL_METRICS,
    "metrics_series": sum(len(v) for v in snap.values()) if snap else 0,
    "flightrec": type(fr).__name__,
    "flightrec_null": fr is flightrec.NULL_FLIGHTREC,
    "flightrec_ring": getattr(fr, "_ring", None) is not None,
}
if fr.enabled:
    out["dump"] = fr.dump("overhead-gate", detail="on-path check")
print(json.dumps(out))
"""


# serve-path child: two submits through a SolveServer with ALL obs
# knobs unset — both tickets must carry the shared NULL_TICKET
# singleton (zero TicketContext allocations per submit)
SERVE_CHILD = r"""
import json
import numpy as np
import superlu_dist_tpu as slu
from superlu_dist_tpu.models.gallery import poisson2d
from superlu_dist_tpu.obs import slo
from superlu_dist_tpu.serve.server import SolveServer

a = poisson2d(10)
_, lu, _, info = slu.gssvx(slu.Options(), a, np.ones(a.n_rows))
assert info == 0, info
with SolveServer(lu, max_wait_s=0.0) as srv:
    t1 = srv.submit(np.ones(a.n_rows))
    t2 = srv.submit(np.ones(a.n_rows))
    srv.flush()
    x1, x2 = t1.result(30.0), t2.result(30.0)
assert np.isfinite(x1).all() and np.isfinite(x2).all()
print(json.dumps({
    "ctx_null": t1._req.ctx is t2._req.ctx is slo.NULL_TICKET,
    "ctx_type": type(t1._req.ctx).__name__,
}))
"""


def run_child(extra_env, src=CHILD):
    env = dict(os.environ, JAX_PLATFORMS="cpu", **extra_env)
    for k in ("SLU_TPU_TRACE", "SLU_TPU_METRICS", "SLU_TPU_FLIGHTREC"):
        env.pop(k, None)
    env.update(extra_env)
    r = subprocess.run([sys.executable, "-c", src], env=env, cwd=REPO,
                       stdout=subprocess.PIPE, stderr=subprocess.PIPE)
    if r.returncode != 0:
        sys.stderr.write(r.stderr.decode())
        raise SystemExit(f"child failed (rc={r.returncode})")
    return json.loads(r.stdout.decode().strip().splitlines()[-1])


def fail(msg):
    print(f"FAIL: {msg}", file=sys.stderr)
    raise SystemExit(1)


def main():
    tmp = tempfile.mkdtemp(prefix="slu_trace_check_")
    trace_path = os.path.join(tmp, "t.json")
    jsonl_path = os.path.join(tmp, "t.jsonl")

    # ---- off path: no telemetry objects, no artifacts --------------------
    off = run_child({})
    if off["tracer"] != "NullTracer" or not off["null_singleton"]:
        fail(f"disabled path allocated a tracer: {off}")
    if not off["span_reused"]:
        fail("disabled path did not reuse the no-op span object")
    if os.path.exists(trace_path) or os.path.exists(jsonl_path):
        fail("disabled path created a trace artifact")
    if off["metrics"] != "NullMetrics" or not off["metrics_null"]:
        fail(f"disabled path allocated a metrics registry: {off}")
    if off["metrics_series"] != 0:
        fail(f"disabled path accumulated metric series: {off}")
    if off["flightrec"] != "NullFlightRecorder" or not off["flightrec_null"]:
        fail(f"disabled path allocated a flight recorder: {off}")
    if off["flightrec_ring"]:
        fail("disabled path allocated a flight-recorder ring")
    print(f"off: null tracer/metrics/flightrec, no artifact, "
          f"FACT {off['fact_seconds']:.3f}s")

    # ---- off path, serve tier: submits must not allocate a ticket
    # context — both tickets carry the one NULL_TICKET singleton
    serve_off = run_child({}, src=SERVE_CHILD)
    if not serve_off["ctx_null"]:
        fail(f"disabled serve path allocated a TicketContext: {serve_off}")
    print("off (serve): submits carry the shared NULL_TICKET singleton")

    # ---- on path: artifact exists and is well-formed ---------------------
    on = run_child({"SLU_TPU_TRACE": trace_path})
    if on["tracer"] != "Tracer":
        fail(f"SLU_TPU_TRACE did not install a Tracer: {on}")
    if not os.path.exists(trace_path):
        fail(f"no Chrome trace artifact at {trace_path}")
    if not os.path.exists(jsonl_path):
        fail(f"no JSONL sidecar at {jsonl_path}")

    doc = json.load(open(trace_path))
    events = doc.get("traceEvents")
    if not isinstance(events, list) or not events:
        fail("traceEvents missing or empty")
    for ev in events:
        for k in ("name", "cat", "ph", "ts", "dur", "pid", "tid"):
            if k not in ev:
                fail(f"event missing field {k!r}: {ev}")
    cats = {ev["cat"] for ev in events}
    if not {"phase", "kernel"} <= cats:
        fail(f"expected phase+kernel spans, got categories {sorted(cats)}")
    # monotone start times per thread (the artifact is sorted)
    last = {}
    for ev in events:
        key = (ev["pid"], ev["tid"])
        if ev["ts"] < last.get(key, float("-inf")):
            fail(f"ts not monotone for {key}")
        last[key] = ev["ts"]
    # kernel spans within each FACT phase must account for its duration
    facts = [e for e in events if e["name"] == "FACT"
             and e["cat"] == "phase"]
    kernels = [e for e in events if e["cat"] == "kernel"]
    if not facts:
        fail("no FACT phase span")
    for f in facts:
        inner = sum(k["dur"] for k in kernels
                    if k["ts"] >= f["ts"]
                    and k["ts"] + k["dur"] <= f["ts"] + f["dur"] + 1)
        if not (0.25 * f["dur"] <= inner <= 1.05 * f["dur"]):
            fail(f"kernel spans ({inner:.0f}us) do not account for the "
                 f"FACT phase ({f['dur']:.0f}us)")
    n_rows = 0
    for line in open(jsonl_path):
        if line.strip():
            json.loads(line)
            n_rows += 1
    if n_rows != len(events):
        fail(f"JSONL rows ({n_rows}) != traceEvents ({len(events)})")
    # compile census: a fresh process builds its kernels, so the trace
    # must carry compile spans and the Stats block must count them
    if "compile" not in cats:
        fail(f"no compile-census spans in a cold run: {sorted(cats)}")
    if on["compile_builds"] < 1:
        fail(f"stats.compile recorded no builds: {on['compile_builds']}")
    anchors = [e for e in events if e["name"] == "clock-anchor"]
    if len(anchors) != 1 or "unix_time" not in anchors[0].get("args", {}):
        fail("missing/malformed wall-clock anchor event")
    print(f"on: {len(events)} spans, categories {sorted(cats)}, "
          f"artifact + sidecar well-formed, "
          f"{on['compile_builds']} censused builds")

    # ---- metrics + flight recorder on: registry fills, dump well-formed --
    fr_path = os.path.join(tmp, "fr.json")
    live = run_child({"SLU_TPU_METRICS": "1", "SLU_TPU_FLIGHTREC": fr_path})
    if live["metrics"] != "Metrics" or live["metrics_series"] < 1:
        fail(f"SLU_TPU_METRICS=1 did not fill the registry: {live}")
    if live["flightrec"] != "FlightRecorder" or live.get("dump") != fr_path:
        fail(f"SLU_TPU_FLIGHTREC did not install/dump: {live}")
    doc = json.load(open(fr_path))
    for key in ("reason", "anchor", "events", "compile", "phase_stack"):
        if key not in doc:
            fail(f"flight dump missing {key!r}: {sorted(doc)}")
    if not doc["events"]:
        fail("flight dump carries no events")
    print(f"metrics+flightrec on: {live['metrics_series']} series, "
          f"dump with {len(doc['events'])} events")

    # ---- repo hygiene: no stray postmortem dumps at the repo root --------
    # SLU_TPU_FLIGHTREC=1 (bare flag, no path) dumps flightrec-<pid>.json
    # into the cwd; a gate that provokes a dump without pointing it at a
    # tempdir litters the checkout (a flightrec-595.json once shipped in a
    # commit).  Every child above runs with an explicit artifact path, so
    # the repo root must stay clean.
    import glob
    stray = sorted(glob.glob(os.path.join(REPO, "flightrec-*.json")))
    if stray:
        fail(f"stray flight-recorder dump(s) at the repo root: {stray} "
             f"(point SLU_TPU_FLIGHTREC at a tempdir path)")
    print("hygiene: no stray flightrec-*.json at the repo root")
    print("trace overhead smoke: PASS")


if __name__ == "__main__":
    main()
