"""SLU115 clean-negative fixture: widening casts and same-width
re-binds never narrow; a bf16 cast inside the sanctioned GEMM helper
(ops/dense.gemm is the ONE place the bf16 tier may narrow inputs) is
out of this rule's package scope by construction."""
import jax.numpy as jnp


def widen(panel, piv):
    p64 = panel.astype(jnp.float64)        # widening: never flagged
    return jnp.matmul(p64, piv, preferred_element_type=jnp.float64)


def rebind(vals, sel):
    v = vals.astype(jnp.float32)
    w = v.astype(jnp.float32)              # same width: not a downcast
    return jnp.dot(w, sel, preferred_element_type=jnp.float32)
