"""Forward abstract interpretation for the dataflow-aware slulint rules.

A deliberately small lattice — each variable carries a set of *taints*,
each taint a kind plus a one-line provenance used verbatim in findings:

* ``i32``  — the value is (or derives from) a 32-bit integer array:
  a ctor/``astype``/``cumsum`` with a 32-bit dtype (including the
  env-selected ``INT`` alias), or the return of a function whose returns
  are i32-tainted.  ``.astype(np.int64)`` *clears* the taint — promotion
  is exactly the fix the rule asks for.
* ``rank`` — the value derives from the caller's rank / grid coordinate
  (``.rank``/``.iam``/``.myrow``/``.mycol`` attribute reads, the lexical
  rank names, or the return of a rank-deriving function like an
  ``is_root(tc)`` predicate).
* ``env``  — the value derives from ``os.environ`` (directly or via the
  registry helpers ``env_int``/``env_float``/``env_str``/``env_flag``).

Propagation is a single in-order forward pass per function (loop bodies
run twice for loop-carried taint), through assignments, augmented
assignments, tuple unpacking, subscripts, a small set of
shape-preserving numpy passthroughs, and — via the call graph — function
returns, iterated to a fixpoint across the project.

Per-function :class:`Summary` records feed the rules: direct + transitive
collective reachability (SLU101), return taints (SLU101 rank predicates,
SLU103 i32-through-return), and direct + transitive env reachability
(SLU105).  One idiom is recognized and *exempted*: a zero-argument
``lru_cache``-decorated env reader (``ops/dense._precision``) is a
read-once latched constant — its value cannot change within a process,
so baking it into a compiled program without a cache key is sound, and
env-reachability does not propagate through it.
"""

from __future__ import annotations

import ast
import dataclasses

from superlu_dist_tpu.analysis.core import dotted_name, is_env_read

TAINT_I32 = "i32"
TAINT_RANK = "rank"
TAINT_ENV = "env"
# v4 (rules_program.SLU113): a value living on the accelerator — the
# result of a jnp/jax.numpy op or of CALLING a jitted program (a name
# bound from a jit-factory result carries TAINT_JITFN; calling it yields
# TAINT_DEVICE).  jax.device_get / jax.block_until_ready are the
# sanctioned EXPLICIT syncs: their results are host-side (taint cleared).
TAINT_DEVICE = "device"
TAINT_JITFN = "jitfn"
# v5 (rules_precision): the precision-flow component.  Width taints say
# what FLOAT width a value is KNOWN to carry (an explicit ctor dtype, an
# `.astype`, an `np.float64(x)` cast, or — via the return-taint fixpoint
# — the return of a function producing one); two separate kinds so the
# width survives the provenance-string wrapping of summarized returns.
# The EFT taint marks df64 hi/lo pair COMPONENTS (results of the
# ops/df64.py error-free transforms): their bit patterns only mean
# something under the EFT primitive algebra, so raw arithmetic on them
# is SLU117's hazard.  16-bit floats get no kind of their own — width 16
# is the lattice floor; nothing narrows below it.
TAINT_F64 = "f64"
TAINT_F32 = "f32"
TAINT_EFT = "eft"

#: taints that do not survive a comparison (comparisons yield bools)
_NONBOOL_TAINTS = (TAINT_I32, TAINT_F64, TAINT_F32, TAINT_EFT)

#: explicit host-materialization calls — the fix SLU113's hint asks for,
#: so their results must not keep the device taint
SYNC_CLEARERS = frozenset({"jax.device_get", "jax.block_until_ready"})

#: TreeComm collective surface (rules_collective re-exports this).
COLLECTIVE_METHODS = frozenset({
    "bcast", "reduce_sum", "allreduce_sum", "bcast_bytes", "bcast_obj",
    "bcast_any", "reduce_sum_any", "allreduce_sum_any",
})

_RANK_ATTRS = frozenset({"rank", "iam", "myrow", "mycol"})
_RANK_NAMES = frozenset({"rank", "iam", "myrank", "my_rank"})

_ENV_HELPER_SUFFIXES = tuple(
    f"options.{n}" for n in ("env_int", "env_float", "env_str", "env_flag"))

# ---- 32-bit dtype recognition (shared with rules_index) -------------------

_I32_DOTTED = frozenset({"np.int32", "numpy.int32", "np.intc",
                         "numpy.intc", "int32"})
# formats.INT is int32 unless SLU_TPU_INT64 is set — treat it as 32-bit
# for accumulator purposes (the whole point of the alias is that callers
# must not feed it to arithmetic that can exceed 2^31)
_I32_ALIASES = frozenset({"INT"})
_I64_NAMES = frozenset({"np.int64", "numpy.int64", "int64", "np.intp",
                        "numpy.intp"})

_ARRAY_CTORS = frozenset({"zeros", "empty", "full", "arange", "array",
                          "asarray", "ones"})

# ---- float dtype recognition (v5 precision lattice) -----------------------
# Complex dtypes resolve to their COMPONENT width: narrowing c128 -> c64
# loses exactly the bits narrowing f64 -> f32 does.

_F64_DOTTED = frozenset({"np.float64", "numpy.float64", "jnp.float64",
                         "float64", "np.double", "numpy.double",
                         "np.complex128", "numpy.complex128",
                         "jnp.complex128", "complex128"})
_F32_DOTTED = frozenset({"np.float32", "numpy.float32", "jnp.float32",
                         "float32", "np.single", "numpy.single",
                         "np.complex64", "numpy.complex64",
                         "jnp.complex64", "complex64"})
_F16_DOTTED = frozenset({"np.float16", "numpy.float16", "jnp.float16",
                         "float16", "jnp.bfloat16", "bfloat16",
                         "ml_dtypes.bfloat16"})

#: the ops/df64.py error-free-transform primitive set — the ONLY algebra
#: allowed to touch df64 hi/lo components (SLU117).  Recognized by call
#: tail so fixture-local definitions taint the same way.  The merge
#: helpers df64_to_f64/zdf64_to_c128 are deliberately absent: their
#: results are plain f64 values, not pair components.
EFT_PRIMITIVES = frozenset({
    "two_sum", "quick_two_sum", "two_prod", "df64_add", "df64_sub",
    "df64_mul", "df64_div", "df64_neg", "df64_from_f64", "zdf64_add",
    "zdf64_sub", "zdf64_mul", "zdf64_div", "zdf64_neg",
    "zdf64_from_c128"})


def float_width_name(name: str) -> int | None:
    """64/32/16 when ``name`` lexically names a float/complex dtype
    (complex -> component width), else None."""
    if name in _F64_DOTTED:
        return 64
    if name in _F32_DOTTED:
        return 32
    if name in _F16_DOTTED:
        return 16
    return None


def float_width_node(node) -> int | None:
    """Float width of a dtype EXPRESSION: ``np.float32`` / ``'float32'``
    / ``jnp.bfloat16`` ... — None for dynamic dtypes (``x.dtype``),
    which the precision rules deliberately cannot see through."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return float_width_name(node.value.strip())
    return float_width_name(dotted_name(node))


def width_taint_kind(width) -> str | None:
    return {64: TAINT_F64, 32: TAINT_F32}.get(width)


def taint_width(taints: dict) -> int | None:
    """The widest float width a taint set attests (promotion picks the
    wider operand, so after a BinOp merge the max is the result width)."""
    if TAINT_F64 in taints:
        return 64
    if TAINT_F32 in taints:
        return 32
    return None
# calls through which an i32 taint survives unchanged
_PASSTHROUGH = frozenset({"cumsum", "asarray", "ascontiguousarray",
                          "array", "copy", "ravel", "reshape",
                          "concatenate"})


def is_i32_dtype(node: ast.AST) -> bool:
    if isinstance(node, ast.Constant) and node.value == "int32":
        return True
    name = dotted_name(node)
    return name in _I32_DOTTED or name in _I32_ALIASES


def is_i64_dtype(node: ast.AST) -> bool:
    if isinstance(node, ast.Constant) and node.value == "int64":
        return True
    return dotted_name(node) in _I64_NAMES


def dtype_kw(call: ast.Call):
    for kw in call.keywords:
        if kw.arg == "dtype":
            return kw.value
    return None


def is_explicit_i32_expr(node: ast.AST) -> bool:
    """np.int32(x) or x.astype(np.int32) / x.astype('int32')."""
    if not isinstance(node, ast.Call):
        return False
    if is_i32_dtype(node.func) and dotted_name(node.func) not in \
            _I32_ALIASES:
        return True
    if isinstance(node.func, ast.Attribute) and node.func.attr == "astype" \
            and node.args and is_i32_dtype(node.args[0]):
        return True
    return False


# --------------------------------------------------------------------------
# per-function summaries
# --------------------------------------------------------------------------

@dataclasses.dataclass
class Summary:
    """What the rest of the project needs to know about one function."""

    return_taints: dict = dataclasses.field(default_factory=dict)
    collective: str | None = None       # direct witness "op at path:line"
    env: str | None = None              # direct witness
    latched_env: bool = False           # zero-arg lru_cached env reader
    # v4: the function returns a jitted callable — `return jax.jit(f)`
    # directly, a name bound from one, or (fixpointed over call edges)
    # the result of calling another jit factory.  Calling such a return
    # value produces device-resident outputs (TAINT_DEVICE).
    returns_jit: bool = False
    # transitive: (qname of the function owning the witness, witness)
    reaches_collective: tuple | None = None
    reaches_env: tuple | None = None
    # concurrency lattice facts (analysis/concurrency.py resolves them):
    # raw with-statement lock-acquisition candidates
    # ("self"|"name", text, line) and raw blocking-operation witnesses
    # (kind, receiver-text, line) lexically in this function's own body
    acquires_raw: list = dataclasses.field(default_factory=list)
    blocking_raw: list = dataclasses.field(default_factory=list)


def _site(path: str, node: ast.AST) -> str:
    return f"{path}:{getattr(node, 'lineno', 0)}"


def _own_body_nodes(fn):
    """Nodes lexically in `fn`'s own body — nested defs/lambdas excluded
    (they execute in their own context and carry their own Summary)."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(node))


def is_env_helper(target: str | None) -> bool:
    return bool(target) and target.endswith(_ENV_HELPER_SUFFIXES)


def _direct_collective(fi) -> str | None:
    for node in _own_body_nodes(fi.node):
        if isinstance(node, ast.Call) and isinstance(node.func,
                                                     ast.Attribute) \
                and node.func.attr in COLLECTIVE_METHODS:
            return f"{node.func.attr} at {_site(fi.path, node)}"
    return None


def _direct_env(proj, fi) -> str | None:
    for node in _own_body_nodes(fi.node):
        env = is_env_read(node)
        if env is not None:
            key = env[0] or "<dynamic>"
            return f"os.environ[{key!r}] at {_site(fi.path, env[1])}"
        if isinstance(node, ast.Call):
            target = proj.call_target(fi.path, node)
            if is_env_helper(target):
                return (f"{target.rsplit('.', 1)[-1]}(...) at "
                        f"{_site(fi.path, node)}")
    return None


def _is_jit_call(node: ast.AST) -> bool:
    """``jax.jit(...)`` / ``jit(...)`` / ``partial(jax.jit, ...)``."""
    if not isinstance(node, ast.Call):
        return False
    fn = node.func
    if dotted_name(fn) in ("jit", "jax.jit"):
        return True
    if dotted_name(fn) in ("partial", "functools.partial") and node.args:
        return dotted_name(node.args[0]) in ("jit", "jax.jit")
    return False


def _returns_jit_direct(fi) -> bool:
    """The function returns a jit object built in its own body: a
    ``return jax.jit(step)`` or a return of a name assigned from one
    (the ``fn = jax.jit(run); ...; return fn`` idiom)."""
    jit_names = set()
    for node in _own_body_nodes(fi.node):
        if isinstance(node, ast.Assign) and _is_jit_call(node.value):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    jit_names.add(t.id)
    for node in _own_body_nodes(fi.node):
        if not isinstance(node, ast.Return) or node.value is None:
            continue
        for sub in ast.walk(node.value):
            if _is_jit_call(sub):
                return True
            if isinstance(sub, ast.Name) and sub.id in jit_names:
                return True
    return False


def _is_lru_decorated(fn) -> bool:
    for d in fn.decorator_list:
        if isinstance(d, ast.Call):
            d = d.func
        if dotted_name(d) in ("lru_cache", "functools.lru_cache",
                              "cache", "functools.cache"):
            return True
    return False


# ---- concurrency lattice: raw lock/blocking facts -------------------------
# The concurrency rules (SLU108-SLU110, analysis/concurrency.py) need two
# lexical facts per function: which locks its body acquires via
# ``with`` statements, and which blocking operations it performs while
# they may be held.  Collected here — alongside the other Summary facts,
# so the transitive fixpoints ride the same call-graph edges — as RAW
# (unresolved) records; identity resolution (which class attr is a Lock,
# which module global) needs the project-wide attr tables that
# concurrency.Model builds.

#: blocking-call kinds recognized lexically (collectives are covered by
#: Summary.collective/reaches_collective already)
BLOCKING_KINDS = ("open", "wait", "join", "block_until_ready", "sleep")

_MUTATOR_METHODS = frozenset({
    "append", "appendleft", "extend", "add", "update", "pop", "popleft",
    "remove", "discard", "clear", "insert", "setdefault", "sort"})


def _acquire_candidate(item: ast.withitem):
    """("self"|"name", text, line) for a with-item whose context is a
    bare name/attribute (locks are with-ed directly; context-manager
    CALLS — tracer spans, nullcontext — are not lock acquisitions)."""
    ctx = item.context_expr
    if isinstance(ctx, ast.Attribute) and isinstance(ctx.value, ast.Name) \
            and ctx.value.id == "self":
        return ("self", ctx.attr, ctx.lineno)
    if isinstance(ctx, ast.Name):
        return ("name", ctx.id, ctx.lineno)
    return None


def _blocking_candidate(node: ast.Call):
    """(kind, receiver-text, line) when `node` is a recognized blocking
    call: file open, a no-timeout ``.wait()`` / ``.join()``, a jax
    ``.block_until_ready()``, or ``time.sleep``."""
    fn = node.func
    if isinstance(fn, ast.Name) and fn.id == "open":
        return ("open", "open", node.lineno)
    if not isinstance(fn, ast.Attribute):
        return None
    recv = dotted_name(fn.value) or "<expr>"
    if fn.attr == "block_until_ready":
        return ("block_until_ready", recv, node.lineno)
    if fn.attr in ("wait", "join") and not node.args and not node.keywords:
        return (fn.attr, recv, node.lineno)
    if fn.attr == "sleep" and recv == "time":
        return ("sleep", recv, node.lineno)
    return None


def _concurrency_facts(fi, summary: Summary) -> None:
    for node in _own_body_nodes(fi.node):
        if isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                cand = _acquire_candidate(item)
                if cand is not None:
                    summary.acquires_raw.append(cand)
        elif isinstance(node, ast.Call):
            cand = _blocking_candidate(node)
            if cand is not None:
                summary.blocking_raw.append(cand)


def _is_latched_const(fi, direct_env) -> bool:
    """Zero-argument lru_cached env reader: reads once per process, so
    its value is a process constant (ops/dense._precision)."""
    a = fi.node.args
    n_args = len(a.posonlyargs) + len(a.args) + len(a.kwonlyargs) \
        + (1 if a.vararg else 0) + (1 if a.kwarg else 0)
    return bool(direct_env) and n_args == 0 and _is_lru_decorated(fi.node)


def summarize(proj) -> None:
    """Fill proj.summaries for every function in the project."""
    sums = {q: Summary() for q in proj.functions}
    proj.summaries = sums
    for q, fi in proj.functions.items():
        s = sums[q]
        s.collective = _direct_collective(fi)
        s.env = _direct_env(proj, fi)
        s.latched_env = _is_latched_const(fi, s.env)
        s.returns_jit = _returns_jit_direct(fi)
        _concurrency_facts(fi, s)
        if s.collective:
            s.reaches_collective = (q, s.collective)
        if s.env and not s.latched_env:
            s.reaches_env = (q, s.env)

    # transitive reachability over resolved call edges (cycle-safe)
    changed = True
    while changed:
        changed = False
        for q, fi in proj.functions.items():
            s = sums[q]
            for callee in fi.calls:
                cs = sums.get(callee)
                if cs is None:
                    continue
                if s.reaches_collective is None \
                        and cs.reaches_collective is not None:
                    s.reaches_collective = cs.reaches_collective
                    changed = True
                if s.reaches_env is None and not s.latched_env \
                        and cs.reaches_env is not None:
                    s.reaches_env = cs.reaches_env
                    changed = True

    # jit-factory fixpoint: returning the RESULT of a call to a jit
    # factory (stream._get_kernel -> _kernel -> jax.jit) is itself a
    # jit factory — calling the returned value yields device arrays
    changed = True
    while changed:
        changed = False
        for q, fi in proj.functions.items():
            s = sums[q]
            if s.returns_jit:
                continue
            for node in _own_body_nodes(fi.node):
                if not isinstance(node, ast.Return) or node.value is None:
                    continue
                for sub in ast.walk(node.value):
                    if isinstance(sub, ast.Call):
                        cs = sums.get(proj.call_target(fi.path, sub))
                        if cs is not None and cs.returns_jit:
                            s.returns_jit = True
                            changed = True
                            break
                if s.returns_jit:
                    break

    # return-taint fixpoint (i32/rank/env through returns and call edges)
    for _ in range(4):
        changed = False
        for q, fi in proj.functions.items():
            flow = FnFlow.for_function(proj, fi)
            flow.run()
            if flow.returns != sums[q].return_taints:
                sums[q].return_taints = flow.returns
                changed = True
        if not changed:
            break


# --------------------------------------------------------------------------
# the forward pass
# --------------------------------------------------------------------------

class FnFlow:
    """One function (or module) body, interpreted in order."""

    def __init__(self, body, path, resolve, summaries):
        self.body = body
        self.path = path
        self.resolve = resolve          # Call node -> qname | None
        self.summaries = summaries
        self.env: dict = {}             # var -> {kind: provenance}
        self.assigns: dict = {}         # (line, col) -> (names, node, taints)
        self.returns: dict = {}         # {kind: provenance}
        self.loop_depth = 0             # lexical For/While nesting (SLU113)

    @classmethod
    def for_function(cls, proj, fi):
        resolve = (lambda call: proj.call_target(fi.path, call))
        return cls(fi.node.body, fi.path, resolve, proj.summaries)

    @classmethod
    def for_module(cls, proj, path, tree):
        resolve = (lambda call: proj.call_target(path, call))
        return cls(tree.body, path, resolve, proj.summaries)

    def run(self):
        self._exec(self.body)
        return self

    def rank_tainted(self, expr) -> str | None:
        """Provenance if `expr` is rank-dependent: lexical rank names,
        rank-tainted locals, or calls returning rank-derived values."""
        for sub in ast.walk(expr):
            if isinstance(sub, ast.Attribute) and sub.attr in _RANK_ATTRS:
                return f"`{dotted_name(sub) or sub.attr}`"
            if isinstance(sub, ast.Name):
                if sub.id in _RANK_NAMES:
                    return f"`{sub.id}`"
                t = self.env.get(sub.id)
                if t and TAINT_RANK in t:
                    return f"`{sub.id}` ({t[TAINT_RANK]})"
            if isinstance(sub, ast.Call):
                s = self._call_summary(sub)
                if s is not None and TAINT_RANK in s.return_taints:
                    return (f"`{dotted_name(sub.func)}()` returns "
                            f"{s.return_taints[TAINT_RANK]}")
        return None

    # ---- expression taint ----------------------------------------------
    def _call_summary(self, call):
        target = self.resolve(call)
        return self.summaries.get(target) if target else None

    def taint(self, node) -> dict:
        if node is None or isinstance(node, ast.Constant):
            return {}
        if isinstance(node, ast.Name):
            t = dict(self.env.get(node.id, ()))
            if node.id in _RANK_NAMES:
                t.setdefault(TAINT_RANK, f"`{node.id}`")
            return t
        if isinstance(node, ast.Attribute):
            if node.attr in _RANK_ATTRS:
                return {TAINT_RANK: f"`{dotted_name(node) or node.attr}`"}
            return {}
        if isinstance(node, ast.Subscript):
            return self.taint(node.value)
        if isinstance(node, ast.UnaryOp):
            return self.taint(node.operand)
        if isinstance(node, ast.BinOp):
            lt, rt = self.taint(node.left), self.taint(node.right)
            out = {}
            # numpy promotes int32 op int64 -> int64: only keep i32 when
            # no operand is known-promoted (a constant keeps the taint)
            if TAINT_I32 in lt and (TAINT_I32 in rt or _const_like(
                    node.right)):
                out[TAINT_I32] = lt[TAINT_I32]
            elif TAINT_I32 in rt and _const_like(node.left):
                out[TAINT_I32] = rt[TAINT_I32]
            for t in (lt, rt):
                for k in (TAINT_RANK, TAINT_ENV, TAINT_DEVICE,
                          TAINT_F64, TAINT_F32, TAINT_EFT):
                    if k in t:
                        out.setdefault(k, t[k])
            return out
        if isinstance(node, (ast.BoolOp, ast.Compare)):
            vals = (node.values if isinstance(node, ast.BoolOp)
                    else [node.left] + list(node.comparators))
            out = {}
            for v in vals:
                for k, p in self.taint(v).items():
                    if k not in _NONBOOL_TAINTS:  # comparisons yield bools
                        out.setdefault(k, p)
            return out
        if isinstance(node, ast.IfExp):
            out = dict(self.taint(node.body))
            for k, p in self.taint(node.orelse).items():
                out.setdefault(k, p)
            return out
        if isinstance(node, (ast.Tuple, ast.List)):
            out = {}
            for e in node.elts:
                for k, p in self.taint(e).items():
                    out.setdefault(k, p)
            return out
        if isinstance(node, ast.Call):
            return self._call_taint(node)
        return {}

    def _call_taint(self, node: ast.Call) -> dict:
        t = self._call_taint_base(node)
        name = dotted_name(node.func)
        # ---- device lattice (SLU113) --------------------------------------
        if name in SYNC_CLEARERS:
            # explicit, sanctioned materialization: result is host-side
            return {k: p for k, p in t.items() if k != TAINT_DEVICE}
        if name.startswith("jnp.") or name.startswith("jax.numpy."):
            t = dict(t)
            t.pop(TAINT_JITFN, None)
            t.setdefault(TAINT_DEVICE, f"`{name}(...)` at line {node.lineno}")
            return t
        if isinstance(node.func, ast.Name):
            ct = self.env.get(node.func.id)
            if ct and TAINT_JITFN in ct:
                return {TAINT_DEVICE:
                        f"result of jitted `{node.func.id}(...)` "
                        f"({ct[TAINT_JITFN]})"}
        target = self.resolve(node)
        s = self.summaries.get(target) if target else None
        if s is not None and s.returns_jit:
            t = dict(t)
            t.setdefault(TAINT_JITFN,
                         f"`{name}(...)` builds a jitted program")
        return t

    def _call_taint_base(self, node: ast.Call) -> dict:
        env = is_env_read(node)
        if env is not None:
            return {TAINT_ENV: f"os.environ[{env[0]!r}]"}
        fn = node.func
        name = dotted_name(fn)
        # x.astype(D): promotion clears, demotion taints; a lexical
        # float dtype rebinds the width kinds (and clears the stale one)
        if isinstance(fn, ast.Attribute) and fn.attr == "astype" \
                and node.args:
            base = dict(self.taint(fn.value))
            if is_i32_dtype(node.args[0]):
                base[TAINT_I32] = f"`.astype({dotted_name(node.args[0]) or 'int32'})` at line {node.lineno}"
            else:
                base.pop(TAINT_I32, None)
            w = float_width_node(node.args[0])
            if w is not None:
                base.pop(TAINT_F64, None)
                base.pop(TAINT_F32, None)
                k = width_taint_kind(w)
                if k is not None:
                    base[k] = (f"`.astype({dotted_name(node.args[0]) or node.args[0].value})` "
                               f"at line {node.lineno}")
            return base
        # np.int32(x) and friends
        if is_explicit_i32_expr(node):
            return {TAINT_I32: f"`{name}()` cast at line {node.lineno}"}
        tail = name.rsplit(".", 1)[-1]
        # the df64 error-free-transform algebra: every result is a pair
        # component (tuple results taint each unpacked element)
        if tail in EFT_PRIMITIVES:
            return {TAINT_EFT: f"`{tail}(...)` at line {node.lineno}"}
        # np.float64(x) / jnp.float32(x) explicit width casts
        if (node.args or node.keywords) and not isinstance(
                node.func, ast.Call):
            k = width_taint_kind(float_width_name(name))
            if k is not None:
                return {k: f"`{name}()` cast at line {node.lineno}"}
        # array ctors / cumsum with an explicit 32-bit dtype
        if tail in _ARRAY_CTORS or tail == "cumsum":
            dt = dtype_kw(node)
            if dt is None and tail in _ARRAY_CTORS and len(node.args) >= 2 \
                    and is_i32_dtype(node.args[-1]):
                dt = node.args[-1]
            if dt is not None:
                if is_i32_dtype(dt):
                    return {TAINT_I32: f"`{name}(dtype="
                                       f"{dotted_name(dt) or 'int32'})` "
                                       f"at line {node.lineno}"}
                k = width_taint_kind(float_width_node(dt))
                if k is not None:
                    return {k: f"`{name}(dtype="
                               f"{dotted_name(dt) or 'float'})` "
                               f"at line {node.lineno}"}
                return {}
            if tail in _PASSTHROUGH and node.args:
                return dict(self.taint(node.args[0]))
            return {}
        if tail in _PASSTHROUGH and node.args:
            return dict(self.taint(node.args[0]))
        target = self.resolve(node)
        if is_env_helper(target):
            return {TAINT_ENV: f"`{name}(...)`"}
        s = self.summaries.get(target) if target else None
        if s is not None and s.return_taints:
            return {k: f"return of `{target}` ({p})"
                    for k, p in s.return_taints.items()}
        return {}

    # ---- statements -----------------------------------------------------
    def _bind(self, target, taints):
        if isinstance(target, ast.Name):
            self.env[target.id] = dict(taints)
        elif isinstance(target, ast.Starred):
            self._bind(target.value, taints)
        elif isinstance(target, (ast.Tuple, ast.List)):
            for e in target.elts:
                self._bind(e, taints)

    def _record(self, targets, node, taints):
        names = [t.id for t in targets if isinstance(t, ast.Name)]
        if not names:
            return
        key = (node.lineno, node.col_offset)
        prev = self.assigns.get(key)
        if prev is not None:
            merged = dict(prev[2])
            for k, p in taints.items():
                merged.setdefault(k, p)
            taints = merged
            names = sorted(set(prev[0]) | set(names))
        self.assigns[key] = (names, node, taints)

    def visit_stmt(self, st) -> None:
        """Hook for rule subclasses: called once per statement, in
        execution order, with the taint environment up to date (loop
        bodies re-run for loop-carried taints, so dedupe by position)."""

    def _exec(self, stmts):
        for st in stmts:
            if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.ClassDef)):
                continue
            self.visit_stmt(st)
            if isinstance(st, ast.Assign):
                t = self.taint(st.value)
                for target in st.targets:
                    # tuple-unpacking a summarized return smears one
                    # element's device taint over host scalars in the
                    # same tuple (start, fronts, pool = helper());
                    # Summary.return_taints is per-function, not
                    # per-element, so stay false-negative-leaning and
                    # drop DEVICE across such unpacks.  Direct jit-call
                    # results keep it: every output of a jitted program
                    # is a device value.
                    if isinstance(target, (ast.Tuple, ast.List)) \
                            and TAINT_DEVICE in t \
                            and t[TAINT_DEVICE].startswith("return of "):
                        t2 = {k: p for k, p in t.items()
                              if k != TAINT_DEVICE}
                        self._bind(target, t2)
                    else:
                        self._bind(target, t)
                self._record(st.targets, st.value, t)
            elif isinstance(st, ast.AnnAssign) and st.value is not None:
                t = self.taint(st.value)
                self._bind(st.target, t)
                self._record([st.target], st.value, t)
            elif isinstance(st, ast.AugAssign):
                t = self.taint(st.value)
                if isinstance(st.target, ast.Name):
                    merged = dict(self.env.get(st.target.id, ()))
                    for k, p in t.items():
                        merged.setdefault(k, p)
                    self.env[st.target.id] = merged
            elif isinstance(st, ast.Return):
                for k, p in self.taint(st.value).items():
                    self.returns.setdefault(k, p)
            elif isinstance(st, (ast.If,)):
                self._exec(st.body)
                self._exec(st.orelse)
            elif isinstance(st, (ast.For, ast.AsyncFor)):
                self._bind(st.target, self.taint(st.iter))
                self.loop_depth += 1
                self._exec(st.body)
                self._exec(st.body)       # loop-carried taints
                self.loop_depth -= 1
                self._exec(st.orelse)
            elif isinstance(st, ast.While):
                self.loop_depth += 1
                self._exec(st.body)
                self._exec(st.body)
                self.loop_depth -= 1
                self._exec(st.orelse)
            elif isinstance(st, (ast.With, ast.AsyncWith)):
                for item in st.items:
                    if item.optional_vars is not None:
                        self._bind(item.optional_vars,
                                   self.taint(item.context_expr))
                self._exec(st.body)
            elif isinstance(st, ast.Try):
                self._exec(st.body)
                for h in st.handlers:
                    self._exec(h.body)
                self._exec(st.orelse)
                self._exec(st.finalbody)


def _const_like(node) -> bool:
    if isinstance(node, ast.Constant):
        return True
    if isinstance(node, ast.UnaryOp):
        return _const_like(node.operand)
    if isinstance(node, ast.BinOp):
        return _const_like(node.left) and _const_like(node.right)
    return False
