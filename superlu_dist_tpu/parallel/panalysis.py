"""Distributed-memory analysis for the multi-process tier (ParSymbFact).

Capability analog of the reference's parallel ordering + parallel
symbolic factorization (options->ParSymbFact):

* get_perm_c_parmetis (SRC/get_perm_c_parmetis.c:104,255) computes a
  nested-dissection ordering on the DISTRIBUTED graph — no rank ever
  assembles the full adjacency structure.
* psymbfact (SRC/psymbfact.c:140,228-242) partitions the symbolic
  factorization by separator subtree across 2^q ranks so the
  O(nnz(L))-sized symbolic work and the O(nnz(A)) graph memory stop
  being replicated per process.

TPU-native redesign, same two properties, different machinery:

1. **Distributed ordering** (the ParMETIS shape).  Each rank holds block
   rows of the structurally-symmetrized, equilibrated, row-permuted
   pattern.  Ranks coarsen their LOCAL subgraphs by greedy heavy-edge
   matching (only same-rank vertex pairs contract, the classic parallel
   multilevel restriction) until the global coarse graph is small; only
   that coarse graph — a bounded O(coarse) object, not the fine graph —
   is gathered to rank 0, which splits it into P parts by recursive
   BFS-level-set bisection.  The coarse separators project back through
   the contraction maps to fine vertex labels; contraction preserves
   edges, so projected parts are genuinely vertex-separated in the fine
   graph.  Each rank then receives its part's rows (an all-to-all over
   the tree collectives) and orders its own ~n/P subgraph with the full
   serial nested dissection (native mlnd) — the subtree-to-subcube
   assignment of the reference.
2. **Subtree-partitioned symbolic** (the psymbfact shape).  Every rank
   runs the supernodal symbolic on its OWN part only, as a bordered
   problem: part columns first, the touched separator vertices as
   opaque trailing boundary columns.  The elimination layout is
   [part 0][part 1]…[part P-1][separators, deepest tree level first,
   top separator last] — fill-equivalent to the interleaved ND order
   because two vertices in different regions can only be connected
   through a strictly higher-numbered separator, so no fill path exists
   between them.  Each part's local-root supernodes contribute their
   boundary row sets as cliques (star-encoded at the clique minimum,
   which survives the elimination etree's postorder because clique
   members form an ancestor chain); rank 0 folds the cliques into the
   separator block's own symbolic.  Per-rank symbolic work and graph
   memory are O(part), not O(global).
3. **Assembly.**  The per-part symbolic pieces are gathered and stitched
   into one global SymbolicFact on rank 0, amalgamated, planned
   (numeric.plan.build_plan) and broadcast — the same replicated
   skeleton the SPMD numeric factorization consumes on every rank
   (numeric/factor.py shards the POOL, not the plan, across the mesh).
   What is distributed here is the analysis *work* and the *fine-graph
   + fill-structure working memory*; the finished O(nnz(L)) index
   skeleton is still replicated, exactly as the non-ParSymbFact path
   replicates it after pddistribute in the reference.

Measured at n=110,592 / 4 ranks
(docs/mesh_analysis_4proc_n110592.json): ordering quality is at
PARITY with the serial native ND (nnz_L 52.5M vs 53.3M, structural
flops 162G vs 161G — the fine-level separator trimming is what closes
this; without it the projected slab separators cost 1.9x fill), the
non-root ranks keep the root+bcast tier's time/peak wins and O(part)
work, and the root's transient peak is slightly BELOW the root-bcast
tier's.  Root wall time runs ~5% behind the root-bcast tier at
n=110,592 and at parity at n=1,000,000 — where the tier additionally
HALVES the root's transient peak (4.2 GB vs 9.3 GB,
docs/mesh_analysis_4proc_n1000000.json): no rank ever holds the full
fine graph + symbolic working set.  The remaining root-side phases are
assembly + plan build (the pddistribute-analog), which stay on root by
design.

Equilibration is computed distributed (the pdgsequ analog: local row
maxima, tree-allreduced column maxima).  LargeDiag_MC64/AWPM row
matchings are serial on rank 0 over a TRANSIENT gather of the scaled
matrix — the reference does exactly this for LargeDiag
(pdgssvx.c:775 gathers before dldperm_dist); NOROWPERM and MY_PERMR
stay fully distributed.

The SamePattern reuse tiers need the serial analysis' value_perm gather
map; a panalyze-produced skeleton records none (values are assembled
directly), so drivers must re-analyze rather than reuse — analyze()
guards this explicitly.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from superlu_dist_tpu.parallel.dist import DistributedCSR
from superlu_dist_tpu.parallel.treecomm import TreeComm
from superlu_dist_tpu.sparse.formats import SparseCSR, invert_perm
from superlu_dist_tpu.utils.errors import SuperLUError


# ---------------------------------------------------------------------------
# collective helpers over the (sum/bcast-only) tree
# ---------------------------------------------------------------------------

def _allreduce_max(tc: TreeComm, vec: np.ndarray,
                   chunk: int = 1 << 16) -> np.ndarray:
    """Elementwise max across ranks over the sum-typed tree: ranks
    stack CHUNKS into disjoint slots and reduce, so the transient
    buffer is O(P·chunk), never O(P·n) — the module's O(part)-memory
    property must survive its own collectives."""
    vec = np.asarray(vec, dtype=np.float64)
    out = np.empty(len(vec))
    for lo in range(0, len(vec), chunk):
        hi = min(lo + chunk, len(vec))
        buf = np.zeros((tc.n_ranks, hi - lo))
        buf[tc.rank] = vec[lo:hi]
        out[lo:hi] = tc.allreduce_sum_any(buf).max(axis=0)
    return out


def _gather_concat(tc: TreeComm, arr: np.ndarray, root: int = 0,
                   all_ranks: bool = False, dtype=np.float64,
                   window: int = 1 << 21):
    """Concatenate every rank's 1-D array in rank order (on root, or on
    every rank) via WINDOWED disjoint-slot sum-reduction: only the
    receiver materializes the O(total) result; every other rank's
    transient is O(window) — the gathers must not break the module's
    O(part)-per-rank memory property."""
    counts = np.zeros(tc.n_ranks)
    counts[tc.rank] = len(arr)
    counts = tc.allreduce_sum_any(counts)
    offs = np.zeros(tc.n_ranks + 1, dtype=np.int64)
    offs[1:] = np.cumsum(counts).astype(np.int64)
    total = int(offs[-1])
    my_lo, my_hi = int(offs[tc.rank]), int(offs[tc.rank + 1])
    op = tc.allreduce_sum_any if all_ranks else tc.reduce_sum_any
    keep = all_ranks or tc.rank == root
    out = np.empty(total, dtype=dtype) if keep else None
    for lo in range(0, total, window):
        hi = min(lo + window, total)
        buf = np.zeros(hi - lo, dtype=dtype)
        a, b = max(my_lo, lo), min(my_hi, hi)
        if a < b:
            buf[a - lo:b - lo] = arr[a - my_lo:b - my_lo]
        buf = op(buf, root=root)
        if keep:
            out[lo:hi] = buf
    return out, offs


def _route(tc: TreeComm, dest: np.ndarray, payloads: dict):
    """All-to-all: item i (with its payload row) goes to rank dest[i].
    Returns {name: received array} on every rank.  Per destination, ONE
    counts-allreduce sizes the slots and the same-dtype keys ride one
    packed disjoint-slot reduction — O(P) rounds, volume O(items)."""
    keys = list(payloads)
    is_cplx = [np.issubdtype(np.asarray(payloads[k]).dtype,
                             np.complexfloating) for k in keys]
    out = {}
    for d in range(tc.n_ranks):
        mask = dest == d
        counts = np.zeros(tc.n_ranks)
        counts[tc.rank] = int(mask.sum())
        counts = tc.allreduce_sum_any(counts)
        offs = np.zeros(tc.n_ranks + 1, dtype=np.int64)
        offs[1:] = np.cumsum(counts).astype(np.int64)
        total = int(offs[-1])
        lo = int(offs[tc.rank])
        for cplx in (False, True):
            ks = [k for k, c in zip(keys, is_cplx) if c == cplx]
            if not ks:
                continue
            dt = np.complex128 if cplx else np.float64
            buf = np.zeros(len(ks) * total, dtype=dt)
            for i, k in enumerate(ks):
                part = np.asarray(payloads[k])[mask]
                buf[i * total + lo:i * total + lo + len(part)] = part
            buf = tc.reduce_sum_any(buf, root=d)
            if tc.rank == d:
                for i, k in enumerate(ks):
                    out[k] = buf[i * total:(i + 1) * total]
    return {k: out.get(k, np.empty(
        0, dtype=np.complex128 if c else np.float64))
        for k, c in zip(keys, is_cplx)}


# ---------------------------------------------------------------------------
# distributed equilibration (pdgsequ/pdlaqgs analog, SRC/pdgsequ.c)
# ---------------------------------------------------------------------------

def _pgsequ(tc: TreeComm, a_loc: DistributedCSR):
    """Distributed gsequ: row scales from local rows, column maxima
    tree-allreduced.  Returns (r_full, c, rowcnd, colcnd, amax) with the
    full global r (assembled — O(n), every rank)."""
    n = a_loc.n
    rows = np.repeat(np.arange(a_loc.m_loc), np.diff(a_loc.indptr))
    absa = np.abs(np.asarray(a_loc.data))
    rowmax_loc = np.zeros(a_loc.m_loc)
    np.maximum.at(rowmax_loc, rows, absa)
    rowmax = np.zeros(n)
    rowmax[a_loc.fst_row:a_loc.fst_row + a_loc.m_loc] = rowmax_loc
    # rows are rank-disjoint: a disjoint-slot sum-reduce IS the max
    rowmax = tc.allreduce_sum_any(rowmax)
    if np.any(rowmax == 0):
        raise SuperLUError(
            f"row {int(np.argmin(rowmax != 0))} of A is exactly zero")
    r = 1.0 / rowmax
    r_loc = r[a_loc.fst_row:a_loc.fst_row + a_loc.m_loc]
    colmax = np.zeros(n)
    np.maximum.at(colmax, np.asarray(a_loc.indices), absa * r_loc[rows])
    colmax = _allreduce_max(tc, colmax)
    if np.any(colmax == 0):
        raise SuperLUError(
            f"column {int(np.argmin(colmax != 0))} of A is exactly zero")
    c = 1.0 / colmax
    smlnum = np.finfo(np.float64).tiny
    bignum = 1.0 / smlnum
    rowcnd = max(r.min(), smlnum) / min(r.max(), bignum)
    colcnd = max(c.min(), smlnum) / min(c.max(), bignum)
    amax = float(_allreduce_max(tc, np.array([absa.max(initial=0.0)]))[0])
    return r, c, float(rowcnd), float(colcnd), amax


# ---------------------------------------------------------------------------
# coarse bisection on rank 0 (the separator-tree builder)
# ---------------------------------------------------------------------------

def _bfs_order(indptr, indices, sub_nodes, start):
    """BFS level sets within the vertex subset; returns list of level
    arrays covering the connected component of `start`."""
    n = len(indptr) - 1
    in_sub = np.zeros(n, dtype=bool)
    in_sub[sub_nodes] = True
    seen = np.zeros(n, dtype=bool)
    seen[start] = True
    frontier = np.array([start], dtype=np.int64)
    levels = [frontier]
    while True:
        nxt = []
        for u in frontier:
            nbr = indices[indptr[u]:indptr[u + 1]]
            nxt.append(nbr)
        if nxt:
            cand = np.unique(np.concatenate(nxt)) if len(nxt) else \
                np.empty(0, dtype=np.int64)
            cand = cand[in_sub[cand] & ~seen[cand]]
        else:
            cand = np.empty(0, dtype=np.int64)
        if len(cand) == 0:
            return levels
        seen[cand] = True
        levels.append(cand)
        frontier = cand


def _coarse_bisect(n, indptr, indices, vwgt, nparts):
    """Recursive BFS-level-set bisection of the coarse graph into
    `nparts` leaf parts.  Returns (labels, n_sep_nodes, part_anc):
    labels[v] = part id in [0, nparts) or -(sep_node_id + 1);
    separator tree nodes are numbered so that DEEPER separators get
    LOWER ids (they are eliminated first; the top separator is the last
    block).  part_anc[p] lists the final separator ids on part p's path
    to the root — the ancestor sets the fine-level separator trimming
    validates moves against.

    The get_perm_c_parmetis.c:255 role: build the separator tree that
    the symbolic phase partitions over."""
    labels = np.full(n, -1, dtype=np.int64)
    sep_nodes = []          # (depth, vertices) in creation order
    part_anc_cre = {}       # part -> ancestor sep CREATION indices
    # work items: (vertex subset, rank ids, depth, ancestor creation ids)
    work = [(np.arange(n, dtype=np.int64), list(range(nparts)), 0, ())]
    while work:
        nodes, ranks, depth, anc = work.pop()
        if len(ranks) == 1:
            labels[nodes] = ranks[0]
            part_anc_cre[ranks[0]] = anc
            continue
        if len(nodes) == 0:
            # empty rank subtree: record the chain anyway so
            # part_anc/anc_allowed coverage stays total for every rank
            for r in ranks:
                part_anc_cre[r] = anc
            continue
        levels = _bfs_order(indptr, indices, nodes, int(nodes[0]))
        comp = np.concatenate(levels)
        if len(comp) < len(nodes):
            # disconnected: split whole components across the two rank
            # halves by weight, no separator needed
            rest = nodes[~np.isin(nodes, comp)]
            # len(ranks) >= 2 here (singleton handled above), so both
            # halves are non-empty; ranks[half:] is the LARGER half when
            # the count is odd and must take the heavier component
            half = len(ranks) // 2
            wc, wr = vwgt[comp].sum(), vwgt[rest].sum()
            if wc >= wr:
                work.append((comp, ranks[half:], depth, anc))
                work.append((rest, ranks[:half], depth, anc))
            else:
                work.append((rest, ranks[half:], depth, anc))
                work.append((comp, ranks[:half], depth, anc))
            continue
        # pseudo-peripheral restart for a better diameter
        levels = _bfs_order(indptr, indices, nodes, int(levels[-1][0]))
        if len(levels) <= 2:
            # clique-ish blob: no useful separator; give it to the first
            # rank half entirely (the other half gets an empty part)
            half = max(len(ranks) // 2, 1)
            work.append((nodes, ranks[:half], depth, anc))
            work.append((np.empty(0, dtype=np.int64), ranks[half:],
                         depth, anc))
            continue
        lw = np.array([vwgt[l].sum() for l in levels], dtype=float)
        half_ranks = len(ranks) // 2
        target = lw.sum() * half_ranks / len(ranks)
        cut = int(np.clip(np.searchsorted(np.cumsum(lw), target),
                          1, len(levels) - 2))
        sep = levels[cut]
        left = np.concatenate(levels[:cut])
        right = (np.concatenate(levels[cut + 1:])
                 if cut + 1 < len(levels) else np.empty(0, dtype=np.int64))
        cre = len(sep_nodes)
        sep_nodes.append((depth, sep))
        work.append((left, ranks[:half_ranks], depth + 1, anc + (cre,)))
        work.append((right, ranks[half_ranks:], depth + 1, anc + (cre,)))
    # separator ids: deeper first, top (depth 0) last
    order = sorted(range(len(sep_nodes)),
                   key=lambda i: -sep_nodes[i][0])
    cre2sid = {i: sid for sid, i in enumerate(order)}
    for sid, i in enumerate(order):
        labels[sep_nodes[i][1]] = -(sid + 1)
    part_anc = {p: [cre2sid[c] for c in anc]
                for p, anc in part_anc_cre.items()}
    return labels, len(sep_nodes), part_anc


def _trim_separators(tc: TreeComm, lab, sr, sc, my_lo, my_hi, part_anc,
                     P, passes: int = 6):
    """Fine-graph separator refinement (the multilevel 'sep thinning'
    step ParMETIS applies during uncoarsening): a projected separator
    vertex whose every neighbor lies in ONE part p or in a separator on
    p's root path moves into p — peeling a k-layer slab from both faces
    until a ~1-layer true separator remains.  Each rank trims only the
    vertices it owns; updates combine by disjoint-slot reduction, and a
    verify round reverts (to separator status — always safe) the
    higher-indexed endpoint of any cross-part edge two simultaneous
    moves created."""
    n = len(lab)
    # allowed (part, separator-label) pairs: p's ancestor chain as a
    # dense boolean table over sep ids (sep label -s-1 -> row s)
    n_sep_ids = int(-lab.min()) if (lab < 0).any() else 0
    allowed = np.zeros((P, n_sep_ids + 1), dtype=bool)
    for p in range(P):
        for s in part_anc.get(p, []):
            if s < n_sep_ids:
                allowed[p, s] = True
    # my owned vertices' adjacency (CSR over the block), self-loops out
    keep = sr != sc
    order = np.argsort(sr[keep], kind="stable")
    sr_s, sc_s = sr[keep][order], sc[keep][order]
    ptr = np.searchsorted(sr_s, np.arange(my_lo, my_hi + 1))
    row_of = sr_s - my_lo
    for _ in range(passes):
        moves = np.zeros(n)
        nl = lab[sc_s]
        # per owned row: are all part-labeled neighbors one part p?
        big = np.where(nl >= 0, nl, P + 1)     # sentinel above any part
        small = np.where(nl >= 0, nl, -2)      # sentinel below any part
        pmax = np.full(my_hi - my_lo, -2, dtype=np.int64)
        pmin = np.full(my_hi - my_lo, P + 1, dtype=np.int64)
        np.minimum.at(pmin, row_of, big)
        np.maximum.at(pmax, row_of, small)
        one_part = (pmin == pmax) & (pmax >= 0)
        # and is every separator-labeled neighbor either the vertex's
        # own slab or an ancestor of that part?
        vlab = lab[my_lo:my_hi]
        is_sep_n = nl < 0
        own = nl[is_sep_n] == vlab[row_of[is_sep_n]]
        p_row = np.clip(pmax[row_of[is_sep_n]], 0, P - 1)
        anc_ok = allowed[p_row, np.clip(-nl[is_sep_n] - 1, 0, n_sep_ids)]
        sep_bad = np.zeros(my_hi - my_lo, dtype=bool)
        np.logical_or.at(sep_bad, row_of[is_sep_n], ~(own | anc_ok))
        movable = (vlab < 0) & one_part & ~sep_bad
        mv = np.flatnonzero(movable)
        moves[mv + my_lo] = pmax[mv] - vlab[mv]     # encode the delta
        moves = tc.allreduce_sum_any(moves)
        if not moves.any():
            break
        cand = lab + moves.astype(np.int64)
        # verify: two adjacent vertices moved into different parts makes
        # a cross-part edge — revert the higher-indexed endpoint
        bad = ((cand[sr_s] >= 0) & (cand[sc_s] >= 0)
               & (cand[sr_s] != cand[sc_s]))
        revert = np.zeros(n)
        if bad.any():
            hi_end = np.maximum(sr_s[bad], sc_s[bad])
            revert[hi_end] = 1.0
        revert = tc.allreduce_sum_any(revert)
        lab = np.where(revert > 0, lab, cand)
    return lab


# ---------------------------------------------------------------------------
# bordered supernodal symbolic (per part, and for the separator block)
# ---------------------------------------------------------------------------

def _constrained_postorder(parent, m):
    """Postorder of the bordered etree, stable-partitioned so the m part
    columns keep positions 0..m-1 (in postorder relative order) and the
    boundary columns keep m..q-1 in their original ascending order.
    Ancestor chains keep their relative order under postorder, so
    parent > child still holds afterwards."""
    from superlu_dist_tpu.ordering.etree import postorder as _po
    from superlu_dist_tpu import native
    post = native.postorder(parent)
    if post is None:
        post = _po(parent)
    part = post[post < m]                    # postorder among part cols
    bnd = np.arange(m, len(parent), dtype=np.int64)  # natural boundary
    return np.concatenate([part, bnd])


def _bordered_symbolic(m, q, indptr, indices, relax, max_supernode):
    """Supernodal symbolic of the leading m columns of a q×q bordered
    pattern (columns m..q-1 are boundary: they appear only as row
    indices; their own fill is computed but discarded).

    Returns (post_part, sn_start, sn_rows, sn_parent, parent_cols):
    post_part maps new part position -> input part column; sn_* describe
    supernodes over the m part columns in the new labels, with row
    indices in the new labeling (boundary rows keep labels >= m, whose
    relative order equals the input's); sn_parent is -1 for local roots.
    parent_cols is the column etree over the m part columns (-1 when the
    parent is a boundary column).

    The machinery is symbolic_factorize's (symbolic/symbfact.py) applied
    to the bordered square: the augmented matrix has empty boundary
    columns, native.etree sees their incident edges through the part
    rows, and the constrained postorder keeps the part block leading."""
    from superlu_dist_tpu import native
    from superlu_dist_tpu.ordering.etree import etree_symmetric

    parent0 = native.etree(q, indptr, indices)
    if parent0 is None:
        parent0 = etree_symmetric(q, indptr, indices)
    post = _constrained_postorder(parent0, m)
    inv_post = invert_perm(post)
    # relabel the pattern (tracer-free: no value alignment needed here)
    tr = SparseCSR(q, q, indptr, indices,
                   np.zeros(len(indices), dtype=np.float64))
    b = tr.permute(post, post)
    old_parents = parent0[post]
    parent = np.where(old_parents >= 0,
                      inv_post[np.clip(old_parents, 0, None)], -1)

    nat = native.symbolic(q, b.indptr, b.indices, parent, relax,
                          max_supernode)
    if nat is not None:
        sn_start, col_to_sn, sn_parent, _lev, rows_ptr, rows_data = nat
        sn_rows = np.split(rows_data, rows_ptr[1:-1])
    else:
        from superlu_dist_tpu.symbolic.symbfact import build_supernodes_py
        sn_start, col_to_sn, sn_rows, sn_parent = build_supernodes_py(
            q, b.indptr, b.indices, parent, relax, max_supernode,
            strict=False)

    # split any supernode straddling the part/boundary frontier, then
    # drop the boundary supernodes (their structures were scaffolding)
    sn_start = np.asarray(sn_start, dtype=np.int64)
    keep_start, keep_rows = [], []
    for s in range(len(sn_start) - 1):
        f, l = int(sn_start[s]), int(sn_start[s + 1])
        if l <= m:
            keep_start.append(f)
            keep_rows.append(np.asarray(sn_rows[s], dtype=np.int64))
        elif f < m:
            # lower piece [f, m): its columns' structure is the removed
            # upper piece's columns plus the full row set (a supernodal
            # superset — stored zeros, same contract as amalgamation)
            keep_start.append(f)
            keep_rows.append(np.concatenate([
                np.arange(m, l, dtype=np.int64),
                np.asarray(sn_rows[s], dtype=np.int64)]))
    ns = len(keep_start)
    sn_start_p = np.array(keep_start + [m], dtype=np.int64)
    col_to_sn_p = np.repeat(np.arange(ns), np.diff(sn_start_p))
    sn_parent_p = np.full(ns, -1, dtype=np.int64)
    for s in range(ns):
        r = keep_rows[s]
        if len(r) and r[0] < m:
            sn_parent_p[s] = col_to_sn_p[r[0]]
    # column etree over part columns (supernodal rule: next member
    # column, else first row)
    parent_cols = np.full(m, -1, dtype=np.int64)
    for s in range(ns):
        f, l = int(sn_start_p[s]), int(sn_start_p[s + 1])
        parent_cols[f:l - 1] = np.arange(f + 1, l)
        r = keep_rows[s]
        parent_cols[l - 1] = int(r[0]) if len(r) and r[0] < m else -1
    return post[:m], sn_start_p, keep_rows, sn_parent_p, parent_cols


# ---------------------------------------------------------------------------
# the driver
# ---------------------------------------------------------------------------

def panalyze(tc: TreeComm, options, a_loc: DistributedCSR, stats=None,
             coarse_target: int | None = None):
    """Distributed analysis: EQUIL → ROWPERM → distributed COLPERM →
    subtree-partitioned SYMBFACT → assembly + plan on root → skeleton
    broadcast.  Returns (lu, bvals) on EVERY rank — drop-in for the
    root-analysis path of parallel/pgssvx._pgssvx_mesh.

    Falls back to the serial root analysis for problems too small to
    partition (n < 64·P).

    Rank-failure tolerance: every stage is parameterized ONLY by
    (tc.n_ranks, tc.rank) and the re-dealt input rows, never by a
    remembered world size — which is what lets a recovery epoch
    (parallel/recover.pgssvx_ft, Options.ft="shrink") simply re-run this
    partitioning over the surviving rank set after a peer died
    mid-analysis; a death inside any collective here surfaces as
    RankFailureError on every survivor once SLU_TPU_COMM_TIMEOUT_S
    bounds the legs."""
    from superlu_dist_tpu.drivers.gssvx import LUFactorization, analyze
    from superlu_dist_tpu.numeric.plan import build_plan
    from superlu_dist_tpu.parallel.pgssvx import gather_distributed
    from superlu_dist_tpu.rowperm.equil import _THRESH
    from superlu_dist_tpu.utils.options import ColPerm, Fact, RowPerm
    from superlu_dist_tpu.utils.stats import Stats

    if stats is None:
        stats = Stats()
    n = a_loc.n
    P = tc.n_ranks
    if options.fact != Fact.DOFACT:
        raise SuperLUError("panalyze supports Fact=DOFACT only "
                           "(reuse tiers need the serial analysis)")
    if options.col_perm != ColPerm.ND_AT_PLUS_A:
        # the reference likewise rejects ParSymbFact with any ColPerm
        # but PARMETIS — the distributed ordering IS the column perm
        raise SuperLUError(
            "ParSymbFact computes its own distributed nested-dissection "
            "ordering; col_perm must be ND/METIS_AT_PLUS_A")
    if P == 1 or n < 64 * P:
        from superlu_dist_tpu.parallel.pgssvx import root_analyze_bcast
        return root_analyze_bcast(tc, options, a_loc, stats)

    complex_in = np.issubdtype(np.asarray(a_loc.data).dtype,
                               np.complexfloating)
    vdtype = np.complex128 if complex_in else np.float64
    lo_row = a_loc.fst_row
    m_loc = a_loc.m_loc

    # ---- EQUIL (distributed pdgsequ/pdlaqgs) -----------------------------
    rows_l = np.repeat(np.arange(m_loc), np.diff(a_loc.indptr))
    with stats.timer("EQUIL"):
        vals = np.asarray(a_loc.data, dtype=vdtype)
        if options.equil:
            r, c, rowcnd, colcnd, amax = _pgsequ(tc, a_loc)
            small = np.finfo(np.float64).tiny / np.finfo(np.float64).eps
            large = 1.0 / small
            do_row = rowcnd < _THRESH
            do_col = colcnd < _THRESH or amax < small or amax > large
            equed = {(False, False): "N", (True, False): "R",
                     (False, True): "C", (True, True): "B"}[(do_row, do_col)]
            dr = r if do_row else np.ones(n)
            dc = c if do_col else np.ones(n)
            vals = vals * dr[lo_row + rows_l] * dc[a_loc.indices]
        else:
            equed = "N"
            dr = dc = np.ones(n)

    # ---- ROWPERM ---------------------------------------------------------
    # LargeDiag matchings are inherently serial — transient gather on
    # root ONLY (freed before the memory-heavy phases), like the
    # reference's gather before dldperm_dist (pdgssvx.c:775).
    with stats.timer("ROWPERM"):
        rp = options.row_perm
        if rp in (RowPerm.LargeDiag_MC64, RowPerm.LargeDiag_AWPM):
            from superlu_dist_tpu.parallel.pgssvx import bcast_result
            from superlu_dist_tpu.rowperm.matching import (
                approximate_weight_matching, maximum_product_matching)
            scaled = DistributedCSR(n=n, m_loc=m_loc, fst_row=lo_row,
                                    indptr=a_loc.indptr,
                                    indices=a_loc.indices, data=vals)
            a1_root = gather_distributed(tc, scaled, root=0)

            def _match():
                if rp == RowPerm.LargeDiag_MC64:
                    return maximum_product_matching(a1_root)
                return (approximate_weight_matching(a1_root),
                        np.ones(n), np.ones(n))

            row_order, r1, c1 = bcast_result(tc, _match)
            del a1_root
        elif rp == RowPerm.MY_PERMR:
            row_order = np.asarray(options.user_perm_r, dtype=np.int64)
            r1 = c1 = np.ones(n)
        else:
            row_order = np.arange(n, dtype=np.int64)
            r1 = c1 = np.ones(n)
        inv_row = invert_perm(row_order)
        vals = vals * r1[lo_row + rows_l] * c1[a_loc.indices]
        # a2-space labels: orig row i -> inv_row[i]; columns unchanged
        gr = inv_row[lo_row + rows_l]            # a2 row label per entry
        gc = np.asarray(a_loc.indices, dtype=np.int64)

    # anorm of a2 = max |entry| (norm_max), scale-invariant to labels
    anorm = float(_allreduce_max(
        tc, np.array([np.abs(vals).max(initial=0.0)]))[0])

    # ---- distributed symmetrization --------------------------------------
    # Route (r, c, v) to owner(r) and the transpose marker (c, r, 0) to
    # owner(c); owners aggregate duplicates by sum (transpose zeros do
    # not perturb) — symmetrize_pattern's union, distributed.
    step = -(-n // P)
    owner_of = lambda v: np.minimum(v // step, P - 1)
    dest = np.concatenate([owner_of(gr), owner_of(gc)])
    got = _route(tc, dest, {
        "r": np.concatenate([gr, gc]),
        "c": np.concatenate([gc, gr]),
        "v": np.concatenate([vals, np.zeros_like(vals)]),
    })
    sr = got["r"].real.astype(np.int64)
    sc = got["c"].real.astype(np.int64)
    sv = got["v"].astype(vdtype)
    # aggregate (r, c) duplicates (empty receive: an overhanging rank)
    if len(sr):
        key = sr * n + sc
        order_k = np.argsort(key, kind="stable")
        key, sr, sc, sv = (key[order_k], sr[order_k], sc[order_k],
                           sv[order_k])
        uniq = np.concatenate([[True], key[1:] != key[:-1]])
        grp = np.cumsum(uniq) - 1
        sv_agg = np.zeros(int(grp[-1]) + 1, dtype=vdtype)
        np.add.at(sv_agg, grp, sv)
        sr, sc, sv = sr[uniq], sc[uniq], sv_agg
    my_lo = min(tc.rank * step, n)
    my_hi = min((tc.rank + 1) * step, n)

    # ---- distributed COLPERM (coarsen -> coarse ND on root) --------------
    with stats.timer("COLPERM"):
        if coarse_target is None:
            coarse_target = max(2048, 64 * P)
        # current level: rank owns contiguous label block [cur_lo, cur_hi)
        cur_r, cur_c = sr - my_lo, sc      # rows local, cols global
        cur_w = np.ones(my_hi - my_lo, dtype=np.int64)   # vertex weights
        cur_ew = np.ones(len(cur_r), dtype=np.int64)     # edge weights
        cur_n = n
        blocks = _block_bounds(tc, my_hi - my_lo)
        maps = []                          # replicated fine->coarse maps
        for _lvl in range(20):
            if cur_n <= coarse_target:
                break
            match = _local_match(len(cur_w), cur_r, cur_c, cur_ew,
                                 blocks[tc.rank])
            # coarse ids: contiguous per rank via count scan
            n_coarse_loc = int(match.max() + 1) if len(match) else 0
            counts = np.zeros(P)
            counts[tc.rank] = n_coarse_loc
            counts = tc.allreduce_sum_any(counts)
            coff = np.zeros(P + 1, dtype=np.int64)
            coff[1:] = np.cumsum(counts).astype(np.int64)
            # replicated fine->coarse map for this level
            fmap = np.zeros(cur_n, dtype=np.int64)
            fmap[blocks[tc.rank][0]:blocks[tc.rank][1]] = \
                match + coff[tc.rank]
            fmap = tc.allreduce_sum_any(fmap).astype(np.int64)
            maps.append(fmap)
            # contract local edges
            ncr = fmap[cur_r + blocks[tc.rank][0]]
            ncc = fmap[cur_c]
            keep = ncr != ncc
            ncr, ncc, new_ew = ncr[keep], ncc[keep], cur_ew[keep]
            k2 = ncr * int(coff[-1]) + ncc
            o2 = np.argsort(k2, kind="stable")
            k2, ncr, ncc, new_ew = k2[o2], ncr[o2], ncc[o2], new_ew[o2]
            u2 = np.concatenate([[True], k2[1:] != k2[:-1]]) \
                if len(k2) else np.empty(0, dtype=bool)
            g2 = np.cumsum(u2) - 1
            ew_agg = np.zeros(int(g2[-1]) + 1 if len(g2) else 0,
                              dtype=np.int64)
            np.add.at(ew_agg, g2, new_ew)
            nw = np.zeros(n_coarse_loc, dtype=np.int64)
            np.add.at(nw, match, cur_w)
            new_n = int(coff[-1])
            if new_n >= 0.95 * cur_n:      # stalled — stop coarsening
                maps.pop()
                break
            cur_r = ncr[u2] - coff[tc.rank]
            cur_c = ncc[u2]
            cur_ew = ew_agg
            cur_w = nw
            cur_n = new_n
            blocks = [(int(coff[i]), int(coff[i + 1])) for i in range(P)]
        # gather the coarse graph (edges + vertex weights) on root
        er, _ = _gather_concat(tc, (cur_r + blocks[tc.rank][0]).astype(
            np.float64))
        ec, _ = _gather_concat(tc, cur_c.astype(np.float64))
        ew, _ = _gather_concat(tc, cur_ew.astype(np.float64))
        vw_full = np.zeros(cur_n)
        vw_full[blocks[tc.rank][0]:blocks[tc.rank][1]] = cur_w
        vw_full = tc.reduce_sum_any(vw_full, root=0)
        from superlu_dist_tpu.parallel.pgssvx import bcast_result

        def _bisect():
            from superlu_dist_tpu.sparse.formats import coo_to_csr
            cg = coo_to_csr(cur_n, cur_n, er.astype(np.int64),
                            ec.astype(np.int64), ew)
            labels, _nsep, part_anc = _coarse_bisect(
                cur_n, cg.indptr, cg.indices, vw_full, P)
            return labels, part_anc

        clabels, part_anc = bcast_result(tc, _bisect)
        clabels = np.asarray(clabels, dtype=np.int64)
        # project through the contraction maps: label of fine vertex v
        lab = clabels
        for fmap in reversed(maps):
            lab = lab[fmap]
        # lab[v] >= 0: part id; < 0: separator node -(id+1), deeper first
        # projected separators are THICK SLABS (one matching level ~
        # doubles the width) and top-separator width enters the fill
        # cubically — refine them on the fine graph before partitioning
        lab = _trim_separators(tc, lab, sr, sc, my_lo, my_hi, part_anc,
                               P)

    # ---- route rows to their part owners (seps to root) ------------------
    dest = np.where(lab[sr] >= 0, lab[sr], 0).astype(np.int64)
    got = _route(tc, dest, {"r": sr.astype(np.float64),
                            "c": sc.astype(np.float64), "v": sv})
    pr = got["r"].real.astype(np.int64)
    pc = got["c"].real.astype(np.int64)
    pv = got["v"].astype(vdtype)
    # rank 0 also received every separator row; split them out
    sep_mask = lab[pr] < 0
    part_mask = lab[pr] == tc.rank
    ppr, ppc, ppv = pr[part_mask], pc[part_mask], pv[part_mask]

    sr0, sc0, sv0 = pr[sep_mask], pc[sep_mask], pv[sep_mask]
    with stats.timer("SYMBFACT"):
        ctx = _part_symbolic(tc, n, P, lab, ppr, ppc, ppv, options,
                             vdtype)

    def _finish_root():
        # root-only: separator symbolic + assembly + plan.  Runs inside
        # bcast_result so an assembly failure reaches every rank
        # instead of stranding them in the skeleton broadcast.
        sf, bvals = _assemble_root(ctx, n, P, lab, sr0, sc0, sv0,
                                   options, vdtype)
        with stats.timer("DIST"):
            # the same scheduler as the serial analysis: per-rank plans
            # are this one root-built skeleton broadcast to every rank,
            # so schedule/window/align must come from the SAME options
            # (a rank-varying env knob would desynchronize the SPMD
            # dispatch sequence)
            plan = build_plan(sf, min_bucket=options.min_bucket,
                              growth=options.bucket_growth,
                              schedule=options.schedule,
                              window=options.sched_window,
                              align=options.sched_align)
        return LUFactorization(
            n=n, options=options, equed=equed, dr=dr, dc=dc, r1=r1,
            c1=c1, row_order=row_order, col_order=None, sf=sf,
            plan=plan, numeric=None, anorm=anorm, a=None,
            a_sym_indptr=None, a_sym_indices=None), bvals

    from superlu_dist_tpu.parallel.pgssvx import bcast_result
    return bcast_result(tc, _finish_root)


def _block_bounds(tc, m_mine):
    counts = np.zeros(tc.n_ranks)
    counts[tc.rank] = m_mine
    counts = tc.allreduce_sum_any(counts)
    offs = np.zeros(tc.n_ranks + 1, dtype=np.int64)
    offs[1:] = np.cumsum(counts).astype(np.int64)
    return [(int(offs[i]), int(offs[i + 1])) for i in range(tc.n_ranks)]


def _local_match(m, er_loc, ec, ew, block):
    """Greedy heavy-edge matching among THIS rank's vertices (both
    endpoints owned); returns fine-local -> coarse-local map."""
    lo, hi = block
    # local-local edges only
    ll = (ec >= lo) & (ec < hi)
    r_l, c_l, w_l = er_loc[ll], ec[ll] - lo, ew[ll]
    order = np.argsort(-w_l, kind="stable")
    matched = np.full(m, -1, dtype=np.int64)
    for i in order:
        u, v = int(r_l[i]), int(c_l[i])
        if u != v and matched[u] < 0 and matched[v] < 0:
            matched[u] = v
            matched[v] = u
    out = np.full(m, -1, dtype=np.int64)
    nxt = 0
    for u in range(m):
        if out[u] >= 0:
            continue
        out[u] = nxt
        if matched[u] >= 0:
            out[matched[u]] = nxt
        nxt += 1
    return out


def _part_symbolic(tc, n, P, lab, pr, pc, pv, options, vdtype):
    """Per-part bordered symbolic + the piece gathers.  Returns the
    gathered context for _assemble_root on rank 0, None elsewhere.
    Everything rank-local here is O(part), the psymbfact property."""
    from superlu_dist_tpu import native
    from superlu_dist_tpu.ordering.dissection import bfs_nd

    relax = options.relax
    max_supernode = options.max_supernode

    # ---- local ordering + bordered symbolic on my part -------------------
    verts = np.unique(pr)                   # my part's vertices (a2 labels)
    m = len(verts)
    r_l = np.searchsorted(verts, pr)
    is_int = lab[pc] == tc.rank
    # the separator invariant must fail COLLECTIVELY: a single-rank
    # assert here would strand the peers in the allreduces below
    # (slulint SLU101 — rank-dependent early exit before a collective)
    bad = np.zeros(1)
    bad[0] = float(np.any(~(is_int | (lab[pc] < 0))))
    if int(tc.allreduce_sum_any(bad)[0]):
        raise SuperLUError(
            "cross-part edge: projected separator is not a separator")
    bnd = np.unique(pc[~is_int])            # touched separator vertices
    c_l = np.where(is_int, np.searchsorted(verts, pc),
                   m + np.searchsorted(bnd, pc))
    from superlu_dist_tpu.sparse.formats import coo_to_csr
    if m:
        # internal subgraph CSR for the ordering
        sub = coo_to_csr(m, m, r_l[is_int], c_l[is_int],
                         np.zeros(int(is_int.sum())))
        order0 = native.mlnd(m, sub.indptr, sub.indices)
        if order0 is None:
            order0 = bfs_nd(m, sub.indptr, sub.indices)
        inv0 = invert_perm(order0)
        q = m + len(bnd)
        aug = coo_to_csr(q, q, inv0[r_l],
                         np.where(c_l < m, inv0[np.clip(c_l, 0, m - 1)],
                                  c_l),
                         np.zeros(len(r_l)))
        post_part, sn_start_p, sn_rows_p, sn_parent_p, parent_cols = \
            _bordered_symbolic(m, q, aug.indptr, aug.indices, relax,
                               max_supernode)
        # my part's final order: position t holds a2 label
        # verts[order0[post_part[t]]]
        part_perm = verts[order0[post_part]]
    else:
        part_perm = np.empty(0, dtype=np.int64)
        sn_start_p = np.array([0], dtype=np.int64)
        sn_rows_p, sn_parent_p = [], np.empty(0, dtype=np.int64)
        parent_cols = np.empty(0, dtype=np.int64)
        bnd = np.empty(0, dtype=np.int64)

    # part offsets in the global elimination layout
    sizes = np.zeros(P)
    sizes[tc.rank] = m
    sizes = tc.allreduce_sum_any(sizes)
    poffs = np.zeros(P + 1, dtype=np.int64)
    poffs[1:] = np.cumsum(sizes).astype(np.int64)
    off_p = int(poffs[tc.rank])
    sep_start = int(poffs[-1])

    # ---- ship symbolic pieces + pattern/value slices to root -------------
    # rows encoding: in-part -> final global (off_p + local); separator
    # -> -(a2_label + 1), decoded on root once the separator order exists
    def enc_rows(rr):
        if len(bnd) == 0:
            return rr + off_p
        return np.where(rr < m, rr + off_p,
                        -(bnd[np.clip(rr - m, 0, len(bnd) - 1)] + 1))
    rows_flat = (np.concatenate([enc_rows(r) for r in sn_rows_p])
                 if sn_rows_p else np.empty(0, dtype=np.int64))
    rows_cnt = np.array([len(r) for r in sn_rows_p], dtype=np.int64)

    # pattern slice: for each of my part columns IN FINAL LOCAL ORDER,
    # its full adjacency (values included) with the same encoding
    if m:
        final_of_vert = np.empty(m, dtype=np.int64)     # vert idx -> final
        final_of_vert[np.searchsorted(verts, part_perm)] = \
            np.arange(m) + off_p
        er_fin = final_of_vert[r_l]
        if len(bnd) == 0:
            ec_enc = final_of_vert[c_l]
        else:
            ec_enc = np.where(c_l < m,
                              final_of_vert[np.clip(c_l, 0, m - 1)],
                              -(bnd[np.clip(c_l - m, 0,
                                            len(bnd) - 1)] + 1))
        o = np.argsort(er_fin, kind="stable")
        er_fin, ec_enc, ev = er_fin[o], ec_enc[o], pv[o]
        row_cnt_pat = np.bincount(er_fin - off_p, minlength=m)
    else:
        er_fin = ec_enc = np.empty(0, dtype=np.int64)
        ev = np.empty(0, dtype=vdtype)
        row_cnt_pat = np.empty(0, dtype=np.int64)

    g = {}
    g["perm"], _ = _gather_concat(tc, part_perm.astype(np.float64))
    g["snw"], _ = _gather_concat(
        tc, np.diff(sn_start_p).astype(np.float64))
    g["snp"], snp_offs = _gather_concat(
        tc, sn_parent_p.astype(np.float64))
    g["rcnt"], _ = _gather_concat(tc, rows_cnt.astype(np.float64))
    g["rflat"], _ = _gather_concat(tc, rows_flat.astype(np.float64))
    g["pcnt"], _ = _gather_concat(tc, row_cnt_pat.astype(np.float64))
    g["pcol"], _ = _gather_concat(tc, ec_enc.astype(np.float64))
    g["pval"], _ = _gather_concat(tc, ev, dtype=vdtype)
    g["pcols_etree"], _ = _gather_concat(
        tc, np.where(parent_cols >= 0, parent_cols + off_p, -1).astype(
            np.float64))

    if tc.rank != 0:
        return None
    return {"g": g, "snp_offs": snp_offs, "sep_start": sep_start}


def _assemble_root(ctx, n, P, lab, sr0, sc0, sv0, options, vdtype):
    """Root-only tail of the distributed symbolic: separator-block
    symbolic with the parts' boundary cliques folded in, then global
    assembly into one SymbolicFact + the permuted values.  Split from
    _part_symbolic so panalyze can run it under the exception-shipping
    broadcast."""
    from superlu_dist_tpu.symbolic.symbfact import (
        _finish, amalgamate_supernodes)

    relax = options.relax
    max_supernode = options.max_supernode
    g = ctx["g"]
    snp_offs = ctx["snp_offs"]
    sep_start = ctx["sep_start"]

    # ---- root: separator block symbolic ---------------------------------
    # separator vertices ordered by (deeper tree node first, then label);
    # the bordered-symbolic's own etree postorder refines within
    sep_verts_all = np.flatnonzero(lab < 0)
    n_sep = len(sep_verts_all)
    assert sep_start + n_sep == n
    sep_sort = np.lexsort((sep_verts_all, -lab[sep_verts_all]))
    sep_init = sep_verts_all[sep_sort]      # initial sep order (a2 labels)
    sep_pos0_arr = np.full(n, -1, dtype=np.int64)
    sep_pos0_arr[sep_init] = np.arange(n_sep)

    # pattern among separators (root received all separator rows)
    ss_mask = lab[sc0] < 0
    ssr = sep_pos0_arr[sr0[ss_mask]]
    ssc = sep_pos0_arr[sc0[ss_mask]]
    # cliques: local-root supernodes' separator rows, from every part
    widths_all = g["snw"].astype(np.int64)
    snp_all = g["snp"].astype(np.int64)
    rcnt_all = g["rcnt"].astype(np.int64)
    rflat_all = g["rflat"].astype(np.int64)
    # the float64 transport copies are dead once decoded — the root's
    # transient peak is THE assembly cost, keep it one copy per payload
    del g["snw"], g["snp"], g["rcnt"], g["rflat"]
    rows_split = np.split(rflat_all, np.cumsum(rcnt_all)[:-1]) \
        if len(rcnt_all) else []
    clique_r, clique_c = [], []
    for s, rowsv in enumerate(rows_split):
        if snp_all[s] >= 0:
            continue
        sep_rows = -rowsv[rowsv < 0] - 1     # a2 labels
        if len(sep_rows) > 1:
            p0 = sep_pos0_arr[sep_rows]
            cmin = p0.min()
            others = p0[p0 != cmin]
            clique_r.append(np.full(len(others), cmin, dtype=np.int64))
            clique_c.append(others)
    if clique_r:
        ssr = np.concatenate([ssr] + clique_r + clique_c)
        ssc = np.concatenate([ssc] + clique_c + clique_r)
    from superlu_dist_tpu.sparse.formats import coo_to_csr
    if n_sep:
        sgraph = coo_to_csr(n_sep, n_sep, ssr, ssc, np.zeros(len(ssr)))
        post_sep, sn_start_s, sn_rows_s, sn_parent_s, parent_cols_s = \
            _bordered_symbolic(n_sep, n_sep, sgraph.indptr,
                               sgraph.indices, relax, max_supernode)
        sep_final = sep_init[post_sep]       # final sep order (a2 labels)
    else:
        sep_final = np.empty(0, dtype=np.int64)
        sn_start_s = np.array([0], dtype=np.int64)
        sn_rows_s, sn_parent_s = [], np.empty(0, dtype=np.int64)
        parent_cols_s = np.empty(0, dtype=np.int64)
    sep_final_pos = np.full(n, -1, dtype=np.int64)
    sep_final_pos[sep_final] = np.arange(n_sep) + sep_start

    # ---- root: global assembly ------------------------------------------
    perm = np.concatenate([g["perm"].astype(np.int64), sep_final])
    assert len(perm) == n
    widths = np.concatenate([widths_all, np.diff(sn_start_s)])
    sn_start = np.zeros(len(widths) + 1, dtype=np.int64)
    np.cumsum(widths, out=sn_start[1:])
    assert sn_start[-1] == n
    ns_part = len(widths_all)
    col_to_sn = np.repeat(np.arange(len(widths)), widths)

    def dec_rows(rv):
        out = np.where(rv >= 0, rv, sep_final_pos[-rv - 1])
        out.sort()
        return out

    sn_rows = [dec_rows(r) for r in rows_split]
    del rows_split, rflat_all          # decoded copies supersede them
    sn_rows += [np.asarray(r, dtype=np.int64) + sep_start
                for r in sn_rows_s]
    # parents: per-part ids shift by the rank's supernode offset; local
    # roots resolve through their (now decoded) first row
    sn_parent = np.empty(len(widths), dtype=np.int64)
    for rk in range(P):
        lo, hi = int(snp_offs[rk]), int(snp_offs[rk + 1])
        for s in range(lo, hi):
            sn_parent[s] = snp_all[s] + lo if snp_all[s] >= 0 else -2
    for s in range(ns_part, len(widths)):
        sp = sn_parent_s[s - ns_part]
        sn_parent[s] = sp + ns_part if sp >= 0 else -1
    for s in range(ns_part):
        if sn_parent[s] == -2:
            r = sn_rows[s]
            sn_parent[s] = col_to_sn[r[0]] if len(r) else -1
    sn_level = np.zeros(len(widths), dtype=np.int64)
    for s in range(len(widths)):
        p = sn_parent[s]
        if p >= 0:
            sn_level[p] = max(sn_level[p], sn_level[s] + 1)

    # column etree (supernodal rule)
    parent = np.full(n, -1, dtype=np.int64)
    pce = g["pcols_etree"].astype(np.int64)
    parent[:sep_start] = pce
    need = np.flatnonzero(parent[:sep_start] < 0)
    for j in need:
        s = col_to_sn[j]
        if j < sn_start[s + 1] - 1:
            parent[j] = j + 1
        else:
            r = sn_rows[s]
            parent[j] = r[0] if len(r) else -1
    for t in range(n_sep):
        j = sep_start + t
        pc_ = parent_cols_s[t]
        if pc_ >= 0:
            parent[j] = pc_ + sep_start
        else:
            s = col_to_sn[j]
            if j < sn_start[s + 1] - 1:
                parent[j] = j + 1
            else:
                r = sn_rows[s]
                parent[j] = r[0] if len(r) else -1

    # ---- root: permuted pattern + values (bvals) -------------------------
    pcnt = g["pcnt"].astype(np.int64)
    pcol_enc = g["pcol"].astype(np.int64)
    pval = g["pval"]
    del g["pcnt"], g["pcol"], g["pval"]
    # separator rows' pattern (root-held), in final labels
    srow_fin = sep_final_pos[sr0]
    scol_fin = np.where(lab[sc0] < 0, sep_final_pos[sc0], -1)
    # non-separator columns in separator rows: their final position is a
    # part position — recover via the part perm
    part_final_pos = np.full(n, -1, dtype=np.int64)
    part_final_pos[perm[:sep_start]] = np.arange(sep_start)
    scol_fin = np.where(scol_fin >= 0, scol_fin, part_final_pos[sc0])
    o = np.argsort(srow_fin, kind="stable")
    srow_fin, scol_fin, sv_fin = srow_fin[o], scol_fin[o], sv0[o]
    sep_cnt = np.bincount(srow_fin - sep_start, minlength=n_sep) \
        if n_sep else np.empty(0, dtype=np.int64)
    # decode part columns' encodings
    pcol_fin = np.where(pcol_enc >= 0, pcol_enc,
                        sep_final_pos[np.where(pcol_enc < 0,
                                               -pcol_enc - 1, 0)])
    counts = np.concatenate([pcnt, sep_cnt])
    indptr = np.zeros(n + 1, dtype=np.int64)
    np.cumsum(counts, out=indptr[1:])
    indices = np.concatenate([pcol_fin, scol_fin])
    bvals = np.concatenate([pval, sv_fin]).astype(vdtype)
    del pcol_enc, pcol_fin, scol_fin, pval, sv_fin
    # sort within each row by final column
    rowid = np.repeat(np.arange(n), counts)
    o = np.lexsort((indices, rowid))
    indices, bvals = indices[o], bvals[o]

    us = np.array([len(r) for r in sn_rows], dtype=np.int64)
    sf = _finish(n, perm, parent, sn_start, col_to_sn, sn_rows,
                 sn_parent, sn_level, us, indptr, indices, None)
    tol = options.amalg_tol
    if tol is None:
        from superlu_dist_tpu.utils.options import _env_float
        tol = _env_float("SLU_TPU_AMALG_TOL", 1.2)
    if tol and tol > 1.0 and sf.n_supernodes > 1:
        sf = amalgamate_supernodes(sf, tol=float(tol),
                                   max_width=max_supernode)
    return sf, bvals
