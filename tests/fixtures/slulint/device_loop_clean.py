"""slulint fixture: the SLU113-clean twin of host_roundtrip_loop.py.

Same dispatch-loop shape, but the loop stays async: device results are
accumulated on the device, explicit syncs go through jax.device_get /
jax.block_until_ready (the sanctioned idiom — visibility is the point),
and all host coercions happen AFTER the loop.
"""

import functools

import jax
import numpy as np


@functools.lru_cache(maxsize=None)
def _kernel(w):
    def step(x):
        return x * 2.0

    return jax.jit(step)


def dispatch(xs):
    ys = []
    for x in xs:
        kern = _kernel(8)
        y = kern(x)
        ys.append(y)                          # stays async
        probe = jax.device_get(y)             # explicit sync: exempt
        if probe[0] > 0:                      # host value: clean
            ys[-1] = y
    total = float(np.asarray(jax.block_until_ready(ys[-1]))[0])
    return ys, total
