#!/usr/bin/env python
"""Precision-lint gate (slulint v5): the tree is clean under the
precision-flow rules and every program the REAL executors build passes
the runtime dtype audit.

Phase A — whole-tree source scan: SLU115 (implicit downcast), SLU116
(accumulation dtype), SLU117 (EFT purity) and SLU118 (tolerance
hygiene) over the default scan scope via the slulint CLI — any finding
fails the gate (the in-tree true positives were fixed by the v5 PR;
new ones must not accrete).

Phase B — runtime twin coverage: ``SLU_TPU_VERIFY_DTYPES=1`` over the
gate gallery (poisson2d + hilbert) through all three factor executors
and the device solve sweeps (fused and streamed, plain and transpose):
every submitted program is traced and walked by
``audit_narrowing``/``audit_accumulation`` with ZERO findings, the
census ``#dtypes`` audit notes cover 100% of the audited programs, and
a bf16-GEMM-tier factorization proves the sanctioned GEMM-input
narrowing (cast consumed by an f32-accumulating dot_general) passes
the audit rather than false-positiving.

Exit 0 = pass.  One gate of scripts/ci_gates.sh (shared contract:
diagnostics on stdout/stderr, non-zero on any regression, hard
timeout).
"""

import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ["SLU_TPU_VERIFY_DTYPES"] = "1"

import numpy as np  # noqa: E402


def phase_a() -> None:
    cmd = [sys.executable, "-m", "superlu_dist_tpu.analysis",
           "--rules", "SLU115,SLU116,SLU117,SLU118", "--no-baseline"]
    proc = subprocess.run(cmd, cwd=REPO, capture_output=True, text=True,
                          timeout=300)
    sys.stdout.write(proc.stdout)
    sys.stderr.write(proc.stderr)
    assert proc.returncode == 0, \
        f"whole-tree SLU115-SLU118 scan found new precision findings"
    print("[precision-lint] phase A: tree clean under SLU115-SLU118")


def _analyzed(a):
    from superlu_dist_tpu.ordering.dispatch import get_perm_c
    from superlu_dist_tpu.sparse.formats import symmetrize_pattern
    from superlu_dist_tpu.symbolic.symbfact import symbolic_factorize
    from superlu_dist_tpu.utils.options import Options

    sym = symmetrize_pattern(a)
    col_order = get_perm_c(Options(), a, sym)
    sf = symbolic_factorize(sym, col_order)
    return sf, sym.data[sf.value_perm], a.norm_max()


def check(name, a, gemm_prec=None) -> int:
    from superlu_dist_tpu.numeric.factor import numeric_factorize
    from superlu_dist_tpu.numeric.plan import build_plan
    from superlu_dist_tpu.solve.device import DeviceSolver

    sf, vals, anorm = _analyzed(a)
    plan = build_plan(sf)
    rng = np.random.default_rng(7)
    rhs = rng.standard_normal((plan.n, 5))
    for ex in ("fused", "stream", "mega"):
        fact = numeric_factorize(plan, vals, anorm, executor=ex,
                                 gemm_prec=gemm_prec)
        if ex == "stream":
            for fused in (True, False):
                ds = DeviceSolver(fact, fused=fused)
                ds.solve(rhs)
                ds.solve_trans(rhs)
    from superlu_dist_tpu.utils import programaudit
    aud = programaudit._DTYPE_AUDITOR
    assert aud is not None, "SLU_TPU_VERIFY_DTYPES=1 allocated no auditor"
    assert aud.findings == [], aud.findings
    tier = f", gemm_prec={gemm_prec}" if gemm_prec else ""
    print(f"[precision-lint] {name}{tier}: {len(aud.audited)} program(s) "
          "audited clean")
    return len(aud.audited)


def main():
    phase_a()

    import jax
    jax.config.update("jax_enable_x64", True)
    from superlu_dist_tpu.models.gallery import hilbert, poisson2d

    total = 0
    total = max(total, check("poisson2d nx=12", poisson2d(12)))
    total = max(total, check("hilbert n=48", hilbert(48)))
    # the bf16 tier narrows GEMM inputs by design — the sanctioned
    # pattern (cast -> f32-accumulating dot_general) must audit CLEAN
    total = max(total, check("poisson2d nx=12", poisson2d(12),
                             gemm_prec="bf16"))

    from superlu_dist_tpu.obs.compilestats import COMPILE_STATS
    blk = COMPILE_STATS.audit_block()
    assert blk["programs"] == total and total > 0, \
        f"census #dtypes notes disagree: {blk} vs {total} audited"
    assert blk["findings"] == 0, f"findings leaked past submit: {blk}"
    print(f"[precision-lint] OK: {blk['programs']} programs dtype-audited, "
          "0 findings, 100% coverage")


if __name__ == "__main__":
    main()
