/*
 * C API for the TPU-native SuperLU_DIST framework.
 *
 * Capability analog of the reference's C-callable library API (pdgssvx,
 * SRC/pdgssvx.c:505) and of its handle-based Fortran wrapper layer
 * (FORTRAN/superlu_c2f_dwrap.c:51-327): C and Fortran programs solve
 * sparse A X = B through a solver runtime hosted in an embedded Python
 * interpreter that drives the JAX/XLA compute path.  Factorization
 * handles give the reference's Fact-reuse tiers (FACTORED re-solves).
 *
 * Matrix input: CSR with int64 indices (the XSDK 64-bit index build of the
 * reference), double values.  Right-hand sides and solutions are
 * column-major (Fortran order), n x nrhs.
 *
 * Fortran usage (ISO_C_BINDING): see superlu_mod.f90 next to this header.
 *
 * Link:  cc app.c -lslu_tpu $(python3-config --embed --ldflags)
 *        with libslu_tpu.so built by bindings/build.py.
 *
 * All functions return 0 on success; > 0 mirrors pdgssvx's info (first
 * zero pivot, 1-based); < 0 is a runtime/usage error.
 */

#ifndef SLU_TPU_H
#define SLU_TPU_H

#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

/* Start the embedded solver runtime (idempotent).  backend may be NULL
 * (session default), "cpu", or "tpu". */
int slu_tpu_init(const char* backend);

/* One-shot expert solve: equilibrate + row-permute + order + factor +
 * solve + refine (the pdgssvx pipeline). */
int slu_tpu_solve(int64_t n, int64_t nnz, const int64_t* indptr,
                  const int64_t* indices, const double* values,
                  const double* b, double* x, int64_t nrhs);

/* Factor once, keep a handle (the dLUstruct_t analog held by the
 * runtime); returns 0 and sets *handle on success. */
int slu_tpu_factor(int64_t n, int64_t nnz, const int64_t* indptr,
                   const int64_t* indices, const double* values,
                   int64_t* handle);

/* Re-solve with an existing factorization (Fact=FACTORED tier). */
int slu_tpu_solve_factored(int64_t handle, int64_t n, const double* b,
                           double* x, int64_t nrhs);

/* Release a factorization handle. */
int slu_tpu_free_handle(int64_t handle);

/* ---- full-surface API (the superlu_c2f_dwrap.c:51-327 analog) ---------
 * Option handles carry the reference's superlu_dist_options_t surface.
 * Keys accept reference names ("Fact", "Equil", "ColPerm", "RowPerm",
 * "ReplaceTinyPivot", "IterRefine", "Trans", "DiagInv", "PrintStat",
 * "ParSymbFact" — the distributed-analysis tier of the multi-process
 * driver, parallel/panalysis.py) or
 * native field names (e.g. "relax", "max_supernode", "factor_dtype").
 * Values are strings: enum member names ("METIS_AT_PLUS_A", "NOTRANS",
 * "SamePattern", ...), "YES"/"NO" for flags, or numbers.
 * Errors: -3 bad handle, -5 unknown key/stat, -6 bad value. */

int slu_tpu_options_create(int64_t* opt);
int slu_tpu_options_set(int64_t opt, const char* key, const char* value);
int slu_tpu_options_get(int64_t opt, const char* key, char* buf,
                        int64_t buflen);
int slu_tpu_options_free(int64_t opt);

/* One-shot expert solve under an options handle (0 = defaults), with
 * column-major B/X of leading dimensions ldb/ldx >= n (the reference
 * pdgssvx ldb contract; 0 means ldb = n). */
int slu_tpu_solve_opts(int64_t opt, int64_t n, int64_t nnz,
                       const int64_t* indptr, const int64_t* indices,
                       const double* values, const double* b, int64_t ldb,
                       double* x, int64_t ldx, int64_t nrhs);

/* Factor under an options handle; keeps the options with the handle. */
int slu_tpu_factor_opts(int64_t opt, int64_t n, int64_t nnz,
                        const int64_t* indptr, const int64_t* indices,
                        const double* values, int64_t* handle);

/* Refactor the handle with NEW values on the SAME pattern through the
 * reference reuse tiers: tier 1 = SamePattern, 2 = SamePattern_SameRowPerm
 * (fact_t, superlu_defs.h:489-510). */
int slu_tpu_refactor(int64_t handle, int64_t nnz, const double* values,
                     int64_t tier);

/* Re-solve through a factorization (Fact=FACTORED) under an options
 * handle (0 = the handle's own options); trans/refine ride the options. */
int slu_tpu_solve_factored_opts(int64_t handle, int64_t opt, int64_t n,
                                const double* b, int64_t ldb, double* x,
                                int64_t ldx, int64_t nrhs);

/* Named statistic of a factorization (PStatPrint analog, SRC/util.c:
 * 484-534): per-phase seconds ("FACT", "SOLVE", "REFINE", "EQUIL",
 * "ROWPERM", "COLPERM", "SYMBFACT", "DIST", ...), "FACT_FLOPS",
 * "FACT_GFLOPS", "TINY_PIVOTS", "REFINE_STEPS", "BERR", "LU_BYTES",
 * "TOTAL_BYTES", "NNZ_L", "NNZ_U". */
int slu_tpu_stat_get(int64_t handle, const char* name, double* value);

/* Shut the runtime down.  TERMINAL for the process: CPython extension
 * modules do not survive re-initialization, so any API call after this
 * returns -4.  Only call when done with the solver for good. */
void slu_tpu_finalize(void);

#ifdef __cplusplus
}
#endif

#endif /* SLU_TPU_H */
