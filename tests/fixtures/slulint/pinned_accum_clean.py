"""SLU116 clean-negative fixture: every matmul-family call pins its
accumulation dtype explicitly; host-side numpy contractions have no
accumulation-dtype freedom and are exempt."""
import numpy as np
import jax.numpy as jnp
from jax import lax


def schur(l21, u12):
    return jnp.matmul(l21, u12, preferred_element_type=l21.dtype)


def gather_sum(oh, child):
    return lax.dot_general(oh, child, (((1,), (0,)), ((), ())),
                           preferred_element_type=jnp.float32)


def host_side(a, b):
    return np.matmul(a, b)                 # numpy: accumulates wide
