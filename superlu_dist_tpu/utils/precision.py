"""Precision / verification utilities.

Library-level versions of the reference's dutil_dist.c helpers: fabricated
solutions (dGenXtrue_dist), right-hand sides (dFillRHS_dist), the infinity
-norm error check (pdinf_norm_error, EXAMPLE/pddrive.c:235), and the U
-diagonal gather (pdGetDiagU, SRC/pdGetDiagU.c).  VERDICT r1 flagged these
as living only in tests/gallery; the CLI and test-suite both use this
module now.
"""

from __future__ import annotations

import numpy as np

from superlu_dist_tpu.sparse.formats import SparseCSR


def gen_xtrue(n: int, nrhs: int = 1, dtype=np.float64, seed: int = 0):
    """dGenXtrue_dist analog: a reproducible fabricated solution."""
    rng = np.random.default_rng(seed)
    shape = (n,) if nrhs == 1 else (n, nrhs)
    x = rng.standard_normal(shape)
    if np.issubdtype(np.dtype(dtype), np.complexfloating):
        x = x + 1j * rng.standard_normal(shape)
    return x.astype(dtype)


def fill_rhs(a: SparseCSR, xtrue: np.ndarray, trans: bool = False):
    """dFillRHS_dist analog: b = A·xtrue (or Aᵀ·xtrue)."""
    op = a.transpose() if trans else a
    return op.matvec(xtrue)


def inf_norm_error(x: np.ndarray, xtrue: np.ndarray) -> float:
    """pdinf_norm_error analog: ‖x − xtrue‖∞ / ‖x‖∞."""
    num = float(np.linalg.norm(np.ravel(x - xtrue), np.inf))
    den = float(np.linalg.norm(np.ravel(x), np.inf))
    return num / max(den, 1e-300)


def get_diag_u(numeric) -> np.ndarray:
    """pdGetDiagU analog (SRC/pdGetDiagU.c): gather the U diagonal in the
    factorization's (permuted) column order."""
    plan = numeric.plan
    sf = plan.sf
    hosts = numeric.pull_to_host()
    out = np.empty(sf.n, dtype=np.dtype(numeric.dtype))
    for s in range(sf.n_supernodes):
        g = int(plan.sn_group[s])
        slot = int(plan.sn_slot[s])
        w = sf.sn_width(s)
        lp = hosts[g][0][slot]
        out[sf.sn_start[s]:sf.sn_start[s] + w] = np.diagonal(lp)[:w]
    return out
