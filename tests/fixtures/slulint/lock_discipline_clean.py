"""SLU109 clean negative: one global acquisition order (a before b),
and the blocking work — file I/O, the collective — runs OUTSIDE the
lock on a snapshot taken under it."""
import threading


class Flusher:
    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()
        self._events = []

    def nested(self):
        with self._a:
            with self._b:
                return len(self._events)

    def also_nested(self):
        with self._a:
            with self._b:
                self._events.append(1)

    def flush(self, path):
        with self._a:
            snapshot = list(self._events)
        with open(path, "w") as f:
            f.write(repr(snapshot))

    def ship(self, tc, payload):
        with self._a:
            out = payload.copy()
        return tc.bcast_any(out)
