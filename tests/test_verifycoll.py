"""Runtime SLU106 tests: TreeComm collective-lockstep verification
(SLU_TPU_VERIFY_COLLECTIVES=1) and the stream-executor retrace sentinel.

The 2-rank steering test is the acceptance case: two ranks driven into
DIVERGENT collective sequences must both raise CollectiveMismatchError
naming both call sites, instead of deadlocking in the shared-memory
tree.  The off-path tests pin the zero-overhead contract: with the knob
unset the collective path allocates no verifier state at all.
"""

import multiprocessing as mp
import os

import numpy as np
import pytest

from superlu_dist_tpu import native

pytestmark = pytest.mark.verifycoll

needs_native = pytest.mark.skipif(not native.available(),
                                  reason="native library unavailable")


# ---------------------------------------------------------------------------
# disabled path: no verifier state
# ---------------------------------------------------------------------------

@needs_native
def test_verify_off_allocates_no_verifier_state(monkeypatch):
    monkeypatch.delenv("SLU_TPU_VERIFY_COLLECTIVES", raising=False)
    monkeypatch.delenv("SLU_TPU_COMM_TIMEOUT_S", raising=False)
    monkeypatch.delenv("SLU_TPU_CHAOS", raising=False)
    from superlu_dist_tpu.parallel import treecomm
    name = f"/slu_vc_off_{os.getpid()}"
    with treecomm.TreeComm(name, 1, 0, max_len=16, create=True) as tc:
        # every optional layer stays unallocated on the default path:
        # no verifier, no failure detector (bounded waits off), no
        # chaos monkey — the public entry pays depth bookkeeping only
        assert tc._verifier is None
        assert tc._detector is None
        assert tc._chaos is None
        b = np.arange(4.0)
        tc.bcast(b)
        tc.allreduce_sum(b)
        assert tc._verifier is None and tc._detector is None


@needs_native
def test_verify_on_counts_checks_and_round_trips(monkeypatch):
    monkeypatch.setenv("SLU_TPU_VERIFY_COLLECTIVES", "1")
    from superlu_dist_tpu.parallel.treecomm import TreeComm
    name = f"/slu_vc_on_{os.getpid()}"
    with TreeComm(name, 1, 0, max_len=32, create=True) as tc:
        assert tc._verifier is not None
        payload = np.arange(40.0).reshape(5, 8)
        got = tc.bcast_any(payload.copy())
        np.testing.assert_array_equal(got, payload)
        got = tc.allreduce_sum_any(payload.copy())
        np.testing.assert_array_equal(got, payload)
        blob = b"\x00\xffverify" * 11
        assert tc.bcast_bytes(blob) == blob
        assert tc.bcast_obj({"k": 3})["k"] == 3
        # one check per PUBLIC op — composites/chunks verify once
        assert tc._verifier.checks == 4
        assert tc._verifier.seq == 4


# ---------------------------------------------------------------------------
# 2-rank steering: divergence -> structured error naming both sites
# ---------------------------------------------------------------------------

def _divergent_worker(name, q):
    # import inside the child: must not inherit initialized JAX state
    from superlu_dist_tpu.parallel.treecomm import TreeComm
    from superlu_dist_tpu.utils.errors import CollectiveMismatchError
    tc = TreeComm(name, 2, 1, max_len=64, create=False)
    try:
        x = np.ones(8)
        tc.allreduce_sum_any(x)                  # matched prologue
        tc.reduce_sum_any(x)                     # DIVERGES from the owner
        q.put(("no-error", None))
    except CollectiveMismatchError as exc:
        q.put(("mismatch", (str(exc), exc.records)))
    finally:
        tc.close()


@needs_native
def test_two_rank_divergence_raises_naming_both_sites(monkeypatch):
    """Acceptance: ranks steered into divergent collective sequences get
    a CollectiveMismatchError citing BOTH call sites — the would-be
    deadlock becomes a diagnosis on every rank."""
    monkeypatch.setenv("SLU_TPU_VERIFY_COLLECTIVES", "1")
    from superlu_dist_tpu.parallel.treecomm import TreeComm
    from superlu_dist_tpu.utils.errors import CollectiveMismatchError
    name = f"/slu_vc_div_{os.getpid()}"
    owner = TreeComm(name, 2, 0, max_len=64, create=True)
    ctx = mp.get_context("fork")
    q = ctx.Queue()
    p = ctx.Process(target=_divergent_worker, args=(name, q))
    p.start()
    try:
        x = np.ones(8)
        owner.allreduce_sum_any(x)               # matched prologue
        with pytest.raises(CollectiveMismatchError) as ei:
            owner.bcast_any(x)                   # diverges from the worker
        kind, payload = q.get(timeout=60)
        p.join(timeout=60)
        assert kind == "mismatch", kind
        worker_msg, worker_records = payload
        for msg in (str(ei.value), worker_msg):
            assert "bcast_any" in msg and "reduce_sum_any" in msg
            assert "test_verifycoll.py" in msg
        # both ranks reconstructed both records, with distinct call sites
        for records in (ei.value.records, worker_records):
            assert len(records) == 2
            sites = {r["site"] for r in records}
            assert len(sites) == 2
            assert all("test_verifycoll.py:" in s for s in sites)
            assert {r["op"] for r in records} == {"bcast_any",
                                                  "reduce_sum_any"}
    finally:
        owner.close(unlink=True)


def _shape_worker(name, q):
    from superlu_dist_tpu.parallel.treecomm import TreeComm
    from superlu_dist_tpu.utils.errors import CollectiveMismatchError
    tc = TreeComm(name, 2, 1, max_len=64, create=False)
    try:
        tc.bcast_any(np.ones((4,)))              # same op, WRONG shape
        q.put(("no-error", None))
    except CollectiveMismatchError as exc:
        q.put(("mismatch", str(exc)))
    finally:
        tc.close()


@needs_native
def test_two_rank_shape_mismatch_detected(monkeypatch):
    monkeypatch.setenv("SLU_TPU_VERIFY_COLLECTIVES", "1")
    from superlu_dist_tpu.parallel.treecomm import TreeComm
    from superlu_dist_tpu.utils.errors import CollectiveMismatchError
    name = f"/slu_vc_shape_{os.getpid()}"
    owner = TreeComm(name, 2, 0, max_len=64, create=True)
    ctx = mp.get_context("fork")
    q = ctx.Queue()
    p = ctx.Process(target=_shape_worker, args=(name, q))
    p.start()
    try:
        with pytest.raises(CollectiveMismatchError):
            owner.bcast_any(np.ones((8,)))
        kind, msg = q.get(timeout=60)
        p.join(timeout=60)
        assert kind == "mismatch"
        assert "[8]" in msg and "[4]" in msg
    finally:
        owner.close(unlink=True)


def _matched_worker(name, q):
    from superlu_dist_tpu.parallel.treecomm import TreeComm
    tc = TreeComm(name, 2, 1, max_len=64, create=False)
    try:
        x = np.full(8, 2.0)
        s = tc.allreduce_sum_any(x)
        tc.bcast_any(np.zeros(3))
        got = tc.bcast_obj(None, root=0)
        q.put((float(s[0]), got["tag"], tc._verifier.checks))
    finally:
        tc.close()


@needs_native
def test_two_rank_matched_sequence_passes(monkeypatch):
    """Verification must be invisible on correct programs: a matched
    sequence (reached from DIFFERENT source lines on each rank) passes
    and payloads stay bit-exact."""
    monkeypatch.setenv("SLU_TPU_VERIFY_COLLECTIVES", "1")
    from superlu_dist_tpu.parallel.treecomm import TreeComm
    name = f"/slu_vc_ok_{os.getpid()}"
    owner = TreeComm(name, 2, 0, max_len=64, create=True)
    ctx = mp.get_context("fork")
    q = ctx.Queue()
    p = ctx.Process(target=_matched_worker, args=(name, q))
    p.start()
    try:
        s = owner.allreduce_sum_any(np.full(8, 2.0))
        owner.bcast_any(np.zeros(3))
        owner.bcast_obj({"tag": "ok"}, root=0)
        w_sum, w_tag, w_checks = q.get(timeout=60)
        p.join(timeout=60)
        assert float(s[0]) == 4.0 and w_sum == 4.0
        assert w_tag == "ok"
        assert owner._verifier.checks == 3 and w_checks == 3
    finally:
        owner.close(unlink=True)


# ---------------------------------------------------------------------------
# retrace sentinel (the dynamic SLU105 counterpart; no native needed)
# ---------------------------------------------------------------------------

def _small_executor():
    import jax.numpy as jnp
    import superlu_dist_tpu as slu
    from superlu_dist_tpu.drivers.gssvx import analyze
    from superlu_dist_tpu.models.gallery import poisson2d
    from superlu_dist_tpu.numeric.stream import StreamExecutor
    from superlu_dist_tpu.utils.stats import Stats
    a = poisson2d(10)
    lu, bvals, _ = analyze(slu.Options(), a, stats=Stats())
    ex = StreamExecutor(lu.plan, "float32")
    return ex, jnp.asarray(bvals), jnp.asarray(0.0, jnp.float32)


def test_retrace_sentinel_quiet_on_warm_rerun(monkeypatch):
    monkeypatch.delenv("SLU_TPU_PIVOT_KERNEL", raising=False)
    ex, avals, thresh = _small_executor()
    ex(avals, thresh)
    assert ex.last_kernel_builds >= 1        # cold compiles are expected
    assert ex.last_retraces == 0
    ex(avals, thresh)
    assert ex.last_kernel_builds == 0        # warmed: nothing rebuilt
    assert ex.last_retraces == 0


def test_retrace_sentinel_flags_real_recompile(monkeypatch, capsys):
    """Provoke a REAL recompile: flip SLU_TPU_PIVOT_KERNEL between two
    calls of a warmed executor — every shape key changes, jit compiles
    fresh kernels, and the sentinel flags exactly that."""
    from superlu_dist_tpu.numeric.stream import RETRACE_SENTINEL
    from superlu_dist_tpu.obs import trace
    monkeypatch.delenv("SLU_TPU_PIVOT_KERNEL", raising=False)
    ex, avals, thresh = _small_executor()
    ex(avals, thresh)
    total0 = RETRACE_SENTINEL.total
    tracer = trace.Tracer(os.path.join(
        os.environ.get("TMPDIR", "/tmp"), f"retrace_{os.getpid()}.json"))
    prev = trace.install(tracer)
    try:
        monkeypatch.setenv("SLU_TPU_PIVOT_KERNEL", "recursive")
        ex(avals, thresh)
    finally:
        trace.install(prev)
        tracer.close()
    assert ex.last_retraces >= 1
    assert RETRACE_SENTINEL.total == total0 + ex.last_retraces
    assert ("retrace sentinel" in capsys.readouterr().err)
    # surfaced as a `verify` trace span
    spans = [e for e in tracer._events if e["cat"] == "verify"]
    assert spans and spans[0]["name"] == "retrace-sentinel"
    assert spans[0]["args"]["builds"] == ex.last_retraces


def test_retraces_reported_in_stats():
    from superlu_dist_tpu.utils.stats import Stats
    s = Stats()
    s.retraces = 3
    s.utime["FACT"] = 1.0
    assert "UNEXPECTED jit retraces: 3" in s.report()
    assert "retraces" not in Stats().report().lower()  # quiet when clean
