#!/usr/bin/env python
"""Prebake the mega executor's closed bucket set into the persistent
compile cache — the fleet warm-start primitive (ROADMAP item 2/4).

The mega executor (numeric/mega.py) compiles one program per CLOSED
shape bucket, and every program's shapes are canonical ladder rungs —
matrix-size-independent by construction.  That makes the persistent XLA
cache (utils/jaxcache.py) effectively keyed by the BUCKET SET rather
than the matrix: compile the set once, and every later process whose
plan maps onto the same buckets — a serving replica cold-starting via
``persist.from_bundle``, the bench, a resumed factorization — loads all
of its factor programs from disk and spends ~0 s in `factor-compile`.

This script builds that warm state ahead of need:

  warm_compile_cache.py [--nx N [N ...]] [--dtype D] [--cache-dir DIR]
      Build the closed plan for poisson3d grids of edge N (default the
      gallery 16 32 48, the BENCH acceptance sizes) with the bench
      blocking, AOT-compile every bucket program into the persistent
      cache, and write a bucket-set warm marker per plan
      (jaxcache.mark_bucket_set_warm).

  warm_compile_cache.py --bundle PATH [--dtype D]
      Same, but for the plan inside a persisted LU handle bundle
      (persist.load_lu) — warm the cache for exactly the matrix a
      serving fleet is about to load, without factoring anything.

Prints one JSON line per plan: bucket set digest, program count, and
the trace/lower/compile stage split (compile ≈ 0 when already warm).
Exit 0 always on success; any failure raises.
"""

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _closed_bench_plan(nx: int):
    """The bench blocking (bench.py CPU defaults) with the shape-key
    closure on — the kernel set the acceptance gallery measures."""
    from superlu_dist_tpu.models.gallery import poisson3d
    from superlu_dist_tpu.numeric.plan import build_plan
    from superlu_dist_tpu.ordering.dispatch import get_perm_c
    from superlu_dist_tpu.sparse.formats import symmetrize_pattern
    from superlu_dist_tpu.symbolic.symbfact import symbolic_factorize
    from superlu_dist_tpu.utils.options import Options

    a = poisson3d(nx)
    sym = symmetrize_pattern(a)
    sf = symbolic_factorize(sym, get_perm_c(Options(), a, sym),
                            relax=128, max_supernode=256, amalg_tol=1.05)
    return build_plan(sf, min_bucket=16, growth=1.05, closed=True)


def warm_plan(plan, dtype: str) -> dict:
    """AOT-compile every bucket program of one plan into the enabled
    persistent cache; mark the bucket set warm.  Returns the summary
    row (shared by the CLI below and tests)."""
    from superlu_dist_tpu.numeric.mega import MegaExecutor
    from superlu_dist_tpu.obs.compilestats import COMPILE_STATS
    from superlu_dist_tpu.utils.jaxcache import mark_bucket_set_warm

    mark = COMPILE_STATS.marker()
    t0 = time.perf_counter()
    ex = MegaExecutor(plan, dtype)
    n = ex.prebake()
    recs = COMPILE_STATS.records[mark:]
    digest = plan.bucket_set_digest()
    mark_bucket_set_warm(digest)
    return {
        "n": plan.n,
        "dtype": str(dtype),
        "bucket_set": list(map(list, plan.bucket_set)),
        "bucket_set_digest": digest,
        "n_kernels": n,
        "seconds": round(time.perf_counter() - t0, 3),
        "trace_seconds": round(sum(r.trace_seconds or 0 for r in recs), 3),
        "lower_seconds": round(sum(r.lower_seconds or 0 for r in recs), 3),
        "compile_seconds": round(sum(r.compile_seconds or 0
                                     for r in recs), 3),
        # time on programs the persistent cache did NOT serve — exactly
        # 0.0 once the bucket set is resident (the warm-start proof)
        "fresh_seconds": round(sum(r.seconds for r in recs
                                   if not r.persistent_hit), 3),
        "persistent_hits": sum(1 for r in recs if r.persistent_hit),
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--nx", type=int, nargs="+", default=[16, 32, 48])
    ap.add_argument("--dtype", default="float32")
    ap.add_argument("--bundle", default=None,
                    help="warm the plan of a persisted LU handle instead")
    ap.add_argument("--cache-dir", default=None,
                    help="persistent cache dir (default: the repo's "
                         "machine-scoped .cache/jax-mach-<fp>)")
    args = ap.parse_args(argv)

    import jax
    jax.config.update("jax_platforms", "cpu") \
        if os.environ.get("JAX_PLATFORMS", "") in ("", "cpu") else None
    from superlu_dist_tpu.utils.jaxcache import enable_compile_cache
    enable_compile_cache(args.cache_dir)

    if args.bundle:
        from superlu_dist_tpu.persist import load_lu
        lu = load_lu(args.bundle)
        plans = [lu.plan]
        if not plans[0].closed:
            print("warm_compile_cache: note — bundle plan is not "
                  "closed (SLU_TPU_BUCKET_CLOSED=0 at factor time); "
                  "prebaking its open key set anyway", file=sys.stderr)
    else:
        plans = [_closed_bench_plan(nx) for nx in args.nx]

    for plan in plans:
        row = warm_plan(plan, args.dtype)
        print(json.dumps(row), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
