"""SLU118 true-positive fixture (tolerance hygiene): ad-hoc float
comparison literals in the eps-scale band, including a negated literal
and rtol=/atol= keyword thresholds — each silently encodes a dtype
assumption utils/tols.py exists to make explicit."""
import numpy as np


def gate(res):
    return res < 1e-8                      # flagged: comparison literal


def drift(delta):
    return -1e-10 <= delta                 # flagged: negated literal


def close(x, ref):
    np.testing.assert_allclose(x, ref, rtol=1e-9,   # flagged: rtol
                               atol=1e-12)          # flagged: atol
