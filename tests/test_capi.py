"""C-binding smoke test: compile the C client against libslu_tpu.so and run
it (the reference's FORTRAN/EXAMPLE binding tests, SURVEY.md §2.2 item 6)."""

import os
import subprocess
import sys
import sysconfig

import pytest

HERE = os.path.dirname(os.path.abspath(__file__))
BINDINGS = os.path.join(HERE, "..", "superlu_dist_tpu", "bindings")


def _embed_link_flags(lib):
    """Shared link recipe for clients embedding the runtime: the built
    libslu_tpu.so plus the python-embed libraries and rpaths."""
    libdir = sysconfig.get_config_var("LIBDIR")
    pyver = f"python{sys.version_info.major}.{sys.version_info.minor}"
    return [lib, f"-L{libdir}", f"-l{pyver}", "-lm", "-ldl",
            f"-Wl,-rpath,{libdir}",
            f"-Wl,-rpath,{os.path.abspath(BINDINGS)}"]


def _run_client(exe):
    """Run a compiled binding client with the repo importable by the
    embedded interpreter; assert it PASSes."""
    env = dict(os.environ)
    env["PYTHONPATH"] = (os.path.abspath(os.path.join(HERE, ".."))
                         + os.pathsep + env.get("PYTHONPATH", ""))
    res = subprocess.run([exe], capture_output=True, text=True, env=env,
                         timeout=300)
    assert res.returncode == 0, (res.stdout, res.stderr)
    assert "PASS" in res.stdout


@pytest.mark.skipif(not os.path.exists("/usr/bin/gcc"), reason="no gcc")
def test_c_client_roundtrip(tmp_path):
    from superlu_dist_tpu.bindings.build import build
    lib = build()
    exe = str(tmp_path / "test_capi")
    subprocess.run(
        ["gcc", "-O2", os.path.join(BINDINGS, "test_capi.c"),
         "-I", BINDINGS, "-o", exe] + _embed_link_flags(lib),
        check=True, capture_output=True)
    _run_client(exe)


def test_fortran_driver_compiles_and_runs(tmp_path):
    """f_pddrive.f90 (FORTRAN/f_pddrive + f_5x5 analog) — compiled and
    executed when a Fortran compiler is available, else skipped (the
    source-level interface is still exercised via the C API tests).
    Same link/run recipe as test_c_client_roundtrip above."""
    import shutil
    gfortran = shutil.which("gfortran")
    if gfortran is None:
        pytest.skip("no gfortran in this image")
    from superlu_dist_tpu.bindings.build import build
    lib = build()
    exe = str(tmp_path / "f_pddrive")
    r = subprocess.run(
        [gfortran, "-o", exe,
         os.path.join(BINDINGS, "superlu_mod.f90"),
         os.path.join(BINDINGS, "f_pddrive.f90"),
         "-J", str(tmp_path)] + _embed_link_flags(lib),
        capture_output=True, cwd=str(tmp_path))
    assert r.returncode == 0, r.stderr.decode()
    _run_client(exe)


# slow tier: multi-process / native-build / at-scale — fast CI runs -m "not slow"
pytestmark = pytest.mark.slow
